"""Apply fallback (planner/apply.py) for correlated shapes decorrelation
can't rewrite — checked against brute-force Python oracles (the
parallel_apply.go:46 + apply_cache.go role)."""

import numpy as np
import pytest

from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE o (o_id BIGINT, o_prio BIGINT, "
              "o_flag VARCHAR(4))")
    s.execute("CREATE TABLE l (l_oid BIGINT, l_qty BIGINT, "
              "l_tag VARCHAR(4))")
    rng = np.random.default_rng(11)
    orows = []
    for i in range(120):
        flag = ["A", "B", "C"][int(rng.integers(0, 3))]
        orows.append(f"({i},{int(rng.integers(0, 5))},'{flag}')")
    s.execute("INSERT INTO o VALUES " + ",".join(orows))
    lrows = []
    for _ in range(900):
        oid = int(rng.integers(0, 118))
        key = "NULL" if rng.random() < 0.03 else str(oid)
        tag = ["A", "B", "C"][int(rng.integers(0, 3))]
        lrows.append(f"({key},{int(rng.integers(1, 40))},'{tag}')")
    s.execute("INSERT INTO l VALUES " + ",".join(lrows))
    return s


@pytest.fixture(scope="module")
def raw(s):
    o = s.query("SELECT o_id, o_prio, o_flag FROM o").rows
    l = s.query("SELECT l_oid, l_qty, l_tag FROM l").rows
    return o, l


def _li_of(l, oid):
    return [r for r in l if r[0] == oid]


def test_apply_exists_limit_offset(s, raw):
    # EXISTS (… LIMIT 1 OFFSET 2): existence requires ≥3 matching rows —
    # not decorrelatable into a plain semi join (decorrelate.py raises
    # "correlated EXISTS with LIMIT OFFSET")
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE EXISTS ("
        "SELECT 1 FROM l WHERE l_oid = o_id LIMIT 1 OFFSET 2)").rows
    o, l = raw
    want = sum(1 for oid, *_ in o if len(_li_of(l, oid)) >= 3)
    assert got[0][0] == want


def test_apply_correlated_agg_argument(s, raw):
    # the outer column appears INSIDE the aggregate argument
    # ("correlated aggregate argument" in decorrelate.py)
    got = s.query(
        "SELECT o_id FROM o WHERE 200 < ("
        "SELECT SUM(l_qty + o_prio) FROM l WHERE l_oid = o_id) "
        "ORDER BY o_id").rows
    o, l = raw
    want = []
    for oid, prio, _ in o:
        items = _li_of(l, oid)
        tot = sum(q + prio for _, q, _t in items) if items else None
        if tot is not None and tot > 200:
            want.append((oid,))
    assert got == want


def test_apply_non_equality_correlation(s, raw):
    # correlated comparison (l_oid < o_id) — only equality correlations
    # decorrelate; this needs the apply path
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE o_prio < ("
        "SELECT MAX(l_qty) FROM l WHERE l_oid < o_id AND l_tag = 'A')"
    ).rows
    o, l = raw
    want = 0
    for oid, prio, _ in o:
        vals = [q for k, q, t in l
                if k is not None and k < oid and t == "A"]
        mx = max(vals) if vals else None
        if mx is not None and prio < mx:
            want += 1
    assert got[0][0] == want


def test_apply_scalar_row_subquery_orderby_limit(s, raw):
    # scalar subquery that is not Projection←Aggregation (ORDER BY/LIMIT
    # row pick): "unsupported correlated scalar subquery"
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE o_prio >= ("
        "SELECT l_qty FROM l WHERE l_oid = o_id ORDER BY l_qty LIMIT 1)"
    ).rows
    o, l = raw
    want = 0
    for oid, prio, _ in o:
        items = sorted(q for _, q, _t in _li_of(l, oid))
        if items and prio >= items[0]:
            want += 1
    assert got[0][0] == want


def test_apply_in_correlated_value_expr(s, raw):
    # the IN value expression itself references the outer row
    # ("correlated IN value expression")
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE o_id IN ("
        "SELECT l_oid + o_prio FROM l WHERE l_tag = o_flag)").rows
    o, l = raw
    want = 0
    for oid, prio, flag in o:
        vals = [k + prio for k, _q, t in l
                if t == flag and k is not None]
        if oid in vals:
            want += 1
        # NULL-membership → NULL → filtered; oid is never NULL here
    assert got[0][0] == want


def test_apply_not_in_null_semantics(s, raw):
    # NOT IN over a set containing NULL filters EVERY row (three-valued
    # logic) — the apply path must preserve that
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE o_id NOT IN ("
        "SELECT l_oid + o_prio * 0 FROM l WHERE l_tag = o_flag)").rows
    o, l = raw
    want = 0
    for oid, prio, flag in o:
        keys = [k for k, _q, t in l if t == flag]
        if any(k is None for k in keys):
            continue                      # NULL in set → never TRUE
        if all(k + prio * 0 != oid for k in keys):
            want += 1
    assert got[0][0] == want


def test_apply_error_multi_row_scalar(s):
    from tidb_tpu.errors import TiDBTPUError
    with pytest.raises(TiDBTPUError, match="more than 1 row"):
        s.query("SELECT COUNT(*) FROM o WHERE o_prio = ("
                "SELECT l_qty FROM l WHERE l_oid = o_id AND o_prio < 99)")


def test_apply_cache_bounds_inner_executions(s):
    # correlation key is o_prio (5 distinct values): the apply cache must
    # bound inner executions by distinct keys, not outer rows
    before = s._subq_execs
    s.query("SELECT COUNT(*) FROM o WHERE EXISTS ("
            "SELECT 1 FROM l WHERE l_qty > o_prio * 8 LIMIT 1 OFFSET 1)")
    execs = s._subq_execs - before
    assert execs <= 6, execs


def test_apply_plan_not_cached(s):
    # data-dependent apply plans must bypass the statement plan cache:
    # inserting a row changes the result immediately
    sql = ("SELECT COUNT(*) FROM o WHERE EXISTS ("
           "SELECT 1 FROM l WHERE l_oid = o_id LIMIT 1 OFFSET 2)")
    a = s.query(sql).rows[0][0]
    s.execute("INSERT INTO o VALUES (5000, 1, 'A'), (5001, 1, 'A'), "
              "(5002, 1, 'A')")
    s.execute("INSERT INTO l VALUES (5000, 5, 'A'), (5000, 6, 'B'), "
              "(5000, 7, 'C')")
    b = s.query(sql).rows[0][0]
    assert b == a + 1
    s.execute("DELETE FROM o WHERE o_id >= 5000")
    s.execute("DELETE FROM l WHERE l_oid >= 5000")


def test_select_list_correlated_scalar(s, raw):
    # correlated scalar subquery as a VALUE expression (SELECT list /
    # arbitrary operands), not a top-level WHERE conjunct
    got = s.query(
        "SELECT o_id, (SELECT MAX(l_qty) FROM l WHERE l_oid = o_id) "
        "FROM o ORDER BY o_id").rows
    o, l = raw
    for oid, mx in got:
        items = [q for k, q, _t in l if k == oid]
        assert mx == (max(items) if items else None), (oid, mx)
    # inside an expression + as a non-conjunct WHERE operand
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE "
        "(SELECT COUNT(*) FROM l WHERE l_oid = o_id) + 1 > 9").rows
    want = 0
    for oid, *_ in o:
        if sum(1 for k, *_x in l if k == oid) + 1 > 9:
            want += 1
    assert got[0][0] == want


def test_value_position_exists_and_in(s, raw):
    got = s.query(
        "SELECT o_id, EXISTS(SELECT 1 FROM l WHERE l_oid = o_id), "
        "NOT EXISTS(SELECT 1 FROM l WHERE l_oid = o_id) "
        "FROM o ORDER BY o_id").rows
    o, l = raw
    present = {k for k, *_ in l if k is not None}
    for oid, ex, nex in got:
        assert ex == int(oid in present) and nex == int(oid not in present)
    # three-valued IN as a VALUE: no match + NULL in set → NULL
    got = s.query(
        "SELECT o_id, o_id + 100000 IN (SELECT l_oid FROM l "
        "WHERE l_qty > o_prio) FROM o ORDER BY o_id LIMIT 3").rows
    for _oid, v in got:
        assert v is None        # never matches; NULL keys exist in l


def test_nested_apply_survives_decorrelation(s, raw):
    # an ApplySubquery riding inside a decorrelated EXISTS's join
    # condition must survive _shift_inner/_subst_corr (rebuild protocol)
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE EXISTS (SELECT 1 FROM l WHERE "
        "(SELECT MAX(l2.l_qty) FROM l l2 WHERE l2.l_oid = l.l_oid) "
        "> o_prio)").rows
    o, l = raw
    maxq = {}
    for k, q, _t in l:
        if k is not None:
            maxq[k] = max(maxq.get(k, 0), q)
    want = sum(1 for _oid, prio, _f in o
               if any(m > prio for m in maxq.values()))
    assert got[0][0] == want


def test_genuine_subquery_errors_surface(s):
    import pytest
    from tidb_tpu.errors import TiDBTPUError
    with pytest.raises(TiDBTPUError, match="bogus"):
        s.query("SELECT (SELECT MAX(l_qty) FROM l "
                "WHERE l_oid = o_id AND bogus > 1) FROM o")
