"""Sort-merge join over cached index views (executor/merge_join.go
analog): chosen for large indexed-both-sides inner joins on the CPU
engine; results match the hash join."""

import numpy as np
import pytest

from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ml (l_k BIGINT, l_v BIGINT)")
    s.execute("CREATE TABLE mr (r_k BIGINT, r_v BIGINT)")
    s.execute("CREATE INDEX il ON ml (l_k)")
    s.execute("CREATE INDEX ir ON mr (r_k)")
    rng = np.random.default_rng(3)
    s.execute("INSERT INTO ml VALUES " + ",".join(
        f"({'NULL' if rng.random() < 0.02 else int(rng.integers(0, 4000))},"
        f"{i})" for i in range(20000)))
    s.execute("INSERT INTO mr VALUES " + ",".join(
        f"({int(rng.integers(0, 5000))},{i})" for i in range(15000)))
    s.execute("ANALYZE TABLE ml")
    s.execute("ANALYZE TABLE mr")
    return s


def oracle(s, sql):
    # force the hash path by pricing index startup out of reach
    from tidb_tpu.planner import cost as C
    saved = C.INDEX_STARTUP
    C.INDEX_STARTUP = 1e18
    try:
        s._plan_cache.clear()
        return s.query(sql).rows
    finally:
        C.INDEX_STARTUP = saved
        s._plan_cache.clear()


def test_explain_picks_merge_join(s):
    txt = "\n".join(str(r) for r in s.query(
        "EXPLAIN SELECT COUNT(*) FROM ml JOIN mr ON l_k = r_k").rows)
    assert "MergeJoin" in txt, txt


@pytest.mark.parametrize("sql", [
    "SELECT COUNT(*), SUM(l_v), SUM(r_v) FROM ml JOIN mr ON l_k = r_k",
    "SELECT COUNT(*) FROM ml JOIN mr ON l_k = r_k "
    "WHERE l_v < 5000 AND r_v < 9000",
    "SELECT COUNT(*) FROM ml JOIN mr ON l_k = r_k AND l_v < r_v",
])
def test_merge_join_matches_hash_join(s, sql):
    assert s.query(sql).rows == oracle(s, sql)


def test_small_sides_keep_hash_join(s):
    s.execute("CREATE TABLE tiny (t_k BIGINT)")
    s.execute("CREATE INDEX it ON tiny (t_k)")
    s.execute("INSERT INTO tiny VALUES (1),(2)")
    txt = "\n".join(str(r) for r in s.query(
        "EXPLAIN SELECT COUNT(*) FROM tiny JOIN mr ON t_k = r_k").rows)
    assert "MergeJoin" not in txt, txt
