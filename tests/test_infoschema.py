"""information_schema virtual tables (ref: infoschema/tables.go)."""

import pytest

from tidb_tpu.session import Engine


@pytest.fixture()
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE t1 (a BIGINT PRIMARY KEY, b VARCHAR(8))")
    s.execute("CREATE INDEX ib ON t1 (b)")
    s.execute("INSERT INTO t1 VALUES (1,'x'),(2,'y'),(3,'z')")
    s.execute("CREATE TABLE t2 (c DOUBLE)")
    return s


def test_tables_and_columns(s):
    rows = dict((r[0], r[1]) for r in s.query(
        "SELECT table_name, table_rows FROM information_schema.tables"
    ).rows)
    assert rows == {"t1": 3, "t2": 0}
    cols = s.query("SELECT column_name, column_key FROM "
                   "information_schema.columns WHERE table_name = 't1' "
                   "ORDER BY ordinal_position").rows
    assert cols == [("a", "PRI"), ("b", "")]


def test_statistics_lists_indexes(s):
    rows = s.query("SELECT index_name, column_name, non_unique FROM "
                   "information_schema.statistics "
                   "WHERE table_name = 't1' ORDER BY index_name").rows
    assert rows == [("PRIMARY", "a", 0), ("ib", "b", 1)]


def test_user_privileges_and_variables(s):
    s.execute("CREATE USER w IDENTIFIED BY 'p'")
    s.execute("GRANT SELECT, INSERT ON t1 TO w")
    rows = s.query("SELECT privilege_type FROM "
                   "information_schema.user_privileges "
                   "WHERE grantee = \"'w'@'%'\" ORDER BY 1").rows
    assert rows == [("INSERT",), ("SELECT",)]
    n = s.query("SELECT COUNT(*) FROM "
                "information_schema.session_variables").scalar()
    assert n >= 5


def test_memtables_compose_with_sql(s):
    # joins/aggregates over memtables run through the normal planner
    rows = s.query(
        "SELECT t.table_name, COUNT(*) FROM information_schema.tables t "
        "JOIN information_schema.columns c ON t.table_name = c.table_name "
        "GROUP BY t.table_name ORDER BY t.table_name").rows
    assert rows == [("t1", 2), ("t2", 1)]


def test_memtable_fresh_per_execution(s):
    q = ("SELECT table_rows FROM information_schema.tables "
         "WHERE table_name = 't1'")
    assert s.query(q).rows == [(3,)]
    s.execute("INSERT INTO t1 VALUES (4,'w')")
    assert s.query(q).rows == [(4,)]


def test_non_superuser_can_read_infoschema(s):
    s.execute("CREATE USER viewer IDENTIFIED BY 'v'")
    s2 = s.engine.new_session()
    s2.user = "viewer"
    assert s2.query("SELECT COUNT(*) FROM information_schema.tables"
                    ).scalar() >= 2


def test_statements_endpoint():
    import json
    import urllib.request
    from tidb_tpu.session import Engine
    from tidb_tpu.util.status_server import StatusServer
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE se (a BIGINT)")
    s.execute("INSERT INTO se VALUES (1)")
    for _ in range(3):
        s.query("SELECT COUNT(*) FROM se")
    srv = StatusServer(eng, port=0).start()
    try:
        url = f"http://127.0.0.1:{srv.port}/statements"
        data = json.load(urllib.request.urlopen(url))
        assert data == sorted(data, key=lambda r: -r["sum_s"])
        hit = [r for r in data if "se" in r["digest"].lower()
               and "count" in r["digest"].lower()]
        assert hit and any(r["count"] >= 3 for r in hit), data
    finally:
        srv.stop()


def test_information_schema_partitions_and_views():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE pt (id BIGINT, v BIGINT) "
              "PARTITION BY RANGE (id) ("
              "PARTITION p0 VALUES LESS THAN (10), "
              "PARTITION p1 VALUES LESS THAN (MAXVALUE))")
    s.execute("INSERT INTO pt VALUES (1, 1), (2, 2), (50, 3)")
    rows = s.query("SELECT PARTITION_NAME, PARTITION_METHOD, "
                   "PARTITION_DESCRIPTION, TABLE_ROWS FROM "
                   "information_schema.partitions WHERE TABLE_NAME = 'pt' "
                   "ORDER BY PARTITION_ORDINAL_POSITION").rows
    assert rows == [("p0", "RANGE", "10", 2),
                    ("p1", "RANGE", "MAXVALUE", 1)]
    s.execute("CREATE VIEW vv AS SELECT id FROM pt WHERE v > 1")
    rows = s.query("SELECT TABLE_NAME, VIEW_DEFINITION FROM "
                   "information_schema.views").rows
    assert rows == [("vv", "SELECT id FROM pt WHERE v > 1")]


def test_show_index():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE si (a BIGINT PRIMARY KEY, b BIGINT, "
              "UNIQUE KEY ub (b))")
    rows = s.query("SHOW INDEX FROM si").rows
    assert ("si", 0, "PRIMARY", 1, "a", "BTREE", "public") in rows
    assert ("si", 0, "ub", 1, "b", "BTREE", "public") in rows
    assert s.query("SHOW KEYS FROM si").rows == rows
