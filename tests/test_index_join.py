"""Index-lookup join (executor/index_join.py; ref:
executor/index_lookup_join.go:59): a tiny outer probing a large indexed
inner picks the index path in EXPLAIN and matches the hash-join oracle."""

import numpy as np
import pytest

from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE fact (f_id BIGINT PRIMARY KEY, f_key BIGINT, "
              "f_val DECIMAL(10,2))")
    s.execute("CREATE TABLE probe (p_key BIGINT, p_tag VARCHAR(8))")
    s.execute("CREATE INDEX ix_fkey ON fact (f_key)")
    rng = np.random.default_rng(17)
    rows = ",".join(
        f"({i},{int(rng.integers(0, 500))},{round(float(rng.uniform(1, 99)), 2)})"
        for i in range(40000))
    s.execute("INSERT INTO fact VALUES " + rows)
    rows = []
    for i in range(30):
        k = "NULL" if i == 7 else str(int(rng.integers(0, 520)))
        rows.append(f"({k},'t{i}')")
    s.execute("INSERT INTO probe VALUES " + ",".join(rows))
    s.execute("ANALYZE TABLE fact")
    s.execute("ANALYZE TABLE probe")
    return s


def oracle(s, sql):
    # force the hash-join path as the semantic oracle by pricing every
    # index-backed shape out of reach of the cost chooser
    from tidb_tpu.planner import cost as C
    saved = C.INDEX_STARTUP
    C.INDEX_STARTUP = 1e18
    try:
        s._plan_cache.clear()
        plan = "\n".join(str(r) for r in
                         s.query("EXPLAIN " + sql).rows)
        assert "IndexLookupJoin" not in plan, plan   # oracle must differ
        return s.query(sql).rows
    finally:
        C.INDEX_STARTUP = saved
        s._plan_cache.clear()


def test_explain_picks_index_join(s):
    rows = s.query("EXPLAIN SELECT p_tag, f_val FROM probe "
                   "JOIN fact ON p_key = f_key").rows
    txt = "\n".join(str(r) for r in rows)
    assert "IndexLookupJoin" in txt, txt
    assert "ix_fkey" in txt, txt
    # the inner table is NOT scanned
    assert "table:fact" not in txt, txt


@pytest.mark.parametrize("sql", [
    "SELECT p_tag, f_id, f_val FROM probe JOIN fact ON p_key = f_key",
    "SELECT p_tag, f_val FROM probe LEFT JOIN fact ON p_key = f_key",
    "SELECT p_tag FROM probe WHERE p_key IN (SELECT f_key FROM fact)",
    "SELECT p_tag FROM probe WHERE p_key NOT IN "
    "(SELECT f_key FROM fact WHERE f_val < 50)",
    "SELECT p_tag, COUNT(*) FROM probe JOIN fact ON p_key = f_key "
    "WHERE f_val < 30 GROUP BY p_tag",
])
def test_index_join_matches_hash_join(s, sql):
    got = sorted(map(str, s.query(sql).rows))
    want = sorted(map(str, oracle(s, sql)))
    assert got == want


def test_pk_point_join(s):
    sql = ("SELECT p_tag, f_val FROM probe JOIN fact ON p_key = f_id")
    rows = s.query("EXPLAIN " + sql).rows
    txt = "\n".join(str(r) for r in rows)
    assert "PRIMARY" in txt, txt
    assert sorted(map(str, s.query(sql).rows)) == \
        sorted(map(str, oracle(s, sql)))


def test_multi_column_index_prefix(s):
    s.execute("CREATE TABLE mc (a BIGINT, b BIGINT, c BIGINT, "
              "d VARCHAR(8))")
    s.execute("CREATE INDEX ix_ab ON mc (a, b)")
    rng = np.random.default_rng(5)
    rows = []
    for i in range(20000):
        a = int(rng.integers(0, 40))
        b = "NULL" if rng.random() < 0.05 else str(int(rng.integers(0, 50)))
        rows.append(f"({a},{b},{i},'x{i % 9}')")
    s.execute("INSERT INTO mc VALUES " + ",".join(rows))
    s.execute("ANALYZE TABLE mc")

    q_eq = "SELECT c FROM mc WHERE a = 7 AND b = 11 ORDER BY c"
    q_rng = "SELECT COUNT(*), SUM(c) FROM mc WHERE a = 3 AND b BETWEEN 10 AND 20"
    q_half = "SELECT COUNT(*) FROM mc WHERE a = 9 AND d = 'x3'"
    txt = "\n".join(str(r) for r in s.query("EXPLAIN " + q_eq).rows)
    assert "ix_ab" in txt and "prefix" in txt, txt

    view = s.query("SELECT a, b, c, d FROM mc").rows
    want_eq = sorted(c for a, b, c, d in view if a == 7 and b == 11)
    assert [r[0] for r in s.query(q_eq).rows] == want_eq
    want = [(sum(1 for a, b, c, d in view
                 if a == 3 and b is not None and 10 <= b <= 20),
             sum(c for a, b, c, d in view
                 if a == 3 and b is not None and 10 <= b <= 20))]
    assert s.query(q_rng).rows == want
    # prefix shorter than the index: leading-column access + residual
    assert s.query(q_half).rows == \
        [(sum(1 for a, b, c, d in view if a == 9 and d == "x3"),)]


def test_multi_column_prefix_null_rows(s):
    # rows with NULL at level 2 must match prefix-only probes but never
    # an equality on the NULL level
    s.execute("CREATE TABLE mcn (a BIGINT, b BIGINT)")
    s.execute("CREATE INDEX ix_n ON mcn (a, b)")
    s.execute("INSERT INTO mcn VALUES " +
              ",".join(f"({i % 5}, NULL)" for i in range(2000)) + "," +
              ",".join(f"({i % 5}, {i % 3})" for i in range(2000)))
    s.execute("ANALYZE TABLE mcn")
    assert s.query("SELECT COUNT(*) FROM mcn WHERE a = 2 AND b = 1"
                   ).rows == [(133,)]
    assert s.query("SELECT COUNT(*) FROM mcn WHERE a = 2 AND b IS NULL"
                   ).rows == [(400,)]


def test_large_outer_keeps_hash_join(s):
    # outer too big for the lookup gate: hash join remains
    rows = s.query("EXPLAIN SELECT COUNT(*) FROM fact f1 "
                   "JOIN fact f2 ON f1.f_key = f2.f_key").rows
    txt = "\n".join(str(r) for r in rows)
    assert "IndexLookupJoin" not in txt, txt
