"""Device fragment execution vs CPU oracle (the vec-vs-scalar twin-test
pattern of the reference, SURVEY §4 tier 1: builtin_*_vec_test.go asserts
vec(X) == scalar(X); here device fragment == CPU volcano pipeline)."""

import numpy as np
import pytest

from tidb_tpu.executor import build, run_to_completion
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE t (a BIGINT, b DOUBLE, c VARCHAR(10), "
              "d DECIMAL(10,2), e DATE)")
    rng = np.random.default_rng(7)
    rows = []
    for _ in range(6000):
        a = int(rng.integers(0, 9))
        b = float(rng.normal())
        c = ["ant", "bee", "cow", "dog"][int(rng.integers(0, 4))]
        d = round(float(rng.uniform(0, 500)), 2)
        e = f"2021-{int(rng.integers(1, 13)):02d}-{int(rng.integers(1, 28)):02d}"
        rows.append(f"({a},{b},'{c}',{d},'{e}')")
    rows.append("(NULL,NULL,NULL,NULL,NULL)")
    rows.append("(3,NULL,'ant',NULL,NULL)")
    s.execute("INSERT INTO t VALUES " + ",".join(rows))
    return s


def run_device(s, sql, *, max_slab=None):
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    if max_slab is not None:
        s.vars["tidb_tpu_max_slab_rows"] = max_slab
    else:
        s.vars.pop("tidb_tpu_max_slab_rows", None)
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags, f"no fragment extracted for: {sql}"
        for f in frags:
            assert f.used_device, f"fell back to CPU for: {sql}"
        return [r for ch in chunks for r in ch.rows()]
    finally:
        s.vars["tidb_tpu_engine"] = "off"
        s.vars.pop("tidb_tpu_max_slab_rows", None)


def assert_same(rows1, rows2, ordered=False):
    assert len(rows1) == len(rows2)
    if not ordered:
        rows1 = sorted(rows1, key=str)
        rows2 = sorted(rows2, key=str)
    for r1, r2 in zip(rows1, rows2):
        for v1, v2 in zip(r1, r2):
            if isinstance(v1, float) and v2 is not None:
                assert abs(v1 - v2) <= 1e-5 * max(1.0, abs(v2)), (r1, r2)
            else:
                assert v1 == v2, (r1, r2)


QUERIES = [
    "SELECT c, a, COUNT(*), SUM(d), AVG(b), MIN(b), MAX(a) FROM t "
    "WHERE a < 6 GROUP BY c, a",
    "SELECT COUNT(*), SUM(a), MIN(b), MAX(d), AVG(d) FROM t WHERE c = 'ant'",
    "SELECT a, COUNT(*), COUNT(b), SUM(b) FROM t GROUP BY a",
    "SELECT e, COUNT(*) FROM t GROUP BY e",
    "SELECT c, VAR_POP(b), STDDEV(b) FROM t GROUP BY c",
    "SELECT a, SUM(d * 2 + 1) FROM t WHERE b > 0 GROUP BY a",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_agg_fragment_matches_cpu(session, sql):
    dev = run_device(session, sql)
    cpu = session.query(sql).rows
    assert_same(dev, cpu)


@pytest.mark.parametrize("sql", QUERIES[:2])
def test_multi_slab_merge(session, sql):
    dev = run_device(session, sql, max_slab=1024)
    cpu = session.query(sql).rows
    assert_same(dev, cpu)


def test_topn_fragment(session):
    sql = "SELECT a, b, c FROM t ORDER BY b DESC LIMIT 9"
    assert_same(run_device(session, sql), session.query(sql).rows,
                ordered=True)


def test_topn_nulls_first_asc(session):
    sql = "SELECT c, a FROM t ORDER BY c, a LIMIT 5"
    dev = run_device(session, sql)
    cpu = session.query(sql).rows
    assert_same(dev, cpu, ordered=True)
    assert dev[0][0] is None  # NULLs first under ASC


def test_topn_multi_slab(session):
    sql = "SELECT a, d FROM t ORDER BY d DESC, a LIMIT 11"
    dev = run_device(session, sql, max_slab=1024)
    assert_same(dev, session.query(sql).rows, ordered=True)


def test_filter_fragment(session):
    sql = "SELECT a, b, c FROM t WHERE b > 1.2 AND a >= 4"
    assert_same(run_device(session, sql), session.query(sql).rows)


def test_filter_fragment_strings(session):
    sql = "SELECT c, d FROM t WHERE c >= 'bee' AND d < 100"
    assert_same(run_device(session, sql), session.query(sql).rows)


def test_sort_fragment(session):
    sql = "SELECT a, b FROM t WHERE a IS NOT NULL ORDER BY a, b DESC"
    assert_same(run_device(session, sql), session.query(sql).rows,
                ordered=True)


def test_group_cap_overflow_retry(session):
    # d has ~6000 distinct values; default cap 65536 covers it, but force a
    # tiny starting cap to exercise the retry loop
    session.vars["tidb_tpu_group_cap"] = 64
    try:
        sql = "SELECT d, COUNT(*) FROM t GROUP BY d"
        assert_same(run_device(session, sql), session.query(sql).rows)
    finally:
        session.vars.pop("tidb_tpu_group_cap", None)


def test_small_input_stays_on_cpu(session):
    session.vars["tidb_tpu_engine"] = "on"
    session.vars["tidb_tpu_row_threshold"] = 10 ** 9
    try:
        plan = session._plan(parse("SELECT a, COUNT(*) FROM t GROUP BY a")[0])
        names = []

        def walk(p):
            names.append(type(p).__name__)
            for c in p.children:
                walk(c)

        walk(plan)
        assert "PhysTpuFragment" not in names
    finally:
        session.vars["tidb_tpu_engine"] = "off"
        session.vars["tidb_tpu_row_threshold"] = 1


def test_multi_slab_per_slab_cap_overflow(session):
    # Advisor r1 high-severity repro: per-slab distinct groups exceed the
    # cap while the MERGED group count stays under it — the per-slab
    # n_groups check must trigger retry, not silently conflate groups.
    # Group by the DOUBLE column: floats have no cached bounds, so this
    # exercises the sort-factorize path (perfect-hash grouping would route
    # around the clipping bug this guards).
    session.vars["tidb_tpu_group_cap"] = 64
    try:
        sql = "SELECT b, COUNT(*) FROM t WHERE b IS NOT NULL GROUP BY b"
        dev = run_device(session, sql, max_slab=2048)
        assert_same(dev, session.query(sql).rows)
    finally:
        session.vars.pop("tidb_tpu_group_cap", None)


def test_device_table_cache_reuse_and_invalidation():
    from tidb_tpu.executor import device_cache
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ct (a BIGINT, c VARCHAR(8))")
    s.execute("INSERT INTO ct VALUES " + ",".join(
        f"({i % 7}, 'v{i % 3}')" for i in range(4000)))
    sql = "SELECT a, COUNT(*) FROM ct GROUP BY a"
    # serial single-session workload → deterministically device 0
    key = (0, id(eng.store), eng.catalog.info_schema.table("ct").id, None)
    r1 = run_device(s, sql)
    ent1 = device_cache._CACHE.get(key)
    assert ent1 is not None and 0 in ent1.dev
    r2 = run_device(s, sql)
    ent2 = device_cache._CACHE.get(key)
    assert ent2 is ent1          # cache hit: same device payload object
    assert_same(r1, r2)
    # a write replaces TableData → identity check must rebuild
    s.execute("INSERT INTO ct VALUES (99, 'new')")
    r3 = run_device(s, sql)
    ent3 = device_cache._CACHE.get(key)
    assert ent3 is not ent1
    assert sum(r[1] for r in r3) == 4001
    assert_same(r3, s.query(sql).rows)


def test_txn_reads_bypass_device_cache():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE tx (a BIGINT)")
    s.execute("INSERT INTO tx VALUES " + ",".join(
        f"({i % 5})" for i in range(3000)))
    s.execute("BEGIN")
    s.execute("INSERT INTO tx VALUES (77)")
    sql = "SELECT a, COUNT(*) FROM tx GROUP BY a"
    dev = run_device(s, sql)      # staged row must be visible
    assert any(r[0] == 77 for r in dev)
    s.execute("ROLLBACK")
    dev2 = run_device(s, sql)
    assert not any(r[0] == 77 for r in dev2)


def test_strict_mode_and_fallback_reason():
    from tidb_tpu.errors import ExecutionError
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE st (a BIGINT)")  # empty table → FragmentFallback
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 0
    s.vars["tidb_tpu_strict"] = True
    try:
        with pytest.raises(ExecutionError, match="fell back"):
            s.query("SELECT a, COUNT(*) FROM st GROUP BY a")
        s.vars["tidb_tpu_strict"] = False
        rs = s.query("SELECT a, COUNT(*) FROM st GROUP BY a")
        assert rs.rows == []
    finally:
        s.vars["tidb_tpu_engine"] = "off"


# ---- fallback-reason taxonomy (tidb_tpu_device_fallbacks_total) -----------

def test_source_reason_codes_stay_in_taxonomy():
    """Every reason= literal across the fragment layers is a member of
    FALLBACK_REASONS — the metric label vocabulary never drifts."""
    import os
    import re

    from tidb_tpu.executor.fragment import FALLBACK_REASONS
    base = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tidb_tpu", "executor")
    found = 0
    for mod in ("fragment.py", "dist_fragment.py", "tree_fragment.py",
                "device_emit.py", "window.py"):
        with open(os.path.join(base, mod)) as f:
            src = f.read()
        for code in re.findall(r'reason="([a-z-]+)"', src):
            assert code in FALLBACK_REASONS, (mod, code)
            found += 1
    assert found >= 10  # the taxonomy is actually in use


def test_unknown_reason_normalizes_to_shape():
    from tidb_tpu.executor.fragment import FragmentFallback
    assert FragmentFallback("x", reason="no-such-code").reason == "shape"
    assert FragmentFallback("x").reason == "shape"


def test_empty_input_fallback_explain_matches_metric():
    """EXPLAIN ANALYZE's device:fallback(code) and the reason= label on
    tidb_tpu_device_fallbacks_total carry the SAME stable code."""
    from tidb_tpu.util.observability import REGISTRY
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE empt (a BIGINT, b DOUBLE)")
    key = ("tidb_tpu_device_fallbacks_total",
           (("reason", "empty-input"),))
    before = REGISTRY.counters.get(key, 0)
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        rows = s.query("EXPLAIN ANALYZE SELECT a, COUNT(*), SUM(b) "
                       "FROM empt GROUP BY a").rows
    finally:
        s.vars["tidb_tpu_engine"] = "off"
    txt = "\n".join(str(r) for r in rows)
    assert "device:fallback(empty-input)" in txt, txt
    assert REGISTRY.counters.get(key, 0) == before + 1


@pytest.mark.parametrize("sql", [
    # DISTINCT under ROLLUP: pair columns assume nk key cols
    "SELECT a, COUNT(DISTINCT c) FROM t GROUP BY a WITH ROLLUP",
    # computed string in an IN-list: no per-dictionary codeset to prepare
    "SELECT COUNT(*) FROM t WHERE SUBSTRING(c, 1, 2) IN ('an', 'be')",
])
def test_ineligible_shape_classes_never_extract_a_fragment(session, sql):
    """Planning-time gates (taxonomy class `shape`) keep the whole plan
    on the host — no fragment, no device attempt, stable results."""
    s = session
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert not frags, f"shape-gated query extracted a fragment: {sql}"
        dev = [r for ch in chunks for r in ch.rows()]
    finally:
        s.vars["tidb_tpu_engine"] = "off"
    assert sorted(dev, key=str) == sorted(s.query(sql).rows, key=str)
