"""Partitioned tables (RANGE/HASH) + partition pruning (ref:
table/tables/partition.go locatePartition, planner/core/
rule_partition_processor.go). TPU-first layout: partitions are region
colocation tags in the one columnar store table — INSERT routes rows so a
region never mixes partitions, and pruning skips whole regions (and thus
whole device slabs)."""

import numpy as np
import pytest

from tidb_tpu.errors import PartitionError, PlanError
from tidb_tpu.session import Engine


def _explain(s, sql):
    return "\n".join(str(r) for r in s.query("EXPLAIN " + sql).rows)


@pytest.fixture()
def s():
    return Engine().new_session()


def _mk_range(s):
    s.execute("CREATE TABLE r (id BIGINT, v BIGINT) "
              "PARTITION BY RANGE (id) ("
              "PARTITION p0 VALUES LESS THAN (100), "
              "PARTITION p1 VALUES LESS THAN (200), "
              "PARTITION p2 VALUES LESS THAN (MAXVALUE))")
    s.execute("INSERT INTO r VALUES " + ",".join(
        f"({i},{i * 2})" for i in range(0, 300, 3)) + ",(NULL, -1)")


def test_range_routing_and_regions(s):
    _mk_range(s)
    info = s.engine.catalog.info_schema.table("r")
    td = s.engine.store.snapshot().table_data(info.id)
    parts_seen = {r.part for r in td.regions}
    assert parts_seen == {0, 1, 2}
    for r in td.regions:          # a region never mixes partitions
        vals = r.chunk.columns[0].values
        valid = r.chunk.columns[0].valid_mask()
        enc = vals[valid]
        if r.part == 0:
            assert (enc < 100).all()
        elif r.part == 1:
            assert ((enc >= 100) & (enc < 200)).all()
        else:
            assert (enc >= 200).all()
    # NULL routes to the first partition
    assert s.query("SELECT COUNT(*) FROM r WHERE id IS NULL").rows == [(1,)]


def test_range_no_partition_for_value(s):
    s.execute("CREATE TABLE rn (id BIGINT) PARTITION BY RANGE (id) ("
              "PARTITION p0 VALUES LESS THAN (10))")
    with pytest.raises(PartitionError):
        s.execute("INSERT INTO rn VALUES (10)")
    s.execute("INSERT INTO rn VALUES (9)")   # boundary-1 fits


def test_range_pruning_in_explain(s):
    _mk_range(s)
    s.execute("ANALYZE TABLE r")
    plan = _explain(s, "SELECT COUNT(*) FROM r WHERE id < 100")
    assert "partition:p0" in plan and "p1" not in plan
    plan = _explain(s, "SELECT COUNT(*) FROM r WHERE id >= 150")
    assert "partition:p1,p2" in plan
    plan = _explain(s, "SELECT COUNT(*) FROM r WHERE id = 150 AND v > 0")
    assert "partition:p1" in plan and "p0" not in plan
    plan = _explain(s, "SELECT COUNT(*) FROM r")
    assert "partition:all" in plan
    # pruned results are correct
    assert s.query("SELECT COUNT(*) FROM r WHERE id < 100").rows == \
        [(34,)]
    assert s.query("SELECT COUNT(*) FROM r WHERE id >= 150 AND id < 210"
                   ).rows == [(20,)]


def test_hash_partition_routing_and_pruning(s):
    s.execute("CREATE TABLE h (id BIGINT, v BIGINT) "
              "PARTITION BY HASH (id) PARTITIONS 4")
    s.execute("INSERT INTO h VALUES " + ",".join(
        f"({i},{i})" for i in range(100)))
    info = s.engine.catalog.info_schema.table("h")
    td = s.engine.store.snapshot().table_data(info.id)
    assert {r.part for r in td.regions} == {0, 1, 2, 3}
    plan = _explain(s, "SELECT COUNT(*) FROM h WHERE id = 7")
    assert "partition:p3" in plan
    assert s.query("SELECT COUNT(*) FROM h WHERE id = 7").rows == [(1,)]
    assert s.query("SELECT SUM(v) FROM h").rows == [(4950,)]


def test_partition_dml_and_cross_partition_update(s):
    _mk_range(s)
    # UPDATE moving a row across partitions (delete + re-routed insert)
    s.execute("UPDATE r SET id = 250 WHERE id = 0")
    assert s.query("SELECT COUNT(*) FROM r WHERE id >= 200").rows == \
        [(34,)]
    info = s.engine.catalog.info_schema.table("r")
    td = s.engine.store.snapshot().table_data(info.id)
    for r in td.regions:
        vals = r.chunk.columns[0].values
        alive = ~r.deleted & r.chunk.columns[0].valid_mask()
        if r.part == 0 and alive.any():
            assert (vals[alive] < 100).all()
    s.execute("DELETE FROM r WHERE id >= 200")
    assert s.query("SELECT COUNT(*) FROM r WHERE id >= 200").rows == [(0,)]


def test_partition_txn_staged_rows(s):
    _mk_range(s)
    s.execute("BEGIN")
    s.execute("INSERT INTO r VALUES (50, 1), (150, 2)")
    # staged rows visible through the pruned scan
    assert s.query("SELECT COUNT(*) FROM r WHERE id = 50").rows == [(1,)]
    s.execute("ROLLBACK")
    assert s.query("SELECT COUNT(*) FROM r WHERE id = 50").rows == [(0,)]


def test_partition_device_engine_parity(s):
    s.execute("CREATE TABLE dp (id BIGINT, g VARCHAR(4), v BIGINT) "
              "PARTITION BY RANGE (id) ("
              "PARTITION p0 VALUES LESS THAN (10000), "
              "PARTITION p1 VALUES LESS THAN (MAXVALUE))")
    rng = np.random.default_rng(6)
    s.execute("INSERT INTO dp VALUES " + ",".join(
        f"({int(rng.integers(0, 20000))},'g{int(rng.integers(0, 4))}',"
        f"{int(rng.integers(0, 100))})" for _ in range(40000)))
    s.execute("ANALYZE TABLE dp")
    sql = ("SELECT g, COUNT(*), SUM(v) FROM dp WHERE id < 10000 "
           "GROUP BY g ORDER BY g")
    want = s.query(sql).rows
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_strict="on")
    try:
        got = s.query(sql).rows
        # different pruning must not reuse the cached pruned slabs
        got_all = s.query("SELECT COUNT(*) FROM dp").rows
    finally:
        s.vars.update(tidb_tpu_engine="off", tidb_tpu_strict="off")
    assert got == want
    assert got_all == s.query("SELECT COUNT(*) FROM dp").rows


def test_partition_show_create_roundtrip(s):
    _mk_range(s)
    ddl = s.query("SHOW CREATE TABLE r").rows[0][1]
    assert "PARTITION BY RANGE" in ddl and "MAXVALUE" in ddl
    s2 = Engine().new_session()
    s2.execute(ddl.replace("`r`", "`r2`", 1))
    info2 = s2.engine.catalog.info_schema.table("r2")
    assert info2.partition is not None
    assert info2.partition.names == ("p0", "p1", "p2")


def test_partition_validation(s):
    with pytest.raises(PlanError):
        s.execute("CREATE TABLE bad (a VARCHAR(4)) "
                  "PARTITION BY HASH (a) PARTITIONS 4")
    with pytest.raises(PlanError):
        s.execute("CREATE TABLE bad2 (a BIGINT) PARTITION BY RANGE (a) ("
                  "PARTITION p0 VALUES LESS THAN (10), "
                  "PARTITION p1 VALUES LESS THAN (5))")


def test_partition_by_date_range(s):
    s.execute("CREATE TABLE ev (d DATE, v BIGINT) "
              "PARTITION BY RANGE (d) ("
              "PARTITION p2023 VALUES LESS THAN ('2024-01-01'), "
              "PARTITION p2024 VALUES LESS THAN ('2025-01-01'))")
    s.execute("INSERT INTO ev VALUES ('2023-06-01', 1), ('2024-06-01', 2)")
    plan = _explain(s, "SELECT * FROM ev WHERE d < '2024-01-01'")
    assert "partition:p2023" in plan
    assert s.query("SELECT SUM(v) FROM ev WHERE d >= '2024-01-01'"
                   ).rows == [(2,)]
    with pytest.raises(PartitionError):
        s.execute("INSERT INTO ev VALUES ('2025-06-01', 3)")


def test_alter_partition_management(s):
    _mk_range(s)
    from tidb_tpu.errors import DDLError
    # TRUNCATE PARTITION drops the region set wholesale
    s.execute("ALTER TABLE r TRUNCATE PARTITION p1")
    assert s.query("SELECT COUNT(*) FROM r WHERE id >= 100 AND id < 200"
                   ).rows == [(0,)]
    assert s.query("SELECT COUNT(*) FROM r WHERE id < 100").rows == [(34,)]
    # ADD PARTITION only extends past the last bound (and never MAXVALUE)
    with pytest.raises(DDLError):
        s.execute("ALTER TABLE r ADD PARTITION "
                  "(PARTITION p3 VALUES LESS THAN (400))")
    # DROP a middle partition: later ordinals shift, rows reroute next
    s.execute("ALTER TABLE r DROP PARTITION p1")
    info = s.engine.catalog.info_schema.table("r")
    assert info.partition.names == ("p0", "p2")
    s.execute("INSERT INTO r VALUES (150, 7)")   # lands in old p2 range
    assert s.query("SELECT COUNT(*) FROM r WHERE id = 150").rows == [(1,)]
    plan = _explain(s, "SELECT * FROM r WHERE id < 50")
    assert "partition:p0" in plan
    # a bounded table can ADD past its last bound
    s.execute("CREATE TABLE ra (a BIGINT) PARTITION BY RANGE (a) ("
              "PARTITION q0 VALUES LESS THAN (10))")
    s.execute("ALTER TABLE ra ADD PARTITION "
              "(PARTITION q1 VALUES LESS THAN (20))")
    s.execute("INSERT INTO ra VALUES (15)")
    assert s.query("SELECT COUNT(*) FROM ra").rows == [(1,)]


def test_partition_error_never_half_applies_dml(s):
    """Review r5: a routing failure must not leave the delete half of an
    UPDATE (or REPLACE's conflict delete) staged."""
    s.execute("CREATE TABLE hp (id BIGINT PRIMARY KEY, v BIGINT) "
              "PARTITION BY RANGE (id) ("
              "PARTITION p0 VALUES LESS THAN (100))")
    s.execute("INSERT INTO hp VALUES (5, 1)")
    s.execute("BEGIN")
    with pytest.raises(PartitionError):
        s.execute("UPDATE hp SET id = 500 WHERE id = 5")
    s.execute("COMMIT")
    assert s.query("SELECT * FROM hp").rows == [(5, 1)]
    with pytest.raises(PartitionError):
        s.execute("REPLACE INTO hp VALUES (5, 999), (500, 2)")
    assert s.query("SELECT * FROM hp").rows == [(5, 1)]


def test_partition_restore_keeps_tags(tmp_path, s):
    from tidb_tpu.tools import backup, restore
    _mk_range(s)
    backup(s.engine, str(tmp_path / "bk"))
    eng2 = Engine()
    restore(eng2, str(tmp_path / "bk"))
    s2 = eng2.new_session()
    assert s2.query("SELECT COUNT(*) FROM r WHERE id < 100").rows == [(34,)]
    n = s2.execute("ALTER TABLE r TRUNCATE PARTITION p0")
    assert s2.query("SELECT COUNT(*) FROM r WHERE id < 100 AND "
                    "id IS NOT NULL").rows == [(0,)]
    assert s2.query("SELECT COUNT(*) FROM r WHERE id >= 100").rows == [(66,)]


def test_alter_add_partition_bad_bound(s):
    s.execute("CREATE TABLE ab (a BIGINT) PARTITION BY RANGE (a) ("
              "PARTITION p0 VALUES LESS THAN (10))")
    with pytest.raises(PlanError):
        s.execute("ALTER TABLE ab ADD PARTITION "
                  "(PARTITION p1 VALUES LESS THAN ('abc'))")


def test_review_r5_partition_findings(s):
    # inexact constants must not prune away satisfying rows
    s.execute("CREATE TABLE px (id BIGINT) PARTITION BY RANGE (id) ("
              "PARTITION p0 VALUES LESS THAN (99), "
              "PARTITION p1 VALUES LESS THAN (MAXVALUE))")
    s.execute("INSERT INTO px VALUES (98), (99), (100)")
    assert s.query("SELECT COUNT(*) FROM px WHERE id < 99.5").rows == \
        [(2,)]
    # int64-max lands in the MAXVALUE partition (no sentinel edge)
    s.execute(f"INSERT INTO px VALUES ({2**63 - 1})")
    assert s.query("SELECT COUNT(*) FROM px").rows == [(4,)]
