"""Builtin breadth: math/string/date functions + DISTINCT aggregates.

Two tiers (the reference's builtin_*_vec_test.go discipline): python-oracle
checks on the CPU engine, and CPU-vs-device differential for everything the
fragment engine claims (the vec == scalar twin-test, SURVEY §4)."""

import datetime as dt

import numpy as np
import pytest

from tidb_tpu.executor import build, run_to_completion
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE b (d DATE, ts DATETIME, x DOUBLE, "
              "s VARCHAR(24), n BIGINT, dec DECIMAL(10,3))")
    rng = np.random.default_rng(31)
    rows = []
    for i in range(4000):
        y, m, day = int(rng.integers(1990, 2025)), \
            int(rng.integers(1, 13)), int(rng.integers(1, 29))
        hh, mm, ss = (int(rng.integers(0, 24)), int(rng.integers(0, 60)),
                      int(rng.integers(0, 60)))
        x = round(float(rng.normal(0, 50)), 4)
        sv = ["alpha", "beta,gamma", "Hello World", "x"][
            int(rng.integers(0, 4))]
        n = int(rng.integers(-20, 21))
        dec = round(float(rng.uniform(-99, 99)), 3)
        rows.append(f"('{y}-{m:02d}-{day:02d}',"
                    f"'{y}-{m:02d}-{day:02d} {hh:02d}:{mm:02d}:{ss:02d}',"
                    f"{x},'{sv}',{n},{dec})")
    rows.append("(NULL,NULL,NULL,NULL,NULL,NULL)")
    s.execute("INSERT INTO b VALUES " + ",".join(rows))
    s.execute("ANALYZE TABLE b")
    return s


def q1(s, sql):
    return s.query(sql).rows[0][0]


# ---- python-oracle checks --------------------------------------------------

def test_date_arithmetic_oracle(session):
    s = session
    assert q1(s, "SELECT DATE_ADD('2020-01-31', INTERVAL 1 MONTH) FROM b "
                 "LIMIT 1") == dt.date(2020, 2, 29)
    assert q1(s, "SELECT DATE_SUB('2020-03-31', INTERVAL 1 MONTH) FROM b "
                 "LIMIT 1") == dt.date(2020, 2, 29)
    assert q1(s, "SELECT DATE_ADD('2020-02-29', INTERVAL 1 YEAR) FROM b "
                 "LIMIT 1") == dt.date(2021, 2, 28)
    assert q1(s, "SELECT DATEDIFF('2020-03-01', '2020-02-01') FROM b "
                 "LIMIT 1") == 29
    assert q1(s, "SELECT DAYOFWEEK('2026-07-26') FROM b LIMIT 1") == 1
    assert q1(s, "SELECT LAST_DAY('2024-02-10') FROM b LIMIT 1") == \
        dt.date(2024, 2, 29)
    assert q1(s, "SELECT HOUR('2020-01-01 13:45:59') FROM b LIMIT 1") == 13
    assert q1(s, "SELECT MINUTE('2020-01-01 13:45:59') FROM b LIMIT 1") == 45
    assert q1(s, "SELECT SECOND('2020-01-01 13:45:59') FROM b LIMIT 1") == 59
    assert q1(s, "SELECT DATE_ADD('2020-01-01', INTERVAL 25 HOUR) FROM b "
                 "LIMIT 1") == dt.datetime(2020, 1, 2, 1, 0, 0)


def test_date_parts_vs_python(session):
    rows = session.query(
        "SELECT d, DAYOFWEEK(d), WEEKDAY(d), DAYOFYEAR(d), QUARTER(d), "
        "LAST_DAY(d) FROM b WHERE d IS NOT NULL").rows
    import calendar
    for d, dow, wd, doy, qtr, last in rows[:500]:
        assert dow == (d.weekday() + 1) % 7 + 1
        assert wd == d.weekday()
        assert doy == d.timetuple().tm_yday
        assert qtr == (d.month + 2) // 3
        assert last == d.replace(
            day=calendar.monthrange(d.year, d.month)[1])


def test_math_oracle(session):
    s = session
    assert abs(q1(s, "SELECT EXP(1) FROM b LIMIT 1") - np.e) < 1e-12
    assert abs(q1(s, "SELECT LOG(2, 1024) FROM b LIMIT 1") - 10.0) < 1e-9
    assert q1(s, "SELECT LN(0) FROM b LIMIT 1") is None   # domain → NULL
    assert q1(s, "SELECT SIGN(-7) FROM b LIMIT 1") == -1
    assert float(q1(s, "SELECT TRUNCATE(3.7777, 2) FROM b LIMIT 1")) == \
        pytest.approx(3.77)
    assert q1(s, "SELECT TRUNCATE(dec, 1) FROM b WHERE dec IS NOT NULL "
                 "LIMIT 1") is not None
    assert q1(s, "SELECT GREATEST(1, 5, 3) FROM b LIMIT 1") == 5
    assert q1(s, "SELECT LEAST(1, NULL, 3) FROM b LIMIT 1") is None


def test_string_oracle(session):
    s = session
    assert q1(s, "SELECT SUBSTR('quadratic', 5) FROM b LIMIT 1") == "ratic"
    assert q1(s, "SELECT SUBSTR('quadratic', -3, 2) FROM b LIMIT 1") == "ti"
    assert q1(s, "SELECT CONCAT('a', NULL, 'c') FROM b LIMIT 1") is None
    assert q1(s, "SELECT CONCAT(1.5, ' x') FROM b LIMIT 1") == "1.5 x"
    assert q1(s, "SELECT LOCATE('bar', 'foobarbar', 5) FROM b LIMIT 1") == 7
    assert q1(s, "SELECT SUBSTRING_INDEX('a.b.c', '.', -1) FROM b LIMIT 1") \
        == "c"
    assert q1(s, "SELECT LPAD('hi', 5, '??') FROM b LIMIT 1") == "???hi"
    assert q1(s, "SELECT STRCMP('a', 'b') FROM b LIMIT 1") == -1


def test_distinct_aggregates_cpu(session):
    rows = session.query(
        "SELECT n, COUNT(DISTINCT s), SUM(DISTINCT n) FROM b "
        "WHERE n IS NOT NULL GROUP BY n").rows
    for n, cd, sd in rows:
        assert 1 <= cd <= 4
        assert sd == n          # SUM(DISTINCT n) grouped by n is n


# ---- device differential ---------------------------------------------------

def run_device(s, sql):
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags, f"no fragment extracted: {sql}"
        for f in frags:
            assert f.used_device, f"fell back ({f.fallback_reason}): {sql}"
        return [r for ch in chunks for r in ch.rows()]
    finally:
        s.vars["tidb_tpu_engine"] = "off"


def assert_same(rows1, rows2):
    assert len(rows1) == len(rows2)
    for r1, r2 in zip(sorted(rows1, key=str), sorted(rows2, key=str)):
        for v1, v2 in zip(r1, r2):
            if isinstance(v1, float) and v2 is not None:
                assert abs(v1 - v2) <= 1e-5 * max(1.0, abs(v2)), (r1, r2)
            else:
                assert v1 == v2, (r1, r2)


DEVICE_QUERIES = [
    # date builtins trace on device (civil-date int ops)
    "SELECT QUARTER(d), COUNT(*) FROM b GROUP BY QUARTER(d)",
    "SELECT DAYOFWEEK(d), COUNT(*), SUM(n) FROM b GROUP BY DAYOFWEEK(d)",
    "SELECT COUNT(*) FROM b WHERE DATEDIFF(d, '2000-01-01') > 0",
    "SELECT COUNT(*) FROM b WHERE d + INTERVAL 1 MONTH > '2020-06-15'",
    # math on device
    "SELECT SIGN(n), COUNT(*) FROM b GROUP BY SIGN(n)",
    "SELECT COUNT(*), SUM(GREATEST(n, 0)) FROM b",
    # distinct aggregates on device (factorize-dedup)
    "SELECT n, COUNT(DISTINCT s) FROM b GROUP BY n",
    "SELECT QUARTER(d), COUNT(DISTINCT n), SUM(DISTINCT n) FROM b "
    "GROUP BY QUARTER(d)",
    "SELECT COUNT(DISTINCT n) FROM b",
]


@pytest.mark.parametrize("sql", DEVICE_QUERIES)
def test_device_matches_cpu(session, sql):
    assert_same(run_device(session, sql), session.query(sql).rows)


def test_epoch_digest_radix_builtins():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE bt (d DATETIME, x BIGINT, t VARCHAR(16))")
    s.execute("INSERT INTO bt VALUES ('2024-03-05 14:30:45', 255, 'abc')")
    r = s.query("SELECT UNIX_TIMESTAMP(d), "
                "FROM_UNIXTIME(UNIX_TIMESTAMP(d)) FROM bt").rows[0]
    assert r[0] == 1709649045
    assert str(r[1]) == "2024-03-05 14:30:45"
    r = s.query("SELECT MD5(t), SHA1(t), SHA2(t, 256), CRC32(t), BIN(x), "
                "OCT(x), UNHEX('414243') FROM bt").rows[0]
    assert r[0] == "900150983cd24fb0d6963f7d28e17f72"
    assert r[1] == "a9993e364706816aba3e25717850c26c9cd0d89d"
    assert r[2].startswith("ba7816bf8f01cfea")
    assert r[3] == 891568578
    assert (r[4], r[5], r[6]) == ("11111111", "377", "ABC")
    r = s.query("SELECT DATE_FORMAT(d, '%Y/%c/%e %T %M %a %p %%') "
                "FROM bt").rows[0][0]
    assert r == "2024/3/5 14:30:45 March Tue PM %"


def test_env_functions():
    from tidb_tpu.session import Engine
    eng = Engine()
    s = eng.new_session()
    assert s.query("SELECT VERSION()").rows[0][0] == "8.0.11-tidb-tpu"
    assert s.query("SELECT USER()").rows[0][0] == "root@%"
    assert s.query("SELECT DATABASE()").rows[0][0] == "test"
    assert s.query("SELECT CONNECTION_ID()").rows[0][0] == s.conn_id
    y = s.query("SELECT YEAR(NOW()), YEAR(CURDATE())").rows[0]
    assert y[0] >= 2026 and y[1] >= 2026
    assert s.query("SELECT UNIX_TIMESTAMP()").rows[0][0] > 1_700_000_000


# ---- round-4 breadth builtins ----------------------------------------------

def test_breadth_string_builtins():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE bb (v VARCHAR(20))")
    s.execute("INSERT INTO bb VALUES ('Hello')")
    r = s.query(
        "SELECT BIT_LENGTH(v), ORD(v), QUOTE(v), SOUNDEX(v), "
        "TO_BASE64(v), FROM_BASE64(TO_BASE64(v)), "
        "INSERT(v, 2, 3, 'XX'), FIELD(v, 'x', 'Hello', 'y'), "
        "ELT(2, 'a', 'b'), CHAR(72, 105) FROM bb").rows[0]
    assert r == (40, 72, "'Hello'", "H400", "SGVsbG8=", "Hello",
                 "HXXo", 2, "b", "Hi")


def test_round_scale_exact_half_away_from_zero():
    """ROUND with a scale argument is EXACT decimal half-away-from-zero
    (the reference's types.Round): float arithmetic would turn 1.005
    into 1.00499…  and round it DOWN."""
    from decimal import Decimal
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    q = lambda sql: s.query(sql).rows[0][0]    # noqa: E731
    assert q("SELECT ROUND(1.005, 2)") == Decimal("1.01")
    assert q("SELECT ROUND(1.25, 1)") == Decimal("1.3")
    assert q("SELECT ROUND(-1.25, 1)") == Decimal("-1.3")
    assert q("SELECT ROUND(2.567, 10)") == Decimal("2.567")
    # half-away-from-zero at scale 0 (Python's round() would give 2/-2)
    assert q("SELECT ROUND(2.5)") == 3
    assert q("SELECT ROUND(-2.5)") == -3
    # negative scale zeroes digits LEFT of the point, on ints too
    assert q("SELECT ROUND(123.456, -2)") == 100
    assert q("SELECT ROUND(12345, -2)") == 12300


def test_cast_decimal_downscale_rounds_half_away():
    """CAST to a SMALLER scale rounds half away from zero (the same
    types.Round rule as ROUND) — it must never reinterpret the scaled
    int at the new scale (1.005 → 10.05)."""
    from decimal import Decimal
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    q = lambda sql: s.query(sql).rows[0][0]    # noqa: E731
    assert q("SELECT CAST(1.005 AS DECIMAL(10,2))") == Decimal("1.01")
    assert q("SELECT CAST(-1.005 AS DECIMAL(10,2))") == Decimal("-1.01")
    assert q("SELECT CAST(1.004 AS DECIMAL(10,2))") == Decimal("1.00")
    assert q("SELECT CAST(2.5 AS DECIMAL(10,0))") == 3
    assert q("SELECT CAST(-2.5 AS DECIMAL(10,0))") == -3
    # up-scale and same-scale stay exact
    assert q("SELECT CAST(1.005 AS DECIMAL(10,4))") == Decimal("1.0050")
    assert q("SELECT CAST(3 AS DECIMAL(10,2))") == Decimal("3.00")
    # column path (not constant-folded), host vs device
    s.execute("CREATE TABLE bdc (d DECIMAL(6,3))")
    s.execute("INSERT INTO bdc VALUES (1.005), (-1.005), (2.499), (NULL)")
    sql = "SELECT CAST(d AS DECIMAL(10,2)) FROM bdc"
    host = [r[0] for r in s.query(sql).rows]
    assert host == [Decimal("1.01"), Decimal("-1.01"),
                    Decimal("2.50"), None]
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1})
    assert [r[0] for r in s.query(sql).rows] == host


def test_breadth_math_misc_builtins():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE bm (n BIGINT)")
    s.execute("INSERT INTO bm VALUES (255)")
    r = s.query(
        "SELECT CONV(n, 10, 16), CONV('ff', 16, 10), "
        "FORMAT(1234567.891, 2), INET_ATON('192.168.0.1'), "
        "INET_NTOA(3232235521), ATAN2(1, 1) FROM bm").rows[0]
    assert r[:5] == ("FF", "255", "1,234,567.89", 3232235521,
                     "192.168.0.1")
    assert abs(r[5] - 0.7853981634) < 1e-9
    u = s.query("SELECT UUID() FROM bm").rows[0][0]
    assert len(u) == 36 and u.count("-") == 4


def test_breadth_temporal_builtins():
    import datetime as dt
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE bt (d DATE, t DATETIME)")
    s.execute("INSERT INTO bt VALUES ('2024-03-15', "
              "'2024-03-15 10:30:45.123456')")
    r = s.query(
        "SELECT TO_DAYS(d), FROM_DAYS(TO_DAYS(d)), YEARWEEK(d), "
        "MAKEDATE(2024, 75), TIME_TO_SEC(t), MICROSECOND(t), "
        "STR_TO_DATE('15,3,2024', '%d,%m,%Y') FROM bt").rows[0]
    assert r[0] == 739325                      # MySQL TO_DAYS value
    assert r[1] == dt.date(2024, 3, 15)
    assert r[2] == 202411
    assert r[3] == dt.date(2024, 3, 15)
    assert r[4] == 10 * 3600 + 30 * 60 + 45
    assert r[5] == 123456
    assert r[6] == dt.datetime(2024, 3, 15)
    r = s.query(
        "SELECT TIMESTAMPDIFF(day, d, '2024-04-15'), "
        "TIMESTAMPDIFF(month, '2023-01-31', '2024-03-01'), "
        "TIMESTAMPDIFF(year, '2020-06-01', '2024-05-31'), "
        "TIMESTAMPADD(hour, 5, t) FROM bt").rows[0]
    assert r[0] == 31 and r[1] == 13 and r[2] == 3
    assert r[3] == dt.datetime(2024, 3, 15, 15, 30, 45, 123456)


def test_breadth_error_codes():
    import pytest
    from tidb_tpu.errors import (NotNullViolation, SubqueryRowError,
                                 UnsupportedFunctionError)
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE ec (a BIGINT NOT NULL, b BIGINT)")
    s.execute("INSERT INTO ec VALUES (1, 2), (2, 3)")
    with pytest.raises(NotNullViolation) as e:
        s.execute("INSERT INTO ec VALUES (NULL, 4)")
    assert e.value.code == 1048
    with pytest.raises(UnsupportedFunctionError) as e:
        s.query("SELECT NO_SUCH_FN(a) FROM ec")
    assert e.value.code == 1305
    with pytest.raises(SubqueryRowError) as e:
        s.query("SELECT * FROM ec WHERE b = (SELECT a FROM ec)")
    assert e.value.code == 1242


def test_set_global_persists_via_backup(tmp_path):
    from tidb_tpu.session import Engine
    from tidb_tpu.tools import backup, restore
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE gp (a BIGINT)")
    s.execute("SET GLOBAL tidb_tpu_row_threshold = 777")
    s.execute("CREATE USER alice IDENTIFIED BY 'pw'")
    s.execute("GRANT SELECT ON gp TO alice")
    # SET GLOBAL must NOT touch the CURRENT session (MySQL scoping)
    assert s.vars.get("tidb_tpu_row_threshold") != 777
    assert eng.new_session().vars["tidb_tpu_row_threshold"] == 777
    backup(eng, str(tmp_path))
    # "restart": a fresh engine restored from the image
    eng2 = Engine()
    restore(eng2, str(tmp_path))
    assert eng2.new_session().vars["tidb_tpu_row_threshold"] == 777
    assert "alice" in eng2.auth.users      # grant tables survived too
    eng2.auth.require("alice", "SELECT", "gp")


def test_show_grants_requires_privilege():
    import pytest
    from tidb_tpu.errors import SpecificAccessDeniedError
    from tidb_tpu.session import Engine
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE USER bob IDENTIFIED BY 'x'")
    s2 = eng.new_session()
    s2.user = "bob"
    s2.query("SHOW GRANTS")                 # own grants: fine
    with pytest.raises(SpecificAccessDeniedError) as ei:
        s2.query("SHOW GRANTS FOR root")    # other users: SUPER only
    assert ei.value.code == 1227


def test_regexp_rlike():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE rx (v VARCHAR(20))")
    s.execute("INSERT INTO rx VALUES ('hello42'), ('WORLD'), ('h2o')")
    assert s.query("SELECT COUNT(*) FROM rx WHERE v REGEXP '[0-9]+'"
                   ).rows[0][0] == 2
    assert s.query("SELECT COUNT(*) FROM rx WHERE v RLIKE '^h'"
                   ).rows[0][0] == 2
    assert s.query("SELECT COUNT(*) FROM rx WHERE v NOT REGEXP '[0-9]'"
                   ).rows[0][0] == 1
    # device path: prepared per-dictionary LUT (like LIKE)
    import numpy as np
    rng = np.random.default_rng(2)
    s.execute("INSERT INTO rx VALUES " + ",".join(
        f"('w{int(rng.integers(0, 100))}')" for _ in range(50000)))
    s.execute("ANALYZE TABLE rx")
    sql = "SELECT COUNT(*) FROM rx WHERE v REGEXP '^w[0-4]'"
    want = s.query(sql).rows
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_strict="on")
    try:
        got = s.query(sql).rows
    finally:
        s.vars.update(tidb_tpu_engine="off", tidb_tpu_strict="off")
    assert got == want


def test_batch2_temporal_builtins():
    import datetime as dt
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE b2 (t DATETIME)")
    s.execute("INSERT INTO b2 VALUES ('2024-03-15 10:00:00')")
    r = s.query(
        "SELECT WEEKOFYEAR(t), PERIOD_ADD(202411, 3), "
        "PERIOD_DIFF(202403, 202311), MAKETIME(10, 30, 15), "
        "ADDTIME(t, MAKETIME(1, 0, 0)), SUBTIME(t, MAKETIME(0, 30, 0)) "
        "FROM b2").rows[0]
    assert r[0] == 11 and r[1] == 202502 and r[2] == 4
    assert r[3] == dt.timedelta(hours=10, minutes=30, seconds=15)
    assert r[4] == dt.datetime(2024, 3, 15, 11, 0)
    assert r[5] == dt.datetime(2024, 3, 15, 9, 30)
    r = s.query("SELECT MAKE_SET(5, 'a', 'b', 'c'), "
                "EXPORT_SET(5, 'Y', 'N', ',', 4) FROM b2").rows[0]
    assert r == ("a,c", "Y,N,Y,N")
    # NULL propagation through the row-loop helpers
    s.execute("INSERT INTO b2 VALUES (NULL)")
    rows = s.query("SELECT WEEKOFYEAR(t), MAKETIME(25, 99, 0) FROM b2"
                   ).rows
    assert (None, None) in [(r[0], r[1]) for r in rows]  # NULL row + bad
    assert all(r[1] is None for r in rows)   # invalid maketime everywhere


def test_extract():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE ex (d DATETIME)")
    s.execute("INSERT INTO ex VALUES ('2024-03-15 10:30:45.123456')")
    r = s.query("SELECT EXTRACT(year FROM d), EXTRACT(quarter FROM d), "
                "EXTRACT(day FROM d), EXTRACT(minute FROM d), "
                "EXTRACT(microsecond FROM d) FROM ex").rows[0]
    assert r == (2024, 1, 15, 30, 123456)


def test_advisor_r4_fixes():
    """Round-4 advisor findings: UUID() not constant-folded (distinct per
    row), INET_ATON malformed → NULL (builtin_miscellaneous.go)."""
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE adv (a BIGINT)")
    s.execute("INSERT INTO adv VALUES (1),(2),(3)")
    uuids = [r[0] for r in s.query("SELECT UUID() FROM adv").rows]
    assert len(set(uuids)) == 3
    # and a second execution (cached plan) yields fresh values
    uuids2 = [r[0] for r in s.query("SELECT UUID() FROM adv").rows]
    assert not set(uuids) & set(uuids2)
    r = s.query("SELECT INET_ATON('256.1.1.1'), INET_ATON('abc'), "
                "INET_ATON('1.2.3.4') FROM adv LIMIT 1").rows[0]
    assert r == (None, None, 16909060)


def test_nondeterministic_fold_propagates():
    # wrapping UUID() must not re-enable constant folding (UPPER(UUID()))
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE nf (a BIGINT)")
    s.execute("INSERT INTO nf VALUES (1),(2),(3)")
    got = [r[0] for r in s.query("SELECT UPPER(UUID()) FROM nf").rows]
    assert len(set(got)) == 3
