"""Memory tracker + disk spill (ref: util/memory/tracker.go,
util/chunk/row_container.go, executor/aggregate.go AggSpillDiskAction)."""

import numpy as np
import pytest

from tidb_tpu.errors import MemoryQuotaExceeded
from tidb_tpu.session import Engine
from tidb_tpu.util.memory import (PartitionedChunkSpill, Tracker,
                                  hash_partition)


def test_tracker_quota_and_handler():
    root = Tracker("q", quota=100)
    child = root.child("op")
    child.consume(60)
    assert root.consumed == 60 and child.consumed == 60
    fired = []

    def handler():
        fired.append(True)
        child.release(60)   # shed everything
        return True

    child.add_handler(handler)
    child.consume(80)       # 140 > 100 → handler sheds
    assert fired
    child.release(80)
    child.remove_handler(handler)
    with pytest.raises(MemoryQuotaExceeded):
        child.consume(200)


def test_hash_partition_null_and_negzero():
    keys = [(np.array([1.0, -0.0, 0.0, 5.5]),
             np.array([True, True, True, False]))]
    p = hash_partition(keys, 8)
    assert p[1] == p[2]      # -0.0 and 0.0 co-locate
    assert p[3] == p[3]      # NULL lands deterministically


def test_chunk_spill_roundtrip():
    from tidb_tpu import types as T
    from tidb_tpu.chunk import Chunk, Column
    fts = [T.bigint(), T.varchar()]
    sp = PartitionedChunkSpill(4, fts)
    c = Chunk([Column(fts[0], np.arange(10, dtype=np.int64), None),
               Column(fts[1], np.array([f"s{i}" for i in range(10)],
                                       dtype=object), None)])
    sp.add_partitioned(c, np.arange(10) % 4)
    total = 0
    for p in range(4):
        for ch in sp.read(p):
            total += ch.num_rows
            assert ch.columns[1].values[0].startswith("s")
    assert total == 10
    sp.close()


@pytest.fixture(scope="module")
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE big (k BIGINT, g BIGINT, s VARCHAR(8), "
              "x DOUBLE)")
    s.execute("CREATE TABLE dim (k BIGINT, name VARCHAR(8), "
              "PRIMARY KEY (k))")
    rng = np.random.default_rng(77)
    rows = []
    for i in range(40000):
        k = int(rng.integers(0, 9000))
        g = int(rng.integers(0, 3000))
        rows.append(f"({k},{g},'v{g % 11}',{round(float(rng.uniform(0, 9)), 3)})")
    s.execute("INSERT INTO big VALUES " + ",".join(rows))
    s.execute("INSERT INTO dim VALUES " +
              ",".join(f"({i},'n{i % 5}')" for i in range(8000)))
    s.execute("ANALYZE TABLE big")
    s.vars["max_chunk_size"] = 1024
    return s


SPILL_QUERIES = [
    "SELECT g, COUNT(*), SUM(x), COUNT(DISTINCT s) FROM big GROUP BY g",
    "SELECT name, COUNT(*), SUM(x) FROM big JOIN dim ON big.k = dim.k "
    "GROUP BY name",
    "SELECT COUNT(*) FROM big LEFT JOIN dim ON big.k = dim.k "
    "WHERE name IS NULL",
    "SELECT COUNT(*) FROM big WHERE k IN (SELECT k FROM dim WHERE k < 500)",
]


@pytest.mark.parametrize("sql", SPILL_QUERIES)
def test_spill_matches_in_memory(session, sql):
    s = session
    s.vars.pop("tidb_mem_quota_query", None)
    base = sorted(map(tuple, s.query(sql).rows), key=str)
    s.vars["tidb_mem_quota_query"] = 400_000
    try:
        spl = sorted(map(tuple, s.query(sql).rows), key=str)
    finally:
        s.vars.pop("tidb_mem_quota_query", None)
    assert len(base) == len(spl)
    for a, b in zip(base, spl):
        for x, y in zip(a, b):
            if isinstance(x, float):
                assert abs(x - y) <= 1e-6 * max(1.0, abs(x)), (a, b)
            else:
                assert x == y, (a, b)


def test_unspillable_query_cancels(session):
    s = session
    s.vars["tidb_mem_quota_query"] = 20_000
    try:
        with pytest.raises(MemoryQuotaExceeded):
            # cross join (no equi keys) cannot grace-partition
            s.query("SELECT COUNT(*) FROM big b1, big b2 "
                    "WHERE b1.x + b2.x > 100")
    finally:
        s.vars.pop("tidb_mem_quota_query", None)


def test_multi_slab_device_sort(session):
    # a full ORDER BY (no LIMIT → Sort root, not TopN) over small slabs:
    # device per-slab sort + host run merge must equal the CPU sort
    from tidb_tpu.executor import build, run_to_completion
    from tidb_tpu.executor.fragment import TpuFragmentExec
    from tidb_tpu.parser import parse
    s = session
    sql = "SELECT k, g, x FROM big ORDER BY x DESC, k, g"
    base = s.query(sql).rows
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_max_slab_rows=4096, tidb_tpu_strict="on")
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags and all(f.used_device for f in frags), \
            [f.fallback_reason for f in frags]
        dev = [r for ch in chunks for r in ch.rows()]
    finally:
        for k in ("tidb_tpu_engine", "tidb_tpu_row_threshold",
                  "tidb_tpu_max_slab_rows", "tidb_tpu_strict"):
            s.vars.pop(k, None)
    assert len(dev) == len(base)
    for a, b in zip(base, dev):
        assert a[0] == b[0] and a[1] == b[1], (a, b)


# ---- failpoints + GC -------------------------------------------------------

def test_failpoint_commit_error():
    from tidb_tpu.errors import TxnError
    from tidb_tpu.util import failpoint
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE fp (a BIGINT)")
    with failpoint.enabled("store-commit", raise_=TxnError("injected")):
        with pytest.raises(TxnError):
            s.execute("INSERT INTO fp VALUES (1)")
        assert failpoint.hits("store-commit") == 1
    # recovered after disable
    s.execute("INSERT INTO fp VALUES (2)")
    assert s.query("SELECT COUNT(*) FROM fp").rows == [(1,)]


def test_failpoint_device_fallback():
    from tidb_tpu.util import failpoint
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE fd (a BIGINT)")
    s.execute("INSERT INTO fd VALUES " +
              ",".join(f"({i})" for i in range(5000)))
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1)
    with failpoint.enabled("device-fragment",
                           raise_=RuntimeError("injected device loss")):
        # device dies → CPU fallback still answers correctly
        assert s.query("SELECT SUM(a) FROM fd").rows == [(12497500,)]
        assert failpoint.hits("device-fragment") >= 1


def test_gc_compaction_reclaims_tombstones():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE gc (a BIGINT)")
    s.execute("INSERT INTO gc VALUES " +
              ",".join(f"({i})" for i in range(10000)))
    info = eng.catalog.info_schema.table("gc")
    s.execute("DELETE FROM gc WHERE a < 8000")   # 80% dead → compaction
    live, dead, regions = eng.store.gc_stats(info.id)
    assert dead == 0, "tombstones not reclaimed"
    assert live == 2000
    assert s.query("SELECT COUNT(*), MIN(a) FROM gc").rows == [(2000, 8000)]
    # caches keyed by TableData identity see the rewrite
    s.execute("INSERT INTO gc VALUES (1)")
    assert s.query("SELECT COUNT(*) FROM gc WHERE a = 1").rows == [(1,)]


def test_parallel_partial_workers_match_sequential():
    # the hash-agg partial-worker pipeline (tidb_tpu_cpu_concurrency > 1)
    # must be byte-identical to sequential, incl. order-sensitive
    # first_row states and DISTINCT dedup
    import numpy as np
    from tidb_tpu.session import Engine
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE pw (g BIGINT, v BIGINT, t VARCHAR(4))")
    rng = np.random.default_rng(2)
    s.execute("INSERT INTO pw VALUES " + ",".join(
        f"({int(rng.integers(0, 50))},{int(rng.integers(0, 1000))},"
        f"'t{int(rng.integers(0, 3))}')" for i in range(30000)))
    s.vars["max_chunk_size"] = 1024      # many batches
    sql = ("SELECT g, COUNT(*), SUM(v), COUNT(DISTINCT v), MIN(t) "
           "FROM pw GROUP BY g ORDER BY g")
    s.vars["tidb_tpu_cpu_concurrency"] = 1
    seq = s.query(sql).rows
    s.vars["tidb_tpu_cpu_concurrency"] = 8
    par = s.query(sql).rows
    assert par == seq
