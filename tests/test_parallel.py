"""Distributed (multi-chip) layer tests on the 8-device virtual CPU mesh —
the in-process cluster pattern of the reference's unistore MPP tests
(SURVEY §4 tier 2: executor/tiflash_test.go runs real MPP plans against an
in-process fake cluster)."""

import numpy as np
import pytest

from tidb_tpu.ops.jax_env import jnp
from tidb_tpu.parallel import make_mesh, shard_rows
from tidb_tpu.parallel import collective as C
from tidb_tpu.parallel.dist_query import (build_agg_join_step,
                                          reference_agg_join)


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh(8)


def test_exchange_round_trip(mesh):
    """Hash exchange delivers every live row exactly once, to its owner."""
    from tidb_tpu.ops.jax_env import shard_map
    import jax

    N = 512
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 10 ** 6, N).astype(np.int64)
    live = rng.random(N) < 0.8
    P = jax.sharding.PartitionSpec

    def step(v, lv):
        dest = C.shard_of(v, 8)
        (rv,), r_live, need = C.exchange([v], dest, lv, 8, N)
        return rv, r_live, need

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=(P("shard"),) * 2,
                           out_specs=(P("shard"), P("shard"), P()),
                           check_rep=False))
    sv, sl = shard_rows(mesh, [vals, live])
    rv, rl, need = fn(sv, sl)
    assert int(need) <= N           # capacity sufficed: nothing dropped
    rv, rl = np.asarray(rv), np.asarray(rl)
    received = sorted(rv[rl].tolist())
    assert received == sorted(vals[live].tolist())
    # ownership: every received row landed on the shard its hash names
    per_shard = rv.reshape(8, -1), rl.reshape(8, -1)
    for shard in range(8):
        v, m = per_shard[0][shard], per_shard[1][shard]
        owners = np.asarray(C.shard_of(jnp.asarray(v[m]), 8))
        assert (owners == shard).all()


def test_exchange_overflow_detected(mesh):
    from tidb_tpu.ops.jax_env import shard_map
    import jax

    N = 256
    vals = np.full(N, 12345, dtype=np.int64)  # all rows → one bucket
    live = np.ones(N, dtype=bool)
    P = jax.sharding.PartitionSpec

    def step(v, lv):
        dest = C.shard_of(v, 8)
        (_rv,), _rl, need = C.exchange([v], dest, lv, 8, 4)
        return need

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=(P("shard"),) * 2,
                           out_specs=P(), check_rep=False))
    # all 256 rows hash to one destination: the reported need is exact,
    # so the caller can size the retry in ONE recompile
    assert int(fn(*shard_rows(mesh, [vals, live]))) == 32  # 256/8 per shard


def test_distributed_agg_join_matches_oracle(mesh):
    rng = np.random.default_rng(0)
    N, B = 1024, 256
    pk = rng.integers(0, B, N).astype(np.int64)
    px = rng.uniform(0, 10, N)
    pq = rng.uniform(0, 1, N)
    bk = np.arange(B, dtype=np.int64)
    bg = rng.integers(0, 5, B).astype(np.int64)
    bw = rng.uniform(0.5, 1.5, B)
    step = build_agg_join_step(mesh, bucket_cap=N, group_cap=64,
                               filter_limit=0.7)
    args = shard_rows(mesh, [pk, px, pq, np.ones(N, bool),
                             bk, bg, bw, np.ones(B, bool)])
    kv, km, sums, counts, live, need, gneed = step(*args)
    assert int(need) <= N and int(gneed) <= 64  # capacities held
    kv, km, sums, counts, live = map(np.asarray,
                                     (kv, km, sums, counts, live))
    got = {}
    for g, m, s, c, lv in zip(kv, km, sums, counts, live):
        if lv and m:
            assert int(g) not in got  # shards own disjoint group sets
            got[int(g)] = (float(s), int(c))
    ref_s, ref_c = reference_agg_join(pk, px, pq, bk, bg, bw, 0.7)
    assert set(got) == set(ref_s)
    for g in ref_s:
        assert got[g][1] == ref_c[g]
        assert abs(got[g][0] - ref_s[g]) <= 1e-6 * max(1, abs(ref_s[g]))


def test_broadcast_build(mesh):
    from tidb_tpu.ops.jax_env import shard_map
    import jax

    N = 64
    vals = np.arange(N, dtype=np.int64)
    live = np.ones(N, dtype=bool)
    P = jax.sharding.PartitionSpec

    def step(v, lv):
        (g,), gl = C.broadcast_build([v], lv)
        return g.sum(), gl.sum()

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=(P("shard"),) * 2,
                           out_specs=(P(), P()), check_rep=False))
    s, c = fn(*shard_rows(mesh, [vals, live]))
    assert int(s) == vals.sum() and int(c) == N


def test_cpu_concurrency_process_pool_matches_sequential():
    """tidb_tpu_cpu_concurrency > 1 routes batch partials through the
    spawned process pool (executor/aggregate.go's partial-worker graph
    with OS processes in the worker role — numpy holds the GIL, threads
    cannot scale it). Results must match the sequential path exactly,
    including ci collations and DISTINCT aggs."""
    import numpy as np

    from tidb_tpu.session import Engine
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE mp (g VARCHAR(8) COLLATE utf8mb4_general_ci, "
              "v BIGINT, w DECIMAL(12,2))")
    rng = np.random.default_rng(13)
    names = ["Red", "RED", "blue", "BLUE", "green"]
    s.execute("INSERT INTO mp VALUES " + ",".join(
        f"('{names[int(rng.integers(0, 5))]}',{int(rng.integers(0, 50))},"
        f"{int(rng.integers(0, 10000)) / 100})" for _ in range(200_000)))
    sqls = [
        "SELECT g, COUNT(*), SUM(v), AVG(w), MIN(v), MAX(w) FROM mp "
        "GROUP BY g",
        "SELECT COUNT(*), SUM(v * 2), COUNT(DISTINCT v) FROM mp",
        "SELECT g, COUNT(DISTINCT v) FROM mp GROUP BY g",
    ]
    want = [sorted(map(str, s.query(q).rows)) for q in sqls]
    s.vars["tidb_tpu_cpu_concurrency"] = 4
    try:
        got = [sorted(map(str, s.query(q).rows)) for q in sqls]
    finally:
        s.vars["tidb_tpu_cpu_concurrency"] = 1
    assert got == want


def test_cpu_concurrency_wide_decimal_matches_sequential():
    # review r5: wide-decimal object columns must survive the worker pipe
    # with their Python-int values intact (stringifying corrupts SUM/MIN)
    import numpy as np

    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE wd (g BIGINT, w DECIMAL(30,2))")
    s.execute("INSERT INTO wd VALUES " + ",".join(
        f"({i % 3},{10**20 + i}.25)" for i in range(5000)))
    q = "SELECT g, SUM(w), MIN(w), MAX(w) FROM wd GROUP BY g ORDER BY g"
    want = s.query(q).rows
    s.vars["tidb_tpu_cpu_concurrency"] = 2
    try:
        got = s.query(q).rows
    finally:
        s.vars["tidb_tpu_cpu_concurrency"] = 1
    assert got == want
