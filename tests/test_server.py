"""MySQL wire protocol + observability surfaces.

The hand-rolled client below implements enough of the protocol-41 text
path (handshake response, COM_QUERY, resultset/OK/ERR parsing) to act as
a stand-in for a stock driver — the reference tests the same surface via
real clients (server/conn_test.go)."""

import json
import socket
import struct
import urllib.request

import pytest

from tidb_tpu.server import Server
from tidb_tpu.session import Engine
from tidb_tpu.util.status_server import StatusServer


class MiniClient:
    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        self.seq = 0
        self._handshake()

    def _recv(self, n):
        buf = b""
        while len(buf) < n:
            part = self.sock.recv(n - len(buf))
            assert part, "server closed"
            buf += part
        return buf

    def read_packet(self):
        h = self._recv(4)
        ln = h[0] | (h[1] << 8) | (h[2] << 16)
        self.seq = (h[3] + 1) & 0xFF
        return self._recv(ln)

    def write_packet(self, payload):
        self.sock.sendall(struct.pack("<I", len(payload))[:3]
                          + bytes([self.seq]) + payload)
        self.seq = (self.seq + 1) & 0xFF

    def _handshake(self):
        greeting = self.read_packet()
        assert greeting[0] == 10              # protocol v10
        assert b"tidb-tpu" in greeting
        caps = 0x0200 | 0x8000 | 0x1 | 0x200  # PROTOCOL_41 | SECURE_CONN
        resp = (struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
                + bytes([0xFF]) + b"\x00" * 23
                + b"root\x00" + b"\x00")      # empty auth
        self.write_packet(resp)
        ok = self.read_packet()
        assert ok[0] == 0x00, ok

    @staticmethod
    def _lenenc(data, i):
        c = data[i]
        if c < 251:
            return c, i + 1
        if c == 0xFC:
            return data[i + 1] | (data[i + 2] << 8), i + 3
        if c == 0xFD:
            return int.from_bytes(data[i + 1:i + 4], "little"), i + 4
        return int.from_bytes(data[i + 1:i + 9], "little"), i + 9

    def query(self, sql):
        self.seq = 0
        self.write_packet(b"\x03" + sql.encode())
        first = self.read_packet()
        if first[0] == 0xFF:
            code = struct.unpack("<H", first[1:3])[0]
            raise RuntimeError(f"ERR {code}: "
                               f"{first[9:].decode(errors='replace')}")
        if first[0] == 0x00:
            affected, _ = self._lenenc(first, 1)
            return {"ok": True, "affected": affected}
        ncols, _ = self._lenenc(first, 0)
        names = []
        for _ in range(ncols):
            col = self.read_packet()
            i = 0
            parts = []
            for _f in range(6):
                ln, i = self._lenenc(col, i)
                parts.append(col[i:i + ln])
                i += ln
            names.append(parts[4].decode())
        eof = self.read_packet()
        assert eof[0] == 0xFE
        rows = []
        while True:
            pkt = self.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            i = 0
            row = []
            while i < len(pkt):
                if pkt[i] == 0xFB:
                    row.append(None)
                    i += 1
                else:
                    ln, i = self._lenenc(pkt, i)
                    row.append(pkt[i:i + ln].decode())
                    i += ln
            rows.append(tuple(row))
        return {"names": names, "rows": rows}

    def ping(self):
        self.seq = 0
        self.write_packet(b"\x0e")
        return self.read_packet()[0] == 0x00

    def close(self):
        self.seq = 0
        try:
            self.write_packet(b"\x01")
        finally:
            self.sock.close()


@pytest.fixture(scope="module")
def server():
    srv = Server(Engine(), port=0).start()
    yield srv
    srv.stop()


def test_handshake_and_ping(server):
    c = MiniClient(server.port)
    assert c.ping()
    c.close()


def test_ddl_dml_query_roundtrip(server):
    c = MiniClient(server.port)
    r = c.query("CREATE TABLE srv (a BIGINT, b VARCHAR(10), c DOUBLE)")
    assert r["ok"]
    r = c.query("INSERT INTO srv VALUES (1,'x',1.5),(2,'y',NULL),"
                "(3,NULL,2.25)")
    assert r["affected"] == 3
    r = c.query("SELECT a, b, c FROM srv ORDER BY a")
    assert r["names"] == ["a", "b", "c"]
    assert r["rows"] == [("1", "x", "1.5"), ("2", "y", None),
                        ("3", None, "2.25")]
    r = c.query("SELECT COUNT(*), SUM(a) FROM srv")
    assert r["rows"] == [("3", "6")]
    c.close()


def test_error_packet_carries_mysql_code(server):
    c = MiniClient(server.port)
    with pytest.raises(RuntimeError) as ei:
        c.query("SELECT * FROM no_such_table")
    assert "ERR" in str(ei.value)
    # session survives the error
    r = c.query("SELECT 2")
    assert r["rows"] == [("2",)]
    c.close()


def test_concurrent_connections_have_isolated_sessions(server):
    c1 = MiniClient(server.port)
    c2 = MiniClient(server.port)
    c1.query("SET @@max_chunk_size = 64")
    r1 = c1.query("SHOW VARIABLES LIKE 'max_chunk%'")
    r2 = c2.query("SHOW VARIABLES LIKE 'max_chunk%'")
    assert r1["rows"] != r2["rows"]
    c1.close()
    c2.close()


def test_transactions_over_wire(server):
    c = MiniClient(server.port)
    c.query("CREATE TABLE txw (a BIGINT)")
    c.query("BEGIN")
    c.query("INSERT INTO txw VALUES (1)")
    c.query("ROLLBACK")
    assert c.query("SELECT COUNT(*) FROM txw")["rows"] == [("0",)]
    c.query("BEGIN")
    c.query("INSERT INTO txw VALUES (2)")
    c.query("COMMIT")
    assert c.query("SELECT COUNT(*) FROM txw")["rows"] == [("1",)]
    c.close()


# ---- observability ---------------------------------------------------------

def test_metrics_and_summaries():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ob (a BIGINT)")
    s.execute("INSERT INTO ob VALUES (1),(2),(3)")
    s.vars["long_query_time"] = 0.0    # capture everything as slow
    s.query("SELECT SUM(a) FROM ob WHERE a > 0")
    rows = s.query("SHOW METRICS").rows
    names = {r[0] for r in rows}
    assert "tidb_tpu_stmt_total" in names
    assert "tidb_tpu_stmt_seconds_count" in names
    slow = s.query("SHOW SLOW QUERIES").rows
    assert any("SELECT SUM" in r[4] for r in slow)
    summ = s.query("SHOW STATEMENT SUMMARY").rows
    assert any("select sum ( a ) from ob" in r[0].lower() or
               "sum" in r[0].lower() for r in summ)


def test_status_http_endpoint():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE h (a BIGINT)")
    srv = StatusServer(eng, port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics") as r:
            text = r.read().decode()
        assert "tidb_tpu_stmt_total" in text
        assert "_bucket{" in text
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/status") as r:
            payload = json.loads(r.read())
        assert payload["status"] == "ok"
        assert any("create table h" in j for j in payload["ddl_history"])
    finally:
        srv.stop()


def test_show_processlist_and_indexes():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE pi (a BIGINT, PRIMARY KEY (a))")
    s.execute("CREATE INDEX ia ON pi (a)")
    rows = s.query("SHOW INDEXES FROM pi").rows
    assert ("pi", 0, "PRIMARY", 1, "a", "BTREE", "public") in rows
    assert ("pi", 1, "ia", 1, "a", "BTREE", "public") in rows
    assert s.query("SHOW PROCESSLIST").rows is not None


def test_tls_connection(tmp_path):
    # TLS upgrade (server/conn.go TLS branch): self-signed cert, client
    # sends SSLRequest, both sides wrap, auth + queries ride TLS
    import subprocess
    from tidb_tpu.client import Client
    from tidb_tpu.server import Server
    from tidb_tpu.session import Engine
    cert = str(tmp_path / "c.pem")
    key = str(tmp_path / "k.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "1",
         "-subj", "/CN=localhost"],
        check=True, capture_output=True)
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE t (a BIGINT)")
    s.execute("INSERT INTO t VALUES (42)")
    srv = Server(eng, port=0, ssl_cert=cert, ssl_key=key).start()
    try:
        c = Client(port=srv.port, ssl=True)
        _names, rows = c.query("SELECT a FROM t")
        assert rows == [("42",)]
        c.close()
        # plaintext clients still work when TLS is optional
        c2 = Client(port=srv.port)
        _n, rows = c2.query("SELECT a + 1 FROM t")
        assert rows == [("43",)]
        c2.close()
    finally:
        srv.stop()
    # ssl=True against a non-TLS server: clear error, not an SSL panic
    import pytest
    from tidb_tpu.client import ClientError
    srv2 = Server(eng, port=0).start()
    try:
        with pytest.raises(ClientError, match="does not support SSL"):
            Client(port=srv2.port, ssl=True)
    finally:
        srv2.stop()
