"""Tier-1 perf guardrails (tiny scale, CPU backend, fast).

Not a benchmark — these pin the two properties the overlap runtime's
speed rests on, which a correctness suite would never notice breaking:

* warm-path stability: repeating an identical query must trace ZERO new
  programs (PROGRAM_TRACES frozen) and re-upload NOTHING (the cache
  entry's device arrays keep their identities);
* phase accounting: a cold multi-slab first touch must attribute time
  to every pipeline phase (encode/upload/compute/fetch/decode) with a
  sane overlap-efficiency ratio, because bench.py and EXPLAIN ANALYZE
  report those numbers as the optimization's evidence.
"""

import numpy as np
import pytest

from tidb_tpu.executor import device_cache as dc
from tidb_tpu.executor import fragment
from tidb_tpu.session import Engine

pytestmark = pytest.mark.perf_smoke

SQL = "SELECT c, COUNT(*), SUM(a), AVG(b) FROM p GROUP BY c"


@pytest.fixture()
def session():
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE p (a BIGINT, b DOUBLE, c VARCHAR(8))")
    rng = np.random.default_rng(3)
    words = ["ant", "bee", "cow", "dog"]
    rows = [f"({int(rng.integers(0, 100))},{float(rng.normal()):.4f},"
            f"'{words[int(rng.integers(0, 4))]}')" for _ in range(3000)]
    s.execute("INSERT INTO p VALUES " + ",".join(rows))
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    s.vars["tidb_tpu_max_slab_rows"] = 1024   # 3 slabs → real streaming
    return eng, s


def _entry(eng):
    tid = eng.catalog.info_schema.table("p").id
    for (_dev, sid, t, _parts), ent in dc._CACHE.items():
        if sid == id(eng.store) and t == tid:
            return ent
    raise AssertionError("table p not cached")


def test_cold_first_touch_reports_all_phases(session):
    eng, s = session
    rows_cold = s.query(SQL).rows
    assert rows_cold
    ph = fragment.LAST_PHASES
    assert ph is not None
    d = ph.as_dict()
    # the cold run really encoded and uploaded (first touch) and computed
    assert d["encode_s"] > 0.0
    assert d["upload_s"] > 0.0
    assert d["compute_s"] > 0.0
    assert d["decode_s"] >= 0.0
    assert 0.0 <= d["overlap_efficiency"] <= 1.0
    assert ph.total > 0.0


def test_warm_concurrency_zero_retraces_zero_reuploads(session):
    """8 threads re-running the warm query concurrently: ZERO new traces
    (per-signature build locks make the compile cache single-flight) and
    ZERO re-uploads (every thread reuses the same device arrays) — the
    serving-throughput claim rests on the warm path staying warm under
    concurrency, not just in a single-threaded loop."""
    import threading
    eng, s = session
    rows_cold = s.query(SQL).rows          # cold: trace + first touch
    ent = _entry(eng)
    dev_ids = {i: [id(v) for v, _m in slabs]
               for i, slabs in ent.dev.items()}
    traces = fragment.PROGRAM_TRACES

    sessions = []
    for _ in range(8):
        ss = eng.new_session()
        ss.vars["tidb_tpu_engine"] = "on"
        ss.vars["tidb_tpu_row_threshold"] = 1
        ss.vars["tidb_tpu_max_slab_rows"] = 1024
        sessions.append(ss)
    failures = []
    barrier = threading.Barrier(8)

    def worker(k):
        barrier.wait()
        for _ in range(3):
            if sessions[k].query(SQL).rows != rows_cold:
                failures.append(f"thread {k} diverged")

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads), "warm replay hung"
    assert not failures, failures
    assert fragment.PROGRAM_TRACES == traces, \
        "concurrent warm replays re-traced a program"
    ent2 = _entry(eng)
    assert ent2 is ent, "concurrent warm replays rebuilt the cache entry"
    for i, ids in dev_ids.items():
        assert [id(v) for v, _m in ent.dev[i]] == ids, \
            f"column {i} re-uploaded under warm concurrency"


def test_repeat_query_zero_retraces_and_no_reupload(session):
    eng, s = session
    rows_cold = s.query(SQL).rows          # cold: trace + first touch
    ent = _entry(eng)
    dev_ids = {i: [id(v) for v, _m in slabs]
               for i, slabs in ent.dev.items()}
    assert dev_ids, "cold run left no device arrays cached"
    traces = fragment.PROGRAM_TRACES

    rows_warm = s.query(SQL).rows          # warm: must reuse everything
    assert fragment.PROGRAM_TRACES == traces, \
        "repeated identical query re-traced a program"
    ent2 = _entry(eng)
    assert ent2 is ent, "repeated query rebuilt the cache entry"
    for i, ids in dev_ids.items():
        assert [id(v) for v, _m in ent.dev[i]] == ids, \
            f"column {i} re-uploaded on a warm repeat"
    assert sorted(map(str, rows_warm)) == sorted(map(str, rows_cold))
    # warm run uploads nothing: its phase record shows no upload seconds
    ph = fragment.LAST_PHASES
    assert ph is not None and ph.as_dict()["upload_s"] == 0.0


def test_warm_selective_scan_launches_only_surviving_slabs():
    """Zone-map slab skipping on the warm path: a selective predicate
    over a sorted column launches exactly `surviving_slabs + 1` programs
    (one partial per surviving slab + the merge), re-uploads ZERO bytes,
    and the Chrome trace carries NO compute spans for the skipped slabs
    — the skip is free, not merely cheap."""
    import json
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE q (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO q VALUES " +
              ",".join(f"({i}, {i % 7})" for i in range(3072)))
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    s.vars["tidb_tpu_max_slab_rows"] = 1024   # 3 slabs, sorted → partitioned
    sel = "SELECT COUNT(*), SUM(a) FROM q WHERE a >= 1024"
    full = "SELECT COUNT(*), SUM(a) FROM q"
    rows_cold = s.query(sel).rows              # cold: encode + upload
    tid = eng.catalog.info_schema.table("q").id
    ent = next(e for (_d, sid, t, _p), e in dc._CACHE.items()
               if sid == id(eng.store) and t == tid)
    # cold-pruned slab 0 committed as a hole (None placeholder): its
    # encode+upload never happened at all
    assert any(t is None for slabs in ent.dev.values() for t in slabs), \
        "cold prune must leave holes, not upload pruned slabs"
    dev_ids = {i: [None if t is None else id(t[0]) for t in slabs]
               for i, slabs in ent.dev.items()}
    traces = fragment.PROGRAM_TRACES

    rows_warm = s.query(sel).rows
    assert rows_warm == rows_cold
    ph = s.last_guard.phases
    assert ph.slabs_skipped == 1, "slab 0 (a in [0,1023]) must be pruned"
    surviving = 2
    assert ph.programs_launched == surviving + 1, \
        f"warm selective launches: {ph.programs_launched}"
    assert ph.h2d_bytes == 0 and ph.as_dict()["upload_s"] == 0.0
    assert fragment.PROGRAM_TRACES == traces, "warm repeat re-traced"
    for i, ids in dev_ids.items():
        now = [None if t is None else id(t[0]) for t in ent.dev[i]]
        assert now == ids, \
            f"column {i} re-uploaded on a pruned warm repeat"

    # Chrome trace: skipping removes exactly the pruned slabs' compute
    # spans (the unfiltered warm run is the 3-slab baseline)
    s.query(full)                              # warm the unfiltered shape

    def compute_spans(sql):
        doc = json.loads(s.query("TRACE FORMAT='chrome' " + sql).rows[0][0])
        return len([e for e in doc["traceEvents"]
                    if e.get("ph") != "M" and e["cat"] == "compute"])

    assert compute_spans(full) - compute_spans(sel) == ph.slabs_skipped


def test_warm_read_after_appends_no_base_reupload_one_extra_launch(session):
    """The HTAP write-path pin: K single-row appends between two warm
    reads must cost the reader ONE delta-slab upload and at most ONE
    extra program launch — ZERO base slabs re-encoded or re-uploaded
    (they are shared by identity across delta generations), and the
    second warm read uploads nothing at all."""
    eng, s = session
    s.vars["tidb_tpu_compaction"] = "off"     # no async rebuild mid-test
    s.query(SQL)                               # cold: trace + first touch
    s.query(SQL)                               # warm baseline
    base_launches = s.last_guard.phases.programs_launched
    ent = _entry(eng)
    n_base = ent.base_slabs
    base_ids = {i: [id(t[0]) for t in slabs[:n_base] if t is not None]
                for i, slabs in ent.dev.items()}

    K = 4
    for k in range(K):
        # in-range values: a within the base FoR bounds, c in the base
        # dictionary — the appends must EXTEND, not rebuild
        s.query(f"INSERT INTO p VALUES ({40 + k}, 0.5, 'ant')")

    rows = s.query(SQL).rows                   # pays the one delta upload
    ent2 = _entry(eng)
    assert ent2.is_delta and ent2.delta_rows == K, \
        "appends must ride the delta extension, not a rebuild"
    for i, ids in base_ids.items():
        now = [id(t[0]) for t in ent2.dev[i][:n_base] if t is not None]
        assert now == ids, f"column {i} base slabs re-uploaded"
    ph = s.last_guard.phases
    assert ph.programs_launched <= base_launches + 1, \
        (f"delta merge cost {ph.programs_launched - base_launches} "
         f"extra launches (max 1: the delta-slab partial)")

    rows2 = s.query(SQL).rows                  # fully warm again
    ph2 = s.last_guard.phases
    assert ph2.h2d_bytes == 0 and ph2.as_dict()["upload_s"] == 0.0, \
        "second warm read after appends must upload nothing"
    assert ph2.programs_launched <= base_launches + 1
    assert sorted(map(str, rows2)) == sorted(map(str, rows))
    # and the rows are RIGHT: the appended 'ant' rows are visible
    got = {r[0]: r[1] for r in rows}
    s.vars["tidb_tpu_engine"] = "off"
    want = {r[0]: r[1] for r in s.query(SQL).rows}
    s.vars["tidb_tpu_engine"] = "on"
    assert got == want
