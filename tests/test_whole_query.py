"""Whole-query compilation over the shapes PR 12 left on the host:
EXISTS/IN semijoins, uncorrelated scalar subqueries, LIMIT-over-join
roots, and multi-arg / multiple-DISTINCT aggregates — each fused vs the
CPU volcano oracle, plus warm launch-count pins."""

import numpy as np
import pytest

from tidb_tpu.executor import build, run_to_completion
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ord (ok BIGINT, pri VARCHAR(8), "
              "odate BIGINT, ck BIGINT)")
    s.execute("CREATE TABLE li (ok BIGINT, qty BIGINT, price DOUBLE, "
              "disc DOUBLE, sdate BIGINT, cdate BIGINT)")
    rng = np.random.default_rng(23)
    orows = []
    for k in range(1500):
        pri = ["'1-URG'", "'2-HIGH'", "'3-MED'", "'4-LOW'"][
            int(rng.integers(0, 4))]
        orows.append(f"({k},{pri},{int(rng.integers(0, 1000))},"
                     f"{int(rng.integers(0, 200))})")
    for i in range(0, len(orows), 500):
        s.execute("INSERT INTO ord VALUES " + ",".join(orows[i:i + 500]))
    lrows = []
    for _ in range(5000):
        ok = int(rng.integers(0, 1800))       # some orders have no items
        sd = int(rng.integers(0, 1000))
        lrows.append(f"({ok},{int(rng.integers(1, 50))},"
                     f"{round(float(rng.uniform(1, 1000)), 2)},"
                     f"{round(float(rng.uniform(0, 0.1)), 2)},"
                     f"{sd},{sd + int(rng.integers(-30, 30))})")
    for i in range(0, len(lrows), 500):
        s.execute("INSERT INTO li VALUES " + ",".join(lrows[i:i + 500]))
    s.execute("CREATE TABLE md (g BIGINT, a BIGINT, b BIGINT, "
              "v BIGINT)")
    mrows = []
    for _ in range(3000):
        mrows.append(f"({int(rng.integers(0, 6))},"
                     f"{int(rng.integers(0, 12))},"
                     f"{int(rng.integers(0, 9))},"
                     f"{int(rng.integers(0, 400))})")
    for i in range(0, len(mrows), 500):
        s.execute("INSERT INTO md VALUES " + ",".join(mrows[i:i + 500]))
    return s


def run_plan(s, sql):
    plan = s._plan(parse(sql)[0])
    root = build(plan)
    chunks = run_to_completion(root, s._exec_ctx())
    frags = []

    def walk(e):
        if isinstance(e, TpuFragmentExec):
            frags.append(e)
        for ch in getattr(e, "children", []):
            walk(ch)

    walk(root)
    return [r for ch in chunks for r in ch.rows()], frags


def device_vs_host(s, sql):
    host, _ = run_plan(s, sql)
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        dev, frags = run_plan(s, sql)
    finally:
        s.vars["tidb_tpu_engine"] = "off"
    assert frags, f"no fragment extracted for: {sql}"
    for f in frags:
        assert f.used_device, f"fell back ({f.fallback_reason}): {sql}"
    hs, ds = sorted(host, key=repr), sorted(dev, key=repr)
    assert len(hs) == len(ds), (len(hs), len(ds), sql)
    for h, d in zip(hs, ds):
        for x, y in zip(h, d):
            if isinstance(x, float) and y is not None:
                assert abs(x - y) <= 1e-6 * max(1.0, abs(x)), (h, d)
            else:
                assert x == y, (h, d)


# ---- semijoins and scalar subqueries --------------------------------------

def test_exists_semijoin_fused(session):
    device_vs_host(session,
                   "SELECT pri, COUNT(*) FROM ord WHERE odate < 800 "
                   "AND EXISTS (SELECT 1 FROM li WHERE li.ok = ord.ok "
                   "AND li.cdate < li.sdate) GROUP BY pri")


def test_in_semijoin_fused(session):
    device_vs_host(session,
                   "SELECT pri, COUNT(*) FROM ord WHERE ok IN "
                   "(SELECT ok FROM li WHERE qty > 40) GROUP BY pri")


def test_scalar_subquery_in_where_fused(session):
    device_vs_host(session,
                   "SELECT COUNT(*), SUM(price) FROM li WHERE qty < "
                   "(SELECT AVG(qty) FROM li WHERE sdate < 500)")


def test_scalar_subquery_in_having_fused(session):
    device_vs_host(session,
                   "SELECT ok, SUM(price * qty) FROM li GROUP BY ok "
                   "HAVING SUM(price * qty) > (SELECT "
                   "SUM(price * qty) * 0.002 FROM li)")


# ---- LIMIT pushdown over join roots ---------------------------------------

def test_limit_over_join_fused(session):
    s = session
    full_sql = ("SELECT ord.pri, li.qty, li.price FROM li "
                "JOIN ord ON li.ok = ord.ok WHERE li.sdate < 700")
    sql = full_sql + " LIMIT 13"
    full = {repr(r) for r in s.query(full_sql).rows}
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        dev, frags = run_plan(s, sql)
    finally:
        s.vars["tidb_tpu_engine"] = "off"
    assert frags and all(f.used_device for f in frags), \
        [f.fallback_reason for f in frags]
    # LIMIT without ORDER BY picks ANY 13 rows — pin count + membership
    assert len(dev) == 13
    assert all(repr(r) in full for r in dev)


# ---- multi-arg and multiple DISTINCT aggregates ---------------------------

def test_multi_arg_count_distinct_fused(session):
    device_vs_host(session,
                   "SELECT g, COUNT(DISTINCT a, b), COUNT(*) FROM md "
                   "GROUP BY g")


def test_multiple_distinct_aggs_fused(session):
    device_vs_host(session,
                   "SELECT g, COUNT(DISTINCT a), COUNT(DISTINCT b), "
                   "SUM(v) FROM md GROUP BY g")


def test_multiple_distinct_scalar_root_fused(session):
    device_vs_host(session,
                   "SELECT COUNT(DISTINCT a), COUNT(DISTINCT b), "
                   "COUNT(DISTINCT a, b) FROM md WHERE v < 300")


# ---- warm launch-count pins -----------------------------------------------

@pytest.mark.parametrize("sql,max_launches", [
    # single slab: partial + fused finalize
    ("SELECT g, COUNT(DISTINCT a, b), SUM(v) FROM md GROUP BY g", 2),
    ("SELECT pri, COUNT(*) FROM ord WHERE ok IN "
     "(SELECT ok FROM li WHERE qty > 40) GROUP BY pri", 3),
    ("SELECT ord.pri, li.qty FROM li JOIN ord ON li.ok = ord.ok "
     "WHERE li.sdate < 700 LIMIT 13", 3),
])
def test_warm_launch_counts(session, sql, max_launches):
    s = session
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        s.query(sql)               # compile + first touch
        s.query(sql)               # warm
        ph = s.last_guard.phases
        assert 1 <= ph.programs_launched <= max_launches, \
            ph.programs_launched
    finally:
        s.vars["tidb_tpu_engine"] = "off"


def test_same_statement_subquery_does_not_poison_specialization(session):
    """Regression: a plan-time uncorrelated subquery executes its own
    fragment under the SAME guard.sql as the outer statement; the
    specialization key must tell the two chains apart or the outer
    fragment adopts the subquery's compiled signature (wrong agg-state
    layout → device-error fallback)."""
    s = session
    sql = ("SELECT COUNT(*), SUM(price) FROM li WHERE qty > "
           "(SELECT AVG(qty) FROM li)")
    host = s.query(sql).rows
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    s.vars["tidb_tpu_strict"] = True      # any device fallback raises
    try:
        cold = s.query(sql).rows
        warm = s.query(sql).rows          # spec-cache hit path
    finally:
        s.vars["tidb_tpu_strict"] = False
        s.vars["tidb_tpu_engine"] = "off"
    for got in (cold, warm):
        assert len(got) == len(host)
        for h, d in zip(host, got):
            assert h[0] == d[0]
            assert abs(h[1] - d[1]) <= 1e-6 * max(1.0, abs(h[1]))
