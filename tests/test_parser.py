"""Parser tests — TPC-H query shapes + DDL/DML (ref: parser/parser_test.go)."""

from decimal import Decimal

import pytest

from tidb_tpu.errors import ParseError
from tidb_tpu.parser import ast, parse, parse_one
from tidb_tpu.types import TypeKind

TPCH_Q1 = """
select
    l_returnflag, l_linestatus,
    sum(l_quantity) as sum_qty,
    sum(l_extendedprice) as sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
    avg(l_quantity) as avg_qty,
    avg(l_extendedprice) as avg_price,
    avg(l_discount) as avg_disc,
    count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval 90 day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

TPCH_Q3 = """
select
    l_orderkey,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING'
  and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10
"""

TPCH_Q5 = """
select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer
  join orders on c_custkey = o_custkey
  join lineitem on l_orderkey = o_orderkey
  join supplier on l_suppkey = s_suppkey and c_nationkey = s_nationkey
  join nation on s_nationkey = n_nationkey
  join region on n_regionkey = r_regionkey
where r_name = 'ASIA'
  and o_orderdate >= date '1994-01-01'
  and o_orderdate < date '1995-01-01'
group by n_name
order by revenue desc
"""

TPCH_Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
"""


def test_q1_shape():
    s = parse_one(TPCH_Q1)
    assert isinstance(s, ast.SelectStmt)
    assert len(s.items) == 10
    assert s.items[2].alias == "sum_qty"
    agg = s.items[4].expr
    assert isinstance(agg, ast.FuncCall) and agg.name == "sum"
    assert isinstance(agg.args[0], ast.BinaryOp) and agg.args[0].op == "mul"
    assert len(s.group_by) == 2 and len(s.order_by) == 2
    assert isinstance(s.where, ast.BinaryOp) and s.where.op == "le"
    # right side: date literal minus interval
    assert isinstance(s.where.right, ast.BinaryOp)
    assert isinstance(s.where.right.right, ast.IntervalExpr)
    cnt = s.items[9].expr
    assert cnt.name == "count" and isinstance(cnt.args[0], ast.Star)


def test_q3_comma_joins_and_limit():
    s = parse_one(TPCH_Q3)
    assert isinstance(s.from_, ast.JoinExpr) and s.from_.kind == "cross"
    assert s.limit == (0, 10)
    assert s.order_by[0][1] is True and s.order_by[1][1] is False


def test_q5_explicit_join_chain():
    s = parse_one(TPCH_Q5)
    j = s.from_
    depth = 0
    while isinstance(j, ast.JoinExpr):
        assert j.kind == "inner" and j.on is not None
        j = j.left
        depth += 1
    assert depth == 5 and isinstance(j, ast.TableName)
    assert j.name == "customer"


def test_q6_between():
    s = parse_one(TPCH_Q6)
    w = s.where
    assert isinstance(w, ast.BinaryOp) and w.op == "and"
    found_between = any(isinstance(n, ast.Between)
                        for n in _walk_expr(s.where))
    assert found_between


def _walk_expr(e):
    yield e
    for attr in ("left", "right", "operand", "expr", "low", "high", "pattern"):
        child = getattr(e, attr, None)
        if isinstance(child, ast.ExprNode):
            yield from _walk_expr(child)
    for child in getattr(e, "args", []) or []:
        if isinstance(child, ast.ExprNode):
            yield from _walk_expr(child)


def test_create_table():
    s = parse_one("""
        CREATE TABLE lineitem (
            l_orderkey BIGINT NOT NULL,
            l_quantity DECIMAL(15,2),
            l_returnflag CHAR(1),
            l_shipdate DATE,
            l_comment VARCHAR(44) DEFAULT 'x',
            PRIMARY KEY (l_orderkey),
            KEY idx_ship (l_shipdate)
        ) ENGINE=InnoDB CHARSET=utf8mb4
    """)
    assert isinstance(s, ast.CreateTable)
    assert s.name == "lineitem" and len(s.columns) == 5
    assert s.primary_key == ["l_orderkey"]
    assert s.columns[0].ftype.nullable is False
    assert s.columns[1].ftype.kind is TypeKind.DECIMAL
    assert s.columns[1].ftype.precision == 15 and s.columns[1].ftype.scale == 2
    assert s.indexes == [ast.IndexDef("idx_ship", ["l_shipdate"], False)]
    assert isinstance(s.columns[4].default, ast.Literal)


def test_create_table_inline_pk_and_if_not_exists():
    s = parse_one("create table if not exists t (id int primary key, v text)")
    assert s.if_not_exists and s.primary_key == ["id"]
    assert s.columns[0].ftype.nullable is False


def test_insert_forms():
    s = parse_one("insert into t (a, b) values (1, 'x'), (2, NULL)")
    assert s.table == "t" and s.columns == ["a", "b"] and len(s.rows) == 2
    assert s.rows[1][1].value is None
    s2 = parse_one("insert into t2 select a, b from t where a > 1")
    assert s2.select is not None


def test_update_delete():
    s = parse_one("update t set a = a + 1, b = 'y' where id = 3")
    assert isinstance(s, ast.Update) and len(s.assignments) == 2
    d = parse_one("delete from t where a in (1, 2, 3)")
    assert isinstance(d, ast.Delete)
    assert isinstance(d.where, ast.InExpr)


def test_subqueries():
    s = parse_one("""
        select a from t where a > (select avg(a) from t)
        and exists (select 1 from u where u.id = t.id)
    """)
    subs = [n for n in _walk_expr(s.where)
            if isinstance(n, (ast.Subquery, ast.ExistsExpr))]
    assert len(subs) >= 2
    s2 = parse_one("select * from (select a, b from t) d where d.a > 1")
    assert isinstance(s2.from_, ast.SubqueryTable) and s2.from_.alias == "d"


def test_union_order_limit():
    s = parse_one("select a from t union all select b from u "
                  "order by 1 desc limit 5")
    assert isinstance(s, ast.SetOpStmt) and s.op == "union" and s.all
    assert s.limit == (0, 5) and s.order_by[0][1] is True


def test_case_both_forms():
    s = parse_one("select case when a > 1 then 'big' else 'small' end, "
                  "case b when 1 then 'one' when 2 then 'two' end from t")
    c1 = s.items[0].expr
    c2 = s.items[1].expr
    assert c1.operand is None and c1.else_ is not None
    assert c2.operand is not None and len(c2.whens) == 2 and c2.else_ is None


def test_operator_precedence():
    s = parse_one("select 1 + 2 * 3 - 4 / 2")
    e = s.items[0].expr            # ((1 + (2*3)) - (4/2))
    assert e.op == "minus"
    assert e.left.op == "plus" and e.left.right.op == "mul"
    assert e.right.op == "div"
    s2 = parse_one("select a or b and c = d")
    e2 = s2.items[0].expr
    assert e2.op == "or" and e2.right.op == "and"
    assert e2.right.right.op == "eq"


def test_not_precedence_and_negated_predicates():
    s = parse_one("select * from t where not a = 1 and b not in (2) "
                  "and c not like 'x%' and d is not null "
                  "and e not between 1 and 2")
    names = [type(n).__name__ for n in _walk_expr(s.where)]
    assert "InExpr" in names and "LikeExpr" in names and "Between" in names
    neg = [n for n in _walk_expr(s.where)
           if getattr(n, "negated", False)]
    assert len(neg) == 4


def test_explain_set_show():
    e = parse_one("explain analyze select * from t")
    assert isinstance(e, ast.Explain) and e.analyze
    st = parse_one("set @@tidb_mem_quota_query = 1024, max_rows = 10")
    assert isinstance(st, ast.SetStmt) and len(st.assignments) == 2
    sh = parse_one("show tables")
    assert sh.kind == "tables"
    sh2 = parse_one("show columns from t")
    assert sh2.kind == "columns" and sh2.target == "t"


def test_multi_statement_script():
    stmts = parse("create table t (a int); insert into t values (1); "
                  "select * from t;")
    assert len(stmts) == 3


def test_string_escapes_and_quotes():
    s = parse_one("select 'it''s', 'a\\'b', \"dq\"")
    vals = [i.expr.value for i in s.items]
    assert vals == ["it's", "a'b", "dq"]


def test_backquoted_identifiers():
    s = parse_one("select `select`, `weird col` from `my table`")
    assert s.items[0].expr.parts == ("select",)
    assert s.from_.name == "my table"


def test_comments_stripped():
    s = parse_one("select a -- trailing\n, b /* inline */ from t # hash\n")
    assert len(s.items) == 2


def test_qualified_star_and_names():
    s = parse_one("select t.*, u.a, db_x.t2.c from t")
    assert isinstance(s.items[0].expr, ast.Star) and s.items[0].expr.table == "t"
    assert s.items[1].expr.parts == ("u", "a")
    assert s.items[2].expr.parts == ("db_x", "t2", "c")


def test_decimal_vs_float_literals():
    s = parse_one("select 1.5, 1.5e3, 42")
    assert s.items[0].expr.kind == "decimal"
    assert s.items[0].expr.value == Decimal("1.5")
    assert s.items[1].expr.kind == "float" and s.items[1].expr.value == 1500.0
    assert s.items[2].expr.kind == "int"


def test_parse_errors():
    for bad in ["select from where", "create table t", "select * from t "
                "group a", "insert t values 1", "select 'unterminated"]:
        with pytest.raises(ParseError):
            parse_one(bad)


def test_txn_statements():
    assert isinstance(parse_one("begin"), ast.BeginStmt)
    assert isinstance(parse_one("start transaction"), ast.BeginStmt)
    assert isinstance(parse_one("commit"), ast.CommitStmt)
    assert isinstance(parse_one("rollback"), ast.RollbackStmt)


def test_review_regressions():
    # REPLACE / INSERT IGNORE keep their semantics
    r = parse_one("replace into t values (1)")
    assert r.replace and not r.ignore
    ig = parse_one("insert ignore into t values (1)")
    assert ig.ignore and not ig.replace
    # scope-qualified sysvars and user variables
    s = parse_one("set @@session.sql_mode = 'x', @@global.max_rows = 1, @u = 2")
    assert [a[0] for a in s.assignments] == ["sql_mode", "max_rows", "@u"]
    v = parse_one("select @@session.autocommit, @x")
    assert v.items[0].expr.system and not v.items[1].expr.system
    # SHOW VARIABLES LIKE requires a string
    with pytest.raises(ParseError):
        parse_one("show variables like")
    with pytest.raises(ParseError):
        parse_one("show variables like 123")
    # malformed exponent stays in the ParseError domain, not ValueError
    try:
        parse_one("select 1e+ from t")
    except ParseError:
        pass
    # parenthesized select with trailing order/limit
    p = parse_one("(select 1 as a) order by 1 limit 3")
    assert p.limit == (0, 3) and p.order_by
    # unique index is structured
    ct = parse_one("create table t (a int, unique key uk (a))")
    assert ct.indexes[0].unique and ct.indexes[0].name == "uk"
