"""CTEs (WITH / WITH RECURSIVE, ref: executor/cte.go) and online schema
changes (ALTER TABLE, ref: ddl/column.go)."""

import numpy as np
import pytest

from tidb_tpu.errors import ExecutionError, TiDBTPUError
from tidb_tpu.session import Engine


@pytest.fixture()
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE t (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO t VALUES (1,10),(2,20),(3,30),(4,40)")
    return s


def test_basic_cte(session):
    r = session.query("WITH big AS (SELECT a, b FROM t WHERE b > 15) "
                      "SELECT COUNT(*), SUM(b) FROM big").rows
    assert r == [(3, 90)]


def test_chained_ctes_and_multiple_references(session):
    r = session.query(
        "WITH x AS (SELECT a FROM t), "
        "y AS (SELECT a FROM x WHERE a > 1) "
        "SELECT COUNT(*) FROM y JOIN x ON x.a = y.a").rows
    assert r == [(3,)]


def test_cte_column_aliases(session):
    r = session.query("WITH c (n, m) AS (SELECT a, b FROM t) "
                      "SELECT SUM(n), MAX(m) FROM c").rows
    assert r == [(10, 40)]


def test_cte_name_shadows_table(session):
    # a CTE named like a real table wins inside the statement
    r = session.query("WITH t AS (SELECT 1 AS a) SELECT COUNT(*) FROM t")
    assert r.rows == [(1,)]
    # and the real table is untouched afterwards
    assert session.query("SELECT COUNT(*) FROM t").rows == [(4,)]


def test_recursive_sequence(session):
    r = session.query(
        "WITH RECURSIVE seq (n) AS (SELECT 1 UNION ALL "
        "SELECT n + 1 FROM seq WHERE n < 100) "
        "SELECT COUNT(*), SUM(n) FROM seq").rows
    assert r == [(100, 5050)]


def test_recursive_union_distinct_fixpoint(session):
    # UNION (distinct) terminates on fixpoint even though the recursive
    # term always produces a row
    r = session.query(
        "WITH RECURSIVE r (n) AS (SELECT 1 UNION SELECT 1 FROM r) "
        "SELECT COUNT(*) FROM r").rows
    assert r == [(1,)]


def test_recursive_depth_limit(session):
    with pytest.raises(ExecutionError):
        session.query(
            "WITH RECURSIVE r (n) AS (SELECT 1 UNION ALL "
            "SELECT n + 1 FROM r) SELECT COUNT(*) FROM r")


def test_cte_temp_tables_cleaned_up(session):
    session.query("WITH c AS (SELECT a FROM t) SELECT * FROM c")
    names = [t.name for t in
             session.engine.catalog.info_schema.list_tables()]
    assert all(not n.startswith("#cte") for n in names)


# ---- ALTER TABLE -----------------------------------------------------------

def test_add_column_lazy_default(session):
    s = session
    s.execute("ALTER TABLE t ADD COLUMN c BIGINT DEFAULT 7")
    assert s.query("SELECT SUM(c) FROM t").rows == [(28,)]
    s.execute("INSERT INTO t VALUES (5, 50, 9)")
    rows = dict((r[0], r[2]) for r in s.query("SELECT a, b, c FROM t").rows)
    assert rows[5] == 9 and rows[1] == 7


def test_drop_column_rewrites_storage(session):
    s = session
    s.execute("ALTER TABLE t ADD COLUMN c BIGINT DEFAULT 7")
    s.execute("INSERT INTO t VALUES (5, 50, 9)")
    s.execute("ALTER TABLE t DROP COLUMN b")
    rows = sorted(s.query("SELECT a, c FROM t").rows)
    assert rows == [(1, 7), (2, 7), (3, 7), (4, 7), (5, 9)]
    with pytest.raises(TiDBTPUError):
        s.query("SELECT b FROM t")


def test_rename_table(session):
    s = session
    s.execute("ALTER TABLE t RENAME TO t_new")
    assert s.query("SELECT COUNT(*) FROM t_new").rows == [(4,)]
    with pytest.raises(TiDBTPUError):
        s.query("SELECT COUNT(*) FROM t")


def test_drop_pk_column_rejected(session):
    s = session
    s.execute("CREATE TABLE pkt (id BIGINT, v BIGINT, PRIMARY KEY (id))")
    with pytest.raises(TiDBTPUError):
        s.execute("ALTER TABLE pkt DROP COLUMN id")


def test_device_cache_sees_new_column(session):
    # device queries after ADD COLUMN must not read stale layouts
    s = session
    s.execute("INSERT INTO t VALUES " + ",".join(
        f"({i},{i * 10})" for i in range(10, 2000)))
    s.execute("ANALYZE TABLE t")
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1)
    try:
        before = s.query("SELECT COUNT(*) FROM t WHERE b > 100").rows
        s.execute("ALTER TABLE t ADD COLUMN d BIGINT DEFAULT 1")
        after = s.query("SELECT COUNT(*), SUM(d) FROM t WHERE b > 100").rows
        assert after[0][0] == before[0][0]
        assert after[0][1] == after[0][0]     # every row d = 1
    finally:
        s.vars.pop("tidb_tpu_engine", None)
