"""Collations (utf8mb4_general_ci via dictionary/fold normalization, the
util/collate analog) and time zones (time_zone sysvar at DATETIME↔epoch
boundaries, types/time.go ConvertTimeZone analog)."""

import numpy as np
import pytest

from tidb_tpu.errors import DuplicateKeyError, PlanError
from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ci (id BIGINT, name VARCHAR(16) COLLATE "
              "utf8mb4_general_ci, tag VARCHAR(8), v BIGINT)")
    rows = [
        (1, "Alpha", "x", 10), (2, "ALPHA", "y", 20), (3, "alpha", "x", 30),
        (4, "Beta", "y", 40), (5, "BETA", "x", 50), (6, "gamma", "y", 60),
        (7, None, "x", 70), (8, "Gamma", None, 80),
    ]
    s.execute("INSERT INTO ci VALUES " + ",".join(
        f"({i},{'NULL' if n is None else repr(n)},"
        f"{'NULL' if t is None else repr(t)},{v})"
        for i, n, t, v in rows))
    return s


def test_ci_compare(s):
    assert s.query("SELECT COUNT(*) FROM ci WHERE name = 'alpha'"
                   ).rows[0][0] == 3
    assert s.query("SELECT COUNT(*) FROM ci WHERE name = 'ALPHA'"
                   ).rows[0][0] == 3
    # the binary column stays case-sensitive
    assert s.query("SELECT COUNT(*) FROM ci WHERE tag = 'X'"
                   ).rows[0][0] == 0
    assert s.query("SELECT COUNT(*) FROM ci WHERE tag = 'x'"
                   ).rows[0][0] == 4


def test_ci_group_by(s):
    rows = s.query("SELECT name, COUNT(*), SUM(v) FROM ci "
                   "GROUP BY name").rows
    by_fold = {(r[0].upper() if r[0] is not None else None):
               (r[1], r[2]) for r in rows}
    assert len(rows) == 4                     # ALPHA, BETA, GAMMA, NULL
    assert by_fold["ALPHA"] == (3, 60)
    assert by_fold["BETA"] == (2, 90)
    assert by_fold["GAMMA"] == (2, 140)
    assert by_fold[None] == (1, 70)


def test_ci_distinct_and_in(s):
    assert s.query("SELECT COUNT(DISTINCT name) FROM ci").rows[0][0] == 3
    assert s.query("SELECT COUNT(*) FROM ci WHERE name IN ('ALPHA', 'beta')"
                   ).rows[0][0] == 5


def test_ci_order_by(s):
    rows = s.query("SELECT name FROM ci WHERE name IS NOT NULL "
                   "ORDER BY name, id").rows
    folded = [r[0].upper() for r in rows]
    assert folded == sorted(folded)


def test_ci_join(s):
    s.execute("CREATE TABLE lookup (lname VARCHAR(16) COLLATE "
              "utf8mb4_general_ci, score BIGINT)")
    s.execute("INSERT INTO lookup VALUES ('ALPHA', 1), ('beta', 2)")
    rows = s.query(
        "SELECT lname, COUNT(*) FROM ci JOIN lookup ON name = lname "
        "GROUP BY lname").rows
    got = {r[0].upper(): r[1] for r in rows}
    assert got == {"ALPHA": 3, "BETA": 2}


def test_ci_min_max(s):
    mn, mx = s.query("SELECT MIN(name), MAX(name) FROM ci").rows[0]
    assert mn.upper() == "ALPHA"
    assert mx.upper() == "GAMMA"


def test_ci_unique_constraint(s):
    s.execute("CREATE TABLE ciu (u VARCHAR(8) COLLATE utf8mb4_general_ci)")
    s.execute("CREATE UNIQUE INDEX uq ON ciu (u)")
    s.execute("INSERT INTO ciu VALUES ('abc')")
    with pytest.raises(DuplicateKeyError):
        s.execute("INSERT INTO ciu VALUES ('ABC')")   # ci conflict


def test_ci_unique_backfill_detects_fold_dup(s):
    s.execute("CREATE TABLE cib (u VARCHAR(8) COLLATE utf8mb4_general_ci)")
    s.execute("INSERT INTO cib VALUES ('x1'), ('X1')")
    with pytest.raises(DuplicateKeyError):
        s.execute("CREATE UNIQUE INDEX uqb ON cib (u)")


def test_ci_device_paths():
    # device compare/group/join run on fold-normalized dictionary codes
    eng = Engine()
    s2 = eng.new_session()
    s2.execute("CREATE TABLE dci (k BIGINT, name VARCHAR(8) COLLATE "
               "utf8mb4_general_ci, v BIGINT)")
    rng = np.random.default_rng(8)
    names = ["Red", "RED", "red", "Blue", "BLUE", "green"]
    s2.execute("INSERT INTO dci VALUES " + ",".join(
        f"({int(rng.integers(0, 9))},'{names[int(rng.integers(0, 6))]}',"
        f"{int(rng.integers(0, 100))})" for _ in range(50000)))
    s2.execute("ANALYZE TABLE dci")
    for sql in [
        "SELECT COUNT(*) FROM dci WHERE name = 'RED'",
        "SELECT name, COUNT(*), SUM(v) FROM dci GROUP BY name",
        "SELECT COUNT(*) FROM dci WHERE name IN ('red', 'BLUE')",
        "SELECT COUNT(DISTINCT name) FROM dci",
    ]:
        want = sorted(str(r[1:]) for r in s2.query(sql).rows)
        s2.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                       tidb_tpu_strict="on")
        try:
            got = sorted(str(r[1:]) for r in s2.query(sql).rows)
        finally:
            s2.vars.update(tidb_tpu_engine="off", tidb_tpu_strict="off")
        assert got == want, sql


def test_unknown_collation_rejected(s):
    from tidb_tpu.errors import ParseError
    with pytest.raises(ParseError, match="Unknown collation"):
        s.execute("CREATE TABLE bad (a VARCHAR(4) COLLATE klingon_ci_xx)")


# ---- time zones -------------------------------------------------------------


def test_time_zone_epoch_boundaries():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE tz (d DATETIME)")
    s.execute("INSERT INTO tz VALUES ('2024-06-01 12:00:00')")
    utc = s.query("SELECT UNIX_TIMESTAMP(d) FROM tz").rows[0][0]
    s.vars["time_zone"] = "+08:00"
    east = s.query("SELECT UNIX_TIMESTAMP(d) FROM tz").rows[0][0]
    assert utc - east == 8 * 3600      # same wall time, earlier epoch
    ft = s.query("SELECT FROM_UNIXTIME(0) FROM tz").rows[0][0]
    assert str(ft) == "1970-01-01 08:00:00"
    s.vars["time_zone"] = "-05:30"
    west = s.query("SELECT UNIX_TIMESTAMP(d) FROM tz").rows[0][0]
    assert west - utc == 5 * 3600 + 1800


def test_convert_tz():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE c (d DATETIME)")
    s.execute("INSERT INTO c VALUES ('2024-01-15 10:00:00')")
    r = s.query("SELECT CONVERT_TZ(d, '+00:00', '+05:30') FROM c"
                ).rows[0][0]
    assert str(r) == "2024-01-15 15:30:00"
    r = s.query("SELECT CONVERT_TZ(d, '+02:00', '-03:00') FROM c"
                ).rows[0][0]
    assert str(r) == "2024-01-15 05:00:00"
    # named zones resolve through zoneinfo
    r = s.query("SELECT CONVERT_TZ(d, 'UTC', 'Asia/Shanghai') FROM c"
                ).rows[0][0]
    assert str(r) == "2024-01-15 18:00:00"
    with pytest.raises(PlanError, match="time zone"):
        s.query("SELECT CONVERT_TZ(d, 'UTC', 'Mars/Olympus') FROM c")


def test_now_honors_time_zone():
    import datetime as dt
    eng = Engine()
    s = eng.new_session()
    s.vars["time_zone"] = "+00:00"
    a = s.query("SELECT NOW()").rows[0][0]
    s.vars["time_zone"] = "+09:00"
    b = s.query("SELECT NOW()").rows[0][0]
    delta = (b - a).total_seconds()
    assert 9 * 3600 - 5 <= delta <= 9 * 3600 + 5
    utcnow = dt.datetime.now(dt.timezone.utc).replace(tzinfo=None)
    assert abs((a - utcnow).total_seconds()) < 5


def test_ci_like(s):
    # LIKE honors ci collation on the host kernel… (advisor r4, high)
    assert s.query("SELECT COUNT(*) FROM ci WHERE name LIKE 'alph%'"
                   ).rows[0][0] == 3
    assert s.query("SELECT COUNT(*) FROM ci WHERE name LIKE '%ETA'"
                   ).rows[0][0] == 2
    # …while binary columns stay case-sensitive
    assert s.query("SELECT COUNT(*) FROM ci WHERE tag LIKE 'X%'"
                   ).rows[0][0] == 0


def test_ci_like_device():
    eng = Engine()
    s2 = eng.new_session()
    s2.execute("CREATE TABLE dlk (name VARCHAR(8) COLLATE "
               "utf8mb4_general_ci, v BIGINT)")
    names = ["Red", "RED", "red", "Blue", "BLUE", "green"]
    rng = np.random.default_rng(4)
    s2.execute("INSERT INTO dlk VALUES " + ",".join(
        f"('{names[int(rng.integers(0, 6))]}',{i})" for i in range(20000)))
    s2.execute("ANALYZE TABLE dlk")
    sql = "SELECT COUNT(*), SUM(v) FROM dlk WHERE name LIKE 'red%'"
    want = s2.query(sql).rows
    s2.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                   tidb_tpu_strict="on")
    try:
        got = s2.query(sql).rows
    finally:
        s2.vars.update(tidb_tpu_engine="off", tidb_tpu_strict="off")
    assert got == want and want[0][0] > 0
