"""Statistics: histogram/NDV/TopN build + selectivity + planner wiring
(ref: statistics/histogram.go, statistics/selectivity.go,
planner/core/find_best_task.go)."""

import numpy as np
import pytest

from tidb_tpu.statistics import (ColumnStats, analyze_columns,
                                 build_column_stats, expr_selectivity)
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine


def test_column_stats_exact_small():
    vals = np.array([1, 2, 2, 3, 3, 3, 4, 4, 4, 4], dtype=np.int64)
    valid = np.ones(10, dtype=bool)
    cs = build_column_stats(vals, valid, 10)
    assert cs.ndv == 4
    assert cs.null_count == 0
    assert cs.min_val == 1 and cs.max_val == 4
    assert abs(cs.eq_selectivity(4) - 0.4) < 1e-9
    assert abs(cs.eq_selectivity(1) - 0.1) < 1e-9
    assert cs.eq_selectivity(99) <= 0.1
    # range: values ≤ 2 are 3 of 10
    assert abs(cs.range_selectivity(hi=2) - 0.3) < 0.05


def test_column_stats_nulls():
    vals = np.arange(100, dtype=np.int64)
    valid = np.ones(100, dtype=bool)
    valid[:25] = False
    cs = build_column_stats(vals, valid, 100)
    assert cs.null_count == 25
    assert abs(cs.null_fraction() - 0.25) < 1e-9
    assert cs.ndv == 75


def test_column_stats_sampled_ndv():
    rng = np.random.default_rng(3)
    # 4M rows, 1000 distinct values → sampling path, NDV estimate close
    vals = rng.integers(0, 1000, 4_000_000).astype(np.int64)
    cs = build_column_stats(vals, np.ones(len(vals), bool), len(vals))
    assert 900 <= cs.ndv <= 1100
    sel = cs.eq_selectivity(5)
    assert 0.0005 <= sel <= 0.002


def test_string_stats():
    vals = np.array(["ant", "bee", "ant", "cow", "ant"], dtype=object)
    cs = build_column_stats(vals, np.ones(5, bool), 5)
    assert cs.ndv == 3
    assert abs(cs.eq_selectivity("ant") - 0.6) < 1e-9
    # prefix range [a, b): the three 'ant's
    assert abs(cs.range_selectivity(lo="a", hi="b", hi_incl=False) - 0.6) \
        < 0.05


@pytest.fixture()
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE st (a BIGINT, b BIGINT, c VARCHAR(8), "
              "d DECIMAL(8,2))")
    rng = np.random.default_rng(9)
    rows = []
    for i in range(20000):
        a = int(rng.integers(0, 10))          # ndv 10
        b = i                                 # ndv 20000 (unique)
        c = ["x", "y"][int(rng.integers(0, 2))]
        d = round(float(rng.uniform(0, 100)), 2)
        rows.append(f"({a},{b},'{c}',{d})")
    s.execute("INSERT INTO st VALUES " + ",".join(rows))
    s.execute("ANALYZE TABLE st")
    return s


def _plan(s, sql):
    return s._plan(parse(sql)[0])


def _find(plan, name):
    if type(plan).__name__ == name:
        return plan
    for c in plan.children:
        hit = _find(c, name)
        if hit is not None:
            return hit
    if hasattr(plan, "root"):
        return _find(plan.root, name)
    return None


def test_scan_filter_selectivity(session):
    p = _plan(session, "SELECT * FROM st WHERE a = 3")
    scan = _find(p, "PhysTableScan")
    assert 1200 <= scan.est_rows <= 2800     # ~1/10 of 20000

    p = _plan(session, "SELECT * FROM st WHERE d < 25.0")
    scan = _find(p, "PhysTableScan")
    assert 3500 <= scan.est_rows <= 6500     # ~25%


def test_agg_group_estimate(session):
    p = _plan(session, "SELECT a, COUNT(*) FROM st GROUP BY a")
    agg = _find(p, "PhysHashAgg")
    assert agg.est_reliable
    assert 8 <= agg.est_rows <= 13

    p = _plan(session, "SELECT b, COUNT(*) FROM st GROUP BY b")
    agg = _find(p, "PhysHashAgg")
    assert agg.est_reliable
    assert 15000 <= agg.est_rows <= 25000


def test_join_estimate(session):
    eng = session.engine
    s2 = eng.new_session()
    s2.execute("CREATE TABLE dim (k BIGINT, v BIGINT)")
    s2.execute("INSERT INTO dim VALUES " +
               ",".join(f"({i},{i * 2})" for i in range(100)))
    s2.execute("ANALYZE TABLE dim")
    # FK join: |st| rows survive ≈ |st| * |dim| / ndv(b)=20000 = 100
    p = _plan(s2, "SELECT * FROM st JOIN dim ON b = k")
    join = _find(p, "PhysHashJoin")
    assert 50 <= join.est_rows <= 300


def test_stats_feed_group_cap(session):
    from tidb_tpu.executor.fragment import _initial_group_cap
    p = _plan(session, "SELECT b, COUNT(*) FROM st GROUP BY b")
    agg = _find(p, "PhysHashAgg")
    cap = _initial_group_cap(agg, 1 << 16, 1 << 23)
    assert cap >= 32768          # ≥ ndv(b)=20000 with headroom

    p = _plan(session, "SELECT a, COUNT(*) FROM st GROUP BY a")
    agg = _find(p, "PhysHashAgg")
    cap = _initial_group_cap(agg, 1 << 16, 1 << 23)
    assert cap == 1024           # small reliable estimate → floor


def test_auto_analyze_lifecycle():
    # statement-boundary auto-analyze (statistics/handle/update.go:939,
    # domain/domain.go:1249): stats appear without a manual ANALYZE once
    # enough rows accumulate, refresh after 10x growth, and the plan that
    # keyed on the stale stats version is replanned
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE aa (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO aa VALUES " +
              ",".join(f"({i},{i % 7})" for i in range(2000)))
    tid = eng.catalog.info_schema.table("aa").id
    assert tid not in eng.table_stats
    sql = "SELECT b, COUNT(*) FROM aa GROUP BY b"
    s.query(sql)
    assert tid in eng.table_stats          # fired with no manual ANALYZE
    assert eng.table_stats[tid].row_count == 2000
    plan1 = s._plan(parse(sql)[0])
    # 10x growth → ratio trigger → fresh stats + replanned estimate
    s.execute("INSERT INTO aa VALUES " +
              ",".join(f"({i},{i % 7})" for i in range(2000, 20000)))
    plan2 = s._plan(parse(sql)[0])
    assert eng.table_stats[tid].row_count == 20000
    assert plan2 is not plan1              # stats version keyed the cache
    assert plan2.est_rows == plan1.est_rows == 7  # NDV(b) stays 7


def test_auto_analyze_disabled_and_small_tables():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE small (a BIGINT)")
    s.execute("INSERT INTO small VALUES (1),(2),(3)")
    tid = eng.catalog.info_schema.table("small").id
    s.query("SELECT COUNT(*) FROM small")
    assert tid not in eng.table_stats      # under tidb_auto_analyze_min_rows
    s.execute("CREATE TABLE big (a BIGINT)")
    s.execute("INSERT INTO big VALUES " +
              ",".join(f"({i})" for i in range(1500)))
    bid = eng.catalog.info_schema.table("big").id
    s.vars["tidb_enable_auto_analyze"] = "off"
    s.query("SELECT COUNT(*) FROM big")
    assert bid not in eng.table_stats      # disabled
    s.vars["tidb_enable_auto_analyze"] = "on"
    s.query("SELECT COUNT(*) FROM big")
    assert bid in eng.table_stats


def test_auto_analyze_ignores_rolled_back_writes():
    # modify counts flush at COMMIT: a rolled-back INSERT must not
    # trigger a spurious re-ANALYZE (statistics/handle/update.go flushes
    # modifyCount on commit)
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE rbk (a BIGINT)")
    s.execute("INSERT INTO rbk VALUES " +
              ",".join(f"({i})" for i in range(1500)))
    s.query("SELECT COUNT(*) FROM rbk")          # baseline auto-analyze
    tid = eng.catalog.info_schema.table("rbk").id
    v0 = eng.table_stats[tid].version
    s.execute("BEGIN")
    s.execute("INSERT INTO rbk VALUES " +
              ",".join(f"({i})" for i in range(50000, 70000)))
    s.execute("ROLLBACK")
    s.query("SELECT COUNT(*) FROM rbk")
    assert eng.table_stats[tid].version == v0    # no spurious re-analyze
    assert eng.modify_counts.get(tid, 0) == 0
    # committed writes DO count
    s.execute("BEGIN")
    s.execute("INSERT INTO rbk VALUES " +
              ",".join(f"({i})" for i in range(50000, 70000)))
    s.execute("COMMIT")
    s.query("SELECT COUNT(*) FROM rbk")
    assert eng.table_stats[tid].row_count == 21500
