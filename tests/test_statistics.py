"""Statistics: histogram/NDV/TopN build + selectivity + planner wiring
(ref: statistics/histogram.go, statistics/selectivity.go,
planner/core/find_best_task.go)."""

import numpy as np
import pytest

from tidb_tpu.statistics import (ColumnStats, analyze_columns,
                                 build_column_stats, expr_selectivity)
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine


def test_column_stats_exact_small():
    vals = np.array([1, 2, 2, 3, 3, 3, 4, 4, 4, 4], dtype=np.int64)
    valid = np.ones(10, dtype=bool)
    cs = build_column_stats(vals, valid, 10)
    assert cs.ndv == 4
    assert cs.null_count == 0
    assert cs.min_val == 1 and cs.max_val == 4
    assert abs(cs.eq_selectivity(4) - 0.4) < 1e-9
    assert abs(cs.eq_selectivity(1) - 0.1) < 1e-9
    assert cs.eq_selectivity(99) <= 0.1
    # range: values ≤ 2 are 3 of 10
    assert abs(cs.range_selectivity(hi=2) - 0.3) < 0.05


def test_column_stats_nulls():
    vals = np.arange(100, dtype=np.int64)
    valid = np.ones(100, dtype=bool)
    valid[:25] = False
    cs = build_column_stats(vals, valid, 100)
    assert cs.null_count == 25
    assert abs(cs.null_fraction() - 0.25) < 1e-9
    assert cs.ndv == 75


def test_column_stats_sampled_ndv():
    rng = np.random.default_rng(3)
    # 4M rows, 1000 distinct values → sampling path, NDV estimate close
    vals = rng.integers(0, 1000, 4_000_000).astype(np.int64)
    cs = build_column_stats(vals, np.ones(len(vals), bool), len(vals))
    assert 900 <= cs.ndv <= 1100
    sel = cs.eq_selectivity(5)
    assert 0.0005 <= sel <= 0.002


def test_string_stats():
    vals = np.array(["ant", "bee", "ant", "cow", "ant"], dtype=object)
    cs = build_column_stats(vals, np.ones(5, bool), 5)
    assert cs.ndv == 3
    assert abs(cs.eq_selectivity("ant") - 0.6) < 1e-9
    # prefix range [a, b): the three 'ant's
    assert abs(cs.range_selectivity(lo="a", hi="b", hi_incl=False) - 0.6) \
        < 0.05


@pytest.fixture()
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE st (a BIGINT, b BIGINT, c VARCHAR(8), "
              "d DECIMAL(8,2))")
    rng = np.random.default_rng(9)
    rows = []
    for i in range(20000):
        a = int(rng.integers(0, 10))          # ndv 10
        b = i                                 # ndv 20000 (unique)
        c = ["x", "y"][int(rng.integers(0, 2))]
        d = round(float(rng.uniform(0, 100)), 2)
        rows.append(f"({a},{b},'{c}',{d})")
    s.execute("INSERT INTO st VALUES " + ",".join(rows))
    s.execute("ANALYZE TABLE st")
    return s


def _plan(s, sql):
    return s._plan(parse(sql)[0])


def _find(plan, name):
    if type(plan).__name__ == name:
        return plan
    for c in plan.children:
        hit = _find(c, name)
        if hit is not None:
            return hit
    if hasattr(plan, "root"):
        return _find(plan.root, name)
    return None


def test_scan_filter_selectivity(session):
    p = _plan(session, "SELECT * FROM st WHERE a = 3")
    scan = _find(p, "PhysTableScan")
    assert 1200 <= scan.est_rows <= 2800     # ~1/10 of 20000

    p = _plan(session, "SELECT * FROM st WHERE d < 25.0")
    scan = _find(p, "PhysTableScan")
    assert 3500 <= scan.est_rows <= 6500     # ~25%


def test_agg_group_estimate(session):
    p = _plan(session, "SELECT a, COUNT(*) FROM st GROUP BY a")
    agg = _find(p, "PhysHashAgg")
    assert agg.est_reliable
    assert 8 <= agg.est_rows <= 13

    p = _plan(session, "SELECT b, COUNT(*) FROM st GROUP BY b")
    agg = _find(p, "PhysHashAgg")
    assert agg.est_reliable
    assert 15000 <= agg.est_rows <= 25000


def test_join_estimate(session):
    eng = session.engine
    s2 = eng.new_session()
    s2.execute("CREATE TABLE dim (k BIGINT, v BIGINT)")
    s2.execute("INSERT INTO dim VALUES " +
               ",".join(f"({i},{i * 2})" for i in range(100)))
    s2.execute("ANALYZE TABLE dim")
    # FK join: |st| rows survive ≈ |st| * |dim| / ndv(b)=20000 = 100
    p = _plan(s2, "SELECT * FROM st JOIN dim ON b = k")
    join = _find(p, "PhysHashJoin")
    assert 50 <= join.est_rows <= 300


def test_stats_feed_group_cap(session):
    from tidb_tpu.executor.fragment import _initial_group_cap
    p = _plan(session, "SELECT b, COUNT(*) FROM st GROUP BY b")
    agg = _find(p, "PhysHashAgg")
    cap = _initial_group_cap(agg, 1 << 16, 1 << 23)
    assert cap >= 32768          # ≥ ndv(b)=20000 with headroom

    p = _plan(session, "SELECT a, COUNT(*) FROM st GROUP BY a")
    agg = _find(p, "PhysHashAgg")
    cap = _initial_group_cap(agg, 1 << 16, 1 << 23)
    assert cap == 1024           # small reliable estimate → floor


def _wait_stats(eng, tid, pred=lambda st: True, timeout=5.0):
    import time as _t
    deadline = _t.time() + timeout
    while _t.time() < deadline:
        st = eng.table_stats.get(tid)
        if st is not None and pred(st):
            return st
        _t.sleep(0.02)
    raise AssertionError("auto-analyze did not fire in time")


def test_auto_analyze_lifecycle():
    # BACKGROUND auto-analyze (statistics/handle/update.go:939 on the
    # domain loop, domain/domain.go:1249): stats appear with NO query at
    # all after a write burst — the triggering statement pays nothing —
    # refresh after 10x growth, and the plan keyed on the stale stats
    # version is replanned
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE aa (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO aa VALUES " +
              ",".join(f"({i},{i % 7})" for i in range(2000)))
    tid = eng.catalog.info_schema.table("aa").id
    # no SELECT issued: the background worker alone produces the stats
    _wait_stats(eng, tid, lambda st: st.row_count == 2000)
    sql = "SELECT b, COUNT(*) FROM aa GROUP BY b"
    plan1 = s._plan(parse(sql)[0])
    # 10x growth → ratio trigger → fresh stats + replanned estimate
    s.execute("INSERT INTO aa VALUES " +
              ",".join(f"({i},{i % 7})" for i in range(2000, 20000)))
    _wait_stats(eng, tid, lambda st: st.row_count == 20000)
    plan2 = s._plan(parse(sql)[0])
    assert plan2 is not plan1              # stats version keyed the cache
    assert plan2.est_rows == plan1.est_rows == 7  # NDV(b) stays 7


def test_auto_analyze_disabled_and_small_tables():
    import time as _t
    eng = Engine()
    s = eng.new_session()
    # disable GLOBALLY first: the analyzer is engine-wide (global scope,
    # like the reference's tidb_enable_auto_analyze)
    s.execute("SET GLOBAL tidb_enable_auto_analyze = 'off'")
    s.execute("CREATE TABLE small (a BIGINT)")
    s.execute("INSERT INTO small VALUES (1),(2),(3)")
    tid = eng.catalog.info_schema.table("small").id
    s.execute("CREATE TABLE big (a BIGINT)")
    s.execute("INSERT INTO big VALUES " +
              ",".join(f"({i})" for i in range(1500)))
    bid = eng.catalog.info_schema.table("big").id
    _t.sleep(0.6)                          # > one worker lease
    assert bid not in eng.table_stats      # disabled
    s.execute("SET GLOBAL tidb_enable_auto_analyze = 'on'")
    eng._kick_analyze()
    _wait_stats(eng, bid)
    assert tid not in eng.table_stats      # under min_rows, never fires


def test_auto_analyze_ignores_rolled_back_writes():
    # modify counts flush at COMMIT: a rolled-back INSERT must not
    # trigger a spurious re-ANALYZE (statistics/handle/update.go flushes
    # modifyCount on commit)
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE rbk (a BIGINT)")
    s.execute("INSERT INTO rbk VALUES " +
              ",".join(f"({i})" for i in range(1500)))
    tid = eng.catalog.info_schema.table("rbk").id
    v0 = _wait_stats(eng, tid).version           # baseline auto-analyze
    s.execute("BEGIN")
    s.execute("INSERT INTO rbk VALUES " +
              ",".join(f"({i})" for i in range(50000, 70000)))
    s.execute("ROLLBACK")
    import time as _t
    _t.sleep(0.6)                                # > one worker lease
    assert eng.table_stats[tid].version == v0    # no spurious re-analyze
    assert eng.modify_counts.get(tid, 0) == 0
    # committed writes DO count
    s.execute("BEGIN")
    s.execute("INSERT INTO rbk VALUES " +
              ",".join(f"({i})" for i in range(50000, 70000)))
    s.execute("COMMIT")
    _wait_stats(eng, tid, lambda st: st.row_count == 21500)


def test_cmsketch_skew_plan_choice():
    """CM-sketch point estimates (statistics/cmsketch.go:46): on a
    skewed column, equality against a hot mid-tail value (outside TopN's
    reach in a wide-key table) estimates high and keeps the table scan,
    while a rare value estimates low and flips to the index path —
    pinned via EXPLAIN in both directions."""
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE sk (k BIGINT, v BIGINT, INDEX ik (k))")
    rows = []
    # values 0..39 hot (1000 rows each = beyond TOPN_SIZE=32 slots),
    # values 1000..10999 rare (1 row each)
    for hot in range(40):
        rows.extend(f"({hot},{i})" for i in range(1000))
    rows.extend(f"({1000 + i},0)" for i in range(10000))
    s.execute("INSERT INTO sk VALUES " + ",".join(rows))
    s.execute("ANALYZE TABLE sk")
    st = eng.table_stats[eng.catalog.info_schema.table("sk").id]
    cs = st.columns[0]
    assert cs.cms is not None
    # hot mid-tail value (39 may fall outside the 32-slot TopN):
    # estimate must be ~1000 rows, not the uniform ~5
    hot_est = cs.eq_selectivity(39) * st.row_count
    rare_est = cs.eq_selectivity(5000) * st.row_count
    assert hot_est > 200, hot_est
    assert rare_est < 50, rare_est
    plan_hot = "\n".join(str(r) for r in s.query(
        "EXPLAIN SELECT SUM(v) FROM sk WHERE k = 39").rows)
    plan_rare = "\n".join(str(r) for r in s.query(
        "EXPLAIN SELECT SUM(v) FROM sk WHERE k = 5000").rows)
    # the sketch's 1000x estimate difference is visible in EXPLAIN
    import re as _re
    est_hot = int(_re.search(r"IndexScan', '(\d+)'", plan_hot).group(1))
    est_rare = int(_re.search(r"IndexScan', '(\d+)'", plan_rare).group(1))
    assert est_hot > 500 and est_rare <= 50, (est_hot, est_rare)
    # ...and flips a real operator choice: the join build side (the
    # smaller side builds; a TopN-missed hot key must not look small)
    s.execute("CREATE TABLE mid (k BIGINT, w BIGINT)")
    s.execute("INSERT INTO mid VALUES " + ",".join(
        f"({i},{i})" for i in range(100)))
    s.execute("ANALYZE TABLE mid")
    jh = "\n".join(str(r) for r in s.query(
        "EXPLAIN SELECT COUNT(*) FROM sk JOIN mid ON sk.v = mid.w "
        "WHERE sk.k = 39").rows)
    jr = "\n".join(str(r) for r in s.query(
        "EXPLAIN SELECT COUNT(*) FROM sk JOIN mid ON sk.v = mid.w "
        "WHERE sk.k = 5000").rows)
    assert "build:right" in jh     # hot side is BIG: build the 100-row mid
    assert "build:left" in jr      # rare side is tiny: it builds
