"""FK-aligned join cache (executor/device_cache.AlignedJoin): PK-FK joins
served as pure streams over cached fact-rowspace build columns — the
coprocessor-cache idea (ref: store/copr/coprocessor_cache.go) applied to
join structures. Covers: activation, filter independence, all join kinds,
snowflake chains in both join orders, NULL/missing keys, non-unique
fallback with negative caching, and DML invalidation."""

import numpy as np
import pytest

from tidb_tpu.executor import device_cache
from tidb_tpu.session import Engine


def _on(s):
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_strict="on")


def _off(s):
    s.vars.update(tidb_tpu_engine="off", tidb_tpu_strict="off")


def _check(s, sql):
    _off(s)
    want = s.query(sql).rows
    _on(s)
    try:
        got = s.query(sql).rows
    finally:
        _off(s)
    assert sorted(map(str, got)) == sorted(map(str, want)), sql
    return want


@pytest.fixture(scope="module")
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE c (ck BIGINT PRIMARY KEY, seg VARCHAR(8), "
              "nation BIGINT)")
    s.execute("CREATE TABLE o (ok BIGINT PRIMARY KEY, ck BIGINT, d BIGINT, "
              "prio VARCHAR(4))")
    s.execute("CREATE TABLE l (lk BIGINT, price BIGINT, sd BIGINT)")
    rng = np.random.default_rng(11)
    NC, NO, NL = 300, 3000, 60000
    s.execute("INSERT INTO c VALUES " + ",".join(
        f"({i},'s{int(rng.integers(0, 5))}',{int(rng.integers(0, 20))})"
        for i in range(NC)))
    s.execute("INSERT INTO o VALUES " + ",".join(
        f"({i},{int(rng.integers(0, NC))},{int(rng.integers(0, 100))},"
        f"'p{int(rng.integers(0, 4))}')" for i in range(NO)))
    vals = []
    for i in range(NL):
        k = "NULL" if i % 997 == 0 else (
            999999 if i % 499 == 0 else int(rng.integers(0, NO)))
        vals.append(f"({k},{int(rng.integers(0, 1000))},"
                    f"{int(rng.integers(0, 100))})")
    s.execute("INSERT INTO l VALUES " + ",".join(vals))
    for t in ("c", "o", "l"):
        s.execute(f"ANALYZE TABLE {t}")
    return s


def test_aligned_activates_and_matches_cpu(s):
    device_cache.clear()
    _check(s, "SELECT prio, COUNT(*), SUM(price) FROM l JOIN o ON lk = ok "
              "WHERE sd < 50 AND d < 70 GROUP BY prio ORDER BY prio")
    assert any(e.unique for e in device_cache._ALIGNED.values()), \
        "PK-FK join should populate the aligned cache"


def test_aligned_filter_independence(s):
    # one cached structure serves every filter variant (no rebuild)
    _check(s, "SELECT COUNT(*) FROM l JOIN o ON lk = ok WHERE d < 10")
    n = len(device_cache._ALIGNED)
    _check(s, "SELECT COUNT(*) FROM l JOIN o ON lk = ok WHERE d >= 90")
    _check(s, "SELECT prio, SUM(price) FROM l JOIN o ON lk = ok "
              "GROUP BY prio")
    assert len(device_cache._ALIGNED) == n


def test_aligned_join_kinds(s):
    _check(s, "SELECT COUNT(*), SUM(d) FROM l LEFT JOIN o ON lk = ok")
    _check(s, "SELECT COUNT(*) FROM l WHERE lk IN "
              "(SELECT ok FROM o WHERE d < 30)")
    _check(s, "SELECT COUNT(*) FROM l WHERE lk NOT IN (SELECT ok FROM o)")


def test_aligned_snowflake_chain(s):
    # (c ⋈ o) ⋈ l — the dimensions-first order the reorderer prefers:
    # the inner join re-anchors to the fact row space recursively
    device_cache.clear()
    _check(s, "SELECT seg, COUNT(*), SUM(price) FROM l JOIN o ON lk = ok "
              "JOIN c ON o.ck = c.ck WHERE sd < 80 GROUP BY seg "
              "ORDER BY seg")
    kinds = sorted(k[1][0] for k in device_cache._ALIGNED)
    assert kinds == ["al", "col"], kinds   # chained entry + base entry
    # deeper filter on the outermost dimension
    _check(s, "SELECT COUNT(*) FROM l JOIN o ON lk = ok "
              "JOIN c ON o.ck = c.ck WHERE nation < 5 AND d < 50")


def test_aligned_non_unique_falls_back(s):
    s2 = s
    _off(s2)
    s2.execute("CREATE TABLE dup (k BIGINT, v BIGINT)")
    s2.execute("INSERT INTO dup VALUES " + ",".join(
        f"({i % 50},{i})" for i in range(200)))
    s2.execute("ANALYZE TABLE dup")
    _check(s2, "SELECT COUNT(*), SUM(v) FROM l JOIN dup ON lk = k")
    neg = [e for e in device_cache._ALIGNED.values() if not e.unique]
    assert len(neg) == 1, "non-unique build must cache the negative result"


def test_aligned_dml_invalidation(s):
    sql = ("SELECT prio, COUNT(*), SUM(price) FROM l JOIN o ON lk = ok "
           "WHERE d < 70 GROUP BY prio ORDER BY prio")
    _check(s, sql)
    _off(s)
    s.execute("UPDATE o SET d = 0 WHERE ok < 500")
    _check(s, sql)                       # fresh data, fresh structures
    s.execute("DELETE FROM o WHERE ok >= 2900")
    _check(s, sql)                       # FK rows now missing build matches


def test_blocked_expand_beyond_out_cap():
    """A many-to-many join whose fan-out exceeds the device out-cap runs
    as K row-range passes with host-merged agg states — device=True, no
    CPU fallback (VERDICT r4 weak #3 / next #2)."""
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE big (k BIGINT, v BIGINT)")
    s.execute("CREATE TABLE m (k BIGINT, w BIGINT)")
    rng = np.random.default_rng(7)
    # 20000 probe rows x avg 8 matches = ~160k output rows; cap at 16384
    # so ~10+ passes are needed, with skew (key 0 is 10x hot)
    keys = np.where(rng.random(20000) < 0.3, 0,
                    rng.integers(0, 200, 20000))
    s.execute("INSERT INTO big VALUES " + ",".join(
        f"({int(k)},{int(rng.integers(0, 50))})" for k in keys))
    s.execute("INSERT INTO m VALUES " + ",".join(
        f"({i % 200},{int(rng.integers(0, 9))})" for i in range(1600)))
    s.execute("ANALYZE TABLE big")
    s.execute("ANALYZE TABLE m")
    sql = ("SELECT w, COUNT(*), SUM(v), MIN(v), AVG(big.k) FROM big "
           "JOIN m ON big.k = m.k GROUP BY w ORDER BY w")
    want = s.query(sql).rows
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_strict="on", tidb_tpu_join_out_cap=16384)
    try:
        got = s.query(sql).rows
    finally:
        _off(s)
    assert got == want, (got[:3], want[:3])
    # global agg over the same fan-out (no group keys)
    sql2 = "SELECT COUNT(*), SUM(v*w) FROM big JOIN m ON big.k = m.k"
    want2 = s.query(sql2).rows
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_strict="on", tidb_tpu_join_out_cap=16384)
    try:
        got2 = s.query(sql2).rows
    finally:
        _off(s)
    assert got2 == want2, (got2, want2)


def test_blocked_expand_inside_build_subtree_is_safe():
    """An overflowing join inside an ANCESTOR's build subtree must not
    run blocked (each pass would expose a partial build side to the
    ancestor — double-counted semi matches); results must still match the
    CPU engine via whatever path executes."""
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE lt (lk BIGINT)")
    s.execute("CREATE TABLE big2 (k BIGINT, v BIGINT)")
    s.execute("CREATE TABLE m2 (k BIGINT)")
    rng = np.random.default_rng(9)
    s.execute("INSERT INTO lt VALUES " + ",".join(
        f"({int(rng.integers(0, 300))})" for _ in range(5000)))
    s.execute("INSERT INTO big2 VALUES " + ",".join(
        f"({int(rng.integers(0, 100))},{i})" for i in range(20000)))
    s.execute("INSERT INTO m2 VALUES " + ",".join(
        f"({i % 100})" for i in range(400)))
    for t in ("lt", "big2", "m2"):
        s.execute(f"ANALYZE TABLE {t}")
    sql = ("SELECT COUNT(*) FROM lt WHERE lk IN "
           "(SELECT big2.v FROM big2 JOIN m2 ON big2.k = m2.k)")
    want = s.query(sql).rows
    # strict OFF: the correct behavior here is CPU fallback, not blocked
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_join_out_cap=8192)
    try:
        got = s.query(sql).rows
    finally:
        _off(s)
    assert got == want, (got, want)
