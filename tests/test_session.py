"""End-to-end SQL tests over the full spine (TestKit pattern, SURVEY §4
tier 2: testkit/testkit.go MustExec/MustQuery over an embedded store)."""

import datetime
from decimal import Decimal

import pytest

from tidb_tpu.errors import (PlanError, TableExistsError, TiDBTPUError,
                             TxnError, UnknownColumnError, UnknownTableError)
from tidb_tpu.session import Engine, Session


class TK:
    """testkit.TestKit analog."""

    def __init__(self, session: Session):
        self.s = session

    def must_exec(self, sql):
        return self.s.query(sql)

    def must_query(self, sql, expect=None):
        rs = self.s.query(sql)
        if expect is not None:
            assert rs.rows == expect, f"{sql}\n got: {rs.rows}\nwant: {expect}"
        return rs


@pytest.fixture()
def tk():
    return TK(Session())


@pytest.fixture()
def people(tk):
    tk.must_exec("create table t (id bigint primary key, name varchar(20), "
                 "age bigint, city varchar(20), salary decimal(10,2))")
    tk.must_exec(
        "insert into t values "
        "(1,'alice',30,'nyc',100.50),"
        "(2,'bob',25,'sf',90.00),"
        "(3,'carol',35,'nyc',120.25),"
        "(4,'dave',null,'la',80.75),"
        "(5,'erin',28,null,null)")
    return tk


# ---------------------------------------------------------------------------
# basics
# ---------------------------------------------------------------------------


def test_select_no_from(tk):
    tk.must_query("select 1", [(1,)])
    tk.must_query("select 1+2*3, 'hi'", [(7, "hi")])
    tk.must_query("select null", [(None,)])


def test_create_insert_select(people):
    people.must_query("select id, name from t order by id",
                      [(1, "alice"), (2, "bob"), (3, "carol"),
                       (4, "dave"), (5, "erin")])


def test_select_star_where(people):
    rs = people.must_query("select * from t where city = 'nyc' order by id")
    assert [r[0] for r in rs.rows] == [1, 3]
    assert rs.names == ["id", "name", "age", "city", "salary"]


def test_where_null_semantics(people):
    # NULL city rows are excluded by any city comparison
    people.must_query("select id from t where city <> 'nyc' order by id",
                      [(2,), (4,)])
    people.must_query("select id from t where city is null", [(5,)])
    people.must_query("select id from t where age is not null and age > 26 "
                      "order by id", [(1,), (3,), (5,)])


def test_expressions(people):
    people.must_query("select id, salary * 2 from t where id = 1",
                      [(1, Decimal("201.00"))])
    people.must_query("select upper(name) from t where id = 2", [("BOB",)])
    people.must_query("select id from t where name like 'a%'", [(1,)])
    people.must_query(
        "select case when age >= 30 then 'old' else 'young' end "
        "from t where id in (1, 2) order by id", [("old",), ("young",)])


def test_order_by_limit(people):
    people.must_query("select id from t order by age desc, id limit 2",
                      [(3,), (1,)])
    # NULLs first ASC
    people.must_query("select id from t order by age limit 1", [(4,)])
    people.must_query("select id from t order by id limit 2 offset 2",
                      [(3,), (4,)])


def test_alias_and_ordinal(people):
    people.must_query("select age + 1 as a from t where id <= 2 "
                      "order by a desc", [(31,), (26,)])
    people.must_query("select id, name from t order by 2 desc limit 1",
                      [(5, "erin")])


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------


def test_scalar_agg(people):
    people.must_query("select count(*), count(age), sum(age), min(age), "
                      "max(age) from t", [(5, 4, 118, 25, 35)])
    rs = people.must_query("select avg(age) from t")
    assert rs.rows[0][0] == Decimal("29.5000")


def test_scalar_agg_empty(tk):
    tk.must_exec("create table e (a bigint)")
    tk.must_query("select count(*), sum(a), min(a) from e",
                  [(0, None, None)])
    tk.must_query("select count(*) from e where a > 5", [(0,)])


def test_group_by(people):
    people.must_query(
        "select city, count(*), sum(salary) from t group by city "
        "order by city",
        [(None, 1, None), ("la", 1, Decimal("80.75")),
         ("nyc", 2, Decimal("220.75")), ("sf", 1, Decimal("90.00"))])


def test_group_by_having(people):
    people.must_query(
        "select city, count(*) as c from t group by city having c > 1",
        [("nyc", 2)])


def test_group_by_expr(people):
    people.must_query(
        "select age > 27, count(*) from t where age is not null "
        "group by age > 27 order by 1", [(0, 1), (1, 3)])


def test_distinct(people):
    people.must_query("select distinct city from t order by city",
                      [(None,), ("la",), ("nyc",), ("sf",)])
    people.must_query("select count(distinct city) from t", [(3,)])


def test_first_row_loose_group(people):
    # MySQL loose GROUP BY: non-grouped column gets first_row
    rs = people.must_query("select city, age from t where id = 1 "
                           "group by city")
    assert rs.rows == [("nyc", 30)]


def test_agg_distinct_and_variance(tk):
    tk.must_exec("create table v (g varchar(5), x double)")
    tk.must_exec("insert into v values ('a',1.0),('a',1.0),('a',3.0),"
                 "('b',5.0),('b',null)")
    tk.must_query("select g, sum(distinct x) from v group by g order by g",
                  [("a", 4.0), ("b", 5.0)])
    rs = tk.must_query("select g, var_pop(x) from v group by g order by g")
    assert rs.rows[0][1] == pytest.approx(8 / 9)
    assert rs.rows[1][1] == pytest.approx(0.0)


def test_group_concat(people):
    rs = people.must_query("select city, group_concat(name) from t "
                           "where city = 'nyc' group by city")
    assert rs.rows == [("nyc", "alice,carol")]


# ---------------------------------------------------------------------------
# joins
# ---------------------------------------------------------------------------


@pytest.fixture()
def orders(people):
    people.must_exec("create table o (oid bigint, uid bigint, amount bigint)")
    people.must_exec("insert into o values (10,1,5),(11,1,7),(12,2,3),"
                     "(13,9,1),(14,null,2)")
    return people


def test_inner_join(orders):
    orders.must_query(
        "select t.id, o.oid from t join o on t.id = o.uid order by o.oid",
        [(1, 10), (1, 11), (2, 12)])


def test_left_join(orders):
    orders.must_query(
        "select t.id, o.oid from t left join o on t.id = o.uid "
        "order by t.id, o.oid",
        [(1, 10), (1, 11), (2, 12), (3, None), (4, None), (5, None)])


def test_right_join(orders):
    orders.must_query(
        "select t.id, o.oid from o right join t on t.id = o.uid "
        "order by t.id, o.oid",
        [(1, 10), (1, 11), (2, 12), (3, None), (4, None), (5, None)])


def test_join_null_keys_never_match(orders):
    # o.uid NULL row must not match anything
    orders.must_query("select count(*) from t join o on t.id = o.uid",
                      [(3,)])


def test_join_with_condition(orders):
    orders.must_query(
        "select t.id, o.oid from t join o on t.id = o.uid and o.amount > 4 "
        "order by o.oid", [(1, 10), (1, 11)])
    orders.must_query(
        "select t.id, o.oid from t left join o on t.id = o.uid "
        "and o.amount > 5 where t.id <= 2 order by t.id",
        [(1, 11), (2, None)])


def test_join_agg(orders):
    orders.must_query(
        "select t.city, sum(o.amount) from t join o on t.id = o.uid "
        "group by t.city order by t.city", [("nyc", 12), ("sf", 3)])


def test_cross_join(orders):
    orders.must_query("select count(*) from t, o", [(25,)])
    orders.must_query(
        "select count(*) from t, o where t.id = o.uid", [(3,)])


def test_self_join(people):
    people.must_query(
        "select a.id, b.id from t a join t b on a.age < b.age "
        "and a.city = b.city", [(1, 3)])


# ---------------------------------------------------------------------------
# subqueries, set ops, derived tables
# ---------------------------------------------------------------------------


def test_scalar_subquery(people):
    people.must_query("select id from t where age > (select avg(age) from t) "
                      "order by id", [(1,), (3,)])


def test_in_subquery(orders):
    orders.must_query("select id from t where id in (select uid from o) "
                      "order by id", [(1,), (2,)])
    orders.must_query("select id from t where id not in "
                      "(select uid from o where uid is not null) "
                      "order by id", [(3,), (4,), (5,)])


def test_exists(orders):
    orders.must_query(
        "select count(*) from t where exists (select 1 from o where amount > 100)",
        [(0,)])


def test_union(people):
    people.must_query(
        "select id from t where id <= 2 union all select id from t "
        "where id = 1 order by id", [(1,), (1,), (2,)])
    people.must_query(
        "select city from t where id=1 union select city from t where id=3",
        [("nyc",)])


def test_derived_table(people):
    people.must_query(
        "select x.c from (select city, count(*) as c from t group by city) x "
        "where x.city = 'nyc'", [(2,)])


# ---------------------------------------------------------------------------
# DML + transactions
# ---------------------------------------------------------------------------


def test_update_delete(people):
    people.must_exec("update t set salary = salary + 10 where city = 'nyc'")
    people.must_query("select sum(salary) from t where city = 'nyc'",
                      [(Decimal("240.75"),)])
    rs = people.must_exec("delete from t where age is null")
    assert rs.affected_rows == 1
    people.must_query("select count(*) from t", [(4,)])


def test_update_all_rows(people):
    people.must_exec("update t set age = 1")
    people.must_query("select sum(age) from t", [(5,)])


def test_txn_commit_rollback():
    eng = Engine()
    s1, s2 = eng.new_session(), eng.new_session()
    s1.query("create table a (x bigint)")
    s1.query("begin")
    s1.query("insert into a values (1)")
    # staged write visible to s1, not s2
    assert s1.query("select count(*) from a").rows == [(1,)]
    assert s2.query("select count(*) from a").rows == [(0,)]
    s1.query("commit")
    assert s2.query("select count(*) from a").rows == [(1,)]
    s1.query("begin")
    s1.query("delete from a")
    s1.query("rollback")
    assert s1.query("select count(*) from a").rows == [(1,)]


def test_txn_write_conflict():
    eng = Engine()
    s1, s2 = eng.new_session(), eng.new_session()
    s1.query("create table c (x bigint); insert into c values (1)")
    s1.query("begin")
    s2.query("begin")
    s1.query("delete from c where x = 1")
    s2.query("delete from c where x = 1")
    s1.query("commit")
    with pytest.raises(TxnError):
        s2.query("commit")


def test_insert_select_and_defaults(tk):
    tk.must_exec("create table src (a bigint, b varchar(10))")
    tk.must_exec("insert into src values (1,'x'),(2,'y')")
    tk.must_exec("create table dst (a bigint, b varchar(10), "
                 "c bigint default 7)")
    tk.must_exec("insert into dst (a, b) select a, b from src")
    tk.must_query("select a, b, c from dst order by a",
                  [(1, "x", 7), (2, "y", 7)])


# ---------------------------------------------------------------------------
# DDL / SHOW / EXPLAIN / errors
# ---------------------------------------------------------------------------


def test_show_and_explain(people):
    rs = people.must_query("show tables")
    assert ("t",) in rs.rows
    rs = people.must_query("explain select city, count(*) from t group by city")
    ops = "".join(r[0] for r in rs.rows)
    assert "HashAgg" in ops and "TableScan" in ops
    rs = people.must_query(
        "explain analyze select count(*) from t where age > 1")
    assert any("rows:" in str(r[2]) for r in rs.rows)


def test_errors(tk):
    tk.must_exec("create table err (a bigint)")
    with pytest.raises(TableExistsError):
        tk.must_exec("create table err (a bigint)")
    with pytest.raises(UnknownTableError):
        tk.must_exec("select * from nope")
    with pytest.raises(UnknownColumnError):
        tk.must_exec("select nope from err")
    with pytest.raises(TiDBTPUError):
        tk.must_exec("insert into err values (1, 2)")


def test_types_roundtrip(tk):
    tk.must_exec("create table ty (d date, dt datetime, dec decimal(12,3), "
                 "f double, s varchar(10))")
    tk.must_exec("insert into ty values ('2024-03-15', "
                 "'2024-03-15 10:30:00', 1.125, 2.5, 'abc')")
    rs = tk.must_query("select * from ty")
    d, dt, dec, f, s = rs.rows[0]
    assert d == datetime.date(2024, 3, 15)
    assert dt == datetime.datetime(2024, 3, 15, 10, 30)
    assert dec == Decimal("1.125")
    assert f == 2.5 and s == "abc"
    tk.must_query("select year(d), month(d), dayofmonth(d) from ty",
                  [(2024, 3, 15)])


def test_truncate(people):
    people.must_exec("truncate table t")
    people.must_query("select count(*) from t", [(0,)])


# ---------------------------------------------------------------------------
# regression tests for review findings (commit atomicity, validation, ...)
# ---------------------------------------------------------------------------


def test_commit_atomicity_multi_table():
    # a conflict on one table must leave the other table untouched
    eng = Engine()
    s1, s2 = eng.new_session(), eng.new_session()
    s1.query("create table t1 (x bigint); insert into t1 values (1),(2)")
    s1.query("create table t2 (x bigint); insert into t2 values (1)")
    s1.query("begin")
    s1.query("delete from t1 where x = 1")
    s1.query("delete from t2 where x = 1")
    s2.query("delete from t2 where x = 1")  # autocommit conflict source
    with pytest.raises(TxnError):
        s1.query("commit")
    assert s2.query("select count(*) from t1").rows == [(2,)]


def test_concurrent_append_then_staged_delete():
    # region top-off must not break a concurrent txn's staged delete mask
    eng = Engine()
    s1, s2 = eng.new_session(), eng.new_session()
    s1.query("create table g (x bigint); insert into g values (1),(2),(3)")
    s1.query("begin")
    s1.query("delete from g where x = 2")
    s2.query("insert into g values (4),(5)")  # merges into the same region
    s1.query("commit")
    assert s2.query("select x from g order by x").rows == \
        [(1,), (3,), (4,), (5,)]


def test_count_distinct_multi_arg(tk):
    tk.must_exec("create table cd (a bigint, b bigint)")
    tk.must_exec("insert into cd values (1,1),(1,2),(2,1),(1,1),(1,null)")
    tk.must_query("select count(distinct a, b) from cd", [(3,)])


def test_insert_unknown_column(tk):
    tk.must_exec("create table iu (a bigint, b bigint)")
    with pytest.raises(UnknownColumnError):
        tk.must_exec("insert into iu (a, zzz) values (1, 99)")


def test_update_not_null(tk):
    tk.must_exec("create table un (a bigint not null)")
    tk.must_exec("insert into un values (1)")
    with pytest.raises(TiDBTPUError):
        tk.must_exec("update un set a = null")


# ---- AUTO_INCREMENT / LAST_INSERT_ID (meta/autoid analog) ------------------

def test_auto_increment_basics():
    from tidb_tpu.session import Engine
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ai (id BIGINT PRIMARY KEY AUTO_INCREMENT, "
              "v VARCHAR(8))")
    s.execute("INSERT INTO ai (v) VALUES ('a'), ('b')")
    assert s.query("SELECT LAST_INSERT_ID()").rows[0][0] == 1
    # NULL means allocate; explicit values push the counter MID-statement
    s.execute("INSERT INTO ai VALUES (NULL,'c'), (100,'d'), (NULL,'e')")
    assert s.query("SELECT id, v FROM ai ORDER BY id").rows == [
        (1, "a"), (2, "b"), (3, "c"), (100, "d"), (101, "e")]
    assert s.query("SELECT LAST_INSERT_ID()").rows[0][0] == 3
    s.execute("INSERT INTO ai (v) VALUES ('f')")
    assert s.query("SELECT id FROM ai WHERE v = 'f'").rows[0][0] == 102
    # SHOW CREATE carries the attribute
    ddl = s.query("SHOW CREATE TABLE ai").rows[0][1]
    assert "AUTO_INCREMENT" in ddl
    # explicit 0 allocates (NO_AUTO_VALUE_ON_ZERO off — MySQL default)
    s.execute("INSERT INTO ai VALUES (0,'g')")
    assert s.query("SELECT id FROM ai WHERE v = 'g'").rows[0][0] == 103


def test_auto_increment_survives_restore(tmp_path):
    from tidb_tpu.session import Engine
    from tidb_tpu.tools import backup, restore
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ar (id BIGINT PRIMARY KEY AUTO_INCREMENT, "
              "v BIGINT)")
    s.execute("INSERT INTO ar (v) VALUES (10), (20), (30)")
    backup(eng, str(tmp_path))
    eng2 = Engine()
    restore(eng2, str(tmp_path))
    s2 = eng2.new_session()
    s2.execute("INSERT INTO ar (v) VALUES (40)")
    # the allocator reseeds from MAX(id), not from 1
    assert s2.query("SELECT id FROM ar WHERE v = 40").rows[0][0] == 4


def test_now_not_cached_stale():
    import time
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    a = s.query("SELECT NOW()").rows[0][0]
    time.sleep(1.1)
    b = s.query("SELECT NOW()").rows[0][0]
    assert b > a        # a cached plan would freeze the folded constant


def test_auto_increment_guardrails():
    import pytest
    from tidb_tpu.errors import NotNullViolation
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE aig (id BIGINT PRIMARY KEY AUTO_INCREMENT, "
              "v BIGINT)")
    s.execute("INSERT INTO aig (v) VALUES (10)")
    # UPDATE keeps the NOT NULL invariant (only INSERT may pass NULL)
    with pytest.raises(NotNullViolation):
        s.execute("UPDATE aig SET id = NULL")
    # LAST_INSERT_ID() usable inside DML (parent-id-into-child pattern)
    s.execute("CREATE TABLE aich (pid BIGINT)")
    s.execute("INSERT INTO aich VALUES (LAST_INSERT_ID())")
    assert s.query("SELECT pid FROM aich").rows == [(1,)]
    # TRUNCATE restarts the counter at 1 (MySQL)
    s.execute("TRUNCATE TABLE aig")
    s.execute("INSERT INTO aig (v) VALUES (99)")
    assert s.query("SELECT id FROM aig").rows == [(1,)]


def test_show_databases_collation_charset():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    assert ("test",) in s.query("SHOW DATABASES").rows
    colls = [r[0] for r in s.query("SHOW COLLATION").rows]
    assert "utf8mb4_general_ci" in colls and "utf8mb4_bin" in colls
    assert s.query("SHOW CHARSET").rows[0][0] == "utf8mb4"
