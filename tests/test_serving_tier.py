"""Priority-aware serving tier: per-class admission queues and same-plan
micro-batching.

Pins the PR's acceptance contract:

* coalescing: 8 queued same-digest point reads with
  tidb_tpu_microbatch_max=8 execute as ONE device launch (summed
  programs_launched across all 8 guards == 1, exactly one
  `batched:<sig>` compute span in the cross-session trace), every
  member byte-exact vs its individual-path oracle;
* priority: an interactive statement queued behind a batch scan is
  granted before the scan's conn re-acquires; aged batch entries are
  promoted (anti-starvation), so nothing waits forever;
* flag-off equivalence: with classification off the scheduler is the
  PR-5 FIFO — grant order is arrival order and the waits/yields
  counters keep their semantics;
* isolation: a member KILLed (or deadline-expired) while parked in a
  micro-batch surfaces its own typed error and leaves the batch; the
  survivors still coalesce and stay byte-exact;
* degradation: a demux fault (microbatch-demux failpoint) falls back to
  warned per-member individual execution — never a shared error;
* digesting: IN-list arity does not fork the micro-batch digest.
"""

import json
import threading
import time

import pytest

from tidb_tpu.errors import TiDBTPUError
from tidb_tpu.executor import microbatch
from tidb_tpu.executor.scheduler import SCHEDULER, DeviceScheduler, AGING_S
from tidb_tpu.session import Engine
from tidb_tpu.util import failpoint, timeline
from tidb_tpu.util.observability import REGISTRY, normalize_sql

N_MEMBERS = 8
MB_ROWS = 256


def _mb_sql(i: int) -> str:
    # mid-range literals: every k is inside the single slab's zone-map
    # range, so all members share one survivor set (one batch key)
    return f"SELECT v FROM mb WHERE k = {40 + i}"


@pytest.fixture()
def tier():
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE mb (k BIGINT, v BIGINT)")
    s.execute("INSERT INTO mb VALUES " +
              ", ".join(f"({i}, {i * i})" for i in range(MB_ROWS)))
    s.execute("CREATE TABLE big (a BIGINT, g BIGINT)")
    s.execute("INSERT INTO big VALUES " +
              ", ".join(f"({i}, {i % 7})" for i in range(3000)))

    def new_session(mb_max: int = N_MEMBERS):
        ss = eng.new_session()
        ss.vars["tidb_tpu_engine"] = "on"
        ss.vars["tidb_tpu_row_threshold"] = 1
        ss.vars["tidb_tpu_microbatch_max"] = mb_max
        return ss

    yield eng, new_session
    eng.close()


def _counter(name: str) -> float:
    return REGISTRY.counters.get((name, ()), 0)


def _pile_up(new_session, n=N_MEMBERS, mb_max=N_MEMBERS):
    """Warm + oracle each member query, then dispatch all n concurrently
    with the device slot held so they rendezvous into one open batch.
    → (sessions, threads, results dict, oracle dict). The caller gets
    control while the slot is still held (leader queued on the
    scheduler, n-1 followers parked) and must release via the returned
    closure."""
    sessions = [new_session(mb_max) for _ in range(n)]
    oracle = {}
    for i in range(n):
        # solo runs take the individual path (a solo leader returns to
        # it untouched) — they are the byte-exactness oracle AND they
        # warm the parametrized program + the resident table
        oracle[i] = sessions[i].query(_mb_sql(i)).rows
        assert oracle[i] == [((40 + i) ** 2,)]
    results: dict = {}

    def worker(i):
        try:
            results[i] = sessions[i].query(_mb_sql(i)).rows
        except TiDBTPUError as e:
            results[i] = ("error", getattr(e, "code", None))

    threads = {i: threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n)}
    SCHEDULER.acquire(conn_id=-1)
    released = []

    def release():
        if not released:
            released.append(True)
            SCHEDULER.release()

    try:
        # first dispatcher in alone → it registers the batch and becomes
        # the leader queued on the (held) scheduler slot
        threads[0].start()
        deadline = time.monotonic() + 10.0
        while SCHEDULER.queue_depth() < 2:
            assert time.monotonic() < deadline, "leader never queued"
            time.sleep(0.005)
        for i in range(1, n):
            threads[i].start()
        want = n - 1
        while microbatch.queued_members() < want:
            assert time.monotonic() < deadline, \
                f"followers parked: {microbatch.queued_members()}/{want}"
            time.sleep(0.005)
    except BaseException:
        release()
        raise
    return sessions, threads, results, oracle, release


def test_eight_point_reads_one_launch_byte_exact(tier, tmp_path):
    """THE acceptance pin: 8 queued same-digest point reads, mb_max=8 →
    ONE device program launch, one `batched:<sig>` trace span, every
    member's rows byte-exact vs its individual run."""
    eng, new_session = tier
    batches0 = _counter("tidb_tpu_microbatch_batches_total")
    members0 = _counter("tidb_tpu_microbatch_members_total")
    timeline.start_global(str(tmp_path))
    sessions = threads = None
    try:
        sessions, threads, results, oracle, release = \
            _pile_up(new_session)
        release()
        for t in threads.values():
            t.join(timeout=30.0)
        assert not any(t.is_alive() for t in threads.values())
        for i in range(N_MEMBERS):
            assert results[i] == oracle[i], f"member {i}: {results[i]}"
        launches = sum(s.last_guard.phases.programs_launched
                       for s in sessions)
        assert launches == 1, \
            f"8 coalesced point reads dispatched {launches} programs"
        # every member was charged its parked/queued time
        assert all(s.last_guard.queue_waits >= 1 for s in sessions)
        assert _counter("tidb_tpu_microbatch_batches_total") \
            == batches0 + 1
        assert _counter("tidb_tpu_microbatch_members_total") \
            == members0 + N_MEMBERS
        # exactly one batched compute span in the cross-session trace
        path = timeline.flush()
        doc = json.loads(open(path).read())
        spans = [e for e in doc["traceEvents"]
                 if e.get("ph") != "M" and e.get("cat") == "compute"
                 and str((e.get("args") or {}).get("sig", ""))
                 .startswith("batched:")]
        assert len(spans) == 1, f"batched spans: {len(spans)}"
    finally:
        if threads is not None:
            release()
        timeline.stop_global()


class _FakeGuard:
    """Minimal guard: classification fields + an inert kill-check."""

    def __init__(self, cls, cost=None):
        self.sched_class = cls
        self.sched_cost = cost
        self.queue_wait_s = 0.0
        self.queue_waits = 0

    def check(self, site):
        return None


def _grant_order(holder_sched, arrivals):
    """Enqueue `arrivals` = [(name, guard, conn_id), ...] one at a time
    (strictly ordered tickets) against a held scheduler, then release →
    the order the scheduler granted them."""
    order = []
    done = threading.Event()

    def worker(name, guard, conn_id):
        holder_sched.acquire(guard=guard, conn_id=conn_id)
        order.append(name)
        holder_sched.release()
        if len(order) == len(arrivals):
            done.set()

    depth = holder_sched.queue_depth()         # holder + pre-queued
    threads = []
    for name, guard, conn_id in arrivals:
        t = threading.Thread(target=worker, args=(name, guard, conn_id),
                             daemon=True)
        t.start()
        threads.append(t)
        depth += 1
        deadline = time.monotonic() + 5.0
        while holder_sched.queue_depth() < depth:
            assert time.monotonic() < deadline
            time.sleep(0.002)
    holder_sched.release()
    assert done.wait(timeout=10.0)
    for t in threads:
        t.join(timeout=5.0)
    return order


def test_interactive_overtakes_queued_batch():
    """An interactive statement that arrives AFTER a heavy batch scan is
    already queued is granted first — strict priority by class."""
    ds = DeviceScheduler()
    ds.acquire(conn_id=-1)
    order = _grant_order(ds, [
        ("batch", _FakeGuard("batch", cost=1.0), 1),
        ("interactive", _FakeGuard("interactive"), 2),
    ])
    assert order == ["interactive", "batch"]
    assert ds.stats()["classes"]["interactive"]["waits"] == 1


def test_aged_batch_is_promoted_over_fresh_interactive():
    """Anti-starvation: a batch entry parked past AGING_S ranks as
    interactive, so its earlier ticket wins over a later arrival."""
    ds = DeviceScheduler()
    ds.acquire(conn_id=-1)
    start = threading.Event()
    order = []

    def batch_worker():
        start.set()
        ds.acquire(guard=_FakeGuard("batch", cost=1.0), conn_id=1)
        order.append("batch")
        ds.release()

    t = threading.Thread(target=batch_worker, daemon=True)
    t.start()
    start.wait(5.0)
    deadline = time.monotonic() + 5.0
    while ds.queue_depth() < 2:
        assert time.monotonic() < deadline, "batch entry never queued"
        time.sleep(0.002)
    time.sleep(AGING_S + 0.1)                  # let the entry age
    rest = _grant_order(ds, [
        ("interactive", _FakeGuard("interactive"), 2),
    ])
    t.join(timeout=5.0)
    assert order + rest == ["batch", "interactive"]


def test_flag_off_is_plain_fifo():
    """Unclassified admissions (priority scheduling off → sched_class
    None) collapse to the PR-5 FIFO: grant order is arrival order, and
    the waits counter charges exactly the queued admissions."""
    ds = DeviceScheduler()
    ds.reset_stats()
    ds.acquire(conn_id=-1)
    names = [f"q{i}" for i in range(4)]
    order = _grant_order(ds, [(n, None, 10 + i)
                              for i, n in enumerate(names)])
    assert order == names, f"flag-off grant order not FIFO: {order}"
    st = ds.stats()
    assert st["admissions"] == 5               # holder + 4 waiters
    assert st["waits"] == 4
    assert st["classes"] == {}                 # nothing was classified


def test_priority_flag_off_leaves_guard_unclassified(tier):
    eng, new_session = tier
    s = new_session()
    s.vars["tidb_tpu_priority_scheduling"] = "off"
    s.query(_mb_sql(0))
    assert s.last_guard.sched_class is None
    s.vars["tidb_tpu_priority_scheduling"] = "on"
    s.query(_mb_sql(0))
    assert s.last_guard.sched_class == "interactive"
    s.query("SELECT g, COUNT(*) FROM big GROUP BY g")
    assert s.last_guard.sched_class == "batch"


def test_kill_and_deadline_isolation_inside_microbatch(tier):
    """One parked member KILLed and one deadline-expired: each surfaces
    its own typed error (1317 / 3024) and leaves the batch; the six
    survivors still coalesce into one launch, byte-exact."""
    eng, new_session = tier
    members0 = _counter("tidb_tpu_microbatch_members_total")
    sessions, threads, results, oracle, release = _pile_up(new_session)
    try:
        # threads 1..7 are followers (thread 0 queued alone first and is
        # the leader). Kill follower 3; expire follower 5's deadline
        # directly on its parked guard (the deadline is armed at
        # admission, so a sysvar change can't reach the in-flight stmt).
        killer = new_session()
        sessions[5].last_guard.deadline = time.monotonic()
        killer.execute(f"KILL QUERY {sessions[3].conn_id}")
        deadline = time.monotonic() + 10.0
        while not (isinstance(results.get(3), tuple)
                   and isinstance(results.get(5), tuple)):
            assert time.monotonic() < deadline, \
                f"victims never errored: {results}"
            time.sleep(0.01)
    finally:
        release()
    for t in threads.values():
        t.join(timeout=30.0)
    assert results[3] == ("error", 1317), results[3]
    assert results[5] == ("error", 3024), results[5]
    survivors = [i for i in range(N_MEMBERS) if i not in (3, 5)]
    for i in survivors:
        assert results[i] == oracle[i], f"member {i}: {results[i]}"
    launches = sum(sessions[i].last_guard.phases.programs_launched
                   for i in survivors)
    assert launches == 1, f"survivors dispatched {launches} programs"
    assert _counter("tidb_tpu_microbatch_members_total") \
        == members0 + len(survivors)
    # victims' sessions still serve afterwards
    assert sessions[3].query(_mb_sql(3)).rows == oracle[3]


def test_demux_fault_degrades_to_warned_individual(tier):
    """microbatch-demux fault: every member still gets exactly its own
    rows (via individual fallback), the leader carries a 1105 warning,
    and the fallbacks counter advances — never a shared typed error."""
    eng, new_session = tier
    fallbacks0 = _counter("tidb_tpu_microbatch_fallbacks_total")
    sessions, threads, results, oracle, release = _pile_up(new_session)
    try:
        failpoint.enable("microbatch-demux",
                         raise_=RuntimeError("test: demux fault"),
                         times=1)
        release()
        for t in threads.values():
            t.join(timeout=30.0)
        assert failpoint.hits("microbatch-demux") > 0, \
            "batch never reached demux"
    finally:
        release()
        failpoint.disable("microbatch-demux")
    for i in range(N_MEMBERS):
        assert results[i] == oracle[i], f"member {i}: {results[i]}"
    assert _counter("tidb_tpu_microbatch_fallbacks_total") \
        == fallbacks0 + 1
    warned = [s for s in sessions
              if any(w[1] == 1105 and "micro-batch" in w[2]
                     for w in s.warnings)]
    assert len(warned) == 1, \
        f"exactly the leader warns, got {len(warned)}"


def test_in_list_arity_shares_digest():
    """normalize_sql collapses IN lists, so prepared bursts differing
    only in IN-arity rendezvous on one micro-batch digest."""
    a = normalize_sql("SELECT v FROM mb WHERE k IN (1, 2, 3)")
    b = normalize_sql("SELECT v FROM mb WHERE k IN (1,2,3,4,5)")
    c = normalize_sql("SELECT v FROM mb WHERE k IN (9)")
    assert a == b == c
    assert "(?)" in a
    # ...but a different shape still forks the digest
    d = normalize_sql("SELECT v FROM mb WHERE k IN (1,2) AND v > 0")
    assert d != a
    # unary minus folds into the placeholder: x = -5 and x = 5 coalesce
    assert normalize_sql("SELECT v FROM mb WHERE k = -5") \
        == normalize_sql("SELECT v FROM mb WHERE k = 5")
