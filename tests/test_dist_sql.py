"""SQL → distributed execution: planner-inserted exchanges compiled to
shard_map programs over the 8-device virtual mesh, results equal to the
single-device CPU engine (the reference's MPP tests over unistore,
executor/tiflash_test.go pattern — a real cluster faked in-process)."""

import numpy as np
import pytest

from tidb_tpu.executor import build, run_to_completion
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE orders (o_id BIGINT, o_prio BIGINT, "
              "o_seg VARCHAR(12))")
    s.execute("CREATE TABLE li (l_oid BIGINT, l_price DECIMAL(12,2), "
              "l_disc DECIMAL(12,2), l_flag VARCHAR(4), l_ship DATE)")
    rng = np.random.default_rng(23)
    n_orders, n_li = 800, 12000
    rows = []
    for i in range(n_orders):
        seg = ["BUILDING", "AUTO", "STEEL"][int(rng.integers(0, 3))]
        rows.append(f"({i},{int(rng.integers(0, 5))},'{seg}')")
    s.execute("INSERT INTO orders VALUES " + ",".join(rows))
    rows = []
    for _ in range(n_li):
        k = int(rng.integers(0, n_orders + 100))
        key = "NULL" if rng.random() < 0.02 else str(k)
        flag = ["A", "N", "R"][int(rng.integers(0, 3))]
        rows.append(f"({key},{round(float(rng.uniform(1, 900)), 2)},"
                    f"{round(float(rng.uniform(0, 0.1)), 2)},'{flag}',"
                    f"'199{int(rng.integers(5, 9))}-0"
                    f"{int(rng.integers(1, 10))}-11')")
    s.execute("INSERT INTO li VALUES " + ",".join(rows))
    # dup_orders: each id appears 1-3 times → a NON-unique join build side
    s.execute("CREATE TABLE dup_orders (d_id BIGINT, d_prio BIGINT, "
              "d_seg VARCHAR(12))")
    rows = []
    for i in range(n_orders):
        seg = ["BUILDING", "AUTO", "STEEL"][int(rng.integers(0, 3))]
        for _ in range(int(rng.integers(1, 4))):
            rows.append(f"({i},{int(rng.integers(0, 5))},'{seg}')")
    s.execute("INSERT INTO dup_orders VALUES " + ",".join(rows))
    s.execute("CREATE TABLE segs (s_name VARCHAR(12), s_rank BIGINT)")
    s.execute("INSERT INTO segs VALUES ('BUILDING',1),('AUTO',2),"
              "('STEEL',3)")
    s.execute("ANALYZE TABLE orders")
    s.execute("ANALYZE TABLE li")
    s.execute("ANALYZE TABLE dup_orders")
    s.execute("ANALYZE TABLE segs")
    return s


def run_dist(s, sql, shards=8):
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    s.vars["tidb_tpu_dist_devices"] = shards
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags, f"no fragment extracted for: {sql}"
        for f in frags:
            assert f.plan.dist == shards, \
                f"fragment not distributed for: {sql}"
            assert f.used_device, \
                f"fell back ({f.fallback_reason}) for: {sql}"
        return [r for ch in chunks for r in ch.rows()]
    finally:
        s.vars["tidb_tpu_engine"] = "off"
        s.vars.pop("tidb_tpu_dist_devices", None)


def assert_same(rows1, rows2, ordered=False):
    assert len(rows1) == len(rows2), (len(rows1), len(rows2))
    if not ordered:
        rows1 = sorted(rows1, key=str)
        rows2 = sorted(rows2, key=str)
    for r1, r2 in zip(rows1, rows2):
        for v1, v2 in zip(r1, r2):
            if isinstance(v1, float) and v2 is not None:
                assert abs(v1 - v2) <= 1e-5 * max(1.0, abs(v2)), (r1, r2)
            else:
                assert v1 == v2, (r1, r2)


# ---- Q1 shape: sharded chain, two-phase distributed aggregate -------------

def test_dist_q1_chain(session):
    sql = ("SELECT l_flag, COUNT(*), SUM(l_price), AVG(l_disc), "
           "MIN(l_price), MAX(l_price) FROM li "
           "WHERE l_ship <= '1998-09-02' GROUP BY l_flag")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_ungrouped_agg(session):
    sql = "SELECT COUNT(*), SUM(l_price), MIN(l_disc) FROM li"
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_high_cardinality_groups(session):
    sql = "SELECT l_oid, COUNT(*), SUM(l_price) FROM li GROUP BY l_oid"
    assert_same(run_dist(session, sql), session.query(sql).rows)


# ---- Q3 shape: exchanges under joins --------------------------------------

def test_dist_q3_join_agg(session):
    sql = ("SELECT o_prio, COUNT(*), SUM(l_price * (1 - l_disc)) FROM li "
           "JOIN orders ON l_oid = o_id GROUP BY o_prio")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_join_filters_both_sides(session):
    sql = ("SELECT o_seg, COUNT(*), SUM(l_price) FROM li "
           "JOIN orders ON l_oid = o_id "
           "WHERE o_prio < 3 AND l_ship < '1998-01-01' GROUP BY o_seg")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_left_join(session):
    sql = ("SELECT o_prio, COUNT(*), COUNT(o_id) FROM li "
           "LEFT JOIN orders ON l_oid = o_id GROUP BY o_prio")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_topn_over_join(session):
    sql = ("SELECT l_oid, l_price, o_prio FROM li JOIN orders "
           "ON l_oid = o_id ORDER BY l_price DESC, l_oid LIMIT 9")
    assert_same(run_dist(session, sql), session.query(sql).rows,
                ordered=True)


def test_exchange_in_explain(session):
    session.vars["tidb_tpu_engine"] = "on"
    session.vars["tidb_tpu_row_threshold"] = 1
    session.vars["tidb_tpu_dist_devices"] = 8
    try:
        rows = session.query(
            "EXPLAIN SELECT o_prio, COUNT(*) FROM li JOIN orders "
            "ON l_oid = o_id GROUP BY o_prio").rows
        txt = "\n".join(str(r) for r in rows)
        assert "Exchange" in txt, txt
        assert "shards:8" in txt, txt
    finally:
        session.vars["tidb_tpu_engine"] = "off"
        session.vars.pop("tidb_tpu_dist_devices", None)


def test_dist_distinct_grouped(session):
    # DISTINCT distributes via a re-keyed exchange on the group keys
    sql = ("SELECT l_flag, COUNT(DISTINCT l_oid), COUNT(*) FROM li "
           "GROUP BY l_flag")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_distinct_global(session):
    sql = "SELECT COUNT(DISTINCT l_oid) FROM li"
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_skewed_exchange_retries_exactly_once(session):
    # 3 distinct group keys hash onto ≤3 of 8 shards: the re-key exchange
    # overflows a deliberately tiny initial bucket cap; the exchange
    # reports its exact need, so recovery is ONE recompile (per-exchange
    # needs, VERDICT r2 weak #7). This pins the MONOLITHIC oracle path —
    # the staged exchange's per-rank equivalent (one skewed rank = one
    # recompile) is pinned in tests/test_staged_exchange.py
    from tidb_tpu.executor import dist_fragment as DF
    sql = ("SELECT l_flag, COUNT(DISTINCT l_oid) FROM li GROUP BY l_flag")
    compiles = []
    orig = DF.DistTreeProgram.__init__

    def counting(self, *a, **k):
        compiles.append(1)
        return orig(self, *a, **k)

    DF.DistTreeProgram.__init__ = counting
    session.vars["tidb_tpu_exchange_bucket_cap"] = 64
    session.vars["tidb_tpu_dist_staged_exchange"] = "off"
    try:
        from tidb_tpu.executor.fragment import _COMPILE_CACHE
        _COMPILE_CACHE.clear()
        got = run_dist(session, sql)
    finally:
        DF.DistTreeProgram.__init__ = orig
        session.vars.pop("tidb_tpu_exchange_bucket_cap", None)
        session.vars.pop("tidb_tpu_dist_staged_exchange", None)
    assert_same(got, session.query(sql).rows)
    assert len(compiles) == 2, compiles    # initial + exactly one retry


def test_dist_fallback_strips_exchanges(session):
    # a runtime fallback of a DISTRIBUTED fragment must run on CPU even
    # though the plan carries Exchange nodes (regression: 'no executor
    # for PhysExchange')
    from tidb_tpu.util import failpoint
    sql = ("SELECT o_prio, COUNT(*) FROM li JOIN orders ON l_oid = o_id "
           "GROUP BY o_prio")
    failpoint.enable("device-fragment",
                     raise_=RuntimeError("injected device loss"))
    session.vars["tidb_tpu_engine"] = "on"
    session.vars["tidb_tpu_row_threshold"] = 1
    session.vars["tidb_tpu_dist_devices"] = 8
    try:
        got = session.query(sql).rows
    finally:
        failpoint.disable("device-fragment")
        session.vars["tidb_tpu_engine"] = "off"
        session.vars.pop("tidb_tpu_dist_devices", None)
    assert_same(got, session.query(sql).rows)


# ---- single-chip parity: non-unique builds, string keys, window/row roots


def test_dist_nonunique_build_join(session):
    # duplicate build keys: the unique bet is lost on some shard; the
    # expand-mode re-trace (per-shard out caps) must recover, not fall
    # back (round-3 seam: FragmentFallback("non-unique join build side"))
    sql = ("SELECT d_prio, COUNT(*), SUM(l_price) FROM li "
           "JOIN dup_orders ON l_oid = d_id GROUP BY d_prio")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_nonunique_left_join(session):
    sql = ("SELECT d_seg, COUNT(*), COUNT(d_id) FROM li "
           "LEFT JOIN dup_orders ON l_oid = d_id GROUP BY d_seg")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_varchar_join_key(session):
    # string equi keys: dictionaries unified host-side before sharding so
    # equal strings hash equal across scans (round-3 seam: "exchange-side
    # dictionary unification TBD")
    sql = ("SELECT s_rank, COUNT(*) FROM li "
           "JOIN orders ON l_oid = o_id "
           "JOIN segs ON o_seg = s_name GROUP BY s_rank")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_varchar_key_groupby_string(session):
    sql = ("SELECT o_seg, s_rank, COUNT(*) FROM orders "
           "JOIN segs ON o_seg = s_name GROUP BY o_seg, s_rank")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_window_root(session):
    # window root: the planner inserts a hash exchange on the partition
    # keys so per-shard windows are globally exact
    sql = ("SELECT l_flag, l_price, "
           "SUM(l_price) OVER (PARTITION BY l_flag ORDER BY l_price), "
           "ROW_NUMBER() OVER (PARTITION BY l_flag ORDER BY l_price DESC)"
           " FROM li")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_row_root_join(session):
    # selection/join row root: per-shard rows, host concatenates
    sql = ("SELECT l_oid, l_price, o_prio FROM li "
           "JOIN orders ON l_oid = o_id WHERE l_price > 890")
    assert_same(run_dist(session, sql), session.query(sql).rows)


def test_dist_matches_single_device_tree(session):
    # same SQL through the single-shard tree path and 8-shard dist path
    sql = ("SELECT o_seg, COUNT(*), SUM(l_price) FROM li "
           "JOIN orders ON l_oid = o_id GROUP BY o_seg")
    dist = run_dist(session, sql)
    session.vars["tidb_tpu_engine"] = "on"
    session.vars["tidb_tpu_row_threshold"] = 1
    try:
        single = session.query(sql).rows
    finally:
        session.vars["tidb_tpu_engine"] = "off"
    assert_same(dist, single)


def test_dist_partitioned_table_pruned_scan(session):
    """Partition pruning composes with the multi-chip path: the pruned
    region set is what gets slabbed and sharded across the mesh."""
    s = session
    s.vars["tidb_tpu_engine"] = "off"
    s.execute("CREATE TABLE pt (id BIGINT, g BIGINT, v BIGINT) "
              "PARTITION BY RANGE (id) ("
              "PARTITION p0 VALUES LESS THAN (4000), "
              "PARTITION p1 VALUES LESS THAN (8000), "
              "PARTITION p2 VALUES LESS THAN (MAXVALUE))")
    rng = np.random.default_rng(31)
    s.execute("INSERT INTO pt VALUES " + ",".join(
        f"({int(rng.integers(0, 12000))},{int(rng.integers(0, 7))},"
        f"{int(rng.integers(0, 100))})" for _ in range(12000)))
    s.execute("ANALYZE TABLE pt")
    sql = ("SELECT g, COUNT(*), SUM(v) FROM pt WHERE id < 8000 "
           "GROUP BY g ORDER BY g")
    want = s.query(sql).rows
    got = run_dist(s, sql)
    assert got == want
