"""Coalesced single-row ingest (session/writebatch.py).

N queued same-digest autocommit writes rendezvous behind the
per-(store, table) commit gate and commit as ONE transaction — one
`delta-append` crossing, one store version bump, one delta extension
for every reader. These tests pin:

* the solo path: INSERT/UPDATE/DELETE through the coalesced gate stay
  byte-identical to the individual write path (affected_rows, typed
  duplicate-key errors, table state);
* the rendezvous: N concurrent writers parked behind a held commit gate
  produce exactly ONE version bump, and every member's row lands;
* per-member error isolation: a duplicate-key member gets ITS OWN typed
  1062 while its batch siblings commit exactly once;
* the lifecycle contract (the satellite): KILL (1317) and a
  max_execution_time deadline (3024) landing on a QUEUED member
  surface the victim's OWN typed error, its write is NEVER applied,
  and the surviving members still commit exactly once — a follow-up
  read sees their rows and not the victim's;
* a commit-time fault (`delta-append`, non-retryable) fails every
  applied member with the SAME typed error and the store version stays
  put — all-or-nothing, never torn.
"""

import threading
import time

import pytest

from tidb_tpu.errors import TiDBTPUError, TxnError
from tidb_tpu.session import Engine, writebatch
from tidb_tpu.util import failpoint
from tidb_tpu.util.guard import PROCESS_REGISTRY
from tidb_tpu.util.observability import REGISTRY


def _engine():
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE t (a BIGINT PRIMARY KEY, b BIGINT)")
    s.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
    return eng, s


def _counter(name):
    return sum(v for (n, _), v in REGISTRY.counters.items() if n == name)


def _spawn_writers(eng, stmts, wait_parked, timeout=5.0):
    """Start one session+thread per statement while the caller holds the
    commit gate; wait until `wait_parked` followers are queued. →
    (threads, sessions, results, errors)."""
    n = len(stmts)
    sessions = [eng.new_session() for _ in range(n)]
    results: list = [None] * n
    errors: list = [None] * n

    def run(i):
        try:
            results[i] = sessions[i].query(stmts[i]).affected_rows
        except TiDBTPUError as e:
            errors[i] = e

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for i, th in enumerate(threads):
        th.start()
        if i == 0:
            # the first arrival must own the batch (leader) before the
            # followers join, so membership is deterministic
            deadline = time.monotonic() + timeout
            while not any(
                    k[2] for k in list(writebatch._BATCHES)) and \
                    time.monotonic() < deadline:
                time.sleep(0.002)
    deadline = time.monotonic() + timeout
    while writebatch.queued_members() < wait_parked and \
            time.monotonic() < deadline:
        time.sleep(0.002)
    assert writebatch.queued_members() >= wait_parked, \
        "followers never parked on the batch"
    return threads, sessions, results, errors


def test_solo_writes_through_the_gate():
    eng, s = _engine()
    assert s.query("INSERT INTO t VALUES (3, 30)").affected_rows == 1
    assert s.query("UPDATE t SET b = 31 WHERE a = 3").affected_rows == 1
    assert s.query("SELECT b FROM t WHERE a = 3").rows == [(31,)]
    assert s.query("DELETE FROM t WHERE a = 3").affected_rows == 1
    assert s.query("SELECT COUNT(*) FROM t").rows == [(2,)]
    with pytest.raises(TiDBTPUError) as ei:
        s.query("INSERT INTO t VALUES (1, 99)")
    assert ei.value.code == 1062
    assert s.query("SELECT COUNT(*) FROM t").rows == [(2,)]


def test_rendezvous_one_commit_for_n_writers():
    eng, s = _engine()
    info = eng.catalog.info_schema.table("t")
    gate = writebatch.commit_gate(eng.store, info.id)
    v0, b0 = eng.store.version, _counter("tidb_tpu_write_batches_total")
    m0 = _counter("tidb_tpu_write_members_total")
    N = 6
    gate.acquire()
    try:
        threads, _sessions, results, errors = _spawn_writers(
            eng, [f"INSERT INTO t VALUES ({100 + i}, {i})"
                  for i in range(N)], wait_parked=N - 1)
    finally:
        gate.release()
    for th in threads:
        th.join(10)
    assert results == [1] * N and errors == [None] * N
    assert eng.store.version - v0 == 1, \
        "N coalesced writers must bump the version ONCE"
    assert _counter("tidb_tpu_write_batches_total") - b0 == 1
    assert _counter("tidb_tpu_write_members_total") - m0 == N
    assert s.query("SELECT COUNT(*) FROM t WHERE a >= 100").rows == [(N,)]


def test_member_error_isolation_duplicate_key():
    eng, s = _engine()
    info = eng.catalog.info_schema.table("t")
    gate = writebatch.commit_gate(eng.store, info.id)
    v0 = eng.store.version
    gate.acquire()
    try:
        threads, _sessions, results, errors = _spawn_writers(
            eng, ["INSERT INTO t VALUES (200, 7)",
                  "INSERT INTO t VALUES (1, 7)",     # collides with seed
                  "INSERT INTO t VALUES (201, 7)",
                  "INSERT INTO t VALUES (202, 7)"], wait_parked=3)
    finally:
        gate.release()
    for th in threads:
        th.join(10)
    assert results[0] == results[2] == results[3] == 1
    assert results[1] is None and errors[1].code == 1062
    assert eng.store.version - v0 == 1, "survivors commit exactly once"
    assert s.query("SELECT COUNT(*) FROM t WHERE a IN (200, 201, 202)"
                   ).rows == [(3,)]
    assert s.query("SELECT b FROM t WHERE a = 1").rows == [(10,)]


@pytest.mark.parametrize("mode", ["kill", "deadline"])
def test_victim_of_queued_member_kill_and_deadline(mode):
    """Satellite: KILL / max_execution_time against a QUEUED coalesced
    write. The victim gets its OWN typed error (1317 / 3024), its row is
    never applied, and the survivors commit exactly once."""
    eng, s = _engine()
    info = eng.catalog.info_schema.table("t")
    gate = writebatch.commit_gate(eng.store, info.id)
    v0 = eng.store.version
    gate.acquire()
    try:
        threads, sessions, results, errors = _spawn_writers(
            eng, [f"INSERT INTO t VALUES ({300 + i}, {i})"
                  for i in range(4)], wait_parked=3)
        # threads[0] is the leader (blocked on the held gate); pick a
        # parked FOLLOWER as the victim
        victim = sessions[1]
        if mode == "kill":
            assert PROCESS_REGISTRY.kill(victim.conn_id, query_only=True)
            want_code = 1317
        else:
            # writes never arm an execute() deadline; model the
            # max_execution_time expiry by expiring the statement's
            # guard directly while it is parked
            g = PROCESS_REGISTRY.info(victim.conn_id)["guard"]
            assert g is not None
            g.deadline = time.monotonic() - 0.001
            want_code = 3024
        threads[1].join(10)
        assert not threads[1].is_alive(), "victim did not unwind"
        assert errors[1] is not None and errors[1].code == want_code, \
            (errors[1], getattr(errors[1], "code", None))
        # the victim left the batch before the leader could claim it
        assert writebatch.queued_members() == 2
    finally:
        gate.release()
    for th in threads:
        th.join(10)
    assert results[0] == results[2] == results[3] == 1
    assert all(e is None for i, e in enumerate(errors) if i != 1)
    assert eng.store.version - v0 == 1, "survivors commit exactly once"
    # follow-up read: survivors' rows landed, the victim's never did
    assert s.query("SELECT a FROM t WHERE a >= 300 ORDER BY a"
                   ).rows == [(300,), (302,), (303,)]


def test_commit_fault_fails_all_members_atomically():
    eng, s = _engine()
    info = eng.catalog.info_schema.table("t")
    gate = writebatch.commit_gate(eng.store, info.id)
    v0 = eng.store.version
    failpoint.enable("delta-append",
                     raise_=TxnError("chaos: commit fault"), times=1)
    gate.acquire()
    try:
        threads, _sessions, results, errors = _spawn_writers(
            eng, [f"INSERT INTO t VALUES ({400 + i}, {i})"
                  for i in range(3)], wait_parked=2)
    finally:
        gate.release()
    for th in threads:
        th.join(10)
    failpoint.disable("delta-append")
    assert results == [None] * 3, "a torn batch must not half-commit"
    assert all(e is not None and isinstance(e, TiDBTPUError)
               for e in errors), errors
    assert eng.store.version == v0, "version must stay put on a fault"
    assert s.query("SELECT COUNT(*) FROM t WHERE a >= 400").rows == [(0,)]
    # the session and the table stay usable afterwards
    assert s.query("INSERT INTO t VALUES (400, 0)").affected_rows == 1


def test_coalesce_off_falls_back_to_individual_commits():
    eng, s = _engine()
    v0 = eng.store.version
    sessions = [eng.new_session() for _ in range(3)]
    for ss in sessions:
        ss.vars["tidb_tpu_write_coalesce"] = "off"
    for i, ss in enumerate(sessions):
        assert ss.query(
            f"INSERT INTO t VALUES ({500 + i}, 1)").affected_rows == 1
    assert eng.store.version - v0 == 3, \
        "coalescing off: every write commits alone"
    assert s.query("SELECT COUNT(*) FROM t WHERE a >= 500").rows == [(3,)]
