"""Whole-query compilation: fused sort/TopN/DISTINCT roots
(executor/device_emit.py emit_sort/emit_topk/emit_distinct +
executor/fragment.py get_finalize_program / specialization cache).

Pinned invariants:

* an ORDER BY / TopN root over a HashAgg runs as ONE fused finalize
  launch (merge → finalize exprs → sort/topn → gather), byte-exact
  against the host-ordered path (`tidb_tpu_fused_finalize='off'`), the
  mega-slab tree path (`tidb_tpu_fused_pipeline='off'`) and the CPU
  volcano — string ci keys, wide-decimal outputs and MySQL NULL
  ordering (NULLs first ASC, last DESC) included;
* single-arg DISTINCT aggs no longer exclude a query from the fused
  pipeline: the (group, value) pair sets dedup on device, and a pair
  set clipped by `tidb_tpu_distinct_pair_cap` resizes through the
  resumable 'pairs' ladder rung — never silently truncating;
* the warm whole-query launch count is slabs + 1 (slab partials + the
  one fused finalize that replaced the root merge);
* EXPLAIN ANALYZE `launches=`/`spec_hits=` and statements_summary's
  PROGRAMS_LAUNCHED / SPECIALIZATION_HITS columns are byte-exact sums
  of the per-statement PhaseTimer ledger;
* the second execution of a repeated statement shape hits the
  per-digest specialization cache and retraces NOTHING;
* a fault at the finalize boundary becomes a warned CPU fallback that
  still returns the oracle rows.
"""

import re

import pytest

from tidb_tpu.executor import build, fragment as frag_mod, run_to_completion
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine
from tidb_tpu.util import failpoint


def agg_fixture(n=3000):
    """Single wide table with a NULLable int key, a lowercase ci string
    key, exact wide-decimal measures and enough rows for 3 slabs at
    max_slab_rows=1024."""
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE ff (g INT, s VARCHAR(8), v BIGINT, "
              "w DECIMAL(30,4))")
    rows = []
    for i in range(n):
        g = "NULL" if i % 11 == 0 else str(i % 7 - 3)
        rows.append(f"({g}, 'key{i % 5}', {(i * 37) % 211 - 100}, "
                    f"{(i * 97) % 100000}.{i % 10000:04d})")
    for base in range(0, n, 500):
        s.execute("INSERT INTO ff VALUES " + ",".join(rows[base:base + 500]))
    s.execute("ANALYZE TABLE ff")
    return eng, s


def device_rows(s, sql, extra_vars=None, *, expect_fallback=None):
    """Run on the device path; assert no CPU fallback (or, when
    expect_fallback is given, that the fallback reason mentions it)."""
    base = {"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
            "tidb_tpu_max_slab_rows": 1024}
    base.update(extra_vars or {})
    saved = {k: s.vars.get(k) for k in base}
    s.vars.update(base)
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags, f"no fragment extracted for: {sql}"
        for f in frags:
            if expect_fallback is None:
                assert f.used_device, f"fell back to CPU: {f.fallback_reason}"
            else:
                assert not f.used_device and \
                    expect_fallback in (f.fallback_reason or ""), \
                    f"wanted fallback {expect_fallback!r}, got " \
                    f"used_device={f.used_device} " \
                    f"reason={f.fallback_reason!r}"
        return [r for ch in chunks for r in ch.rows()]
    finally:
        for k, v in saved.items():
            if v is None:
                s.vars.pop(k, None)
            else:
                s.vars[k] = v


ORDER_SHAPES = [
    # NULL group key, both directions: MySQL NULLs first ASC, last DESC
    "SELECT g, COUNT(*), SUM(v) FROM ff GROUP BY g ORDER BY g",
    "SELECT g, COUNT(*), SUM(v) FROM ff GROUP BY g ORDER BY g DESC",
    # string ci key root order (lowercase data: mega-slab host order
    # ranks bytes, ci dicts rank folded — keep them agreeing)
    "SELECT s, COUNT(*), AVG(v) FROM ff GROUP BY s ORDER BY s",
    # wide-decimal agg OUTPUT rides the finalize gather untouched
    "SELECT g, SUM(w) FROM ff GROUP BY g ORDER BY g DESC",
    # TopN over an agg-output key, with offset
    "SELECT g, SUM(v) FROM ff GROUP BY g ORDER BY SUM(v) DESC LIMIT 3",
    "SELECT s, COUNT(*) FROM ff GROUP BY s ORDER BY COUNT(*) DESC, s "
    "LIMIT 2 OFFSET 1",
]


@pytest.mark.parametrize("sql", ORDER_SHAPES,
                         ids=["null-asc", "null-desc", "string-ci",
                              "wide-decimal", "topn-agg-key",
                              "topn-offset"])
def test_fused_finalize_byte_exact(sql):
    _, s = agg_fixture()
    cpu = s.query(sql).rows
    fused = device_rows(s, sql)
    host_ord = device_rows(s, sql, {"tidb_tpu_fused_finalize": "off"})
    mega = device_rows(s, sql, {"tidb_tpu_fused_pipeline": "off"})
    assert fused == host_ord, "fused finalize vs host-order mismatch"
    assert fused == mega, "fused finalize vs mega-slab mismatch"
    assert fused == cpu, "fused finalize vs CPU volcano mismatch"


# ---------------------------------------------------------------------------
# single-arg DISTINCT aggs inside the fused pipeline
# ---------------------------------------------------------------------------

DISTINCT_CHAIN = ("SELECT g, COUNT(DISTINCT v), SUM(v) FROM ff "
                  "GROUP BY g ORDER BY g")
DISTINCT_STRING = ("SELECT s, COUNT(DISTINCT g), COUNT(*) FROM ff "
                   "GROUP BY s ORDER BY s DESC")


@pytest.mark.parametrize("sql", [DISTINCT_CHAIN, DISTINCT_STRING],
                         ids=["int-value", "null-key-value"])
def test_single_arg_distinct_fused(sql):
    _, s = agg_fixture()
    cpu = s.query(sql).rows
    fused = device_rows(s, sql)
    assert fused == cpu
    # multi-slab DISTINCT really shipped pair sets through the fused
    # path, not the mega-slab fallback
    ph = frag_mod.LAST_PHASES
    assert ph is not None and ph.programs_launched > 0


def test_distinct_join_tree_fused():
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE dm (id INT, name VARCHAR(16))")
    s.execute("INSERT INTO dm VALUES " + ",".join(
        f"({i}, 'name{i:02d}')" for i in range(8)))
    s.execute("CREATE TABLE fx (b INT, v BIGINT)")
    for base in range(0, 3000, 500):
        s.execute("INSERT INTO fx VALUES " + ",".join(
            f"({i % 8}, {(i * 37) % 997})"
            for i in range(base, base + 500)))
    s.execute("ANALYZE TABLE dm")
    s.execute("ANALYZE TABLE fx")
    sql = ("SELECT d.name, COUNT(DISTINCT f.v) FROM fx f "
           "JOIN dm d ON f.b = d.id GROUP BY d.name ORDER BY d.name")
    cpu = s.query(sql).rows
    assert device_rows(s, sql) == cpu


def test_distinct_pair_cap_overflow_resumable():
    """A pair cap below the per-slab distinct pair count must clip, be
    DETECTED (true counts travel with the clipped sets), resize through
    the 'pairs' ladder rung to the exact need, re-run the clipped slabs
    and still answer the oracle."""
    _, s = agg_fixture()
    cpu = s.query(DISTINCT_CHAIN).rows
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_max_slab_rows": 1024,
                   "tidb_tpu_distinct_pair_cap": 64})
    try:
        assert s.query(DISTINCT_CHAIN).rows == cpu
        esc = s.last_guard.escalation
        assert esc.exact_resizes >= 1, esc.summary()
        assert esc.slabs_rerun >= 1, esc.summary()
    finally:
        for k in ("tidb_tpu_engine", "tidb_tpu_row_threshold",
                  "tidb_tpu_max_slab_rows", "tidb_tpu_distinct_pair_cap"):
            s.vars.pop(k, None)


def test_finalize_fault_warned_cpu_fallback():
    """A raise at the fused-finalize-overflow boundary surfaces as a
    warned CPU fallback returning the oracle rows — never a truncated
    or partial fused result."""
    _, s = agg_fixture()
    sql = ORDER_SHAPES[0]
    cpu = s.query(sql).rows
    with failpoint.enabled("fused-finalize-overflow",
                           raise_=RuntimeError("chaos: finalize"),
                           times=9):
        rows = device_rows(s, sql, expect_fallback="chaos: finalize")
    assert rows == cpu


# ---------------------------------------------------------------------------
# ledger byte-exactness: EXPLAIN ANALYZE + statements_summary
# ---------------------------------------------------------------------------

def test_explain_analyze_counts_finalize_as_one_launch():
    _, s = agg_fixture()
    sql = ORDER_SHAPES[0]
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_max_slab_rows": 1024})
    try:
        s.query(sql)                       # cold: trace + first touch
        # the spec key pins RAW SQL (literals are trace constants), so
        # the EA statement is its own shape: run it once cold, then
        # assert on its warm repetition
        s.query("EXPLAIN ANALYZE " + sql)
        ea = s.query("EXPLAIN ANALYZE " + sql).rows
        text = " ".join(str(c) for r in ea for c in r)
        m = re.search(r"launches=(\d+)", text)
        assert m, f"no launches= in EXPLAIN ANALYZE: {text}"
        ph = s.last_guard.phases
        # byte-exact vs the ledger of the EA execution itself, and the
        # fused finalize counts as exactly ONE program over the slabs
        assert int(m.group(1)) == ph.programs_launched
        assert ph.programs_launched == ph.fused_pipelines + 1, ph.summary()
        sh = re.search(r"spec_hits=(\d+)", text)
        assert sh and int(sh.group(1)) == ph.specialization_hits
        assert ph.specialization_hits >= 1, \
            "second execution of the digest must hit the spec cache"
    finally:
        for k in ("tidb_tpu_engine", "tidb_tpu_row_threshold",
                  "tidb_tpu_max_slab_rows"):
            s.vars.pop(k, None)


def test_statements_summary_specialization_hits_ledger():
    _, s = agg_fixture()
    sql = DISTINCT_CHAIN
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_max_slab_rows": 1024})
    q = ("SELECT digest_text, programs_launched, specialization_hits "
         "FROM information_schema.statements_summary")

    def digest_counts():
        # the registry is process-global: measure as a delta
        hits = [r for r in s.query(q).rows if r[0] == sql]
        assert len(hits) <= 1, hits
        return (hits[0][1], hits[0][2]) if hits else (0, 0)

    try:
        l0, h0 = digest_counts()
        want_launch = want_hits = 0
        for _ in range(3):
            s.query(sql)
            ph = s.last_guard.phases
            want_launch += ph.programs_launched
            want_hits += ph.specialization_hits
        l1, h1 = digest_counts()
        assert l1 - l0 == want_launch
        assert h1 - h0 == want_hits
        assert want_hits >= 2, "executions 2 and 3 must hit the cache"
    finally:
        for k in ("tidb_tpu_engine", "tidb_tpu_row_threshold",
                  "tidb_tpu_max_slab_rows"):
            s.vars.pop(k, None)


def test_specialization_distinguishes_literals():
    """Same digest, different literal: the traced programs embed the
    literal as an XLA constant, so the specialization entries must NOT
    be shared across literals."""
    _, s = agg_fixture()
    qa = "SELECT g, COUNT(*) FROM ff WHERE v > 5 GROUP BY g ORDER BY g"
    qb = "SELECT g, COUNT(*) FROM ff WHERE v > 90 GROUP BY g ORDER BY g"
    cpu_a, cpu_b = s.query(qa).rows, s.query(qb).rows
    assert cpu_a != cpu_b, "fixture must make the literals distinguish"
    assert device_rows(s, qa) == cpu_a
    assert device_rows(s, qb) == cpu_b
    # warm re-runs, reversed order: hits must serve the RIGHT programs
    assert device_rows(s, qb) == cpu_b
    assert device_rows(s, qa) == cpu_a


# ---------------------------------------------------------------------------
# perf pins: slabs + 1 warm launches, zero retrace on a repeated digest
# ---------------------------------------------------------------------------

@pytest.mark.perf_smoke
@pytest.mark.parametrize("sql", [ORDER_SHAPES[0], ORDER_SHAPES[2],
                                 ORDER_SHAPES[4]],
                         ids=["order-null-key", "order-string",
                              "topn-agg-key"])
def test_warm_whole_query_is_slabs_plus_one(sql):
    _, s = agg_fixture()
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_max_slab_rows": 1024})   # 3 slabs
    try:
        cold = s.query(sql).rows
        traces = frag_mod.PROGRAM_TRACES
        for _ in range(2):
            assert s.query(sql).rows == cold
            ph = s.last_guard.phases
            assert ph.fused_pipelines == 3, ph.summary()
            assert ph.programs_launched <= ph.fused_pipelines + 1, \
                ph.summary()
            assert ph.specialization_hits >= 1, ph.summary()
        assert frag_mod.PROGRAM_TRACES == traces, \
            "repeated digest must not retrace"
    finally:
        for k in ("tidb_tpu_engine", "tidb_tpu_row_threshold",
                  "tidb_tpu_max_slab_rows"):
            s.vars.pop(k, None)
