"""Views (CREATE/DROP/SHOW CREATE VIEW, builder expansion — ref:
ddl/ddl_api.go:2186, logical_plan_builder.go:4376 BuildDataSourceFromView)
and optimizer hints (/*+ ... */ steering the physical search — ref:
planner/optimize.go:138)."""

import numpy as np
import pytest

from tidb_tpu.errors import DDLError, PlanError, TableExistsError
from tidb_tpu.session import Engine


def _explain(s, sql):
    return "\n".join(str(r) for r in s.query("EXPLAIN " + sql).rows)


@pytest.fixture()
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE t (a BIGINT, b BIGINT, g VARCHAR(4))")
    s.execute("INSERT INTO t VALUES " + ",".join(
        f"({i},{i % 10},'g{i % 3}')" for i in range(1000)))
    s.execute("ANALYZE TABLE t")
    return s


def test_view_basics(s):
    s.execute("CREATE VIEW v AS SELECT g, SUM(a) AS total FROM t GROUP BY g")
    rows = s.query("SELECT * FROM v ORDER BY g").rows
    assert len(rows) == 3 and rows[0][0] == "g0"
    # views join with tables and take aliases
    r = s.query("SELECT v.total FROM v JOIN t ON v.g = t.g "
                "WHERE t.a = 0").rows
    assert len(r) == 1
    # WHERE over the view projects through
    assert s.query("SELECT total FROM v WHERE g = 'g1'").rows == \
        s.query("SELECT SUM(a) FROM t WHERE g = 'g1'").rows


def test_view_column_list_and_or_replace(s):
    s.execute("CREATE VIEW v2 (grp, cnt) AS SELECT g, COUNT(*) FROM t "
              "GROUP BY g")
    assert s.query("SELECT grp, cnt FROM v2 ORDER BY grp").rows[0] == \
        ("g0", 334)
    with pytest.raises(TableExistsError):
        s.execute("CREATE VIEW v2 AS SELECT 1")
    s.execute("CREATE OR REPLACE VIEW v2 AS SELECT a FROM t WHERE a < 3")
    assert len(s.query("SELECT * FROM v2").rows) == 3
    with pytest.raises(TableExistsError):
        s.execute("CREATE VIEW t AS SELECT 1")   # name clash with table


def test_view_nesting_and_drop(s):
    s.execute("CREATE VIEW base AS SELECT a, b FROM t WHERE a < 100")
    s.execute("CREATE VIEW top1 AS SELECT b, COUNT(*) AS n FROM base "
              "GROUP BY b")
    assert len(s.query("SELECT * FROM top1").rows) == 10
    names = [r[0] for r in s.query("SHOW TABLES").rows]
    assert "base" in names and "top1" in names
    ddl = s.query("SHOW CREATE VIEW base").rows[0][1]
    assert ddl.startswith("CREATE VIEW `base` AS SELECT")
    s.execute("DROP VIEW top1, base")
    with pytest.raises(Exception):
        s.query("SELECT * FROM base")
    s.execute("DROP VIEW IF EXISTS base")   # no error


def test_view_dml_rejected_and_schema_tracking(s):
    s.execute("CREATE VIEW vd AS SELECT a FROM t")
    with pytest.raises(DDLError):
        s.execute("INSERT INTO vd VALUES (1)")
    with pytest.raises(DDLError):
        s.execute("DELETE FROM vd")
    # invalid definitions fail at CREATE time
    with pytest.raises(Exception):
        s.execute("CREATE VIEW bad AS SELECT nosuch FROM t")
    # view over a dropped table errors at USE time (MySQL behavior)
    s.execute("CREATE TABLE tmp (x BIGINT)")
    s.execute("CREATE VIEW vtmp AS SELECT x FROM tmp")
    s.execute("DROP TABLE tmp")
    with pytest.raises(Exception):
        s.query("SELECT * FROM vtmp")


def test_view_on_device_engine(s):
    s.execute("CREATE TABLE big (k BIGINT, v BIGINT)")
    rng = np.random.default_rng(2)
    s.execute("INSERT INTO big VALUES " + ",".join(
        f"({int(rng.integers(0, 50))},{int(rng.integers(0, 100))})"
        for _ in range(50000)))
    s.execute("ANALYZE TABLE big")
    s.execute("CREATE VIEW vb AS SELECT k, SUM(v) AS sv FROM big GROUP BY k")
    want = sorted(s.query("SELECT * FROM vb").rows)
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_strict="on")
    try:
        got = sorted(s.query("SELECT * FROM vb").rows)
    finally:
        s.vars.update(tidb_tpu_engine="off", tidb_tpu_strict="off")
    assert got == want


# ---- optimizer hints --------------------------------------------------------


@pytest.fixture()
def hs():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE inner_t (k BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("CREATE TABLE outer_t (k BIGINT, w BIGINT, INDEX ik (k))")
    s.execute("INSERT INTO inner_t VALUES " + ",".join(
        f"({i},{i % 7})" for i in range(20000)))
    s.execute("INSERT INTO outer_t VALUES " + ",".join(
        f"({i % 20000},{i})" for i in range(20000)))
    s.execute("ANALYZE TABLE inner_t")
    s.execute("ANALYZE TABLE outer_t")
    return s


def test_join_hints_flip_plan(hs):
    s = hs
    sql = "SELECT {} COUNT(*) FROM outer_t JOIN inner_t ON outer_t.k = inner_t.k"
    base = _explain(s, sql.format(""))
    # cost picks merge join for this shape; hints force the others
    assert "MergeJoin" in base
    hinted = _explain(s, sql.format("/*+ HASH_JOIN(inner_t) */"))
    assert "HashJoin" in hinted and "MergeJoin" not in hinted
    hinted = _explain(s, sql.format("/*+ INL_JOIN(inner_t) */"))
    assert "IndexLookupJoin" in hinted
    # results identical under every forced shape
    want = s.query(sql.format("")).rows
    for h in ("/*+ HASH_JOIN(inner_t) */", "/*+ INL_JOIN(inner_t) */",
              "/*+ MERGE_JOIN(inner_t) */"):
        assert s.query(sql.format(h)).rows == want, h


def test_agg_hints_flip_plan(hs):
    s = hs
    sql = "SELECT {} k, COUNT(*) FROM outer_t GROUP BY k"
    base = _explain(s, sql.format(""))
    assert "StreamAgg" in base          # near-unique key → stream by cost
    hinted = _explain(s, sql.format("/*+ HASH_AGG() */"))
    assert "HashAgg" in hinted and "StreamAgg" not in hinted
    assert sorted(s.query(sql.format("/*+ HASH_AGG() */")).rows) == \
        sorted(s.query(sql.format("")).rows)
    # STREAM_AGG() forces the other direction on a low-NDV key
    s.execute("CREATE TABLE lo2 (k BIGINT, INDEX ik (k))")
    s.execute("INSERT INTO lo2 VALUES " + ",".join(
        f"({i % 3})" for i in range(5000)))
    s.execute("ANALYZE TABLE lo2")
    assert "HashAgg" in _explain(s, "SELECT k, COUNT(*) FROM lo2 GROUP BY k")
    forced = _explain(
        s, "SELECT /*+ STREAM_AGG() */ k, COUNT(*) FROM lo2 GROUP BY k")
    assert "StreamAgg" in forced


def test_review_r5_view_findings(s):
    # CTE must not hijack a view's base table (isolation)
    s.execute("CREATE VIEW iso AS SELECT a FROM t WHERE a = 1")
    rows = s.query("WITH t AS (SELECT 99 AS a) SELECT * FROM iso").rows
    assert rows == [(1,)]
    # CREATE TABLE over a view name is rejected (one namespace)
    with pytest.raises(TableExistsError):
        s.execute("CREATE TABLE iso (x BIGINT)")
    # circular views hit the depth cap, not the Python recursion limit
    s.execute("CREATE VIEW ca AS SELECT 1 AS x")
    s.execute("CREATE VIEW cb AS SELECT (SELECT MAX(x) FROM ca) AS x")
    s.execute("CREATE OR REPLACE VIEW ca AS "
              "SELECT (SELECT MAX(x) FROM cb) AS x")
    with pytest.raises(Exception, match="[Vv]iew"):
        s.query("SELECT * FROM ca")
    # view plans are cacheable: repeated queries hit the plan cache
    s.query("SELECT * FROM iso")
    before = len(s._plan_cache)
    s.query("SELECT * FROM iso")
    assert len(s._plan_cache) == before and before > 0
    # hints in non-SELECT positions parse as plain comments
    s.execute("INSERT /*+ IGNORE_PLAN_CACHE() */ INTO t VALUES (5000,0,'gx')")
    assert s.query("SELECT COUNT(*) FROM t WHERE a = 5000").rows == [(1,)]
