"""TRACE statement + optimizer trace + per-operator spans
(ref: executor/trace.go, util/tracing/opt_trace.go, the per-executor
spans of executor.go:278)."""

import numpy as np
import pytest

from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE tr (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO tr VALUES " +
              ",".join(f"({i},{i % 5})" for i in range(1000)))
    s.execute("ANALYZE TABLE tr")
    return s


def test_trace_select_renders_span_tree(s):
    rs = s.query("TRACE SELECT b, COUNT(*) FROM tr WHERE a > 10 "
                 "GROUP BY b ORDER BY b")
    assert rs.names[0] == "operation"
    ops = [r[0] for r in rs.rows]
    text = "\n".join(ops)
    # session + planner + executor phases
    assert any("session.run" in o for o in ops), text
    assert any("planner.optimize" in o for o in ops), text
    assert any("executor.run" in o for o in ops), text
    # optimizer trace: rewrite rules appear as child spans
    assert any("rule.predicate_pushdown" in o for o in ops), text
    assert any("rule.constant_folding" in o for o in ops), text
    # per-operator spans with row counts
    assert any("op.HashAggExec" in o or "op.TpuFragmentExec" in o
               for o in ops), text
    # durations parse as numbers and nest under the root
    for _, start, dur in rs.rows:
        float(start), float(dur)


def test_trace_dml(s):
    rs = s.query("TRACE INSERT INTO tr VALUES (10000, 1)")
    ops = [r[0] for r in rs.rows]
    assert any("session.run" in o for o in ops)
    # the insert actually happened
    assert s.query("SELECT COUNT(*) FROM tr WHERE a = 10000").rows == [(1,)]


def test_trace_has_no_effect_outside_trace(s):
    # a plain query right after TRACE carries no tracer
    s.query("TRACE SELECT COUNT(*) FROM tr")
    assert s._tracer is None
    assert s.query("SELECT COUNT(*) FROM tr").rows[0][0] >= 1000


def test_operator_spans_report_rows(s):
    rs = s.query("TRACE SELECT * FROM tr WHERE b = 2")
    op_rows = [r for r in rs.rows if r[0].strip().startswith("└─op.")
               or "op." in r[0]]
    assert any("rows=" in r[0] for r in op_rows), rs.rows
