"""Greedy join reorder (planner/rules.py reorder_joins; ref:
planner/core/rule_join_reorder.go): a 3-table chain written largest-first
must plan smallest-first, and results must be unchanged."""

import numpy as np
import pytest

from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE big (b_id BIGINT, b_mid BIGINT, b_v BIGINT)")
    s.execute("CREATE TABLE mid (m_id BIGINT, m_small BIGINT, m_v BIGINT)")
    s.execute("CREATE TABLE small (s_id BIGINT, s_v BIGINT)")
    rng = np.random.default_rng(9)
    rows = ",".join(
        f"({i},{int(rng.integers(0, 400))},{int(rng.integers(0, 100))})"
        for i in range(4000))
    s.execute("INSERT INTO big VALUES " + rows)
    rows = ",".join(
        f"({i},{int(rng.integers(0, 20))},{int(rng.integers(0, 100))})"
        for i in range(400))
    s.execute("INSERT INTO mid VALUES " + rows)
    rows = ",".join(f"({i},{int(rng.integers(0, 100))})" for i in range(20))
    s.execute("INSERT INTO small VALUES " + rows)
    s.execute("ANALYZE TABLE big")
    s.execute("ANALYZE TABLE mid")
    s.execute("ANALYZE TABLE small")
    return s


CHAIN = ("FROM big JOIN mid ON b_mid = m_id "
         "JOIN small ON m_small = s_id")


def test_three_table_chain_reorders_smallest_first(s):
    rows = s.query(f"EXPLAIN SELECT COUNT(*) {CHAIN}").rows
    text = "\n".join(str(r) for r in rows)
    # the first (deepest-left) scan must be one of the small tables, not
    # `big` as written; scan order in EXPLAIN output is depth-first
    scan_lines = [str(r) for r in rows if "table:" in str(r)]
    assert scan_lines, text
    first = scan_lines[0]
    assert "table:big" not in first, text


def test_reorder_preserves_results(s):
    sql = (f"SELECT s_v, COUNT(*), SUM(b_v + m_v) {CHAIN} "
           "WHERE b_v < 50 GROUP BY s_v ORDER BY s_v")
    got = s.query(sql).rows
    big = s.query("SELECT b_id, b_mid, b_v FROM big").rows
    mid = {m: (sm, mv) for m, sm, mv in
           s.query("SELECT m_id, m_small, m_v FROM mid").rows}
    small = {i: v for i, v in s.query("SELECT s_id, s_v FROM small").rows}
    want = {}
    for _, bm, bv in big:
        if bv >= 50 or bm not in mid:
            continue
        sm, mv = mid[bm]
        if sm not in small:
            continue
        sv = small[sm]
        c, t = want.get(sv, (0, 0))
        want[sv] = (c + 1, t + bv + mv)
    assert got == [(k, c, t) for k, (c, t) in sorted(want.items())]


def test_reorder_preserves_column_order(s):
    # star select across the chain must keep the written column order
    got = s.query(f"SELECT * {CHAIN} WHERE b_id = 7").rows
    assert len(got) <= 1
    if got:
        row = got[0]
        assert row[0] == 7                    # b_id first as written
        assert len(row) == 3 + 3 + 2


def test_reorder_with_filters_and_cross_edge(s):
    # non-adjacent equi edge (big↔small) + filters: results unchanged
    sql = ("SELECT COUNT(*) FROM big JOIN mid ON b_mid = m_id "
           "JOIN small ON m_small = s_id AND b_v = s_v")
    got = s.query(sql).rows[0][0]
    big = s.query("SELECT b_id, b_mid, b_v FROM big").rows
    mid = {m: (sm, mv) for m, sm, mv in
           s.query("SELECT m_id, m_small, m_v FROM mid").rows}
    small = {i: v for i, v in s.query("SELECT s_id, s_v FROM small").rows}
    want = 0
    for _, bm, bv in big:
        if bm in mid:
            sm, _ = mid[bm]
            if sm in small and small[sm] == bv:
                want += 1
    assert got == want


# ---- outer-join simplification (rule_predicate_push_down simplifyOuterJoin)


def _plan_text(s, sql):
    from tidb_tpu.parser import parse
    plan = s._plan(parse(sql)[0])
    return "\n".join(str(r) for r in plan.explain_lines())


def test_outer_join_simplifies_to_inner():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE oa (x BIGINT)")
    s.execute("CREATE TABLE ob (y BIGINT, z BIGINT)")
    s.execute("INSERT INTO oa VALUES (1),(2),(3)")
    s.execute("INSERT INTO ob VALUES (1,10),(2,NULL)")
    # z > 5 rejects null-extended rows → INNER
    txt = _plan_text(s, "SELECT * FROM oa LEFT JOIN ob ON x = y "
                        "WHERE z > 5")
    assert "inner" in txt and "left" not in txt, txt
    assert s.query("SELECT * FROM oa LEFT JOIN ob ON x = y WHERE z > 5"
                   ).rows == [(1, 1, 10)]
    # IS NOT NULL on the inner side rejects too
    txt = _plan_text(s, "SELECT * FROM oa LEFT JOIN ob ON x = y "
                        "WHERE y IS NOT NULL")
    assert "inner" in txt, txt
    # arithmetic over an inner column still propagates NULL
    txt = _plan_text(s, "SELECT * FROM oa LEFT JOIN ob ON x = y "
                        "WHERE z + 1 > 5")
    assert "inner" in txt, txt


def test_outer_join_not_simplified_when_null_safe():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE oc (x BIGINT)")
    s.execute("CREATE TABLE od (y BIGINT, z BIGINT)")
    s.execute("INSERT INTO oc VALUES (1),(2),(3)")
    s.execute("INSERT INTO od VALUES (1,10)")
    # outer-side-only filter keeps LEFT
    txt = _plan_text(s, "SELECT * FROM oc LEFT JOIN od ON x = y "
                        "WHERE x > 0")
    assert "left" in txt, txt
    rows = s.query("SELECT * FROM oc LEFT JOIN od ON x = y WHERE x > 0"
                   ).rows
    assert len(rows) == 3
    # COALESCE swallows NULL: must NOT convert
    txt = _plan_text(s, "SELECT * FROM oc LEFT JOIN od ON x = y "
                        "WHERE COALESCE(z, 99) > 5")
    assert "left" in txt, txt
    rows = s.query("SELECT * FROM oc LEFT JOIN od ON x = y "
                   "WHERE COALESCE(z, 99) > 5").rows
    assert len(rows) == 3          # null-extended rows pass via 99
