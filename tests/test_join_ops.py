"""Unit tests for the device join primitives (ops/join.py) against a
numpy oracle: LUT and sort formulations, unique and multi variants, and
the static-shape expansion kernel."""

import numpy as np
import pytest

from tidb_tpu.ops import join as J
from tidb_tpu.ops.jax_env import jnp


def np_matches(build, ok_b, probe, ok_p):
    """Oracle: per probe row, the list of matching build row indices."""
    out = []
    for p, okp in zip(probe, ok_p):
        if not okp:
            out.append([])
        else:
            out.append([i for i, (b, okb) in enumerate(zip(build, ok_b))
                        if okb and b == p])
    return out


def _case(seed, nb, np_, dom):
    rng = np.random.default_rng(seed)
    build = rng.integers(0, dom, nb).astype(np.int64)
    probe = rng.integers(-2, dom + 2, np_).astype(np.int64)
    ok_b = rng.random(nb) > 0.2
    ok_p = rng.random(np_) > 0.2
    return build, probe, ok_b, ok_p


def test_lut_probe_unique_matches_oracle():
    nb, npr, dom = 37, 64, 50
    rng = np.random.default_rng(0)
    build = rng.permutation(dom)[:nb].astype(np.int64)   # unique keys
    probe = rng.integers(-3, dom + 3, npr).astype(np.int64)
    ok_b = rng.random(nb) > 0.2
    ok_p = rng.random(npr) > 0.2
    pc = np.clip(probe, 0, dom - 1)
    ok_probe = ok_p & (probe >= 0) & (probe < dom)
    idx, matched, unique = J.lut_probe_unique(
        jnp.asarray(build), jnp.asarray(ok_b), dom,
        jnp.asarray(pc), jnp.asarray(ok_probe))
    assert bool(unique)
    oracle = np_matches(build, ok_b, probe, ok_probe)
    for i, m in enumerate(oracle):
        assert bool(matched[i]) == (len(m) == 1)
        if m:
            assert int(idx[i]) == m[0]


def test_lut_probe_unique_flags_duplicates():
    build = np.array([5, 7, 5, 9], dtype=np.int64)
    ok_b = np.ones(4, bool)
    _, _, unique = J.lut_probe_unique(
        jnp.asarray(build), jnp.asarray(ok_b), 16,
        jnp.zeros(4, np.int64), jnp.ones(4, bool))
    assert not bool(unique)
    # dead duplicate doesn't count
    ok_b2 = np.array([True, True, False, True])
    _, _, unique2 = J.lut_probe_unique(
        jnp.asarray(build), jnp.asarray(ok_b2), 16,
        jnp.zeros(4, np.int64), jnp.ones(4, bool))
    assert bool(unique2)


@pytest.mark.parametrize("form", ["lut", "sort"])
def test_probe_multi_matches_oracle(form):
    dom = 20
    build, probe, ok_b, ok_p = _case(3, 41, 57, dom)
    if form == "lut":
        pc = np.clip(probe, 0, dom - 1)
        okp = ok_p & (probe >= 0) & (probe < dom)
        start, count, order = J.lut_probe_multi(
            jnp.asarray(build), jnp.asarray(ok_b), dom,
            jnp.asarray(pc), jnp.asarray(okp))
        oracle = np_matches(build, ok_b, probe, okp)
    else:
        start, count, order = J.sorted_probe_multi(
            jnp.asarray(build), jnp.asarray(ok_b),
            jnp.asarray(probe), jnp.asarray(ok_p))
        oracle = np_matches(build, ok_b, probe, ok_p)
    start, count, order = map(np.asarray, (start, count, order))
    for i, m in enumerate(oracle):
        assert count[i] == len(m)
        got = sorted(order[start[i]:start[i] + count[i]].tolist())
        assert got == sorted(m)


@pytest.mark.parametrize("outer", [False, True])
def test_expand_matches_oracle(outer):
    dom = 12
    build, probe, ok_b, ok_p = _case(7, 23, 31, dom)
    live = np.ones(31, bool)
    live[-3:] = False
    start, count, order = J.sorted_probe_multi(
        jnp.asarray(build), jnp.asarray(ok_b),
        jnp.asarray(probe), jnp.asarray(ok_p & live))
    out_cap = 256
    p_idx, b_idx, matched, out_live, k, total = J.expand(
        start, count, order, out_cap, outer, jnp.asarray(live))
    if outer:
        k = np.asarray(k)
        assert (k[np.asarray(out_live) & ~np.asarray(matched)] == 0).all()
    p_idx, b_idx = np.asarray(p_idx), np.asarray(b_idx)
    matched, out_live = np.asarray(matched), np.asarray(out_live)
    oracle = np_matches(build, ok_b, probe, ok_p & live)
    pairs = set()
    extended = set()
    for j in range(out_cap):
        if not out_live[j]:
            continue
        if matched[j]:
            pairs.add((int(p_idx[j]), int(b_idx[j])))
        else:
            extended.add(int(p_idx[j]))
    want_pairs = {(i, b) for i, m in enumerate(oracle) if live[i]
                  for b in m}
    assert pairs == want_pairs
    want_total = sum(max(len(m), 1) if outer else len(m)
                     for i, m in enumerate(oracle) if live[i])
    assert int(total) == want_total
    if outer:
        assert extended == {i for i, m in enumerate(oracle)
                            if live[i] and not m}
    else:
        assert not extended


def test_expand_overflow_reports_total():
    build = np.zeros(8, np.int64)        # all same key: fanout 8 per probe
    probe = np.zeros(4, np.int64)
    ones8, ones4 = np.ones(8, bool), np.ones(4, bool)
    start, count, order = J.sorted_probe_multi(
        jnp.asarray(build), jnp.asarray(ones8),
        jnp.asarray(probe), jnp.asarray(ones4))
    _, _, _, out_live, _, total = J.expand(start, count, order, 16, False,
                                           jnp.asarray(ones4))
    assert int(total) == 32          # true need reported despite cap 16
    assert int(np.asarray(out_live).sum()) == 16


def test_pack_bounded_codes():
    keys = [(jnp.asarray(np.array([3, 5, 9, 4], np.int64)),
             jnp.asarray(np.array([True, True, True, False]))),
            (jnp.asarray(np.array([-1, 0, 2, 1], np.int64)),
             jnp.asarray(np.ones(4, bool)))]
    codes, ok = J.pack_bounded_codes(keys, [(3, 8), (-1, 2)])
    codes, ok = np.asarray(codes), np.asarray(ok)
    assert ok.tolist() == [True, True, False, False]   # 9 out of bounds; NULL
    # code = (v0-3) + (v1+1)*6
    assert codes[0] == 0 + 0 * 6
    assert codes[1] == 2 + 1 * 6
