"""Join-tree device fragments vs CPU volcano oracle (the Q3 shape).

Differential pattern of the reference's vec-vs-scalar twin tests
(expression/builtin_*_vec_test.go): every device tree result must equal the
CPU hash-join pipeline, including NULL keys and outer/semi/anti semantics
(executor/joiner.go:60 variants)."""

import numpy as np
import pytest

from tidb_tpu.executor import build, run_to_completion
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def session():
    eng = Engine()
    s = eng.new_session()
    # orders: unique PK (o_id); lineitem: FK with NULLs and misses
    s.execute("CREATE TABLE orders (o_id BIGINT, o_date DATE, "
              "o_prio BIGINT, o_seg VARCHAR(12))")
    s.execute("CREATE TABLE li (l_oid BIGINT, l_price DECIMAL(12,2), "
              "l_disc DECIMAL(12,2), l_ship DATE)")
    rng = np.random.default_rng(11)
    n_orders, n_li = 500, 5000
    rows = []
    for i in range(n_orders):
        seg = ["BUILDING", "AUTO", "STEEL"][int(rng.integers(0, 3))]
        rows.append(f"({i},'199{int(rng.integers(5, 9))}-0{int(rng.integers(1, 10))}-15',"
                    f"{int(rng.integers(0, 5))},'{seg}')")
    s.execute("INSERT INTO orders VALUES " + ",".join(rows))
    rows = []
    for _ in range(n_li):
        # keys beyond n_orders miss; a few NULL keys
        k = int(rng.integers(0, n_orders + 60))
        key = "NULL" if rng.random() < 0.02 else str(k)
        rows.append(f"({key},{round(float(rng.uniform(1, 900)), 2)},"
                    f"{round(float(rng.uniform(0, 0.1)), 2)},"
                    f"'199{int(rng.integers(5, 9))}-0{int(rng.integers(1, 10))}-10')")
    s.execute("INSERT INTO li VALUES " + ",".join(rows))
    return s


def run_device(s, sql, expect_fallback=None):
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags, f"no fragment extracted for: {sql}"
        if expect_fallback is None:
            for f in frags:
                assert f.used_device, \
                    f"fell back ({f.fallback_reason}) for: {sql}"
        else:
            assert any(not f.used_device and
                       expect_fallback in (f.fallback_reason or "")
                       for f in frags), \
                f"expected fallback {expect_fallback!r}, got " \
                f"{[f.fallback_reason for f in frags]}"
        return [r for ch in chunks for r in ch.rows()]
    finally:
        s.vars["tidb_tpu_engine"] = "off"


def assert_same(rows1, rows2, ordered=False):
    assert len(rows1) == len(rows2), (len(rows1), len(rows2))
    if not ordered:
        rows1 = sorted(rows1, key=str)
        rows2 = sorted(rows2, key=str)
    for r1, r2 in zip(rows1, rows2):
        for v1, v2 in zip(r1, r2):
            if isinstance(v1, float) and v2 is not None:
                assert abs(v1 - v2) <= 1e-5 * max(1.0, abs(v2)), (r1, r2)
            else:
                assert v1 == v2, (r1, r2)


TREE_QUERIES = [
    # Q3 shape: join + group + aggregate
    "SELECT o_prio, COUNT(*), SUM(l_price * (1 - l_disc)) FROM li "
    "JOIN orders ON l_oid = o_id GROUP BY o_prio",
    # filters on both sides
    "SELECT o_prio, SUM(l_price) FROM li JOIN orders ON l_oid = o_id "
    "WHERE o_seg = 'BUILDING' AND l_ship < '1998-01-01' GROUP BY o_prio",
    # ungrouped agg over join
    "SELECT COUNT(*), SUM(l_price), MIN(l_disc) FROM li "
    "JOIN orders ON l_oid = o_id WHERE o_prio < 3",
    # string group key from the build side (dictionary flows through join)
    "SELECT o_seg, COUNT(*) FROM li JOIN orders ON l_oid = o_id "
    "GROUP BY o_seg",
]


@pytest.mark.parametrize("sql", TREE_QUERIES)
def test_join_tree_matches_cpu(session, sql):
    dev = run_device(session, sql)
    cpu = session.query(sql).rows
    assert_same(dev, cpu)


def test_left_join_tree(session):
    sql = ("SELECT o_prio, COUNT(*), COUNT(o_id), SUM(l_price) FROM li "
           "LEFT JOIN orders ON l_oid = o_id GROUP BY o_prio")
    assert_same(run_device(session, sql), session.query(sql).rows)


def test_semi_anti_join_tree(session):
    for kw in ("IN", "NOT IN"):
        sql = (f"SELECT COUNT(*), SUM(l_price) FROM li WHERE l_oid "
               f"{kw} (SELECT o_id FROM orders WHERE o_prio = 1)")
        assert_same(run_device(session, sql), session.query(sql).rows)


def test_topn_over_join_tree(session):
    sql = ("SELECT l_oid, l_price, o_prio FROM li JOIN orders "
           "ON l_oid = o_id ORDER BY l_price DESC, l_oid LIMIT 7")
    assert_same(run_device(session, sql), session.query(sql).rows,
                ordered=True)


def test_three_table_tree(session):
    # self-join chain: li ⋈ orders ⋈ orders-copy (both unique builds)
    session.execute("CREATE TABLE prio_names (p_id BIGINT, p_name VARCHAR(8))")
    session.execute("INSERT INTO prio_names VALUES (0,'p0'),(1,'p1'),"
                    "(2,'p2'),(3,'p3'),(4,'p4')")
    sql = ("SELECT p_name, COUNT(*) FROM li JOIN orders ON l_oid = o_id "
           "JOIN prio_names ON o_prio = p_id GROUP BY p_name")
    assert_same(run_device(session, sql), session.query(sql).rows)


def test_non_unique_build_runs_on_device(session):
    # join key o_prio is NOT unique in orders (~100 rows per key): the
    # expansion path materializes every match on device, no CPU fallback
    sql = ("SELECT COUNT(*), SUM(l_price) FROM li JOIN orders "
           "ON l_oid = o_prio")
    dev = run_device(session, sql)
    assert_same(dev, session.query(sql).rows)


def test_non_unique_left_join_device(session):
    # duplicate build keys + probe rows with no match (null-extended) +
    # NULL probe keys, all through the expansion path
    sql = ("SELECT COUNT(*), COUNT(o_id), SUM(o_date) FROM li "
           "LEFT JOIN orders ON l_oid = o_prio")
    dev = run_device(session, sql)
    assert_same(dev, session.query(sql).rows)


def test_string_key_join_device(session):
    # VARCHAR equi key: probe codes remap into the build dictionary space
    session.execute("CREATE TABLE segs (s_name VARCHAR(12), s_rank BIGINT)")
    session.execute("INSERT INTO segs VALUES ('BUILDING',1),('AUTO',2),"
                    "('STEEL',3),('GHOST',4)")
    sql = ("SELECT s_rank, COUNT(*) FROM orders JOIN segs "
           "ON o_seg = s_name GROUP BY s_rank")
    dev = run_device(session, sql)
    assert_same(dev, session.query(sql).rows)


def test_repeat_query_hits_compile_cache(session):
    # second run re-plans (fresh node objects) but reuses the compiled
    # program — prep alignment must be structural, not id-based
    sql = ("SELECT o_seg, COUNT(*), SUM(l_price) FROM li "
           "JOIN orders ON l_oid = o_id WHERE l_ship < '1998-01-01' "
           "GROUP BY o_seg")
    first = run_device(session, sql)
    second = run_device(session, sql)
    assert_same(first, session.query(sql).rows)
    assert_same(second, session.query(sql).rows)


def test_explain_analyze_tree_uses_device(session):
    sql = ("SELECT o_seg, COUNT(*) FROM li JOIN orders ON l_oid = o_id "
           "GROUP BY o_seg")
    run_device(session, sql)
    session.vars["tidb_tpu_engine"] = "on"
    session.vars["tidb_tpu_row_threshold"] = 1
    try:
        rows = session.query("EXPLAIN ANALYZE " + sql).rows
        frag_rows = [r for r in rows if "TpuFragment" in str(r[0])]
        assert frag_rows and "device:yes" in frag_rows[0][2], frag_rows
    finally:
        session.vars["tidb_tpu_engine"] = "off"


def test_multi_slab_join_device(session):
    # slab cap 1024 → li (5000 rows) splits into 5 slabs that concatenate
    # inside the program (the SF=10 shape scaled down)
    session.vars["tidb_tpu_max_slab_rows"] = 1000
    try:
        sql = ("SELECT o_prio, COUNT(*), SUM(l_price * (1 - l_disc)) "
               "FROM li JOIN orders ON l_oid = o_id GROUP BY o_prio")
        assert_same(run_device(session, sql), session.query(sql).rows)
        # non-unique build + multi-slab probe
        sql2 = "SELECT COUNT(*), SUM(l_price) FROM li JOIN orders ON l_oid = o_prio"
        assert_same(run_device(session, sql2), session.query(sql2).rows)
    finally:
        session.vars.pop("tidb_tpu_max_slab_rows", None)


def test_multi_slab_distinct_agg_device(session):
    session.vars["tidb_tpu_max_slab_rows"] = 1000
    try:
        sql = ("SELECT COUNT(DISTINCT l_oid), COUNT(*) FROM li "
               "WHERE l_ship < '1999-01-01'")
        assert_same(run_device(session, sql), session.query(sql).rows)
        sql2 = ("SELECT l_ship, COUNT(DISTINCT l_oid) FROM li "
                "GROUP BY l_ship")
        assert_same(run_device(session, sql2), session.query(sql2).rows)
    finally:
        session.vars.pop("tidb_tpu_max_slab_rows", None)


def test_multi_slab_distinct_mixed_aggs(session):
    # cross-slab pair-set merge (_distinct_pairs + _merge_distinct_states):
    # SUM/AVG over DISTINCT values, several distinct aggs with different
    # args alongside plain aggs, and a dictionary-coded (string) arg
    # (slab cap 300 splits orders too, so the string query is multi-slab)
    session.vars["tidb_tpu_max_slab_rows"] = 300
    try:
        for sql in [
            "SELECT SUM(DISTINCT l_oid), AVG(DISTINCT l_oid), COUNT(*) "
            "FROM li",
            "SELECT o_prio, COUNT(DISTINCT l_oid), SUM(DISTINCT l_oid), "
            "SUM(l_price) FROM li JOIN orders ON l_oid = o_id "
            "GROUP BY o_prio",
            "SELECT o_prio, COUNT(DISTINCT o_seg), COUNT(DISTINCT o_id) "
            "FROM orders GROUP BY o_prio",
        ]:
            assert_same(run_device(session, sql), session.query(sql).rows)
    finally:
        session.vars.pop("tidb_tpu_max_slab_rows", None)


def test_multi_slab_window_device(session):
    session.vars["tidb_tpu_max_slab_rows"] = 1000
    try:
        sql = ("SELECT l_oid, l_price, "
               "RANK() OVER (PARTITION BY l_ship ORDER BY l_price DESC), "
               "SUM(l_price) OVER (PARTITION BY l_ship) FROM li")
        assert_same(run_device(session, sql), session.query(sql).rows)
    finally:
        session.vars.pop("tidb_tpu_max_slab_rows", None)


def test_group_cap_retry_over_join(session):
    # group by the join key itself: ~500 groups, cap 64 forces retry
    session.vars["tidb_tpu_group_cap"] = 64
    try:
        sql = ("SELECT l_oid, COUNT(*), SUM(l_price) FROM li "
               "JOIN orders ON l_oid = o_id GROUP BY l_oid")
        assert_same(run_device(session, sql), session.query(sql).rows)
    finally:
        session.vars.pop("tidb_tpu_group_cap", None)
