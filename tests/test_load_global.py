"""LOAD DATA INFILE (executor/load_data.go analog) and SET GLOBAL
persistence (sessionctx/variable global scope)."""

import pytest

from tidb_tpu.session import Engine


def test_load_data_infile(tmp_path):
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ld (a BIGINT, b VARCHAR(8), c DOUBLE)")
    p = tmp_path / "in.csv"
    p.write_text("# header\n1,one,1.5\n2,two,\\N\n3,th'ree,3.5\n")
    rs = s.execute(f"LOAD DATA LOCAL INFILE '{p}' INTO TABLE ld "
                   f"FIELDS TERMINATED BY ',' IGNORE 1 LINES")
    assert rs[0].affected_rows == 3
    assert s.query("SELECT * FROM ld ORDER BY a").rows == [
        (1, "one", 1.5), (2, "two", None), (3, "th'ree", 3.5)]


def test_load_data_requires_insert_priv(tmp_path):
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ld2 (a BIGINT)")
    s.execute("CREATE USER u IDENTIFIED BY 'x'")
    p = tmp_path / "in2.csv"
    p.write_text("1\n")
    s2 = eng.new_session()
    s2.user = "u"
    with pytest.raises(Exception, match="denied"):
        s2.execute(f"LOAD DATA INFILE '{p}' INTO TABLE ld2")


def test_set_global_inherited_and_gated():
    eng = Engine()
    s = eng.new_session()
    s.execute("SET GLOBAL tidb_tpu_row_threshold = 777")
    assert eng.new_session().vars["tidb_tpu_row_threshold"] == 777
    # session scope does not leak
    s.execute("SET max_chunk_size = 42")
    assert eng.new_session().vars["max_chunk_size"] != 42
    # non-superusers cannot SET GLOBAL
    s.execute("CREATE USER v IDENTIFIED BY 'x'")
    s2 = eng.new_session()
    s2.user = "v"
    with pytest.raises(Exception, match="SET GLOBAL"):
        s2.execute("SET GLOBAL long_query_time = 1")
