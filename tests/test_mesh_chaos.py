"""Mesh-aware chaos: adversarial key distributions through the
distributed exchange/factorize/agg-join path, the capacity-escalation
ladder (exact-need resize, typed CapacityError on exhaustion), and
shard-fault recovery — all on the forced multi-device CPU mesh
(conftest.py pins XLA_FLAGS=--xla_force_host_platform_device_count=8).

The float payloads are integer-valued on purpose: double-precision sums
of integers are exact under any reduction order, so the distributed
result must equal the numpy oracle BYTE-exactly — a dropped row or a
conflated group cannot hide inside float tolerance."""

import numpy as np
import pytest

from tidb_tpu.errors import CapacityError, ShardFailure
from tidb_tpu.parallel import make_mesh
from tidb_tpu.parallel import collective as C
from tidb_tpu.parallel.dist_query import (reference_agg_join, run_agg_join)
from tidb_tpu.util import failpoint


@pytest.fixture(scope="module")
def mesh4(eight_devices):
    return make_mesh(4)


def _oracle(pk, px, pq, bk, bg, bw, limit):
    sums, counts = reference_agg_join(pk, px, pq, bk, bg, bw, limit)
    return {g: (float(sums[g]), int(counts[g])) for g in sums}


def _build(b, n_groups=5):
    bk = np.arange(b, dtype=np.int64)
    bg = (bk % n_groups).astype(np.int64)
    bw = np.ones(b, dtype=np.float64)        # integer-valued: exact sums
    return bk, bg, bw


# ---- adversarial distributions through the escalation ladder ---------------

def test_all_rows_one_shard_exact_need_one_recompile(mesh4):
    # EVERY probe row carries the same key: the hash exchange funnels the
    # whole table into one destination bucket. The step reports the exact
    # need, so recovery is ONE exact-need recompile — and the result is
    # byte-equal to the oracle (overflow is never silent row loss).
    N, B = 512, 64
    rng = np.random.default_rng(7)
    pk = np.full(N, 13, dtype=np.int64)
    px = rng.integers(0, 100, N).astype(np.float64)
    pq = np.zeros(N)                         # filter keeps everything
    bk, bg, bw = _build(B)
    out, stats = run_agg_join(mesh4, pk, px, pq, bk, bg, bw,
                              bucket_cap=16, group_cap=64,
                              filter_limit=0.5)
    assert out == _oracle(pk, px, pq, bk, bg, bw, 0.5)
    assert stats.by_kind.get("exchange:exact") == 1
    assert stats.recompiles == 1             # exactly one re-execution


def test_dense_group_explosion_exact_need(mesh4):
    # distinct group count blows past group_cap: factorize still reports
    # the TRUE count, so the ladder resizes the group slots to exact need
    # in one recompile, not a doubling ladder
    N, B = 1024, 256
    rng = np.random.default_rng(11)
    pk = rng.integers(0, B, N).astype(np.int64)
    px = rng.integers(0, 50, N).astype(np.float64)
    pq = rng.uniform(0, 1, N)
    bk = np.arange(B, dtype=np.int64)
    bg = bk.copy()                           # every build row its own group
    bw = np.ones(B, dtype=np.float64)
    out, stats = run_agg_join(mesh4, pk, px, pq, bk, bg, bw,
                              bucket_cap=1024, group_cap=16,
                              filter_limit=0.7)
    assert out == _oracle(pk, px, pq, bk, bg, bw, 0.7)
    assert stats.by_kind.get("group:exact", 0) >= 1
    assert stats.recompiles == 1


def test_null_heavy_keys_exact(mesh4):
    # 70% of probe rows are NULL-keyed (dead in the live mask): they must
    # neither travel through the exchange nor leak into any group
    N, B = 1024, 128
    rng = np.random.default_rng(23)
    pk = rng.integers(0, B, N).astype(np.int64)
    px = rng.integers(0, 30, N).astype(np.float64)
    pq = rng.uniform(0, 1, N)
    live = rng.random(N) >= 0.7
    bk, bg, bw = _build(B)
    out, stats = run_agg_join(mesh4, pk, px, pq, bk, bg, bw,
                              bucket_cap=512, group_cap=64,
                              filter_limit=0.6, p_live=live)
    assert out == _oracle(pk[live], px[live], pq[live], bk, bg, bw, 0.6)
    assert stats.total == 0                  # capacities held: no retry


def test_skew_and_group_explosion_combined(mesh4):
    # both rungs in one statement: a skewed exchange AND a group blowout —
    # each overflowed structure costs exactly one exact-need recompile
    N, B = 768, 192
    rng = np.random.default_rng(31)
    pk = np.where(rng.random(N) < 0.9, 5, rng.integers(0, B, N)) \
        .astype(np.int64)
    px = rng.integers(0, 20, N).astype(np.float64)
    pq = np.zeros(N)
    bk = np.arange(B, dtype=np.int64)
    bg = bk.copy()
    bw = np.ones(B, dtype=np.float64)
    out, stats = run_agg_join(mesh4, pk, px, pq, bk, bg, bw,
                              bucket_cap=32, group_cap=16,
                              filter_limit=0.5)
    assert out == _oracle(pk, px, pq, bk, bg, bw, 0.5)
    assert stats.by_kind.get("exchange:exact") == 1
    assert stats.by_kind.get("group:exact") == 1
    assert stats.recompiles <= 2


# ---- typed errors: the ladder never returns truncated rows ----------------

def test_ladder_exhaustion_is_typed_capacity_error(mesh4):
    # the cap limit is already reached and the skew still overflows: the
    # driver must raise CapacityError, NOT return a truncated result
    N, B = 512, 64
    rng = np.random.default_rng(3)
    pk = np.full(N, 9, dtype=np.int64)
    px = rng.integers(0, 10, N).astype(np.float64)
    pq = np.zeros(N)
    bk, bg, bw = _build(B)
    with pytest.raises(CapacityError) as ei:
        run_agg_join(mesh4, pk, px, pq, bk, bg, bw,
                     bucket_cap=16, group_cap=64, filter_limit=0.5,
                     max_bucket_cap=16)
    assert ei.value.code == 1104


def test_require_capacity_guard():
    # exchange callers without a resize ladder must assert, not drop rows
    C.require_capacity(64, 64)               # need == cap: fine
    with pytest.raises(CapacityError):
        C.require_capacity(65, 64, what="test-exchange")


def test_factorize_reports_true_count_past_cap():
    # the exact-need ladder only works because factorize counts BEFORE
    # clamping: n_groups is the true distinct count even when cap is tiny
    from tidb_tpu.ops import factorize as F
    from tidb_tpu.ops.jax_env import jnp
    keys = jnp.asarray(np.arange(100, dtype=np.int64))
    live = jnp.ones(100, dtype=bool)
    _gids, n_groups, _rep = F.factorize([(keys, None)], live, 16)
    assert int(n_groups) == 100


# ---- shard faults at the SQL level ----------------------------------------

@pytest.fixture(scope="module")
def dist_session(eight_devices):
    from tidb_tpu.session import Engine
    eng = Engine()
    s = eng.new_session()
    s.execute("create table mc (k bigint, g bigint, v bigint)")
    rows = ", ".join(f"({i % 97}, {i % 5}, {i % 101})" for i in range(4000))
    s.execute(f"insert into mc values {rows}")
    s.execute("analyze table mc")
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_dist_devices": 4})
    yield s
    eng.close()


DIST_SQL = "select g, count(*), sum(v) from mc group by g order by g"


def test_shard_fault_heals_with_one_retry(dist_session):
    s = dist_session
    oracle = [(i, 800, sum(j % 101 for j in range(i, 4000, 5)))
              for i in range(5)]
    with failpoint.enabled("shard-step",
                           raise_=ShardFailure("chaos: shard 2 down"),
                           after_hits=2, times=1):
        rows = s.query(DIST_SQL).rows
    assert [tuple(int(x) for x in r) for r in rows] == oracle
    # the recovery is visible: one whole-step retry, charged to the ladder
    assert s.last_guard.escalation.shard_retries == 1


def test_persistent_shard_fault_is_one_typed_error(dist_session):
    s = dist_session
    with failpoint.enabled("shard-step",
                           raise_=ShardFailure("chaos: shard down")):
        with pytest.raises(ShardFailure) as ei:
            s.query(DIST_SQL)
    assert ei.value.code == 1105
    assert "twice" in str(ei.value)
    # the store and the session survived: same statement now answers
    rows = s.query(DIST_SQL).rows
    assert [int(r[1]) for r in rows] == [800] * 5
    assert s.query("select count(*) from mc").scalar() == 4000
