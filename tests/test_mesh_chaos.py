"""Mesh-aware chaos: adversarial key distributions through the
distributed exchange/factorize/agg-join path, the capacity-escalation
ladder (exact-need resize, typed CapacityError on exhaustion), and
shard-fault recovery — all on the forced multi-device CPU mesh
(conftest.py pins XLA_FLAGS=--xla_force_host_platform_device_count=8).

The float payloads are integer-valued on purpose: double-precision sums
of integers are exact under any reduction order, so the distributed
result must equal the numpy oracle BYTE-exactly — a dropped row or a
conflated group cannot hide inside float tolerance."""

import numpy as np
import pytest

from tidb_tpu.errors import CapacityError, ShardFailure
from tidb_tpu.parallel import make_mesh
from tidb_tpu.parallel import collective as C
from tidb_tpu.parallel.dist_query import (reference_agg_join, run_agg_join)
from tidb_tpu.util import failpoint


@pytest.fixture(scope="module")
def mesh4(eight_devices):
    return make_mesh(4)


def _oracle(pk, px, pq, bk, bg, bw, limit):
    sums, counts = reference_agg_join(pk, px, pq, bk, bg, bw, limit)
    return {g: (float(sums[g]), int(counts[g])) for g in sums}


def _build(b, n_groups=5):
    bk = np.arange(b, dtype=np.int64)
    bg = (bk % n_groups).astype(np.int64)
    bw = np.ones(b, dtype=np.float64)        # integer-valued: exact sums
    return bk, bg, bw


# ---- adversarial distributions through the escalation ladder ---------------

def test_all_rows_one_shard_exact_need_one_recompile(mesh4):
    # EVERY probe row carries the same key: the hash exchange funnels the
    # whole table into one destination bucket. The step reports the exact
    # need, so recovery is ONE exact-need recompile — and the result is
    # byte-equal to the oracle (overflow is never silent row loss).
    N, B = 512, 64
    rng = np.random.default_rng(7)
    pk = np.full(N, 13, dtype=np.int64)
    px = rng.integers(0, 100, N).astype(np.float64)
    pq = np.zeros(N)                         # filter keeps everything
    bk, bg, bw = _build(B)
    out, stats = run_agg_join(mesh4, pk, px, pq, bk, bg, bw,
                              bucket_cap=16, group_cap=64,
                              filter_limit=0.5)
    assert out == _oracle(pk, px, pq, bk, bg, bw, 0.5)
    assert stats.by_kind.get("exchange:exact") == 1
    assert stats.recompiles == 1             # exactly one re-execution


def test_dense_group_explosion_exact_need(mesh4):
    # distinct group count blows past group_cap: factorize still reports
    # the TRUE count, so the ladder resizes the group slots to exact need
    # in one recompile, not a doubling ladder
    N, B = 1024, 256
    rng = np.random.default_rng(11)
    pk = rng.integers(0, B, N).astype(np.int64)
    px = rng.integers(0, 50, N).astype(np.float64)
    pq = rng.uniform(0, 1, N)
    bk = np.arange(B, dtype=np.int64)
    bg = bk.copy()                           # every build row its own group
    bw = np.ones(B, dtype=np.float64)
    out, stats = run_agg_join(mesh4, pk, px, pq, bk, bg, bw,
                              bucket_cap=1024, group_cap=16,
                              filter_limit=0.7)
    assert out == _oracle(pk, px, pq, bk, bg, bw, 0.7)
    assert stats.by_kind.get("group:exact", 0) >= 1
    assert stats.recompiles == 1


def test_null_heavy_keys_exact(mesh4):
    # 70% of probe rows are NULL-keyed (dead in the live mask): they must
    # neither travel through the exchange nor leak into any group
    N, B = 1024, 128
    rng = np.random.default_rng(23)
    pk = rng.integers(0, B, N).astype(np.int64)
    px = rng.integers(0, 30, N).astype(np.float64)
    pq = rng.uniform(0, 1, N)
    live = rng.random(N) >= 0.7
    bk, bg, bw = _build(B)
    out, stats = run_agg_join(mesh4, pk, px, pq, bk, bg, bw,
                              bucket_cap=512, group_cap=64,
                              filter_limit=0.6, p_live=live)
    assert out == _oracle(pk[live], px[live], pq[live], bk, bg, bw, 0.6)
    assert stats.total == 0                  # capacities held: no retry


def test_skew_and_group_explosion_combined(mesh4):
    # both rungs in one statement: a skewed exchange AND a group blowout —
    # each overflowed structure costs exactly one exact-need recompile
    N, B = 768, 192
    rng = np.random.default_rng(31)
    pk = np.where(rng.random(N) < 0.9, 5, rng.integers(0, B, N)) \
        .astype(np.int64)
    px = rng.integers(0, 20, N).astype(np.float64)
    pq = np.zeros(N)
    bk = np.arange(B, dtype=np.int64)
    bg = bk.copy()
    bw = np.ones(B, dtype=np.float64)
    out, stats = run_agg_join(mesh4, pk, px, pq, bk, bg, bw,
                              bucket_cap=32, group_cap=16,
                              filter_limit=0.5)
    assert out == _oracle(pk, px, pq, bk, bg, bw, 0.5)
    assert stats.by_kind.get("exchange:exact") == 1
    assert stats.by_kind.get("group:exact") == 1
    assert stats.recompiles <= 2


# ---- typed errors: the ladder never returns truncated rows ----------------

def test_ladder_exhaustion_is_typed_capacity_error(mesh4):
    # the cap limit is already reached and the skew still overflows: the
    # driver must raise CapacityError, NOT return a truncated result
    N, B = 512, 64
    rng = np.random.default_rng(3)
    pk = np.full(N, 9, dtype=np.int64)
    px = rng.integers(0, 10, N).astype(np.float64)
    pq = np.zeros(N)
    bk, bg, bw = _build(B)
    with pytest.raises(CapacityError) as ei:
        run_agg_join(mesh4, pk, px, pq, bk, bg, bw,
                     bucket_cap=16, group_cap=64, filter_limit=0.5,
                     max_bucket_cap=16)
    assert ei.value.code == 1104


def test_require_capacity_guard():
    # exchange callers without a resize ladder must assert, not drop rows
    C.require_capacity(64, 64)               # need == cap: fine
    with pytest.raises(CapacityError):
        C.require_capacity(65, 64, what="test-exchange")


def test_factorize_reports_true_count_past_cap():
    # the exact-need ladder only works because factorize counts BEFORE
    # clamping: n_groups is the true distinct count even when cap is tiny
    from tidb_tpu.ops import factorize as F
    from tidb_tpu.ops.jax_env import jnp
    keys = jnp.asarray(np.arange(100, dtype=np.int64))
    live = jnp.ones(100, dtype=bool)
    _gids, n_groups, _rep = F.factorize([(keys, None)], live, 16)
    assert int(n_groups) == 100


# ---- shard faults at the SQL level ----------------------------------------

@pytest.fixture(scope="module")
def dist_session(eight_devices):
    from tidb_tpu.session import Engine
    eng = Engine()
    s = eng.new_session()
    s.execute("create table mc (k bigint, g bigint, v bigint)")
    rows = ", ".join(f"({i % 97}, {i % 5}, {i % 101})" for i in range(4000))
    s.execute(f"insert into mc values {rows}")
    s.execute("analyze table mc")
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_dist_devices": 4})
    yield s
    eng.close()


DIST_SQL = "select g, count(*), sum(v) from mc group by g order by g"


ORACLE = [(i, 800, sum(j % 101 for j in range(i, 4000, 5)))
          for i in range(5)]


def _rows(rs):
    return [tuple(int(x) for x in r) for r in rs.rows]


def test_shard_fault_heals_with_one_retry(dist_session):
    # transient fault on ONE rank's dispatch: the staged path re-executes
    # only that rank (same device), reusing the other ranks' checkpoints
    s = dist_session
    with failpoint.enabled("shard-step",
                           raise_=ShardFailure("chaos: shard 2 down"),
                           after_hits=2, times=1):
        rows = _rows(s.query(DIST_SQL))
    assert rows == ORACLE                     # byte-exact, not approximate
    esc = s.last_guard.escalation
    assert esc.shard_retries == 1             # one same-device retry
    assert esc.shards_rerun == 1              # exactly the failed rank
    assert esc.shards_reused == 3             # N-1 checkpoints reused
    assert esc.degraded_mesh == 0             # never left the full mesh
    assert "shard:partial-reuse" in esc.summary()


def test_checkpoint_write_fault_heals(dist_session):
    # the device→host checkpoint itself is a fault domain: losing one
    # rank's checkpoint re-runs only that rank
    s = dist_session
    with failpoint.enabled("shard-checkpoint-write",
                           raise_=ShardFailure("chaos: checkpoint lost"),
                           times=1):
        rows = _rows(s.query(DIST_SQL))
    assert rows == ORACLE
    esc = s.last_guard.escalation
    assert esc.shards_rerun == 1 and esc.shards_reused == 3


def test_persistent_device_fault_degrades_mesh(dist_session):
    # one rank's device fails dispatch AND the same-device retry: the
    # rank's work re-dispatches onto a surviving device (degraded mesh),
    # the query completes byte-exactly, and a retryable warning is left
    # for SHOW WARNINGS
    s = dist_session
    with failpoint.enabled("shard-step",
                           raise_=ShardFailure("chaos: device 2 bad"),
                           after_hits=2, times=2):
        rows = _rows(s.query(DIST_SQL))
    assert rows == ORACLE
    esc = s.last_guard.escalation
    assert esc.degraded_mesh == 1
    assert esc.shards_rerun == 1 and esc.shards_reused == 3
    assert "shard:redispatch" in esc.summary()
    warns = s.query("SHOW WARNINGS").rows
    assert len(warns) == 1, warns
    level, code, msg = warns[0]
    assert level == "Warning" and int(code) == ShardFailure.code
    assert "degraded mesh" in msg and "re-dispatched" in msg
    # the diagnostics area resets on the next ordinary statement
    assert s.query("select 1 + 1").scalar() == 2
    assert s.query("SHOW WARNINGS").rows == []


def test_fully_dead_shard_is_one_typed_error(dist_session):
    # the rank fails on its own device AND on re-dispatch to a surviving
    # device: the ladder is exhausted — ONE typed retryable ShardFailure,
    # never a truncated result — and the session/store stay usable
    s = dist_session
    with failpoint.enabled("shard-step",
                           raise_=ShardFailure("chaos: device down"),
                           after_hits=2):
        with failpoint.enabled("shard-redispatch",
                               raise_=ShardFailure("chaos: spare down")):
            with pytest.raises(ShardFailure) as ei:
                s.query(DIST_SQL)
    assert ei.value.code == 1105
    assert ei.value.retryable
    assert "re-dispatch" in str(ei.value)
    # the store and the session survived: same statement now answers
    assert _rows(s.query(DIST_SQL)) == ORACLE
    assert s.query("select count(*) from mc").scalar() == 4000


def test_staged_matches_monolithic_bytes(dist_session):
    # same SQL through both distributed paths: the staged (checkpointed)
    # aggregation must be byte-identical to the monolithic shard_map run
    s = dist_session
    staged = _rows(s.query(DIST_SQL))
    s.vars["tidb_tpu_dist_staged"] = "off"
    try:
        mono = _rows(s.query(DIST_SQL))
    finally:
        s.vars["tidb_tpu_dist_staged"] = "on"
    assert staged == mono == ORACLE


def test_skewed_keys_survive_shard_fault_byte_exact(dist_session):
    # adversarial skew: ~90% of rows share one key, so one rank owns a
    # giant group while others are sparse — a mid-mesh fault must still
    # reproduce the oracle byte-exactly
    s = dist_session
    s.execute("create table ms (k bigint, v bigint)")
    vals = [(7 if i % 10 else 700 + i, i % 13) for i in range(2000)]
    s.execute("insert into ms values " +
              ", ".join(f"({k}, {v})" for k, v in vals))
    s.execute("analyze table ms")
    oracle = {}
    for k, v in vals:
        c, t = oracle.get(k, (0, 0))
        oracle[k] = (c + 1, t + v)
    expect = [(k, c, t) for k, (c, t) in sorted(oracle.items())]
    sql = "select k, count(*), sum(v) from ms group by k order by k"
    with failpoint.enabled("shard-step",
                           raise_=ShardFailure("chaos: shard down"),
                           after_hits=1, times=2):
        rows = _rows(s.query(sql))
    assert rows == expect
    esc = s.last_guard.escalation
    assert esc.shards_rerun >= 1
