"""Prepared statements (binary protocol) + auth/privileges over the wire.

Covers the reference's server/conn_stmt.go surface (COM_STMT_PREPARE /
EXECUTE / CLOSE, binary parameter decoding, binary resultset rows) and
the privilege path (mysql_native_password challenge, CREATE USER / GRANT
enforcement) with a hand-rolled client, since no stock driver ships in
the image."""

import hashlib
import socket
import struct

import pytest

from tidb_tpu.server import (Server, count_placeholders,
                             substitute_placeholders)
from tidb_tpu.session import Engine


def scramble(password: str, salt: bytes) -> bytes:
    if not password:
        return b""
    sha_pw = hashlib.sha1(password.encode()).digest()
    stage2 = hashlib.sha1(sha_pw).digest()
    mix = hashlib.sha1(salt + stage2).digest()
    return bytes(a ^ b for a, b in zip(sha_pw, mix))


class StmtClient:
    def __init__(self, port, user="root", password=""):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=5)
        self.seq = 0
        self._handshake(user, password)

    def _recv(self, n):
        buf = b""
        while len(buf) < n:
            part = self.sock.recv(n - len(buf))
            assert part, "server closed"
            buf += part
        return buf

    def read_packet(self):
        h = self._recv(4)
        ln = h[0] | (h[1] << 8) | (h[2] << 16)
        self.seq = (h[3] + 1) & 0xFF
        return self._recv(ln)

    def write_packet(self, payload):
        self.sock.sendall(struct.pack("<I", len(payload))[:3]
                          + bytes([self.seq]) + payload)
        self.seq = (self.seq + 1) & 0xFF

    def _handshake(self, user, password):
        g = self.read_packet()
        assert g[0] == 10
        i = g.index(b"\x00", 1) + 1        # server version
        i += 4                             # conn id
        salt = g[i:i + 8]
        i += 9                             # salt1 + filler
        i += 2 + 1 + 2 + 2 + 1 + 10        # caps, charset, status, caps2,
        #                                    auth len, reserved
        salt += g[i:i + 12]
        token = scramble(password, salt)
        caps = 0x0200 | 0x8000 | 0x1
        resp = (struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
                + bytes([0xFF]) + b"\x00" * 23
                + user.encode() + b"\x00"
                + bytes([len(token)]) + token)
        self.write_packet(resp)
        ok = self.read_packet()
        if ok[0] != 0x00:
            code = struct.unpack("<H", ok[1:3])[0]
            raise PermissionError(f"auth failed {code}")

    @staticmethod
    def _lenenc(data, i):
        c = data[i]
        if c < 251:
            return c, i + 1
        if c == 0xFC:
            return data[i + 1] | (data[i + 2] << 8), i + 3
        if c == 0xFD:
            return int.from_bytes(data[i + 1:i + 4], "little"), i + 4
        return int.from_bytes(data[i + 1:i + 9], "little"), i + 9

    def query(self, sql):
        self.seq = 0
        self.write_packet(b"\x03" + sql.encode())
        first = self.read_packet()
        if first[0] == 0xFF:
            code = struct.unpack("<H", first[1:3])[0]
            raise RuntimeError(f"ERR {code}: "
                               f"{first[9:].decode(errors='replace')}")
        if first[0] == 0x00:
            return {"ok": True}
        ncols, _ = self._lenenc(first, 0)
        for _ in range(ncols):
            self.read_packet()
        assert self.read_packet()[0] == 0xFE
        rows = []
        while True:
            pkt = self.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            i, row = 0, []
            while i < len(pkt):
                if pkt[i] == 0xFB:
                    row.append(None)
                    i += 1
                else:
                    ln, i = self._lenenc(pkt, i)
                    row.append(pkt[i:i + ln].decode())
                    i += ln
            rows.append(tuple(row))
        return {"rows": rows}

    # -- prepared statements -------------------------------------------------
    def prepare(self, sql):
        self.seq = 0
        self.write_packet(b"\x16" + sql.encode())
        resp = self.read_packet()
        assert resp[0] == 0x00, resp
        stmt_id, n_cols, n_params = struct.unpack("<IHH", resp[1:9])
        for _ in range(n_params):
            self.read_packet()
        if n_params:
            assert self.read_packet()[0] == 0xFE
        for _ in range(n_cols):
            self.read_packet()
        if n_cols:
            assert self.read_packet()[0] == 0xFE
        return stmt_id, n_params

    def execute(self, stmt_id, params):
        self.seq = 0
        body = struct.pack("<IBI", stmt_id, 0, 1)
        n = len(params)
        if n:
            bitmap = bytearray((n + 7) // 8)
            types = b""
            values = b""
            for i, p in enumerate(params):
                if p is None:
                    bitmap[i // 8] |= 1 << (i % 8)
                    types += bytes([0x06, 0])
                elif isinstance(p, bool):
                    types += bytes([0x01, 0])
                    values += struct.pack("<b", int(p))
                elif isinstance(p, int):
                    types += bytes([0x08, 0])
                    values += struct.pack("<q", p)
                elif isinstance(p, float):
                    types += bytes([0x05, 0])
                    values += struct.pack("<d", p)
                else:
                    raw = str(p).encode()
                    types += bytes([0xFD, 0])
                    values += bytes([len(raw)]) if len(raw) < 251 else \
                        b"\xfc" + struct.pack("<H", len(raw))
                    values += raw
            body += bytes(bitmap) + b"\x01" + types + values
        self.write_packet(b"\x17" + body)
        first = self.read_packet()
        if first[0] == 0xFF:
            code = struct.unpack("<H", first[1:3])[0]
            raise RuntimeError(f"ERR {code}")
        if first[0] == 0x00:
            return {"ok": True}
        ncols, _ = self._lenenc(first, 0)
        col_types = []
        for _ in range(ncols):
            col = self.read_packet()
            i = 0
            for _f in range(6):
                ln, i = self._lenenc(col, i)
                i += ln
            col_types.append(col[i + 7])     # 0x0c + charset2 + length4
        assert self.read_packet()[0] == 0xFE
        rows = []
        while True:
            pkt = self.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            rows.append(self._binary_row(pkt, col_types))
        return {"rows": rows, "types": col_types}

    def _binary_row(self, pkt, col_types):
        ncols = len(col_types)
        nb = (ncols + 9) // 8
        bitmap = pkt[1:1 + nb]
        i = 1 + nb
        row = []
        for ci, tp in enumerate(col_types):
            pos = ci + 2
            if bitmap[pos // 8] & (1 << (pos % 8)):
                row.append(None)
                continue
            if tp == 0x08:
                row.append(struct.unpack_from("<q", pkt, i)[0])
                i += 8
            elif tp == 0x03:
                row.append(struct.unpack_from("<i", pkt, i)[0])
                i += 4
            elif tp == 0x05:
                row.append(struct.unpack_from("<d", pkt, i)[0])
                i += 8
            elif tp in (0x0A, 0x0C, 0x07):
                ln = pkt[i]
                i += 1
                y, mo, d = struct.unpack_from("<HBB", pkt, i)
                val = f"{y:04d}-{mo:02d}-{d:02d}"
                if ln >= 7:
                    h, mi, s = pkt[i + 4], pkt[i + 5], pkt[i + 6]
                    val += f" {h:02d}:{mi:02d}:{s:02d}"
                i += ln
                row.append(val)
            else:
                ln, i = self._lenenc(pkt, i)
                row.append(pkt[i:i + ln].decode())
                i += ln
        return tuple(row)

    def close_stmt(self, stmt_id):
        self.seq = 0
        self.write_packet(b"\x19" + struct.pack("<I", stmt_id))

    def close(self):
        self.seq = 0
        try:
            self.write_packet(b"\x01")
        finally:
            self.sock.close()


@pytest.fixture(scope="module")
def setup():
    eng = Engine()
    srv = Server(eng, port=0).start()
    s = eng.new_session()
    s.execute("CREATE TABLE ps (a BIGINT, b VARCHAR(16), c DOUBLE, "
              "d DATE, e DECIMAL(10,2))")
    s.execute("INSERT INTO ps VALUES (1,'one',1.5,'2024-01-15',10.25),"
              "(2,'two',NULL,'2024-02-20',20.50),"
              "(3,NULL,3.5,NULL,NULL)")
    yield eng, srv
    srv.stop()


def test_placeholder_scanner():
    assert count_placeholders("SELECT ? + ?") == 2
    assert count_placeholders("SELECT '?', \"?\", `a?b`, ?") == 1
    assert count_placeholders("SELECT 1 -- ?\n + ? /* ? */ # ?") == 1
    assert substitute_placeholders("SELECT ?, '?', ?", [1, "x'y"]) == \
        "SELECT 1, '?', 'x\\'y'"


def test_prepare_execute_roundtrip(setup):
    eng, srv = setup
    c = StmtClient(srv.port)
    sid, n_params = c.prepare("SELECT a, b, c, d, e FROM ps "
                              "WHERE a >= ? ORDER BY a")
    assert n_params == 1
    r = c.execute(sid, [2])
    assert r["rows"] == [
        (2, "two", None, "2024-02-20", "20.50"),
        (3, None, 3.5, None, None)]
    # re-execute with a different param reuses the statement
    r = c.execute(sid, [1])
    assert len(r["rows"]) == 3
    assert r["rows"][0] == (1, "one", 1.5, "2024-01-15", "10.25")
    c.close_stmt(sid)
    c.close()


def test_execute_param_types(setup):
    eng, srv = setup
    c = StmtClient(srv.port)
    sid, n = c.prepare("SELECT ?, ?, ?, ?")
    assert n == 4
    r = c.execute(sid, [42, 2.5, "héllo", None])
    assert r["rows"][0][0] == 42
    assert abs(float(r["rows"][0][1]) - 2.5) < 1e-9
    assert r["rows"][0][2] == "héllo"
    assert r["rows"][0][3] is None
    c.close()


def test_prepared_dml(setup):
    eng, srv = setup
    c = StmtClient(srv.port)
    c.query("CREATE TABLE psw (k BIGINT, v VARCHAR(8))")
    sid, _ = c.prepare("INSERT INTO psw VALUES (?, ?)")
    c.execute(sid, [1, "a"])
    c.execute(sid, [2, "b'c"])
    r = c.query("SELECT k, v FROM psw ORDER BY k")
    assert r["rows"] == [("1", "a"), ("2", "b'c")]
    c.close()


def test_unknown_stmt_id_errors(setup):
    eng, srv = setup
    c = StmtClient(srv.port)
    with pytest.raises(RuntimeError, match="1243"):
        c.execute(9999, [])
    c.close()


# ---- auth / privileges -----------------------------------------------------


def test_password_auth(setup):
    eng, srv = setup
    s = eng.new_session()
    s.execute("CREATE USER 'alice'@'%' IDENTIFIED BY 'secret'")
    # correct password connects
    c = StmtClient(srv.port, "alice", "secret")
    # wrong password rejected
    with pytest.raises(PermissionError):
        StmtClient(srv.port, "alice", "wrong")
    # unknown user rejected
    with pytest.raises(PermissionError):
        StmtClient(srv.port, "mallory", "")
    c.close()


def test_privilege_enforcement(setup):
    eng, srv = setup
    s = eng.new_session()
    s.execute("CREATE USER IF NOT EXISTS 'bob' IDENTIFIED BY 'pw'")
    c = StmtClient(srv.port, "bob", "pw")
    with pytest.raises(RuntimeError, match="1142"):
        c.query("SELECT * FROM ps")
    with pytest.raises(RuntimeError, match="1142"):
        c.query("CREATE TABLE bobt (a BIGINT)")
    # non-superuser cannot administer users
    with pytest.raises(RuntimeError, match="1142"):
        c.query("CREATE USER eve")
    s.execute("GRANT SELECT ON ps TO 'bob'@'%'")
    assert c.query("SELECT COUNT(*) FROM ps")["rows"] == [("3",)]
    with pytest.raises(RuntimeError, match="1142"):
        c.query("INSERT INTO ps VALUES (9,NULL,NULL,NULL,NULL)")
    s.execute("GRANT INSERT, DELETE ON *.* TO 'bob'")
    c.query("INSERT INTO ps VALUES (9,'nine',9.5,'2024-09-09',90.00)")
    c.query("DELETE FROM ps WHERE a = 9")
    grants = c.query("SHOW GRANTS")["rows"]
    assert any("SELECT" in g[0] for g in grants)
    s.execute("REVOKE SELECT ON ps FROM bob")
    with pytest.raises(RuntimeError, match="1142"):
        c.query("SELECT * FROM ps")
    c.close()


def test_subquery_respects_privileges(setup):
    # regression: expression subqueries must not bypass the grant check
    eng, srv = setup
    s = eng.new_session()
    s.execute("CREATE USER IF NOT EXISTS 'dave' IDENTIFIED BY 'pw'")
    c = StmtClient(srv.port, "dave", "pw")
    with pytest.raises(RuntimeError, match="1142"):
        c.query("SELECT (SELECT MAX(a) FROM ps)")
    with pytest.raises(RuntimeError, match="1142"):
        c.query("SELECT 1 WHERE 1 IN (SELECT a FROM ps)")
    c.close()


def test_db_grant_is_not_superuser(setup):
    # regression: a db-level grant must NOT satisfy user administration
    eng, srv = setup
    s = eng.new_session()
    s.execute("CREATE USER IF NOT EXISTS 'erin' IDENTIFIED BY 'pw'")
    s.execute("GRANT ALL ON test.* TO erin")
    c = StmtClient(srv.port, "erin", "pw")
    assert c.query("SELECT COUNT(*) FROM ps")["rows"]  # db grant works
    with pytest.raises(RuntimeError, match="1142"):
        c.query("CREATE USER mallory")
    with pytest.raises(RuntimeError, match="1142"):
        c.query("GRANT ALL ON *.* TO erin")
    c.close()


def test_reexecute_without_rebound_types(setup):
    # C-client drivers send parameter types only on the FIRST execute
    eng, srv = setup
    c = StmtClient(srv.port)
    sid, _ = c.prepare("SELECT a FROM ps WHERE a = ?")
    assert c.execute(sid, [1])["rows"] == [(1,)]
    # second execute: new_params_bound_flag=0, no type bytes
    c.seq = 0
    body = (struct.pack("<IBI", sid, 0, 1) + b"\x00" + b"\x00"
            + struct.pack("<q", 2))
    c.write_packet(b"\x17" + body)
    first = c.read_packet()
    assert first[0] != 0xFF, first
    ncols, _ = c._lenenc(first, 0)
    types = []
    for _ in range(ncols):
        col = c.read_packet()
        i = 0
        for _f in range(6):
            ln, i = c._lenenc(col, i)
            i += ln
        types.append(col[i + 7])
    assert c.read_packet()[0] == 0xFE
    rows = []
    while True:
        pkt = c.read_packet()
        if pkt[0] == 0xFE and len(pkt) < 9:
            break
        rows.append(c._binary_row(pkt, types))
    assert rows == [(2,)]
    c.close()


def test_placeholder_in_comment(setup):
    # regression: '?' inside a comment must not consume a parameter
    eng, srv = setup
    c = StmtClient(srv.port)
    sid, n = c.prepare("SELECT /* ? */ a FROM ps WHERE a = ? -- ?")
    assert n == 1
    assert c.execute(sid, [2])["rows"] == [(2,)]
    c.close()


def test_drop_user(setup):
    eng, srv = setup
    s = eng.new_session()
    s.execute("CREATE USER carol IDENTIFIED BY 'x'")
    StmtClient(srv.port, "carol", "x").close()
    s.execute("DROP USER carol")
    with pytest.raises(PermissionError):
        StmtClient(srv.port, "carol", "x")


def _lenenc_str(b, i):
    ln = b[i]
    i += 1
    return b[i:i + ln].decode(), i + ln


def test_prepare_reports_result_metadata(setup):
    """COM_STMT_PREPARE sends true column count + definitions (ref:
    server/conn_stmt.go writePrepare) — strict binary clients read the
    result shape before EXECUTE (round-4 advisor weak #5)."""
    eng, srv = setup
    c = StmtClient(srv.port)
    c.seq = 0
    c.write_packet(b"\x16" + b"SELECT a, b AS label FROM ps WHERE a > ?")
    resp = c.read_packet()
    assert resp[0] == 0x00
    stmt_id, n_cols, n_params = struct.unpack("<IHH", resp[1:9])
    assert n_cols == 2 and n_params == 1
    for _ in range(n_params):
        c.read_packet()
    assert c.read_packet()[0] == 0xFE
    names = []
    for _ in range(n_cols):
        pkt = c.read_packet()
        i = 0
        for _field in range(4):            # catalog, schema, table, org_t
            _, i = _lenenc_str(pkt, i)
        nm, i = _lenenc_str(pkt, i)
        names.append(nm)
    assert c.read_packet()[0] == 0xFE
    assert names == ["a", "label"]
    # the statement still executes fine afterwards
    r = c.execute(stmt_id, [1])
    assert len(r["rows"]) == 2
    # DML prepares report 0 columns
    c.seq = 0
    c.write_packet(b"\x16" + b"INSERT INTO ps (a) VALUES (?)")
    resp = c.read_packet()
    _, n_cols2, n_params2 = struct.unpack("<IHH", resp[1:9])
    assert n_cols2 == 0 and n_params2 == 1
    for _ in range(n_params2):
        c.read_packet()
    assert c.read_packet()[0] == 0xFE
    c.close()
