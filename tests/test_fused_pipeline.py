"""Whole-pipeline fragment fusion (executor/fragment.py
_run_fused_pipeline + executor/device_emit.py emit layer).

Pinned invariants:

* the fused per-slab program (scan → filter/project → join-probe →
  partial-agg in ONE traced XLA call per slab, plus one root merge) is
  byte-exact against both the operator-at-a-time mega-slab tree path
  (`tidb_tpu_fused_pipeline='off'`) and the CPU volcano — including
  string-dictionary group keys and exact decimal sums;
* the Q1 chain shape (wide decimals included) runs its partials through
  the same emit layer and reports per-slab fused launches;
* a group-cap overflow INSIDE the fused pipeline re-runs only the
  overflowed slabs (EscalationStats slabs_rerun/slabs_reused) and the
  resumed result matches a Python oracle;
* warm repeats retrace nothing (PROGRAM_TRACES frozen) and launch at
  most 2 device programs per slab (slab partial + amortized merge);
* fused compute spans land in the Chrome timeline one-per-slab, labeled
  with the pipeline signature digest, and cold builds charge the
  `compile:fused` lane.
"""

import collections

import pytest

from tidb_tpu.executor import build, fragment as frag_mod, run_to_completion
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine
from tidb_tpu.util import timeline


def run_device(s, sql, *, max_slab=None, fused=None):
    """Execute on the device path, asserting no CPU fallback."""
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    if max_slab is not None:
        s.vars["tidb_tpu_max_slab_rows"] = max_slab
    if fused is not None:
        s.vars["tidb_tpu_fused_pipeline"] = fused
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags, f"no fragment extracted for: {sql}"
        for f in frags:
            assert f.used_device, f"fell back to CPU: {f.fallback_reason}"
        return [r for ch in chunks for r in ch.rows()]
    finally:
        s.vars["tidb_tpu_engine"] = "off"
        for k in ("tidb_tpu_max_slab_rows", "tidb_tpu_fused_pipeline"):
            s.vars.pop(k, None)


def join_fixture(n_facts=3072):
    """Star fixture: n_facts facts → 8-row dim → 2-row reg, with a
    string-dictionary group key and exact decimal measures; every fact
    row matches exactly one dim row."""
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE dim (id INT, name VARCHAR(16), r_id INT)")
    s.execute("CREATE TABLE reg (id INT, rname VARCHAR(8))")
    s.execute("INSERT INTO reg VALUES (0,'east'),(1,'west')")
    s.execute("INSERT INTO dim VALUES " + ",".join(
        f"({i}, 'name{i:02d}', {i % 2})" for i in range(8)))
    s.execute("CREATE TABLE facts (b INT, s VARCHAR(8), v BIGINT, "
              "dec DECIMAL(12,2))")
    for base in range(0, n_facts, 512):
        vals = ", ".join(
            f"({i % 8}, 'seg{i % 5}', {(i * 37) % 211 - 100}, "
            f"{(i * 53) % 9973}.{i % 100:02d})"
            for i in range(base, min(base + 512, n_facts)))
        s.execute(f"INSERT INTO facts VALUES {vals}")
    s.execute("ANALYZE TABLE dim")
    s.execute("ANALYZE TABLE reg")
    s.execute("ANALYZE TABLE facts")
    return eng, s


Q3_SHAPE = ("SELECT d.name, COUNT(*), SUM(f.v) FROM facts f "
            "JOIN dim d ON f.b = d.id WHERE f.v > -50 "
            "GROUP BY d.name ORDER BY d.name")
Q5_SHAPE = ("SELECT r.rname, COUNT(*), SUM(f.dec) FROM facts f "
            "JOIN dim d ON f.b = d.id JOIN reg r ON d.r_id = r.id "
            "GROUP BY r.rname ORDER BY r.rname")
STR_KEY = ("SELECT f.s, COUNT(*), SUM(f.dec), SUM(f.v) FROM facts f "
           "JOIN dim d ON f.b = d.id GROUP BY f.s ORDER BY f.s")


# ---------------------------------------------------------------------------
# byte-exact: fused vs operator-at-a-time vs CPU
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sql", [Q3_SHAPE, Q5_SHAPE, STR_KEY],
                         ids=["q3", "q5", "string-key"])
def test_fused_byte_exact_vs_unfused_and_cpu(sql):
    _, s = join_fixture()
    cpu = s.query(sql).rows
    fused = run_device(s, sql, max_slab=1024, fused="on")
    unfused = run_device(s, sql, max_slab=1024, fused="off")
    assert fused == unfused, "fused vs mega-slab tree mismatch"
    assert fused == cpu, "fused vs CPU volcano mismatch"


def test_fused_counters_and_chain_wide_decimal():
    # Q1 chain shape: the per-slab partial IS a fused pipeline through
    # the shared emit layer — wide decimals and string keys included
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE st (c VARCHAR(8), a BIGINT, w DECIMAL(30,4))")
    for base in range(0, 3000, 500):
        vals = ", ".join(
            f"('k{i % 7}', {i % 50 - 25}, {(i * 97) % 100000}.{i % 10000:04d})"
            for i in range(base, base + 500))
        s.execute(f"INSERT INTO st VALUES {vals}")
    sql = "SELECT c, COUNT(a), SUM(w) FROM st GROUP BY c ORDER BY c"
    cpu = s.query(sql).rows
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_max_slab_rows": 1024})
    assert s.query(sql).rows == cpu
    ph = s.last_guard.phases
    # 3 slabs → 3 fused partial launches; every launch is fused except
    # the single root merge
    assert ph.fused_pipelines == 3, ph.summary()
    assert ph.programs_launched == ph.fused_pipelines + 1, ph.summary()


def test_fused_join_launch_accounting():
    _, s = join_fixture()
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_max_slab_rows": 1024})
    cpu_rows = None
    for _ in range(2):             # cold then warm — same counts
        rows = s.query(Q3_SHAPE).rows
        cpu_rows = cpu_rows or rows
        assert rows == cpu_rows
        ph = s.last_guard.phases
        # 3 probe slabs × 1 fused program + 1 root merge
        assert ph.fused_pipelines == 3, ph.summary()
        assert ph.programs_launched == 4, ph.summary()
        assert ph.programs_launched <= 2 * ph.fused_pipelines


def test_statements_summary_matches_phase_ledger():
    # satellite: the digest profile's launch counters are byte-exact
    # sums of the per-statement PhaseTimer ledger
    _, s = join_fixture()
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_max_slab_rows": 1024})
    q = ("SELECT digest_text, programs_launched, fused_pipelines"
         " FROM information_schema.statements_summary")

    def digest_counts():
        # the registry is process-global, so measure this test as a DELTA
        # over whatever earlier tests already folded into the digest
        hits = [r for r in s.query(q).rows
                if "rname" in r[0] and "facts" in r[0]]
        assert len(hits) <= 1, hits
        return (hits[0][1], hits[0][2]) if hits else (0, 0)

    l0, f0 = digest_counts()
    want_launch = want_fused = 0
    for _ in range(3):
        s.query(Q5_SHAPE)
        ph = s.last_guard.phases
        want_launch += ph.programs_launched
        want_fused += ph.fused_pipelines
    assert want_fused > 0
    l1, f1 = digest_counts()
    assert l1 - l0 == want_launch
    assert f1 - f0 == want_fused


# ---------------------------------------------------------------------------
# escalation mid-pipeline: rerun only the overflowed slabs
# ---------------------------------------------------------------------------

def test_fused_group_overflow_reruns_only_overflowed_slabs():
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE dim (id INT, name VARCHAR(16))")
    s.execute("INSERT INTO dim VALUES " + ",".join(
        f"({i}, 'name{i:02d}')" for i in range(8)))
    s.execute("CREATE TABLE fx (k BIGINT, b INT, v BIGINT)")
    oracle = collections.defaultdict(int)
    stride = 5_000_000       # key span defeats the perfect-hash gate
    for slab, nd in enumerate((10, 200, 10)):
        rows = []
        for i in range(1024):
            k = (slab * 1000 + i % nd) * stride
            rows.append(f"({k}, {i % 8}, {i})")
            oracle[k] += i
        s.execute("INSERT INTO fx VALUES " + ",".join(rows))
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_max_slab_rows": 1024,
                   "tidb_tpu_group_cap": 64})
    res = s.query("SELECT f.k, SUM(f.v) FROM fx f "
                  "JOIN dim d ON f.b = d.id GROUP BY f.k")
    assert {int(k): int(v) for k, v in res.rows} == dict(oracle)
    esc = s.last_guard.escalation
    # slab 1 (200 distinct) overflows the 64 cap; slabs 0/2 (10 each) are
    # checkpointed fused partials merged back untouched
    assert esc.slabs_rerun == 1, esc.summary()
    assert esc.slabs_reused == 2, esc.summary()
    assert esc.exact_resizes == 1, esc.summary()
    assert esc.by_kind.get("group:partial-reuse") == 1, esc.summary()
    ph = s.last_guard.phases
    # 3 cold fused launches + 1 rerun launch (+2 merges)
    assert ph.fused_pipelines == 4, ph.summary()


# ---------------------------------------------------------------------------
# warm repeat: zero retraces, ≤2 launches per slab
# ---------------------------------------------------------------------------

@pytest.mark.perf_smoke
def test_fused_warm_repeat_zero_retrace_two_launches_per_slab():
    _, s = join_fixture()
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_max_slab_rows": 1024})
    cold = s.query(STR_KEY).rows
    traces = frag_mod.PROGRAM_TRACES
    for _ in range(3):
        assert s.query(STR_KEY).rows == cold
        ph = s.last_guard.phases
        assert ph.fused_pipelines == 3, ph.summary()
        assert ph.programs_launched <= 2 * ph.fused_pipelines, ph.summary()
    assert frag_mod.PROGRAM_TRACES == traces, \
        "warm fused repeat must not retrace"


# ---------------------------------------------------------------------------
# Chrome-trace: one labeled fused span per slab + compile:fused lane
# ---------------------------------------------------------------------------

def test_timeline_fused_spans_and_compile_lane():
    _, s = join_fixture(n_facts=1500)
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_max_slab_rows": 512})
    # the filter constant lands in the tree signature, so this variant is
    # cold even though the compile cache is process-global and q3 above
    # already built the -50 shape
    sql = Q3_SHAPE.replace("> -50", "> -49")
    with timeline.capture() as col:
        s.query(sql)
    ph = s.last_guard.phases
    fused_spans = [e for e in col.events
                   if e["name"] == "compute"
                   and str(e.get("args", {}).get("sig", ""))
                   .startswith("fused:")]
    # exactly one labeled compute span per fused slab launch
    assert ph.fused_pipelines >= 2, ph.summary()
    assert len(fused_spans) == ph.fused_pipelines, \
        [e.get("args") for e in col.events]
    sigs = {e["args"]["sig"] for e in fused_spans}
    assert len(sigs) == 1, "one pipeline → one signature digest"
    # cold pipeline build must charge the compile:fused lane
    compiles = [e for e in col.events if e["name"] == "compile:fused"]
    assert compiles, [e["name"] for e in col.events]
