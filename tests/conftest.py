"""Test environment: force an 8-device virtual CPU mesh before jax loads.

Mirrors the reference's testing strategy (SURVEY §4): the whole distributed
surface is exercised in-process — unistore fakes a TiKV cluster in one Go
process; we fake an 8-chip TPU pod slice with XLA host devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_ENABLE_X64"] = "1"

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
