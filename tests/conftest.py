"""Test environment: force an 8-device virtual CPU mesh.

Mirrors the reference's testing strategy (SURVEY §4): the whole distributed
surface is exercised in-process — unistore fakes a TiKV cluster in one Go
process; we fake an 8-chip TPU pod slice with XLA host devices.

On machines where a TPU site hook (sitecustomize) imports jax at
interpreter start, env vars set here are too late — so pytest_configure
re-execs the test process once with a scrubbed environment (after
suspending pytest's fd capture so the new process owns the terminal).
This also keeps tests off the real chip entirely: it is single-tenant,
and benches own it."""

import os
import sys

_WANT_XLA = "--xla_force_host_platform_device_count=8"


def _needs_reexec() -> bool:
    if os.environ.get("_TIDB_TPU_TEST_REEXEC") == "1":
        return False
    return ("jax" in sys.modules
            or bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
            or os.environ.get("JAX_PLATFORMS") not in (None, "cpu"))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers", "chaos: failpoint/chaos-sweep tests")
    config.addinivalue_line(
        "markers", "perf_smoke: tier-1 perf guardrails (tiny scale, "
        "asserts zero retraces and streamed-overlap phase accounting)")
    if not _needs_reexec():
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.suspend_global_capture(in_=True)
    env = dict(os.environ)
    env["_TIDB_TPU_TEST_REEXEC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)   # the site hook gates on this
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " " + _WANT_XLA).strip()
    env["JAX_ENABLE_X64"] = "1"
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest"] + sys.argv[1:], env)


os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " " + _WANT_XLA).strip()
os.environ["JAX_ENABLE_X64"] = "1"

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
