"""Native C++ row codec vs the Python encoder — byte-identical output
(ref: server/util.go dumpTextRow, the reference's result hot loop)."""

import numpy as np
import pytest

from tidb_tpu import native
from tidb_tpu import types as T
from tidb_tpu.chunk import Chunk, Column


def python_encode(chunk, seq):
    from tidb_tpu.server import _lenenc_str, _text_value
    out = bytearray()
    for row in chunk.rows():
        body = b""
        for v in row:
            body += b"\xfb" if v is None else _lenenc_str(_text_value(v))
        out += len(body).to_bytes(3, "little") + bytes([seq]) + body
        seq = (seq + 1) & 0xFF
    return bytes(out), seq


def check(chunk, ftypes, seq=0):
    enc = native.encode_text_rows(chunk, ftypes, seq)
    if enc is None:
        pytest.skip("native rowcodec unavailable (no toolchain)")
    ref, ref_seq = python_encode(chunk, seq)
    assert enc[0] == ref
    assert enc[1] == ref_seq


def test_edge_values():
    fts = [T.bigint(), T.double(), T.decimal(10, 3), T.date(),
           T.datetime(), T.varchar()]
    rows = [
        (0, 0.0, "0.000", "1970-01-01", "1970-01-01 00:00:00", ""),
        (-(2**63) + 1, -1.5e-7, "-0.001", "1969-12-31",
         "1969-12-31 23:59:59", "héllo ✓"),
        (2**62, 3.141592653589793, "1234567.890", "9999-12-31",
         "2024-02-29 12:34:56.000123", "x" * 300),
        (None, None, None, None, None, None),
        (42, 1.0, "-99.999", "2000-02-29", "2000-01-01 00:00:00.5",
         "tab\tnl\n"),
    ]
    chunk = Chunk.from_rows(fts, rows)
    check(chunk, fts, seq=250)      # seq wraps mid-batch


def test_float_repr_parity():
    # exactly the notation boundaries where std::to_chars and python repr
    # disagree by default: fixed vs scientific selection
    fts = [T.double()]
    vals = [100000.0, 0.0001, 2e5, 1e16, 1e15, 9.999e15, 1e-4, 9e-5,
            -1.5e-5, 1e22, 123456789012345.6, -0.0, 2.5e-10, 3e300]
    chunk = Chunk.from_rows(fts, [(v,) for v in vals])
    check(chunk, fts)


def test_bulk_random_roundtrip():
    rng = np.random.default_rng(5)
    n = 5000
    fts = [T.bigint(), T.double(), T.decimal(12, 2), T.varchar()]
    chunk = Chunk([
        Column(fts[0], rng.integers(-10**15, 10**15, n), None),
        Column(fts[1], rng.normal(size=n) * 10.0 ** rng.integers(-8, 8, n),
               rng.random(n) > 0.05),
        Column(fts[2], rng.integers(-10**10, 10**10, n), None),
        Column(fts[3], np.array([f"v{i % 321}" for i in range(n)],
                                dtype=object), rng.random(n) > 0.02),
    ])
    check(chunk, fts)


def test_wire_roundtrip_uses_native(monkeypatch):
    # end-to-end: server sends native-encoded rows; client parses them
    import sys
    sys.path.insert(0, "tests")
    from test_server import MiniClient
    from tidb_tpu.server import Server
    from tidb_tpu.session import Engine
    if native.get_lib() is None:
        pytest.skip("native rowcodec unavailable (no toolchain)")
    calls = []
    real = native.encode_text_rows

    def spy(chunk, ftypes, seq):
        out = real(chunk, ftypes, seq)
        calls.append(out is not None)
        return out

    monkeypatch.setattr(native, "encode_text_rows", spy)
    srv = Server(Engine(), port=0).start()
    try:
        c = MiniClient(srv.port)
        c.query("CREATE TABLE n (a BIGINT, d DECIMAL(8,2), s VARCHAR(8))")
        c.query("INSERT INTO n VALUES (1, 2.50, 'x'), (-7, NULL, NULL)")
        r = c.query("SELECT * FROM n ORDER BY a")
        assert r["rows"] == [("-7", None, None), ("1", "2.50", "x")]
        c.close()
    finally:
        srv.stop()
    assert calls and all(calls), "native encoder did not carry the rows"
