"""GROUP BY ... WITH ROLLUP: host hash-path oracle semantics (MySQL
super-aggregate rows) and the fused device lowering (levels tiled into
one program per slab with a grouping-level key column) byte-exact
against the host."""

import numpy as np
import pytest

from tidb_tpu.executor import build, run_to_completion
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE r (a BIGINT, b BIGINT, c BIGINT, d DOUBLE, "
              "s VARCHAR(8))")
    rng = np.random.default_rng(11)
    rows = []
    for _ in range(4000):
        a = "NULL" if rng.random() < 0.04 else str(int(rng.integers(1, 6)))
        b = "NULL" if rng.random() < 0.04 else str(int(rng.integers(1, 8)))
        c = int(rng.integers(1, 1000))
        d = round(float(rng.uniform(0, 100)), 3)
        sv = ["'ant'", "'bee'", "'cow'", "NULL"][int(rng.integers(0, 4))]
        rows.append(f"({a},{b},{c},{d},{sv})")
    for i in range(0, len(rows), 500):
        s.execute("INSERT INTO r VALUES " + ",".join(rows[i:i + 500]))
    s.execute("CREATE TABLE dim (a BIGINT, name BIGINT)")
    s.execute("INSERT INTO dim VALUES " +
              ",".join(f"({i},{i * 10})" for i in range(1, 6)))
    s.execute("CREATE TABLE mt (a BIGINT, c BIGINT)")  # stays empty
    return s


def run_plan(s, sql):
    plan = s._plan(parse(sql)[0])
    root = build(plan)
    chunks = run_to_completion(root, s._exec_ctx())
    frags = []

    def walk(e):
        if isinstance(e, TpuFragmentExec):
            frags.append(e)
        for ch in getattr(e, "children", []):
            walk(ch)

    walk(root)
    return [r for ch in chunks for r in ch.rows()], frags


def device_vs_host(s, sql, *, max_slab=None, expect_device=True):
    host, _ = run_plan(s, sql)
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    if max_slab is not None:
        s.vars["tidb_tpu_max_slab_rows"] = max_slab
    try:
        dev, frags = run_plan(s, sql)
    finally:
        s.vars["tidb_tpu_engine"] = "off"
        s.vars.pop("tidb_tpu_max_slab_rows", None)
    if expect_device:
        assert frags, f"no fragment extracted for: {sql}"
        for f in frags:
            assert f.used_device, \
                f"fell back ({f.fallback_reason}) for: {sql}"
    else:
        assert not any(f.used_device for f in frags), \
            f"expected the host oracle for: {sql}"
    hs, ds = sorted(host, key=repr), sorted(dev, key=repr)
    assert len(hs) == len(ds), (len(hs), len(ds), sql)
    for h, d in zip(hs, ds):
        for x, y in zip(h, d):
            if isinstance(x, float) and y is not None:
                assert abs(x - y) <= 1e-9 * max(1.0, abs(x)), (h, d)
            else:
                assert x == y, (h, d)
    return host


# ---- host oracle semantics (engine off) -----------------------------------

def test_rollup_grand_total_matches_scalar_agg(session):
    rows = session.query("SELECT a, b, COUNT(*), SUM(c) FROM r "
                         "GROUP BY a, b WITH ROLLUP").rows
    total = session.query("SELECT COUNT(*), SUM(c) FROM r").rows[0]
    grand = [r for r in rows if r[0] is None and r[1] is None]
    # genuinely-NULL (a, b) detail rows also have both keys NULL; the
    # grand total is there EXTRA, so: detail(a=NULL,b=NULL) + the
    # subtotal of a=NULL + the grand total itself
    assert any(r[2] == total[0] and r[3] == total[1] for r in grand), \
        (grand, total)


def test_rollup_level_counts(session):
    rows = session.query("SELECT a, b, COUNT(*) FROM r "
                         "GROUP BY a, b WITH ROLLUP").rows
    detail = session.query("SELECT a, b, COUNT(*) FROM r "
                           "GROUP BY a, b").rows
    sub = session.query("SELECT a, COUNT(*) FROM r GROUP BY a").rows
    # one row per (a, b) group, one per a-prefix subtotal, one grand
    assert len(rows) == len(detail) + len(sub) + 1
    n = session.query("SELECT COUNT(*) FROM r").rows[0][0]
    assert sum(r[2] for r in rows) == 3 * n  # every input row counted
    # at each of the 3 levels exactly once


def test_rollup_null_keys_stay_separate_from_subtotals(session):
    rows = session.query("SELECT a, COUNT(*) FROM r "
                         "GROUP BY a WITH ROLLUP").rows
    null_rows = [r for r in rows if r[0] is None]
    null_detail = session.query(
        "SELECT COUNT(*) FROM r WHERE a IS NULL").rows[0][0]
    total = session.query("SELECT COUNT(*) FROM r").rows[0][0]
    # the NULL-keyed detail group and the grand total must be two rows
    assert sorted(r[1] for r in null_rows) == sorted([null_detail, total])


def test_rollup_empty_input_no_rows(session):
    assert session.query("SELECT a, COUNT(*) FROM mt "
                         "GROUP BY a WITH ROLLUP").rows == []


def test_rollup_having_filters_super_aggregates_too(session):
    rows = session.query("SELECT a, b, SUM(c) FROM r "
                         "GROUP BY a, b WITH ROLLUP "
                         "HAVING SUM(c) > 100000").rows
    assert rows
    assert all(r[2] > 100000 for r in rows)


# ---- fused device path vs host oracle -------------------------------------

ROLLUP_QUERIES = [
    "SELECT a, b, COUNT(*), SUM(c), MIN(c), MAX(c) FROM r "
    "GROUP BY a, b WITH ROLLUP",
    "SELECT a, COUNT(*), SUM(c), AVG(c) FROM r GROUP BY a WITH ROLLUP",
    "SELECT s, a, COUNT(*), SUM(d) FROM r GROUP BY s, a WITH ROLLUP",
    "SELECT a, b, COUNT(*), SUM(c) FROM r GROUP BY a, b WITH ROLLUP "
    "ORDER BY a, b, 3 LIMIT 10",
    "SELECT a, b, SUM(c) FROM r GROUP BY a, b WITH ROLLUP "
    "HAVING SUM(c) > 100000",
    "SELECT a, SUM(c) FROM r GROUP BY a WITH ROLLUP ORDER BY a",
]


@pytest.mark.parametrize("sql", ROLLUP_QUERIES)
def test_device_rollup_matches_host(session, sql):
    device_vs_host(session, sql)


def test_device_rollup_multi_slab(session):
    device_vs_host(session, ROLLUP_QUERIES[0], max_slab=1024)


def test_device_rollup_join_tree(session):
    device_vs_host(session,
                   "SELECT dim.name, r.b, COUNT(*), SUM(r.c) FROM r "
                   "JOIN dim ON r.a = dim.a "
                   "GROUP BY dim.name, r.b WITH ROLLUP")


def test_distinct_rollup_stays_on_host_oracle(session):
    # pair columns assume nk key cols; DISTINCT under ROLLUP is gated
    # off the device and must still be correct via the host oracle
    device_vs_host(session,
                   "SELECT a, COUNT(DISTINCT b) FROM r "
                   "GROUP BY a WITH ROLLUP", expect_device=False)


def test_warm_rollup_launch_count(session):
    """Warm single-fragment rollup is <= slabs + 1 programs: the level
    tiling rides inside the per-slab partial program, not extra
    launches."""
    s = session
    sql = ROLLUP_QUERIES[0]
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        s.query(sql)               # compile + first-touch
        s.query(sql)               # warm
        ph = s.last_guard.phases
        assert ph.programs_launched >= 1
        # 4000 rows pad into one slab: partial + fused finalize
        assert ph.programs_launched <= 2, ph.programs_launched
    finally:
        s.vars["tidb_tpu_engine"] = "off"
