"""Builtin batch 3 (round 5): info / IP / UUID / JSON-mutation / crypto /
misc breadth (ref: expression/builtin_info.go, builtin_miscellaneous.go,
builtin_json.go, builtin_encryption.go). Every function asserted against
MySQL-documented outputs."""

import pytest

from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def s():
    s = Engine().new_session()
    s.execute("CREATE TABLE one (x BIGINT)")
    s.execute("INSERT INTO one VALUES (1)")
    return s


def q1(s, expr):
    return s.query(f"SELECT {expr} FROM one").rows[0][0]


def test_ip_functions(s):
    assert q1(s, "IS_IPV4('10.0.5.9')") == 1
    assert q1(s, "IS_IPV4('10.0.5.256')") == 0
    assert q1(s, "IS_IPV6('::1')") == 1
    assert q1(s, "IS_IPV6('10.0.5.9')") == 0
    assert q1(s, "INET6_NTOA(INET6_ATON('fdfe::5a55:caff:fefa:9089'))") \
        == "fdfe::5a55:caff:fefa:9089"
    assert q1(s, "INET6_NTOA(INET6_ATON('10.0.5.9'))") == "10.0.5.9"
    assert q1(s, "IS_IPV4_MAPPED(INET6_ATON('::ffff:10.0.5.9'))") == 1
    assert q1(s, "IS_IPV4_COMPAT(INET6_ATON('::10.0.5.9'))") == 1
    assert q1(s, "IS_IPV4_MAPPED(INET6_ATON('::10.0.5.9'))") == 0


def test_uuid_functions(s):
    u = "6ccd780c-baba-1026-9564-5b8c656024db"
    assert q1(s, f"IS_UUID('{u}')") == 1
    assert q1(s, "IS_UUID('nope')") == 0
    assert q1(s, f"BIN_TO_UUID(UUID_TO_BIN('{u}'))") == u
    assert q1(s, f"BIN_TO_UUID(UUID_TO_BIN('{u}', 1), 1)") == u
    a, b = q1(s, "UUID_SHORT()"), q1(s, "UUID_SHORT()")
    assert isinstance(a, int) and a != b


def test_string_additions(s):
    assert q1(s, "CONCAT_WS(',', 'a', NULL, 'b')") == "a,b"
    assert q1(s, "CONCAT_WS(NULL, 'a', 'b')") is None
    assert q1(s, "BIT_COUNT(29)") == 4
    assert q1(s, "BIT_COUNT(-1)") == 64
    assert q1(s, "OCTET_LENGTH('héllo')") == 6
    assert q1(s, "FORMAT_BYTES(512)") == "512 bytes"
    assert q1(s, "FORMAT_BYTES(2048)") == "2.00 KiB"
    assert "ns" in q1(s, "FORMAT_PICO_TIME(3501)")
    assert q1(s, "WEIGHT_STRING('ab')") == "6162".upper()
    assert q1(s, "LOAD_FILE('/etc/passwd')") is None


def test_regexp_family(s):
    assert q1(s, "REGEXP_INSTR('dog cat dog', 'dog', 1, 2)") == 9
    assert q1(s, "REGEXP_SUBSTR('abc def ghi', '[a-z]+', 1, 3)") == "ghi"
    assert q1(s, "REGEXP_REPLACE('a b c', 'b', 'X')") == "a X c"


def test_crypto_functions(s):
    assert q1(s, "UNCOMPRESS(COMPRESS('hello world'))") == "hello world"
    assert q1(s, "UNCOMPRESSED_LENGTH(COMPRESS('hello world'))") == 11
    assert len(q1(s, "RANDOM_BYTES(8)")) == 16       # 8 bytes, hex text
    assert q1(s, "AES_DECRYPT(AES_ENCRYPT('secret', 'key'), 'key')") \
        == "secret"
    assert q1(s, "AES_DECRYPT(AES_ENCRYPT('s', 'k1'), 'k2')") is None
    assert q1(s, "PASSWORD('mypass')") == \
        "*6C8989366EAF75BB670AD8EA7A7FC1176A95CEF4"
    d = q1(s, "STATEMENT_DIGEST('select * from t where a = 1')")
    assert len(d) == 64
    assert q1(s, "STATEMENT_DIGEST_TEXT('select * from t where a = 1')") \
        == "select * from t where a = ?"


def test_info_and_misc(s):
    assert q1(s, "CHARSET('abc')") == "utf8mb4"
    assert q1(s, "COLLATION('abc')") in ("utf8mb4_bin",)
    assert q1(s, "COERCIBILITY('abc')") == 4
    assert q1(s, "ANY_VALUE(x)") == 1
    assert q1(s, "NAME_CONST('myname', 14)") == 14
    assert q1(s, "INTERVAL(23, 1, 15, 17, 30, 44, 200)") == 3
    assert q1(s, "INTERVAL(10, 20, 30)") == 0
    assert q1(s, "SLEEP(0)") == 0
    assert q1(s, "BENCHMARK(10, 1+1)") == 0
    assert q1(s, "TIDB_SHARD(12373743746)") == 130
    assert q1(s, "TIDB_IS_DDL_OWNER()") == 1
    assert q1(s, "VALIDATE_PASSWORD_STRENGTH('N0Tweak$_x')") == 100
    r = q1(s, "RAND()")
    assert 0.0 <= r < 1.0
    assert q1(s, "RAND(5)") == q1(s, "RAND(5)")
    assert s.query("SELECT SCHEMA(), SESSION_USER(), FOUND_ROWS(), "
                   "ROW_COUNT(), CURRENT_ROLE(), ICU_VERSION()").rows


def test_user_locks(s):
    assert q1(s, "GET_LOCK('l1', 0)") == 1
    assert q1(s, "IS_FREE_LOCK('l1')") == 0
    assert q1(s, "IS_USED_LOCK('l1')") is not None
    assert q1(s, "RELEASE_LOCK('l1')") == 1
    assert q1(s, "RELEASE_LOCK('l1')") is None
    assert q1(s, "GET_LOCK('l2', 0) + GET_LOCK('l3', 0)") == 2
    assert q1(s, "RELEASE_ALL_LOCKS()") == 2
    assert q1(s, "IS_FREE_LOCK('l2')") == 1


def test_json_mutation(s):
    assert q1(s, """JSON_SET('{"a": 1}', '$.b', 2)""") == \
        '{"a": 1, "b": 2}'
    assert q1(s, """JSON_INSERT('{"a": 1}', '$.a', 9)""") == '{"a": 1}'
    assert q1(s, """JSON_REPLACE('{"a": 1}', '$.b', 9)""") == '{"a": 1}'
    assert q1(s, """JSON_REMOVE('{"a": 1, "b": 2}', '$.b')""") == \
        '{"a": 1}'
    assert q1(s, "JSON_QUOTE('he\"llo')") == '"he\\"llo"'
    assert q1(s, """JSON_DEPTH('{"a": {"b": 1}}')""") == 3
    assert q1(s, "JSON_DEPTH('[]')") == 1
    assert q1(s, """JSON_ARRAY_APPEND('[1, 2]', '$', 3)""") == "[1, 2, 3]"
    assert q1(s, """JSON_ARRAY_INSERT('[1, 3]', '$[1]', 2)""") == \
        "[1, 2, 3]"
    assert q1(s, """JSON_MERGE_PATCH('{"a": 1, "b": 2}',
              '{"b": null, "c": 3}')""") == '{"a": 1, "c": 3}'
    assert q1(s, """JSON_MERGE_PRESERVE('[1]', '[2]')""") == "[1, 2]"
    assert q1(s, """JSON_CONTAINS_PATH('{"a": 1}', 'one', '$.a',
              '$.z')""") == 1
    assert q1(s, """JSON_CONTAINS_PATH('{"a": 1}', 'all', '$.a',
              '$.z')""") == 0
    assert q1(s, """JSON_SEARCH('["abc", {"x": "abc"}]', 'one',
              'abc')""") == '"$[0]"'
    assert q1(s, """JSON_OVERLAPS('[1, 3]', '[3, 4]')""") == 1
    assert q1(s, """JSON_OVERLAPS('[1, 2]', '[3, 4]')""") == 0
    assert q1(s, """JSON_MEMBER_OF(3, '[1, 3]')""") == 1
    assert q1(s, """JSON_VALUE('{"fname": "Pete"}', '$.fname')""") == \
        "Pete"
    assert q1(s, """JSON_PRETTY('[1]')""") == "[\n  1\n]"
    assert q1(s, """JSON_STORAGE_SIZE('{"a": 1}')""") > 0


def test_xml_functions(s):
    assert q1(s, "EXTRACTVALUE('<a><b>X</b></a>', '/a/b')") == "X"
    assert q1(s, "UPDATEXML('<a><b>ccc</b></a>', '/a/b', '<e>f</e>')") \
        == "<a><e>f</e></a>"


def test_gtid_and_ps(s):
    u = "3e11fa47-71ca-11e1-9e33-c80aa9429562"
    assert q1(s, f"GTID_SUBSET('{u}:23', '{u}:21-57')") == 1
    assert q1(s, f"GTID_SUBSET('{u}:23-80', '{u}:21-57')") == 0
    assert q1(s, f"GTID_SUBTRACT('{u}:21-57', '{u}:30-39')") == \
        f"{u}:21-29:40-57"
    assert q1(s, "PS_THREAD_ID(7)") == 7
    assert q1(s, "PS_CURRENT_THREAD_ID()") > 0
    assert "graphml" in q1(s, "ROLES_GRAPHML()")


def test_temporal_additions(s):
    s.execute("CREATE TABLE td (d DATETIME, dt DATE)")
    s.execute("INSERT INTO td VALUES "
              "('2009-11-29 13:43:32', '2009-11-29')")
    r = s.query("SELECT TO_SECONDS(d), TO_SECONDS(dt) FROM td").rows[0]
    assert r == (63426721412, 63426672000)
    assert s.query("SELECT TIME_FORMAT(TIMEDIFF(d, TIMESTAMP(dt)), "
                   "'%H:%i:%s') FROM td").rows[0][0] == "13:43:32"
    assert s.query("SELECT TIME_FORMAT(TIME(d), '%H-%i') FROM td"
                   ).rows[0][0] == "13-43"
    assert q1(s, "GET_FORMAT('DATE', 'ISO')") == "%Y-%m-%d"
    assert q1(s, "GET_FORMAT('DATETIME', 'JIS')") == "%Y-%m-%d %H:%i:%s"


def test_aes_fips_known_answer():
    # FIPS-197 appendix C.1 vector pins the cipher core
    from tidb_tpu.expression import _aes_block, _aes_expand_key
    ct = _aes_block(bytes.fromhex("00112233445566778899aabbccddeeff"),
                    _aes_expand_key(bytes(range(16))), True)
    assert ct.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"


def test_review_r5_builtin_findings(s):
    # temporal functions over string args (the canonical MySQL usage)
    assert s.query("SELECT TIME_FORMAT(TIMEDIFF('10:00:00', '09:20:30'),"
                   " '%H:%i:%s') FROM one").rows[0][0] == "00:39:30"
    assert s.query("SELECT TIME_FORMAT(TIME('10:05:03'), '%H:%i:%s') "
                   "FROM one").rows[0][0] == "10:05:03"
    assert q1(s, "TO_SECONDS('2009-11-29')") == 63426672000
    # REGEXP_REPLACE occurrence = the Nth match only (0 = all)
    assert q1(s, "REGEXP_REPLACE('abc abd abe', 'ab.', 'X', 1, 3)") == \
        "abc abd X"
    assert q1(s, "REGEXP_REPLACE('abc abd abe', 'ab.', 'X')") == "X X X"
    # JSON path members must exist; JSON null is present, not missing
    assert q1(s, """JSON_SET('{}', '$.a.b', 1)""") == "{}"
    assert q1(s, """JSON_SET('{"a": null}', '$.a.b', 1)""") == \
        '{"a": null}'


def test_json_aggregates(s):
    s.execute("CREATE TABLE ja (g BIGINT, k VARCHAR(8), v BIGINT)")
    s.execute("INSERT INTO ja VALUES (1,'a',10),(1,'b',20),(2,'c',NULL),"
              "(2,'a',40)")
    rows = s.query("SELECT g, JSON_ARRAYAGG(v) FROM ja GROUP BY g "
                   "ORDER BY g").rows
    assert rows[0][1] == "[10, 20]"
    assert rows[1][1] == "[null, 40]"     # SQL NULL → JSON null
    rows = s.query("SELECT g, JSON_OBJECTAGG(k, v) FROM ja GROUP BY g "
                   "ORDER BY g").rows
    import json
    assert json.loads(rows[0][1]) == {"a": 10, "b": 20}
    assert json.loads(rows[1][1]) == {"c": None, "a": 40}
    # duplicate keys keep the LAST value
    s.execute("INSERT INTO ja VALUES (1,'a',99)")
    rows = s.query("SELECT JSON_OBJECTAGG(k, v) FROM ja WHERE g = 1").rows
    assert json.loads(rows[0][0])["a"] == 99


def test_json_aggregates_edge_semantics(s):
    # empty input → NULL (not "[]"/"{}")
    s.execute("CREATE TABLE je (g BIGINT, d DATE, v BIGINT)")
    assert s.query("SELECT JSON_ARRAYAGG(v), JSON_OBJECTAGG(g, v) "
                   "FROM je").rows == [(None, None)]
    # non-string keys decode through their type; nested JSON stays JSON
    s.execute("INSERT INTO je VALUES (1, '2026-07-30', 5)")
    import json
    r = s.query("SELECT JSON_OBJECTAGG(d, v), "
                "JSON_ARRAYAGG(JSON_OBJECT('a', v)) FROM je").rows[0]
    assert json.loads(r[0]) == {"2026-07-30": 5}
    assert json.loads(r[1]) == [{"a": 5}]


def test_json_aggregates_spill_and_decimal_exactness(s):
    from tidb_tpu.errors import PlanError
    s.execute("CREATE TABLE js (g BIGINT, k VARCHAR(8), "
              "w DECIMAL(25,2))")
    s.execute("INSERT INTO js VALUES " + ",".join(
        f"({i % 50},'k{i}',{10**18 + i}.25)" for i in range(3000)))
    # quota engages spill: list-state aggregates must survive it
    s.vars["tidb_mem_quota_query"] = 20000
    try:
        rows = s.query("SELECT g, JSON_ARRAYAGG(k) FROM js GROUP BY g "
                       "ORDER BY g").rows
    finally:
        s.vars["tidb_mem_quota_query"] = 0
    assert len(rows) == 50 and rows[0][1].count("k") == 60
    # DECIMAL values stay exact in JSON output
    r = s.query("SELECT JSON_ARRAYAGG(w) FROM js WHERE g = 0 AND "
                "k = 'k0'").rows[0][0]
    assert r == "[1000000000000000000.25]", r
    # DISTINCT rejected like MySQL
    import pytest as _pt
    with _pt.raises(PlanError):
        s.query("SELECT JSON_ARRAYAGG(DISTINCT g) FROM js")
    with _pt.raises(PlanError):
        s.query("SELECT JSON_OBJECTAGG(DISTINCT k, g) FROM js")
