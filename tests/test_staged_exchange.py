"""Checkpointable staged exchanges (dist_fragment.StagedDistExchange):
distributed joins, DISTINCT re-keys and window shapes restructured into
per-rank partition programs → device→host bucket checkpoints + host
routing → per-rank probe programs, with per-shard fault recovery.

Three invariants pinned here:

  * byte-exactness — the staged path must reproduce the monolithic
    shard_map program (the oracle, `tidb_tpu_dist_staged_exchange=off`)
    and the CPU engine exactly, including skewed and ci-collation keys;
  * single-rank recovery — a fault at any stage re-executes ONLY the
    failed rank (shards_rerun==1, shards_reused==N-1), the degraded-mesh
    path completes on N-1 devices with exactly ONE retryable warning,
    and an exhausted ladder is ONE typed ShardFailure;
  * bounded cost — one skewed rank's bucket overflow costs one exact-need
    recompile (never a whole-step retrace), and abandoned device buffers
    are deleted before every retry (no HBM growth across injected
    faults)."""

import numpy as np
import pytest

from tidb_tpu.errors import ShardFailure
from tidb_tpu.util import failpoint


@pytest.fixture(scope="module")
def s(eight_devices):
    from tidb_tpu.session import Engine
    eng = Engine()
    s = eng.new_session()
    s.execute("create table xf (a bigint, b bigint, v bigint)")
    rows = ", ".join(f"({i % 97}, {i % 7}, {i % 101})" for i in range(4000))
    s.execute(f"insert into xf values {rows}")
    s.execute("create table xd (id bigint, w bigint)")
    rows = ", ".join(f"({i}, {i * i})" for i in range(3000))
    s.execute(f"insert into xd values {rows}")
    s.execute("create table xs (nm varchar(8) collate utf8mb4_general_ci,"
              " v bigint)")
    rows = ", ".join(f"('{'AbC' if i % 3 else 'aBc'}{i % 11}', {i % 13})"
                     for i in range(2000))
    s.execute(f"insert into xs values {rows}")
    s.execute("analyze table xf")
    s.execute("analyze table xd")
    s.execute("analyze table xs")
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_dist_devices": 4})
    yield s
    eng.close()


JOIN_SQL = ("select xd.w, count(*), sum(xf.v) from xf join xd "
            "on xf.a = xd.id group by xd.w order by xd.w")
DISTINCT_SQL = "select b, count(distinct a) from xf group by b order by b"
WINDOW_SQL = "select a, v, sum(v) over (partition by a) from xf"


def _rows(rs):
    return [tuple(x for x in r) for r in rs.rows]


def _run(s, sql, **vars_):
    old = {k: s.vars.get(k) for k in vars_}
    s.vars.update(vars_)
    try:
        out = _rows(s.query(sql))
    finally:
        for k, v in old.items():
            if v is None:
                s.vars.pop(k, None)
            else:
                s.vars[k] = v
    return out


def _three_ways(s, sql, sort=False):
    """(staged, monolithic, cpu) result rows for one statement; asserts
    the staged path actually engaged (its checkpoint site was hit)."""
    failpoint.reset_counters()       # counts survive enabled() scopes
    with failpoint.counting():
        staged = _run(s, sql, tidb_tpu_dist_staged_exchange="on")
        hits = failpoint.counters()
    failpoint.reset_counters()
    assert hits.get("exchange-checkpoint-write", 0) > 0, \
        "statement did not take the staged exchange path"
    mono = _run(s, sql, tidb_tpu_dist_staged_exchange="off")
    cpu = _run(s, sql, tidb_tpu_engine="off")
    if sort:
        staged, mono, cpu = sorted(staged), sorted(mono), sorted(cpu)
    return staged, mono, cpu


# ---- byte-exactness against the monolithic oracle and the CPU --------------

def test_distributed_join_byte_exact(s):
    staged, mono, cpu = _three_ways(s, JOIN_SQL)
    assert staged == mono == cpu
    assert len(staged) == 97


def test_broadcast_join_byte_exact(s):
    # the tiny build side makes insert_exchanges pick a broadcast
    # exchange: stage 1 checkpoints each rank's filtered build rows, the
    # host replicates the concatenation to every destination
    s.execute("create table xdim (id bigint, w bigint)")
    s.execute("insert into xdim values " +
              ", ".join(f"({i}, {10 * i})" for i in range(8)))
    s.execute("analyze table xdim")
    sql = ("select xdim.w, count(*), sum(xf.v) from xf join xdim "
           "on xf.b % 8 = xdim.id group by xdim.w order by xdim.w")
    staged, mono, cpu = _three_ways(s, sql)
    assert staged == mono == cpu


def test_distinct_rekey_byte_exact(s):
    staged, mono, cpu = _three_ways(s, DISTINCT_SQL)
    assert staged == mono == cpu
    assert staged == [(b, len({a for a in range(97)
                               if any(i % 97 == a and i % 7 == b
                                      for i in range(4000))}))
                      for b in range(7)]


def test_global_distinct_byte_exact(s):
    staged, mono, cpu = _three_ways(s, "select count(distinct a) from xf")
    assert staged == mono == cpu == [(97,)]


def test_window_byte_exact(s):
    staged, mono, cpu = _three_ways(s, WINDOW_SQL)
    # identical INCLUDING row order: the host-routed buckets preserve
    # (source rank, source row) order exactly like the all_to_all
    assert staged == mono
    assert sorted(staged) == sorted(cpu)
    assert len(staged) == 4000


def test_skewed_keys_byte_exact(s):
    # ~90% of probe rows share one join key: one rank owns a giant
    # receive payload — padding under the shared recv cap, not drops
    s.execute("create table xk (k bigint, v bigint)")
    rows = ", ".join(
        f"({7 if i % 10 else i % 97}, {i % 13})" for i in range(3000))
    s.execute(f"insert into xk values {rows}")
    s.execute("analyze table xk")
    sql = ("select xk.k, count(*), sum(xd.w) from xk join xd "
           "on xk.k = xd.id group by xk.k order by xk.k")
    staged, mono, cpu = _three_ways(s, sql)
    assert staged == mono == cpu


def test_ci_collation_distinct_keys_byte_exact(s):
    # ci string keys hash by dictionary code after fold normalization —
    # equal-under-ci strings co-locate, so per-rank dedup stays exact.
    # The staged path must match the monolithic oracle byte-for-byte;
    # the CPU engine may pick a different (equally valid) case variant
    # as the group representative, so it is compared fold-insensitively
    sql = "select nm, count(distinct v) from xs group by nm order by nm"
    staged, mono, cpu = _three_ways(s, sql)
    assert staged == mono
    fold = lambda rs: sorted((nm.lower(), c) for nm, c in rs)
    assert fold(staged) == fold(cpu)
    assert len(staged) == 11        # 'abc0'..'abc10' fold together


# ---- satellite: one skewed rank costs ONE recompile -------------------------

def test_skewed_rank_overflow_is_one_exact_resize(s):
    # rank 0's slice is all one key (its bucket needs ~1000 rows); the
    # other ranks stay under the forced 512 cap. Only rank 0 must resize
    # — at exact need, one ladder charge — while ranks 1..3 keep their
    # cached stage-1 program and their committed checkpoints
    s.execute("create table xsk (k bigint, v bigint)")
    rows = ", ".join(
        f"({7 if i < 1000 else i % 89}, {i % 13})" for i in range(4000))
    s.execute(f"insert into xsk values {rows}")
    s.execute("analyze table xsk")
    sql = ("select xsk.k, count(*), sum(xd.w) from xsk join xd "
           "on xsk.k = xd.id group by xsk.k order by xsk.k")
    cpu = _run(s, sql, tidb_tpu_engine="off")
    out = _run(s, sql, tidb_tpu_exchange_bucket_cap=512)
    assert out == cpu
    esc = s.last_guard.escalation
    assert esc.by_kind.get("exchange:exact") == 1
    assert esc.recompiles == 1               # one charge, not per rank
    assert esc.slabs_rerun == 1              # only the skewed rank re-ran
    assert esc.slabs_reused == 3


# ---- chaos: per-rank recovery at the new failpoints -------------------------

@pytest.mark.chaos
@pytest.mark.parametrize("sql", [JOIN_SQL, DISTINCT_SQL],
                         ids=["join", "distinct"])
def test_checkpoint_loss_heals_one_rank(s, sql):
    # losing one rank's stage-1 bucket checkpoint re-runs only that
    # rank's partition program; the other ranks' checkpoints are reused
    cpu = _run(s, sql, tidb_tpu_engine="off")
    with failpoint.enabled("exchange-checkpoint-write",
                           raise_=ShardFailure("chaos: checkpoint lost"),
                           times=1):
        rows = _run(s, sql)
    assert rows == cpu
    esc = s.last_guard.escalation
    assert esc.shard_retries == 1
    assert esc.shards_rerun == 1
    assert esc.shards_reused == 3
    assert esc.degraded_mesh == 0


@pytest.mark.chaos
@pytest.mark.parametrize("sql", [JOIN_SQL, DISTINCT_SQL],
                         ids=["join", "distinct"])
def test_degraded_mesh_heals_one_rank(s, sql):
    # one rank's device fails its stage dispatch AND the same-device
    # retry: the rank re-dispatches onto a surviving device through the
    # exchange-degraded-replan / exchange-redispatch rungs, the query
    # completes byte-exactly on N-1 devices, and exactly ONE retryable
    # warning is left (per recovered rank, NOT per surviving rank)
    cpu = _run(s, sql, tidb_tpu_engine="off")
    failpoint.reset_counters()       # counts survive enabled() scopes
    with failpoint.counting():
        with failpoint.enabled("shard-step",
                               raise_=ShardFailure("chaos: device bad"),
                               after_hits=2, times=2):
            rows = _run(s, sql)
        hits = failpoint.counters()
    failpoint.reset_counters()
    assert rows == cpu
    assert hits.get("exchange-degraded-replan", 0) == 1
    assert hits.get("exchange-redispatch", 0) == 1
    esc = s.last_guard.escalation
    assert esc.degraded_mesh == 1
    assert esc.shards_rerun == 1
    assert esc.shards_reused == 3
    warns = s.query("SHOW WARNINGS").rows
    assert len(warns) == 1, warns
    level, code, msg = warns[0]
    assert level == "Warning" and int(code) == ShardFailure.code
    assert "degraded mesh" in msg and "re-dispatched" in msg


@pytest.mark.chaos
def test_fully_dead_rank_is_one_typed_error(s):
    # the rank fails on its own device AND on re-dispatch to a surviving
    # device: ONE typed retryable ShardFailure, never truncated rows —
    # and the session stays usable
    with failpoint.enabled("shard-step",
                           raise_=ShardFailure("chaos: device down"),
                           after_hits=2):
        with failpoint.enabled("exchange-redispatch",
                               raise_=ShardFailure("chaos: spare down")):
            with pytest.raises(ShardFailure) as ei:
                s.query(JOIN_SQL)
    assert ei.value.code == 1105
    assert ei.value.retryable
    assert "re-dispatch" in str(ei.value)
    cpu = _run(s, JOIN_SQL, tidb_tpu_engine="off")
    assert _run(s, JOIN_SQL) == cpu
    assert s.query("select count(*) from xf").scalar() == 4000


@pytest.mark.chaos
def test_degraded_warning_surfaces_once_in_explain_analyze(s):
    # EXPLAIN ANALYZE executes the statement: a degraded-mesh retry must
    # surface the retryable warning EXACTLY once (not per surviving
    # rank) and the runtime escalation summary must carry the per-shard
    # reuse split
    with failpoint.enabled("shard-step",
                           raise_=ShardFailure("chaos: device bad"),
                           after_hits=2, times=2):
        ea = s.query("EXPLAIN ANALYZE " + JOIN_SQL).rows
    text = "\n".join(" ".join(str(c) for c in r) for r in ea)
    assert "degraded_mesh=1" in text
    assert "shards_rerun=1" in text and "shards_reused=3" in text
    warns = [w for w in s.last_guard.warnings
             if int(w[1]) == ShardFailure.code]
    assert len(warns) == 1, warns
    assert "degraded mesh" in warns[0][2]


@pytest.mark.chaos
def test_no_hbm_growth_across_injected_faults(s):
    # abandoned device buffers must be delete()d BEFORE every retry /
    # re-dispatch uploads its generation: three injected faults in a row
    # must not grow the set of live device arrays
    import gc
    import jax
    cpu = _run(s, JOIN_SQL, tidb_tpu_engine="off")
    assert _run(s, JOIN_SQL) == cpu         # warm caches first
    gc.collect()
    base = len(jax.live_arrays())
    for _ in range(3):
        with failpoint.enabled("exchange-checkpoint-write",
                               raise_=ShardFailure("chaos: ckpt lost"),
                               times=1):
            assert _run(s, JOIN_SQL) == cpu
    gc.collect()
    assert len(jax.live_arrays()) <= base


def test_staged_exchange_gate_off_uses_monolithic(s):
    # the flag is a real gate: off → the monolithic shard_map program
    # runs (no staged-exchange checkpoint site is ever reached)
    failpoint.reset_counters()       # counts survive enabled() scopes
    with failpoint.counting():
        rows = _run(s, JOIN_SQL, tidb_tpu_dist_staged_exchange="off")
        hits = failpoint.counters()
    failpoint.reset_counters()
    assert hits.get("exchange-checkpoint-write", 0) == 0
    assert rows == _run(s, JOIN_SQL, tidb_tpu_engine="off")
