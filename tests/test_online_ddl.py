"""Online unique-index build: write_only → public state walk (ref:
ddl/index.go:519-527, ddl/ddl_worker.go:493). A writer racing CREATE
UNIQUE INDEX must either be rejected by the write-time check (the index
is write-only from the start of the build) or abort — never slip a
duplicate under a published unique index."""

import threading
import time

import pytest

from tidb_tpu.errors import DuplicateKeyError, DDLError
from tidb_tpu.session import Engine
from tidb_tpu.util import failpoint


@pytest.fixture()
def eng():
    return Engine()


def test_concurrent_writer_cannot_slip_a_duplicate(eng):
    s = eng.new_session()
    s.execute("CREATE TABLE ou (k BIGINT, v BIGINT)")
    s.execute("INSERT INTO ou VALUES " + ",".join(
        f"({i},{i})" for i in range(5000)))

    writer_err = []
    started = threading.Event()

    def racing_writer():
        w = eng.new_session()
        started.wait(5)
        try:
            # k=7 already exists: under the write-only index this must
            # raise ER 1062 even though the index is not public yet
            w.execute("INSERT INTO ou VALUES (7, 999)")
        except Exception as e:  # noqa: BLE001
            writer_err.append(e)

    t = threading.Thread(target=racing_writer)
    t.start()

    fired = []

    def pause_mid_backfill():
        if not fired:
            fired.append(1)
            started.set()
            time.sleep(0.4)      # writer races while validation runs

    failpoint.enable("index-backfill", hook=pause_mid_backfill)
    try:
        s.vars["tidb_ddl_reorg_batch_size"] = 512
        s.execute("CREATE UNIQUE INDEX uk ON ou (k)")
    finally:
        failpoint.disable("index-backfill")
        t.join(10)

    # invariant: the index is public AND no duplicate exists
    info = eng.catalog.info_schema.table("ou")
    ix = next(i for i in info.indexes if i.name == "uk")
    assert ix.state == "public"
    assert len(writer_err) == 1 and \
        isinstance(writer_err[0], DuplicateKeyError)
    assert s.query("SELECT COUNT(*) FROM ou WHERE k = 7").rows == [(1,)]
    # post-build writes keep enforcing
    with pytest.raises(DuplicateKeyError):
        s.execute("INSERT INTO ou VALUES (7, 1000)")


def test_write_only_index_invisible_to_readers(eng):
    s = eng.new_session()
    s.execute("CREATE TABLE wo (k BIGINT, v BIGINT, INDEX pub (v))")
    s.execute("INSERT INTO wo VALUES " + ",".join(
        f"({i},{i % 100})" for i in range(20000)))
    s.execute("ANALYZE TABLE wo")
    from tidb_tpu.catalog import IndexInfo
    eng.catalog.add_index("wo", IndexInfo("hidden", ("k",), True,
                                          state="write_only"))
    plan = "\n".join(str(r) for r in s.query(
        "EXPLAIN SELECT * FROM wo WHERE k = 5").rows)
    assert "hidden" not in plan          # readers must not use it
    # but the write path enforces it
    with pytest.raises(DuplicateKeyError):
        s.execute("INSERT INTO wo VALUES (5, 1)")


def test_failed_backfill_leaves_no_index(eng):
    s = eng.new_session()
    s.execute("CREATE TABLE fb (k BIGINT)")
    s.execute("INSERT INTO fb VALUES (1), (2), (2)")
    with pytest.raises(DuplicateKeyError):
        s.execute("CREATE UNIQUE INDEX uk ON fb (k)")
    info = eng.catalog.info_schema.table("fb")
    assert not any(i.name == "uk" for i in info.indexes)
    s.execute("INSERT INTO fb VALUES (1)")    # no phantom enforcement


def test_autocommit_writer_schema_lease(eng):
    """Review r5 #1: an AUTOCOMMIT statement that captured its TableInfo
    before the index published must abort at commit (the schema lease
    covers autocommit too), never slip an unchecked duplicate."""
    import numpy as np
    s = eng.new_session()
    s.execute("CREATE TABLE al (k BIGINT)")
    s.execute("INSERT INTO al VALUES (1), (2), (3)")
    w = eng.new_session()
    txn, auto = w._write_txn()
    assert auto
    # stage a duplicate the pre-publication way (no index seen)
    from tidb_tpu.chunk import Chunk
    info = eng.catalog.info_schema.table("al")
    txn.append(info.id, Chunk.from_rows(info.field_types, [(2,)]))
    # DDL lands while the statement is "in flight"
    s.execute("CREATE UNIQUE INDEX uk ON al (k)")
    from tidb_tpu.errors import TxnError
    with pytest.raises(TxnError, match="schema is changed"):
        w._commit_auto(txn)
    assert s.query("SELECT COUNT(*) FROM al WHERE k = 2").rows == [(1,)]
