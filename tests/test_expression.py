"""Expression engine differential tests.

Three-way oracle (mirrors the reference's builtin_*_vec_test.go pattern,
SURVEY A.6): a row-at-a-time Python interpreter with explicit SQL NULL
semantics is ground truth; the host numpy evaluator and the jit-compiled
device evaluator must both match it exactly.
"""

import math
from decimal import Decimal

import numpy as np
import pytest

from tidb_tpu import types as T
from tidb_tpu.chunk import Chunk
from tidb_tpu.chunk.device import from_device, to_device
from tidb_tpu.expression import ColumnRef, cast, func, lit
from tidb_tpu.expression.runner import (eval_on_chunk, eval_on_device,
                                        filter_mask)

RNG = np.random.default_rng(42)
N = 500


def make_chunk():
    fts = [T.bigint(), T.bigint(), T.double(), T.decimal(12, 2),
           T.decimal(12, 2), T.varchar(10)]
    ints1 = [int(RNG.integers(-100, 100)) if RNG.random() > 0.1 else None
             for _ in range(N)]
    ints2 = [int(RNG.integers(-10, 10)) if RNG.random() > 0.1 else None
             for _ in range(N)]
    dbls = [float(np.round(RNG.normal(), 3)) if RNG.random() > 0.1 else None
            for _ in range(N)]
    dec1 = [Decimal(int(RNG.integers(-10_000, 10_000))) / 100
            if RNG.random() > 0.1 else None for _ in range(N)]
    dec2 = [Decimal(int(RNG.integers(1, 500))) / 100
            if RNG.random() > 0.1 else None for _ in range(N)]
    strs = [RNG.choice(["apple", "banana", "cherry", "date", "Fig", ""])
            if RNG.random() > 0.1 else None for _ in range(N)]
    return Chunk.from_columns_data(fts, [ints1, ints2, dbls, dec1, dec2, strs])


CH = make_chunk()
C = {i: ColumnRef(i, ft) for i, ft in enumerate(CH.field_types)}


def scalar_oracle(fn):
    """Row-at-a-time evaluation with None-propagation done by `fn` itself."""
    return [fn(*CH.row(i)) for i in range(CH.num_rows)]


def run_both(expr, approx=False):
    """Evaluate on host and device; return both as python lists."""
    host = eval_on_chunk([expr], CH).columns[0].to_pylist()
    dev_chunk = eval_on_device([expr], to_device(CH))
    dev = from_device(dev_chunk, CH.num_rows).columns[0].to_pylist()
    if approx:
        for h, d in zip(host, dev):
            assert (h is None) == (d is None)
            if h is not None:
                assert math.isclose(h, d, rel_tol=1e-5, abs_tol=1e-6), (h, d)
    else:
        assert host == dev, _diff(host, dev)
    return host


def _diff(a, b):
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            return f"row {i}: host={x!r} device={y!r}"
    return "length mismatch"


def check(expr, oracle_fn, approx=False):
    got = run_both(expr, approx=approx)
    want = scalar_oracle(oracle_fn)
    if approx:
        for g, w in zip(got, want):
            assert (g is None) == (w is None), (g, w)
            if g is not None:
                assert math.isclose(g, w, rel_tol=1e-5, abs_tol=1e-6), (g, w)
    else:
        assert got == want, _diff(got, want)


# ---- arithmetic -----------------------------------------------------------

def test_int_plus_minus_mul():
    check(func("plus", C[0], C[1]),
          lambda a, b, *_: None if a is None or b is None else a + b)
    check(func("minus", C[0], C[1]),
          lambda a, b, *_: None if a is None or b is None else a - b)
    check(func("mul", C[0], C[1]),
          lambda a, b, *_: None if a is None or b is None else a * b)


def test_decimal_plus_and_mul():
    check(func("plus", C[3], C[4]),
          lambda a, b, c, d, e, f: None if d is None or e is None else d + e)
    # decimal*decimal: scale adds (2+2=4)
    expr = func("mul", C[3], C[4])
    assert expr.ftype.scale == 4
    check(expr, lambda a, b, c, d, e, f: None if d is None or e is None
          else (d * e).quantize(Decimal("0.0001")))


def test_div_returns_double_and_null_on_zero():
    check(func("div", C[0], C[1]),
          lambda a, b, *_: None if a is None or b is None or b == 0 else a / b,
          approx=True)


def test_intdiv_and_mod_truncate_toward_zero():
    check(func("intdiv", C[0], C[1]),
          lambda a, b, *_: None if a is None or b is None or b == 0
          else int(a / b) if b else None)
    check(func("mod", C[0], C[1]),
          lambda a, b, *_: None if a is None or b is None or b == 0
          else a - int(a / b) * b)


def test_mixed_decimal_int_arith():
    check(func("plus", C[3], C[1]),
          lambda a, b, c, d, *_: None if d is None or b is None else d + b)


# ---- comparisons ----------------------------------------------------------

def test_numeric_comparisons():
    for op, py in [("eq", lambda x, y: x == y), ("ne", lambda x, y: x != y),
                   ("lt", lambda x, y: x < y), ("le", lambda x, y: x <= y),
                   ("gt", lambda x, y: x > y), ("ge", lambda x, y: x >= y)]:
        check(func(op, C[0], C[1]),
              lambda a, b, *_, _py=py: None if a is None or b is None
              else int(_py(a, b)))


def test_decimal_vs_int_comparison():
    check(func("lt", C[3], C[1]),
          lambda a, b, c, d, *_: None if d is None or b is None
          else int(d < b))


def test_string_eq_constant_device_rank_trick():
    check(func("eq", C[5], lit("banana")),
          lambda *r: None if r[5] is None else int(r[5] == "banana"))
    check(func("ne", C[5], lit("banana")),
          lambda *r: None if r[5] is None else int(r[5] != "banana"))


def test_string_order_vs_constant():
    check(func("lt", C[5], lit("cherry")),
          lambda *r: None if r[5] is None else int(r[5] < "cherry"))
    check(func("ge", C[5], lit("banana")),
          lambda *r: None if r[5] is None else int(r[5] >= "banana"))
    # flipped: const < col
    check(func("lt", lit("banana"), C[5]),
          lambda *r: None if r[5] is None else int("banana" < r[5]))


def test_string_eq_absent_constant():
    check(func("eq", C[5], lit("zzz-not-present")),
          lambda *r: None if r[5] is None else 0)


def test_nulleq():
    check(func("nulleq", C[0], C[1]),
          lambda a, b, *_: int(a == b) if a is not None and b is not None
          else int(a is None and b is None))


# ---- logic (Kleene) -------------------------------------------------------

def _tri_and(x, y):
    if x == 0 or y == 0:
        return 0
    if x is None or y is None:
        return None
    return 1


def _tri_or(x, y):
    if (x is not None and x != 0) or (y is not None and y != 0):
        return 1
    if x is None or y is None:
        return None
    return 0


def test_three_valued_and_or():
    gt = func("gt", C[0], lit(0))
    lt = func("lt", C[1], lit(0))

    def _gt0(a):
        return None if a is None else int(a > 0)

    def _lt0(b):
        return None if b is None else int(b < 0)

    check(func("and", gt, lt),
          lambda a, b, *_: _tri_and(_gt0(a), _lt0(b)))
    check(func("or", gt, lt),
          lambda a, b, *_: _tri_or(_gt0(a), _lt0(b)))
    check(func("not", gt),
          lambda a, *_: None if a is None else int(not (a > 0)))


def test_isnull():
    check(func("isnull", C[0]), lambda a, *_: int(a is None))


def test_filter_mask_null_excluded():
    mask = filter_mask(func("gt", C[0], lit(0)), CH)
    want = np.array([r[0] is not None and r[0] > 0 for r in CH.rows()])
    assert (mask == want).all()


# ---- control --------------------------------------------------------------

def test_if_ifnull_coalesce():
    check(func("if", func("gt", C[0], lit(0)), C[0], C[1]),
          lambda a, b, *_: (a if (a is not None and a > 0) else b))
    check(func("ifnull", C[0], C[1]),
          lambda a, b, *_: a if a is not None else b)
    check(func("coalesce", C[0], C[1], lit(7)),
          lambda a, b, *_: a if a is not None else (b if b is not None else 7))


def test_case_when():
    expr = func("case",
                func("lt", C[0], lit(-50)), lit(-1),
                func("lt", C[0], lit(50)), lit(0),
                lit(1))

    def oracle(a, *_):
        if a is None:
            return 1  # both whens NULL → else
        if a < -50:
            return -1
        if a < 50:
            return 0
        return 1

    check(expr, oracle)


def test_case_without_else_yields_null():
    expr = func("case", func("gt", C[0], lit(0)), lit(1))
    check(expr, lambda a, *_: 1 if (a is not None and a > 0) else None)


# ---- casts ----------------------------------------------------------------

def test_cast_decimal_to_double_and_back():
    check(cast(C[3], T.double()),
          lambda a, b, c, d, *_: None if d is None else float(d), approx=True)
    check(cast(C[0], T.decimal(12, 2)),
          lambda a, *_: None if a is None else Decimal(a).quantize(
              Decimal("0.01")))


def test_cast_decimal_rescale():
    check(cast(C[3], T.decimal(12, 4)),
          lambda a, b, c, d, *_: None if d is None else d.quantize(
              Decimal("0.0001")))


# ---- math -----------------------------------------------------------------

def test_abs_ceil_floor_round_decimal():
    check(func("abs", C[3]),
          lambda a, b, c, d, *_: None if d is None else abs(d))
    check(func("ceil", C[3]),
          lambda a, b, c, d, *_: None if d is None else Decimal(
              math.ceil(d)))
    check(func("floor", C[3]),
          lambda a, b, c, d, *_: None if d is None else Decimal(
              math.floor(d)))


def test_round_half_away_from_zero():
    expr = func("round", C[3])

    def oracle(a, b, c, d, *_):
        if d is None:
            return None
        q = int(abs(d) * 100 + 50) // 100
        return Decimal(q if d >= 0 else -q)

    check(expr, oracle)


def test_sqrt_negative_is_null():
    check(func("sqrt", C[0]),
          lambda a, *_: None if a is None or a < 0 else math.sqrt(a),
          approx=True)


# ---- strings (dictionary pushdown) ----------------------------------------

def test_string_length_upper_on_device():
    check(func("length", C[5]),
          lambda *r: None if r[5] is None else len(r[5]))
    check(func("upper", C[5]),
          lambda *r: None if r[5] is None else r[5].upper())
    check(func("lower", C[5]),
          lambda *r: None if r[5] is None else r[5].lower())


def test_like():
    check(func("like", C[5], lit("%an%")),
          lambda *r: None if r[5] is None else int("an" in r[5]))
    check(func("like", C[5], lit("_pple")),
          lambda *r: None if r[5] is None else
          int(len(r[5]) == 5 and r[5].endswith("pple")))


def test_in_strings_and_ints():
    check(func("in", C[5], lit("apple"), lit("Fig")),
          lambda *r: None if r[5] is None else int(r[5] in ("apple", "Fig")))
    check(func("in", C[0], lit(1), lit(2), lit(99)),
          lambda a, *_: None if a is None else int(a in (1, 2, 99)))


# ---- temporal -------------------------------------------------------------

def test_date_parts():
    import datetime
    dates = [datetime.date(1970, 1, 1), datetime.date(2024, 2, 29),
             datetime.date(1969, 7, 20), datetime.date(9999, 12, 31),
             datetime.date(1900, 3, 1), None]
    ch = Chunk.from_columns_data([T.date()], [dates])
    col = ColumnRef(0, T.date())
    for part, attr in [("year", "year"), ("month", "month"),
                       ("dayofmonth", "day")]:
        host = eval_on_chunk([func(part, col)], ch).columns[0].to_pylist()
        dev = from_device(eval_on_device([func(part, col)], to_device(ch)),
                          ch.num_rows).columns[0].to_pylist()
        want = [None if d is None else getattr(d, attr) for d in dates]
        assert host == want == dev, (part, host, dev, want)


# ---- misc -----------------------------------------------------------------

def test_constant_folding_inputs():
    expr = func("plus", lit(2), func("mul", lit(3), lit(4)))
    assert expr.is_constant()
    out = eval_on_chunk([expr], CH).columns[0].to_pylist()
    assert all(v == 14 for v in out)


def test_references():
    expr = func("and", func("gt", C[0], lit(0)), func("lt", C[2], C[3]))
    assert expr.references() == [0, 2, 3]


def test_decimal_div_descales_once():
    """Regression: decimal/int and decimal/double divided an extra 10^scale."""
    check(func("div", C[3], C[1]),
          lambda a, b, c, d, *_: None if d is None or b is None or b == 0
          else float(d) / b, approx=True)
    check(func("div", C[3], C[4]),
          lambda a, b, c, d, e, f: None if d is None or e is None or e == 0
          else float(d) / float(e), approx=True)
    check(func("div", C[3], C[2]),
          lambda a, b, c, d, *_: None if d is None or c is None or c == 0
          else float(d) / c, approx=True)
