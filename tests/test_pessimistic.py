"""Pessimistic transactions (row locks, SELECT … FOR UPDATE) and
AS OF TIMESTAMP historical reads (ref: session/txn.go pessimistic mode,
the TiKV lock CF, and the tidb_snapshot/stale-read path; GC safepoint
discipline of store/gcworker)."""

import threading
import time

import numpy as np
import pytest

from tidb_tpu.errors import TxnError
from tidb_tpu.session import Engine


@pytest.fixture()
def eng():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE acct (id BIGINT, bal BIGINT)")
    s.execute("INSERT INTO acct VALUES (1, 100), (2, 200), (3, 300)")
    return eng


def test_select_for_update_blocks_conflicting_dml(eng):
    s1, s2 = eng.new_session(), eng.new_session()
    s2.vars["innodb_lock_wait_timeout"] = 0.2
    s1.execute("BEGIN PESSIMISTIC")
    rows = s1.query("SELECT * FROM acct WHERE id = 1 FOR UPDATE").rows
    assert rows == [(1, 100)]
    s2.execute("BEGIN PESSIMISTIC")
    with pytest.raises(TxnError, match="Lock wait timeout"):
        s2.execute("UPDATE acct SET bal = 0 WHERE id = 1")
    # a different row is not blocked
    s2.execute("UPDATE acct SET bal = 201 WHERE id = 2")
    s2.execute("COMMIT")
    s1.execute("COMMIT")
    # after release the row is free again
    s2.execute("BEGIN PESSIMISTIC")
    s2.execute("UPDATE acct SET bal = 101 WHERE id = 1")
    s2.execute("COMMIT")
    assert eng.new_session().query(
        "SELECT bal FROM acct WHERE id = 1").rows == [(101,)]


def test_lock_wait_resolves_on_commit(eng):
    s1, s2 = eng.new_session(), eng.new_session()
    s1.execute("BEGIN PESSIMISTIC")
    s1.execute("UPDATE acct SET bal = bal + 1 WHERE id = 1")
    done = {}

    def waiter():
        s2.execute("BEGIN PESSIMISTIC")
        s2.execute("UPDATE acct SET bal = bal + 10 WHERE id = 1")
        s2.execute("COMMIT")
        done["ok"] = True

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.15)          # let the waiter hit the lock
    assert "ok" not in done
    s1.execute("COMMIT")
    t.join(timeout=10)
    assert done.get("ok")
    # both increments landed (no lost update)
    assert eng.new_session().query(
        "SELECT bal FROM acct WHERE id = 1").rows == [(111,)]


def test_rollback_releases_locks(eng):
    s1, s2 = eng.new_session(), eng.new_session()
    s2.vars["innodb_lock_wait_timeout"] = 0.2
    s1.execute("BEGIN PESSIMISTIC")
    s1.query("SELECT * FROM acct FOR UPDATE")
    s1.execute("ROLLBACK")
    s2.execute("BEGIN PESSIMISTIC")
    s2.execute("UPDATE acct SET bal = 1 WHERE id = 3")
    s2.execute("COMMIT")


def test_optimistic_txn_does_not_lock(eng):
    s1, s2 = eng.new_session(), eng.new_session()
    s2.vars["innodb_lock_wait_timeout"] = 0.2
    s1.execute("BEGIN")                 # optimistic default
    s1.execute("UPDATE acct SET bal = 7 WHERE id = 1")
    # optimistic: no lock held, the other session proceeds…
    s2.execute("UPDATE acct SET bal = 8 WHERE id = 1")
    # …and the first committer won: s1's commit now conflicts
    with pytest.raises(TxnError, match="conflict"):
        s1.execute("COMMIT")


def test_txn_mode_variable(eng):
    s = eng.new_session()
    s.vars["tidb_txn_mode"] = "pessimistic"
    s.execute("BEGIN")
    assert s.txn.pessimistic
    s.execute("ROLLBACK")
    s.execute("BEGIN OPTIMISTIC")
    assert not s.txn.pessimistic
    s.execute("ROLLBACK")


def test_for_update_preserves_repeatable_read(eng):
    # regression: FOR UPDATE must not shift the txn's start-ts view for
    # later plain reads
    s1, s2 = eng.new_session(), eng.new_session()
    s1.execute("BEGIN PESSIMISTIC")
    assert s1.query("SELECT COUNT(*) FROM acct").rows == [(3,)]
    s2.execute("INSERT INTO acct VALUES (9, 900)")
    # FOR UPDATE itself reads the LATEST committed version…
    got = s1.query("SELECT COUNT(*) FROM acct FOR UPDATE").rows
    assert got == [(4,)]
    # …but plain reads stay at the transaction's start view
    assert s1.query("SELECT COUNT(*) FROM acct").rows == [(3,)]
    s1.execute("COMMIT")


def test_stale_retry_locks_release(eng):
    # rows locked under a stale snapshot but no longer matching after the
    # for-update-ts refresh must not stay locked
    s1, s2, s3 = (eng.new_session() for _ in range(3))
    s3.vars["innodb_lock_wait_timeout"] = 0.2
    s1.execute("BEGIN PESSIMISTIC")
    s1.query("SELECT * FROM acct WHERE id = 1 FOR UPDATE")
    s1.execute("COMMIT")
    # id=1 must be free now for another pessimistic writer
    s3.execute("BEGIN PESSIMISTIC")
    s3.execute("UPDATE acct SET bal = 5 WHERE id = 1")
    s3.execute("COMMIT")


# ---- AS OF TIMESTAMP historical reads --------------------------------------


def test_as_of_timestamp_reads_history(eng):
    import datetime
    s = eng.new_session()
    time.sleep(0.02)
    t0 = datetime.datetime.now()
    time.sleep(0.02)
    s.execute("UPDATE acct SET bal = 999 WHERE id = 1")
    s.execute("INSERT INTO acct VALUES (4, 400)")
    assert s.query("SELECT bal FROM acct WHERE id = 1").rows == [(999,)]
    old = s.query(f"SELECT bal FROM acct AS OF TIMESTAMP '{t0}' "
                  "WHERE id = 1").rows
    assert old == [(100,)]
    assert s.query(f"SELECT COUNT(*) FROM acct AS OF TIMESTAMP '{t0}'"
                   ).rows == [(3,)]


def test_as_of_before_safepoint_errors(eng):
    s = eng.new_session()
    with pytest.raises(TxnError, match="safepoint"):
        s.query("SELECT * FROM acct AS OF TIMESTAMP '1999-01-01 00:00:00'")


def test_as_of_rejected_in_txn(eng):
    import datetime
    s = eng.new_session()
    t0 = datetime.datetime.now()
    s.execute("BEGIN")
    with pytest.raises(TxnError, match="not allowed"):
        s.query(f"SELECT * FROM acct AS OF TIMESTAMP '{t0}'")
    s.execute("ROLLBACK")


def test_deadlock_detected_in_milliseconds(eng):
    # opposite-order locking: the wait-for cycle must abort one waiter
    # with ER 1213 (unistore/tikv/detector.go), NOT stall both to the
    # full innodb_lock_wait_timeout
    from tidb_tpu.errors import DeadlockError
    s1, s2 = eng.new_session(), eng.new_session()
    for s in (s1, s2):
        s.vars["innodb_lock_wait_timeout"] = 30.0   # long: detector must win
    s1.execute("BEGIN PESSIMISTIC")
    s2.execute("BEGIN PESSIMISTIC")
    s1.execute("UPDATE acct SET bal = 1 WHERE id = 1")
    s2.execute("UPDATE acct SET bal = 2 WHERE id = 2")
    errs = []

    def cross(sess, sql):
        try:
            sess.execute(sql)
        except TxnError as e:
            errs.append(e)
            sess.execute("ROLLBACK")

    t = threading.Thread(
        target=cross, args=(s1, "UPDATE acct SET bal = 1 WHERE id = 2"))
    t0 = time.perf_counter()
    t.start()
    time.sleep(0.1)        # let s1 enter the wait
    cross(s2, "UPDATE acct SET bal = 2 WHERE id = 1")
    t.join(timeout=10)
    elapsed = time.perf_counter() - t0
    assert len(errs) == 1, errs            # exactly ONE victim
    assert isinstance(errs[0], DeadlockError)
    assert errs[0].code == 1213
    assert "Deadlock found" in str(errs[0])
    assert elapsed < 5, elapsed            # ms-scale, not lock_wait_timeout


def test_deadlock_error_reaches_wire_code(eng):
    from tidb_tpu.errors import DeadlockError
    assert DeadlockError("x").code == 1213
    assert issubclass(DeadlockError, TxnError)   # drivers matching 1205 path
