"""Chunk format + codec + device marshalling tests.

Oracle pattern mirrors util/chunk/chunk_test.go and codec_test.go.
"""

import datetime
from decimal import Decimal

import numpy as np
import pytest

from tidb_tpu import types as T
from tidb_tpu.chunk import Chunk, Column, iter_chunks
from tidb_tpu.chunk.codec import decode_chunk, encode_chunk


def make_mixed_chunk():
    fts = [T.bigint(), T.double(), T.decimal(10, 2), T.varchar(20), T.date()]
    data = [
        [1, 2, None, 4, 5],
        [1.5, None, 2.5, -3.0, 0.0],
        [Decimal("12.34"), Decimal("-0.01"), None, Decimal("99.99"), Decimal("0")],
        ["alpha", "beta", None, "", "delta"],
        ["2024-01-01", None, "1999-12-31", "1970-01-01", "2024-06-30"],
    ]
    return Chunk.from_columns_data(fts, data)


def test_basic_shape_and_access():
    ch = make_mixed_chunk()
    assert ch.num_rows == 5 and ch.num_cols == 5
    assert ch.row(0) == (1, 1.5, Decimal("12.34"), "alpha",
                         datetime.date(2024, 1, 1))
    assert ch.row(1)[1] is None and ch.row(2)[0] is None
    assert ch.columns[0].null_count == 1
    assert ch.columns[3].get(2) is None


def test_decimal_encoding_is_scaled_int64():
    col = Column.from_list(T.decimal(10, 2), [Decimal("12.34"), None, 1])
    assert col.values.dtype == np.int64
    assert col.values[0] == 1234 and col.values[2] == 100
    assert col.get(0) == Decimal("12.34") and col.get(1) is None


def test_filter_take_concat_slice():
    ch = make_mixed_chunk()
    f = ch.filter(np.array([True, False, True, False, True]))
    assert f.num_rows == 3 and f.row(1)[3] is None
    t = ch.take(np.array([4, 0]))
    assert t.row(0)[0] == 5 and t.row(1)[0] == 1
    c = Chunk.concat([ch, ch])
    assert c.num_rows == 10 and c.row(7) == ch.row(2)
    s = ch.slice(1, 3)
    assert s.num_rows == 2 and s.row(0) == ch.row(1)
    parts = list(iter_chunks(c, 4))
    assert [p.num_rows for p in parts] == [4, 4, 2]


def test_codec_roundtrip():
    ch = make_mixed_chunk()
    buf = encode_chunk(ch)
    back = decode_chunk(buf, ch.field_types)
    assert back.rows() == ch.rows()


def test_codec_roundtrip_empty_and_allnull():
    fts = [T.bigint(), T.varchar()]
    empty = Chunk.from_columns_data(fts, [[], []])
    assert decode_chunk(encode_chunk(empty), fts).num_rows == 0
    allnull = Chunk([Column.all_null(fts[0], 3), Column.all_null(fts[1], 3)])
    back = decode_chunk(encode_chunk(allnull), fts)
    assert back.rows() == [(None, None)] * 3


def test_device_roundtrip():
    from tidb_tpu.chunk.device import from_device, to_device

    ch = make_mixed_chunk()
    d = to_device(ch)
    assert d.capacity == 1024 and int(d.n_rows) == 5
    mask = np.asarray(d.row_mask())
    assert mask.sum() == 5 and mask[:5].all()
    back = from_device(d)
    assert back.rows() == ch.rows()


def test_device_bucketing():
    from tidb_tpu.chunk.device import bucket_capacity

    assert bucket_capacity(1) == 1024
    assert bucket_capacity(1024) == 1024
    assert bucket_capacity(1025) == 2048
    assert bucket_capacity(100_000) == 131072


def test_temporal_types():
    col = Column.from_list(T.datetime(), ["2024-01-02T03:04:05", None])
    assert col.get(0) == datetime.datetime(2024, 1, 2, 3, 4, 5)
    dur = Column.from_list(T.FieldType(T.TypeKind.TIME),
                           [datetime.timedelta(hours=1)])
    assert dur.get(0) == datetime.timedelta(hours=1)


def test_device_chunk_flows_through_jit():
    """Dictionaries must not poison the jit cache (pytree aux regression)."""
    from tidb_tpu.chunk.device import from_device, to_device
    from tidb_tpu.ops.jax_env import jax, jnp

    @jax.jit
    def first_col_values(d):
        return d.columns[0].values + 0

    ch1 = Chunk.from_columns_data([T.bigint(), T.varchar()],
                                  [[1, 2], ["a", "b"]])
    ch2 = Chunk.from_columns_data([T.bigint(), T.varchar()],
                                  [[3, 4], ["x", "y"]])
    v1 = first_col_values(to_device(ch1))
    v2 = first_col_values(to_device(ch2))  # second call: cached trace
    assert int(v1[0]) == 1 and int(v2[0]) == 3

    @jax.jit
    def identity(d):
        return d

    out = identity(to_device(ch2))
    # dictionary is dropped through jit; reattach host-side
    out.columns[1] = out.columns[1].with_dictionary(
        np.array(["x", "y"], dtype=object))
    assert from_device(out).rows() == ch2.rows()


def test_fixed_dictionary_miss_decodes_to_null():
    from tidb_tpu.chunk.device import DeviceChunk, from_device, to_device_column
    from tidb_tpu.ops.jax_env import jnp

    col = Column.from_list(T.varchar(), ["a", "zzz"])
    dc = to_device_column(col, 1024, dictionary=np.array(["a", "b"], dtype=object))
    d = DeviceChunk([dc], jnp.asarray(2, dtype=jnp.int32))
    assert from_device(d).rows() == [("a",), (None,)]


def test_datetime_microsecond_precision_far_future():
    ft = T.datetime()
    v = datetime.datetime(9999, 12, 31, 23, 59, 59, 999999)
    assert ft.decode_value(ft.encode_value(v)) == v
