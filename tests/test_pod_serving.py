"""Pod-scale serving: per-device HBM caches, locality-aware placement,
replication vs partitioning, and work stealing (over the conftest's
forced 8-device CPU mesh, where `tidb_tpu_device_queues=auto` activates
the pool for the whole suite).

Pins the PR's acceptance contract:

* locality routing: a repeat digest routes to the device already
  holding its tables — even when that queue is deeper — so a warm dim
  table is uploaded exactly ONCE pool-wide (no thundering replicas);
* replication: a second device touching the same small table lazily
  builds its own replica, counted by `tidb_tpu_table_replicas_total`
  and visible to `locate_tables`;
* partitioning: a fact table past `tidb_tpu_partition_min_rows` gets
  ONE pod-wide entry (cache key device -1) whose slab ranges spread
  contiguously across the mesh — each resident slab's buffers live on
  exactly its owner device, never double-resident — and the routed
  result stays byte-exact vs the CPU oracle;
* work stealing: an idle sibling drains a 16-deep admission queue while
  the home device stays held (every waiter migrates, none lost, none
  run twice);
* lifecycle on a STOLEN waiter: KILL lands as a typed 1317 while the
  migrated statement is queued on its new device;
* steal-migrate fault: an injected fault at the handoff re-queues the
  waiter on its HOME device (backoff charged) — the statement still
  runs exactly once and answers the oracle.
"""

import threading
import time

import pytest

from tidb_tpu.errors import TiDBTPUError
from tidb_tpu.executor import device_cache as dc
from tidb_tpu.executor.scheduler import POOL
from tidb_tpu.session import Engine
from tidb_tpu.util import failpoint
from tidb_tpu.util.observability import REGISTRY

DIM_SQL = "SELECT g, COUNT(*), SUM(a) FROM dim GROUP BY g ORDER BY g"


@pytest.fixture()
def pod():
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE dim (a BIGINT, g BIGINT)")
    s.execute("INSERT INTO dim VALUES " +
              ", ".join(f"({i}, {i % 5})" for i in range(600)))

    def new_session():
        ss = eng.new_session()
        ss.vars["tidb_tpu_engine"] = "on"
        ss.vars["tidb_tpu_row_threshold"] = 1
        return ss

    yield eng, new_session
    failpoint.disable_all()
    eng.close()


def _counter(name: str, dev: int):
    return REGISTRY.counters.get((name, (("device", str(dev)),)), 0)


def _table_keys(eng, name: str):
    tid = eng.catalog.info_schema.table(name).id
    return [k for k in dc._CACHE
            if k[1] == id(eng.store) and k[2] == tid]


def _dev_of(a):
    """The single jax device an array is committed to."""
    ds = getattr(a, "devices", None)
    if callable(ds):
        got = list(a.devices())
        assert len(got) == 1
        return got[0]
    return a.device


# ---------------------------------------------------------------------------
# locality routing + lazy replication
# ---------------------------------------------------------------------------

def test_repeat_digest_routes_to_resident_device(pod):
    """Warm digest → locality placement beats least-queue-depth: the
    statement waits for device 0 (where its table lives) instead of
    hopping to an idle sibling, so the dim table uploads exactly once
    pool-wide."""
    eng, new_session = pod
    s = new_session()
    assert s.query(DIM_SQL).rows  # cold: all queues idle → device 0
    assert s.last_guard.device_index == 0
    assert POOL.size() >= 8       # auto sized the pool to the mesh
    keys = _table_keys(eng, "dim")
    assert len(keys) == 1 and keys[0][0] == 0

    oracle = s.query(DIM_SQL).rows
    result: dict = {}

    def rerun():
        try:
            result["rows"] = s.query(DIM_SQL).rows
        except TiDBTPUError as e:  # pragma: no cover — must not happen
            result["err"] = e

    # device 0 busy, devices 1..7 idle: least-depth would route away,
    # locality must NOT
    POOL.schedulers[0].acquire(conn_id=-1)
    try:
        th = threading.Thread(target=rerun, daemon=True)
        th.start()
        deadline = time.monotonic() + 10.0
        while POOL.schedulers[0].queue_depth() < 2:
            assert time.monotonic() < deadline, "repeat never queued"
            time.sleep(0.005)
    finally:
        POOL.schedulers[0].release()
    th.join(10.0)
    assert not th.is_alive() and result.get("rows") == oracle
    assert s.last_guard.device_index == 0
    # still exactly one resident copy — routing made replication moot
    assert _table_keys(eng, "dim") == keys


def test_cold_digest_on_busy_device_builds_replica(pod):
    """A DIFFERENT digest over the same table, placed while device 0 is
    busy, lands on an idle sibling and lazily replicates the table
    there — counted and locatable."""
    eng, new_session = pod
    s = new_session()
    s.query(DIM_SQL)                      # dim resident on device 0
    tid = eng.catalog.info_schema.table("dim").id
    before = _counter("tidb_tpu_table_replicas_total", 1)

    s2 = new_session()
    cold = "SELECT g, COUNT(*) FROM dim WHERE a < 500 GROUP BY g"
    result: dict = {}

    def run_cold():
        try:
            result["rows"] = s2.query(cold).rows
        except TiDBTPUError as e:  # pragma: no cover
            result["err"] = e

    POOL.schedulers[0].acquire(conn_id=-1)
    try:
        th = threading.Thread(target=run_cold, daemon=True)
        th.start()
        th.join(10.0)
    finally:
        POOL.schedulers[0].release()
    assert not th.is_alive() and "rows" in result
    assert s2.last_guard.device_index == 1    # least depth, lowest idx
    devs = {k[0] for k in _table_keys(eng, "dim")}
    assert devs == {0, 1}, devs
    assert dc.locate_tables([tid]).get(tid) == {0, 1}
    assert _counter("tidb_tpu_table_replicas_total", 1) == before + 1
    assert dc.replica_overhead_bytes() > 0


# ---------------------------------------------------------------------------
# pod-partitioned fact table
# ---------------------------------------------------------------------------

def test_partitioned_fact_slabs_spread_single_resident(pod):
    """A fact table past tidb_tpu_partition_min_rows gets ONE pod-wide
    cache entry: contiguous slab ranges owned per device, each resident
    slab's buffers on exactly its owner, results byte-exact vs CPU."""
    import jax
    eng, new_session = pod
    s = new_session()
    s.execute("CREATE TABLE facts (a BIGINT, g BIGINT)")
    for base in range(0, 8192, 1024):
        s.execute("INSERT INTO facts VALUES " + ", ".join(
            f"({i}, {i % 7})" for i in range(base, base + 1024)))
    s.vars["tidb_tpu_max_slab_rows"] = 1024
    s.vars["tidb_tpu_partition_min_rows"] = 1000

    sel = "SELECT COUNT(*), SUM(a) FROM facts WHERE a >= 1024"
    full = "SELECT g, COUNT(*), SUM(a) FROM facts GROUP BY g ORDER BY g"
    s.vars["tidb_tpu_engine"] = "off"
    oracle = {q: s.query(q).rows for q in (sel, full)}
    s.vars["tidb_tpu_engine"] = "on"
    for q in (sel, full):
        assert s.query(q).rows == oracle[q], q

    keys = _table_keys(eng, "facts")
    assert len(keys) == 1 and keys[0][0] == -1, keys
    ent = dc._CACHE[keys[0]]
    owners = ent.owners
    assert owners is not None and len(owners) == 8
    # contiguous non-decreasing ranges over the mesh
    assert owners == sorted(owners) and len(set(owners)) > 1
    devs = jax.devices()
    for i, slabs in ent.dev.items():
        for sl, t in enumerate(slabs):
            if t is None:
                continue                  # cold-pruned hole
            for arr in t:
                assert _dev_of(arr) == devs[owners[sl]], \
                    f"col {i} slab {sl} off its owner device"


# ---------------------------------------------------------------------------
# work stealing
# ---------------------------------------------------------------------------

def test_steal_drains_deep_queue_while_home_idles(pod):
    """16 batch statements parked on a held device 0 all migrate to
    idle siblings — via the release-into-empty pull chain and the
    patience-based self-spill — the queue drains with device 0 never
    granting, and every result matches the oracle."""
    eng, new_session = pod
    warm = new_session()
    oracle = warm.query(DIM_SQL).rows      # dim → device 0, digest warm
    dev0, dev1 = POOL.schedulers[0], POOL.schedulers[1]
    steals0 = sum(s.stats()["steals"] for s in POOL.schedulers)
    ctr0 = sum(_counter("tidb_tpu_work_steals_total", d)
               for d in range(POOL.size()))
    adm0 = dev0.stats()["admissions"]

    n = 16
    sessions = [new_session() for _ in range(n)]
    results: dict = {}

    def worker(i):
        try:
            results[i] = sessions[i].query(DIM_SQL).rows
        except TiDBTPUError as e:
            results[i] = ("error", getattr(e, "code", None))

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n)]
    dev0.acquire(conn_id=-1)
    try:
        for th in threads:
            th.start()
        # kick the pull chain immediately (before the patience spill):
        # device 1's release-into-empty steals the first parked waiter
        deadline = time.monotonic() + 15.0
        while True:
            with dev0._cv:
                if dev0._stealable >= 1:
                    break
            assert time.monotonic() < deadline, "no waiter parked"
            time.sleep(0.005)
        dev1.acquire(conn_id=-1)
        dev1.release()
        for th in threads:
            th.join(30.0)
            assert not th.is_alive(), "stolen statement hung"
    finally:
        dev0.release()
    assert all(results[i] == oracle for i in range(n)), results
    # every one of the 16 migrated exactly once (device 0 never granted
    # a single statement — it was held throughout) and landed off-home
    steals = sum(s.stats()["steals"] for s in POOL.schedulers) - steals0
    ctr = sum(_counter("tidb_tpu_work_steals_total", d)
              for d in range(POOL.size())) - ctr0
    assert steals == n and ctr == n
    # +1 is this test's own hold — no STATEMENT was granted on device 0
    assert dev0.stats()["admissions"] == adm0 + 1
    assert all(sessions[i].last_guard.device_index != 0 for i in range(n))
    # aggregate stats expose the per-device breakdown
    agg = POOL.stats()
    assert agg["steals"] >= n and "device1" in agg["devices"]


def test_kill_lands_on_stolen_waiter(pod):
    """KILL while queued on the STOLEN-to device: typed 1317 within
    ~2s, and both queues are clean afterwards."""
    eng, new_session = pod
    victim = new_session()
    victim.query(DIM_SQL)                 # warm → locality pins device 0
    killer = new_session()
    dev0, dev1 = POOL.schedulers[0], POOL.schedulers[1]
    result: dict = {}

    def run_victim():
        try:
            victim.execute(DIM_SQL)
            result["outcome"] = "completed"
        except TiDBTPUError as e:
            result["outcome"] = "error"
            result["code"] = getattr(e, "code", None)

    dev0.acquire(conn_id=-1)
    dev1.acquire(conn_id=-1)
    try:
        th = threading.Thread(target=run_victim, daemon=True)
        th.start()
        deadline = time.monotonic() + 10.0
        while True:
            with dev0._cv:
                if dev0._stealable >= 1:
                    break
            assert time.monotonic() < deadline, "victim never parked"
            time.sleep(0.005)
        assert POOL.steal_into(dev1)      # migrate; dev1 held → re-queues
        while dev1.queue_depth() < 2:
            assert time.monotonic() < deadline, "migrant never queued"
            time.sleep(0.005)
        t_kill = time.monotonic()
        killer.execute(f"KILL QUERY {victim.conn_id}")
        th.join(10.0)
        assert not th.is_alive(), "KILLed stolen waiter hung"
        assert result.get("outcome") == "error", result
        assert result.get("code") == 1317, result
        assert time.monotonic() - t_kill < 2.0
    finally:
        dev1.release()
        dev0.release()
    assert dev0.queue_depth() == 0 and dev1.queue_depth() == 0
    assert victim.query(DIM_SQL).rows    # session still serves


def test_steal_migrate_fault_requeues_home(pod):
    """An injected fault at the steal handoff re-queues the waiter on
    its HOME device with the backoff charged — the statement runs
    exactly once, on home, and answers the oracle."""
    eng, new_session = pod
    s = new_session()
    oracle = s.query(DIM_SQL).rows        # warm → home is device 0
    dev0, dev1 = POOL.schedulers[0], POOL.schedulers[1]
    steals0 = dev1.stats()["steals"]
    ctr0 = _counter("tidb_tpu_work_steals_total", 1)
    result: dict = {}

    def rerun():
        try:
            result["rows"] = s.query(DIM_SQL).rows
        except TiDBTPUError as e:
            result["err"] = e

    failpoint.enable("steal-migrate",
                     raise_=RuntimeError("test: handoff fault"), times=1)
    failpoint.enable("backoff-sleep", value="skip")
    dev0.acquire(conn_id=-1)
    try:
        th = threading.Thread(target=rerun, daemon=True)
        th.start()
        deadline = time.monotonic() + 10.0
        while True:
            with dev0._cv:
                if dev0._stealable >= 1:
                    break
            assert time.monotonic() < deadline, "waiter never parked"
            time.sleep(0.005)
        assert POOL.steal_into(dev1)
        # the fault bounces it home: back on device 0's queue, no
        # longer steal-eligible
        while True:
            with dev0._cv:
                if dev0._queue and dev0._stealable == 0:
                    break
            assert time.monotonic() < deadline, "waiter never came home"
            time.sleep(0.005)
    finally:
        dev0.release()
        failpoint.disable_all()
    th.join(10.0)
    assert not th.is_alive()
    assert result.get("rows") == oracle
    assert s.last_guard.device_index == 0          # ran at home
    assert failpoint.hits("steal-migrate") == 1
    assert dev1.stats()["steals"] == steals0       # never counted
    assert _counter("tidb_tpu_work_steals_total", 1) == ctr0
