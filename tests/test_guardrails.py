"""Query lifecycle guardrails: cooperative KILL, statement timeouts,
OOM/spill cancellation, retry budgets, and the chaos sweep (ref:
util/sqlkiller/sqlkiller.go, executor/executor.go QueryTimeLimit,
server's killConn path)."""

import threading
import time

import pytest

from tidb_tpu.errors import (BackoffExhausted, MemoryQuotaExceeded,
                             NoSuchThreadError, QueryInterrupted,
                             QueryTimeout, TxnError)
from tidb_tpu.session import Engine
from tidb_tpu.util import failpoint
from tidb_tpu.util.guard import PROCESS_REGISTRY


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    s = e.new_session()
    s.execute("CREATE TABLE gt (a BIGINT, b BIGINT, c VARCHAR(24))")
    for base in range(0, 6000, 1000):
        vals = ", ".join(f"({i}, {i % 7}, 'v{i:05d}')"
                         for i in range(base, base + 1000))
        s.execute(f"INSERT INTO gt VALUES {vals}")
    yield e
    e.close()


@pytest.fixture()
def session(eng):
    s = eng.new_session()
    saved = dict(s.vars)
    yield s
    failpoint.disable_all()
    s.vars.clear()
    s.vars.update(saved)


# ---- cooperative KILL ------------------------------------------------------

def test_kill_query_mid_next(session):
    """KILL QUERY flips the guard; the NEXT chunk boundary raises 1317
    and the session survives to run the following statement."""
    s = session
    with failpoint.enabled(
            "scan-next",
            hook=lambda: PROCESS_REGISTRY.kill(s.conn_id,
                                               query_only=True)):
        with pytest.raises(QueryInterrupted) as ei:
            s.query("SELECT COUNT(*), SUM(a) FROM gt")
    assert ei.value.code == 1317
    g = s.last_guard                 # capture before the next statement
    # the scan polled the flag at chunk boundaries before dying
    assert sum(g.checkpoints.values()) >= 1, g.checkpoints
    # session is still usable — KILL QUERY keeps the connection
    assert s.query("SELECT COUNT(*) FROM gt").scalar() == 6000


def test_kill_query_from_other_session(eng):
    """The real shape: session B interrupts session A's running
    statement through the registry, cross-thread."""
    s1, s2 = eng.new_session(), eng.new_session()
    started = threading.Event()

    def slow_chunk():
        started.set()
        time.sleep(0.05)

    result = {}

    def victim():
        try:
            result["rows"] = s1.query("SELECT SUM(a) FROM gt").rows
        except Exception as e:  # noqa: BLE001
            result["err"] = e

    failpoint.enable("scan-next", hook=slow_chunk)
    try:
        t = threading.Thread(target=victim)
        t.start()
        assert started.wait(5.0)
        s2.execute(f"KILL QUERY {s1.conn_id}")
        t.join(10.0)
        assert not t.is_alive()
    finally:
        failpoint.disable_all()
    assert isinstance(result.get("err"), QueryInterrupted), result
    # and s1's connection survived the QUERY-only kill
    assert s1.query("SELECT 1 + 1").scalar() == 2


def test_kill_connection_poisons_session(eng):
    s1, s2 = eng.new_session(), eng.new_session()
    s2.execute(f"KILL {s1.conn_id}")
    with pytest.raises(QueryInterrupted):
        s1.query("SELECT 1")
    assert PROCESS_REGISTRY.conn_killed(s1.conn_id)


def test_kill_unknown_thread(session):
    with pytest.raises(NoSuchThreadError) as ei:
        session.execute("KILL QUERY 99999999")
    assert ei.value.code == 1094


def test_show_processlist_lists_this_connection(session):
    rows = session.query("SHOW PROCESSLIST").rows
    assert any(str(session.conn_id) == str(r[0]) for r in rows), rows


# ---- PROCESS / SUPER privileges --------------------------------------------

def test_kill_other_user_without_super_is_1095(eng):
    """MySQL's error split: unknown thread → 1094; thread exists but is
    someone else's and the killer lacks SUPER → 1095; with a global
    SUPER grant the kill goes through."""
    from tidb_tpu.errors import KillDeniedError
    root_s = eng.new_session()
    root_s.execute("CREATE USER IF NOT EXISTS killer IDENTIFIED BY 'x'")
    s_eve = eng.new_session()
    s_eve.user = "killer"
    # unknown id stays strictly 1094 — even for an unprivileged user
    with pytest.raises(NoSuchThreadError) as ei:
        s_eve.execute("KILL QUERY 99999999")
    assert ei.value.code == 1094
    # root's live thread: exists, not yours, no SUPER → 1095
    with pytest.raises(KillDeniedError) as ei:
        s_eve.execute(f"KILL QUERY {root_s.conn_id}")
    assert ei.value.code == 1095
    assert str(root_s.conn_id) in str(ei.value)
    # ...and the target was NOT killed
    assert root_s.query("SELECT 1 + 1").scalar() == 2
    # SUPER must be a *.* grant; a db-scoped one must not escalate
    root_s.execute("GRANT SUPER ON test.* TO killer")
    with pytest.raises(KillDeniedError):
        s_eve.execute(f"KILL QUERY {root_s.conn_id}")
    root_s.execute("GRANT SUPER ON *.* TO killer")
    s_eve.execute(f"KILL QUERY {root_s.conn_id}")   # idle target: no-op
    assert root_s.query("SELECT 1 + 1").scalar() == 2
    root_s.execute("DROP USER killer")


def test_processlist_requires_process_priv_to_see_others(eng):
    """Without the global PROCESS privilege SHOW PROCESSLIST (and
    information_schema.processlist) lists only the caller's own
    threads (sql/sql_show.cc mysqld_list_processes)."""
    root_s = eng.new_session()
    root_s.execute("CREATE USER IF NOT EXISTS watcher IDENTIFIED BY 'x'")
    root_s.execute("GRANT SELECT ON *.* TO watcher")
    s_w = eng.new_session()
    s_w.user = "watcher"

    def visible(sess):
        return {int(r[0]) for r in sess.query("SHOW PROCESSLIST").rows}

    assert root_s.conn_id not in visible(s_w)
    assert s_w.conn_id in visible(s_w)
    ids = {int(r[0]) for r in s_w.query(
        "SELECT ID FROM information_schema.processlist").rows}
    assert root_s.conn_id not in ids and s_w.conn_id in ids
    # root (ALL on *.*) sees everyone
    assert {root_s.conn_id, s_w.conn_id} <= visible(root_s)
    # a db-scoped PROCESS grant must not unlock the global view
    root_s.execute("GRANT PROCESS ON test.* TO watcher")
    assert root_s.conn_id not in visible(s_w)
    root_s.execute("GRANT PROCESS ON *.* TO watcher")
    assert {root_s.conn_id, s_w.conn_id} <= visible(s_w)
    root_s.execute("DROP USER watcher")


# ---- statement timeout -----------------------------------------------------

def test_max_execution_time_interrupts_multichunk_scan(session):
    s = session
    s.vars["max_execution_time"] = 60          # ms
    with failpoint.enabled("scan-next", hook=lambda: time.sleep(0.03)):
        with pytest.raises(QueryTimeout) as ei:
            s.query("SELECT COUNT(*), SUM(a) FROM gt")
    assert ei.value.code == 3024
    g = s.last_guard
    assert sum(g.checkpoints.values()) >= 1, g.checkpoints
    # clearing the var restores normal execution
    s.vars["max_execution_time"] = 0
    assert s.query("SELECT COUNT(*) FROM gt").scalar() == 6000


def test_timeout_zero_means_no_deadline(session):
    session.vars["max_execution_time"] = 0
    session.query("SELECT COUNT(*) FROM gt")
    assert session.last_guard.deadline is None


def test_timeout_scoped_to_read_only_select(session):
    """MySQL semantics: max_execution_time arms ONLY read-only SELECTs.
    A write slower than the deadline must run to completion (aborting a
    half-applied mutation on a timer would corrupt), and SELECT ... FOR
    UPDATE locks so it is exempt too — only explicit KILL stops those."""
    s = session
    s.vars["max_execution_time"] = 40          # ms
    before = s.query("SELECT COUNT(*) FROM gt").scalar()
    with failpoint.enabled("store-commit",
                           hook=lambda: time.sleep(0.08)):
        s.execute("INSERT INTO gt VALUES (100001, 1, 'slowwrite')")
    assert s.last_guard.deadline is None       # write ran unarmed
    assert s.query("SELECT COUNT(*) FROM gt").scalar() == before + 1
    s.execute("DELETE FROM gt WHERE a = 100001")
    # FOR UPDATE: exempt even though it reads
    s.query("SELECT a FROM gt WHERE a < 3 FOR UPDATE")
    assert s.last_guard.deadline is None
    # the same sysvar still times out a plain SELECT
    with failpoint.enabled("scan-next", hook=lambda: time.sleep(0.03)):
        with pytest.raises(QueryTimeout):
            s.query("SELECT COUNT(*), SUM(a) FROM gt")


def test_processlist_exposes_escalations(session):
    """information_schema.processlist grows an ESCALATIONS column fed by
    the running statement's guard (util/escalation.py EscalationStats) —
    a squeezed group cap makes the device fragment recompile, and the
    summary shows up on the SAME statement's guard."""
    s = session
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_group_cap=64)
    # a + 0 is an expression key: no cached bounds, no NDV pre-sizing —
    # 6000 distinct values overflow cap 64 → exact-need ladder recompile
    s.query("SELECT a + 0, COUNT(*) FROM gt GROUP BY a + 0")
    esc = s.last_guard.escalation
    assert esc.recompiles >= 1 and esc.exact_resizes >= 1, esc.summary()
    assert "group:exact" in esc.summary()
    # the column exists and is well-formed for every live connection
    rows = s.query("SELECT ID, ESCALATIONS FROM "
                   "information_schema.processlist").rows
    assert any(str(r[0]) == str(s.conn_id) for r in rows), rows


# ---- lifecycle errors vs the device fallback ladder ------------------------

def test_kill_not_swallowed_by_cpu_fallback(session):
    """A lifecycle error raised while the device fragment runs must
    unwind — the generic except clause retries plain device faults on
    CPU, and before the guardrails it would have eaten the kill too."""
    s = session
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1)
    with failpoint.enabled(
            "device-fragment",
            raise_=QueryInterrupted("Query execution was interrupted")):
        with pytest.raises(QueryInterrupted):
            s.query("SELECT b, SUM(a) FROM gt GROUP BY b")
    # plain device faults still fall back quietly
    with failpoint.enabled("device-fragment",
                           raise_=RuntimeError("chaos: device down"),
                           times=1):
        rows = s.query("SELECT COUNT(*) FROM gt").rows
    assert rows == [(6000,)]
    assert s.last_guard.hits("device-dispatch") >= 1


# ---- OOM actions: spill, then cancel ---------------------------------------

def test_quota_spills_then_kill_cancels_spill(session):
    s = session
    q = ("SELECT c, COUNT(*) FROM gt GROUP BY c ORDER BY c LIMIT 3")
    s.vars["tidb_mem_quota_query"] = 8000
    # under quota pressure the agg spills and still answers correctly
    assert s.query(q).rows == [("v00000", 1), ("v00001", 1),
                               ("v00002", 1)]
    g = s.last_guard
    assert g.hits("spill") >= 1, g.checkpoints
    # a kill landing during spill I/O cancels instead of grinding on
    with failpoint.enabled(
            "spill-write",
            hook=lambda: PROCESS_REGISTRY.kill(s.conn_id,
                                               query_only=True)):
        with pytest.raises(QueryInterrupted):
            s.query(q)
    s.vars.pop("tidb_mem_quota_query")
    assert s.query("SELECT COUNT(*) FROM gt").scalar() == 6000


def test_unspillable_quota_is_typed(session):
    session.vars["tidb_mem_quota_query"] = 8000
    with failpoint.enabled("tracker-quota",
                           raise_=MemoryQuotaExceeded("chaos: quota"),
                           times=1):
        with pytest.raises(MemoryQuotaExceeded):
            session.query("SELECT c, COUNT(*) FROM gt GROUP BY c")


# ---- retry budgets ---------------------------------------------------------

def test_commit_retry_budget_exhausts(eng):
    s = eng.new_session()
    s.execute("CREATE TABLE bo (a BIGINT)")
    conflict = TxnError("chaos: hot key")
    conflict.retryable = True
    failpoint.enable("commit-conflict", raise_=conflict)
    failpoint.enable("backoff-sleep", value="skip")   # budget, no wall-clock
    try:
        with pytest.raises(BackoffExhausted) as ei:
            s.execute("INSERT INTO bo VALUES (1)")
        assert failpoint.hits("commit-conflict") > 3   # it really retried
        assert isinstance(ei.value.__cause__, TxnError)
    finally:
        failpoint.disable_all()
    # transient conflicts (heal after 2) are absorbed by the retry loop
    conflict2 = TxnError("chaos: transient")
    conflict2.retryable = True
    failpoint.enable("commit-conflict", raise_=conflict2, times=2)
    failpoint.enable("backoff-sleep", value="skip")
    try:
        s.execute("INSERT INTO bo VALUES (2)")
    finally:
        failpoint.disable_all()
    assert s.query("SELECT COUNT(*) FROM bo").scalar() == 1


# ---- ADVICE regressions ----------------------------------------------------

def test_ci_group_by_folds_case_despite_index(eng):
    """A _ci key's index view is raw-ordered, so stream-agg over it
    split case-variant groups; the planner must refuse that path."""
    s = eng.new_session()
    s.execute("CREATE TABLE ci_t (a BIGINT, "
              "s VARCHAR(16) COLLATE utf8mb4_general_ci)")
    s.execute("CREATE INDEX ci_s ON ci_t (s)")
    s.execute("INSERT INTO ci_t VALUES (1, 'Alpha'), (2, 'alpha'), "
              "(3, 'BETA'), (4, 'beta'), (5, 'beta')")
    rows = s.query("SELECT COUNT(*) FROM ci_t GROUP BY s").rows
    assert sorted(c for (c,) in rows) == [2, 3], rows


def test_ci_order_by_uses_collation_not_index(eng):
    s = eng.new_session()
    s.execute("CREATE TABLE ci_o (s VARCHAR(16) COLLATE "
              "utf8mb4_general_ci)")
    s.execute("CREATE INDEX ci_os ON ci_o (s)")
    s.execute("INSERT INTO ci_o VALUES ('b'), ('A'), ('a'), ('B')")
    got = [r[0] for r in s.query("SELECT s FROM ci_o ORDER BY s").rows]
    folded = [v.lower() for v in got]
    assert folded == sorted(folded), got   # collation order, not raw


def test_device_cache_eviction_keeps_partitioned_entries():
    from tidb_tpu.executor import device_cache as dc

    class _Ent:
        def hbm_bytes(self):
            return 100

    saved = dict(dc._CACHE)
    dc._CACHE.clear()
    try:
        dc._CACHE[(0, 1, 10, None)] = _Ent()     # evictable
        dc._CACHE[(0, 1, 20, (0,))] = _Ent()     # partitioned, protected
        dc._CACHE[(0, 1, 20, (1,))] = _Ent()     # partitioned, protected
        dc._evict_to_budget(150, keep=None,
                            keep_tables=frozenset({(1, 20)}))
        assert (0, 1, 20, (0,)) in dc._CACHE
        assert (0, 1, 20, (1,)) in dc._CACHE
        assert (0, 1, 10, None) not in dc._CACHE
    finally:
        dc._CACHE.clear()
        dc._CACHE.update(saved)


def test_hash_partition_routes_negative_keys_like_mysql(eng):
    """MySQL hash partitioning is ABS(truncated MOD); routing and
    pruning must agree or equality lookups on negative keys lose rows."""
    s = eng.new_session()
    s.execute("CREATE TABLE hp (a BIGINT) "
              "PARTITION BY HASH (a) PARTITIONS 4")
    keys = [-7, -3, -1, 0, 1, 3, 7]
    s.execute("INSERT INTO hp VALUES " +
              ", ".join(f"({k})" for k in keys))
    for k in keys:
        assert s.query(
            f"SELECT COUNT(*) FROM hp WHERE a = {k}").scalar() == 1, k
    assert s.query("SELECT COUNT(*) FROM hp").scalar() == len(keys)


# ---- chaos sweep -----------------------------------------------------------

@pytest.mark.chaos
def test_chaos_sweep_contract():
    from tidb_tpu.tools.chaos_sweep import run_sweep
    report = run_sweep()
    assert not report["failures"], report["failures"]
    assert report["scenarios"] >= 12
    # the clean workload must exercise the core CPU-path sites, or the
    # sweep is faulting dead code
    covered = {k for k, v in report["coverage"].items() if v > 0}
    assert {"scan-next", "store-commit", "tracker-quota"} <= covered
    # the coverage GATE: without a mesh only the mesh-only sites may stay
    # cold — everything else must have a working scenario
    assert not report["gated_unreached"], report["gated_unreached"]


@pytest.mark.chaos
def test_chaos_sweep_mesh_contract(eight_devices):
    # the distributed scenarios only: skewed-exchange escalation and
    # shard-step fault recovery over a 4-device mesh (the tests already
    # run under the forced 8-device host platform, so no re-exec needed)
    from tidb_tpu.tools.chaos_sweep import run_sweep
    report = run_sweep(mesh=4, mesh_only=True)
    assert not report["failures"], report["failures"]
    assert report["scenarios"] >= 3
    assert not report["gated_unreached"], report["gated_unreached"]


@pytest.mark.chaos
def test_check_failpoints_clean_on_repo_and_catches_drift(tmp_path):
    """The failpoint drift lint (tools/check_failpoints.py) the sweep
    runs as preflight: clean on this repo, and it actually catches both
    drift directions on a synthetic bad file."""
    import importlib.util
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_failpoints", os.path.join(repo, "tools",
                                         "check_failpoints.py"))
    cf = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cf)
    assert cf.run(repo) == []
    bad = tmp_path / "bad.py"
    bad.write_text(
        'from tidb_tpu.util import failpoint\n'
        'failpoint.inject("never-registered-site")\n'
        'failpoint.inject(some_variable)\n')
    inj, dyn, reg, strings, errs = cf.scan_file(str(bad))
    assert errs == []
    assert inj == [("never-registered-site", 2)]
    assert dyn == [3]
    assert "never-registered-site" in strings
