"""Index access paths: ranger derivation + sorted-index scan vs the full
table scan oracle (ref: executor/point_get.go, util/ranger/points.go)."""

import numpy as np
import pytest

from tidb_tpu.planner.ranger import Range, detach_ranges
from tidb_tpu.expression import ColumnRef, Constant, func, lit
from tidb_tpu import types as T
from tidb_tpu.session import Engine


def col(i, ft=None):
    return ColumnRef(i, ft or T.bigint())


# ---- ranger ---------------------------------------------------------------

def test_detach_eq():
    r, rest = detach_ranges([func("eq", col(0), lit(5))], 0)
    assert r == [Range(5, 5, True, True)]
    assert rest == []


def test_detach_range_intersection():
    fs = [func("ge", col(0), lit(10)), func("lt", col(0), lit(20)),
          func("gt", col(1), lit(0))]
    r, rest = detach_ranges(fs, 0)
    assert r == [Range(10, 20, True, False)]
    assert len(rest) == 1 and rest[0].op == "gt"


def test_detach_in_points():
    r, rest = detach_ranges([func("in", col(0), lit(3), lit(1), lit(3))], 0)
    assert [x.lo for x in r] == [1, 3]


def test_detach_unsatisfiable():
    fs = [func("gt", col(0), lit(10)), func("lt", col(0), lit(5))]
    r, rest = detach_ranges(fs, 0)
    assert r == []


def test_detach_flipped_and_null():
    r, _ = detach_ranges([func("lt", lit(10), col(0))], 0)   # 10 < c
    assert r == [Range(10, None, False, True)]
    r, _ = detach_ranges([func("isnull", col(0))], 0)
    assert r == [Range(include_null=True)]


def test_detach_unconstrained():
    r, rest = detach_ranges([func("gt", col(1), lit(0))], 0)
    assert r is None
    assert len(rest) == 1


# ---- executor differential -------------------------------------------------

@pytest.fixture(scope="module")
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE it (id BIGINT, v BIGINT, s VARCHAR(8), "
              "PRIMARY KEY (id))")
    rng = np.random.default_rng(41)
    rows = []
    for i in range(30000):
        v = "NULL" if rng.random() < 0.02 else str(int(rng.integers(0, 500)))
        rows.append(f"({i},{v},'s{i % 7}')")
    s.execute("INSERT INTO it VALUES " + ",".join(rows))
    s.execute("ANALYZE TABLE it")
    s.execute("CREATE INDEX iv ON it (v)")
    return s


def plan_uses_index(s, sql, index=None):
    rows = s.query("EXPLAIN " + sql).rows
    txt = "\n".join(str(r) for r in rows)
    return "IndexScan" in txt and (index is None or f"index:{index}" in txt)


QUERIES = [
    ("SELECT * FROM it WHERE id = 12345", "PRIMARY"),
    ("SELECT * FROM it WHERE id IN (5, 17, 29999, 99999)", "PRIMARY"),
    ("SELECT * FROM it WHERE id BETWEEN 777 AND 792", "PRIMARY"),
    ("SELECT COUNT(*), SUM(id) FROM it WHERE v = 123", "iv"),
    ("SELECT * FROM it WHERE v = 7 AND id < 500", None),
    ("SELECT COUNT(*) FROM it WHERE v IS NULL", "iv"),
    ("SELECT * FROM it WHERE id > 29990", "PRIMARY"),
]


@pytest.mark.parametrize("sql,index", QUERIES)
def test_index_scan_matches_full_scan(session, sql, index):
    s = session
    assert plan_uses_index(s, sql, index), s.query("EXPLAIN " + sql).rows
    via_index = sorted(map(tuple, s.query(sql).rows), key=str)
    # oracle: force the full-scan path by disabling index selection
    from tidb_tpu.planner import physical
    gate = physical.INDEX_SELECTIVITY_GATE
    physical.INDEX_SELECTIVITY_GATE = -1.0
    try:
        def no_index(ds, ctx):
            return None
        orig = physical._try_index_access
        physical._try_index_access = no_index
        try:
            full = sorted(map(tuple, s.query(sql).rows), key=str)
        finally:
            physical._try_index_access = orig
    finally:
        physical.INDEX_SELECTIVITY_GATE = gate
    assert via_index == full


def test_low_selectivity_stays_table_scan(session):
    # v < 499 matches ~everything → index must NOT be chosen
    assert not plan_uses_index(session, "SELECT * FROM it WHERE v < 499")


def test_index_sees_fresh_writes(session):
    s = session
    s.execute("INSERT INTO it VALUES (90001, 123, 'zz')")
    rows = s.query("SELECT id FROM it WHERE id = 90001").rows
    assert rows == [(90001,)]
    s.execute("DELETE FROM it WHERE id = 90001")
    assert s.query("SELECT id FROM it WHERE id = 90001").rows == []


def test_index_inside_transaction(session):
    s = session
    s.execute("BEGIN")
    try:
        s.execute("INSERT INTO it VALUES (91000, 123, 'tx')")
        assert s.query("SELECT id FROM it WHERE id = 91000").rows == \
            [(91000,)]
    finally:
        s.execute("ROLLBACK")
    assert s.query("SELECT id FROM it WHERE id = 91000").rows == []


def test_create_drop_index_ddl(session):
    s = session
    s.execute("CREATE UNIQUE INDEX is2 ON it (id)")
    assert plan_uses_index(s, "SELECT * FROM it WHERE id = 3")
    s.execute("DROP INDEX is2 ON it")
    from tidb_tpu.errors import DDLError
    with pytest.raises(DDLError):
        s.execute("DROP INDEX is2 ON it")


# ---- unique-key enforcement (write path) ----------------------------------

def test_unique_enforcement():
    from tidb_tpu.errors import DuplicateKeyError
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE u (id BIGINT, v BIGINT, PRIMARY KEY (id))")
    s.execute("INSERT INTO u VALUES (1,10),(2,10)")
    with pytest.raises(DuplicateKeyError):
        s.execute("INSERT INTO u VALUES (1,99)")
    with pytest.raises(DuplicateKeyError):      # in-batch dup
        s.execute("INSERT INTO u VALUES (5,1),(5,2)")
    with pytest.raises(DuplicateKeyError):      # backfill over dup data
        s.execute("CREATE UNIQUE INDEX uv ON u (v)")
    s.execute("INSERT IGNORE INTO u VALUES (1,99),(3,30)")
    assert sorted(s.query("SELECT id FROM u").rows) == [(1,), (2,), (3,)]
    s.execute("REPLACE INTO u VALUES (1,111)")
    assert sorted(s.query("SELECT id, v FROM u").rows) == \
        [(1, 111), (2, 10), (3, 30)]
    # NULLs never conflict in unique secondary indexes
    s.execute("CREATE TABLE un (a BIGINT, b BIGINT)")
    s.execute("CREATE UNIQUE INDEX ub ON un (b)")
    s.execute("INSERT INTO un VALUES (1,NULL),(2,NULL)")
    assert len(s.query("SELECT * FROM un").rows) == 2
    # txn-staged conflicts are seen
    s.execute("BEGIN")
    s.execute("INSERT INTO u VALUES (7,700)")
    with pytest.raises(DuplicateKeyError):
        s.execute("INSERT INTO u VALUES (7,701)")
    s.execute("ROLLBACK")


def test_invalid_create_index_syntax_rejected():
    from tidb_tpu.errors import ParseError
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE z (a BIGINT)")
    with pytest.raises(ParseError):
        s.execute("CREATE UNIQUE FROB zz ON z (a)")


# ---- resumable CREATE UNIQUE INDEX backfill (tidb_tpu/ddl.py) --------------

def test_unique_backfill_resumes_from_checkpoint(tmp_path):
    from tidb_tpu.errors import DuplicateKeyError
    from tidb_tpu.session import Engine
    from tidb_tpu.util import failpoint
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE rb (a BIGINT, b BIGINT)")
    # several INSERT batches → several storage regions (backfill units)
    for lo in range(0, 4000, 1000):
        s.execute("INSERT INTO rb VALUES " + ",".join(
            f"({i},{i * 2})" for i in range(lo, lo + 1000)))
    s.vars["tidb_ddl_reorg_checkpoint_dir"] = str(tmp_path)
    s.vars["tidb_ddl_reorg_batch_size"] = 1000      # 4 backfill batches
    # kill the backfill after the SECOND batch
    hits = [0]

    def boom():
        hits[0] += 1
        if hits[0] == 2:
            raise RuntimeError("injected crash mid-backfill")

    failpoint.enable("index-backfill", hook=boom)
    try:
        try:
            s.execute("CREATE UNIQUE INDEX u_a ON rb (a)")
            raise AssertionError("failpoint did not fire")
        except RuntimeError:
            pass
    finally:
        failpoint.disable("index-backfill")
    # a checkpoint + at least one persisted run survived the crash
    files = [f.name for f in tmp_path.iterdir()]
    assert any(f.startswith("reorg_u_a") and f.endswith(".json")
               for f in files), files
    assert any(".run" in f for f in files), files
    # "restart": a fresh session resumes and completes
    s2 = eng.new_session()
    s2.vars["tidb_ddl_reorg_checkpoint_dir"] = str(tmp_path)
    s2.vars["tidb_ddl_reorg_batch_size"] = 1000
    s2.execute("CREATE UNIQUE INDEX u_a ON rb (a)")
    info = eng.catalog.info_schema.table("rb")
    assert any(ix.name == "u_a" and ix.unique for ix in info.indexes)
    # checkpoint + runs cleaned up after completion
    assert not list(tmp_path.iterdir()), list(tmp_path.iterdir())
    # the index enforces uniqueness afterwards
    import pytest
    with pytest.raises(DuplicateKeyError):
        s2.execute("INSERT INTO rb VALUES (5, 99)")


def test_unique_backfill_cross_region_duplicate(tmp_path):
    import pytest
    from tidb_tpu.errors import DuplicateKeyError
    from tidb_tpu.session import Engine
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE rbd (a BIGINT)")
    s.execute("INSERT INTO rbd VALUES (1),(2),(3)")
    s.execute("INSERT INTO rbd VALUES (7),(8),(2)")   # dup spans regions
    s.vars["tidb_ddl_reorg_checkpoint_dir"] = str(tmp_path)
    with pytest.raises(DuplicateKeyError, match="Duplicate entry"):
        s.execute("CREATE UNIQUE INDEX u_d ON rbd (a)")
