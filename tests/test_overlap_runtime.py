"""Overlap-aware device runtime (streamed first-touch, resumable
escalation, donation/deletion discipline).

Three invariants pinned here:

* streamed per-slab encoding is BYTE-EXACT against the whole-column
  encode (`_encode_col` / `wide_decimal_limbs` + manual slicing) — the
  global dictionary makes per-slab searchsorted ≡ np.unique's
  return_inverse;
* a group-cap overflow re-executes ONLY the overflowed slabs: the
  checkpointed partials are merged back in, observable through the
  EscalationStats slabs_rerun/slabs_reused counters, and the resumed
  result is byte-exact against a Python oracle;
* evicted cache entries FREE their device buffers immediately
  (jax.Array.is_deleted), so a recompile right after eviction cannot
  double the HBM high-water mark.
"""

import collections
from decimal import Decimal

import numpy as np
import pytest

from tidb_tpu.executor import build, device_cache as dc, run_to_completion
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine


def run_device(s, sql, *, max_slab=None):
    """Execute on the device path, asserting no CPU fallback."""
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    if max_slab is not None:
        s.vars["tidb_tpu_max_slab_rows"] = max_slab
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags, f"no fragment extracted for: {sql}"
        for f in frags:
            assert f.used_device, f"fell back to CPU: {f.fallback_reason}"
        return [r for ch in chunks for r in ch.rows()]
    finally:
        s.vars["tidb_tpu_engine"] = "off"
        s.vars.pop("tidb_tpu_max_slab_rows", None)


def _cache_entry(eng, table_name):
    tid = eng.catalog.info_schema.table(table_name).id
    for (_dev, sid, t, _parts), ent in dc._CACHE.items():
        if sid == id(eng.store) and t == tid:
            return ent
    raise AssertionError(f"no cache entry for {table_name}")


# ---------------------------------------------------------------------------
# streamed first-touch: byte-exact vs whole-column encode
# ---------------------------------------------------------------------------

def test_streamed_slabs_byte_exact_vs_upload_all():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE st (a BIGINT, b DOUBLE, c VARCHAR(10), "
              "d DECIMAL(10,2), w DECIMAL(30,4))")
    rng = np.random.default_rng(11)
    rows = []
    words = ["ant", "Bee", "cow", "dog", "EEL", "fox"]
    for i in range(3000):
        if i % 97 == 0:
            rows.append("(NULL,NULL,NULL,NULL,NULL)")
            continue
        rows.append(f"({int(rng.integers(-50, 50))},{float(rng.normal()):.6f},"
                    f"'{words[int(rng.integers(0, 6))]}',"
                    f"{float(rng.uniform(0, 500)):.2f},"
                    f"{float(rng.uniform(-9e9, 9e9)):.4f})")
    s.execute("INSERT INTO st VALUES " + ",".join(rows))

    cpu = sorted(s.query(
        "SELECT c, COUNT(a), SUM(b), SUM(d), SUM(w) FROM st GROUP BY c").rows,
        key=str)
    dev = sorted(run_device(
        s, "SELECT c, COUNT(a), SUM(b), SUM(d), SUM(w) FROM st GROUP BY c",
        max_slab=1024), key=str)
    assert len(cpu) == len(dev)
    for r1, r2 in zip(dev, cpu):
        for v1, v2 in zip(r1, r2):
            if isinstance(v2, float):
                assert abs(v1 - v2) <= 1e-6 * max(1.0, abs(v2))
            else:
                assert v1 == v2

    ent = _cache_entry(eng, "st")
    assert ent.n_slabs >= 3, "scenario must actually stream multiple slabs"
    fts = [c.ftype for c in eng.catalog.info_schema.table("st").columns]
    checked = 0
    for i, ft in enumerate(fts):
        if i not in ent.dev:
            continue
        vals, valid = dc._materialize_col(ent, i)
        if ft.is_wide_decimal:
            enc = dc.wide_decimal_limbs(vals, ft.wide_limb_count)
        else:
            enc, dictionary = dc._encode_col(ft, vals, valid)
            if dictionary is None:
                assert ent.dicts[i] is None
            else:
                assert np.array_equal(ent.dicts[i], dictionary)
        assert len(ent.dev[i]) == ent.n_slabs
        lay = ent.layouts.get(i)
        # compressed columns: the resident slab is packed words — decode
        # reproduces the logical column under validity (invalid slots
        # decode to the layout's reference value, not the raw bytes)
        slabs = dc._decoded_slabs(ent, i) if lay is not None \
            else ent.dev[i]
        for si, (dv, dm) in enumerate(slabs):
            start = si * ent.slab_cap
            stop = min(start + ent.slab_cap, ent.total)
            n = stop - start
            hv, hm = np.asarray(dv), np.asarray(dm)
            if ft.is_wide_decimal:
                assert np.array_equal(hv[:, :n], enc[:, start:stop])
                assert not hv[:, n:].any(), "padding must be zero"
            elif lay is not None:
                sel = np.asarray(valid[start:stop])
                assert np.array_equal(hv[:n][sel], enc[start:stop][sel])
            else:
                assert hv.dtype == enc.dtype
                assert np.array_equal(hv[:n], enc[start:stop])
                assert not hv[n:].any(), "padding must be zero"
            assert np.array_equal(hm[:n], valid[start:stop])
            assert not hm[n:].any()
        checked += 1
    assert checked >= 4, f"expected ≥4 streamed columns, saw {checked}"


# ---------------------------------------------------------------------------
# resumable escalation: rerun only the overflowed slabs
# ---------------------------------------------------------------------------

def _resumable_engine(per_slab_distinct, stride=5_000_000):
    """3 slabs × 1024 rows; per-slab key cardinality from the given list.
    Keys are spread by `stride` so the packed domain exceeds the
    perfect-hash gate (DOMAIN_CAP) and the agg takes the sort-factorize
    path whose per-slab group counts drive the resumable ladder. A FRESH
    engine per case with auto-analyze pinned off: reliable NDV stats
    would start the cap high enough to dodge the overflow entirely."""
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE r (k BIGINT, v BIGINT)")
    rows = []
    oracle = collections.defaultdict(int)
    for slab, nd in enumerate(per_slab_distinct):
        for i in range(1024):
            k = (slab * 1000 + i % nd) * stride
            rows.append(f"({k}, {i})")
            oracle[k] += i
    s.execute("INSERT INTO r VALUES " + ",".join(rows))
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    s.vars["tidb_tpu_max_slab_rows"] = 1024
    s.vars["tidb_tpu_group_cap"] = 64
    return s, oracle


def _check_oracle(rows, oracle):
    got = {int(k): int(v) for k, v in rows}
    assert got == dict(oracle)


def test_group_overflow_reruns_only_overflowed_slabs():
    # slab 1 overflows the 64-group cap (200 distinct); slabs 0/2 do not:
    # the retry must re-execute exactly one slab and reuse two partials
    s, oracle = _resumable_engine((10, 200, 10))
    res = s.query("SELECT k, SUM(v) FROM r GROUP BY k")
    _check_oracle(res.rows, oracle)
    esc = s.last_guard.escalation
    assert esc.slabs_rerun == 1, esc.summary()
    assert esc.slabs_reused == 2, esc.summary()
    assert esc.recompiles == 1, esc.summary()
    assert esc.exact_resizes == 1, esc.summary()
    assert esc.by_kind.get("group:partial-reuse") == 1, esc.summary()


def test_merged_count_overflow_reruns_zero_slabs():
    # every slab fits the cap (60 groups) but the MERGED count (180) does
    # not: the retry reuses every checkpointed partial and only re-merges
    s, oracle = _resumable_engine((60, 60, 60), stride=5_000_000)
    # disjoint key ranges per slab: 60 × 3 = 180 merged groups
    res = s.query("SELECT k, SUM(v) FROM r GROUP BY k")
    _check_oracle(res.rows, oracle)
    esc = s.last_guard.escalation
    assert esc.slabs_rerun == 0, esc.summary()
    assert esc.slabs_reused == 3, esc.summary()
    assert esc.recompiles == 1, esc.summary()


# ---------------------------------------------------------------------------
# donation / deletion discipline
# ---------------------------------------------------------------------------

def _held_arrays(ent):
    out = []
    for slabs in ent.dev.values():
        for t in slabs:
            if t is not None:        # zone-map hole: never uploaded
                out.extend(t)        # raw (v, m) or packed 2/3-tuple
    return out


def test_evicted_entries_free_device_buffers():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE d1 (a BIGINT)")
    s.execute("INSERT INTO d1 VALUES " +
              ",".join(f"({i})" for i in range(2000)))
    run_device(s, "SELECT COUNT(*), SUM(a) FROM d1")
    held = _held_arrays(_cache_entry(eng, "d1"))
    assert held and not any(a.is_deleted() for a in held)

    # LRU budget eviction mid-stream of another table's first touch must
    # delete d1's buffers NOW, not when the GC runs
    s.execute("CREATE TABLE d2 (a BIGINT)")
    s.execute("INSERT INTO d2 VALUES " +
              ",".join(f"({i})" for i in range(2000)))
    s.vars["tidb_tpu_hbm_budget"] = 1        # force eviction
    try:
        run_device(s, "SELECT COUNT(*), SUM(a) FROM d2")
    finally:
        s.vars.pop("tidb_tpu_hbm_budget", None)
    assert all(a.is_deleted() for a in held), \
        "evicted entry left device buffers resident"

    # clear() frees everything it held
    held2 = _held_arrays(_cache_entry(eng, "d2"))
    assert held2
    dc.clear()
    assert all(a.is_deleted() for a in held2)
