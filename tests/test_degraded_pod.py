"""Degraded-pod serving: device health, quarantine, queue migration,
cache re-homing, and healed readmission (over the conftest's forced
8-device CPU mesh).

Pins the PR's acceptance contract:

* fault-free pods stay on the empty-record fast path — no health
  records, placement byte-identical to the pre-health pool — and a
  single-slot pool REFUSES to quarantine its last healthy device;
* an in-flight DeviceLost (the `device-lost-dispatch` /
  `device-lost-upload` boundaries) quarantines the device and retries
  the victim ONCE on a survivor with a retryable 1105 SHOW WARNINGS
  row and the `migrated:` marker in EXPLAIN ANALYZE — a second loss
  surfaces the typed error, never a silent CPU re-run;
* quarantine drains the dead device's queue: every steal-eligible
  waiter migrates to survivors (counted as migration, not stealing)
  and still answers the oracle;
* a release-into-empty steal racing the quarantine drain of the same
  home queue migrates the waiter EXACTLY once (the _claim_waiter
  rendezvous — satellite 1);
* KILL (1317) and an expired deadline (3024) land on a waiter that was
  migrated off a quarantined device while queued (satellite 3);
* `evict_device` re-homes a pod-partitioned entry: only the lost slab
  ranges are nulled + re-owned onto survivors (holes + `lost` set),
  untouched owners keep their arrays by IDENTITY, and the next touch
  refills exactly the lost slabs;
* readmission is gated by the `device-readmit` probe: an armed gate
  keeps the device out, a clean pass past the flap-guard delay rejoins
  placement.
"""

import threading
import time

import pytest

from tidb_tpu.errors import DeviceLost, TiDBTPUError
from tidb_tpu.executor import device_cache as dc
from tidb_tpu.executor.scheduler import POOL, SchedulerPool
from tidb_tpu.session import Engine
from tidb_tpu.util import failpoint
from tidb_tpu.util.observability import REGISTRY

DIM_SQL = "SELECT g, COUNT(*), SUM(a) FROM dim GROUP BY g ORDER BY g"


@pytest.fixture()
def pod():
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE dim (a BIGINT, g BIGINT)")
    s.execute("INSERT INTO dim VALUES " +
              ", ".join(f"({i}, {i % 5})" for i in range(600)))

    def new_session():
        ss = eng.new_session()
        ss.vars["tidb_tpu_engine"] = "on"
        ss.vars["tidb_tpu_row_threshold"] = 1
        return ss

    yield eng, new_session
    failpoint.disable_all()
    # restore the fault-free fast path for the rest of the suite: the
    # pool is a process singleton, and a lingering health record would
    # put every later test on the (behavior-identical but guarded)
    # degraded-placement branch
    with POOL.health._lock:
        POOL.health._rec.clear()
    eng.close()


def _ctr_sum(name: str) -> int:
    return sum(v for (n, _lbl), v in REGISTRY.counters.items()
               if n == name)


def _counter(name: str, dev: int):
    return REGISTRY.counters.get((name, (("device", str(dev)),)), 0)


def _dev_of(a):
    ds = getattr(a, "devices", None)
    if callable(ds):
        got = list(a.devices())
        assert len(got) == 1
        return got[0]
    return a.device


# ---------------------------------------------------------------------------
# fault-free fast path + single-slot refusal
# ---------------------------------------------------------------------------

def test_fault_free_pod_stays_on_fast_path(pod):
    """No faults → no health records: active() stays False through
    serving, placement lands on device 0 exactly as before the fault
    domain existed, and stats report healthy with no fault fields."""
    eng, new_session = pod
    s = new_session()
    assert not POOL.health.active()
    assert s.query(DIM_SQL).rows
    assert s.last_guard.device_index == 0
    assert not POOL.health.active()
    d0 = POOL.stats()["devices"]["device0"]
    assert d0["healthy"] is True
    assert "faults" not in d0 and "readmissions" not in d0


def test_single_slot_pool_refuses_quarantine():
    """A pool of one keeps serving: report_fault refuses the last
    healthy device and leaves no record behind (the typed DeviceLost
    surfaces to the caller instead)."""
    p = SchedulerPool(1)
    assert p.health.report_fault(0, RuntimeError("x")) is False
    assert not p.health.active()
    assert p.health.healthy(0)


def test_last_healthy_device_never_quarantined(pod):
    """With every other device already out, the last healthy member
    refuses quarantine — a fully degraded pod still serves."""
    eng, new_session = pod
    s = new_session()
    s.query(DIM_SQL)                       # sizes the pool to the mesh
    n = POOL.size()
    assert n >= 2
    for i in range(n - 1):
        assert POOL.health.report_fault(i, RuntimeError("test: dead"))
    assert POOL.health.report_fault(n - 1, RuntimeError("test: dead")) \
        is False
    assert POOL.health.healthy(n - 1)
    assert s.query(DIM_SQL).rows           # the survivor serves


# ---------------------------------------------------------------------------
# in-flight DeviceLost: classify, quarantine, retry once
# ---------------------------------------------------------------------------

def test_device_lost_dispatch_retries_once_on_survivor(pod):
    """The dispatch boundary fault classifies into DeviceLost: the
    placed device is quarantined, the statement retries ONCE on a
    survivor, answers the oracle, and records the retryable 1105
    warning + migration accounting."""
    eng, new_session = pod
    s = new_session()
    oracle = s.query(DIM_SQL).rows         # warm → home is device 0
    mig0 = _ctr_sum("tidb_tpu_statements_migrated_total")
    q0 = _counter("tidb_tpu_device_quarantines_total", 0)
    # hold the readmission gate shut: placement runs opportunistic
    # probes, and on the CPU mesh a bare probe would heal device 0
    # right back mid-test
    failpoint.enable("device-readmit",
                     raise_=RuntimeError("test: still dead"))
    failpoint.enable("device-lost-dispatch",
                     raise_=RuntimeError("test: device lost"), times=1)
    try:
        rows = s.query(DIM_SQL).rows
    finally:
        failpoint.disable("device-lost-dispatch")
        failpoint.disable("device-readmit")
    assert rows == oracle
    g = s.last_guard
    assert g.sched_migrated == 1
    assert g.device_index != 0             # survivor, not the victim
    assert not POOL.health.healthy(0)
    snap = POOL.health.snapshot()
    assert snap[0]["faults"] == 1 and snap[0]["quarantined"]
    assert _counter("tidb_tpu_device_quarantines_total", 0) == q0 + 1
    assert _ctr_sum("tidb_tpu_statements_migrated_total") == mig0 + 1
    warns = s.query("SHOW WARNINGS").rows
    assert any(int(w[1]) == 1105 and "lost" in str(w[2]) for w in warns), \
        warns
    # the dead device's cache shard was evicted with the quarantine
    tid = eng.catalog.info_schema.table("dim").id
    assert not any(k[0] == 0 and k[1] == id(eng.store) and k[2] == tid
                   for k in dc._CACHE), \
        "quarantine must evict the dead device's cache shard"


def test_device_lost_upload_classifies_and_heals(pod):
    """A transfer fault while the COLD shard streams in classifies at
    the upload boundary: same quarantine + one-retry contract, and the
    survivor's re-stream serves the oracle."""
    eng, new_session = pod
    s = new_session()
    s.vars["tidb_tpu_engine"] = "off"
    oracle = s.query(DIM_SQL).rows
    s.vars["tidb_tpu_engine"] = "on"
    failpoint.enable("device-readmit",
                     raise_=RuntimeError("test: still dead"))
    failpoint.enable("device-lost-upload",
                     raise_=RuntimeError("test: transfer fault"), times=1)
    try:
        rows = s.query(DIM_SQL).rows
        assert POOL.health.quarantined_indexes()
    finally:
        failpoint.disable("device-lost-upload")
        failpoint.disable("device-readmit")
    assert failpoint.hits("device-lost-upload") >= 1
    assert rows == oracle
    assert s.last_guard.sched_migrated == 1
    assert s.query(DIM_SQL).rows == oracle     # warm on the survivor


def test_second_device_loss_surfaces_typed_error(pod):
    """The retry is ONCE: a fault that also kills the survivor attempt
    surfaces the typed retryable DeviceLost — never a silent CPU re-run
    that would hide a dead pod."""
    eng, new_session = pod
    s = new_session()
    s.query(DIM_SQL)
    failpoint.enable("device-lost-dispatch",
                     raise_=RuntimeError("test: device lost"))
    try:
        with pytest.raises(DeviceLost) as ei:
            s.query(DIM_SQL)
    finally:
        failpoint.disable("device-lost-dispatch")
    assert ei.value.code == 1105 and ei.value.retryable
    assert failpoint.hits("device-lost-dispatch") == 2
    assert s.query(DIM_SQL).rows               # session still serves


def test_explain_analyze_shows_migrated_marker(pod):
    """EXPLAIN ANALYZE of a statement that survived a device loss shows
    the migrated marker in its runtime info."""
    eng, new_session = pod
    s = new_session()
    s.query(DIM_SQL)
    failpoint.enable("device-lost-dispatch",
                     raise_=RuntimeError("test: device lost"), times=1)
    try:
        rows = s.query("EXPLAIN ANALYZE " + DIM_SQL).rows
    finally:
        failpoint.disable("device-lost-dispatch")
    text = "\n".join(str(c) for r in rows for c in r)
    assert "migrated:1" in text, text


# ---------------------------------------------------------------------------
# quarantine drains the dead device's queue
# ---------------------------------------------------------------------------

def test_quarantine_drains_queued_waiters_to_survivors(pod):
    """Waiters queued on a device when it is quarantined migrate to
    healthy survivors, run exactly once, answer the oracle — and the
    moves are counted as migrations, not steals."""
    eng, new_session = pod
    warm = new_session()
    oracle = warm.query(DIM_SQL).rows
    dev0 = POOL.schedulers[0]
    mig0 = _ctr_sum("tidb_tpu_statements_migrated_total")
    steals0 = sum(sch.stats()["steals"] for sch in POOL.schedulers)

    n = 6
    sessions = [new_session() for _ in range(n)]
    results: dict = {}

    def worker(i):
        try:
            results[i] = sessions[i].query(DIM_SQL).rows
        except TiDBTPUError as e:
            results[i] = ("error", getattr(e, "code", None))

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n)]
    dev0.acquire(conn_id=-1)
    try:
        for th in threads:
            th.start()
        deadline = time.monotonic() + 15.0
        while True:
            with dev0._cv:
                if dev0._stealable >= n:
                    break
            assert time.monotonic() < deadline, "waiters never parked"
            time.sleep(0.005)
        assert POOL.health.report_fault(0, RuntimeError("test: dead"))
        for th in threads:
            th.join(30.0)
            assert not th.is_alive(), "migrated waiter hung"
    finally:
        dev0.release()
    assert all(results.get(i) == oracle for i in range(n)), results
    assert all(sessions[i].last_guard.device_index != 0
               for i in range(n))
    assert _ctr_sum("tidb_tpu_statements_migrated_total") >= mig0 + n
    assert sum(sch.stats()["steals"] for sch in POOL.schedulers) \
        == steals0


def test_steal_race_quarantine_drain_migrates_exactly_once(pod):
    """Satellite 1: a release-into-empty steal racing the quarantine
    drain of the same home queue — both claim through _claim_waiter
    under the home lock, so the waiter is migrated exactly once, runs
    exactly once, and total (steal + migration) accounting is 1."""
    eng, new_session = pod
    s = new_session()
    oracle = s.query(DIM_SQL).rows         # warm → home is device 0
    dev0, dev1 = POOL.schedulers[0], POOL.schedulers[1]
    mig0 = _ctr_sum("tidb_tpu_statements_migrated_total")
    steals0 = sum(sch.stats()["steals"] for sch in POOL.schedulers)
    result: dict = {}

    def rerun():
        try:
            result["rows"] = s.query(DIM_SQL).rows
        except TiDBTPUError as e:  # pragma: no cover — must not happen
            result["err"] = e

    barrier = threading.Barrier(2)

    def do_steal():
        barrier.wait()
        result["stole"] = POOL.steal_into(dev1)

    def do_drain():
        barrier.wait()
        result["quarantined"] = \
            POOL.health.report_fault(0, RuntimeError("test: dead"))

    dev0.acquire(conn_id=-1)
    try:
        th = threading.Thread(target=rerun, daemon=True)
        th.start()
        deadline = time.monotonic() + 10.0
        while True:
            with dev0._cv:
                if dev0._stealable >= 1:
                    break
            assert time.monotonic() < deadline, "waiter never parked"
            time.sleep(0.005)
        racers = [threading.Thread(target=do_steal),
                  threading.Thread(target=do_drain)]
        for r in racers:
            r.start()
        for r in racers:
            r.join(10.0)
            assert not r.is_alive()
    finally:
        dev0.release()
    th.join(15.0)
    assert not th.is_alive(), "raced waiter hung"
    assert result.get("rows") == oracle
    assert result.get("quarantined") is True
    moved = (_ctr_sum("tidb_tpu_statements_migrated_total") - mig0) + \
        (sum(sch.stats()["steals"] for sch in POOL.schedulers) - steals0)
    assert moved == 1, f"waiter must migrate exactly once, moved={moved}"


# ---------------------------------------------------------------------------
# lifecycle on a migrated waiter (satellite 3)
# ---------------------------------------------------------------------------

def _park_migrate(pod, act):
    """Park one victim statement on device 0 (all pool slots held),
    quarantine device 0 so the waiter migrates to a held survivor's
    queue, then run `act(victim)` and return the victim's outcome."""
    eng, new_session = pod
    victim = new_session()
    victim.query(DIM_SQL)                  # warm → home is device 0
    scheds = list(POOL.schedulers)
    result: dict = {}

    def run_victim():
        try:
            victim.execute(DIM_SQL)
            result["outcome"] = "completed"
        except TiDBTPUError as e:
            result["outcome"] = "error"
            result["code"] = getattr(e, "code", None)

    for sch in scheds:
        sch.acquire(conn_id=-1)
    try:
        th = threading.Thread(target=run_victim, daemon=True)
        th.start()
        deadline = time.monotonic() + 10.0
        while True:
            with scheds[0]._cv:
                if scheds[0]._stealable >= 1:
                    break
            assert time.monotonic() < deadline, "victim never parked"
            time.sleep(0.005)
        assert POOL.health.report_fault(0, RuntimeError("test: dead"))
        # migrated onto SOME held survivor's queue (depth 2 = holder +
        # the migrant)
        while not any(sch.queue_depth() > 1 for sch in scheds[1:]):
            assert time.monotonic() < deadline, "migrant never queued"
            time.sleep(0.005)
        t_act = time.monotonic()
        act(victim, new_session)
        th.join(10.0)
        assert not th.is_alive(), "migrated waiter hung"
        assert time.monotonic() - t_act < 5.0
    finally:
        for sch in scheds:
            sch.release()
    assert all(sch.queue_depth() == 0 for sch in scheds)
    return victim, result


def test_kill_lands_on_waiter_migrated_off_quarantined_device(pod):
    """KILL while queued on the migrated-to device: typed 1317."""
    def kill(victim, new_session):
        new_session().execute(f"KILL QUERY {victim.conn_id}")

    victim, result = _park_migrate(pod, kill)
    assert result.get("outcome") == "error", result
    assert result.get("code") == 1317, result
    assert victim.query(DIM_SQL).rows      # session still serves


def test_deadline_lands_on_waiter_migrated_off_quarantined_device(pod):
    """max_execution_time expiring while queued on the migrated-to
    device: typed 3024 (the deadline was armed at admission and rides
    the migration)."""
    def expire(victim, _new_session):
        victim.last_guard.deadline = time.monotonic()

    victim, result = _park_migrate(pod, expire)
    assert result.get("outcome") == "error", result
    assert result.get("code") == 3024, result
    assert victim.query(DIM_SQL).rows


# ---------------------------------------------------------------------------
# cache re-homing (evict_device on a pod-partitioned entry)
# ---------------------------------------------------------------------------

def test_evict_device_rehomes_lost_slabs_onto_survivors(pod):
    """Losing one owner of a pod-partitioned entry nulls ONLY its slab
    ranges (holes + `lost`), re-owns them onto survivors, frees the
    dead buffers, and keeps every untouched owner's arrays by identity;
    the next touch refills exactly the lost slabs onto the new owners
    and still answers the oracle."""
    import jax
    eng, new_session = pod
    s = new_session()
    s.execute("CREATE TABLE facts (a BIGINT, g BIGINT)")
    for base in range(0, 8192, 1024):
        s.execute("INSERT INTO facts VALUES " + ", ".join(
            f"({i}, {i % 7})" for i in range(base, base + 1024)))
    s.vars["tidb_tpu_max_slab_rows"] = 1024
    s.vars["tidb_tpu_partition_min_rows"] = 1000
    full = "SELECT g, COUNT(*), SUM(a) FROM facts GROUP BY g ORDER BY g"
    s.vars["tidb_tpu_engine"] = "off"
    oracle = s.query(full).rows
    s.vars["tidb_tpu_engine"] = "on"
    assert s.query(full).rows == oracle

    tid = eng.catalog.info_schema.table("facts").id
    key = next(k for k in dc._CACHE
               if k[0] == -1 and k[1] == id(eng.store) and k[2] == tid)
    ent = dc._CACHE[key]
    owners0 = list(ent.owners)
    assert len(set(owners0)) > 1
    victim = owners0[0]
    lost = {si for si, o in enumerate(owners0) if o == victim}
    kept = {i: {si: t for si, t in enumerate(slabs)
                if t is not None and si not in lost}
            for i, slabs in ent.dev.items()}
    victim_arrays = [a for slabs in ent.dev.values()
                     for si in sorted(lost) if slabs[si] is not None
                     for a in slabs[si]]
    assert victim_arrays
    survivors = [d for d in range(POOL.size()) if d != victim]

    dc.evict_device(victim, survivors)
    assert ent.lost == lost
    assert all(o != victim for o in ent.owners)
    for i, slabs in ent.dev.items():
        for si in lost:
            assert slabs[si] is None       # lost range nulled
        for si, t in kept[i].items():
            assert slabs[si] is t          # untouched slabs untouched
            assert ent.owners[si] == owners0[si]
    assert all(a.is_deleted() for a in victim_arrays), \
        "dead owner's buffers must be freed NOW, not at GC time"

    # next touch: partial refill of EXACTLY the lost slabs, onto the
    # re-homed owners — untouched arrays stay by identity
    assert s.query(full).rows == oracle
    ent2 = dc._CACHE[key]
    assert ent2 is ent, "partial refill must reuse the entry in place"
    assert not ent.lost
    devs = jax.devices()
    for i, slabs in ent.dev.items():
        for si, t in enumerate(slabs):
            if t is None:
                continue
            for a in t:
                assert _dev_of(a) == devs[ent.owners[si]], \
                    f"col {i} slab {si} off its re-homed owner"
        for si, t in kept[i].items():
            assert slabs[si] is t, "untouched slab was re-uploaded"


# ---------------------------------------------------------------------------
# readmission
# ---------------------------------------------------------------------------

class _G:
    """Bare placement guard stub (no pin, no table profile)."""


def test_readmission_gated_by_probe_then_rejoins(pod):
    """An armed device-readmit gate keeps the device quarantined (the
    flap budget is charged); once the gate clears, the next due probe
    readmits it and least-depth placement returns to device 0."""
    eng, new_session = pod
    s = new_session()
    s.query(DIM_SQL)                       # sizes the pool
    failpoint.enable("device-readmit",
                     raise_=RuntimeError("test: still dead"))
    try:
        assert POOL.health.report_fault(0, RuntimeError("test: dead"))
        assert POOL.place_statement(_G(), conn_id=0) != 0
        deadline = time.monotonic() + 5.0
        while failpoint.hits("device-readmit") == 0:
            assert time.monotonic() < deadline, "probe never ran"
            POOL.health.maybe_readmit()
            time.sleep(0.01)
        assert not POOL.health.healthy(0), \
            "an armed probe gate must keep the device out"
    finally:
        failpoint.disable("device-readmit")

    deadline = time.monotonic() + 10.0
    while not POOL.health.healthy(0):
        assert time.monotonic() < deadline, "device never readmitted"
        POOL.health.maybe_readmit()
        time.sleep(0.01)
    snap = POOL.health.snapshot()
    assert snap[0]["readmissions"] == 1
    assert not snap[0]["quarantined"]
    # placements return: no votes, all queues idle → least depth picks
    # the lowest healthy index again
    assert POOL.place_statement(_G(), conn_id=0) == 0
    d0 = POOL.stats()["devices"]["device0"]
    assert d0["healthy"] is True and d0["readmissions"] == 1
