"""Window functions vs a row-at-a-time python oracle
(ref: executor/window.go semantics; default RANGE frame with ties)."""

import numpy as np
import pytest

from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE w (id BIGINT, g VARCHAR(4), o BIGINT, "
              "x DOUBLE, d DECIMAL(8,2))")
    rng = np.random.default_rng(17)
    rows = []
    for i in range(800):
        g = "NULL" if rng.random() < 0.05 else \
            f"'g{int(rng.integers(0, 6))}'"
        o = "NULL" if rng.random() < 0.05 else str(int(rng.integers(0, 20)))
        x = round(float(rng.normal(0, 10)), 3)
        d = round(float(rng.uniform(0, 50)), 2)
        rows.append(f"({i},{g},{o},{x},{d})")
    s.execute("INSERT INTO w VALUES " + ",".join(rows))
    return s


def fetch(session, sql):
    return session.query(sql).rows


def _partitions(rows, gi):
    parts = {}
    for r in rows:
        parts.setdefault(r[gi], []).append(r)
    return parts


def _okey(o):
    # MySQL ASC NULLS FIRST total order for the oracle
    return (0, 0) if o is None else (1, o)


def test_row_number_rank_dense(session):
    rows = fetch(session,
                 "SELECT id, g, o, "
                 "ROW_NUMBER() OVER (PARTITION BY g ORDER BY o), "
                 "RANK() OVER (PARTITION BY g ORDER BY o), "
                 "DENSE_RANK() OVER (PARTITION BY g ORDER BY o) FROM w")
    for part in _partitions(rows, 1).values():
        part.sort(key=lambda r: _okey(r[2]))
        seen_orders = []
        rank_of = {}
        for i, r in enumerate(part):
            if r[2] not in rank_of:
                rank_of[r[2]] = i + 1
                seen_orders.append(r[2])
        rns = sorted(r[3] for r in part)
        assert rns == list(range(1, len(part) + 1))
        for r in part:
            assert r[4] == rank_of[r[2]], r
            assert r[5] == seen_orders.index(r[2]) + 1, r


def test_full_partition_aggregates(session):
    rows = fetch(session,
                 "SELECT g, x, SUM(x) OVER (PARTITION BY g), "
                 "COUNT(*) OVER (PARTITION BY g), "
                 "MIN(x) OVER (PARTITION BY g), "
                 "MAX(x) OVER (PARTITION BY g), "
                 "AVG(d) OVER (PARTITION BY g) FROM w")
    for part in _partitions(rows, 0).values():
        xs = [r[1] for r in part]
        for r in part:
            assert r[2] == pytest.approx(sum(xs), rel=1e-9)
            assert r[3] == len(part)
            assert r[4] == pytest.approx(min(xs))
            assert r[5] == pytest.approx(max(xs))


def test_running_sum_with_ties(session):
    rows = fetch(session,
                 "SELECT g, o, x, SUM(x) OVER (PARTITION BY g ORDER BY o) "
                 "FROM w")
    for part in _partitions(rows, 0).values():
        part.sort(key=lambda r: _okey(r[1]))
        for r in part:
            # RANGE frame: all rows with o <= current o (peers included)
            expect = sum(p[2] for p in part
                         if _okey(p[1]) <= _okey(r[1]))
            assert r[3] == pytest.approx(expect, rel=1e-9), (r, expect)


def test_lag_lead(session):
    rows = fetch(session,
                 "SELECT id, g, o, x, "
                 "LAG(x) OVER (PARTITION BY g ORDER BY o, id), "
                 "LEAD(x, 2, 0.5) OVER (PARTITION BY g ORDER BY o, id) "
                 "FROM w")
    for part in _partitions(rows, 1).values():
        part.sort(key=lambda r: (_okey(r[2]), r[0]))
        for i, r in enumerate(part):
            expect_lag = part[i - 1][3] if i >= 1 else None
            assert r[4] == (pytest.approx(expect_lag)
                            if expect_lag is not None else None), r
            expect_lead = part[i + 2][3] if i + 2 < len(part) else 0.5
            assert r[5] == pytest.approx(expect_lead), r


def test_running_min_max(session):
    rows = fetch(session,
                 "SELECT g, o, x, MIN(x) OVER (PARTITION BY g ORDER BY o), "
                 "MAX(x) OVER (PARTITION BY g ORDER BY o) FROM w")
    for part in _partitions(rows, 0).values():
        part.sort(key=lambda r: _okey(r[1]))
        for r in part:
            frame = [p[2] for p in part if _okey(p[1]) <= _okey(r[1])]
            assert r[3] == pytest.approx(min(frame)), r
            assert r[4] == pytest.approx(max(frame)), r


def test_window_desc_order(session):
    rows = fetch(session,
                 "SELECT g, o, ROW_NUMBER() OVER "
                 "(PARTITION BY g ORDER BY o DESC) FROM w "
                 "WHERE o IS NOT NULL")
    for part in _partitions(rows, 0).values():
        part.sort(key=lambda r: -r[1])
        by_rn = sorted(part, key=lambda r: r[2])
        os = [r[1] for r in by_rn]
        assert os == sorted(os, reverse=True)


def test_no_partition(session):
    rows = fetch(session, "SELECT id, ROW_NUMBER() OVER (ORDER BY id) "
                          "FROM w")
    rows.sort(key=lambda r: r[0])
    for i, r in enumerate(rows):
        assert r[1] == i + 1


def test_window_with_arithmetic_and_alias(session):
    rows = fetch(session,
                 "SELECT g, RANK() OVER (PARTITION BY g ORDER BY o) + 100 "
                 "AS r100 FROM w")
    assert all(r[1] >= 101 for r in rows)


def test_window_in_where_rejected(session):
    from tidb_tpu.errors import TiDBTPUError
    with pytest.raises(TiDBTPUError):
        session.query("SELECT id FROM w "
                      "WHERE ROW_NUMBER() OVER (ORDER BY id) < 5")


def test_empty_input(session):
    rows = fetch(session, "SELECT g, ROW_NUMBER() OVER (ORDER BY o) "
                          "FROM w WHERE id < 0")
    assert rows == []


# ---- device differential (fragment engine window root) ---------------------

DEVICE_WINDOW_QUERIES = [
    "SELECT g, o, id, ROW_NUMBER() OVER (PARTITION BY g ORDER BY o, id) "
    "FROM w",
    "SELECT g, o, RANK() OVER (PARTITION BY g ORDER BY o), "
    "DENSE_RANK() OVER (PARTITION BY g ORDER BY o) FROM w",
    "SELECT g, SUM(x) OVER (PARTITION BY g), "
    "COUNT(*) OVER (PARTITION BY g), MIN(x) OVER (PARTITION BY g) FROM w",
    "SELECT g, o, SUM(x) OVER (PARTITION BY g ORDER BY o) FROM w",
    "SELECT g, o, MIN(x) OVER (PARTITION BY g ORDER BY o), "
    "MAX(x) OVER (PARTITION BY g ORDER BY o) FROM w",
    "SELECT g, o, id, LAG(x) OVER (PARTITION BY g ORDER BY o, id), "
    "LEAD(x, 2, 0.25) OVER (PARTITION BY g ORDER BY o, id) FROM w",
]


@pytest.mark.parametrize("sql", DEVICE_WINDOW_QUERIES)
def test_device_window_matches_cpu(session, sql):
    from tidb_tpu.executor import build, run_to_completion
    from tidb_tpu.executor.fragment import TpuFragmentExec
    from tidb_tpu.parser import parse
    s = session
    cpu = s.query(sql).rows
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags and all(f.used_device for f in frags), \
            [f.fallback_reason for f in frags]
        dev = [r for ch in chunks for r in ch.rows()]
    finally:
        s.vars["tidb_tpu_engine"] = "off"
    assert len(dev) == len(cpu)
    for a, b in zip(sorted(cpu, key=str), sorted(dev, key=str)):
        for x, y in zip(a, b):
            if isinstance(x, float) and y is not None:
                assert abs(x - y) <= 1e-4 * max(1.0, abs(x)), (a, b)
            else:
                assert x == y, (a, b)


# ---- frame clauses (ROWS BETWEEN …) ----------------------------------------

def _frame_oracle(rows, key, val, pre, post, agg):
    """Brute-force ROWS-frame oracle over (partition_key, value) rows."""
    from collections import defaultdict
    parts = defaultdict(list)
    for i, (k, v) in enumerate(rows):
        parts[k].append((i, v))
    out = {}
    for k, items in parts.items():
        for j, (i, _v) in enumerate(items):
            lo = 0 if pre is None else max(j - pre, 0)
            hi = len(items) - 1 if post is None else min(j + post,
                                                         len(items) - 1)
            window = [v for _, v in items[lo:hi + 1] if v is not None]
            if agg == "sum":
                out[i] = sum(window) if window else None
            elif agg == "count":
                out[i] = len(window)
            elif agg == "min":
                out[i] = min(window) if window else None
            elif agg == "max":
                out[i] = max(window) if window else None
    return out


def test_rows_frame_sum_count_min_max():
    import numpy as np
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE wf (id BIGINT, k BIGINT, v BIGINT)")
    rng = np.random.default_rng(31)
    data = []
    for i in range(400):
        k = int(rng.integers(0, 5))
        v = None if rng.random() < 0.1 else int(rng.integers(0, 100))
        data.append((k, v))
    s.execute("INSERT INTO wf VALUES " + ",".join(
        f"({i},{k},{v if v is not None else 'NULL'})"
        for i, (k, v) in enumerate(data)))
    for agg, pre, post, clause in [
        ("sum", 2, 0, "ROWS BETWEEN 2 PRECEDING AND CURRENT ROW"),
        ("sum", 1, 3, "ROWS BETWEEN 1 PRECEDING AND 3 FOLLOWING"),
        ("count", None, 0, "ROWS UNBOUNDED PRECEDING"),
        ("min", 3, 3, "ROWS BETWEEN 3 PRECEDING AND 3 FOLLOWING"),
        ("max", 0, None,
         "ROWS BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING"),
        ("min", None, 2, "ROWS BETWEEN UNBOUNDED PRECEDING AND "
                         "2 FOLLOWING"),
    ]:
        got = dict(s.query(
            f"SELECT id, {agg.upper()}(v) OVER "
            f"(PARTITION BY k ORDER BY id {clause}) FROM wf").rows)
        want = _frame_oracle(data, "k", "v", pre, post, agg)
        assert got == want, (agg, clause)


def test_first_last_value():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE fv (id BIGINT, k BIGINT, v BIGINT)")
    s.execute("INSERT INTO fv VALUES (1,1,10),(2,1,20),(3,1,20),(4,1,30),"
              "(5,2,7)")
    rows = s.query(
        "SELECT id, FIRST_VALUE(v) OVER (PARTITION BY k ORDER BY v), "
        "LAST_VALUE(v) OVER (PARTITION BY k ORDER BY v) FROM fv "
        "ORDER BY id").rows
    # default frame: last_value ends at the current PEER group (MySQL)
    assert rows == [(1, 10, 10), (2, 10, 20), (3, 10, 20), (4, 10, 30),
                    (5, 7, 7)]
    rows = s.query(
        "SELECT id, LAST_VALUE(v) OVER (PARTITION BY k ORDER BY v "
        "ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) "
        "FROM fv ORDER BY id").rows
    assert rows == [(1, 30), (2, 30), (3, 30), (4, 30), (5, 7)]


def test_frames_on_device():
    import numpy as np
    from tidb_tpu.session import Engine
    from tidb_tpu.executor import build, run_to_completion
    from tidb_tpu.executor.fragment import TpuFragmentExec
    from tidb_tpu.parser import parse
    s = Engine().new_session()
    s.execute("CREATE TABLE wd (id BIGINT, k BIGINT, v BIGINT)")
    rng = np.random.default_rng(13)
    s.execute("INSERT INTO wd VALUES " + ",".join(
        f"({i},{int(rng.integers(0, 7))},{int(rng.integers(0, 50))})"
        for i in range(3000)))
    sql = ("SELECT id, SUM(v) OVER (PARTITION BY k ORDER BY id "
           "ROWS BETWEEN 2 PRECEDING AND 1 FOLLOWING), "
           "MIN(v) OVER (PARTITION BY k ORDER BY id "
           "ROWS BETWEEN 3 PRECEDING AND CURRENT ROW) FROM wd")
    cpu = sorted(map(str, s.query(sql).rows))
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_strict": "on"})
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags and all(f.used_device for f in frags), \
            [f.fallback_reason for f in frags]
        dev = sorted(map(str, (r for ch in chunks for r in ch.rows())))
    finally:
        s.vars.update({"tidb_tpu_engine": "off", "tidb_tpu_strict": "off"})
    assert dev == cpu


def test_frame_edge_cases():
    from tidb_tpu.session import Engine
    import pytest as _pt
    s = Engine().new_session()
    s.execute("CREATE TABLE wfe (id BIGINT, v BIGINT)")
    s.execute("INSERT INTO wfe VALUES (1,10),(2,20),(3,30),(4,40)")
    # fully-FOLLOWING frames run off the partition end: empty -> NULL
    rows = s.query(
        "SELECT id, SUM(v) OVER (ORDER BY id ROWS BETWEEN 2 FOLLOWING "
        "AND 3 FOLLOWING), MIN(v) OVER (ORDER BY id ROWS BETWEEN "
        "2 FOLLOWING AND 3 FOLLOWING) FROM wfe ORDER BY id").rows
    # row 2's window [idx 3, idx 4] clamps to just idx 3; rows 3/4 run
    # entirely off the end → empty frame → NULL
    assert rows == [(1, 70, 30), (2, 40, 40), (3, None, None),
                    (4, None, None)]
    # invalid bounds are clean errors, not crashes
    with _pt.raises(Exception, match="UNBOUNDED FOLLOWING"):
        s.query("SELECT SUM(v) OVER (ORDER BY id ROWS BETWEEN "
                "UNBOUNDED FOLLOWING AND CURRENT ROW) FROM wfe")
    with _pt.raises(Exception, match="shorthand|PRECEDING"):
        s.query("SELECT SUM(v) OVER (ORDER BY id ROWS 2 FOLLOWING) "
                "FROM wfe")
    with _pt.raises(Exception, match="parameter count"):
        s.query("SELECT FIRST_VALUE(v, id) OVER (ORDER BY id) FROM wfe")


def test_rank_family_extras():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE wr (id BIGINT, k BIGINT, v BIGINT)")
    s.execute("INSERT INTO wr VALUES (1,1,10),(2,1,20),(3,1,20),(4,1,40),"
              "(5,2,5),(6,2,6),(7,2,7)")
    rows = s.query(
        "SELECT id, PERCENT_RANK() OVER (PARTITION BY k ORDER BY v), "
        "CUME_DIST() OVER (PARTITION BY k ORDER BY v), "
        "NTILE(2) OVER (PARTITION BY k ORDER BY v), "
        "NTH_VALUE(v, 2) OVER (PARTITION BY k ORDER BY v) "
        "FROM wr ORDER BY id").rows
    # partition k=1: ranks 1,2,2,4 over 4 rows
    assert rows[0][1:] == (0.0, 0.25, 1, None)       # nth frame ends at peer
    assert rows[1][1] == pytest.approx(1 / 3)
    assert rows[1][2] == pytest.approx(0.75)
    assert rows[1][3] == 1 and rows[1][4] == 20
    assert rows[2][1] == pytest.approx(1 / 3)
    assert rows[2][3] == 2 and rows[2][4] == 20
    assert rows[3][1:] == (1.0, 1.0, 2, 20)
    # partition k=2: 3 rows, NTILE(2) → buckets 1,1,2
    assert [r[3] for r in rows[4:]] == [1, 1, 2]
    # NTH_VALUE with an explicit full frame sees the whole partition
    rows = s.query(
        "SELECT id, NTH_VALUE(v, 3) OVER (PARTITION BY k ORDER BY v "
        "ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) "
        "FROM wr ORDER BY id").rows
    assert [r[1] for r in rows] == [20, 20, 20, 20, 7, 7, 7]
