"""Window functions vs a row-at-a-time python oracle
(ref: executor/window.go semantics; default RANGE frame with ties)."""

import numpy as np
import pytest

from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE w (id BIGINT, g VARCHAR(4), o BIGINT, "
              "x DOUBLE, d DECIMAL(8,2))")
    rng = np.random.default_rng(17)
    rows = []
    for i in range(800):
        g = "NULL" if rng.random() < 0.05 else \
            f"'g{int(rng.integers(0, 6))}'"
        o = "NULL" if rng.random() < 0.05 else str(int(rng.integers(0, 20)))
        x = round(float(rng.normal(0, 10)), 3)
        d = round(float(rng.uniform(0, 50)), 2)
        rows.append(f"({i},{g},{o},{x},{d})")
    s.execute("INSERT INTO w VALUES " + ",".join(rows))
    return s


def fetch(session, sql):
    return session.query(sql).rows


def _partitions(rows, gi):
    parts = {}
    for r in rows:
        parts.setdefault(r[gi], []).append(r)
    return parts


def _okey(o):
    # MySQL ASC NULLS FIRST total order for the oracle
    return (0, 0) if o is None else (1, o)


def test_row_number_rank_dense(session):
    rows = fetch(session,
                 "SELECT id, g, o, "
                 "ROW_NUMBER() OVER (PARTITION BY g ORDER BY o), "
                 "RANK() OVER (PARTITION BY g ORDER BY o), "
                 "DENSE_RANK() OVER (PARTITION BY g ORDER BY o) FROM w")
    for part in _partitions(rows, 1).values():
        part.sort(key=lambda r: _okey(r[2]))
        seen_orders = []
        rank_of = {}
        for i, r in enumerate(part):
            if r[2] not in rank_of:
                rank_of[r[2]] = i + 1
                seen_orders.append(r[2])
        rns = sorted(r[3] for r in part)
        assert rns == list(range(1, len(part) + 1))
        for r in part:
            assert r[4] == rank_of[r[2]], r
            assert r[5] == seen_orders.index(r[2]) + 1, r


def test_full_partition_aggregates(session):
    rows = fetch(session,
                 "SELECT g, x, SUM(x) OVER (PARTITION BY g), "
                 "COUNT(*) OVER (PARTITION BY g), "
                 "MIN(x) OVER (PARTITION BY g), "
                 "MAX(x) OVER (PARTITION BY g), "
                 "AVG(d) OVER (PARTITION BY g) FROM w")
    for part in _partitions(rows, 0).values():
        xs = [r[1] for r in part]
        for r in part:
            assert r[2] == pytest.approx(sum(xs), rel=1e-9)
            assert r[3] == len(part)
            assert r[4] == pytest.approx(min(xs))
            assert r[5] == pytest.approx(max(xs))


def test_running_sum_with_ties(session):
    rows = fetch(session,
                 "SELECT g, o, x, SUM(x) OVER (PARTITION BY g ORDER BY o) "
                 "FROM w")
    for part in _partitions(rows, 0).values():
        part.sort(key=lambda r: _okey(r[1]))
        for r in part:
            # RANGE frame: all rows with o <= current o (peers included)
            expect = sum(p[2] for p in part
                         if _okey(p[1]) <= _okey(r[1]))
            assert r[3] == pytest.approx(expect, rel=1e-9), (r, expect)


def test_lag_lead(session):
    rows = fetch(session,
                 "SELECT id, g, o, x, "
                 "LAG(x) OVER (PARTITION BY g ORDER BY o, id), "
                 "LEAD(x, 2, 0.5) OVER (PARTITION BY g ORDER BY o, id) "
                 "FROM w")
    for part in _partitions(rows, 1).values():
        part.sort(key=lambda r: (_okey(r[2]), r[0]))
        for i, r in enumerate(part):
            expect_lag = part[i - 1][3] if i >= 1 else None
            assert r[4] == (pytest.approx(expect_lag)
                            if expect_lag is not None else None), r
            expect_lead = part[i + 2][3] if i + 2 < len(part) else 0.5
            assert r[5] == pytest.approx(expect_lead), r


def test_running_min_max(session):
    rows = fetch(session,
                 "SELECT g, o, x, MIN(x) OVER (PARTITION BY g ORDER BY o), "
                 "MAX(x) OVER (PARTITION BY g ORDER BY o) FROM w")
    for part in _partitions(rows, 0).values():
        part.sort(key=lambda r: _okey(r[1]))
        for r in part:
            frame = [p[2] for p in part if _okey(p[1]) <= _okey(r[1])]
            assert r[3] == pytest.approx(min(frame)), r
            assert r[4] == pytest.approx(max(frame)), r


def test_window_desc_order(session):
    rows = fetch(session,
                 "SELECT g, o, ROW_NUMBER() OVER "
                 "(PARTITION BY g ORDER BY o DESC) FROM w "
                 "WHERE o IS NOT NULL")
    for part in _partitions(rows, 0).values():
        part.sort(key=lambda r: -r[1])
        by_rn = sorted(part, key=lambda r: r[2])
        os = [r[1] for r in by_rn]
        assert os == sorted(os, reverse=True)


def test_no_partition(session):
    rows = fetch(session, "SELECT id, ROW_NUMBER() OVER (ORDER BY id) "
                          "FROM w")
    rows.sort(key=lambda r: r[0])
    for i, r in enumerate(rows):
        assert r[1] == i + 1


def test_window_with_arithmetic_and_alias(session):
    rows = fetch(session,
                 "SELECT g, RANK() OVER (PARTITION BY g ORDER BY o) + 100 "
                 "AS r100 FROM w")
    assert all(r[1] >= 101 for r in rows)


def test_window_in_where_rejected(session):
    from tidb_tpu.errors import TiDBTPUError
    with pytest.raises(TiDBTPUError):
        session.query("SELECT id FROM w "
                      "WHERE ROW_NUMBER() OVER (ORDER BY id) < 5")


def test_empty_input(session):
    rows = fetch(session, "SELECT g, ROW_NUMBER() OVER (ORDER BY o) "
                          "FROM w WHERE id < 0")
    assert rows == []


# ---- device differential (fragment engine window root) ---------------------

DEVICE_WINDOW_QUERIES = [
    "SELECT g, o, id, ROW_NUMBER() OVER (PARTITION BY g ORDER BY o, id) "
    "FROM w",
    "SELECT g, o, RANK() OVER (PARTITION BY g ORDER BY o), "
    "DENSE_RANK() OVER (PARTITION BY g ORDER BY o) FROM w",
    "SELECT g, SUM(x) OVER (PARTITION BY g), "
    "COUNT(*) OVER (PARTITION BY g), MIN(x) OVER (PARTITION BY g) FROM w",
    "SELECT g, o, SUM(x) OVER (PARTITION BY g ORDER BY o) FROM w",
    "SELECT g, o, MIN(x) OVER (PARTITION BY g ORDER BY o), "
    "MAX(x) OVER (PARTITION BY g ORDER BY o) FROM w",
    "SELECT g, o, id, LAG(x) OVER (PARTITION BY g ORDER BY o, id), "
    "LEAD(x, 2, 0.25) OVER (PARTITION BY g ORDER BY o, id) FROM w",
]


@pytest.mark.parametrize("sql", DEVICE_WINDOW_QUERIES)
def test_device_window_matches_cpu(session, sql):
    from tidb_tpu.executor import build, run_to_completion
    from tidb_tpu.executor.fragment import TpuFragmentExec
    from tidb_tpu.parser import parse
    s = session
    cpu = s.query(sql).rows
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags and all(f.used_device for f in frags), \
            [f.fallback_reason for f in frags]
        dev = [r for ch in chunks for r in ch.rows()]
    finally:
        s.vars["tidb_tpu_engine"] = "off"
    assert len(dev) == len(cpu)
    for a, b in zip(sorted(cpu, key=str), sorted(dev, key=str)):
        for x, y in zip(a, b):
            if isinstance(x, float) and y is not None:
                assert abs(x - y) <= 1e-4 * max(1.0, abs(x)), (a, b)
            else:
                assert x == y, (a, b)


# ---- frame clauses (ROWS BETWEEN …) ----------------------------------------

def _frame_oracle(rows, key, val, pre, post, agg):
    """Brute-force ROWS-frame oracle over (partition_key, value) rows."""
    from collections import defaultdict
    parts = defaultdict(list)
    for i, (k, v) in enumerate(rows):
        parts[k].append((i, v))
    out = {}
    for k, items in parts.items():
        for j, (i, _v) in enumerate(items):
            lo = 0 if pre is None else max(j - pre, 0)
            hi = len(items) - 1 if post is None else min(j + post,
                                                         len(items) - 1)
            window = [v for _, v in items[lo:hi + 1] if v is not None]
            if agg == "sum":
                out[i] = sum(window) if window else None
            elif agg == "count":
                out[i] = len(window)
            elif agg == "min":
                out[i] = min(window) if window else None
            elif agg == "max":
                out[i] = max(window) if window else None
    return out


def test_rows_frame_sum_count_min_max():
    import numpy as np
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE wf (id BIGINT, k BIGINT, v BIGINT)")
    rng = np.random.default_rng(31)
    data = []
    for i in range(400):
        k = int(rng.integers(0, 5))
        v = None if rng.random() < 0.1 else int(rng.integers(0, 100))
        data.append((k, v))
    s.execute("INSERT INTO wf VALUES " + ",".join(
        f"({i},{k},{v if v is not None else 'NULL'})"
        for i, (k, v) in enumerate(data)))
    for agg, pre, post, clause in [
        ("sum", 2, 0, "ROWS BETWEEN 2 PRECEDING AND CURRENT ROW"),
        ("sum", 1, 3, "ROWS BETWEEN 1 PRECEDING AND 3 FOLLOWING"),
        ("count", None, 0, "ROWS UNBOUNDED PRECEDING"),
        ("min", 3, 3, "ROWS BETWEEN 3 PRECEDING AND 3 FOLLOWING"),
        ("max", 0, None,
         "ROWS BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING"),
        ("min", None, 2, "ROWS BETWEEN UNBOUNDED PRECEDING AND "
                         "2 FOLLOWING"),
    ]:
        got = dict(s.query(
            f"SELECT id, {agg.upper()}(v) OVER "
            f"(PARTITION BY k ORDER BY id {clause}) FROM wf").rows)
        want = _frame_oracle(data, "k", "v", pre, post, agg)
        assert got == want, (agg, clause)


def test_first_last_value():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE fv (id BIGINT, k BIGINT, v BIGINT)")
    s.execute("INSERT INTO fv VALUES (1,1,10),(2,1,20),(3,1,20),(4,1,30),"
              "(5,2,7)")
    rows = s.query(
        "SELECT id, FIRST_VALUE(v) OVER (PARTITION BY k ORDER BY v), "
        "LAST_VALUE(v) OVER (PARTITION BY k ORDER BY v) FROM fv "
        "ORDER BY id").rows
    # default frame: last_value ends at the current PEER group (MySQL)
    assert rows == [(1, 10, 10), (2, 10, 20), (3, 10, 20), (4, 10, 30),
                    (5, 7, 7)]
    rows = s.query(
        "SELECT id, LAST_VALUE(v) OVER (PARTITION BY k ORDER BY v "
        "ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) "
        "FROM fv ORDER BY id").rows
    assert rows == [(1, 30), (2, 30), (3, 30), (4, 30), (5, 7)]


def test_frames_on_device():
    import numpy as np
    from tidb_tpu.session import Engine
    from tidb_tpu.executor import build, run_to_completion
    from tidb_tpu.executor.fragment import TpuFragmentExec
    from tidb_tpu.parser import parse
    s = Engine().new_session()
    s.execute("CREATE TABLE wd (id BIGINT, k BIGINT, v BIGINT)")
    rng = np.random.default_rng(13)
    s.execute("INSERT INTO wd VALUES " + ",".join(
        f"({i},{int(rng.integers(0, 7))},{int(rng.integers(0, 50))})"
        for i in range(3000)))
    sql = ("SELECT id, SUM(v) OVER (PARTITION BY k ORDER BY id "
           "ROWS BETWEEN 2 PRECEDING AND 1 FOLLOWING), "
           "MIN(v) OVER (PARTITION BY k ORDER BY id "
           "ROWS BETWEEN 3 PRECEDING AND CURRENT ROW) FROM wd")
    cpu = sorted(map(str, s.query(sql).rows))
    s.vars.update({"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
                   "tidb_tpu_strict": "on"})
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags and all(f.used_device for f in frags), \
            [f.fallback_reason for f in frags]
        dev = sorted(map(str, (r for ch in chunks for r in ch.rows())))
    finally:
        s.vars.update({"tidb_tpu_engine": "off", "tidb_tpu_strict": "off"})
    assert dev == cpu


def test_frame_edge_cases():
    from tidb_tpu.session import Engine
    import pytest as _pt
    s = Engine().new_session()
    s.execute("CREATE TABLE wfe (id BIGINT, v BIGINT)")
    s.execute("INSERT INTO wfe VALUES (1,10),(2,20),(3,30),(4,40)")
    # fully-FOLLOWING frames run off the partition end: empty -> NULL
    rows = s.query(
        "SELECT id, SUM(v) OVER (ORDER BY id ROWS BETWEEN 2 FOLLOWING "
        "AND 3 FOLLOWING), MIN(v) OVER (ORDER BY id ROWS BETWEEN "
        "2 FOLLOWING AND 3 FOLLOWING) FROM wfe ORDER BY id").rows
    # row 2's window [idx 3, idx 4] clamps to just idx 3; rows 3/4 run
    # entirely off the end → empty frame → NULL
    assert rows == [(1, 70, 30), (2, 40, 40), (3, None, None),
                    (4, None, None)]
    # invalid bounds are clean errors, not crashes
    with _pt.raises(Exception, match="UNBOUNDED FOLLOWING"):
        s.query("SELECT SUM(v) OVER (ORDER BY id ROWS BETWEEN "
                "UNBOUNDED FOLLOWING AND CURRENT ROW) FROM wfe")
    with _pt.raises(Exception, match="shorthand|PRECEDING"):
        s.query("SELECT SUM(v) OVER (ORDER BY id ROWS 2 FOLLOWING) "
                "FROM wfe")
    with _pt.raises(Exception, match="parameter count"):
        s.query("SELECT FIRST_VALUE(v, id) OVER (ORDER BY id) FROM wfe")


def test_rank_family_extras():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE wr (id BIGINT, k BIGINT, v BIGINT)")
    s.execute("INSERT INTO wr VALUES (1,1,10),(2,1,20),(3,1,20),(4,1,40),"
              "(5,2,5),(6,2,6),(7,2,7)")
    rows = s.query(
        "SELECT id, PERCENT_RANK() OVER (PARTITION BY k ORDER BY v), "
        "CUME_DIST() OVER (PARTITION BY k ORDER BY v), "
        "NTILE(2) OVER (PARTITION BY k ORDER BY v), "
        "NTH_VALUE(v, 2) OVER (PARTITION BY k ORDER BY v) "
        "FROM wr ORDER BY id").rows
    # partition k=1: ranks 1,2,2,4 over 4 rows
    assert rows[0][1:] == (0.0, 0.25, 1, None)       # nth frame ends at peer
    assert rows[1][1] == pytest.approx(1 / 3)
    assert rows[1][2] == pytest.approx(0.75)
    assert rows[1][3] == 1 and rows[1][4] == 20
    assert rows[2][1] == pytest.approx(1 / 3)
    assert rows[2][3] == 2 and rows[2][4] == 20
    assert rows[3][1:] == (1.0, 1.0, 2, 20)
    # partition k=2: 3 rows, NTILE(2) → buckets 1,1,2
    assert [r[3] for r in rows[4:]] == [1, 1, 2]
    # NTH_VALUE with an explicit full frame sees the whole partition
    rows = s.query(
        "SELECT id, NTH_VALUE(v, 3) OVER (PARTITION BY k ORDER BY v "
        "ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) "
        "FROM wr ORDER BY id").rows
    assert [r[1] for r in rows] == [20, 20, 20, 20, 7, 7, 7]


# ---- RANGE frames with offsets ---------------------------------------------

def _range_oracle(rows, pre, post, agg, desc=False):
    """Positional oracle over (part, okey, val) rows, MySQL RANGE
    semantics: sort each partition by key (NULLs first ASC / last DESC);
    a NULL row's offset bound is its NULL-block edge; unbounded sides
    reach the partition edges (and thus include NULL-key rows); non-NULL
    offset bounds never include NULLs."""
    from collections import defaultdict
    parts = defaultdict(list)
    for i, (p, k, v) in enumerate(rows):
        parts[p].append((i, k, v))
    out = {}
    for items in parts.values():
        items = sorted(items, key=lambda t: (
            (t[1] is None) == desc, (-t[1] if desc else t[1])
            if t[1] is not None else 0))
        n = len(items)
        null_pos = [j for j, (_i, k, _v) in enumerate(items)
                    if k is None]
        for j, (i, k, _v) in enumerate(items):
            if k is None:
                lo = 0 if pre is None else null_pos[0]
                hi = n - 1 if post is None else null_pos[-1]
            else:
                def inside(kk):
                    lo_ok = pre is None or (
                        kk >= k - pre if not desc else kk <= k + pre)
                    hi_ok = post is None or (
                        kk <= k + post if not desc else kk >= k - post)
                    return lo_ok and hi_ok
                ok_pos = [jj for jj, (_x, kk, _y) in enumerate(items)
                          if kk is not None and inside(kk)]
                lo = 0 if pre is None else (min(ok_pos) if ok_pos
                                            else n)
                hi = n - 1 if post is None else (max(ok_pos) if ok_pos
                                                 else -1)
            window = [v for _x, _k, v in items[lo:hi + 1]
                      if v is not None]
            if agg == "sum":
                out[i] = sum(window) if window else None
            elif agg == "count":
                out[i] = len(window)
    return out


def _mk_range_table(s, name, with_nulls=True):
    import numpy as np
    rng = np.random.default_rng(41)
    data = []
    for _ in range(500):
        p = int(rng.integers(0, 4))
        k = None if (with_nulls and rng.random() < 0.08) \
            else int(rng.integers(0, 40))
        v = None if rng.random() < 0.1 else int(rng.integers(0, 100))
        data.append((p, k, v))
    s.execute(f"CREATE TABLE {name} (id BIGINT, p BIGINT, k BIGINT, "
              f"v BIGINT)")
    s.execute(f"INSERT INTO {name} VALUES " + ",".join(
        f"({i},{p},{'NULL' if k is None else k},"
        f"{'NULL' if v is None else v})"
        for i, (p, k, v) in enumerate(data)))
    return data


def test_range_frame_sum_count():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    data = _mk_range_table(s, "rf")
    for agg, pre, post, clause in [
        ("sum", 3, 0, "RANGE BETWEEN 3 PRECEDING AND CURRENT ROW"),
        ("sum", 2, 5, "RANGE BETWEEN 2 PRECEDING AND 5 FOLLOWING"),
        ("count", 0, 0, "RANGE BETWEEN CURRENT ROW AND CURRENT ROW"),
        ("sum", None, 1,
         "RANGE BETWEEN UNBOUNDED PRECEDING AND 1 FOLLOWING"),
        ("count", 4, None,
         "RANGE BETWEEN 4 PRECEDING AND UNBOUNDED FOLLOWING"),
        ("sum", 3, 3, "RANGE 3 PRECEDING"),     # shorthand: end=current…
    ]:
        if clause.endswith("3 PRECEDING") and "BETWEEN" not in clause:
            post = 0
        got = dict(s.query(
            f"SELECT id, {agg.upper()}(v) OVER "
            f"(PARTITION BY p ORDER BY k {clause}) FROM rf").rows)
        want = _range_oracle(data, pre, post, agg)
        assert got == want, (agg, clause,
                             {i: (got[i], want[i]) for i in got
                              if got[i] != want[i]})


def test_range_frame_desc():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    data = _mk_range_table(s, "rfd")
    got = dict(s.query(
        "SELECT id, SUM(v) OVER (PARTITION BY p ORDER BY k DESC "
        "RANGE BETWEEN 3 PRECEDING AND CURRENT ROW) FROM rfd").rows)
    want = _range_oracle(data, 3, 0, "sum", desc=True)
    assert got == want


def test_range_frame_first_last_value():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE rfv (id BIGINT, k BIGINT, v BIGINT)")
    s.execute("INSERT INTO rfv VALUES (1,1,10),(2,2,20),(3,4,40),"
              "(4,5,50),(5,9,90)")
    rows = s.query(
        "SELECT id, FIRST_VALUE(v) OVER (ORDER BY k "
        "RANGE BETWEEN 2 PRECEDING AND 1 FOLLOWING), "
        "LAST_VALUE(v) OVER (ORDER BY k "
        "RANGE BETWEEN 2 PRECEDING AND 1 FOLLOWING) FROM rfv "
        "ORDER BY id").rows
    # frames: k=1→{1,2}; k=2→{1,2}; k=4→{2,4,5}; k=5→{4,5}; k=9→{9}
    assert rows == [(1, 10, 20), (2, 10, 20), (3, 20, 50),
                    (4, 40, 50), (5, 90, 90)]


def test_range_frame_decimal_key_scaled_offsets():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE rdc (id BIGINT, k DECIMAL(8,2), v BIGINT)")
    s.execute("INSERT INTO rdc VALUES (1,'1.00',1),(2,'1.75',2),"
              "(3,'2.00',4),(4,'3.50',8),(5,'9.00',16)")
    got = dict(s.query(
        "SELECT id, SUM(v) OVER (ORDER BY k RANGE BETWEEN 1 PRECEDING "
        "AND CURRENT ROW) FROM rdc").rows)
    # offsets scale into DECIMAL units: 1 ⇒ 1.00
    assert got == {1: 1, 2: 3, 3: 7, 4: 8, 5: 16}


def test_range_frame_errors():
    import pytest
    from tidb_tpu.errors import PlanError
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    s.execute("CREATE TABLE rfe (id BIGINT, a BIGINT, b VARCHAR(4), "
              "v BIGINT)")
    s.execute("INSERT INTO rfe VALUES (1,1,'x',1)")
    with pytest.raises(PlanError, match="exactly one ORDER BY"):
        s.query("SELECT SUM(v) OVER (ORDER BY id, a RANGE BETWEEN 1 "
                "PRECEDING AND CURRENT ROW) FROM rfe")
    with pytest.raises(PlanError, match="numeric or temporal"):
        s.query("SELECT SUM(v) OVER (ORDER BY b RANGE BETWEEN 1 "
                "PRECEDING AND CURRENT ROW) FROM rfe")
    with pytest.raises(PlanError, match="ROWS frame"):
        s.query("SELECT MIN(v) OVER (ORDER BY a RANGE BETWEEN 1 "
                "PRECEDING AND CURRENT ROW) FROM rfe")


def test_range_frame_device_matches_cpu():
    from tidb_tpu.session import Engine
    s = Engine().new_session()
    _mk_range_table(s, "rdev")
    s.execute("ANALYZE TABLE rdev")
    sql = ("SELECT id, SUM(v) OVER (PARTITION BY p ORDER BY k "
           "RANGE BETWEEN 3 PRECEDING AND 2 FOLLOWING), "
           "COUNT(v) OVER (PARTITION BY p ORDER BY k DESC "
           "RANGE BETWEEN 1 PRECEDING AND CURRENT ROW) FROM rdev")
    want = sorted(map(str, s.query(sql).rows))
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_strict="on")
    try:
        got = sorted(map(str, s.query(sql).rows))
    finally:
        s.vars.update(tidb_tpu_engine="off", tidb_tpu_strict="off")
    assert got == want


def test_warm_window_launch_count(session):
    """Warm single-fragment window query stays <= slabs + 1 programs:
    the segmented scans ride inside the fused program, not extra
    launches."""
    s = session
    sql = DEVICE_WINDOW_QUERIES[0]
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        s.query(sql)               # compile + first touch
        s.query(sql)               # warm
        ph = s.last_guard.phases
        # 800 rows pad into one slab: one fused program (+ finalize)
        assert 1 <= ph.programs_launched <= 2, ph.programs_launched
    finally:
        s.vars["tidb_tpu_engine"] = "off"
