"""Delta slabs: incremental extension of device-cached tables.

A committed write no longer invalidates a cached table wholesale: when
the region diff is expressible as appended rows + tombstones, the cache
grows a NEW generation that shares every untouched base device array
with its predecessor, uploads one delta slab for the appended rows, and
rewrites tombstoned base slabs in-trace (executor/delta.py +
device_emit.emit_delta_merge). These tests pin:

* oracle equality through inserts, scattered deletes, mixed
  insert+delete on one generation, and deletes that land in the delta
  slab itself (cumulative re-diff);
* base-array SHARING — an extension must not re-upload base slabs;
* the decline ladder — a value the base layouts cannot carry (a new
  dictionary string) rebuilds from scratch, never a wrong merge;
* the `delta-merge-stale` failpoint → typed LayoutError → warned CPU
  fallback with oracle rows, then a clean extension once disarmed;
* threshold-scheduled compaction: the rebuilt generation drops
  `is_delta`, re-chooses layouts, and answers the oracle; a fault at
  `compaction-commit` abandons the rebuild (buffers deleted) while the
  old base+delta generation keeps serving byte-exactly, and the next
  extension re-schedules the job (heals);
* eviction/invalidation of a delta generation deletes the DELTA device
  arrays too — no HBM leak (the satellite-2 guarantee).
"""

import numpy as np
import pytest

from tidb_tpu.executor import delta
from tidb_tpu.executor import device_cache as dc
from tidb_tpu.session import Engine
from tidb_tpu.util import failpoint
from tidb_tpu.util.observability import REGISTRY


def _engine(compression="on"):
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE t (a BIGINT, b BIGINT, c VARCHAR(10))")
    # non-monotonic b: choose_layout must pick pack/raw (delta-kind
    # layouts decline tombstones by design)
    s.execute("INSERT INTO t VALUES " + ",".join(
        f"({i % 40}, {(i * 7919) % 5000}, 'k{i % 5}')"
        for i in range(3000)))
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    s.vars["tidb_tpu_compression"] = compression
    s.vars["tidb_tpu_compaction"] = "off"   # drain by hand, deterministic
    return eng, s


Q = "SELECT a, COUNT(*), SUM(b) FROM t GROUP BY a ORDER BY a"


def _oracle(s, q=Q):
    s.vars["tidb_tpu_engine"] = "off"
    try:
        return s.query(q).rows
    finally:
        s.vars["tidb_tpu_engine"] = "on"


def _entry(eng, name="t"):
    tid = eng.catalog.info_schema.table(name).id
    for (_dev, sid, t, _parts), ent in dc._CACHE.items():
        if sid == id(eng.store) and t == tid:
            return ent
    raise AssertionError(f"table {name} not cached")


def _base_ids(ent):
    """id() of every base-slab device array, per column."""
    n_base = ent.base_slabs
    return {i: [None if t is None else tuple(id(a) for a in t)
                for t in slabs[:n_base]]
            for i, slabs in ent.dev.items()}


@pytest.mark.parametrize("compression", ["on", "off"])
def test_insert_extends_without_reupload(compression):
    eng, s = _engine(compression)
    s.query(Q)
    ent0 = _entry(eng)
    ids0 = _base_ids(ent0)
    s.query("INSERT INTO t VALUES (3, 1234, 'k2')")
    rows = s.query(Q).rows
    ent1 = _entry(eng)
    assert ent1 is not ent0 and ent1.is_delta
    assert ent1.delta_rows == 1
    # no tombstones → every base device array is SHARED, not re-encoded
    assert _base_ids(ent1) == ids0, "extension re-uploaded base slabs"
    assert rows == _oracle(s)


@pytest.mark.parametrize("compression", ["on", "off"])
def test_tombstones_and_mixed_writes(compression):
    eng, s = _engine(compression)
    s.query(Q)
    s.query("DELETE FROM t WHERE b % 97 = 3")
    rows = s.query(Q).rows
    ent = _entry(eng)
    assert ent.is_delta and any(len(v) for v in ent.tomb.values())
    assert rows == _oracle(s)
    # mixed insert + delete on the SAME generation
    s.query("INSERT INTO t VALUES (3, 1234, 'k2')")
    s.query("DELETE FROM t WHERE b = 4998")
    assert s.query(Q).rows == _oracle(s)
    # delete the row that lives in the DELTA slab (cumulative re-diff)
    s.query("DELETE FROM t WHERE b = 1234 AND a = 3")
    assert s.query(Q).rows == _oracle(s)
    # dictionary-string path still correct on the delta generation
    q2 = "SELECT c, COUNT(*) FROM t WHERE a < 10 GROUP BY c ORDER BY c"
    assert s.query(q2).rows == _oracle(s, q2)


def test_new_dictionary_string_declines_to_rebuild():
    eng, s = _engine()
    q2 = "SELECT c, COUNT(*) FROM t GROUP BY c ORDER BY c"
    s.query(q2)                     # cache covers the dictionary column
    # 'zzz' is not in the base dictionary: the extension must DECLINE
    # and the open falls back to a full rebuild — never a wrong merge
    s.query("INSERT INTO t VALUES (1, 1, 'zzz')")
    rows = s.query(q2).rows
    ent = _entry(eng)
    assert not ent.is_delta, "un-encodable append must rebuild, not merge"
    assert rows == _oracle(s, q2)
    assert s.query(Q).rows == _oracle(s)


def test_delta_version_in_plan_keys():
    """Two generations of the same table must never share a specialized
    program: the fragment spec key carries delta_version."""
    eng, s = _engine()
    s.query(Q)
    v0 = _entry(eng).delta_version
    s.query("INSERT INTO t VALUES (3, 1234, 'k2')")
    s.query(Q)
    v1 = _entry(eng).delta_version
    assert v1 > v0


def test_delta_merge_stale_fault_warned_cpu_fallback():
    eng, s = _engine()
    s.query(Q)
    s.query("INSERT INTO t VALUES (3, 1234, 'k2')")
    oracle = _oracle(s)
    failpoint.enable("delta-merge-stale", value="test: stale diff")
    try:
        rows = s.query(Q).rows
        assert failpoint.hits("delta-merge-stale") > 0
        assert rows == oracle, "fallback must still return oracle rows"
    finally:
        failpoint.disable("delta-merge-stale")
    # disarmed: the extension engages and keeps answering the oracle
    rows2 = s.query(Q).rows
    assert rows2 == oracle
    ent = _entry(eng)
    assert ent.is_delta and ent.delta_rows == 1


def test_compaction_rebuilds_and_drops_delta():
    eng, s = _engine()
    s.vars["tidb_tpu_delta_compact_rows"] = 4
    s.query(Q)
    for i in range(5):
        s.query(f"INSERT INTO t VALUES ({i % 40}, {i * 7 % 5000}, 'k1')")
    s.query(Q)
    assert _entry(eng).is_delta
    assert delta.pending_compactions() >= 1
    oracle = _oracle(s)
    assert delta.run_pending_compactions() == 1
    ent = _entry(eng)
    assert not ent.is_delta, "compaction must fold the delta into base"
    assert ent.delta_rows == 0 and not any(
        len(v) for v in getattr(ent, "tomb", {}).values())
    assert s.query(Q).rows == oracle
    key = ("tidb_tpu_compactions_total",
           (("table", str(eng.catalog.info_schema.table("t").id)),))
    assert REGISTRY.counters.get(key, 0) >= 1


def test_compaction_commit_fault_old_generation_serves():
    eng, s = _engine()
    s.vars["tidb_tpu_delta_compact_rows"] = 4
    s.query(Q)
    s.query("DELETE FROM t WHERE b % 499 = 7")   # tombstones too
    for i in range(5):
        s.query(f"INSERT INTO t VALUES ({i % 40}, {i * 7 % 5000}, 'k1')")
    warm = s.query(Q).rows
    ent0 = _entry(eng)
    assert ent0.is_delta and delta.pending_compactions() >= 1
    failpoint.enable("compaction-commit",
                     raise_=RuntimeError("chaos: compaction fault"))
    try:
        assert delta.run_pending_compactions() == 0
    finally:
        failpoint.disable("compaction-commit")
    assert failpoint.hits("compaction-commit") > 0
    # the old base+delta generation is UNTOUCHED and serves byte-exactly
    assert _entry(eng) is ent0
    assert s.query(Q).rows == warm == _oracle(s)
    # the next extension past the threshold re-schedules — compaction
    # HEALS once the fault clears
    s.query("INSERT INTO t VALUES (9, 99, 'k0')")
    s.query(Q)
    assert delta.pending_compactions() >= 1
    assert delta.run_pending_compactions() == 1
    ent2 = _entry(eng)
    assert not ent2.is_delta
    assert s.query(Q).rows == _oracle(s)


def test_compaction_skips_fresh_and_evicted_entries():
    eng, s = _engine()
    s.vars["tidb_tpu_delta_compact_rows"] = 1
    s.query(Q)
    s.query("INSERT INTO t VALUES (3, 1234, 'k2')")
    s.query(Q)
    assert delta.pending_compactions() == 1
    dc.clear()                      # entry evicted before the drain runs
    assert delta.run_pending_compactions() == 0, \
        "an evicted entry must not be rebuilt behind the cache's back"


def test_invalidation_frees_delta_device_arrays():
    """Satellite: evicting a delta generation must jax.Array.delete()
    the delta-slab and rewritten-keep arrays too — device memory for a
    dropped generation is freed NOW, not at GC time."""
    eng, s = _engine()
    s.query(Q)
    s.query("DELETE FROM t WHERE b % 97 = 3")
    s.query("INSERT INTO t VALUES (3, 1234, 'k2')")
    s.query(Q)
    ent = _entry(eng)
    assert ent.is_delta
    arrays = [a for slabs in ent.dev.values() for t in slabs
              if t is not None for a in t]
    assert arrays
    tid = eng.catalog.info_schema.table("t").id
    dc.invalidate(tid)
    leaked = [a for a in arrays if not a.is_deleted()]
    assert not leaked, \
        f"{len(leaked)} delta-generation arrays survived invalidation"


def test_pod_partitioned_delta_eviction_frees_every_owner():
    """Satellite 2: a pod-partitioned (dev=-1) delta generation spreads
    its slabs over SEVERAL owner devices — the delta slab and rewritten
    tombstone slabs included. Invalidation must jax.Array.delete() the
    buffers on EVERY owner, not just the tail owner that holds the
    delta slab; a survivor-device array that slips through is an HBM
    leak that outlives the table."""
    eng, s = _engine()
    s.execute("CREATE TABLE pt (a BIGINT, b BIGINT, c VARCHAR(10))")
    for base in range(0, 8192, 1024):
        s.execute("INSERT INTO pt VALUES " + ",".join(
            f"({i % 40}, {(i * 7919) % 5000}, 'k{i % 5}')"
            for i in range(base, base + 1024)))
    s.vars["tidb_tpu_max_slab_rows"] = 1024
    s.vars["tidb_tpu_partition_min_rows"] = 1000
    qp = "SELECT a, COUNT(*), SUM(b) FROM pt GROUP BY a ORDER BY a"
    s.query(qp)
    # tombstones land in non-tail slabs too, so the rewritten keeps sit
    # on non-tail owners alongside the tail-pinned delta slab
    s.query("DELETE FROM pt WHERE b % 97 = 3")
    s.query("INSERT INTO pt VALUES (3, 1234, 'k2')")
    assert s.query(qp).rows == _oracle(s, qp)
    ent = _entry(eng, "pt")
    assert ent.is_delta
    assert len(set(ent.owners)) > 1, \
        "pod entry must span several owners for this test to bite"
    import jax
    arrays = [a for slabs in ent.dev.values() for t in slabs
              if t is not None for a in t]
    assert len({_dev_of(a) for a in arrays}) > 1, \
        "delta generation's arrays must live on more than one device"
    tid = eng.catalog.info_schema.table("pt").id
    dc.invalidate(tid)
    leaked = [a for a in arrays if not a.is_deleted()]
    assert not leaked, (
        f"{len(leaked)} arrays survived invalidation on devices "
        f"{sorted({str(_dev_of(a)) for a in leaked})} — every owner "
        f"device must be freed, not just the delta slab's tail owner")


def _dev_of(a):
    ds = getattr(a, "devices", None)
    if callable(ds):
        got = list(a.devices())
        assert len(got) == 1
        return got[0]
    return a.device


def test_delta_rows_in_phase_accounting():
    eng, s = _engine()
    s.query(Q)
    s.query("INSERT INTO t VALUES (3, 1234, 'k2')")
    s.query(Q)
    ph = s.last_guard.phases
    assert ph.as_dict().get("delta_rows", 0) == 1
