"""SQL plan cache (ref: planner/core/cache.go): repeated SELECT texts
reuse the compiled physical plan; DDL/ANALYZE/var changes invalidate via
the cache key; plans that baked eager-subquery results never cache."""

import numpy as np
import pytest

from tidb_tpu.session import Engine


@pytest.fixture()
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE pc (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO pc VALUES " +
              ",".join(f"({i},{i % 7})" for i in range(500)))
    return s


def _hits(s):
    from tidb_tpu.util.observability import REGISTRY
    rows = s.query("SHOW METRICS").rows
    for name, *rest in rows:
        if name == "tidb_tpu_plan_cache_hits_total":
            return float(rest[-1])
    return 0.0


def test_repeated_select_hits_cache(s):
    sql = "SELECT b, COUNT(*), SUM(a) FROM pc GROUP BY b ORDER BY b"
    first = s.query(sql).rows
    h0 = _hits(s)
    second = s.query(sql).rows
    assert second == first
    assert _hits(s) > h0
    assert len(s._plan_cache) >= 1


def test_ddl_invalidates(s):
    sql = "SELECT COUNT(*) FROM pc"
    s.query(sql)
    assert any(k[0] == sql for k in s._plan_cache)
    s.execute("ALTER TABLE pc ADD COLUMN c BIGINT")
    # key embeds the schema version: old entry is unreachable
    s.query(sql)
    versions = {k[1] for k in s._plan_cache if k[0] == sql}
    assert len(versions) == 2


def test_dml_correctness_through_cache(s):
    sql = "SELECT COUNT(*) FROM pc"
    assert s.query(sql).rows == [(500,)]
    s.execute("INSERT INTO pc VALUES (1000, 1)")
    # same plan object, fresh execution: reads the new row
    assert s.query(sql).rows == [(501,)]


def test_eager_subquery_plans_never_cached(s):
    sql = "SELECT COUNT(*) FROM pc WHERE a < (SELECT AVG(a) FROM pc)"
    before = s.query(sql).rows
    assert not any(k[0] == sql for k in s._plan_cache)
    s.execute("INSERT INTO pc VALUES (100000, 1)")   # shifts AVG
    after = s.query(sql).rows
    assert after != before or True    # must recompute, not replay
    # the subquery reran: the new AVG includes the outlier
    avg = s.query("SELECT AVG(a) FROM pc").scalar()
    want = s.query(f"SELECT COUNT(*) FROM pc WHERE a < {avg}").rows
    assert after == want


def test_var_change_misses(s):
    sql = "SELECT SUM(a) FROM pc"
    s.query(sql)
    n0 = len(s._plan_cache)
    s.vars["tidb_tpu_row_threshold"] = 1
    s.query(sql)
    assert len(s._plan_cache) == n0 + 1
