"""Correlated subqueries (planner/decorrelate.py) vs brute-force oracles.

The reference covers these via expression_rewriter.go + rule_decorrelate.go
and SQL-level tests; here every decorrelated shape is checked against a
Python recomputation over the raw rows (TPC-H Q4/Q17/Q21/Q22 shapes)."""

import numpy as np
import pytest

from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE o (o_id BIGINT, o_prio BIGINT, o_flag VARCHAR(4))")
    s.execute("CREATE TABLE l (l_oid BIGINT, l_qty BIGINT, l_commit BIGINT, "
              "l_receipt BIGINT)")
    rng = np.random.default_rng(3)
    orows = []
    for i in range(300):
        flag = ["A", "B", "C"][int(rng.integers(0, 3))]
        orows.append(f"({i},{int(rng.integers(0, 5))},'{flag}')")
    # a few orders with no lineitems; order 298/299 keys never in l
    s.execute("INSERT INTO o VALUES " + ",".join(orows))
    lrows = []
    for _ in range(2000):
        oid = int(rng.integers(0, 298))
        key = "NULL" if rng.random() < 0.02 else str(oid)
        c, r = int(rng.integers(0, 50)), int(rng.integers(0, 50))
        lrows.append(f"({key},{int(rng.integers(1, 40))},{c},{r})")
    s.execute("INSERT INTO l VALUES " + ",".join(lrows))
    return s


@pytest.fixture(scope="module")
def raw(s):
    o = s.query("SELECT o_id, o_prio, o_flag FROM o").rows
    l = s.query("SELECT l_oid, l_qty, l_commit, l_receipt FROM l").rows
    return o, l


def test_correlated_exists(s, raw):
    # Q4 shape: orders with at least one late lineitem
    got = s.query(
        "SELECT o_prio, COUNT(*) FROM o WHERE EXISTS ("
        "SELECT 1 FROM l WHERE l_oid = o_id AND l_commit < l_receipt) "
        "GROUP BY o_prio ORDER BY o_prio").rows
    o, l = raw
    hit = {oid for oid, q, c, r in l if oid is not None and c < r}
    want = {}
    for oid, prio, _ in o:
        if oid in hit:
            want[prio] = want.get(prio, 0) + 1
    assert got == sorted(want.items())


def test_correlated_not_exists(s, raw):
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE NOT EXISTS ("
        "SELECT 1 FROM l WHERE l_oid = o_id)").rows
    o, l = raw
    present = {oid for oid, *_ in l if oid is not None}
    assert got[0][0] == sum(1 for oid, *_ in o if oid not in present)


def test_correlated_exists_extra_filter(s, raw):
    # correlated + uncorrelated filters inside the subquery
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE o_flag = 'A' AND EXISTS ("
        "SELECT 1 FROM l WHERE l_oid = o_id AND l_qty > 30)").rows
    o, l = raw
    hit = {oid for oid, q, *_ in l if oid is not None and q > 30}
    assert got[0][0] == sum(1 for oid, p, f in o if f == "A" and oid in hit)


def test_correlated_scalar_avg(s, raw):
    # Q17 shape: rows below a correlated per-key average
    got = s.query(
        "SELECT COUNT(*), SUM(l_qty) FROM l WHERE l_qty < ("
        "SELECT 0.5 * AVG(l_qty) FROM l AS inner_l "
        "WHERE inner_l.l_oid = l.l_oid)").rows
    _, l = raw
    by_key = {}
    for oid, q, *_ in l:
        if oid is not None:
            by_key.setdefault(oid, []).append(q)
    cnt = tot = 0
    for oid, q, *_ in l:
        if oid is None:
            continue
        avg = sum(by_key[oid]) / len(by_key[oid])
        if q < 0.5 * avg:
            cnt += 1
            tot += q
    assert got[0][0] == cnt and got[0][1] == tot


def test_correlated_scalar_count_empty_is_zero(s, raw):
    # COUNT over an empty correlated set must read 0, not NULL
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE ("
        "SELECT COUNT(*) FROM l WHERE l_oid = o_id) = 0").rows
    o, l = raw
    present = {oid for oid, *_ in l if oid is not None}
    assert got[0][0] == sum(1 for oid, *_ in o if oid not in present)
    assert got[0][0] > 0          # fixture guarantees childless orders


def test_correlated_in(s, raw):
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE o_prio IN ("
        "SELECT l_qty FROM l WHERE l_oid = o_id)").rows
    o, l = raw
    sets = {}
    for oid, q, *_ in l:
        if oid is not None:
            sets.setdefault(oid, set()).add(q)
    assert got[0][0] == sum(1 for oid, p, _ in o if p in sets.get(oid, set()))


def test_correlated_not_in_null_aware(s):
    # NOT IN against a set containing NULL filters everything for keys
    # whose set is non-empty-with-NULL; empty sets pass
    s.execute("CREATE TABLE a (k BIGINT, v BIGINT)")
    s.execute("CREATE TABLE b (k BIGINT, v BIGINT)")
    s.execute("INSERT INTO a VALUES (1, 10), (2, 20), (3, 30), (4, NULL)")
    s.execute("INSERT INTO b VALUES (1, 10), (1, 11), (2, NULL), (2, 21)")
    got = s.query(
        "SELECT a.k FROM a WHERE a.v NOT IN ("
        "SELECT b.v FROM b WHERE b.k = a.k) ORDER BY a.k").rows
    # k=1: 10 IN {10,11} → fail; k=2: set has NULL → NULL → fail;
    # k=3: empty set → pass; k=4: v NULL but empty set → pass (MySQL)
    assert got == [(3,), (4,)]


def test_correlated_non_equality_condition(s, raw):
    # non-eq correlation rides as a join condition (Q21-ish)
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE EXISTS ("
        "SELECT 1 FROM l WHERE l_oid = o_id AND l_qty > o_prio * 5)").rows
    o, l = raw
    by_key = {}
    for oid, q, *_ in l:
        if oid is not None:
            by_key.setdefault(oid, []).append(q)
    assert got[0][0] == sum(
        1 for oid, p, _ in o
        if any(q > p * 5 for q in by_key.get(oid, [])))


def test_uncorrelated_still_eager(s, raw):
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE o_prio < (SELECT AVG(o_prio) FROM o)"
    ).rows
    o, _ = raw
    avg = sum(p for _, p, _ in o) / len(o)
    assert got[0][0] == sum(1 for _, p, _ in o if p < avg)


def test_correlated_in_with_uncorrelated_filter(s, raw):
    # regression: extra uncorrelated conjunct in the IN subquery used to
    # spin the planner forever
    got = s.query(
        "SELECT COUNT(*) FROM o WHERE o_prio IN ("
        "SELECT l_qty FROM l WHERE l_oid = o_id AND l_qty > 2)").rows
    o, l = raw
    sets = {}
    for oid, q, *_ in l:
        if oid is not None and q > 2:
            sets.setdefault(oid, set()).add(q)
    assert got[0][0] == sum(1 for oid, p, _ in o if p in sets.get(oid, set()))


def test_correlated_exists_limit_offset_apply(s, raw):
    # existence under a per-outer-row OFFSET cannot decorrelate into a
    # semi join — it runs on the Apply fallback (planner/apply.py) and
    # must match the brute-force count, not error (round-4 upgrade of the
    # old rejection test)
    got = s.query("SELECT COUNT(*) FROM o WHERE EXISTS ("
                  "SELECT 1 FROM l WHERE l_oid = o_id LIMIT 1 OFFSET 5)"
                  ).rows
    o, l = raw
    counts = {}
    for oid, *_ in l:
        if oid is not None:
            counts[oid] = counts.get(oid, 0) + 1
    assert got[0][0] == sum(1 for oid, *_ in o if counts.get(oid, 0) >= 6)


def test_correlated_agg_argument_apply(s, raw):
    # correlation inside an aggregate argument: Apply fallback, exact
    got = s.query("SELECT COUNT(*) FROM o WHERE 1 < ("
                  "SELECT SUM(l_qty + o_prio) FROM l WHERE l_oid = o_id)"
                  ).rows
    o, l = raw
    want = 0
    for oid, prio, _ in o:
        items = [q for k, q, *_ in l if k == oid]
        tot = sum(q + prio for q in items) if items else None
        if tot is not None and tot > 1:
            want += 1
    assert got[0][0] == want
