"""ENUM / SET / JSON types + function family (ref: types/etc.go enum/set,
types/json + expression/builtin_json.go)."""

import pytest

from tidb_tpu.session import Engine


@pytest.fixture()
def s():
    return Engine().new_session()


def test_enum_roundtrip_order_group(s):
    s.execute("CREATE TABLE et (p ENUM('low','medium','high'), v BIGINT)")
    s.execute("INSERT INTO et VALUES ('low',1),('high',2),('medium',3),"
              "(NULL,4),('HIGH',5)")          # case-insensitive member
    assert s.query("SELECT p FROM et WHERE v = 5").rows == [("high",)]
    # ORDER BY uses the member INDEX, not the string (MySQL enum order)
    assert [r[0] for r in
            s.query("SELECT p FROM et WHERE p IS NOT NULL "
                    "ORDER BY p").rows] == \
        ["low", "medium", "high", "high"]
    assert s.query("SELECT v FROM et WHERE p = 'medium'").rows == [(3,)]
    assert s.query("SELECT v FROM et WHERE p > 'low' ORDER BY v").rows \
        == [(2,), (3,), (5,)]
    got = dict(s.query("SELECT p, COUNT(*) FROM et GROUP BY p").rows)
    assert got == {None: 1, "low": 1, "medium": 1, "high": 2}
    with pytest.raises(Exception, match="truncated|Data"):
        s.execute("INSERT INTO et VALUES ('bogus', 9)")


def test_set_roundtrip(s):
    s.execute("CREATE TABLE st (tags SET('red','green','blue'))")
    s.execute("INSERT INTO st VALUES ('red,blue'),(''),('green'),"
              "('blue,red')")
    rows = [r[0] for r in s.query("SELECT tags FROM st").rows]
    assert rows == ["red,blue", "", "green", "red,blue"]   # member order
    assert s.query("SELECT COUNT(*) FROM st WHERE tags = 'red,blue'"
                   ).rows == [(2,)]


def test_json_type_and_functions(s):
    s.execute("CREATE TABLE j (id BIGINT, doc JSON)")
    s.execute('INSERT INTO j VALUES '
              '(1, \'{"a": 1, "b": [10, 20], "c": {"d": "x"}}\'),'
              '(2, \'{"a": 2, "b": []}\'), (3, NULL)')
    assert s.query("SELECT id, doc->'$.a' FROM j ORDER BY id").rows == [
        (1, "1"), (2, "2"), (3, None)]
    assert s.query("SELECT doc->>'$.c.d', doc->'$.b[1]' FROM j "
                   "WHERE id = 1").rows == [("x", "20")]
    assert s.query("SELECT JSON_LENGTH(doc), JSON_TYPE(doc->'$.b') "
                   "FROM j WHERE id = 1").rows == [(3, "ARRAY")]
    assert s.query("SELECT JSON_KEYS(doc) FROM j WHERE id = 2").rows == [
        ('["a", "b"]',)]
    assert s.query("SELECT id FROM j WHERE JSON_CONTAINS(doc->'$.b', "
                   "'10')").rows == [(1,)]
    assert s.query("SELECT JSON_VALID('{\"x\":1}'), JSON_VALID('nope')"
                   ).rows == [(1, 0)]
    # builders nest JSON args instead of double-encoding them
    assert s.query("SELECT JSON_OBJECT('k', id, 'arr', "
                   "JSON_ARRAY(1, 'two')) FROM j WHERE id = 2").rows == [
        ('{"k": 2, "arr": [1, "two"]}',)]
    # invalid documents rejected at INSERT
    with pytest.raises(Exception):
        s.execute("INSERT INTO j VALUES (9, '{broken')")


def test_json_group_and_dump_fidelity(tmp_path, s):
    from tidb_tpu import tools
    s.execute("CREATE TABLE jg (k ENUM('a','b'), doc JSON)")
    s.execute('INSERT INTO jg VALUES (\'a\', \'{"n": 1}\'),'
              '(\'b\', \'{"n": 2}\'),(\'a\', \'{"n": 1}\')')
    assert dict(s.query("SELECT k, COUNT(*) FROM jg GROUP BY k").rows) \
        == {"a": 2, "b": 1}
    # backup/restore preserves the extended types
    out = str(tmp_path / "bk")
    tools.backup(s.engine, out, ["jg"])
    eng2 = Engine()
    tools.restore(eng2, out)
    s2 = eng2.new_session()
    assert sorted(map(str, s2.query("SELECT k, doc FROM jg").rows)) == \
        sorted(map(str, s.query("SELECT k, doc FROM jg").rows))
