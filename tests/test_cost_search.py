"""Cost-based alternative-shape physical search (ref: planner/core/
find_best_task.go:285, exhaust_physical_plans.go): stream agg vs hash agg
by group cardinality, sort elimination via index order, and index-lookup
vs hash join flipping with outer-side stats. Each choice is pinned in
BOTH directions, and every alternative shape is checked for result parity
with the baseline shape."""

import numpy as np
import pytest

from tidb_tpu.session import Engine


def _explain(s, sql):
    return "\n".join(str(r) for r in s.query("EXPLAIN " + sql).rows)


@pytest.fixture()
def s():
    return Engine().new_session()


def test_stream_agg_flips_on_group_cardinality(s):
    # near-unique indexed key → stream agg; low-cardinality key → hash agg
    s.execute("CREATE TABLE hi (k BIGINT, v BIGINT, INDEX ik (k))")
    s.execute("CREATE TABLE lo (k BIGINT, v BIGINT, INDEX ik (k))")
    rows_hi = ",".join(f"({i},{i % 97})" for i in range(20000))
    rows_lo = ",".join(f"({i % 5},{i % 97})" for i in range(20000))
    s.execute("INSERT INTO hi VALUES " + rows_hi)
    s.execute("INSERT INTO lo VALUES " + rows_lo)
    s.execute("ANALYZE TABLE hi")
    s.execute("ANALYZE TABLE lo")
    sql_hi = "SELECT k, COUNT(*), SUM(v) FROM hi GROUP BY k"
    sql_lo = "SELECT k, COUNT(*), SUM(v) FROM lo GROUP BY k"
    assert "StreamAgg" in _explain(s, sql_hi)
    assert "HashAgg" in _explain(s, sql_lo)
    assert "StreamAgg" not in _explain(s, sql_lo)
    # parity: stream agg result == hash agg result (incl. NULL group)
    s.execute("INSERT INTO hi VALUES (NULL, 7), (NULL, 8)")
    got = s.query(sql_hi + " ").rows
    s.vars["tidb_tpu_engine"] = "off"
    want = {}
    for k, v in [(None, 7), (None, 8)] + [(i, i % 97)
                                          for i in range(20000)]:
        c, t = want.get(k, (0, 0))
        want[k] = (c + 1, t + v)
    assert len(got) == len(want)
    for k, c, t in got:
        assert want[k] == (c, t), k


def test_stream_agg_respects_filters(s):
    s.execute("CREATE TABLE fa (k BIGINT, v BIGINT, INDEX ik (k))")
    s.execute("INSERT INTO fa VALUES " + ",".join(
        f"({i},{i % 10})" for i in range(20000)))
    s.execute("ANALYZE TABLE fa")
    # weakly selective filter: stream agg still wins and must apply it
    sql = ("SELECT k, COUNT(*) FROM fa WHERE v < 8 GROUP BY k "
           "ORDER BY k LIMIT 5")
    plan = _explain(s, sql)
    assert "StreamAgg" in plan
    assert s.query(sql).rows == [(0, 1), (1, 1), (2, 1), (3, 1), (4, 1)]
    # heavily selective filter: full-table index gather is overpriced,
    # the hash path over the filtered scan wins
    assert "HashAgg" in _explain(
        s, "SELECT k, COUNT(*) FROM fa WHERE v = 1 GROUP BY k")


def test_sort_elimination_flips_on_size(s):
    s.execute("CREATE TABLE big (k BIGINT, v BIGINT, INDEX ik (k))")
    s.execute("CREATE TABLE small (k BIGINT, v BIGINT, INDEX ik (k))")
    s.execute("INSERT INTO big VALUES " + ",".join(
        f"({(i * 37) % 50000},{i})" for i in range(50000)))
    s.execute("INSERT INTO small VALUES (3,1),(1,2),(2,3),(NULL,4)")
    s.execute("ANALYZE TABLE big")
    s.execute("ANALYZE TABLE small")
    assert "IndexOrderedScan" in _explain(
        s, "SELECT * FROM big ORDER BY k")
    assert "Sort" in _explain(s, "SELECT * FROM small ORDER BY k")
    # order parity incl. NULLs-first asc / NULLs-last desc
    s.execute("INSERT INTO big VALUES (NULL, -1), (NULL, -2)")
    asc = [r[0] for r in s.query("SELECT k FROM big ORDER BY k").rows]
    assert asc[0] is None and asc[1] is None
    assert asc[2:] == sorted(a for a in asc if a is not None)
    desc = [r[0] for r in
            s.query("SELECT k FROM big ORDER BY k DESC").rows]
    assert desc[-1] is None and desc[-2] is None
    assert desc[:-2] == sorted((a for a in desc if a is not None),
                               reverse=True)


def test_index_join_flips_on_outer_stats(s):
    s.execute("CREATE TABLE inner_t (k BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("CREATE TABLE outer_t (k BIGINT, w BIGINT)")
    s.execute("INSERT INTO inner_t VALUES " + ",".join(
        f"({i},{i % 7})" for i in range(50000)))
    s.execute("INSERT INTO outer_t VALUES " + ",".join(
        f"({i * 101 % 50000},{i})" for i in range(40)))
    s.execute("ANALYZE TABLE inner_t")
    s.execute("ANALYZE TABLE outer_t")
    sql = ("SELECT COUNT(*), SUM(v) FROM outer_t "
           "JOIN inner_t ON outer_t.k = inner_t.k")
    assert "IndexLookupJoin" in _explain(s, sql)
    small_result = s.query(sql).rows
    assert small_result[0][0] == 40
    # grow the outer side past the cost crossover; stats flip the plan
    s.execute("INSERT INTO outer_t VALUES " + ",".join(
        f"({i % 50000},{i})" for i in range(60000)))
    s.execute("ANALYZE TABLE outer_t")
    assert "HashJoin" in _explain(s, sql)
    assert "IndexLookupJoin" not in _explain(s, sql)


def test_merge_join_still_chosen_for_large_indexed(s):
    s.execute("CREATE TABLE a (k BIGINT, v BIGINT, INDEX ik (k))")
    s.execute("CREATE TABLE b (k BIGINT, w BIGINT, INDEX ik (k))")
    s.execute("INSERT INTO a VALUES " + ",".join(
        f"({i},{i % 5})" for i in range(20000)))
    s.execute("INSERT INTO b VALUES " + ",".join(
        f"({i},{i % 3})" for i in range(20000)))
    s.execute("ANALYZE TABLE a")
    s.execute("ANALYZE TABLE b")
    sql = "SELECT COUNT(*) FROM a JOIN b ON a.k = b.k"
    assert "MergeJoin" in _explain(s, sql)
    assert s.query(sql).rows == [(20000,)]
