"""Per-slab zone maps + host-side slab skipping (executor/zonemap.py
and its wiring through device_cache / fragment / dist_fragment).

Pinned invariants:

* the conjunct evaluator is sound per-op: `_range_excludes` prunes a
  slab only when NO value in [lo, hi] can pass, and `column_stats`
  reports per-slab min/max/null-count/rows in the compared space;
* skipped-vs-unskipped results are byte-exact against the CPU oracle
  for every comparison shape (range, BETWEEN, IN, string equality over
  dict codes, floats, FoR negatives, delta PKs) across the chain, tree,
  fused-pipeline and both distributed executors;
* NULL semantics are Kleene-correct: a NULL-only slab is prunable by
  any comparison and by IS NOT NULL, a no-NULL slab by IS NULL;
* all slabs pruned means ZERO program launches and still the correct
  result — the agg identity (COUNT 0, SUM/MIN/MAX NULL) for a global
  aggregate, the empty rowset for GROUP BY / ORDER BY roots;
* pruning is an encode-time artifact: `tidb_tpu_compression = off`
  disables it entirely (slabs_skipped stays 0) while results agree;
* a stale zone map at the prune decision (failpoint `zone-map-stale`)
  surfaces as a typed LayoutError and a warned CPU fallback with oracle
  rows — never silently skipped live slabs;
* a layout re-choice EVICTS the per-digest specialization entry (its
  cached signature names programs that decode the old layouts): flipping
  `tidb_tpu_compression` swaps the entry's layout signature in place and
  keeps answering the oracle;
* sorted fully-valid PK columns choose the delta layout and round-trip
  byte-exactly through numpy AND jnp decode; the `group_heavy` workload
  hint raises the dictionary cap and wins width ties.
"""

import numpy as np
import pytest

from tidb_tpu.chunk import compress
from tidb_tpu.errors import LayoutError
from tidb_tpu.executor import build, run_to_completion, zonemap
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.executor import fragment
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine
from tidb_tpu.util import failpoint
from tidb_tpu.util.observability import REGISTRY


# ---------------------------------------------------------------------------
# evaluator units
# ---------------------------------------------------------------------------

def test_range_excludes_truth_table():
    ex = zonemap._range_excludes
    # eq: only values outside [lo, hi] are impossible
    assert ex("eq", 10, 20, 9) and ex("eq", 10, 20, 21)
    assert not ex("eq", 10, 20, 10) and not ex("eq", 10, 20, 20)
    # ne: impossible only when the slab is the single value c
    assert ex("ne", 7, 7, 7)
    assert not ex("ne", 7, 8, 7) and not ex("ne", 6, 6, 7)
    # strict/loose bounds at the boundary
    assert ex("lt", 10, 20, 10) and not ex("lt", 9, 20, 10)
    assert ex("le", 11, 20, 10) and not ex("le", 10, 20, 10)
    assert ex("gt", 10, 20, 20) and not ex("gt", 10, 21, 20)
    assert ex("ge", 10, 19, 20) and not ex("ge", 10, 20, 20)


def test_column_stats_per_slab():
    vals = np.arange(10, dtype=np.int64)
    valid = np.ones(10, dtype=bool)
    valid[7:] = False                       # slab 1: rows 4..7 → 7 NULL
    zm = zonemap.column_stats(vals, valid, 4, 10)
    assert zm.n_slabs == 3
    assert zm.rows == [4, 4, 2]
    assert zm.lo[0] == 0 and zm.hi[0] == 3
    assert zm.lo[1] == 4 and zm.hi[1] == 6
    assert zm.nulls == [0, 1, 2]
    # NULL-only slab carries no bounds
    assert zm.lo[2] is None and zm.hi[2] is None and zm.distinct[2] == 0
    # dense int space: the distinct estimate is exact
    assert zm.distinct[0] == 4


# ---------------------------------------------------------------------------
# engine fixtures
# ---------------------------------------------------------------------------

N, SLAB = 4096, 1024   # 4 slabs; every column sorted so slab ranges partition

DEV = {"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": 1,
       "tidb_tpu_max_slab_rows": SLAB}


def _zm_engine():
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE zm (pk BIGINT, v BIGINT, neg BIGINT, "
              "g BIGINT, w VARCHAR(8), f DOUBLE)")
    rows = [f"({i}, {i}, {i - N}, {i // SLAB}, 'w{i // SLAB}', {i / 10.0})"
            for i in range(N)]
    s.execute("INSERT INTO zm VALUES " + ",".join(rows))
    return eng, s


def q_dev(s, sql, **extra):
    """s.query on the device path → (rows, PhaseTimer). wall_s is added
    only when the device fragment SERVED (no fallback)."""
    vars_ = {**DEV, **extra}
    saved = {k: s.vars.get(k) for k in vars_}
    s.vars.update(vars_)
    try:
        rows = s.query(sql).rows
        ph = s.last_guard.phases
        assert ph.wall_s > 0.0, f"CPU fallback for: {sql}"
        return rows, ph
    finally:
        for k, v in saved.items():
            if v is None:
                s.vars.pop(k, None)
            else:
                s.vars[k] = v


# every predicate shape the pruner understands, with the slab count it
# must prove empty on the sorted fixture (4 slabs of 1024)
PRED_CASES = [
    ("v >= 3072", 3),                       # ge over a delta-layout column
    ("v < 1024", 3),                        # lt keeps only slab 0
    ("v BETWEEN 1100 AND 1200", 3),         # desugared and(ge, le)
    ("v IN (5, 2000)", 2),                  # IN over two slabs
    ("v = 9999999", 4),                     # eq outside every slab
    ("w = 'w2'", 3),                        # string eq over dict codes
    ("w IN ('w0', 'zzz')", 3),              # string IN, one absent item
    ("f < 100.0", 3),                       # float zone map
    ("neg < -3000", 2),                     # FoR negatives (min-referenced)
    ("pk >= 4000", 3),                      # sorted PK (delta layout)
    ("v >= 1024 AND v < 2048", 3),          # conjunction prunes both ends
]


@pytest.mark.parametrize("pred,expect_skip", PRED_CASES)
def test_pruning_byte_exact_chain(pred, expect_skip):
    eng, s = _zm_engine()
    q = (f"SELECT COUNT(*), COUNT(v), SUM(v), MIN(pk), MAX(f) "
         f"FROM zm WHERE {pred}")
    oracle = s.query(q).rows
    cold, ph_cold = q_dev(s, q)
    assert cold == oracle
    assert ph_cold.slabs_skipped == expect_skip, pred
    # cold prune skipped the pruned slabs' encode+upload entirely
    if expect_skip:
        assert ph_cold.h2d_skipped_bytes > 0
    warm, ph_warm = q_dev(s, q)
    assert warm == oracle
    assert ph_warm.slabs_skipped == expect_skip
    assert ph_warm.h2d_bytes == 0, "warm repeat must re-upload nothing"


def test_pruning_counters_reach_registry():
    eng, s = _zm_engine()
    key = ("tidb_tpu_slabs_skipped_total",
           (("device", "0"), ("engine", "device")))
    before = REGISTRY.counters.get(key, 0)
    h2d_before = sum(h[1] for (name, _l), h in REGISTRY.hists.items()
                     if name == "tidb_tpu_h2d_skipped_bytes")
    _, ph = q_dev(s, "SELECT COUNT(*) FROM zm WHERE v >= 3072")
    assert REGISTRY.counters.get(key, 0) == before + ph.slabs_skipped > before
    h2d_after = sum(h[1] for (name, _l), h in REGISTRY.hists.items()
                    if name == "tidb_tpu_h2d_skipped_bytes")
    assert h2d_after - h2d_before == ph.h2d_skipped_bytes > 0


# ---------------------------------------------------------------------------
# all slabs pruned: zero launches, correct identities
# ---------------------------------------------------------------------------

def test_all_pruned_global_agg_identity():
    eng, s = _zm_engine()
    q = ("SELECT COUNT(*), COUNT(v), SUM(v), MIN(v), MAX(v), AVG(f) "
         "FROM zm WHERE v > 100000")
    oracle = s.query(q).rows
    assert oracle == [(0, 0, None, None, None, None)]
    cold, _ = q_dev(s, q)
    assert cold == oracle
    warm, ph = q_dev(s, q)
    assert warm == oracle
    assert ph.slabs_skipped == 4
    assert ph.programs_launched == 0, "pruned slabs must not launch"
    assert ph.h2d_bytes == 0


def test_all_pruned_grouped_and_order_empty():
    eng, s = _zm_engine()
    for q in ("SELECT g, COUNT(*), SUM(v) FROM zm WHERE v > 100000 "
              "GROUP BY g",
              "SELECT v FROM zm WHERE v > 100000 ORDER BY v LIMIT 5"):
        assert s.query(q).rows == []
        cold, _ = q_dev(s, q)
        assert cold == []
        warm, ph = q_dev(s, q)
        assert warm == []
        assert ph.programs_launched == 0


# ---------------------------------------------------------------------------
# NULL-only slabs vs IS [NOT] NULL (Kleene soundness)
# ---------------------------------------------------------------------------

def _null_slab_engine():
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    s.execute("CREATE TABLE nl (a BIGINT, b BIGINT)")
    rows = [f"(NULL, {i})" if i < SLAB else f"({i}, {i})"
            for i in range(2 * SLAB)]
    s.execute("INSERT INTO nl VALUES " + ",".join(rows))
    return eng, s


@pytest.mark.parametrize("pred,expect_skip", [
    ("a IS NOT NULL", 1),       # slab 0 is entirely NULL
    ("a IS NULL", 1),           # slab 1 has zero NULLs
    ("a >= 0", 1),              # any comparison filters a NULL-only slab
    ("a IS NULL AND b < 500", 1),
    ("NOT (a IS NULL)", 1),
])
def test_null_slab_pruning(pred, expect_skip):
    eng, s = _null_slab_engine()
    q = f"SELECT COUNT(*), COUNT(a), SUM(b) FROM nl WHERE {pred}"
    oracle = s.query(q).rows
    got, ph = q_dev(s, q)
    assert got == oracle
    assert ph.slabs_skipped == expect_skip, pred


# ---------------------------------------------------------------------------
# compression off: no zone maps, no pruning, same answers
# ---------------------------------------------------------------------------

def test_pruning_off_without_compression():
    eng, s = _zm_engine()
    q = "SELECT COUNT(*), SUM(v) FROM zm WHERE v >= 3072"
    oracle = s.query(q).rows
    got, ph = q_dev(s, q, tidb_tpu_compression="off")
    assert got == oracle
    assert ph.slabs_skipped == 0
    assert ph.h2d_skipped_bytes == 0
    # and compression back on prunes again, same rows
    got_on, ph_on = q_dev(s, q)
    assert got_on == oracle and ph_on.slabs_skipped == 3


# ---------------------------------------------------------------------------
# tree / fused-pipeline / distributed paths
# ---------------------------------------------------------------------------

def _with_dim(s):
    s.execute("CREATE TABLE dim (id BIGINT, tag VARCHAR(8))")
    s.execute("INSERT INTO dim VALUES (0,'a'),(1,'b'),(2,'c'),(3,'d')")


JOIN_Q = ("SELECT dim.tag, COUNT(*), SUM(zm.v) FROM zm "
          "JOIN dim ON zm.g = dim.id WHERE zm.v >= 3072 "
          "GROUP BY dim.tag ORDER BY dim.tag")


def test_pruning_byte_exact_fused_pipeline():
    eng, s = _zm_engine()
    _with_dim(s)
    oracle = s.query(JOIN_Q).rows
    got, ph = q_dev(s, JOIN_Q)
    assert got == oracle
    assert ph.slabs_skipped == 3


def test_pruning_byte_exact_tree_path():
    eng, s = _zm_engine()
    _with_dim(s)
    oracle = s.query(JOIN_Q).rows
    got, ph = q_dev(s, JOIN_Q, tidb_tpu_fused_pipeline="off")
    assert got == oracle
    assert ph.slabs_skipped == 3


def test_pruning_byte_exact_staged_dist():
    eng, s = _zm_engine()
    q = "SELECT g, COUNT(*), SUM(v) FROM zm WHERE v >= 3072 GROUP BY g"
    oracle = sorted(s.query(q).rows, key=str)
    got, ph = q_dev(s, q, tidb_tpu_dist=4)
    assert sorted(got, key=str) == oracle
    # rank-sliced zone maps: 3 of the 4 sorted rank slices are empty
    assert ph.slabs_skipped == 3
    assert ph.h2d_skipped_bytes > 0


def test_byte_exact_monolithic_dist():
    eng, s = _zm_engine()
    q = "SELECT g, COUNT(*), SUM(v) FROM zm WHERE v >= 3072 GROUP BY g"
    oracle = sorted(s.query(q).rows, key=str)
    got, _ph = q_dev(s, q, tidb_tpu_dist=4, tidb_tpu_dist_staged="off")
    assert sorted(got, key=str) == oracle


# ---------------------------------------------------------------------------
# stale zone map: typed error → warned CPU fallback, oracle rows
# ---------------------------------------------------------------------------

def test_stale_zone_map_falls_back_to_cpu():
    eng, s = _zm_engine()
    q = "SELECT COUNT(*), SUM(v) FROM zm WHERE v >= 3072"
    oracle = s.query(q).rows
    s.vars.update(DEV)
    failpoint.enable("zone-map-stale", value="test: stale map")
    try:
        plan = s._plan(parse(q)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        got = [r for ch in chunks for r in ch.rows()]
        assert got == oracle, "fallback must still return oracle rows"
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags
        for f in frags:
            assert not f.used_device, "stale zone map must not serve"
            assert "zone map" in (f.fallback_reason or ""), \
                f.fallback_reason
    finally:
        failpoint.disable("zone-map-stale")
        for k in DEV:
            s.vars.pop(k, None)
    # disarmed: the device path prunes and serves the same rows again
    got2, ph = q_dev(s, q)
    assert got2 == oracle and ph.slabs_skipped == 3


def test_stale_zone_map_error_is_typed():
    eng, s = _zm_engine()
    q_dev(s, "SELECT COUNT(*) FROM zm WHERE v >= 3072")   # build zone maps
    ent = next(iter(
        __import__("tidb_tpu.executor.device_cache",
                   fromlist=["_CACHE"])._CACHE.values()))
    scan = type("S", (), {"filters": [object()]})()
    failpoint.enable("zone-map-stale", value="boom")
    try:
        with pytest.raises(LayoutError, match="zone map"):
            zonemap.prune_slabs(ent, scan)
    finally:
        failpoint.disable("zone-map-stale")


# ---------------------------------------------------------------------------
# specialization cache: layout re-choice evicts, never shadows
# ---------------------------------------------------------------------------

def test_spec_cache_evicted_on_compression_flip():
    eng, s = _zm_engine()
    q = "SELECT g, COUNT(*), SUM(v) FROM zm GROUP BY g ORDER BY g"
    oracle = s.query(q).rows
    got, _ = q_dev(s, q)                    # cold: stores the spec entry
    assert got == oracle
    _, ph = q_dev(s, q)                     # warm: entry serves
    assert ph.specialization_hits >= 1

    def entries():
        return {k: v.get("lay_sig") for k, v in fragment._SPEC_CACHE.items()
                if len(k) > 2 and k[2] == q}
    on_sigs = entries()
    assert on_sigs and all(sig != "-" for sig in on_sigs.values()), on_sigs

    got_off, ph_off = q_dev(s, q, tidb_tpu_compression="off")
    assert got_off == oracle
    off_sigs = entries()
    # the stale compressed-layout entry was EVICTED (not shadowed): every
    # surviving entry for this statement names the raw layout set
    assert off_sigs and all(sig == "-" for sig in off_sigs.values()), \
        (on_sigs, off_sigs)
    # and the raw entry serves warm in turn
    _, ph_off2 = q_dev(s, q, tidb_tpu_compression="off")
    assert ph_off2.specialization_hits >= 1


# ---------------------------------------------------------------------------
# workload-adaptive layouts: delta for sorted PKs, group_heavy dict cap
# ---------------------------------------------------------------------------

def test_sorted_pk_chooses_delta_and_roundtrips():
    from tidb_tpu.ops.jax_env import jnp
    vals = (10_000_000 + np.cumsum(
        np.random.default_rng(7).integers(0, 4, size=3000))).astype(np.int64)
    valid = np.ones(3000, dtype=bool)
    lay, dv = compress.choose_layout(vals, valid)
    assert lay is not None and lay.kind == "delta"
    assert lay.width == 2, "max gap 3 must pack at width 2"
    cap = 4096
    pv = np.zeros(cap, dtype=np.int64)
    pm = np.zeros(cap, dtype=bool)
    pv[:3000], pm[:3000] = vals, valid
    slab = compress.pack_slab(lay, pv, pm)
    assert len(slab) == 3, "delta slabs carry a per-slab base"
    for xp in (np, jnp):
        got_v, got_m = compress.decode_slab(lay, slab, cap, xp)
        assert np.array_equal(np.asarray(got_v)[:3000], vals)
        assert np.array_equal(np.asarray(got_m), pm)


def test_delta_beats_pack_on_dense_sorted_keys():
    # dense sorted ints over a wide range: FoR needs 16 bits, delta 1
    vals = np.arange(50_000, 50_000 + 4000, dtype=np.int64)
    lay, _ = compress.choose_layout(vals, np.ones(4000, dtype=bool),
                                    allow_dict=False)
    assert lay.kind == "delta" and lay.width == 1


def test_delta_requires_sorted_and_fully_valid():
    rng = np.random.default_rng(11)
    unsorted = rng.permutation(np.arange(4000)).astype(np.int64)
    lay, _ = compress.choose_layout(unsorted, np.ones(4000, dtype=bool),
                                    allow_dict=False)
    assert lay.kind == "pack"
    sorted_nulls = np.arange(4000, dtype=np.int64)
    lay2, _ = compress.choose_layout(sorted_nulls,
                                     rng.random(4000) > 0.1,
                                     allow_dict=False)
    assert lay2.kind == "pack"


def test_group_heavy_hint_raises_dict_cap():
    # cardinality above the base cap but under the 4× group-heavy cap,
    # spread sparsely so packing needs the full 32 bits
    rng = np.random.default_rng(13)
    uniq = rng.choice(1 << 20, size=6000, replace=False).astype(np.int64)
    vals = uniq[rng.integers(0, 6000, size=20_000)]
    valid = np.ones(20_000, dtype=bool)
    lay, _ = compress.choose_layout(vals, valid)
    assert lay.kind == "pack", "above the base cap: no dictionary"
    lay2, dv = compress.choose_layout(vals, valid,
                                      hints={"group_heavy": True})
    card = len(np.unique(vals))
    assert card > compress.DICT_CARD_CAP
    assert lay2.kind == "dict" and lay2.card == card
    assert dv is not None and len(dv) == card


def test_group_heavy_hint_wins_width_ties():
    # dense 0..255: pack and dict both land at width 8 — the hint
    # prefers dict (codes feed group factorization directly)
    vals = np.arange(256, dtype=np.int64)[
        np.random.default_rng(5).integers(0, 256, size=5000)]
    valid = np.ones(5000, dtype=bool)
    lay, _ = compress.choose_layout(vals, valid)
    assert lay.kind == "pack" and lay.width == 8
    lay2, _ = compress.choose_layout(vals, valid,
                                     hints={"group_heavy": True})
    assert lay2.kind == "dict" and lay2.width == 8
