"""Concurrent device serving: many connection threads, one accelerator.

The wire server runs one OS thread per connection; the device runtime
(HBM cache, compiled-program cache, scheduler) is process-global shared
state. These tests pin the contract that makes that safe:

* byte-exactness: N threads running a mixed workload (device fragments,
  point reads, a DDL rider) each get exactly the rows a serial run gets
  — never a sibling's rows, never a torn cache entry;
* eviction safety: HBM-pressure eviction never deletes the device
  buffers of a table another statement is mid-flight on (per-thread
  protection, executor/device_cache.py protect_tables);
* queue lifecycle: a statement KILLed while waiting for the device
  dispatch slot surfaces a typed 1317 promptly — it never has to reach
  the device first.

The stress body runs under sys.setswitchinterval(1e-5) so the GIL
rotates ~1000x more often than default, shaking out check-then-act races
that the default 5ms interval hides.
"""

import sys
import threading
import time

import numpy as np
import pytest

from tidb_tpu.errors import TiDBTPUError
from tidb_tpu.executor import device_cache as dc
from tidb_tpu.executor.scheduler import SCHEDULER
from tidb_tpu.session import Engine

N_THREADS = 8
M_QUERIES = 6
N_DEV_TABLES = 6          # > device_cache.MAX_CACHED_TABLES → real churn


def _dev_sql(i: int) -> str:
    return (f"SELECT g, COUNT(*), SUM(a), SUM(b) FROM d{i} "
            f"GROUP BY g ORDER BY g")


PT_SQL = "SELECT v FROM pt WHERE k = 17"


@pytest.fixture()
def serving():
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    rng = np.random.default_rng(11)
    for i in range(N_DEV_TABLES):
        s.execute(f"CREATE TABLE d{i} (a BIGINT, b BIGINT, g BIGINT)")
        rows = ", ".join(
            f"({int(rng.integers(0, 1000))},{int(rng.integers(0, 50))},"
            f"{int(rng.integers(0, 5))})" for _ in range(1200))
        s.execute(f"INSERT INTO d{i} VALUES {rows}")
    s.execute("CREATE TABLE pt (k BIGINT PRIMARY KEY, v BIGINT)")
    s.execute("INSERT INTO pt VALUES " +
              ", ".join(f"({k}, {k * k})" for k in range(100)))

    def new_session():
        ss = eng.new_session()
        ss.vars["tidb_tpu_engine"] = "on"
        ss.vars["tidb_tpu_row_threshold"] = 1
        return ss

    yield eng, new_session
    eng.close()


def _oracle(new_session):
    """Serial reference results, warm-compiling every shape first."""
    s = new_session()
    out = {}
    for i in range(N_DEV_TABLES):
        out[_dev_sql(i)] = s.query(_dev_sql(i)).rows
    out[PT_SQL] = s.query(PT_SQL).rows
    return out


def test_stress_mixed_workload_byte_exact(serving):
    """8 threads × 6 mixed statements (device aggs over 6 tables churning
    the HBM cache, point reads, one thread riding a DDL) — every result
    byte-exact vs the serial oracle, under a hair-trigger GIL switch."""
    eng, new_session = serving
    oracle = _oracle(new_session)
    read_qs = [_dev_sql(i) for i in range(N_DEV_TABLES)] + [PT_SQL]
    sessions = [new_session() for _ in range(N_THREADS)]
    failures: list = []
    barrier = threading.Barrier(N_THREADS)

    def worker(k: int):
        ss = sessions[k]
        barrier.wait()
        for j in range(M_QUERIES):
            if k == 0 and j == 2:
                # the DDL rider: schema churn (user_version bump +
                # info_schema invalidation) mid-stress must not corrupt
                # sibling statements or the device cache
                ss.execute("CREATE TABLE ddl_rider (x BIGINT)")
                ss.execute("INSERT INTO ddl_rider VALUES (1), (2)")
                ss.execute("DROP TABLE ddl_rider")
                continue
            q = read_qs[(k + j) % len(read_qs)]
            rows = ss.query(q).rows
            if rows != oracle[q]:
                failures.append(
                    f"thread {k} stmt {j}: {q!r} diverged from oracle")

    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)
    try:
        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "stress hung"
    finally:
        sys.setswitchinterval(old)
    assert not failures, failures

    # no torn cache entries: every cached device table still answers
    # its query byte-exact after the churn
    check = new_session()
    for i in range(N_DEV_TABLES):
        assert check.query(_dev_sql(i)).rows == oracle[_dev_sql(i)]


def test_eviction_never_deletes_protected_sibling(serving):
    """A statement mid-flight on table d0 (protection held, as
    TpuFragmentExec.next() does) must keep d0's cache entry and device
    buffers across sibling-driven LRU pressure from 5 other tables."""
    eng, new_session = serving
    s = new_session()
    s.query(_dev_sql(0))                       # d0 hot in the HBM cache
    tid0 = eng.catalog.info_schema.table("d0").id
    key0 = None
    for (dev, sid, t, _parts) in list(dc._CACHE):
        if sid == id(eng.store) and t == tid0:
            key0 = (dev, sid, t, _parts)
    assert key0 is not None, "d0 not cached after its query"
    ent0 = dc._CACHE[key0]
    dev_ids = {i: [id(v) for v, _m in slabs] for i, slabs in ent0.dev.items()}
    assert dev_ids

    with dc.protect_tables({(id(eng.store), tid0)}):
        # 5 more tables through a 4-entry LRU: d0 is the cold head and
        # would be trimmed first — protection must skip it
        for i in range(1, N_DEV_TABLES):
            s.query(_dev_sql(i))
        assert key0 in dc._CACHE, "protected entry evicted"
        ent_after = dc._CACHE[key0]
        assert ent_after is ent0, "protected entry replaced mid-flight"
        for i, ids in dev_ids.items():
            assert [id(v) for v, _m in ent_after.dev[i]] == ids, \
                f"protected column {i} re-uploaded/deleted under pressure"
    # after release, normal LRU applies again on the next open
    s.query(_dev_sql(0))
    # the LRU budget is PER DEVICE now: entries for distinct devices
    # never pressure each other
    per_dev: dict = {}
    for k in dc._CACHE:
        per_dev[k[0]] = per_dev.get(k[0], 0) + 1
    assert all(n <= dc.MAX_CACHED_TABLES + 1 for n in per_dev.values())


def test_kill_while_queued_returns_1317_promptly(serving):
    """A statement waiting for the device slot is KILLable: typed 1317
    within ~2s, without ever reaching the device."""
    eng, new_session = serving
    victim = new_session()
    victim.query(_dev_sql(0))                  # warm: no compile in play
    killer = new_session()

    result: dict = {}

    def run_victim():
        t0 = time.monotonic()
        try:
            victim.execute(_dev_sql(0))
            result["outcome"] = "completed"
        except TiDBTPUError as e:
            result["outcome"] = "error"
            result["code"] = getattr(e, "code", None)
            result["type"] = type(e).__name__
        result["dt"] = time.monotonic() - t0

    # occupy the dispatch slot from this thread so the victim queues
    SCHEDULER.acquire(conn_id=-1)
    try:
        th = threading.Thread(target=run_victim, daemon=True)
        th.start()
        deadline = time.monotonic() + 10.0
        while SCHEDULER.queue_depth() < 2:    # holder + queued victim
            assert time.monotonic() < deadline, "victim never queued"
            time.sleep(0.005)
        t_kill = time.monotonic()
        killer.execute(f"KILL QUERY {victim.conn_id}")
        th.join(timeout=10.0)
        assert not th.is_alive(), "KILLed-while-queued statement hung"
        assert result.get("outcome") == "error", result
        assert result.get("code") == 1317, result
        assert time.monotonic() - t_kill < 2.0, \
            f"KILL took {time.monotonic() - t_kill:.2f}s to land"
    finally:
        SCHEDULER.release()

    # the scheduler is clean afterwards: the killed waiter left the queue
    assert SCHEDULER.queue_depth() == 0
    # and the victim session still serves
    assert victim.query(PT_SQL).rows == [(17 * 17,)]


def test_fairness_cap_rotates_between_connections(serving):
    """A tight repeated-query loop on one connection must not starve a
    sibling: the scheduler's consecutive-grant cap forces rotation."""
    eng, new_session = serving
    a, b = new_session(), new_session()
    a.query(_dev_sql(0))
    b.query(_dev_sql(1))                       # both warm
    SCHEDULER.reset_stats()
    stop = threading.Event()

    def loop(ss, sql):
        while not stop.is_set():
            ss.query(sql)

    ta = threading.Thread(target=loop, args=(a, _dev_sql(0)), daemon=True)
    tb = threading.Thread(target=loop, args=(b, _dev_sql(1)), daemon=True)
    ta.start()
    tb.start()
    time.sleep(2.0)
    stop.set()
    ta.join(timeout=30)
    tb.join(timeout=30)
    stats = SCHEDULER.stats()
    assert stats["admissions"] > 0
    # both connections kept making progress the whole window; queue waits
    # were charged when contention actually happened
    assert stats["waits"] >= 0
