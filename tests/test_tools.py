"""Ecosystem tools: dump/load, backup/restore with resume, CSV, CLI.

Mirrors the reference's BR/dumpling/lightning test surfaces (SURVEY §2.5,
br/pkg/task tests) at the scale the in-process engine serves — incl. the
checkpoint/resume discipline (a crash mid-backup resumes where it
stopped, the br/lightning checkpoint pattern)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from tidb_tpu import tools
from tidb_tpu.session import Engine


def make_engine():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE a (k BIGINT PRIMARY KEY, v VARCHAR(12), "
              "d DECIMAL(8,2), t DATE)")
    s.execute("CREATE INDEX iv ON a (v)")
    s.execute("CREATE TABLE b (x BIGINT, y DOUBLE)")
    s.execute("INSERT INTO a VALUES (1,'one',1.25,'2024-01-01'),"
              "(2,'it''s',NULL,'2024-02-02'),(3,NULL,3.75,NULL)")
    s.execute("INSERT INTO b VALUES (10, 1.5), (20, NULL)")
    s.execute("DELETE FROM b WHERE x = 20")   # tombstones excluded
    return eng, s


def contents(s):
    return {
        "a": sorted(map(str, s.query("SELECT * FROM a").rows)),
        "b": sorted(map(str, s.query("SELECT * FROM b").rows)),
    }


def test_backup_restore_roundtrip(tmp_path):
    eng, s = make_engine()
    want = contents(s)
    done = s.query(f"BACKUP TO '{tmp_path}/bk'").rows
    assert sorted(r[0] for r in done) == ["a", "b"]

    eng2 = Engine()
    s2 = eng2.new_session()
    s2.execute(f"RESTORE FROM '{tmp_path}/bk'")
    assert contents(s2) == want
    # schema incl. PK and index survived
    ddl = s2.query("SHOW CREATE TABLE a").rows[0][1]
    assert "PRIMARY KEY" in ddl and "iv" in ddl


def test_backup_resume_after_crash(tmp_path):
    from tidb_tpu.util import failpoint
    eng, s = make_engine()
    bkdir = str(tmp_path / "bk2")

    calls = {"n": 0}

    def boom(**kw):
        calls["n"] += 1
        if calls["n"] == 2:      # crash before the SECOND table
            raise RuntimeError("injected crash")

    failpoint.enable("backup-table", hook=boom)
    try:
        with pytest.raises(RuntimeError, match="injected crash"):
            tools.backup(eng, bkdir)
    finally:
        failpoint.disable("backup-table")
    # one table landed, checkpoint recorded it
    assert os.path.exists(os.path.join(bkdir, "checkpoint.json"))
    resumed = tools.backup(eng, bkdir)
    assert len(resumed) == 1          # only the remaining table
    assert not os.path.exists(os.path.join(bkdir, "checkpoint.json"))

    eng2 = Engine()
    tools.restore(eng2, bkdir)
    assert contents(eng2.new_session()) == contents(s)


def test_dump_and_load(tmp_path):
    eng, s = make_engine()
    out = str(tmp_path / "dump")
    written = tools.dump_sql(s, out)
    assert sorted(written) == ["a", "b"]
    assert os.path.exists(os.path.join(out, "a-schema.sql"))
    eng2 = Engine()
    s2 = eng2.new_session()
    tools.load_dump(s2, out)
    assert contents(s2) == contents(s)


def test_csv_roundtrip(tmp_path):
    eng, s = make_engine()
    path = str(tmp_path / "a.csv")
    n = tools.export_csv(s, "a", path)
    assert n == 3
    s.execute("CREATE TABLE a2 (k BIGINT, v VARCHAR(12), d DECIMAL(8,2), "
              "t DATE)")
    assert tools.import_csv(s, "a2", path) == 3
    assert sorted(map(str, s.query("SELECT * FROM a2").rows)) == \
        sorted(map(str, s.query("SELECT * FROM a").rows))


def test_backup_requires_superuser(tmp_path):
    eng, s = make_engine()
    s.execute("CREATE USER u1 IDENTIFIED BY 'x'")
    s2 = eng.new_session()
    s2.user = "u1"
    with pytest.raises(Exception, match="denied"):
        s2.execute(f"BACKUP TO '{tmp_path}/nope'")


def test_dump_cli_over_the_wire(tmp_path):
    from tidb_tpu.server import Server
    eng, s = make_engine()
    srv = Server(eng, port=0).start()
    try:
        out = str(tmp_path / "wire_dump")
        env = dict(os.environ)
        r = subprocess.run(
            [sys.executable, "-m", "tidb_tpu.tools", "dump",
             "--port", str(srv.port), "-o", out],
            capture_output=True, text=True, env=env, timeout=120,
            cwd="/root/repo")
        assert r.returncode == 0, r.stderr
        assert "dumped 2 table(s)" in r.stdout
        eng2 = Engine()
        s2 = eng2.new_session()
        tools.load_dump(s2, out)
        assert contents(s2) == contents(s)
    finally:
        srv.stop()


# ---- device-coverage ratchet (tools/check_coverage.py) --------------------

def _load_check_coverage():
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_coverage", os.path.join(repo, "tools", "check_coverage.py"))
    cc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cc)
    return cc


def test_check_coverage_negative_fails_on_regression(tmp_path,
                                                     monkeypatch):
    """The ratchet: a query pinned fused in COVERAGE.json that now falls
    back is a reported problem; so is a drifted or out-of-taxonomy
    fallback reason. A newly-fused query is NOT a problem."""
    import json
    cc = _load_check_coverage()
    (tmp_path / "COVERAGE.json").write_text(json.dumps({"queries": {
        "q1": {"fused": True, "fallback": None},
        "q2": {"fused": False, "fallback": "shape"},
        "q3": {"fused": False, "fallback": "shape"},
    }}))
    monkeypatch.setattr(cc, "_sweep", lambda root: {
        "q1": {"fused": False, "fallback": "device-error"},  # regressed
        "q2": {"fused": False, "fallback": "group-cap"},     # drifted
        "q3": {"fused": True, "fallback": None},             # advanced
    })
    problems = cc.run(str(tmp_path))
    assert any("q1" in p and "REGRESSED" in p for p in problems)
    assert any("q2" in p and "drifted" in p for p in problems)
    assert not any("q3" in p for p in problems)
    # and the clean case really is clean
    monkeypatch.setattr(cc, "_sweep", lambda root: {
        "q1": {"fused": True, "fallback": None},
        "q2": {"fused": False, "fallback": "shape"},
        "q3": {"fused": False, "fallback": "shape"},
    })
    assert cc.run(str(tmp_path)) == []


def test_check_coverage_missing_baseline_is_a_problem(tmp_path):
    cc = _load_check_coverage()
    problems = cc.run(str(tmp_path))
    assert problems and "COVERAGE.json" in problems[0]


def test_check_coverage_out_of_taxonomy_reason(tmp_path, monkeypatch):
    import json
    cc = _load_check_coverage()
    (tmp_path / "COVERAGE.json").write_text(json.dumps({"queries": {
        "q1": {"fused": False, "fallback": "shape"}}}))
    monkeypatch.setattr(cc, "_sweep", lambda root: {
        "q1": {"fused": False, "fallback": "weird"}})
    problems = cc.run(str(tmp_path))
    assert any("taxonomy" in p for p in problems)


def test_check_coverage_wired_into_chaos_preflight():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = open(os.path.join(repo, "tidb_tpu", "tools",
                            "chaos_sweep.py")).read()
    assert '"check_coverage"' in src, \
        "check_coverage must run as a chaos-sweep preflight"


def test_committed_coverage_baseline_shape():
    """COVERAGE.json exists, covers 22 queries, and every pinned
    fallback reason is in the fragment taxonomy."""
    import json

    from tidb_tpu.executor.fragment import FALLBACK_REASONS
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "COVERAGE.json")) as f:
        base = json.load(f)
    assert base["total"] == len(base["queries"]) == 22
    assert base["fused"] == sum(
        1 for v in base["queries"].values() if v["fused"])
    assert base["fused"] >= 16          # the ISSUE 20 coverage floor
    for q, v in base["queries"].items():
        if not v["fused"]:
            assert v["fallback"] in FALLBACK_REASONS, (q, v)
