"""Query-time attribution layer: TopSQL-style digest profiles,
cross-session Chrome-trace export, roofline accounting, metric lint.

Tier-1 (CPU-jax): the PhaseTimer ledger (device seconds, h2d/d2h/scan
bytes, compile counts, queue waits) must flow byte-exactly from the
executor through ExecutionGuard into information_schema tables, the
slow log, /statements and the timeline — and cost nothing when off."""

import json
import os
import re
import threading

import pytest

from tidb_tpu.session import Engine
from tidb_tpu.util import timeline
from tidb_tpu.util.observability import (REGISTRY, Registry, hist_quantile,
                                         normalize_sql)


@pytest.fixture()
def dev_session():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE li (a BIGINT PRIMARY KEY, b BIGINT, c DOUBLE)")
    s.execute("INSERT INTO li VALUES " +
              ",".join(f"({i},{i % 5},{i * 0.5})" for i in range(3000)))
    s.execute("SET tidb_tpu_engine = 'on'")
    s.execute("SET tidb_tpu_row_threshold = 1")
    return s


AGG = "SELECT b, COUNT(*), SUM(c) FROM li GROUP BY b"


# ---- digest profiles ------------------------------------------------------

def test_statements_summary_matches_phase_ledger_byte_exact(dev_session):
    """The digest row's device/byte/compile counters equal the exact sum
    of the per-statement PhaseTimer ledgers (the same ledger EXPLAIN
    ANALYZE renders) — integer counters match to the byte."""
    s = dev_session
    want = {"h2d": 0, "d2h": 0, "scan": 0, "compiles": 0, "wall": 0.0}
    reps = 3
    for _ in range(reps):
        assert s.query(AGG).row_count == 5
        ph = s.last_guard.phases
        want["h2d"] += ph.h2d_bytes
        want["d2h"] += ph.d2h_bytes
        want["scan"] += ph.scan_bytes
        want["compiles"] += ph.compiles
        want["wall"] += ph.wall_s
    assert want["scan"] > 0 and want["d2h"] > 0     # device path ran
    row = s.query(
        "SELECT EXEC_COUNT, DEVICE_SECONDS, H2D_BYTES, D2H_BYTES, "
        "SCAN_BYTES, COMPILES, QUEUE_P99_MS FROM "
        "information_schema.statements_summary "
        f"WHERE DIGEST_TEXT = '{AGG}'").rows
    assert len(row) == 1
    cnt, dev_s, h2d, d2h, scan, compiles, p99 = row[0]
    assert cnt == reps
    assert (h2d, d2h, scan, compiles) == (
        want["h2d"], want["d2h"], want["scan"], want["compiles"])
    assert dev_s == pytest.approx(want["wall"], abs=1e-3)
    assert p99 >= 0.0
    # warm reps re-read the resident slabs: scan accumulates every rep,
    # upload bytes only on the cold first touch
    assert scan > h2d


def test_explain_analyze_bytes_match_summary_row(dev_session):
    """The h2d/d2h bytes EXPLAIN ANALYZE prints are the same integers
    its own digest row aggregates."""
    s = dev_session
    s.query(AGG)                                    # warm compile + cache
    ea = "EXPLAIN ANALYZE " + AGG
    info = "\n".join(" ".join(str(c) for c in r) for r in s.query(ea).rows)
    m = re.search(r"h2d=(\d+)B d2h=(\d+)B", info)
    assert m, info
    h2d_printed, d2h_printed = int(m.group(1)), int(m.group(2))
    row = s.query(
        "SELECT H2D_BYTES, D2H_BYTES, EXEC_COUNT FROM "
        "information_schema.statements_summary "
        f"WHERE DIGEST_TEXT = '{ea}'").rows
    assert row == [(h2d_printed, d2h_printed, 1)]


def test_slow_query_table_carries_device_attribution(dev_session):
    s = dev_session
    s.execute("SET long_query_time = 0")            # everything is "slow"
    s.query(AGG)
    ph = s.last_guard.phases
    rows = s.query(
        "SELECT QUERY_TIME_S, DEVICE_SECONDS, H2D_BYTES, COMPILES, QUERY "
        "FROM information_schema.slow_query").rows
    mine = [r for r in rows if r[4].startswith("SELECT b, COUNT(*)")]
    assert mine
    qt, dev_s, h2d, compiles, _q = mine[0]          # newest first
    assert qt > 0.0 and dev_s > 0.0
    assert h2d == ph.h2d_bytes and compiles == ph.compiles


def test_explain_analyze_reports_roofline_fraction(dev_session):
    from tidb_tpu.util import roofline
    s = dev_session
    # deterministic denom; 0.5 GB/s keeps the warm sub-ms fraction
    # well above the 3-decimal display rounding edge
    roofline.set_measured_gbs(0.5)
    try:
        s.query(AGG)
        info = "\n".join(" ".join(str(c) for c in r)
                         for r in s.query("EXPLAIN ANALYZE " + AGG).rows)
        m = re.search(r"roofline_fraction:(\d+\.\d+)", info)
        assert m, info
        frac = float(m.group(1))
        assert 0.0 < frac <= 1.0
        ph = s.last_guard.phases
        assert frac == pytest.approx(
            roofline.fraction(ph.scan_bytes, ph.wall_s, gbs=0.5),
            abs=1e-3)
    finally:
        roofline.set_measured_gbs(0.0)


# ---- satellite: registry fixes -------------------------------------------

def test_metric_rows_include_histogram_buckets():
    r = Registry()
    for v in (0.003, 0.003, 0.05, 1.0):
        r.observe("tidb_tpu_stmt_seconds", v, {"stmt": "Q"})
    rows = {(n, lbl): v for n, lbl, v in r.metric_rows()}
    # cumulative per-bucket rows, matching render_prometheus semantics
    assert rows[("tidb_tpu_stmt_seconds_bucket", "stmt=Q,le=0.005")] == 2.0
    assert rows[("tidb_tpu_stmt_seconds_bucket", "stmt=Q,le=0.1")] == 3.0
    assert rows[("tidb_tpu_stmt_seconds_bucket", "stmt=Q,le=2.0")] == 4.0
    assert rows[("tidb_tpu_stmt_seconds_bucket", "stmt=Q,le=+Inf")] == 4.0
    assert rows[("tidb_tpu_stmt_seconds_count", "stmt=Q")] == 4.0
    # SQL-derivable p50 from the buckets (the point of the fix)
    h = r.hists[("tidb_tpu_stmt_seconds", (("stmt", "Q"),))]
    assert 0.001 <= hist_quantile(h, 0.5) <= 0.005
    assert hist_quantile([[0] * 8, 0.0, 0], 0.99) == 0.0


def test_normalize_sql_collapses_negative_literals():
    pos = normalize_sql("SELECT * FROM t WHERE x = 5")
    neg = normalize_sql("SELECT * FROM t WHERE x = -5")
    assert pos == neg == "SELECT * FROM t WHERE x = ?"
    assert normalize_sql("SELECT * FROM t WHERE x IN (-1, 2, -3)") == \
        "SELECT * FROM t WHERE x IN (?)"
    # binary minus between operands is NOT a sign — keep it
    assert normalize_sql("SELECT a - 5 FROM t") == "SELECT a - ? FROM t"
    assert normalize_sql("SELECT 1 - -2") == "SELECT ? - ?"


def test_registry_processlist_delegates_to_session_registry():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE pr (a BIGINT)")
    seen = {}

    def probe():
        # capture the registry's view WHILE a statement is running
        seen["rows"] = REGISTRY.process_rows()
        return 1

    import tidb_tpu.session as sess_mod
    orig = sess_mod.Session._execute_stmt

    def wrapper(self, stmt):
        rs = orig(self, stmt)
        if not seen:
            probe()
        return rs

    try:
        sess_mod.Session._execute_stmt = wrapper
        s.query("SELECT COUNT(*) FROM pr")
    finally:
        sess_mod.Session._execute_stmt = orig
    rows = seen["rows"]
    assert any(cid == s.conn_id and "pr" in (sql or "")
               for cid, _t, sql in rows)
    # the registry holds NO duplicate processlist state of its own
    assert not hasattr(REGISTRY, "processlist")


# ---- timeline -------------------------------------------------------------

def test_timeline_off_by_default_and_zero_events(dev_session):
    assert timeline.ENABLED is False
    s = dev_session
    s.query(AGG)
    assert timeline.ENABLED is False
    assert timeline.global_path() is None
    # record() is a no-op without a collector attached
    timeline.record("x", "sched", dur_us=5.0, pid=1)


def test_trace_format_chrome_single_statement(dev_session):
    s = dev_session
    rs = s.query("TRACE FORMAT='chrome' " + AGG)
    assert rs.names == ["trace"]
    doc = json.loads(rs.rows[0][0])
    evs = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    assert evs, "no events captured"
    cats = {e["cat"] for e in evs}
    assert "compute" in cats and "fetch" in cats
    assert {e["pid"] for e in evs} == {s.conn_id}
    # scoped capture must detach afterwards
    assert timeline.ENABLED is False
    with pytest.raises(Exception):
        s.query("TRACE FORMAT='bogus' SELECT 1")


def test_cross_session_trace_c8_storm(tmp_path):
    """8 concurrent sessions with tidb_tpu_trace_dir set produce ONE
    Chrome-trace JSON: parseable, ts monotonic per (pid, tid), with
    scheduler-queue, compile, upload-stream and eviction events from
    at least 2 distinct connections."""
    eng = Engine()
    boot = eng.new_session()
    boot.execute(
        "CREATE TABLE st (a BIGINT PRIMARY KEY, b BIGINT, c DOUBLE)")
    boot.execute("INSERT INTO st VALUES " +
                 ",".join(f"({i},{i % 9},{i * 1.5})" for i in range(4000)))
    try:
        sessions = []
        for _ in range(8):
            ss = eng.new_session()
            ss.execute("SET tidb_tpu_engine = 'on'")
            ss.execute("SET tidb_tpu_row_threshold = 1")
            ss.execute(f"SET tidb_tpu_trace_dir = '{tmp_path}'")
            sessions.append(ss)
        errors = []

        def worker(k):
            try:
                for i in range(3):
                    # per-thread distinct aggregate → distinct compile
                    sessions[k].query(
                        f"SELECT b, COUNT(*), SUM(c + {k}) FROM st "
                        f"GROUP BY b")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        # force evictions into the trace: shrink the HBM budget so the
        # next table the engine opens must evict st's resident slabs
        sessions[0].execute(
            "CREATE TABLE st2 (a BIGINT PRIMARY KEY, b BIGINT)")
        sessions[0].execute("INSERT INTO st2 VALUES " +
                            ",".join(f"({i},{i % 3})" for i in range(2000)))
        sessions[0].execute("SET tidb_tpu_hbm_budget = 1024")
        sessions[0].query("SELECT b, COUNT(*) FROM st2 GROUP BY b")
        path = timeline.flush()
        assert path is not None and os.path.dirname(path) == str(tmp_path)
        files = [f for f in os.listdir(tmp_path) if f.endswith(".json")]
        assert len(files) == 1                      # ONE cross-session file
        doc = json.loads(open(path).read())         # parses cleanly
        evs = [e for e in doc["traceEvents"] if e["ph"] != "M"]
        last = {}
        for e in evs:                               # monotonic ts per lane
            key = (e["pid"], e["tid"])
            assert e["ts"] >= last.get(key, -1.0)
            last[key] = e["ts"]
        cats = {e["cat"] for e in evs}
        assert {"sched", "compile", "upload", "cache"} <= cats, cats
        assert len({e["pid"] for e in evs}) >= 2
        names = {e["name"] for e in evs}
        assert "evict" in names
        # process/thread metadata lanes exist for the viewer
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert any(m["name"] == "process_name" for m in metas)
        assert any(m["name"] == "thread_name" for m in metas)
    finally:
        timeline.stop_global()
    assert timeline.ENABLED is False


# ---- satellite: status server under concurrency --------------------------

def test_status_server_concurrent_storm_and_clean_shutdown():
    import urllib.request
    from tidb_tpu.util.status_server import StatusServer
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ss (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO ss VALUES " +
              ",".join(f"({i},{i % 4})" for i in range(500)))
    srv = StatusServer(eng, port=0).start()
    stop = threading.Event()
    errors = []

    def querier():
        ses = eng.new_session()
        while not stop.is_set():
            try:
                ses.query("SELECT b, COUNT(*) FROM ss GROUP BY b")
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    def prom_parse(text):
        """Minimal Prometheus text parser: name{labels} value."""
        out = []
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            name_part, _, val = line.rpartition(" ")
            assert name_part, line
            float(val)                               # value must be numeric
            out.append(name_part)
        return out

    def getter(path, check):
        url = f"http://127.0.0.1:{srv.port}{path}"
        for _ in range(10):
            try:
                check(urllib.request.urlopen(url, timeout=5).read())
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    qthreads = [threading.Thread(target=querier) for _ in range(8)]
    gthreads = [
        threading.Thread(target=getter, args=(
            "/metrics", lambda b: prom_parse(b.decode()))),
        threading.Thread(target=getter, args=(
            "/status", lambda b: json.loads(b))),
        threading.Thread(target=getter, args=(
            "/statements", lambda b: json.loads(b))),
    ]
    for t in qthreads + gthreads:
        t.start()
    for t in gthreads:
        t.join()
    stop.set()
    for t in qthreads:
        t.join()
    srv.stop()                                       # clean shutdown
    assert not errors, errors[:3]
    # the extended payload keeps the original keys AND the profile ones
    import urllib.error
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=1)


def test_statements_payload_has_attribution_keys(dev_session):
    import urllib.request
    from tidb_tpu.util.status_server import StatusServer
    s = dev_session
    s.query(AGG)
    srv = StatusServer(port=0).start()
    try:
        data = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/statements"))
        hit = [r for r in data if r["digest"] == AGG]
        assert hit
        for k in ("digest", "count", "sum_s", "device_s", "h2d_bytes",
                  "d2h_bytes", "scan_bytes", "compiles", "queue_p50_ms",
                  "queue_p99_ms", "phase_s"):
            assert k in hit[0], k
        assert hit[0]["scan_bytes"] > 0
    finally:
        srv.stop()


# ---- satellite: metrics lint ---------------------------------------------

def test_check_metrics_clean_on_repo_and_catches_drift(tmp_path):
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_metrics", os.path.join(repo, "tools", "check_metrics.py"))
    cm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cm)
    assert cm.run(repo) == []
    bad = tmp_path / "bad.py"
    bad.write_text(
        'REGISTRY.inc("queries")\n'
        'REGISTRY.inc("tidb_tpu_fooTotal_total")\n'
        'REGISTRY.observe("tidb_tpu_x_total", 1.0)\n'
        'REGISTRY.inc("tidb_tpu_ok_total", {"weird_label": "v"})\n'
        'REGISTRY.inc(name_var)\n')
    problems = cm.check_file(str(bad))
    assert len(problems) >= 5
    assert any("snake_case" in p for p in problems)
    assert any("unit suffix" in p or "_total" in p for p in problems)
    assert any("vocabulary" in p for p in problems)
