"""Compressed device-resident column layouts (chunk/compress.py +
device_cache encode/decode wiring).

Pinned invariants:

* the codec round-trips byte-exactly through every edge case: NULL
  validity under bit-packing, negative ints (min-as-reference FoR),
  all-NULL columns (width 0), single-distinct columns (width 0), and a
  dictionary-cardinality threshold crossing mid-table falls back to
  plain packing rather than overflowing the code width;
* a corrupted layout descriptor raises a typed LayoutError — never a
  silent mis-decode — and the `compressed-decode-mismatch` failpoint
  drives the full statement path to a warned CPU fallback that still
  returns oracle rows;
* compression on/off/CPU-oracle agree byte-exactly through the chain,
  fused-pipeline and staged-dist executors on a table built from the
  edge cases above;
* `information_schema.table_storage` physical/logical bytes reconcile
  byte-exactly with the cold statement's PhaseTimer ledger and with
  the statements_summary H2D counters;
* the HBM budget evicts on PHYSICAL bytes: two tables whose combined
  physical residency fits a budget their logical footprint does not
  both stay resident;
* EXPLAIN ANALYZE reports an effective_roofline_fraction (logical
  bytes, unclamped) strictly above the physical roofline_fraction when
  compression is active.
"""

import re

import numpy as np
import pytest

from tidb_tpu.chunk import compress
from tidb_tpu.chunk.compress import ColLayout
from tidb_tpu.errors import LayoutError
from tidb_tpu.executor import build, device_cache as dc, run_to_completion
from tidb_tpu.executor.fragment import TpuFragmentExec
from tidb_tpu.parser import parse
from tidb_tpu.session import Engine
from tidb_tpu.util import failpoint


# ---------------------------------------------------------------------------
# codec round-trips (numpy oracle — the same decode the trace emits)
# ---------------------------------------------------------------------------

def _roundtrip(vals, valid, *, allow_dict=True, cap=None):
    """choose → pack → decode one padded slab; returns (layout, dv, dm)."""
    cap = cap or len(vals)
    lay, dictvals = compress.choose_layout(vals, valid,
                                           allow_dict=allow_dict)
    assert lay is not None
    pv = np.zeros(cap, dtype=vals.dtype)
    pm = np.zeros(cap, dtype=bool)
    pv[:len(vals)], pm[:len(valid)] = vals, valid
    slab = compress.pack_slab(lay, pv, pm, dictvals)
    if lay.kind == "dict":
        slab = slab + (dictvals,)
    dv, dm = compress.decode_slab(lay, slab, cap, np)
    return lay, np.asarray(dv), np.asarray(dm)


def test_null_validity_under_bitpacking():
    rng = np.random.default_rng(3)
    vals = rng.integers(0, 200, size=777).astype(np.int64)
    valid = rng.random(777) > 0.3
    lay, dv, dm = _roundtrip(vals, valid, allow_dict=False, cap=1024)
    assert lay.kind == "pack" and lay.width == 8
    # the packed mask restores validity bit-for-bit, padding included
    assert np.array_equal(dm[:777], valid) and not dm[777:].any()
    assert np.array_equal(dv[:777][valid], vals[valid])


def test_negative_ints_use_min_as_reference():
    vals = np.array([-1000, -997, -3, -1000, -500], dtype=np.int64)
    valid = np.ones(5, dtype=bool)
    lay, dv, dm = _roundtrip(vals, valid, allow_dict=False, cap=8)
    assert lay.ref == -1000, "FoR reference must be the observed min"
    assert np.array_equal(dv[:5], vals)
    assert dv.dtype == np.int64


def test_all_null_column_packs_to_width_zero():
    vals = np.zeros(300, dtype=np.int64)
    valid = np.zeros(300, dtype=bool)
    lay, dv, dm = _roundtrip(vals, valid, cap=512)
    assert lay.width == 0
    assert not dm.any()
    # width-0 slab stores a 1-word stub, not cap words
    slab = compress.pack_slab(lay, np.zeros(512, dtype=np.int64),
                              np.zeros(512, dtype=bool))
    assert slab[0].shape == (1,)


def test_single_distinct_column_packs_to_width_zero():
    vals = np.full(400, 42, dtype=np.int64)
    valid = np.ones(400, dtype=bool)
    lay, dv, dm = _roundtrip(vals, valid, cap=512)
    assert lay.width == 0 and lay.ref == 42
    assert (dv[:400] == 42).all() and dm[:400].all()


def test_dict_chosen_for_sparse_low_cardinality():
    # 7 distinct values spread over a 2^40 range: FoR needs >32 bits
    # (raw), the dictionary needs 4
    rng = np.random.default_rng(5)
    uniq = np.array([0, 1 << 20, 1 << 30, 1 << 35, 1 << 38, 1 << 39,
                     (1 << 40) - 1], dtype=np.int64)
    vals = uniq[rng.integers(0, 7, size=900)]
    valid = rng.random(900) > 0.1
    lay, dv, dm = _roundtrip(vals, valid, cap=1024)
    assert lay.kind == "dict" and lay.card == 7 and lay.width == 4
    assert np.array_equal(dv[:900][valid], vals[valid])


def test_dict_threshold_crossing_falls_back_to_pack():
    """First half low-cardinality, second half crosses DICT_CARD_CAP:
    the GLOBAL layout decision must abandon the dictionary (codes would
    overflow) and still round-trip exactly via plain packing."""
    lo = np.arange(100, dtype=np.int64) % 16
    hi = np.arange(compress.DICT_CARD_CAP + 50, dtype=np.int64)
    vals = np.concatenate([lo, hi])
    valid = np.ones(len(vals), dtype=bool)
    lay, dv, dm = _roundtrip(vals, valid, cap=8192)
    assert lay.kind == "pack", "cardinality above the cap must not dict"
    assert np.array_equal(dv[:len(vals)], vals)


@pytest.mark.parametrize("width,hi", [(1, 2), (2, 4), (4, 16), (8, 256),
                                      (16, 65536), (32, 1 << 32)])
def test_pack_roundtrip_every_width(width, hi):
    rng = np.random.default_rng(width)
    vals = rng.integers(0, hi, size=500).astype(np.int64)
    vals[0], vals[1] = 0, hi - 1                    # pin the extremes
    valid = rng.random(500) > 0.2
    valid[:2] = True
    lay, dv, dm = _roundtrip(vals, valid, allow_dict=False, cap=512)
    assert lay.width == width
    assert np.array_equal(dv[:500][valid], vals[valid])


def test_validate_rejects_corrupt_descriptors():
    good = ColLayout("pack", 8, 0, "int64")
    compress.validate(good)                         # sanity: passes
    for bad in (
        "not-a-layout",
        ColLayout("zstd", 8, 0, "int64"),           # unknown kind
        ColLayout("pack", 7, 0, "int64"),           # illegal width
        ColLayout("pack", 8, 0, "float64"),         # non-integer dtype
        ColLayout("dict", 4, 0, "int64", 0),        # dict without card
    ):
        with pytest.raises(LayoutError):
            compress.validate(bad)


# ---------------------------------------------------------------------------
# engine fixtures
# ---------------------------------------------------------------------------

def run_device(s, sql, *, max_slab=None, dist=None, staged=None):
    """Execute on the device path, asserting no CPU fallback."""
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    if max_slab is not None:
        s.vars["tidb_tpu_max_slab_rows"] = max_slab
    if dist is not None:
        s.vars["tidb_tpu_dist"] = dist
    if staged is not None:
        s.vars["tidb_tpu_dist_staged"] = staged
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags, f"no fragment extracted for: {sql}"
        for f in frags:
            assert f.used_device, f"fell back to CPU: {f.fallback_reason}"
        return [r for ch in chunks for r in ch.rows()]
    finally:
        s.vars["tidb_tpu_engine"] = "off"
        for k in ("tidb_tpu_max_slab_rows", "tidb_tpu_dist",
                  "tidb_tpu_dist_staged"):
            s.vars.pop(k, None)


def _cache_entry(eng, table_name):
    tid = eng.catalog.info_schema.table(table_name).id
    for (_dev, sid, t, _parts), ent in dc._CACHE.items():
        if sid == id(eng.store) and t == tid:
            return ent
    raise AssertionError(f"no cache entry for {table_name}")


def _edge_case_engine(n=3000):
    """One table exercising every layout edge case at once: negatives
    with NULLs (FoR), an all-NULL column, a single-distinct column, a
    sparse low-cardinality dict column and a date-like FoR column."""
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE ec (neg BIGINT, con BIGINT, nul BIGINT, "
              "dct BIGINT, d BIGINT)")
    rng = np.random.default_rng(17)
    uniq = [0, 1 << 30, 1 << 35, 1 << 39]
    rows = []
    for i in range(n):
        neg = "NULL" if i % 13 == 0 else str(int(rng.integers(-900, -100)))
        rows.append(f"({neg}, 7, NULL, {uniq[i % 4]}, "
                    f"{20200101 + int(rng.integers(0, 365))})")
    s.execute("INSERT INTO ec VALUES " + ",".join(rows))
    return eng, s


EC_Q = ("SELECT dct, COUNT(*), COUNT(neg), COUNT(nul), SUM(neg), "
        "MIN(con), MIN(d), MAX(d) FROM ec GROUP BY dct")


def _sorted_rows(rows):
    return sorted(rows, key=str)


def test_edge_cases_byte_exact_chain_on_off_oracle():
    eng, s = _edge_case_engine()
    oracle = _sorted_rows(s.query(EC_Q).rows)
    on = _sorted_rows(run_device(s, EC_Q, max_slab=1024))
    assert on == oracle
    ent = _cache_entry(eng, "ec")
    sigs = {i: l.sig() for i, l in ent.layouts.items() if l is not None}
    assert any(s_.startswith("dict:") for s_ in sigs.values()), sigs
    assert any(s_.startswith("pack:w0:") for s_ in sigs.values()), sigs
    # negatives must be min-referenced packs, not raw
    assert any(":r-" in s_ for s_ in sigs.values()), sigs
    s.vars["tidb_tpu_compression"] = "off"
    off = _sorted_rows(run_device(s, EC_Q, max_slab=1024))
    assert off == oracle
    ent2 = _cache_entry(eng, "ec")
    assert not any(l is not None for l in ent2.layouts.values())


def test_edge_cases_byte_exact_staged_dist():
    eng, s = _edge_case_engine()
    oracle = _sorted_rows(s.query(EC_Q).rows)
    got = _sorted_rows(run_device(s, EC_Q, max_slab=1024, dist=4))
    assert got == oracle


def test_edge_cases_byte_exact_monolithic_dist():
    eng, s = _edge_case_engine()
    oracle = _sorted_rows(s.query(EC_Q).rows)
    got = _sorted_rows(
        run_device(s, EC_Q, max_slab=1024, dist=4, staged="off"))
    assert got == oracle


def test_fused_join_byte_exact_on_off_oracle():
    eng, s = _edge_case_engine()
    s.execute("CREATE TABLE dim (id BIGINT, tag VARCHAR(8))")
    s.execute("INSERT INTO dim VALUES (0,'a'),(1073741824,'b'),"
              f"({1 << 35},'c'),({1 << 39},'d')")
    q = ("SELECT dim.tag, COUNT(*), SUM(ec.neg) FROM ec "
         "JOIN dim ON ec.dct = dim.id GROUP BY dim.tag")
    oracle = _sorted_rows(s.query(q).rows)
    fused = _sorted_rows(run_device(s, q, max_slab=1024))
    assert fused == oracle
    s.vars["tidb_tpu_fused_pipeline"] = "off"
    try:
        tree = _sorted_rows(run_device(s, q, max_slab=1024))
    finally:
        s.vars.pop("tidb_tpu_fused_pipeline", None)
    assert tree == oracle


# ---------------------------------------------------------------------------
# storage accounting: table_storage ↔ PhaseTimer ↔ statements_summary
# ---------------------------------------------------------------------------

def test_table_storage_reconciles_with_phase_ledger():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE tsr (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO tsr VALUES " +
              ",".join(f"({i % 50}, {i % 3})" for i in range(4000)))
    s.execute("SET tidb_tpu_engine = 'on'")
    s.execute("SET tidb_tpu_row_threshold = 1")
    q = "SELECT b, COUNT(*), SUM(a) FROM tsr GROUP BY b"
    s.query(q)                                      # cold first touch
    ph = s.last_guard.phases
    assert ph.h2d_bytes > 0
    assert ph.h2d_logical_bytes > ph.h2d_bytes, \
        "narrow ints must actually compress"
    rows = s.query(
        "SELECT COLUMN_NAME, LAYOUT, PHYSICAL_BYTES, LOGICAL_BYTES "
        "FROM information_schema.table_storage "
        "WHERE TABLE_NAME = 'tsr'").rows
    assert {r[0] for r in rows} == {"a", "b"}
    assert all(r[1].startswith("pack:") for r in rows), rows
    # the cold upload IS the physical residency — byte-exact both ways
    assert sum(r[2] for r in rows) == ph.h2d_bytes
    assert sum(r[3] for r in rows) == ph.h2d_logical_bytes
    # and the digest row aggregates the same integers
    srow = s.query(
        "SELECT H2D_BYTES, H2D_LOGICAL_BYTES, SCAN_LOGICAL_BYTES FROM "
        "information_schema.statements_summary "
        f"WHERE DIGEST_TEXT = '{q}'").rows
    assert srow == [(ph.h2d_bytes, ph.h2d_logical_bytes,
                     ph.scan_logical_bytes)]


def test_eviction_budget_charges_physical_bytes():
    """Two tables whose combined PHYSICAL bytes fit a budget their
    LOGICAL footprint does not must both stay resident — the budget
    accountant sees compressed reality, not the uncompressed fiction."""
    eng = Engine()
    s = eng.new_session()
    for t in ("ev1", "ev2"):
        s.execute(f"CREATE TABLE {t} (a BIGINT)")
        s.execute(f"INSERT INTO {t} VALUES " +
                  ",".join(f"({i % 4})" for i in range(4000)))
    run_device(s, "SELECT COUNT(*), SUM(a) FROM ev1")
    e1 = _cache_entry(eng, "ev1")
    phys, logical = e1.hbm_bytes(), e1.logical_bytes()
    assert phys * 4 < logical, (phys, logical)
    s.vars["tidb_tpu_hbm_budget"] = phys * 3        # fits 2×phys, not logical
    try:
        run_device(s, "SELECT COUNT(*), SUM(a) FROM ev2")
    finally:
        s.vars.pop("tidb_tpu_hbm_budget", None)
    # ev1 survived: charging logical bytes would have evicted it
    e1b = _cache_entry(eng, "ev1")
    assert e1b is e1
    assert not any(a.is_deleted() for slabs in e1.dev.values()
                   for t in slabs for a in t)


def test_effective_roofline_fraction_reported():
    from tidb_tpu.util import roofline
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE rf (a BIGINT, b BIGINT)")
    s.execute("INSERT INTO rf VALUES " +
              ",".join(f"({i % 50}, {i % 3})" for i in range(3000)))
    s.execute("SET tidb_tpu_engine = 'on'")
    s.execute("SET tidb_tpu_row_threshold = 1")
    q = "SELECT b, COUNT(*), SUM(a) FROM rf GROUP BY b"
    # 0.5 GB/s keeps the warm sub-ms fractions well above
    # the 3-decimal display rounding edge
    roofline.set_measured_gbs(0.5)
    try:
        s.query(q)
        info = "\n".join(" ".join(str(c) for c in r)
                         for r in s.query("EXPLAIN ANALYZE " + q).rows)
        m = re.search(r"(?<!effective_)roofline_fraction:(\d+\.\d+)", info)
        me = re.search(r"effective_roofline_fraction:(\d+\.\d+)", info)
        assert m and me, info
        frac, eff = float(m.group(1)), float(me.group(1))
        ph = s.last_guard.phases
        assert ph.scan_logical_bytes > ph.scan_bytes
        # logical bytes > physical bytes → the effective figure is
        # strictly the larger one (and may legitimately exceed 1.0)
        assert eff > frac > 0.0
        assert eff == pytest.approx(
            roofline.effective_fraction(ph.scan_logical_bytes, ph.wall_s,
                                        gbs=0.5), abs=1e-3)
    finally:
        roofline.set_measured_gbs(0.0)


# ---------------------------------------------------------------------------
# corruption: typed error + CPU fallback, never silent wrong rows
# ---------------------------------------------------------------------------

def test_corrupted_descriptor_falls_back_to_cpu():
    eng, s = _edge_case_engine(n=1500)
    oracle = _sorted_rows(s.query(EC_Q).rows)
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    failpoint.enable("compressed-decode-mismatch",
                     value="test: descriptor drift")
    try:
        plan = s._plan(parse(EC_Q)[0])
        root = build(plan)
        chunks = run_to_completion(root, s._exec_ctx())
        got = _sorted_rows([r for ch in chunks for r in ch.rows()])
        assert got == oracle, "fallback must still return oracle rows"
        frags = []

        def walk(e):
            if isinstance(e, TpuFragmentExec):
                frags.append(e)
            for c in getattr(e, "children", []):
                walk(c)

        walk(root)
        assert frags
        for f in frags:
            assert not f.used_device, "corrupt layout must not serve"
            assert "layout" in (f.fallback_reason or "").lower() or \
                "corrupt" in (f.fallback_reason or "").lower(), \
                f.fallback_reason
    finally:
        failpoint.disable("compressed-decode-mismatch")
        s.vars["tidb_tpu_engine"] = "off"
    # disarmed: the device path serves the same rows again
    assert _sorted_rows(run_device(s, EC_Q)) == oracle


def test_layout_error_is_typed_not_silent():
    """The failpoint surfaces as LayoutError at the cache layer — the
    executor's fallback is catching a TYPED error, not swallowing a
    wrong answer."""
    eng, s = _edge_case_engine(n=800)
    run_device(s, EC_Q)                             # populate the cache
    ent = _cache_entry(eng, "ec")
    failpoint.enable("compressed-decode-mismatch", value="boom")
    try:
        with pytest.raises(LayoutError, match="corrupted"):
            dc._validate_layouts(ent, list(ent.dev))
    finally:
        failpoint.disable("compressed-decode-mismatch")
    dc._validate_layouts(ent, list(ent.dev))        # disarmed: clean
