"""Wide decimals — DECIMAL(>18) as exact Python ints host-side and
base-10⁹ limb planes on device (ref: types/mydecimal.go:236-246 MyDecimal
9-digit words; executor/aggfuncs/func_sum.go decimal states)."""

import decimal
from decimal import Decimal

decimal.getcontext().prec = 200   # oracle math must not round (the
                                  # default 28-digit context would)

import numpy as np
import pytest

from tidb_tpu.session import Engine


@pytest.fixture(scope="module")
def s():
    eng = Engine()
    s = eng.new_session()
    s.execute("CREATE TABLE w (g BIGINT, a DECIMAL(38,10), "
              "b DECIMAL(15,2))")
    rng = np.random.default_rng(4)
    rows = []
    for _ in range(30000):
        big = int(rng.integers(-10**18, 10**18))
        frac = int(rng.integers(0, 10**10))
        rows.append(f"({int(rng.integers(0, 7))},"
                    f"'{big}{int(rng.integers(0, 10**9)):09d}.{frac:010d}',"
                    f"{round(float(rng.uniform(-999, 999)), 2)})")
    for i in range(0, len(rows), 10000):
        s.execute("INSERT INTO w VALUES " + ",".join(rows[i:i + 10000]))
    s.execute("INSERT INTO w VALUES (0, NULL, NULL)")
    s.execute("ANALYZE TABLE w")
    return s


def test_exact_roundtrip(s):
    s.execute("CREATE TABLE wr (a DECIMAL(38,10))")
    lit = "1234567890123456789012345678.0123456789"
    s.execute(f"INSERT INTO wr VALUES ('{lit}'), ('-0.0000000001'), (NULL)")
    got = s.query("SELECT a FROM wr ORDER BY a").rows
    assert got[0][0] is None
    assert got[1][0] == Decimal("-0.0000000001")
    assert got[2][0] == Decimal(lit)        # all 38 digits survive


def test_wide_65_digits(s):
    s.execute("CREATE TABLE w65 (a DECIMAL(65,30))")
    lit = ("9" * 35) + "." + ("8" * 30)
    s.execute(f"INSERT INTO w65 VALUES ('{lit}'), ('{lit}')")
    got = s.query("SELECT SUM(a), MIN(a), MAX(a) FROM w65").rows[0]
    assert got[0] == Decimal(lit) * 2
    assert got[1] == got[2] == Decimal(lit)


def test_cpu_aggregates_exact(s):
    # brute-force oracle over the raw rows
    raw = s.query("SELECT g, a FROM w WHERE a IS NOT NULL").rows
    sums = {}
    for g, a in raw:
        sums.setdefault(g, []).append(a)
    got = {r[0]: r for r in s.query(
        "SELECT g, SUM(a), MIN(a), MAX(a), COUNT(a) FROM w GROUP BY g"
    ).rows}
    for g, vals in sums.items():
        assert got[g][1] == sum(vals)
        assert got[g][2] == min(vals)
        assert got[g][3] == max(vals)
        assert got[g][4] == len(vals)


def test_arithmetic_and_compare(s):
    r = s.query("SELECT a + a, a * 2 FROM w WHERE a > 0 LIMIT 5").rows
    for twice, dbl in r:
        assert twice == dbl
    n_pos = s.query("SELECT COUNT(*) FROM w WHERE a > 0").rows[0][0]
    n_neg = s.query("SELECT COUNT(*) FROM w WHERE a < 0").rows[0][0]
    n = s.query("SELECT COUNT(a) FROM w").rows[0][0]
    assert n_pos + n_neg == n       # no zeros in the generated data


def test_device_limb_aggs_match_cpu(s):
    # SUM/AVG/COUNT run on the device limb path (SumAgg._update_wide over
    # wide_decimal_limbs planes); strict mode proves no CPU fallback
    sql = "SELECT g, SUM(a), AVG(a), COUNT(a), SUM(b) FROM w GROUP BY g"
    want = sorted(map(str, s.query(sql).rows))
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_strict="on", tidb_tpu_max_slab_rows=8192)
    try:
        got = sorted(map(str, s.query(sql).rows))   # 4 slabs, limb merge
    finally:
        s.vars.update(tidb_tpu_engine="off", tidb_tpu_strict="off")
        s.vars.pop("tidb_tpu_max_slab_rows", None)
    assert got == want


def test_device_narrow_arg_wide_result(s):
    # SUM(DECIMAL(15,2)) types as DECIMAL(37,2): the device must split
    # int64 inputs into limbs, or the accumulation overflows silently
    sql = "SELECT SUM(b) FROM w"
    want = s.query(sql).rows
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_strict="on")
    try:
        got = s.query(sql).rows
    finally:
        s.vars.update(tidb_tpu_engine="off", tidb_tpu_strict="off")
    assert got == want


def test_device_unsupported_wide_shapes_fall_back(s):
    # MIN/MAX / filters over wide columns route to CPU (still correct)
    for sql in [
        "SELECT g, MIN(a), MAX(a) FROM w GROUP BY g",
        "SELECT COUNT(*) FROM w WHERE a > 0",
    ]:
        want = sorted(map(str, s.query(sql).rows))
        s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1)
        try:
            got = sorted(map(str, s.query(sql).rows))
        finally:
            s.vars.update(tidb_tpu_engine="off")
        assert got == want


def test_codec_roundtrip_wide(s):
    from tidb_tpu.chunk import Column, Chunk
    from tidb_tpu.chunk.codec import decode_chunk, encode_chunk
    from tidb_tpu import types as T
    ft = T.decimal(40, 5)
    col = Column.from_list(ft, ["1" * 35 + ".12345", None, "-" + "9" * 30])
    buf = encode_chunk(Chunk([col]))
    back = decode_chunk(buf, [ft]).columns[0]
    assert back.values[0] == col.values[0]
    assert back.is_null(1)
    assert back.values[2] == col.values[2]


def test_limb_split_recombine():
    from tidb_tpu.executor.device_cache import (wide_decimal_limbs,
                                                wide_decimal_unlimb)
    vals = np.array([10**37 - 1, -(10**37 - 1), 0, 123456789,
                     -987654321012345678901234567], dtype=object)
    limbs = wide_decimal_limbs(vals, 5)
    assert limbs.dtype == np.int64
    # lower planes in [0, 2^30); recombination is exact
    assert (limbs[:-1] >= 0).all() and (limbs[:-1] < (1 << 30)).all()
    back = wide_decimal_unlimb(limbs)
    assert list(back) == list(vals)


def test_device_computed_wide_expression(s):
    # SUM/AVG over a COMPUTED wide-typed expression (DECIMAL×DECIMAL →
    # DECIMAL(34,4)) arrives on device as 1-D int64 and must split/
    # recombine in the SAME limb base as storage planes (round-4 review
    # catch: a base mismatch here returned silently wrong sums)
    s.execute("CREATE TABLE cw (a DECIMAL(15,2), c DECIMAL(15,2))")
    rng = np.random.default_rng(6)
    s.execute("INSERT INTO cw VALUES " + ",".join(
        f"({round(float(rng.uniform(1, 99999)), 2)},"
        f"{round(float(rng.uniform(1, 99999)), 2)})"
        for _ in range(20000)))
    s.execute("ANALYZE TABLE cw")
    sql = "SELECT SUM(a * c), AVG(a * c), COUNT(*) FROM cw"
    want = s.query(sql).rows
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1,
                  tidb_tpu_strict="on")
    try:
        got = s.query(sql).rows
    finally:
        s.vars.update(tidb_tpu_engine="off", tidb_tpu_strict="off")
    assert got == want


def test_device_scan_root_fragment_emits_all_columns(s):
    # a bare filtered-scan fragment must upload EVERY schema column
    # (round-4 regression: only filter columns uploaded → IndexError)
    sql = "SELECT * FROM w WHERE g = 3"
    want = sorted(map(str, s.query(sql).rows))
    s.vars.update(tidb_tpu_engine="on", tidb_tpu_row_threshold=1)
    try:
        got = sorted(map(str, s.query(sql).rows))
    finally:
        s.vars.update(tidb_tpu_engine="off")
    assert got == want
