#!/usr/bin/env python
"""Failpoint-catalog drift lint: the chaos sweep can only gate coverage
over sites it can ENUMERATE, so the site catalog
(tidb_tpu/util/failpoint.py `register(...)` — plus module-local
registrations like executor/zonemap.py's) and the `failpoint.inject(...)`
call sites in the tree must agree both ways:

  * every inject() with a literal site name must name a REGISTERED site
    (an unregistered site is invisible to the sweep's coverage gate —
    a fault path nobody sweeps);
  * every registered site must be REFERENCED in code — as an inject()
    literal or (for the shared-helper sites the distributed path
    dispatches dynamically, e.g. `failpoint.inject(site)`) as a string
    literal passed toward one;
  * inject() must not be called with a dynamic name unless some
    registered site reaches it as a literal elsewhere in the same file
    (otherwise the name can drift from the catalog silently).

Run directly (`python tools/check_failpoints.py`) or let the chaos
sweep entry point run it — like tools/check_metrics.py, drift fails the
sweep before any scenario spends wall time. Exit 0 = clean, 1 =
violations (one per line as path:lineno: message)."""

import ast
import os
import sys


def _is_inject(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "inject"
            and isinstance(f.value, ast.Name) and f.value.id == "failpoint")


def _is_register(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "register" and \
            isinstance(f.value, ast.Name) and f.value.id == "failpoint":
        return True
    # failpoint.py registers its own sites via a bare register() call
    return isinstance(f, ast.Name) and f.id == "register"


def scan_file(path: str):
    """→ (inject_literals [(name, lineno)], dynamic_injects [lineno],
    registered [(name, lineno)], string_constants {str})."""
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [], [], [], set(), [f"{path}:{e.lineno}: unparseable: {e.msg}"]
    injects, dynamic, registered, strings = [], [], [], set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            strings.add(node.value)
        if not isinstance(node, ast.Call):
            continue
        if _is_inject(node):
            arg = node.args[0] if node.args else None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                injects.append((arg.value, node.lineno))
            else:
                dynamic.append(node.lineno)
        elif _is_register(node):
            arg = node.args[0] if node.args else None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                registered.append((arg.value, node.lineno))
            # failpoint.py's bulk loop registers from a tuple literal —
            # those names land in `strings` and the catalog is loaded
            # dynamically below, so nothing is lost here
    return injects, dynamic, registered, strings, []


def _catalog(root: str, register_files):
    """The authoritative registered-site set: import failpoint plus
    every module that calls failpoint.register() at import time."""
    sys.path.insert(0, root)
    try:
        from tidb_tpu.util import failpoint
        for path in register_files:
            rel = os.path.relpath(path, root)
            if not rel.startswith("tidb_tpu") or rel.endswith("__main__.py"):
                continue
            mod = rel[:-3].replace(os.sep, ".")
            try:
                __import__(mod)
            except Exception as e:  # noqa: BLE001 — a module that can't
                # import can't register either; surface it
                print(f"check_failpoints: warning: import {mod}: {e}",
                      file=sys.stderr)
        return failpoint.catalog()
    finally:
        sys.path.remove(root)


def run(root: str = None):
    if root is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..")
    root = os.path.abspath(root)
    targets = []
    for sub in ("tidb_tpu", "tools"):
        for dirpath, _dirs, files in os.walk(os.path.join(root, sub)):
            targets.extend(os.path.join(dirpath, f) for f in files
                           if f.endswith(".py"))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        targets.append(bench)

    problems = []
    injects, dynamic, register_files = [], [], []
    all_strings = set()
    per_file_strings = {}
    for path in sorted(targets):
        inj, dyn, reg, strings, errs = scan_file(path)
        problems.extend(errs)
        injects.extend((n, path, ln) for n, ln in inj)
        dynamic.extend((path, ln) for ln in dyn)
        if reg or path.endswith(os.path.join("util", "failpoint.py")):
            register_files.append(path)
        all_strings |= strings
        per_file_strings[path] = strings

    catalog = _catalog(root, register_files)

    # direction 0: the sweep's `--list-sites` enumeration must agree
    # with the catalog this lint derives from the tree — that printed
    # "N sites" number is what the docs/README advertise, and a
    # module-scope registration the sweep forgot to import (or a stale
    # import that registers a site nothing sweeps) would silently skew
    # the coverage gate
    sys.path.insert(0, root)
    try:
        from tidb_tpu.tools import chaos_sweep
        listed = set(chaos_sweep.list_sites())
        if listed != set(catalog):
            missing = sorted(set(catalog) - listed)
            extra = sorted(listed - set(catalog))
            problems.append(
                f"catalog: chaos_sweep --list-sites prints {len(listed)} "
                f"sites but the tree registers {len(catalog)}"
                + (f"; not listed: {missing}" if missing else "")
                + (f"; listed but unregistered: {extra}" if extra else ""))
    except Exception as e:  # noqa: BLE001 — an unimportable sweep can't
        # enumerate anything; that IS the drift
        problems.append(
            f"catalog: cannot import tidb_tpu.tools.chaos_sweep to "
            f"cross-check --list-sites: {type(e).__name__}: {e}")
    finally:
        sys.path.remove(root)

    # direction 0b: the README's failpoint catalog table must list
    # exactly the registered sites — a new site that skips the table is
    # undocumented, a removed site that lingers advertises a fault
    # boundary that no longer exists
    readme = os.path.join(root, "README.md")
    if os.path.exists(readme):
        with open(readme) as f:
            lines = f.read().splitlines()
        rows, in_table = set(), False
        for line in lines:
            s = line.strip()
            if s.startswith("| Site |"):
                in_table = True
                continue
            if in_table:
                if not s.startswith("|"):
                    break
                cell = s.split("|")[1].strip()
                if cell.startswith("`") and cell.rstrip("† ").endswith("`"):
                    rows.add(cell.strip("`† "))
        if not in_table:
            problems.append(
                "README.md: failpoint catalog table (header '| Site |') "
                "not found — document the catalog or drop this gate")
        else:
            undocumented = sorted(set(catalog) - rows)
            stale = sorted(rows - set(catalog))
            if undocumented:
                problems.append(
                    f"README.md: failpoint table is missing registered "
                    f"site(s): {undocumented}")
            if stale:
                problems.append(
                    f"README.md: failpoint table lists unregistered "
                    f"site(s): {stale}")

    # direction 1: every literal inject site is registered
    for name, path, ln in injects:
        if name not in catalog:
            problems.append(
                f"{path}:{ln}: inject site {name!r} is not in the "
                f"failpoint catalog — the chaos sweep cannot gate it "
                f"(register it in util/failpoint.py or at module scope)")

    # direction 2: every registered site is referenced somewhere in code
    referenced = {n for n, _p, _l in injects}
    for name in catalog:
        if name in referenced:
            continue
        # dynamically-dispatched sites (inject(site) helpers) still
        # carry the name as a string literal at their call sites
        if any(name in per_file_strings[p] for p, _l in dynamic):
            continue
        problems.append(
            f"catalog: registered site {name!r} has no inject() call "
            f"site in the tree — dead catalog entry (remove it, or the "
            f"sweep's coverage gate chases a site that can never fire)")

    # dynamic injects in a file with no catalog names at all: the name
    # cannot be cross-checked — require at least one registered site
    # to appear as a literal in the same file
    for path, ln in dynamic:
        if not (per_file_strings[path] & set(catalog)):
            problems.append(
                f"{path}:{ln}: inject() with a dynamic site name and no "
                f"registered site literal in the file — the name can "
                f"drift from the catalog silently")
    return problems


def main(argv=None) -> int:
    problems = run(argv[0] if argv else None)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"check_failpoints: {len(problems)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_failpoints: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
