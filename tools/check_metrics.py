#!/usr/bin/env python
"""Metric-name/label lint: walk every `REGISTRY.inc(...)` /
`REGISTRY.observe(...)` call site in the tree and enforce the naming
contract the dashboards and tools/check_metrics assertions depend on:

  * metric names are literal snake_case strings starting with
    `tidb_tpu_` and ending in a unit suffix — `_total` (counters),
    `_seconds` / `_bytes` (histograms/quantities);
  * label KEYS come from a fixed vocabulary, so a new call site cannot
    silently fork cardinality (`stmt` vs `statement` vs `kind`).

Run directly (`python tools/check_metrics.py`) or let the chaos sweep
entry point run it — metric drift fails the sweep fast, before any
scenario executes.  Exit 0 = clean, 1 = violations (printed one per
line as path:lineno: message)."""

import ast
import os
import sys

UNIT_SUFFIXES = ("_total", "_seconds", "_bytes")
LABEL_VOCAB = {"stmt", "engine", "table", "site", "device", "phase",
               "reason", "class", "le"}
PREFIX = "tidb_tpu_"


def _is_registry_call(node: ast.Call):
    """→ 'inc' | 'observe' | 'set_gauge' when the call is
    REGISTRY.inc/observe/set_gauge, else None."""
    f = node.func
    if not isinstance(f, ast.Attribute) \
            or f.attr not in ("inc", "observe", "set_gauge"):
        return None
    target = f.value
    if isinstance(target, ast.Name) and target.id == "REGISTRY":
        return f.attr
    return None


def _label_keys(node: ast.Call, arg_index: int):
    """Label-dict keys of the call, or None when not statically known."""
    args = list(node.args)
    dict_arg = args[arg_index] if len(args) > arg_index else None
    for kw in node.keywords:
        if kw.arg == "labels":
            dict_arg = kw.value
    if dict_arg is None or (isinstance(dict_arg, ast.Constant)
                            and dict_arg.value is None):
        return []
    if not isinstance(dict_arg, ast.Dict):
        return None
    keys = []
    for k in dict_arg.keys:
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        keys.append(k.value)
    return keys


def check_file(path: str):
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: unparseable: {e.msg}"]
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _is_registry_call(node)
        if kind is None:
            continue
        where = f"{path}:{node.lineno}"
        if not node.args:
            problems.append(f"{where}: {kind}() without a metric name")
            continue
        name_arg = node.args[0]
        if not (isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)):
            problems.append(
                f"{where}: metric name must be a string literal "
                f"(dynamic names fork cardinality invisibly)")
            continue
        name = name_arg.value
        if not name.startswith(PREFIX):
            problems.append(
                f"{where}: metric {name!r} must start with '{PREFIX}'")
        if name != name.lower() or not all(
                c.isalnum() or c == "_" for c in name):
            problems.append(f"{where}: metric {name!r} is not snake_case")
        if kind != "set_gauge" and not name.endswith(UNIT_SUFFIXES):
            problems.append(
                f"{where}: metric {name!r} lacks a unit suffix "
                f"({'/'.join(UNIT_SUFFIXES)})")
        if kind == "inc" and not name.endswith("_total"):
            problems.append(
                f"{where}: counter {name!r} must end in '_total'")
        if kind == "observe" and name.endswith("_total"):
            problems.append(
                f"{where}: histogram {name!r} must not end in '_total'")
        if kind == "set_gauge" and name.endswith("_total"):
            problems.append(
                f"{where}: gauge {name!r} must not end in '_total' "
                f"(gauges are set-points, not counters)")
        keys = _label_keys(node, 1 if kind == "inc" else 2)
        if keys is None:
            problems.append(
                f"{where}: labels for {name!r} must be an inline dict "
                f"with string-literal keys")
        else:
            for k in keys:
                if k not in LABEL_VOCAB:
                    problems.append(
                        f"{where}: label key {k!r} on {name!r} not in "
                        f"the fixed vocabulary {sorted(LABEL_VOCAB)}")
    return problems


def run(root: str = None):
    """Lint every .py under the package + bench/tools. → problem list."""
    if root is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..")
    root = os.path.abspath(root)
    targets = []
    for sub in ("tidb_tpu", "tools"):
        for dirpath, _dirs, files in os.walk(os.path.join(root, sub)):
            targets.extend(os.path.join(dirpath, f) for f in files
                           if f.endswith(".py"))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        targets.append(bench)
    problems = []
    for path in sorted(targets):
        problems.extend(check_file(path))
    return problems


def main(argv=None) -> int:
    problems = run(argv[0] if argv else None)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"check_metrics: {len(problems)} violation(s)",
              file=sys.stderr)
        return 1
    print("check_metrics: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
