#!/usr/bin/env python
"""Device-coverage ratchet: the committed COVERAGE.json pins which of
the 22 TPC-H-shaped coverage queries (tidb_tpu/tools/coverage.py) run
their analytic core as fused device fragments.  A fresh small-SF sweep
must keep every pinned-fused query fused — a regression (query that was
fused now reports a fallback) fails, as does a fallback whose reason
code drifts off the committed one or out of the fragment taxonomy.

Newly-fused queries (fallback → fused) are NOT failures; they print as
ratchet advances so the baseline can be re-pinned.

Run directly (`python tools/check_coverage.py`) or via the chaos-sweep
preflight beside check_metrics/check_failpoints.  Exit 0 = clean,
1 = regression.  `python tools/check_coverage.py --update` rewrites
COVERAGE.json from the fresh sweep."""

import json
import os
import sys

BASELINE = "COVERAGE.json"
SWEEP_ROWS = 6000        # small-SF: seconds, not minutes


def _sweep(root: str):
    sys.path.insert(0, root)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tidb_tpu.tools import coverage as C
    _eng, s = C.fresh_session(SWEEP_ROWS)
    rows = C.run_coverage(s, time_cpu=False)
    return {r["query"]: {"fused": r["fused"], "fallback": r["fallback"]}
            for r in rows}


def run(root: str = None):
    """→ problem list (empty = ratchet holds)."""
    if root is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..")
    root = os.path.abspath(root)
    base_path = os.path.join(root, BASELINE)
    if not os.path.exists(base_path):
        return [f"{BASELINE} missing — generate with "
                f"`python tools/check_coverage.py --update`"]
    with open(base_path) as f:
        baseline = json.load(f)["queries"]
    fresh = _sweep(root)
    from tidb_tpu.executor.fragment import FALLBACK_REASONS
    problems = []
    for q in sorted(baseline, key=lambda n: int(n[1:])):
        pin = baseline[q]
        now = fresh.get(q)
        if now is None:
            problems.append(f"coverage: {q} pinned in {BASELINE} but "
                            f"missing from the sweep")
            continue
        if pin["fused"] and not now["fused"]:
            problems.append(
                f"coverage: {q} REGRESSED fused -> fallback"
                f"({now['fallback']})")
        elif not pin["fused"] and not now["fused"]:
            if now["fallback"] not in FALLBACK_REASONS:
                problems.append(
                    f"coverage: {q} fallback reason {now['fallback']!r} "
                    f"not in the fragment taxonomy {FALLBACK_REASONS}")
            elif now["fallback"] != pin["fallback"]:
                problems.append(
                    f"coverage: {q} fallback reason drifted "
                    f"{pin['fallback']!r} -> {now['fallback']!r} "
                    f"(re-pin if intentional)")
        elif not pin["fused"] and now["fused"]:
            print(f"coverage: {q} newly fused — ratchet can advance "
                  f"(re-pin {BASELINE})")
    for q in sorted(fresh):
        if q not in baseline:
            problems.append(f"coverage: {q} in the sweep but not pinned "
                            f"in {BASELINE} — re-pin")
    return problems


def update(root: str = None) -> str:
    if root is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..")
    root = os.path.abspath(root)
    fresh = _sweep(root)
    path = os.path.join(root, BASELINE)
    fused = sum(1 for v in fresh.values() if v["fused"])
    with open(path, "w") as f:
        json.dump({"fused": fused, "total": len(fresh), "queries": fresh},
                  f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if argv and argv[0] == "--update":
        path = update(argv[1] if len(argv) > 1 else None)
        print(f"check_coverage: wrote {path}")
        return 0
    problems = run(argv[0] if argv else None)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"check_coverage: {len(problems)} regression(s)",
              file=sys.stderr)
        return 1
    print("check_coverage: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
