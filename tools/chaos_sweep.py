#!/usr/bin/env python
"""Repo-root wrapper for the chaos/failpoint sweep.

    python tools/chaos_sweep.py [-v] [--mesh N [--mesh-only]]

--mesh N forces an N-device host CPU mesh (XLA_FLAGS must be set BEFORE
jax first loads, which is why this wrapper — not the sweep module —
owns it) so the distributed scenarios run: skewed-exchange overflow
through the escalation ladder, and shard-step fault recovery.

See tidb_tpu/tools/chaos_sweep.py for the scenario list and contract."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

if "--mesh" in sys.argv:
    try:
        _n = int(sys.argv[sys.argv.index("--mesh") + 1])
    except (IndexError, ValueError):
        _n = 0
    if _n > 1 and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={_n}").strip()
        # multi-device needs deterministic 64-bit keys shard-side too
        os.environ.setdefault("JAX_ENABLE_X64", "1")

from tidb_tpu.tools.chaos_sweep import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
