#!/usr/bin/env python
"""Repo-root wrapper for the chaos/failpoint sweep.

    python tools/chaos_sweep.py [-v]

See tidb_tpu/tools/chaos_sweep.py for the scenario list and contract."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from tidb_tpu.tools.chaos_sweep import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
