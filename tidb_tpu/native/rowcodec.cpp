// MySQL text-protocol row encoder — the native hot loop of result
// delivery (ref: server/util.go:390 dumpTextRow / conn.go:2131
// writeChunks, which the reference keeps on its fastest path because
// every SELECT's output funnels through it).
//
// One call encodes a whole columnar batch into framed MySQL packets
// (4-byte header + seq per row, length-encoded text values), so Python
// touches each ROW zero times instead of building per-value strings.
// Exposed via ctypes (no pybind11 in the image); numpy arrays pass as
// raw pointers.
//
// Column physical encodings match tidb_tpu.types:
//   kind 0: int64                      kind 3: DATE  (int32 days)
//   kind 1: float64 (shortest repr)    kind 4: DATETIME (int64 usec)
//   kind 2: DECIMAL (int64 scaled)     kind 5: string (utf8 buf+offsets)

#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

namespace {

struct Col {
    int32_t kind;
    int32_t scale;          // DECIMAL scale
    const void *values;     // typed array
    const uint8_t *valid;   // nullable; 1 = not NULL
    const char *strbuf;     // kind 5: utf8 payload
    const int64_t *stroff;  // kind 5: n+1 offsets
};

struct Out {
    std::vector<uint8_t> buf;

    void put(const void *p, size_t n) {
        const uint8_t *b = static_cast<const uint8_t *>(p);
        buf.insert(buf.end(), b, b + n);
    }
    void byte(uint8_t b) { buf.push_back(b); }

    void lenenc_int(uint64_t v) {
        if (v < 251) {
            byte(static_cast<uint8_t>(v));
        } else if (v < (1ull << 16)) {
            byte(0xfc); byte(v & 0xff); byte((v >> 8) & 0xff);
        } else if (v < (1ull << 24)) {
            byte(0xfd); byte(v & 0xff); byte((v >> 8) & 0xff);
            byte((v >> 16) & 0xff);
        } else {
            byte(0xfe);
            for (int i = 0; i < 8; i++) byte((v >> (8 * i)) & 0xff);
        }
    }
    void lenenc_str(const char *s, size_t n) {
        lenenc_int(n);
        put(s, n);
    }
};

void civil_from_days(int64_t z, int &y, int &m, int &d) {
    z += 719468;
    const int64_t era = (z >= 0 ? z : z - 146096) / 146097;
    const int64_t doe = z - era * 146097;
    const int64_t yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096)
                        / 365;
    const int64_t doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
    const int64_t mp = (5 * doy + 2) / 153;
    d = static_cast<int>(doy - (153 * mp + 2) / 5 + 1);
    m = static_cast<int>(mp < 10 ? mp + 3 : mp - 9);
    y = static_cast<int>(yoe + era * 400 + (mp >= 10 ? 1 : 0));
}

// Python repr(float): shortest round-trip digits, FIXED notation when the
// decimal exponent is in [-4, 16), else scientific with a sign and a
// >=2-digit exponent. std::to_chars's shortest form picks notation by
// string length instead, so we render scientific and re-format.
size_t fmt_double_pyrepr(double v, char *tmp, size_t cap) {
    if (v != v) { memcpy(tmp, "nan", 3); return 3; }
    if (v == __builtin_inf()) { memcpy(tmp, "inf", 3); return 3; }
    if (v == -__builtin_inf()) { memcpy(tmp, "-inf", 4); return 4; }
    char sci[48];
    auto r = std::to_chars(sci, sci + sizeof sci, v,
                           std::chars_format::scientific);
    size_t sn = r.ptr - sci;
    // parse: [-]D[.DDDD]e±XX
    char *p = tmp;
    size_t i = 0;
    if (sci[0] == '-') { *p++ = '-'; i = 1; }
    char digits[40];
    int nd = 0;
    digits[nd++] = sci[i++];
    if (i < sn && sci[i] == '.') {
        i++;
        while (i < sn && sci[i] != 'e') digits[nd++] = sci[i++];
    }
    // exponent
    int exp = 0, esign = 1;
    i++;                                   // past 'e'
    if (sci[i] == '-') { esign = -1; i++; }
    else if (sci[i] == '+') { i++; }
    while (i < sn) exp = exp * 10 + (sci[i++] - '0');
    exp *= esign;
    if (exp >= -4 && exp < 16) {           // fixed notation
        if (exp >= 0) {
            int k = 0;
            for (; k <= exp; k++) *p++ = k < nd ? digits[k] : '0';
            *p++ = '.';
            if (k < nd) { for (; k < nd; k++) *p++ = digits[k]; }
            else *p++ = '0';
        } else {
            *p++ = '0'; *p++ = '.';
            for (int z = 0; z < -exp - 1; z++) *p++ = '0';
            for (int k = 0; k < nd; k++) *p++ = digits[k];
        }
        return p - tmp;
    }
    // scientific: d[.ddd]e±XX (exponent at least 2 digits)
    *p++ = digits[0];
    if (nd > 1) {
        *p++ = '.';
        for (int k = 1; k < nd; k++) *p++ = digits[k];
    }
    *p++ = 'e';
    *p++ = exp < 0 ? '-' : '+';
    int ae = exp < 0 ? -exp : exp;
    char eb[8];
    int en = 0;
    while (ae) { eb[en++] = '0' + ae % 10; ae /= 10; }
    while (en < 2) eb[en++] = '0';
    while (en) *p++ = eb[--en];
    return p - tmp;
}

size_t fmt_value(const Col &c, int64_t row, char *tmp, size_t cap) {
    switch (c.kind) {
    case 0: {  // int64
        int64_t v = static_cast<const int64_t *>(c.values)[row];
        auto r = std::to_chars(tmp, tmp + cap, v);
        return r.ptr - tmp;
    }
    case 1: {  // float64 — byte-identical to python repr()
        double v = static_cast<const double *>(c.values)[row];
        return fmt_double_pyrepr(v, tmp, cap);
    }
    case 2: {  // DECIMAL: scaled int64 → fixed point
        int64_t v = static_cast<const int64_t *>(c.values)[row];
        int s = c.scale;
        char *p = tmp;
        uint64_t a = v < 0 ? static_cast<uint64_t>(-(v + 1)) + 1
                           : static_cast<uint64_t>(v);
        if (v < 0) *p++ = '-';
        if (s == 0) {
            auto r = std::to_chars(p, tmp + cap, a);
            return r.ptr - tmp;
        }
        uint64_t pow = 1;
        for (int i = 0; i < s; i++) pow *= 10;
        uint64_t ip = a / pow, fp = a % pow;
        auto r = std::to_chars(p, tmp + cap, ip);
        p = const_cast<char *>(r.ptr);
        *p++ = '.';
        char fbuf[24];
        int fn = snprintf(fbuf, sizeof fbuf, "%0*llu", s,
                          static_cast<unsigned long long>(fp));
        memcpy(p, fbuf, fn);
        return (p - tmp) + fn;
    }
    case 3: {  // DATE: days since epoch
        int32_t days = static_cast<const int32_t *>(c.values)[row];
        int y, m, d;
        civil_from_days(days, y, m, d);
        return snprintf(tmp, cap, "%04d-%02d-%02d", y, m, d);
    }
    case 4: {  // DATETIME: microseconds since epoch
        int64_t us = static_cast<const int64_t *>(c.values)[row];
        int64_t day = us >= 0 ? us / 86400000000LL
                              : (us - 86399999999LL) / 86400000000LL;
        int64_t tod = us - day * 86400000000LL;
        int y, m, d;
        civil_from_days(day, y, m, d);
        int hh = static_cast<int>(tod / 3600000000LL);
        int mm = static_cast<int>((tod / 60000000LL) % 60);
        int ss = static_cast<int>((tod / 1000000LL) % 60);
        int frac = static_cast<int>(tod % 1000000LL);
        if (frac)
            return snprintf(tmp, cap,
                            "%04d-%02d-%02d %02d:%02d:%02d.%06d",
                            y, m, d, hh, mm, ss, frac);
        return snprintf(tmp, cap, "%04d-%02d-%02d %02d:%02d:%02d",
                        y, m, d, hh, mm, ss);
    }
    default:
        return 0;
    }
}

}  // namespace

extern "C" {

// Encode `n_rows` rows as framed MySQL text-protocol packets.
// Returns bytes written into `out` (caller sizes it generously and
// retries bigger on -1), and the next sequence id via *seq_io.
long long encode_text_rows(const Col *cols, int32_t n_cols,
                           int64_t n_rows, uint8_t *seq_io,
                           uint8_t *out, int64_t out_cap) {
    Out o;
    o.buf.reserve(static_cast<size_t>(n_rows) * n_cols * 12);
    uint8_t seq = *seq_io;
    char tmp[64];
    Out ro;                        // reused row buffer (no per-row alloc)
    for (int64_t r = 0; r < n_rows; r++) {
        ro.buf.clear();
        for (int32_t c = 0; c < n_cols; c++) {
            const Col &col = cols[c];
            if (col.valid && !col.valid[r]) {
                ro.byte(0xfb);            // NULL
                continue;
            }
            if (col.kind == 5) {
                int64_t a = col.stroff[r], b = col.stroff[r + 1];
                ro.lenenc_str(col.strbuf + a,
                              static_cast<size_t>(b - a));
            } else {
                size_t n = fmt_value(col, r, tmp, sizeof tmp);
                ro.lenenc_str(tmp, n);
            }
        }
        size_t plen = ro.buf.size();
        if (plen >= 0xFFFFFF) return -2;   // needs continuation packets:
                                           // python path handles those
        o.byte(plen & 0xff);
        o.byte((plen >> 8) & 0xff);
        o.byte((plen >> 16) & 0xff);
        o.byte(seq);
        seq = (seq + 1) & 0xff;
        o.put(ro.buf.data(), plen);
    }
    if (static_cast<int64_t>(o.buf.size()) > out_cap) return -1;
    memcpy(out, o.buf.data(), o.buf.size());
    *seq_io = seq;
    return static_cast<long long>(o.buf.size());
}

}  // extern "C"
