"""Native (C++) runtime components, loaded via ctypes.

The reference keeps result delivery native-fast (server/util.go
dumpTextRow is pure Go on the hot path); our analog compiles
rowcodec.cpp once per checkout with the baked-in g++ and falls back to
the pure-Python encoder when no toolchain is available. No pybind11 in
the image, so the ABI is a C struct array + raw numpy pointers.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "rowcodec.cpp")
_LIB = os.path.join(_DIR, "_rowcodec.so")

_lock = threading.Lock()
_lib = None
_tried = False


class _Col(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_int32),
        ("scale", ctypes.c_int32),
        ("values", ctypes.c_void_p),
        ("valid", ctypes.c_void_p),
        ("strbuf", ctypes.c_char_p),
        ("stroff", ctypes.c_void_p),
    ]


def _build() -> Optional[str]:
    try:
        if os.path.exists(_LIB) and \
                os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
            return _LIB
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC,
             "-o", _LIB + ".tmp"],
            check=True, capture_output=True, timeout=120)
        os.replace(_LIB + ".tmp", _LIB)
        return _LIB
    except Exception:  # noqa: BLE001 — no toolchain → python fallback
        return None


def get_lib():
    """The compiled library, or None (callers fall back to Python)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
            lib.encode_text_rows.restype = ctypes.c_longlong
            lib.encode_text_rows.argtypes = [
                ctypes.POINTER(_Col), ctypes.c_int32, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64]
            _lib = lib
        except OSError:
            _lib = None
        return _lib


# column kind tags (must match rowcodec.cpp)
K_INT, K_FLOAT, K_DECIMAL, K_DATE, K_DATETIME, K_STR = range(6)


def encode_text_rows(chunk, ftypes, seq: int) -> Optional[Tuple[bytes, int]]:
    """Whole-chunk MySQL text-row packets → (bytes, next_seq), or None
    when a column shape isn't supported (caller uses the Python path)."""
    from tidb_tpu.types import TypeKind
    lib = get_lib()
    if lib is None or chunk.num_rows == 0:
        return None
    n = chunk.num_rows
    cols = (_Col * chunk.num_cols)()
    keepalive: List[np.ndarray] = []
    str_bytes = 0
    for i, (col, ft) in enumerate(zip(chunk.columns, ftypes)):
        c = cols[i]
        c.scale = ft.scale
        valid = col.validity
        if valid is not None:
            v8 = np.ascontiguousarray(valid, dtype=np.uint8)
            keepalive.append(v8)
            c.valid = v8.ctypes.data_as(ctypes.c_void_p)
        else:
            c.valid = None
        k = ft.kind
        vals = col.values
        if k.is_string:
            encoded = [str(x).encode("utf-8") for x in vals]
            offs = np.zeros(n + 1, dtype=np.int64)
            np.cumsum([len(b) for b in encoded], out=offs[1:])
            buf = b"".join(encoded)
            str_bytes += len(buf)
            keepalive.append(offs)
            c.kind = K_STR
            c.strbuf = buf
            keepalive.append(buf)  # type: ignore[arg-type]
            c.stroff = offs.ctypes.data_as(ctypes.c_void_p)
            continue
        if k is TypeKind.DECIMAL:
            c.kind = K_DECIMAL
            arr = np.ascontiguousarray(vals, dtype=np.int64)
        elif k is TypeKind.DATE:
            c.kind = K_DATE
            arr = np.ascontiguousarray(vals, dtype=np.int32)
        elif k in (TypeKind.DATETIME, TypeKind.TIMESTAMP):
            c.kind = K_DATETIME
            arr = np.ascontiguousarray(vals, dtype=np.int64)
        elif k.is_float:
            c.kind = K_FLOAT
            arr = np.ascontiguousarray(vals, dtype=np.float64)
        elif k.is_integer:
            c.kind = K_INT
            arr = np.ascontiguousarray(vals, dtype=np.int64)
        else:
            return None           # TIME etc: python path
        keepalive.append(arr)
        c.values = arr.ctypes.data_as(ctypes.c_void_p)
    # capacity: UTF-8 BYTES (already summed) + framing + numeric worst case
    cap = 64 + str_bytes
    for ft in ftypes:
        cap += (9 if ft.kind.is_string else 40) * n
    out = (ctypes.c_uint8 * cap)()
    seq_io = ctypes.c_uint8(seq)
    written = lib.encode_text_rows(cols, chunk.num_cols, n,
                                   ctypes.byref(seq_io), out, cap)
    if written < 0:
        return None
    return ctypes.string_at(out, written), seq_io.value
