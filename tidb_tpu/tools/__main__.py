"""CLI for the ecosystem tools (ref: dumpling/main.go, br CLI).

    python -m tidb_tpu.tools dump       --host H --port P -o DIR [tables…]
    python -m tidb_tpu.tools export-csv --host H --port P -t TABLE -o FILE
    python -m tidb_tpu.tools serve-demo            # throwaway server

Backup/restore are engine-side (SQL `BACKUP TO '...'` / `RESTORE FROM
'...'` or the tidb_tpu.tools library API): the backing store lives inside
the server process, exactly like BR reaches the cluster through it."""

from __future__ import annotations

import argparse
import sys

from tidb_tpu import tools
from tidb_tpu.client import Client


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tidb_tpu.tools")
    sub = ap.add_subparsers(dest="cmd", required=True)

    d = sub.add_parser("dump", help="logical SQL dump (dumpling)")
    d.add_argument("tables", nargs="*")
    d.add_argument("-o", "--out", required=True)

    e = sub.add_parser("export-csv")
    e.add_argument("-t", "--table", required=True)
    e.add_argument("-o", "--out", required=True)

    i = sub.add_parser("import-csv")
    i.add_argument("-t", "--table", required=True)
    i.add_argument("-i", "--infile", required=True)

    for p in (d, e, i):
        p.add_argument("--host", default="127.0.0.1")
        p.add_argument("--port", type=int, default=4000)
        p.add_argument("-u", "--user", default="root")
        p.add_argument("-p", "--password", default="")

    args = ap.parse_args(argv)
    with Client(args.host, args.port, args.user, args.password) as c:
        if args.cmd == "dump":
            done = tools.dump_sql(c, args.out, args.tables or None)
            print(f"dumped {len(done)} table(s) to {args.out}")
        elif args.cmd == "export-csv":
            n = tools.export_csv(c, args.table, args.out)
            print(f"exported {n} row(s)")
        elif args.cmd == "import-csv":
            class _SessionShim:
                def execute(self, sql):
                    c.execute(sql)
            n = tools.import_csv(_SessionShim(), args.table, args.infile)
            print(f"imported {n} row(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
