"""22-query TPC-H device-coverage sweep (the whole-query compilation
ratchet).

Flare's argument is that query compilation pays off only when it covers
whole workloads, not showcase queries — so the tracked metric here is the
fraction of the full TPC-H suite whose ANALYTIC CORE runs as fused device
fragments with zero CPU fallback.  Every query is the TPC-H shape adapted
to this engine's SQL surface (same joins, aggregates, subquery and
ordering structure; synthetic column distributions) over a generated
schema of all eight tables.

Per query the sweep reports:

  fused              every extracted fragment ran on device (and at
                     least one fragment was extracted)
  n_fragments        device fragments extracted from the plan
  fallback           normalized reason code (fragment.FALLBACK_REASONS)
                     of the first fragment that fell back, else None
  programs_per_slab  warm-run device launches / data slabs — the
                     slabs+1 fused-pipeline model shows up as ~1.x
  speedup            CPU wall / device wall on this host (small SF:
                     indicative only, the ratchet keys on `fused`)

`tools/check_coverage.py` compares a fresh sweep against the committed
COVERAGE.json baseline and fails when a query that was fused regresses
to fallback; bench.py embeds the same table at benchmark scale.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

# ---------------------------------------------------------------------------
# Queries: TPC-H 1-22, adapted to the engine's SQL surface.
# ---------------------------------------------------------------------------

QUERIES: Dict[str, str] = {
    # pricing summary report: the headline fused agg+sort chain
    "q1": """SELECT l_returnflag, l_linestatus, SUM(l_quantity),
        SUM(l_extendedprice), SUM(l_extendedprice * (1 - l_discount)),
        AVG(l_quantity), COUNT(*) FROM lineitem
        WHERE l_shipdate <= '1998-09-02'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus""",
    # minimum-cost supplier: join chain + grouped MIN, TopN root
    "q2": """SELECT n_name, MIN(ps_supplycost), COUNT(*)
        FROM partsupp JOIN supplier ON ps_suppkey = s_suppkey
        JOIN nation ON s_nationkey = n_nationkey
        JOIN region ON n_regionkey = r_regionkey
        WHERE r_name = 'EUROPE'
        GROUP BY n_name ORDER BY 2 LIMIT 10""",
    # shipping priority: join + agg + TopN over revenue
    "q3": """SELECT l_orderkey, SUM(l_extendedprice * (1 - l_discount)),
        MIN(o_orderdate)
        FROM lineitem JOIN orders ON l_orderkey = o_orderkey
        JOIN customer ON o_custkey = c_custkey
        WHERE c_mktsegment = 'BUILDING' AND o_orderdate < '1995-03-15'
          AND l_shipdate > '1995-03-15'
        GROUP BY l_orderkey ORDER BY 2 DESC LIMIT 10""",
    # order priority checking: EXISTS semijoin + grouped count
    "q4": """SELECT o_orderpriority, COUNT(*) FROM orders
        WHERE o_orderdate >= '1993-07-01' AND o_orderdate < '1993-10-01'
          AND EXISTS (SELECT 1 FROM lineitem
                      WHERE l_orderkey = o_orderkey
                        AND l_commitdate < l_receiptdate)
        GROUP BY o_orderpriority ORDER BY o_orderpriority""",
    # local supplier volume: 5-way join + grouped revenue
    "q5": """SELECT n_name, SUM(l_extendedprice * (1 - l_discount))
        FROM lineitem JOIN orders ON l_orderkey = o_orderkey
        JOIN customer ON o_custkey = c_custkey
        JOIN nation ON c_nationkey = n_nationkey
        JOIN region ON n_regionkey = r_regionkey
        WHERE r_name = 'ASIA' AND o_orderdate >= '1994-01-01'
          AND o_orderdate < '1995-01-01'
        GROUP BY n_name ORDER BY 2 DESC""",
    # forecasting revenue change: the selective zone-map scan
    "q6": """SELECT COUNT(*), SUM(l_extendedprice * l_discount)
        FROM lineitem WHERE l_shipdate >= '1994-01-01'
          AND l_shipdate < '1995-01-01'
          AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24""",
    # volume shipping: join + YEAR() group keys
    "q7": """SELECT n_name, YEAR(l_shipdate), SUM(l_extendedprice)
        FROM lineitem JOIN supplier ON l_suppkey = s_suppkey
        JOIN nation ON s_nationkey = n_nationkey
        WHERE l_shipdate >= '1995-01-01' AND l_shipdate <= '1996-12-31'
        GROUP BY n_name, YEAR(l_shipdate)
        ORDER BY n_name, 2""",
    # national market share: CASE share aggregation over a join chain
    "q8": """SELECT YEAR(o_orderdate),
        SUM(CASE WHEN n_name = 'BRAZIL'
            THEN l_extendedprice * (1 - l_discount) ELSE 0 END),
        SUM(l_extendedprice * (1 - l_discount))
        FROM lineitem JOIN orders ON l_orderkey = o_orderkey
        JOIN supplier ON l_suppkey = s_suppkey
        JOIN nation ON s_nationkey = n_nationkey
        WHERE o_orderdate >= '1995-01-01' AND o_orderdate <= '1996-12-31'
        GROUP BY YEAR(o_orderdate) ORDER BY 1""",
    # product type profit: LIKE filter + multi-join grouped profit
    "q9": """SELECT n_name, YEAR(o_orderdate),
        SUM(l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity)
        FROM lineitem JOIN orders ON l_orderkey = o_orderkey
        JOIN part ON l_partkey = p_partkey
        JOIN partsupp ON l_partkey = ps_partkey AND l_suppkey = ps_suppkey
        JOIN supplier ON l_suppkey = s_suppkey
        JOIN nation ON s_nationkey = n_nationkey
        WHERE p_name LIKE '%green%'
        GROUP BY n_name, YEAR(o_orderdate) ORDER BY n_name, 2 DESC""",
    # returned item reporting: join + agg + TopN 20
    "q10": """SELECT c_custkey, c_name,
        SUM(l_extendedprice * (1 - l_discount)), MIN(c_acctbal)
        FROM lineitem JOIN orders ON l_orderkey = o_orderkey
        JOIN customer ON o_custkey = c_custkey
        WHERE o_orderdate >= '1993-10-01' AND o_orderdate < '1994-01-01'
          AND l_returnflag = 'R'
        GROUP BY c_custkey, c_name ORDER BY 3 DESC LIMIT 20""",
    # important stock identification: value threshold via uncorrelated
    # scalar subquery over the same aggregation
    "q11": """SELECT ps_partkey, SUM(ps_supplycost * ps_availqty)
        FROM partsupp JOIN supplier ON ps_suppkey = s_suppkey
        JOIN nation ON s_nationkey = n_nationkey
        WHERE n_name = 'GERMANY'
        GROUP BY ps_partkey
        HAVING SUM(ps_supplycost * ps_availqty) >
            (SELECT SUM(ps_supplycost * ps_availqty) * 0.0005
             FROM partsupp JOIN supplier ON ps_suppkey = s_suppkey
             JOIN nation ON s_nationkey = n_nationkey
             WHERE n_name = 'GERMANY')
        ORDER BY 2 DESC LIMIT 20""",
    # shipping modes and order priority: CASE tallies over a join
    "q12": """SELECT l_shipmode,
        SUM(CASE WHEN o_orderpriority = '1' OR o_orderpriority = '2'
            THEN 1 ELSE 0 END),
        SUM(CASE WHEN o_orderpriority <> '1' AND o_orderpriority <> '2'
            THEN 1 ELSE 0 END)
        FROM lineitem JOIN orders ON l_orderkey = o_orderkey
        WHERE l_shipmode IN ('MAIL', 'SHIP')
          AND l_commitdate < l_receiptdate
          AND l_receiptdate >= '1994-01-01' AND l_receiptdate < '1995-01-01'
        GROUP BY l_shipmode ORDER BY l_shipmode""",
    # customer distribution: two-level aggregation (count per customer,
    # then histogram of the counts) — the agg-over-agg shape
    "q13": """SELECT cnt, COUNT(*) FROM
        (SELECT o_custkey, COUNT(*) AS cnt FROM orders
         WHERE o_orderpriority <> '5' GROUP BY o_custkey) t
        GROUP BY cnt ORDER BY 2 DESC, cnt DESC LIMIT 20""",
    # promotion effect: CASE revenue share over a join
    "q14": """SELECT SUM(CASE WHEN p_type LIKE 'PROMO%'
            THEN l_extendedprice * (1 - l_discount) ELSE 0 END),
        SUM(l_extendedprice * (1 - l_discount))
        FROM lineitem JOIN part ON l_partkey = p_partkey
        WHERE l_shipdate >= '1995-09-01' AND l_shipdate < '1995-10-01'""",
    # top supplier: revenue per supplier ranked by a window function
    "q15": """SELECT s_suppkey, total,
        RANK() OVER (ORDER BY total DESC) AS rnk FROM
        (SELECT l_suppkey AS s_suppkey,
                SUM(l_extendedprice * (1 - l_discount)) AS total
         FROM lineitem
         WHERE l_shipdate >= '1996-01-01' AND l_shipdate < '1996-04-01'
         GROUP BY l_suppkey) rev
        ORDER BY rnk, s_suppkey LIMIT 10""",
    # parts/supplier relationship: grouped COUNT(DISTINCT) — the
    # cross-slab pair-dedup path
    "q16": """SELECT p_brand, p_size, COUNT(DISTINCT ps_suppkey)
        FROM partsupp JOIN part ON ps_partkey = p_partkey
        WHERE p_brand <> 'Brand#45' AND p_size < 20
        GROUP BY p_brand, p_size ORDER BY 3 DESC, p_brand LIMIT 20""",
    # small-quantity-order revenue: uncorrelated scalar AVG threshold
    "q17": """SELECT COUNT(*), SUM(l_extendedprice)
        FROM lineitem JOIN part ON l_partkey = p_partkey
        WHERE p_container = 'MED BOX' AND
          l_quantity < (SELECT AVG(l_quantity) * 0.5 FROM lineitem)""",
    # large volume customer: IN semijoin over a grouped HAVING subquery
    "q18": """SELECT c_custkey, o_orderkey, MIN(o_totalprice), SUM(l_quantity)
        FROM lineitem JOIN orders ON l_orderkey = o_orderkey
        JOIN customer ON o_custkey = c_custkey
        WHERE o_orderkey IN (SELECT l_orderkey FROM lineitem
                             GROUP BY l_orderkey HAVING SUM(l_quantity) > 150)
        GROUP BY c_custkey, o_orderkey ORDER BY 3 DESC, o_orderkey LIMIT 20""",
    # discounted revenue: the OR-of-ANDs disjunctive filter join
    "q19": """SELECT COUNT(*), SUM(l_extendedprice * (1 - l_discount))
        FROM lineitem JOIN part ON l_partkey = p_partkey
        WHERE (p_container = 'SM CASE' AND l_quantity <= 11)
           OR (p_container = 'MED BOX' AND l_quantity >= 10
               AND l_quantity <= 20)
           OR (p_container = 'LG BOX' AND l_quantity >= 20
               AND l_quantity <= 30)""",
    # potential part promotion: nested IN semijoins
    "q20": """SELECT s_suppkey, COUNT(*) FROM supplier
        JOIN nation ON s_nationkey = n_nationkey
        WHERE n_name = 'CANADA'
          AND s_suppkey IN (SELECT ps_suppkey FROM partsupp
                            WHERE ps_partkey IN
                                (SELECT p_partkey FROM part
                                 WHERE p_name LIKE 'forest%')
                              AND ps_availqty > 100)
        GROUP BY s_suppkey ORDER BY s_suppkey LIMIT 20""",
    # suppliers who kept orders waiting: semijoin + late-line filter
    "q21": """SELECT s_name, COUNT(*) FROM lineitem
        JOIN orders ON l_orderkey = o_orderkey
        JOIN supplier ON l_suppkey = s_suppkey
        WHERE o_orderstatus = 'F' AND l_receiptdate > l_commitdate
          AND l_orderkey IN (SELECT l_orderkey FROM lineitem
                             GROUP BY l_orderkey HAVING COUNT(*) > 1)
        GROUP BY s_name ORDER BY 2 DESC, s_name LIMIT 20""",
    # global sales opportunity: SUBSTRING group key + NOT EXISTS
    # anti-join against orders
    "q22": """SELECT SUBSTRING(c_phone, 1, 2), COUNT(*), SUM(c_acctbal)
        FROM customer
        WHERE SUBSTRING(c_phone, 1, 2) IN ('13', '31', '23', '29')
          AND c_acctbal > 0
          AND NOT EXISTS (SELECT 1 FROM orders WHERE o_custkey = c_custkey)
        GROUP BY SUBSTRING(c_phone, 1, 2) ORDER BY 1""",
}

# queries whose analytic core is NOT expected to fuse yet, with the
# taxonomy code the fragment layer reports — the ratchet allows these to
# stay fallback but fails if a FUSED query joins them
EXPECTED_FALLBACK: Dict[str, str] = {
    # IN over a grouped-HAVING subquery decorrelates to a semijoin whose
    # build side is an aggregation — interior aggs aren't tree-fusable
    "q18": "shape",
    # the SUBSTRING(c_phone, ...) group key / IN-list is a COMPUTED
    # string: no dictionary to prepare codes against, host executes
    "q22": "shape",
}


# ---------------------------------------------------------------------------
# Schema + data
# ---------------------------------------------------------------------------

def build_schema(s, n_lineitem: int = 6000, seed: int = 42) -> None:
    """Create and populate all eight TPC-H tables at a size proportional
    to `n_lineitem` (SF≈n/6M), via direct chunk appends like bench.py."""
    from tidb_tpu.chunk import Chunk, Column

    eng = s.engine if hasattr(s, "engine") else s._engine
    rng = np.random.default_rng(seed)
    n = n_lineitem
    n_ord = max(n // 4, 8)
    n_cust = max(n // 15, 8)
    n_part = max(n // 20, 8)
    n_supp = max(n // 100, 4)
    n_ps = max(n // 10, 16)

    s.execute(
        "CREATE TABLE lineitem (l_orderkey BIGINT, l_partkey BIGINT, "
        "l_suppkey BIGINT, l_quantity DECIMAL(15,2), "
        "l_extendedprice DECIMAL(15,2), l_discount DECIMAL(15,2), "
        "l_tax DECIMAL(15,2), l_returnflag CHAR(1), l_linestatus CHAR(1), "
        "l_shipdate DATE, l_commitdate DATE, l_receiptdate DATE, "
        "l_shipmode CHAR(10))")
    s.execute(
        "CREATE TABLE orders (o_orderkey BIGINT PRIMARY KEY, "
        "o_custkey BIGINT, o_orderstatus CHAR(1), "
        "o_totalprice DECIMAL(15,2), o_orderdate DATE, "
        "o_orderpriority CHAR(1))")
    s.execute(
        "CREATE TABLE customer (c_custkey BIGINT PRIMARY KEY, "
        "c_name CHAR(18), c_nationkey BIGINT, c_acctbal DECIMAL(15,2), "
        "c_mktsegment CHAR(10), c_phone CHAR(15))")
    s.execute(
        "CREATE TABLE part (p_partkey BIGINT PRIMARY KEY, p_name CHAR(32), "
        "p_brand CHAR(10), p_type CHAR(16), p_size BIGINT, "
        "p_container CHAR(10))")
    s.execute(
        "CREATE TABLE supplier (s_suppkey BIGINT PRIMARY KEY, "
        "s_name CHAR(18), s_nationkey BIGINT, s_acctbal DECIMAL(15,2))")
    s.execute(
        "CREATE TABLE partsupp (ps_partkey BIGINT, ps_suppkey BIGINT, "
        "ps_availqty BIGINT, ps_supplycost DECIMAL(15,2))")
    s.execute(
        "CREATE TABLE nation (n_nationkey BIGINT PRIMARY KEY, "
        "n_name CHAR(16), n_regionkey BIGINT)")
    s.execute(
        "CREATE TABLE region (r_regionkey BIGINT PRIMARY KEY, "
        "r_name CHAR(12))")

    def append(table: str, arrays) -> None:
        info = eng.catalog.info_schema.table(table)
        fts = [c.ftype for c in info.columns]
        chunk = Chunk([Column(ft, a, None) for ft, a in zip(fts, arrays)])
        txn = eng.store.begin()
        txn.append(info.id, chunk)
        txn.commit()

    def pick(options, count):
        arr = np.array(options, dtype=object)
        return arr[rng.integers(0, len(arr), count)]

    # dates as day numbers, 1992-01-01..1998-12-01 ≈ 8036..10560
    ship = rng.integers(8036, 10560, n).astype(np.int32)
    ship.sort()      # shipdate-clustered storage, as in TPC-H loads
    commit = ship + rng.integers(-10, 40, n).astype(np.int32)
    receipt = commit + rng.integers(-5, 30, n).astype(np.int32)
    append("lineitem", [
        rng.integers(0, n_ord, n).astype(np.int64),
        rng.integers(0, n_part, n).astype(np.int64),
        rng.integers(0, n_supp, n).astype(np.int64),
        rng.integers(100, 5001, n).astype(np.int64),
        rng.integers(90_000, 10_500_001, n).astype(np.int64),
        rng.integers(0, 11, n).astype(np.int64),
        rng.integers(0, 9, n).astype(np.int64),
        pick(["A", "N", "R"], n), pick(["F", "O"], n),
        ship, commit, receipt,
        pick(["MAIL", "SHIP", "AIR", "TRUCK", "RAIL"], n)])
    append("orders", [
        np.arange(n_ord, dtype=np.int64),
        rng.integers(0, n_cust, n_ord).astype(np.int64),
        pick(["F", "O", "P"], n_ord),
        rng.integers(1_000, 50_000_000, n_ord).astype(np.int64),
        rng.integers(8036, 10560, n_ord).astype(np.int32),
        pick(["1", "2", "3", "4", "5"], n_ord)])
    append("customer", [
        np.arange(n_cust, dtype=np.int64),
        np.array([f"Customer#{i:09d}" for i in range(n_cust)],
                 dtype=object),
        rng.integers(0, 25, n_cust).astype(np.int64),
        rng.integers(-99_999, 999_999, n_cust).astype(np.int64),
        pick(["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY",
              "HOUSEHOLD"], n_cust),
        np.array([f"{c}-{i % 900 + 100}-{i % 9000 + 1000}"
                  for i, c in enumerate(
                      rng.integers(10, 35, n_cust))], dtype=object)])
    adjectives = ["green", "blue", "red", "ivory", "forest", "navy",
                  "plum", "puff"]
    nouns = ["almond", "steel", "linen", "cream", "misty", "tomato"]
    append("part", [
        np.arange(n_part, dtype=np.int64),
        np.array([f"{adjectives[i % 8]} {nouns[i % 6]} part{i}"
                  for i in range(n_part)], dtype=object),
        np.array([f"Brand#{i % 5 + 1}{i % 5 + 1}" for i in range(n_part)],
                 dtype=object),
        pick(["PROMO BOX", "PROMO CASE", "STANDARD TIN", "SMALL PLATED",
              "MEDIUM BAG"], n_part),
        rng.integers(1, 50, n_part).astype(np.int64),
        pick(["SM CASE", "MED BOX", "LG BOX", "JUMBO JAR", "WRAP BAG"],
             n_part)])
    append("supplier", [
        np.arange(n_supp, dtype=np.int64),
        np.array([f"Supplier#{i:09d}" for i in range(n_supp)],
                 dtype=object),
        rng.integers(0, 25, n_supp).astype(np.int64),
        rng.integers(-99_999, 999_999, n_supp).astype(np.int64)])
    append("partsupp", [
        rng.integers(0, n_part, n_ps).astype(np.int64),
        rng.integers(0, n_supp, n_ps).astype(np.int64),
        rng.integers(1, 10_000, n_ps).astype(np.int64),
        rng.integers(100, 100_000, n_ps).astype(np.int64)])
    nations = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT",
               "ETHIOPIA", "FRANCE", "GERMANY", "INDIA", "INDONESIA",
               "IRAN", "IRAQ", "JAPAN", "JORDAN", "KENYA", "MOROCCO",
               "MOZAMBIQUE", "PERU", "CHINA", "ROMANIA", "SAUDI ARABIA",
               "VIETNAM", "RUSSIA", "UNITED KINGDOM", "UNITED STATES"]
    append("nation", [
        np.arange(25, dtype=np.int64),
        np.array(nations, dtype=object),
        (np.arange(25, dtype=np.int64) % 5)])
    append("region", [
        np.arange(5, dtype=np.int64),
        np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"],
                 dtype=object)])
    for t in ("lineitem", "orders", "customer", "part", "supplier",
              "partsupp", "nation", "region"):
        s.execute(f"ANALYZE TABLE {t}")


# ---------------------------------------------------------------------------
# Sweep
# ---------------------------------------------------------------------------

def _fragments(root) -> list:
    from tidb_tpu.executor.fragment import TpuFragmentExec
    out = []

    def walk(e):
        if isinstance(e, TpuFragmentExec):
            out.append(e)
        for c in getattr(e, "children", []):
            walk(c)

    walk(root)
    return out


def run_one(s, name: str, time_cpu: bool = True) -> dict:
    """Run one coverage query (device on, forced threshold) and report
    fused status, fallback code, warm launches-per-slab, and speedup."""
    from tidb_tpu.executor import build, run_to_completion
    from tidb_tpu.parser import parse

    sql = QUERIES[name]
    cpu_s = None
    if time_cpu:
        s.vars["tidb_tpu_engine"] = "off"
        t0 = time.perf_counter()
        s.query(sql)
        cpu_s = time.perf_counter() - t0
    s.vars["tidb_tpu_engine"] = "on"
    s.vars["tidb_tpu_row_threshold"] = 1
    try:
        plan = s._plan(parse(sql)[0])
        root = build(plan)
        run_to_completion(root, s._exec_ctx())     # cold: compile + upload
        frags = _fragments(root)
        fused = bool(frags) and all(f.used_device for f in frags)
        fallback = None
        for f in frags:
            if not f.used_device:
                fallback = getattr(f, "fallback_code", None) or "device-error"
                break
        if not frags:
            fallback = "shape"
        t0 = time.perf_counter()
        s.query(sql)                               # warm, for launch count
        dev_s = time.perf_counter() - t0
        ph = s.last_guard.phases if s.last_guard is not None else None
        launches = getattr(ph, "programs_launched", 0) if ph else 0
        # slab count: fused-pipeline launches when the pipeline ran,
        # else partial launches (everything but the one merge/finalize) —
        # the slabs+1 model reads as programs_per_slab → 1.0 at scale
        fused_l = getattr(ph, "fused_pipelines", 0) if ph else 0
        slabs = max(fused_l or launches - 1, 1)
        pps = round(launches / slabs, 2) if launches else None
    finally:
        s.vars["tidb_tpu_engine"] = "off"
        s.vars.pop("tidb_tpu_row_threshold", None)
    return {
        "query": name,
        "fused": fused,
        "n_fragments": len(frags),
        "fallback": fallback,
        "launches": launches,
        "programs_per_slab": pps,
        "device_s": round(dev_s, 4),
        "cpu_s": round(cpu_s, 4) if cpu_s is not None else None,
        "speedup": round(cpu_s / dev_s, 2)
        if cpu_s is not None and dev_s > 0 else None,
    }


def run_coverage(s, time_cpu: bool = True,
                 queries: Optional[List[str]] = None) -> List[dict]:
    rows = []
    for name in queries or sorted(QUERIES, key=lambda q: int(q[1:])):
        rows.append(run_one(s, name, time_cpu=time_cpu))
    return rows


def coverage_table(rows: List[dict]) -> str:
    """Render the per-query table bench.py embeds in its log output."""
    hdr = (f"{'query':<6}{'fused':<7}{'frags':<7}{'fallback':<15}"
           f"{'prog/slab':<11}{'speedup':<8}")
    lines = [hdr, "-" * len(hdr)]
    for r in rows:
        lines.append(
            f"{r['query']:<6}{str(r['fused']):<7}{r['n_fragments']:<7}"
            f"{str(r['fallback'] or '-'):<15}"
            f"{str(r['programs_per_slab'] or '-'):<11}"
            f"{str(r['speedup'] or '-'):<8}")
    fused = sum(1 for r in rows if r["fused"])
    lines.append(f"fused: {fused}/{len(rows)}")
    return "\n".join(lines)


def fresh_session(n_lineitem: int = 6000):
    from tidb_tpu.session import Engine
    eng = Engine()
    eng.global_vars["tidb_enable_auto_analyze"] = False
    s = eng.new_session()
    build_schema(s, n_lineitem)
    return eng, s
