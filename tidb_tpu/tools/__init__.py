"""Ecosystem tools: logical dump, binary backup/restore, CSV import/export.

The reference ships these as in-repo CLIs (SURVEY §2.5): **dumpling**
(logical SQL dump over a MySQL connection), **BR** (physical backup /
restore with resumable checkpoints, br/pkg/{backup,restore,task}), and
**lightning** (bulk file import with checkpoints,
br/pkg/lightning/checkpoints/). The TPU-first engine stores tables as
immutable columnar regions, so the physical format here is the Chunk wire
codec (tidb_tpu/chunk/codec.py — the same Arrow-shaped layout the device
marshalling uses) plus a JSON schema sidecar.

Checkpoint discipline (BR + lightning checkpoints; also the repo's
checkpoint/resume answer to ddl/reorg.go's resumable backfill): every
table lands atomically (tmp file + rename) and is then recorded in
`checkpoint.json`; a re-run of the same operation skips recorded tables,
so a crash mid-way resumes instead of restarting.
"""

from __future__ import annotations

import datetime
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from tidb_tpu.chunk import Chunk
from tidb_tpu.chunk.codec import decode_chunk, encode_chunk
from tidb_tpu.errors import TiDBTPUError

BACKUP_FORMAT_VERSION = 1


# ---------------------------------------------------------------------------
# Checkpoints (ref: br/pkg/lightning/checkpoints, ddl/reorg.go handles)
# ---------------------------------------------------------------------------


class Checkpoint:
    """Crash-resumable progress marker: a JSON set of finished units."""

    def __init__(self, path: str, op: str):
        self.path = path
        self.op = op
        self.done: List[str] = []
        if os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
            if data.get("op") != op:
                raise TiDBTPUError(
                    f"checkpoint at {path} belongs to a different "
                    f"operation ({data.get('op')!r}, not {op!r})")
            self.done = list(data.get("done", []))

    def is_done(self, unit: str) -> bool:
        return unit in self.done

    def mark(self, unit: str) -> None:
        self.done.append(unit)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"op": self.op, "done": self.done}, f)
        os.replace(tmp, self.path)

    def finish(self) -> None:
        if os.path.exists(self.path):
            os.remove(self.path)


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# DDL regeneration (shared by dump + backup metadata)
# ---------------------------------------------------------------------------


def create_table_sql(info) -> str:
    cols = []
    for c in info.columns:
        spec = f"`{c.name}` {c.ftype}"
        if getattr(c, "auto_increment", False):
            spec += " AUTO_INCREMENT"
        if not c.ftype.nullable and not c.primary_key:
            spec += " NOT NULL"
        cols.append(spec)
    if info.primary_key:
        cols.append("PRIMARY KEY (" +
                    ", ".join(f"`{c}`" for c in info.primary_key) + ")")
    ddl = f"CREATE TABLE `{info.name}` (\n  " + ",\n  ".join(cols) + "\n)"
    p = getattr(info, "partition", None)
    if p is not None:
        if p.kind == "hash":
            ddl += (f"\nPARTITION BY HASH (`{p.column}`) "
                    f"PARTITIONS {p.num}")
        else:
            ft = info.columns[p.col_offset].ftype
            defs = []
            for name, b in zip(p.names, p.bounds):
                if b is None:
                    lit = "MAXVALUE"
                else:
                    val = ft.decode_value(b)
                    lit = (str(val) if isinstance(val, (int, float))
                           else "'" + str(val) + "'")
                defs.append(f"PARTITION `{name}` VALUES LESS THAN ({lit})")
            ddl += (f"\nPARTITION BY RANGE (`{p.column}`) (\n  " +
                    ",\n  ".join(defs) + "\n)")
    extra = []
    for ix in info.indexes:
        u = "UNIQUE " if ix.unique else ""
        extra.append(f"CREATE {u}INDEX `{ix.name}` ON `{info.name}` (" +
                     ", ".join(f"`{c}`" for c in ix.columns) + ")")
    return ";\n".join([ddl] + extra) + ";"


def _sql_literal(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, (datetime.date, datetime.datetime)):
        return f"'{v}'"
    s = str(v).replace("\\", "\\\\").replace("'", "\\'")
    return f"'{s}'"


# ---------------------------------------------------------------------------
# dumpling — logical SQL dump over a connection or in-process session
# ---------------------------------------------------------------------------


def dump_sql(source, out_dir: str, tables: Optional[Sequence[str]] = None,
             rows_per_insert: int = 1000) -> List[str]:
    """Write `<table>-schema.sql` + `<table>.sql` per table (dumpling's
    file layout). `source` is anything with .query(sql) returning rows —
    a tidb_tpu.client.Client (over the wire) or a Session."""
    os.makedirs(out_dir, exist_ok=True)
    ckpt = Checkpoint(os.path.join(out_dir, "checkpoint.json"), "dump")
    names = _table_names(source, tables)
    written = []
    for t in names:
        if ckpt.is_done(t):
            continue
        ddl = _show_create(source, t)
        _atomic_write(os.path.join(out_dir, f"{t}-schema.sql"),
                      (ddl.rstrip(";\n ") + ";\n").encode())
        rows = _query_rows(source, f"SELECT * FROM `{t}`")
        lines = []
        for start in range(0, len(rows), rows_per_insert):
            batch = rows[start:start + rows_per_insert]
            vals = ",\n".join(
                "(" + ", ".join(_sql_literal(v) for v in r) + ")"
                for r in batch)
            lines.append(f"INSERT INTO `{t}` VALUES\n{vals};")
        _atomic_write(os.path.join(out_dir, f"{t}.sql"),
                      ("\n".join(lines) + "\n").encode())
        ckpt.mark(t)
        written.append(t)
    ckpt.finish()
    return written


def load_dump(session, dump_dir: str) -> List[str]:
    """Replay a dump directory into a session (schema files first)."""
    files = sorted(os.listdir(dump_dir))
    loaded = []
    for f in files:
        if f.endswith("-schema.sql"):
            session.execute(open(os.path.join(dump_dir, f)).read())
            loaded.append(f)
    for f in files:
        if f.endswith(".sql") and not f.endswith("-schema.sql"):
            sql = open(os.path.join(dump_dir, f)).read().strip()
            if sql:
                session.execute(sql)
            loaded.append(f)
    return loaded


# ---------------------------------------------------------------------------
# BR — physical backup/restore of the columnar store
# ---------------------------------------------------------------------------


def backup(engine, out_dir: str,
           tables: Optional[Sequence[str]] = None) -> List[str]:
    """Physical backup: per table, a JSON schema sidecar + the live rows
    as Chunk-codec payloads (ref: br/pkg/backup; the payload format is
    the engine's own wire codec, SURVEY A.1). Resumable via checkpoint."""
    os.makedirs(out_dir, exist_ok=True)
    ckpt = Checkpoint(os.path.join(out_dir, "checkpoint.json"), "backup")
    snap = engine.store.snapshot()
    infos = [t for t in engine.catalog.info_schema.list_tables()
             if not t.name.startswith("#")]
    if tables is not None:
        want = {t.lower() for t in tables}
        infos = [t for t in infos if t.name.lower() in want]
    done = []
    for info in infos:
        if ckpt.is_done(info.name):
            continue
        from tidb_tpu.util import failpoint
        failpoint.inject("backup-table")
        payloads = []
        if snap.has_table(info.id):
            for region, alive in snap.scan(info.id):
                from tidb_tpu.executor.scan import align_chunk_to_schema
                chunk = align_chunk_to_schema(region.chunk, info)
                if not alive.all():
                    chunk = chunk.take(np.nonzero(alive)[0])
                if chunk.num_rows:
                    payloads.append(encode_chunk(chunk))
        meta = {
            "version": BACKUP_FORMAT_VERSION,
            "name": info.name,
            "ddl": create_table_sql(info),
            "n_chunks": len(payloads),
        }
        body = b"".join(
            len(p).to_bytes(8, "little") + p for p in payloads)
        _atomic_write(os.path.join(out_dir, f"{info.name}.meta.json"),
                      json.dumps(meta).encode())
        _atomic_write(os.path.join(out_dir, f"{info.name}.chunks"), body)
        ckpt.mark(info.name)
        done.append(info.name)
    # system state: SET GLOBAL variables + users/grants — the
    # mysql.global_variables / mysql.user tables' analog, so both
    # survive a restore-into-a-fresh-engine "restart"
    with engine.stats_lock:
        gvars = dict(engine.global_vars)
    sys_state = {"global_vars": gvars,
                 "auth": engine.auth.dump_state()}
    _atomic_write(os.path.join(out_dir, "system.meta.json"),
                  json.dumps(sys_state).encode())
    ckpt.finish()
    return done


def restore(engine, backup_dir: str) -> List[str]:
    """Recreate tables + data from a backup directory; resumable (a table
    already restored — recorded in the restore checkpoint — is skipped)."""
    ckpt = Checkpoint(os.path.join(backup_dir, "restore.checkpoint.json"),
                      "restore")
    session = engine.new_session()
    restored = []
    sys_path = os.path.join(backup_dir, "system.meta.json")
    if os.path.exists(sys_path):
        with open(sys_path) as f:
            sys_state = json.load(f)
        with engine.stats_lock:
            engine.global_vars.update(sys_state.get("global_vars", {}))
        if sys_state.get("auth"):
            engine.auth.load_state(sys_state["auth"])
    metas = sorted(f for f in os.listdir(backup_dir)
                   if f.endswith(".meta.json") and f != "system.meta.json")
    for mf in metas:
        with open(os.path.join(backup_dir, mf)) as f:
            meta = json.load(f)
        name = meta["name"]
        if ckpt.is_done(name):
            continue
        if meta.get("version", 0) > BACKUP_FORMAT_VERSION:
            raise TiDBTPUError(
                f"backup of {name} uses a newer format "
                f"({meta['version']} > {BACKUP_FORMAT_VERSION})")
        from tidb_tpu.util import failpoint
        failpoint.inject("restore-table")
        session.execute(meta["ddl"])
        info = engine.catalog.info_schema.table(name)
        ftypes = [c.ftype for c in info.columns]
        path = os.path.join(backup_dir, f"{name}.chunks")
        buf = open(path, "rb").read() if os.path.exists(path) else b""
        pos = 0
        txn = engine.store.begin()
        while pos < len(buf):
            ln = int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
            chunk = decode_chunk(buf[pos:pos + ln], ftypes)
            pos += ln
            if info.partition is not None:
                # restored rows must re-acquire their region partition
                # tags or partition DDL/pruning would miss them
                from tidb_tpu.planner.partition import split_chunk
                for ordinal, sub in split_chunk(info.partition, chunk):
                    txn.append(info.id, sub, part=ordinal)
            else:
                txn.append(info.id, chunk)
        txn.commit()
        ckpt.mark(name)
        restored.append(name)
    ckpt.finish()
    return restored


# ---------------------------------------------------------------------------
# CSV import/export (lightning-lite)
# ---------------------------------------------------------------------------


def export_csv(source, table: str, path: str, delimiter: str = ",") -> int:
    import csv
    names, rows = _query_cols_rows(source, f"SELECT * FROM `{table}`")
    with open(path, "w", newline="") as f:
        w = csv.writer(f, delimiter=delimiter)
        w.writerow(names)
        for r in rows:
            w.writerow(["\\N" if v is None else v for v in r])
    return len(rows)


def import_csv(session, table: str, path: str, delimiter: str = ",",
               batch_rows: int = 2000) -> int:
    """Bulk CSV load through the SQL layer (lightning's logical mode);
    the header row must name the columns."""
    import csv
    total = 0
    with open(path, newline="") as f:
        r = csv.reader(f, delimiter=delimiter)
        header = next(r)
        cols = ", ".join(f"`{c}`" for c in header)
        batch: List[str] = []
        for row in r:
            vals = ", ".join(
                "NULL" if v == "\\N" else _sql_literal(v) for v in row)
            batch.append(f"({vals})")
            if len(batch) >= batch_rows:
                session.execute(
                    f"INSERT INTO `{table}` ({cols}) VALUES " +
                    ",".join(batch))
                total += len(batch)
                batch = []
        if batch:
            session.execute(f"INSERT INTO `{table}` ({cols}) VALUES " +
                            ",".join(batch))
            total += len(batch)
    return total


# ---------------------------------------------------------------------------
# source adapters (Client vs Session)
# ---------------------------------------------------------------------------


def _table_names(source, tables) -> List[str]:
    if tables is not None:
        return list(tables)
    if hasattr(source, "engine"):            # Session
        return [t.name for t in
                source.engine.catalog.info_schema.list_tables()
                if not t.name.startswith("#")]
    _, rows = source.query("SHOW TABLES")
    return [r[0] for r in rows]


def _show_create(source, table: str) -> str:
    if hasattr(source, "engine"):
        info = source.engine.catalog.info_schema.table(table)
        return create_table_sql(info)
    _, rows = source.query(f"SHOW CREATE TABLE `{table}`")
    return rows[0][1]


def _query_rows(source, sql: str):
    if hasattr(source, "engine"):
        return source.query(sql).rows
    _, rows = source.query(sql)
    return rows


def _query_cols_rows(source, sql: str):
    if hasattr(source, "engine"):
        rs = source.query(sql)
        return rs.names, rs.rows
    return source.query(sql)
