"""Chaos / failpoint sweep: fault-inject every registered site under a
live workload and assert the lifecycle contract — every statement either
returns the oracle answer or raises a TYPED TiDBTPUError, within a
deadline; writes are atomic (COUNT advances exactly when the INSERT
succeeded); the session stays usable afterwards. Never a hang, never
silent corruption (ref: the reference's failpoint-enabled CI runs,
pingcap/failpoint + tests/realtikvtest).

Runnable three ways:

    python -m tidb_tpu.tools.chaos_sweep          # CLI, nonzero on fail
    python tools/chaos_sweep.py [--mesh N]        # repo-root wrapper
    pytest -m chaos                               # via tests/test_guardrails

The sweep builds its fixture CLEANLY first (faults off), records oracle
results, then runs one scenario per fault. Each scenario is
(site, fault, workload): read workloads re-check every query against the
oracle; write workloads re-count the table. failpoint.counting() meters
which sites the workload actually reached, so a refactor that silently
moves a site out of the hot path shows up as lost coverage — and the CLI
exits non-zero when a site the run was supposed to reach stayed cold
(mesh-only sites are exempt unless --mesh N forces a multi-device CPU
mesh, which makes the distributed scenarios — skewed exchange overflow,
shard-step faults — runnable too)."""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from tidb_tpu.errors import (ExecutionError, MemoryQuotaExceeded,
                             ShardFailure, TiDBTPUError, TxnError)
from tidb_tpu.util import failpoint

# every statement must finish (result or typed error) inside this
DEADLINE_S = 30.0

QUERIES = [
    "select count(*), sum(a) from cs_facts",
    "select b, count(*) from cs_facts group by b order by b",
    "select d.name, count(*) from cs_facts f join cs_dim d "
    "on f.b = d.id group by d.name order by d.name",
    "select a from cs_facts order by a limit 5",
    # high-cardinality group key: under a squeezed quota this one is what
    # drives the agg's spill container (thousands of string groups)
    "select c, count(*) from cs_facts group by c order by c limit 3",
]

# ~3001 distinct doubles behind an EXPRESSION key: no cached bounds to
# perfect-hash, no column NDV stats to pre-size the cap — with
# tidb_tpu_group_cap squeezed the factorize cap overflows and the
# escalation ladder recompiles exactly once (the only single-process
# road to the device-recompile site). Compared as sorted row sets:
# without an ORDER BY the engines may emit groups in any order.
RECOMPILE_QUERY = "select d + 0.0, count(*) from cs_facts group by d + 0.0"

# join + EXPRESSION group key: the agg-over-join shape rides the fused
# per-slab pipeline, and the expression key (no cached bounds, no NDV
# stats) keeps the factorize cap at the session var — squeezing
# tidb_tpu_group_cap makes the overflow land INSIDE the fused driver's
# batched flag round, where the resumable retry re-runs only the
# overflowed slabs. ~997 distinct keys; compared as sorted row sets.
FUSED_QUERY = ("select f.a + 0, count(*) from cs_facts f "
               "join cs_dim d on f.b = d.id group by f.a + 0")

# single-arg DISTINCT agg under an ORDER BY root: the shape that rides
# the fused finalize (agg merge → finalize exprs → root ORDER BY in ONE
# launch) with per-slab (group, value) pair sets for the DISTINCT.
# Squeezing tidb_tpu_distinct_pair_cap below the per-slab distinct pair
# count (~1000 pairs per 1024-row slab here) makes the pair transfer cap
# overflow, which must resize through the resumable 'pairs' ladder rung
# — a clipped pair set must never be consumed
FINALIZE_QUERY = ("select b, count(distinct a) from cs_facts "
                  "group by b order by b")

# selective scan whose WHERE rides the zone maps: with compression on
# (the default) the host consults per-slab min/max BEFORE dispatch, so
# this query walks the prune decision — the zone-map-stale site —
# on every device attempt
PRUNE_QUERY = "select count(*), sum(a) from cs_facts where a > 100"

# distributed shapes — integer results, so dist vs CPU comparison is
# exact. The DISTINCT agg and the join matter: a plain group-by
# distributes through gather_partials (no re-key), so only the DISTINCT
# re-key exchange and a non-broadcast join carry exchanges — by default
# these now run STAGED (per-rank partition programs, device→host bucket
# checkpoints, host routing, per-rank probes), which is what puts the
# exchange-checkpoint-write / exchange-redispatch /
# exchange-degraded-replan sites in reach of the mesh coverage gate
MESH_QUERIES = [
    QUERIES[1],
    FINALIZE_QUERY,
    QUERIES[2],
]


def _retryable_txn(msg: str) -> TxnError:
    e = TxnError(msg)
    e.retryable = True
    return e


class Scenario:
    def __init__(self, name: str, site: Optional[str], enable_kw: dict,
                 run: str = "read", vars: Optional[Dict[str, str]] = None,
                 extra: Optional[Dict[str, dict]] = None,
                 mesh: bool = False, require_error: bool = False):
        self.name = name
        self.site = site
        self.enable_kw = enable_kw
        self.run = run               # read | write | ddl | backup | ...
        self.vars = vars or {}
        self.extra = extra or {}     # additional site → enable kwargs
        self.mesh = mesh             # needs run_sweep(mesh=N)
        self.require_error = require_error   # fault must SURFACE typed


def _scenarios(mesh: Optional[int] = None) -> List[Scenario]:
    device_on = {"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": "0"}
    out = [
        # -- CPU pipeline faults ------------------------------------------
        Scenario("scan transient fault", "scan-next",
                 dict(raise_=ExecutionError("chaos: scan-next"), times=1)),
        Scenario("scan fault after warmup", "scan-next",
                 dict(raise_=ExecutionError("chaos: scan-late"),
                      after_hits=2, times=1)),
        Scenario("scan flaky one-in-3", "scan-next",
                 dict(raise_=ExecutionError("chaos: scan-flaky"),
                      one_in=3, times=2)),
        Scenario("tracker quota blown", "tracker-quota",
                 dict(raise_=MemoryQuotaExceeded("chaos: quota"),
                      after_hits=5, times=1)),
        # -- spill path (quota squeezed so the agg engages its spill) -----
        Scenario("spill write I/O error", "spill-write",
                 dict(raise_=ExecutionError("chaos: spill-write"), times=1),
                 vars={"tidb_mem_quota_query": "8000"}),
        Scenario("spill read-back error", "spill-read",
                 dict(raise_=ExecutionError("chaos: spill-read"), times=1),
                 vars={"tidb_mem_quota_query": "8000"}),
        # -- commit path ---------------------------------------------------
        Scenario("commit hard conflict", "store-commit",
                 dict(raise_=TxnError("chaos: conflict"), times=1),
                 run="write"),
        Scenario("commit transient conflict (heals)", "commit-conflict",
                 dict(raise_=_retryable_txn("chaos: transient"), times=2),
                 run="write"),
        Scenario("commit retry budget exhausted", "commit-conflict",
                 dict(raise_=_retryable_txn("chaos: hot key")),
                 run="write",
                 extra={"backoff-sleep": dict(value="skip")}),
        # -- device path (engine forced on; CPU backend still JITs) -------
        Scenario("device fragment crash → CPU fallback", "device-fragment",
                 dict(raise_=RuntimeError("chaos: device down"), times=9),
                 vars=dict(device_on)),
        Scenario("HBM upload failure → CPU fallback", "device-transfer",
                 dict(raise_=RuntimeError("chaos: transfer"), times=9),
                 vars=dict(device_on)),
        Scenario("host fetch interrupted", "host-fetch",
                 dict(raise_=ExecutionError("chaos: host-fetch"), times=9),
                 vars=dict(device_on)),
        # group-cap overflow engages the escalation ladder; the fault
        # lands on its first recompile attempt → warned CPU fallback,
        # still the oracle answer (never truncated rows)
        Scenario("recompile ladder fault → CPU fallback", "device-recompile",
                 dict(raise_=RuntimeError("chaos: recompile"), times=1),
                 run="recompile",
                 vars={**device_on, "tidb_tpu_group_cap": "64"}),
        # the fused per-slab pipeline's capacity boundary: the site is
        # armed with NO action — it purely meters that the fused driver's
        # overflow-classification round ran — while the squeezed group
        # cap forces an in-pipeline escalation whose resumable retry is
        # asserted through the capacity ladder (slabs_rerun, exact
        # resize), results staying byte-equal to the oracle
        Scenario("fused pipeline overflow → resumable in-pipeline retry",
                 "fused-pipeline-overflow", dict(), run="fused",
                 vars={**device_on, "tidb_tpu_group_cap": "64",
                       "tidb_tpu_max_slab_rows": "1024"}),
        # a fault AT the fused capacity boundary: the per-statement guard
        # converts it to a warned CPU fallback — oracle rows, never a
        # truncated fused result
        Scenario("fused boundary fault → CPU fallback",
                 "fused-pipeline-overflow",
                 dict(raise_=RuntimeError("chaos: fused boundary"),
                      times=9),
                 run="fused", vars=dict(device_on)),
        # the fused finalize's distinct-pair transfer cap: armed with NO
        # action, the site purely meters that the per-slab pair-count
        # validation round ran — while the squeezed pair cap forces the
        # resumable 'pairs' escalation (exact resize to the true pair
        # count, only clipped slabs re-run) and the ordered result stays
        # byte-equal to the oracle
        Scenario("fused finalize pair overflow → resumable resize",
                 "fused-finalize-overflow", dict(), run="finalize",
                 vars={**device_on, "tidb_tpu_max_slab_rows": "1024",
                       "tidb_tpu_distinct_pair_cap": "64"}),
        # a fault AT the finalize boundary: the per-statement guard
        # converts it to a warned CPU fallback — oracle rows, never a
        # truncated ORDER BY/TopN result
        Scenario("fused finalize fault → CPU fallback",
                 "fused-finalize-overflow",
                 dict(raise_=RuntimeError("chaos: finalize boundary"),
                      times=9),
                 run="finalize", vars=dict(device_on)),
        # a corrupted compressed-layout descriptor: the serving path's
        # validation failpoint stands in for a descriptor that no longer
        # matches its packed words — open_table raises a typed
        # LayoutError, the executor converts it into a warned CPU
        # fallback, and rows stay byte-equal to the oracle (NEVER a
        # silent wrong decode)
        Scenario("compressed descriptor corrupt → CPU fallback",
                 "compressed-decode-mismatch",
                 dict(value="chaos: descriptor drift", times=9),
                 vars=dict(device_on)),
        # a stale zone map at the host-side slab-prune decision: the
        # consult raises a typed LayoutError, the per-statement guard
        # converts it into a warned CPU fallback, and the selective
        # query still answers the oracle — a stale map must NEVER
        # silently skip slabs that hold passing rows
        Scenario("stale zone map → CPU fallback", "zone-map-stale",
                 dict(value="chaos: stale zone map", times=9),
                 run="prune", vars=dict(device_on)),
        # a fault at the micro-batch result de-multiplex: 8 concurrent
        # same-digest point reads coalesce into ONE batched launch, the
        # demux raises once — every member must degrade to warned
        # individual re-execution with ITS OWN oracle rows; a member must
        # never see a sibling's rows or a shared typed error
        Scenario("micro-batch demux fault → warned per-member fallback",
                 "microbatch-demux",
                 dict(raise_=RuntimeError("chaos: demux"), times=1),
                 run="microbatch",
                 vars={**device_on, "tidb_tpu_microbatch_max": "8"}),
        # a fault at the work-steal handoff: a batch statement parked at
        # its admission turnstile is pulled by an idle sibling, the
        # migration faults once — the waiter must re-queue on its HOME
        # device (backoff charged), run exactly once, and still answer
        # the oracle within the deadline; never lost, never doubled
        Scenario("work-steal handoff fault → re-queued home, never lost",
                 "steal-migrate",
                 dict(raise_=RuntimeError("chaos: steal handoff"),
                      times=1),
                 run="steal",
                 vars={**device_on, "tidb_tpu_device_queues": "on"},
                 extra={"backoff-sleep": dict(value="skip")}),
        # -- degraded pod (device fault domain) ---------------------------
        # a pool device dies at its DISPATCH boundary mid-concurrent-mix:
        # the in-flight victim classifies into a typed DeviceLost, the
        # health monitor quarantines the device (queued waiters migrate
        # to survivors, its HBM shard is evicted/re-homed) and the victim
        # retries ONCE on a survivor with a retryable 1105 warning —
        # EVERY statement in the mix must still answer the oracle within
        # the deadline (zero lost, zero doubled). Once the one-shot fault
        # is spent, the flap-guard delay elapses, the placement-driven
        # readmit probe (metered through the armed device-readmit gate)
        # rejoins the device, and placements land on it again
        Scenario("device lost at dispatch → quarantine, migrate, readmit",
                 "device-lost-dispatch",
                 dict(raise_=RuntimeError("chaos: device lost"), times=1),
                 run="podfault",
                 vars={**device_on, "tidb_tpu_device_queues": "on"},
                 extra={"backoff-sleep": dict(value="skip"),
                        "device-readmit": dict()}),
        # the same fault domain at the UPLOAD boundary: the device dies
        # while its cold cache shard is streaming in (device_put). The
        # partially-committed shard is evicted with the quarantine and
        # the statement re-streams onto a survivor — same
        # exactly-once/readmission contract as the dispatch fault
        Scenario("device lost at upload → quarantine, re-stream, readmit",
                 "device-lost-upload",
                 dict(raise_=RuntimeError("chaos: upload lost"), times=1),
                 run="podfault",
                 vars={**device_on, "tidb_tpu_device_queues": "on"},
                 extra={"backoff-sleep": dict(value="skip"),
                        "device-readmit": dict()}),
        # -- HTAP write path (delta slabs) --------------------------------
        # a transient fault at the two-phase delta append's atomic apply
        # point: the commit backoff loop retries and the write lands
        # exactly once (the post-scenario count probe asserts that)
        Scenario("delta append transient fault (heals)", "delta-append",
                 dict(raise_=_retryable_txn("chaos: delta append"),
                      times=2),
                 run="write",
                 extra={"backoff-sleep": dict(value="skip")}),
        # a hard fault at the same boundary: ONE typed error surfaces
        # with the old delta version intact — the count probe proves the
        # append was never torn (all-or-nothing)
        Scenario("delta append hard fault → typed, never torn",
                 "delta-append",
                 dict(raise_=TxnError("chaos: torn append"), times=1),
                 run="write"),
        # a diff/encode fault at the delta-extension entry while a
        # cached table is stale: typed LayoutError → warned CPU
        # fallback, still the oracle answer — never a wrong merge
        Scenario("delta merge stale → CPU fallback", "delta-merge-stale",
                 dict(value="chaos: stale diff", times=9),
                 run="delta", vars=dict(device_on)),
        # a fault at the compaction's atomic install point: the rebuilt
        # generation is abandoned (buffers deleted) and the old
        # base+delta keeps serving byte-exactly; once the fault clears,
        # the next extension re-schedules and the compaction heals
        Scenario("compaction commit fault → old generation serves",
                 "compaction-commit",
                 dict(raise_=RuntimeError("chaos: compaction fault"),
                      times=1),
                 run="compact",
                 vars={**device_on, "tidb_tpu_delta_compact_rows": "4",
                       "tidb_tpu_compaction": "off"}),
        # -- DDL -----------------------------------------------------------
        Scenario("unique backfill dies mid-reorg", "index-backfill",
                 dict(raise_=ExecutionError("chaos: backfill"), times=1),
                 run="ddl"),
        # -- tools ---------------------------------------------------------
        Scenario("backup dies between tables", "backup-table",
                 dict(raise_=TiDBTPUError("chaos: backup"), times=1),
                 run="backup"),
        Scenario("restore dies between tables", "restore-table",
                 dict(raise_=TiDBTPUError("chaos: restore"), times=1),
                 run="restore"),
    ]
    if mesh:
        dist_on = {"tidb_tpu_engine": "on", "tidb_tpu_row_threshold": "1",
                   "tidb_tpu_dist_devices": str(mesh)}
        out += [
            # squeezed bucket cap: every hash exchange overflows, reports
            # its exact need, and the ladder resizes ONCE — the site is
            # armed with no action, purely metering that the resize path
            # ran while results stay byte-equal to the CPU oracle
            Scenario("mesh exchange overflow → exact-need resize",
                     "exchange-overflow", dict(), run="mesh-read",
                     vars={**dist_on, "tidb_tpu_exchange_bucket_cap": "8"},
                     mesh=True),
            # one shard's step raises once: every distributed shape now
            # re-runs only that rank against its checkpoints — the staged
            # agg for the plain group-by, the staged exchange for the
            # DISTINCT re-key and the join (its stage-1 partition and
            # stage-3 probe attempts trace the same shard-step site)
            Scenario("mesh shard fault heals after retry", "shard-step",
                     dict(raise_=ShardFailure("chaos: shard down"),
                          times=1),
                     run="mesh-read", vars=dict(dist_on), mesh=True),
            # losing one rank's device→host checkpoint re-runs only that
            # rank (staged path only — hence the mesh-agg workload)
            Scenario("mesh checkpoint write fails once → heals",
                     "shard-checkpoint-write",
                     dict(raise_=ShardFailure("chaos: checkpoint lost"),
                          times=1),
                     run="mesh-agg", vars=dict(dist_on), mesh=True),
            # a persistently bad device: dispatch AND same-device retry
            # fail, so the rank's work re-dispatches onto a surviving
            # device (degraded mesh) and the result still matches the
            # oracle; the extras are armed with no action purely to meter
            # that the recovery sites actually fired
            Scenario("mesh device persistently bad → degraded-mesh heal",
                     "shard-step",
                     dict(raise_=ShardFailure("chaos: device bad"),
                          times=2),
                     run="mesh-agg", vars=dict(dist_on), mesh=True,
                     extra={"degraded-mesh-replan": dict(),
                            "shard-redispatch": dict()}),
            # the fault persists through every recovery rung — the
            # same-device retry AND the re-dispatch onto a spare: ONE
            # typed ShardFailure must surface (a silent CPU re-run would
            # hide a dead shard). Both re-dispatch rungs are armed: the
            # staged agg's shard-redispatch AND the staged exchange's
            # exchange-redispatch (the DISTINCT re-key / join shapes
            # would otherwise heal onto the spare device)
            Scenario("mesh shard fault persists → typed error",
                     "shard-step",
                     dict(raise_=ShardFailure("chaos: shard down")),
                     run="mesh-read", vars=dict(dist_on), mesh=True,
                     require_error=True,
                     extra={"shard-redispatch":
                            dict(raise_=ShardFailure("chaos: spare down")),
                            "exchange-redispatch":
                            dict(raise_=ShardFailure("chaos: spare down"))
                            }),
            # -- staged exchanges (joins, DISTINCT re-keys, windows) -----
            # losing one rank's stage-1 bucket checkpoint re-runs only
            # that rank's partition program; the other ranks' committed
            # checkpoints are routed untouched. times=1 so only the FIRST
            # exchange-carrying shape (the DISTINCT re-key) takes the
            # fault and its same-device retry heals cleanly; the join
            # runs clean after it (both-shapes recovery is pinned per
            # failpoint in tests/test_staged_exchange.py)
            Scenario("mesh exchange checkpoint lost → heals one rank",
                     "exchange-checkpoint-write",
                     dict(raise_=ShardFailure("chaos: bucket ckpt lost"),
                          times=1),
                     run="mesh-read", vars=dict(dist_on), mesh=True),
            # a persistently bad device under a DISTRIBUTED JOIN: the
            # rank's stage fails on its device and on the same-device
            # retry, re-dispatches onto a surviving device through the
            # exchange-degraded-replan / exchange-redispatch rungs
            # (armed with no action purely to meter reachability), and
            # the join still answers the oracle on N-1 devices
            Scenario("mesh join device bad → degraded-mesh heal",
                     "shard-step",
                     dict(raise_=ShardFailure("chaos: device bad"),
                          times=2),
                     run="mesh-join", vars=dict(dist_on), mesh=True,
                     extra={"exchange-degraded-replan": dict(),
                            "exchange-redispatch": dict()}),
            # the join's shard is fully dead — its own device AND the
            # re-dispatch spare both fail: ONE typed retryable
            # ShardFailure surfaces and the session stays usable (the
            # post-scenario count probe asserts that)
            Scenario("mesh join shard fully dead → typed error",
                     "shard-step",
                     dict(raise_=ShardFailure("chaos: device down")),
                     run="mesh-join", vars=dict(dist_on), mesh=True,
                     require_error=True,
                     extra={"exchange-redispatch":
                            dict(raise_=ShardFailure("chaos: spare down"))
                            }),
            # two-session isolation: session A takes a shard fault on
            # the mesh path while session B serves the single-process
            # device path CONCURRENTLY — B must stay byte-exact and
            # error-free throughout (the fault, the retry, the shared
            # HBM/compile caches and scheduler never leak across
            # sessions), and A still heals to the oracle answer
            Scenario("shard fault isolated from concurrent session",
                     "shard-step",
                     dict(raise_=ShardFailure("chaos: shard down"),
                          times=1),
                     run="mesh-isolation", vars=dict(dist_on), mesh=True),
        ]
    return out


def list_sites() -> Dict[str, str]:
    """The sweep's authoritative failpoint catalog: every site
    registered in util/failpoint.py PLUS module-scope registrations
    (executor/zonemap.py's zone-map-stale) — imported here so the
    enumeration matches what the coverage gate sweeps.
    → {site: description} (tools/check_failpoints.py cross-checks the
    count, keeping the advertised site number honest)."""
    from tidb_tpu.executor import zonemap  # noqa: F401 — registers at import
    return failpoint.catalog()


def _run_statement(session, sql: str):
    """→ (rows|None, error|None, elapsed). Non-TiDBTPUError escapes —
    that IS a sweep failure."""
    t0 = time.monotonic()
    try:
        rs = session.query(sql)
        return rs.rows, None, time.monotonic() - t0
    except TiDBTPUError as e:
        return None, e, time.monotonic() - t0


def run_sweep(verbose: bool = False, mesh: Optional[int] = None,
              mesh_only: bool = False) -> dict:
    """mesh=N runs the distributed scenarios over an N-device mesh (the
    process must already see ≥N devices — the CLI's --mesh forces a host
    CPU mesh via XLA_FLAGS before jax loads). mesh_only skips the
    single-process scenarios: the cheap pytest `-m chaos` mesh variant."""
    from tidb_tpu.session import Engine
    if mesh:
        import jax
        if len(jax.devices()) < mesh:
            raise RuntimeError(
                f"--mesh {mesh} needs {mesh} devices, jax sees "
                f"{len(jax.devices())}; set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={mesh} before "
                f"jax loads (tools/chaos_sweep.py --mesh does this)")
    failpoint.disable_all()
    eng = Engine()
    s = eng.new_session()

    # fixture FIRST, faults off — the oracle must be trustworthy
    s.execute("create table cs_dim (id int, name varchar(16))")
    s.execute("create table cs_facts (a int, b int, c varchar(24), "
              "d double)")
    dim = ", ".join(f"({i}, 'name{i:02d}')" for i in range(8))
    s.execute(f"insert into cs_dim values {dim}")
    for base in range(0, 4000, 500):
        vals = ", ".join(
            f"({(i * 37) % 997 - 200}, {i % 8}, 'payload-{i:05d}', "
            f"{((i * 53) % 3001) / 8.0})"
            for i in range(base, base + 500))
        s.execute(f"insert into cs_facts values {vals}")
    # NDV stats so the distributed planner trusts its row estimates
    s.execute("analyze table cs_dim")
    s.execute("analyze table cs_facts")

    # coverage meter: which sites does the clean workload even reach?
    failpoint.reset_counters()
    with failpoint.counting():
        for q in QUERIES:
            s.query(q)
        s.execute("insert into cs_facts values (1, 1, 'probe', 0.0)")
    coverage = failpoint.counters()

    # oracle recorded AFTER the probe write; re-recorded after every
    # mutating scenario, so "correct result" always means "what a clean
    # run over the CURRENT data returns"
    oracle_qs = QUERIES + [RECOMPILE_QUERY, FUSED_QUERY, PRUNE_QUERY] + \
        [q for q in MESH_QUERIES if q not in QUERIES]
    oracle = {q: s.query(q).rows for q in oracle_qs}
    base_count = s.query("select count(*) from cs_facts").scalar()

    failures: List[str] = []
    results: List[dict] = []
    reached = {k for k, v in coverage.items() if v > 0}
    write_seq = 0

    for sc in _scenarios(mesh):
        if mesh_only and not sc.mesh:
            continue
        saved = {k: s.vars.get(k) for k in sc.vars}
        s.vars.update(sc.vars)
        if sc.site is not None:
            failpoint.enable(sc.site, **sc.enable_kw)
        for site, kw in sc.extra.items():
            failpoint.enable(site, **kw)
        errors, wrong, slow = 0, 0, 0
        try:
            if sc.run == "read":
                for q in QUERIES:
                    rows, err, dt = _run_statement(s, q)
                    if dt > DEADLINE_S:
                        slow += 1
                        failures.append(f"{sc.name}: {q!r} took {dt:.1f}s")
                    if err is not None:
                        errors += 1
                    elif rows != oracle[q]:
                        wrong += 1
                        failures.append(
                            f"{sc.name}: {q!r} SILENT WRONG RESULT")
            elif sc.run == "recompile":
                q = RECOMPILE_QUERY
                rows, err, dt = _run_statement(s, q)
                if dt > DEADLINE_S:
                    slow += 1
                    failures.append(f"{sc.name}: {q!r} took {dt:.1f}s")
                if err is not None:
                    errors += 1
                elif sorted(rows) != sorted(oracle[q]):
                    wrong += 1
                    failures.append(f"{sc.name}: {q!r} SILENT WRONG RESULT")
            elif sc.run == "prune":
                q = PRUNE_QUERY
                rows, err, dt = _run_statement(s, q)
                if dt > DEADLINE_S:
                    slow += 1
                    failures.append(f"{sc.name}: {q!r} took {dt:.1f}s")
                if err is not None:
                    errors += 1
                elif rows != oracle[q]:
                    wrong += 1
                    failures.append(f"{sc.name}: {q!r} SILENT WRONG RESULT")
            elif sc.run == "fused":
                q = FUSED_QUERY
                rows, err, dt = _run_statement(s, q)
                if dt > DEADLINE_S:
                    slow += 1
                    failures.append(f"{sc.name}: {q!r} took {dt:.1f}s")
                if err is not None:
                    errors += 1
                elif sorted(rows) != sorted(oracle[q]):
                    wrong += 1
                    failures.append(f"{sc.name}: {q!r} SILENT WRONG RESULT")
                elif sc.enable_kw.get("raise_") is None:
                    # site armed with no action → the fused driver must
                    # have taken its RESUMABLE escalation: the squeezed
                    # group cap overflows inside the pipeline, the ladder
                    # records one exact resize, and only overflowed slab
                    # partials re-run (uniform key spread here → all of
                    # them overflow; reuse-split skew is pinned down in
                    # tests/test_fused_pipeline.py)
                    esc = s.last_guard.escalation
                    if esc.slabs_rerun == 0 or esc.exact_resizes == 0:
                        failures.append(
                            f"{sc.name}: fused driver skipped the "
                            f"resumable retry (slabs_rerun="
                            f"{esc.slabs_rerun} exact_resizes="
                            f"{esc.exact_resizes})")
            elif sc.run == "finalize":
                q = FINALIZE_QUERY
                rows, err, dt = _run_statement(s, q)
                if dt > DEADLINE_S:
                    slow += 1
                    failures.append(f"{sc.name}: {q!r} took {dt:.1f}s")
                if err is not None:
                    errors += 1
                elif rows != oracle[q]:
                    wrong += 1
                    failures.append(f"{sc.name}: {q!r} SILENT WRONG RESULT")
                elif sc.enable_kw.get("raise_") is None:
                    # site armed with no action → the driver must have
                    # taken the resumable 'pairs' escalation: the
                    # squeezed pair cap clips every slab's pair set, the
                    # ladder records one exact resize to the true count,
                    # and the clipped slabs re-run against the original
                    # resident columns
                    esc = s.last_guard.escalation
                    if esc.slabs_rerun == 0 or esc.exact_resizes == 0:
                        failures.append(
                            f"{sc.name}: finalize driver skipped the "
                            f"resumable pairs retry (slabs_rerun="
                            f"{esc.slabs_rerun} exact_resizes="
                            f"{esc.exact_resizes})")
            elif sc.run in ("mesh-read", "mesh-agg", "mesh-join"):
                # mesh-agg: only the plain group-by (the staged-AGG
                # checkpoint ladder); mesh-join: only the distributed
                # join (the staged-EXCHANGE ladder — stage-1 partition
                # checkpoints, host bucket routing, stage-3 probe).
                # mesh-read runs all three shapes — since the staged
                # exchange landed, the DISTINCT re-key and the join ride
                # the same per-rank recovery as the agg
                if sc.run == "mesh-agg":
                    qs = MESH_QUERIES[:1]
                elif sc.run == "mesh-join":
                    qs = MESH_QUERIES[2:3]
                else:
                    qs = MESH_QUERIES
                for q in qs:
                    rows, err, dt = _run_statement(s, q)
                    if dt > DEADLINE_S:
                        slow += 1
                        failures.append(f"{sc.name}: {q!r} took {dt:.1f}s")
                    if err is not None:
                        errors += 1
                        if not sc.require_error:
                            failures.append(
                                f"{sc.name}: {q!r} unexpected typed error "
                                f"{type(err).__name__}: {err}")
                    elif sc.require_error:
                        failures.append(
                            f"{sc.name}: {q!r} expected a typed error, "
                            f"got a silent result")
                    elif rows != oracle[q]:
                        wrong += 1
                        failures.append(
                            f"{sc.name}: {q!r} SILENT WRONG RESULT")
            elif sc.run == "mesh-isolation":
                # session B: single-process device path (no mesh vars →
                # it never traces shard-step), looping a read the whole
                # time session A's mesh query faults and heals
                s2 = eng.new_session()
                s2.vars["tidb_tpu_engine"] = "on"
                s2.vars["tidb_tpu_row_threshold"] = "1"
                b_query = QUERIES[1]
                b_fail: List[str] = []
                b_done = [0]
                stop = threading.Event()

                def sibling():
                    try:
                        while not stop.is_set() and b_done[0] < 24:
                            rows = s2.query(b_query).rows
                            if rows != oracle[b_query]:
                                b_fail.append(
                                    "sibling session WRONG RESULT while "
                                    "peer shard faulted")
                                return
                            b_done[0] += 1
                    except BaseException as e:  # noqa: BLE001
                        b_fail.append(
                            f"sibling session error during peer fault: "
                            f"{type(e).__name__}: {e}")

                th = threading.Thread(target=sibling, daemon=True)
                th.start()
                try:
                    for q in MESH_QUERIES:
                        rows, err, dt = _run_statement(s, q)
                        if dt > DEADLINE_S:
                            slow += 1
                            failures.append(
                                f"{sc.name}: {q!r} took {dt:.1f}s")
                        if err is not None:
                            errors += 1
                            failures.append(
                                f"{sc.name}: {q!r} did not heal: "
                                f"{type(err).__name__}: {err}")
                        elif rows != oracle[q]:
                            wrong += 1
                            failures.append(
                                f"{sc.name}: {q!r} SILENT WRONG RESULT")
                finally:
                    stop.set()
                    th.join(DEADLINE_S)
                if th.is_alive():
                    failures.append(f"{sc.name}: sibling session HUNG")
                failures.extend(f"{sc.name}: {m}" for m in b_fail)
                if b_done[0] == 0 and not b_fail:
                    failures.append(
                        f"{sc.name}: sibling session made no progress")
            elif sc.run == "microbatch":
                from tidb_tpu.executor import microbatch as _mb
                from tidb_tpu.executor.scheduler import SCHEDULER
                from tidb_tpu.util.observability import REGISTRY
                # oracle per member, run SOLO (a solo leader takes the
                # individual path, so the armed demux site never fires)
                # no ORDER BY: order roots don't micro-batch; the filter
                # path emits rows in slab order, which is deterministic,
                # so raw row-list comparison is exact
                mb_qs = [f"select a, c from cs_facts where b = {k}"
                         for k in range(8)]
                mb_sessions = []
                for _ in mb_qs:
                    s_i = eng.new_session()
                    s_i.vars.update(sc.vars)
                    mb_sessions.append(s_i)
                mb_oracle = [s.query(q).rows for q in mb_qs]
                mb_rows: List[Optional[list]] = [None] * len(mb_qs)
                mb_errs: List[Optional[BaseException]] = \
                    [None] * len(mb_qs)

                def mb_run(i):
                    try:
                        mb_rows[i] = mb_sessions[i].query(mb_qs[i]).rows
                    except BaseException as e:  # noqa: BLE001
                        mb_errs[i] = e

                fb0 = REGISTRY.counters.get(
                    ("tidb_tpu_microbatch_fallbacks_total", ()), 0)
                # hold the device slot so every dispatcher queues, then
                # release once the followers are parked on the batch
                SCHEDULER.acquire(conn_id=-1)
                try:
                    ths = [threading.Thread(target=mb_run, args=(i,))
                           for i in range(len(mb_qs))]
                    for th in ths:
                        th.start()
                    t_park = time.monotonic()
                    while _mb.queued_members() < len(mb_qs) - 1 and \
                            time.monotonic() - t_park < 5.0:
                        time.sleep(0.01)
                finally:
                    SCHEDULER.release()
                for th in ths:
                    th.join(DEADLINE_S)
                    if th.is_alive():
                        slow += 1
                        failures.append(f"{sc.name}: member HUNG")
                for i, (rows, err) in enumerate(zip(mb_rows, mb_errs)):
                    if err is not None:
                        errors += 1
                        failures.append(
                            f"{sc.name}: member {i} surfaced "
                            f"{type(err).__name__}: {err} — a demux "
                            f"fault must never fail a member")
                    elif rows != mb_oracle[i]:
                        wrong += 1
                        failures.append(
                            f"{sc.name}: member {i} SILENT WRONG ROWS")
                if failpoint.hits("microbatch-demux") > 0:
                    fb1 = REGISTRY.counters.get(
                        ("tidb_tpu_microbatch_fallbacks_total", ()), 0)
                    if fb1 <= fb0:
                        failures.append(
                            f"{sc.name}: demux faulted but no fallback "
                            f"was recorded")
            elif sc.run == "steal":
                from tidb_tpu.executor.scheduler import POOL
                q = QUERIES[1]
                # a second serving peer even on a 1-device host: the
                # steal protocol is pure host-side queue mechanics, so
                # the CPU sweep exercises it with device_queues forced
                # on and the pool grown explicitly
                POOL.ensure(2)
                dev0, dev1 = POOL.schedulers[0], POOL.schedulers[1]
                st_rows: List[Optional[list]] = [None]
                st_err: List[Optional[BaseException]] = [None]

                def st_run():
                    try:
                        st_rows[0] = s.query(q).rows
                    except BaseException as e:  # noqa: BLE001
                        st_err[0] = e

                # hold BOTH dispatch slots so the batch statement parks
                # at its admission turnstile (placement ties to device 0)
                dev0.acquire(conn_id=-1)
                dev1.acquire(conn_id=-1)
                th = threading.Thread(target=st_run, daemon=True)
                stole = False
                try:
                    th.start()
                    t_park = time.monotonic()
                    while time.monotonic() - t_park < 5.0:
                        with dev0._cv:
                            if dev0._stealable > 0:
                                break
                        time.sleep(0.01)
                    # the idle sibling pulls the parked waiter; the
                    # armed failpoint faults the handoff
                    stole = POOL.steal_into(dev1)
                finally:
                    dev1.release()
                    dev0.release()
                th.join(DEADLINE_S)
                if th.is_alive():
                    slow += 1
                    failures.append(f"{sc.name}: stolen statement HUNG")
                elif not stole:
                    failures.append(
                        f"{sc.name}: no steal-eligible waiter parked "
                        f"(batch admission never reached the turnstile)")
                elif st_err[0] is not None:
                    errors += 1
                    failures.append(
                        f"{sc.name}: statement must re-queue home and "
                        f"heal, not fail: {type(st_err[0]).__name__}: "
                        f"{st_err[0]}")
                elif st_rows[0] != oracle[q]:
                    wrong += 1
                    failures.append(f"{sc.name}: {q!r} SILENT WRONG "
                                    f"RESULT after faulted steal")
            elif sc.run == "podfault":
                from tidb_tpu.executor import device_cache as _dc
                from tidb_tpu.executor.scheduler import POOL
                from tidb_tpu.util.observability import REGISTRY

                def _ctr(name):
                    return sum(v for (n, _l), v in
                               REGISTRY.counters.items() if n == name)

                # a pod of two serving peers even on a 1-device host (the
                # fault domain is host-side pool mechanics), and a COLD
                # cache so the upload-boundary site actually streams
                POOL.ensure(2)
                _dc.clear()
                q_before = _ctr("tidb_tpu_device_quarantines_total")
                m_before = _ctr("tidb_tpu_statements_migrated_total")
                pf_qs = QUERIES * 2
                pf_sessions = []
                for _ in pf_qs:
                    s_i = eng.new_session()
                    s_i.vars.update(sc.vars)
                    pf_sessions.append(s_i)
                pf_rows: List[Optional[list]] = [None] * len(pf_qs)
                pf_errs: List[Optional[BaseException]] = \
                    [None] * len(pf_qs)

                def pf_run(i):
                    try:
                        pf_rows[i] = pf_sessions[i].query(pf_qs[i]).rows
                    except BaseException as e:  # noqa: BLE001
                        pf_errs[i] = e

                ths = [threading.Thread(target=pf_run, args=(i,),
                                        daemon=True)
                       for i in range(len(pf_qs))]
                for th in ths:
                    th.start()
                for i, th in enumerate(ths):
                    th.join(DEADLINE_S)
                    if th.is_alive():
                        slow += 1
                        failures.append(
                            f"{sc.name}: statement {i} HUNG past the "
                            f"deadline (lost to the dead device?)")
                # exactly-once: every statement must come back with the
                # oracle rows — the one victim heals through its single
                # survivor retry, so even a typed error is a failure here
                for i, (rows, err) in enumerate(zip(pf_rows, pf_errs)):
                    if err is not None:
                        errors += 1
                        failures.append(
                            f"{sc.name}: statement {i} must retry on a "
                            f"survivor, not fail: "
                            f"{type(err).__name__}: {err}")
                    elif rows != oracle[pf_qs[i]]:
                        wrong += 1
                        failures.append(
                            f"{sc.name}: statement {i} SILENT WRONG "
                            f"ROWS after device loss")
                if failpoint.hits(sc.site) == 0:
                    failures.append(
                        f"{sc.name}: the armed fault never fired — the "
                        f"mix missed the {sc.site} boundary")
                else:
                    if _ctr("tidb_tpu_device_quarantines_total") \
                            <= q_before:
                        failures.append(
                            f"{sc.name}: device fault fired but no "
                            f"device was quarantined")
                    if _ctr("tidb_tpu_statements_migrated_total") \
                            <= m_before:
                        failures.append(
                            f"{sc.name}: device fault fired but the "
                            f"victim statement never migrated")
                    victims = sorted(
                        i for i, r in POOL.health.snapshot().items()
                        if r["faults"] > 0)
                    if not victims:
                        failures.append(
                            f"{sc.name}: fault fired but the health "
                            f"monitor recorded no victim")
                    # heal: the one-shot fault is spent; placement drives
                    # the readmit sweep, so issuing statements past the
                    # flap-guard delay must readmit every quarantined
                    # device (the probe passes through the armed
                    # device-readmit gate, which also meters it)
                    t_heal = time.monotonic()
                    healed = False
                    while time.monotonic() - t_heal < 10.0:
                        _run_statement(s, QUERIES[0])
                        if not POOL.health.quarantined_indexes():
                            healed = True
                            break
                        time.sleep(0.05)
                    if not healed:
                        failures.append(
                            f"{sc.name}: device(s) "
                            f"{POOL.health.quarantined_indexes()} never "
                            f"readmitted after the fault cleared")
                    elif failpoint.hits("device-readmit") == 0:
                        failures.append(
                            f"{sc.name}: device readmitted without a "
                            f"health probe")
                    elif victims:
                        # placements return: park every OTHER member so
                        # least-depth placement of an uncached table must
                        # pick the readmitted device (locality votes
                        # can't — its shard was evicted, so the probe
                        # table is cold everywhere after the clear())
                        try:
                            s.execute("create table cs_pod (x int)")
                            s.execute("insert into cs_pod values "
                                      "(1), (2), (3)")
                        except TiDBTPUError:
                            pass        # second podfault scenario
                        with POOL._lock:
                            members = list(POOL.schedulers)
                        parked = [m for m in members
                                  if m.device_index not in victims]
                        a0 = sum(m.stats()["admissions"] for m in members
                                 if m.device_index in victims)
                        for m in parked:
                            m.acquire(conn_id=-1)
                        try:
                            _, perr, _ = _run_statement(
                                s, "select count(*) from cs_pod")
                        finally:
                            for m in parked:
                                m.release()
                        a1 = sum(m.stats()["admissions"] for m in members
                                 if m.device_index in victims)
                        if perr is not None:
                            failures.append(
                                f"{sc.name}: probe statement on the "
                                f"readmitted device failed: {perr}")
                        elif a1 <= a0:
                            failures.append(
                                f"{sc.name}: readmitted device(s) "
                                f"{victims} received no placements")
            elif sc.run == "delta":
                # warm the device cache, then commit an IN-RANGE row so
                # the next device read must extend the stale entry —
                # with the diff fault armed the extension must fall back
                # warned and still answer the post-write CPU oracle
                q = QUERIES[0]
                s.query(q)
                write_seq += 1
                _, werr, _ = _run_statement(
                    s, f"insert into cs_facts values "
                       f"(500, {write_seq % 8}, 'dl{write_seq}', 0.0)")
                if werr is not None:
                    failures.append(f"{sc.name}: fixture write failed "
                                    f"{werr}")
                else:
                    base_count += 1
                eng_saved = s.vars.get("tidb_tpu_engine")
                s.vars["tidb_tpu_engine"] = "off"
                cpu = s.query(q).rows
                s.vars["tidb_tpu_engine"] = eng_saved
                rows, err, dt = _run_statement(s, q)
                if dt > DEADLINE_S:
                    slow += 1
                    failures.append(f"{sc.name}: {q!r} took {dt:.1f}s")
                if err is not None:
                    errors += 1
                    failures.append(
                        f"{sc.name}: {q!r} must fall back, not fail: "
                        f"{type(err).__name__}: {err}")
                elif rows != cpu:
                    wrong += 1
                    failures.append(f"{sc.name}: {q!r} SILENT WRONG RESULT")
            elif sc.run == "compact":
                from tidb_tpu.executor import delta as _delta
                q = QUERIES[0]
                s.query(q)
                # pile IN-RANGE appends past the squeezed threshold so
                # the next read's extension schedules a compaction job
                for _i in range(4):
                    write_seq += 1
                    _, werr, _ = _run_statement(
                        s, f"insert into cs_facts values "
                           f"(501, {write_seq % 8}, 'cp{write_seq}', 0.0)")
                    if werr is None:
                        base_count += 1
                s.query(q)
                if _delta.pending_compactions() == 0:
                    failures.append(
                        f"{sc.name}: extension never scheduled a "
                        f"compaction job")
                committed = _delta.run_pending_compactions()
                if committed != 0:
                    failures.append(
                        f"{sc.name}: compaction committed THROUGH an "
                        f"armed commit fault")
                eng_saved = s.vars.get("tidb_tpu_engine")
                s.vars["tidb_tpu_engine"] = "off"
                cpu = s.query(q).rows
                s.vars["tidb_tpu_engine"] = eng_saved
                rows, err, dt = _run_statement(s, q)
                if err is not None:
                    errors += 1
                    failures.append(
                        f"{sc.name}: old generation failed to serve: "
                        f"{type(err).__name__}: {err}")
                elif rows != cpu:
                    wrong += 1
                    failures.append(
                        f"{sc.name}: old base+delta generation served "
                        f"WRONG ROWS after an abandoned rebuild")
                # fault clears → the next extension re-schedules and the
                # compaction HEALS
                failpoint.disable(sc.site)
                write_seq += 1
                _, werr, _ = _run_statement(
                    s, f"insert into cs_facts values "
                       f"(502, {write_seq % 8}, 'cp{write_seq}', 0.0)")
                if werr is None:
                    base_count += 1
                s.query(q)
                if _delta.run_pending_compactions() < 1:
                    failures.append(
                        f"{sc.name}: compaction did not heal after the "
                        f"fault cleared")
                s.vars["tidb_tpu_engine"] = "off"
                cpu2 = s.query(q).rows
                s.vars["tidb_tpu_engine"] = eng_saved
                rows2, err2, _ = _run_statement(s, q)
                if err2 is not None or rows2 != cpu2:
                    failures.append(
                        f"{sc.name}: compacted generation diverged")
            elif sc.run == "write":
                write_seq += 1
                ins = (f"insert into cs_facts values "
                       f"(9000, {write_seq % 8}, 'w{write_seq}', 0.0)")
                _, err, dt = _run_statement(s, ins)
                if dt > DEADLINE_S:
                    slow += 1
                    failures.append(f"{sc.name}: insert took {dt:.1f}s")
                if err is not None:
                    errors += 1
                else:
                    base_count += 1
                failpoint.disable_all()
                now = s.query("select count(*) from cs_facts").scalar()
                if now != base_count:
                    wrong += 1
                    failures.append(
                        f"{sc.name}: NON-ATOMIC WRITE "
                        f"(count {now} != expected {base_count})")
            elif sc.run == "ddl":
                _, err, dt = _run_statement(
                    s, "create unique index cs_uk on cs_facts (c)")
                if err is None:
                    # injected fault didn't stop it — clean up
                    s.execute("drop index cs_uk on cs_facts")
                else:
                    errors += 1
                if dt > DEADLINE_S:
                    slow += 1
                    failures.append(f"{sc.name}: ddl took {dt:.1f}s")
            elif sc.run in ("backup", "restore"):
                import tempfile
                with tempfile.TemporaryDirectory() as d:
                    if sc.run == "restore":
                        # backup runs CLEAN (only restore-table is armed):
                        # the restore then re-applies identical data, so a
                        # partial restore is detectable as count drift
                        s.query(f"backup to '{d}/bk'")
                        stmt = f"restore from '{d}/bk'"
                    else:
                        stmt = f"backup to '{d}/bk'"
                    _, err, dt = _run_statement(s, stmt)
                    if err is not None:
                        errors += 1
                    if dt > DEADLINE_S:
                        slow += 1
                        failures.append(
                            f"{sc.name}: {sc.run} took {dt:.1f}s")
        except BaseException as e:  # noqa: BLE001 — untyped escape = bug
            failures.append(
                f"{sc.name}: UNTYPED ERROR {type(e).__name__}: {e}")
        finally:
            # hits() survives disable (counters persist), so meter the
            # scenario's own coverage before clearing faults
            for site in ([sc.site] if sc.site else []) + list(sc.extra):
                if failpoint.hits(site) > 0:
                    reached.add(site)
            failpoint.disable_all()
            for k, v in saved.items():
                if v is None:
                    s.vars.pop(k, None)
                else:
                    s.vars[k] = v

        # the session must still work after every scenario
        after = s.query("select count(*) from cs_facts").scalar()
        if after != base_count:
            failures.append(f"{sc.name}: count drifted after scenario")
        if sc.run not in ("read", "recompile", "fused", "finalize",
                          "mesh-read", "mesh-agg", "mesh-join"):
            # mutating scenarios move the goalposts: refresh the oracle
            oracle = {q: s.query(q).rows for q in oracle_qs}
            base_count = after
        results.append({"scenario": sc.name, "site": sc.site,
                        "errors": errors, "wrong": wrong, "slow": slow})
        if verbose:
            print(f"  {sc.name:45s} errors={errors} wrong={wrong}")

    unreached = sorted(set(failpoint.catalog()) - reached)
    # the coverage GATE: a cold site the run was supposed to exercise.
    # Without a mesh, mesh-only sites are exempt (a single-process
    # workload cannot trace an exchange); mesh_only conversely gates only
    # the distributed sites (the CPU scenarios were skipped on purpose).
    exempt = set()
    if not mesh:
        exempt = failpoint.mesh_only_sites()
    elif mesh_only:
        exempt = set(failpoint.catalog()) - failpoint.mesh_only_sites()
    gated_unreached = sorted(set(unreached) - exempt)
    report = {"scenarios": len(results), "results": results,
              "failures": failures, "coverage": coverage,
              "unreached": unreached,
              "gated_unreached": gated_unreached}
    eng.close()
    return report


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="chaos_sweep")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--mesh", type=int, default=0, metavar="N",
                    help="also run the distributed scenarios over an "
                         "N-device forced host CPU mesh")
    ap.add_argument("--mesh-only", action="store_true",
                    help="with --mesh: run ONLY the distributed scenarios")
    ap.add_argument("--list-sites", action="store_true",
                    help="print the failpoint catalog (site, description,"
                         " mesh-only tag) and exit without sweeping")
    args = ap.parse_args(argv)
    if args.list_sites:
        sites = list_sites()
        mesh_sites = failpoint.mesh_only_sites()
        for name in sorted(sites):
            tag = " [mesh-only]" if name in mesh_sites else ""
            print(f"{name}{tag}: {sites[name]}")
        print(f"{len(sites)} sites")
        return 0
    # drift lints FIRST: a drifting metric name/label or a failpoint
    # site missing from the catalog fails the sweep before any scenario
    # spends wall time (tools/check_metrics.py, tools/check_failpoints.py
    # — the latter is what keeps the coverage gate below trustworthy).
    # check_coverage is the device-coverage ratchet: it replays the 22
    # TPC-H-shaped coverage queries at small SF against COVERAGE.json,
    # so a planner/fragment change that silently de-fuses a pinned query
    # fails here before any chaos scenario runs.
    import importlib.util as _ilu
    _repo = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "..", "..")
    for _tool in ("check_metrics", "check_failpoints", "check_coverage"):
        _path = os.path.join(_repo, "tools", f"{_tool}.py")
        if not os.path.exists(_path):
            continue
        _spec = _ilu.spec_from_file_location(_tool, _path)
        _cm = _ilu.module_from_spec(_spec)
        _spec.loader.exec_module(_cm)
        _problems = _cm.run(_repo)
        if _problems:
            for p in _problems:
                print(p)
            print(f"chaos sweep: {_tool} lint failed "
                  f"({len(_problems)} violation(s))")
            return 1
        print(f"chaos sweep: {_tool} lint ok")
    t0 = time.monotonic()
    report = run_sweep(verbose=args.verbose, mesh=args.mesh or None,
                       mesh_only=args.mesh_only)
    dt = time.monotonic() - t0
    print(f"chaos sweep: {report['scenarios']} scenarios in {dt:.1f}s")
    print(f"  sites reached by clean workload: "
          f"{sorted(k for k, v in report['coverage'].items() if v)}")
    if report["unreached"]:
        print(f"  unreached sites: {report['unreached']}")
    if report["failures"]:
        print(f"FAILURES ({len(report['failures'])}):")
        for f in report["failures"]:
            print(f"  - {f}")
        return 1
    if report["gated_unreached"]:
        print(f"COVERAGE GATE: sites this run should have reached stayed "
              f"cold: {report['gated_unreached']}")
        return 1
    print("OK — every fault produced a correct result or a typed error")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
