"""Chaos / failpoint sweep: fault-inject every registered site under a
live workload and assert the lifecycle contract — every statement either
returns the oracle answer or raises a TYPED TiDBTPUError, within a
deadline; writes are atomic (COUNT advances exactly when the INSERT
succeeded); the session stays usable afterwards. Never a hang, never
silent corruption (ref: the reference's failpoint-enabled CI runs,
pingcap/failpoint + tests/realtikvtest).

Runnable three ways:

    python -m tidb_tpu.tools.chaos_sweep          # CLI, nonzero on fail
    python tools/chaos_sweep.py                   # repo-root wrapper
    pytest -m chaos                               # via tests/test_guardrails

The sweep builds its fixture CLEANLY first (faults off), records oracle
results, then runs one scenario per fault. Each scenario is
(site, fault, workload): read workloads re-check every query against the
oracle; write workloads re-count the table. failpoint.counting() meters
which sites the workload actually reached, so a refactor that silently
moves a site out of the hot path shows up as lost coverage."""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from tidb_tpu.errors import (ExecutionError, MemoryQuotaExceeded,
                             TiDBTPUError, TxnError)
from tidb_tpu.util import failpoint

# every statement must finish (result or typed error) inside this
DEADLINE_S = 30.0

QUERIES = [
    "select count(*), sum(a) from cs_facts",
    "select b, count(*) from cs_facts group by b order by b",
    "select d.name, count(*) from cs_facts f join cs_dim d "
    "on f.b = d.id group by d.name order by d.name",
    "select a from cs_facts order by a limit 5",
    # high-cardinality group key: under a squeezed quota this one is what
    # drives the agg's spill container (thousands of string groups)
    "select c, count(*) from cs_facts group by c order by c limit 3",
]


def _retryable_txn(msg: str) -> TxnError:
    e = TxnError(msg)
    e.retryable = True
    return e


class Scenario:
    def __init__(self, name: str, site: Optional[str], enable_kw: dict,
                 run: str = "read", vars: Optional[Dict[str, str]] = None,
                 extra: Optional[Dict[str, dict]] = None):
        self.name = name
        self.site = site
        self.enable_kw = enable_kw
        self.run = run               # read | write | ddl | backup
        self.vars = vars or {}
        self.extra = extra or {}     # additional site → enable kwargs


def _scenarios() -> List[Scenario]:
    return [
        # -- CPU pipeline faults ------------------------------------------
        Scenario("scan transient fault", "scan-next",
                 dict(raise_=ExecutionError("chaos: scan-next"), times=1)),
        Scenario("scan fault after warmup", "scan-next",
                 dict(raise_=ExecutionError("chaos: scan-late"),
                      after_hits=2, times=1)),
        Scenario("scan flaky one-in-3", "scan-next",
                 dict(raise_=ExecutionError("chaos: scan-flaky"),
                      one_in=3, times=2)),
        Scenario("tracker quota blown", "tracker-quota",
                 dict(raise_=MemoryQuotaExceeded("chaos: quota"),
                      after_hits=5, times=1)),
        # -- spill path (quota squeezed so the agg engages its spill) -----
        Scenario("spill write I/O error", "spill-write",
                 dict(raise_=ExecutionError("chaos: spill-write"), times=1),
                 vars={"tidb_mem_quota_query": "8000"}),
        Scenario("spill read-back error", "spill-read",
                 dict(raise_=ExecutionError("chaos: spill-read"), times=1),
                 vars={"tidb_mem_quota_query": "8000"}),
        # -- commit path ---------------------------------------------------
        Scenario("commit hard conflict", "store-commit",
                 dict(raise_=TxnError("chaos: conflict"), times=1),
                 run="write"),
        Scenario("commit transient conflict (heals)", "commit-conflict",
                 dict(raise_=_retryable_txn("chaos: transient"), times=2),
                 run="write"),
        Scenario("commit retry budget exhausted", "commit-conflict",
                 dict(raise_=_retryable_txn("chaos: hot key")),
                 run="write",
                 extra={"backoff-sleep": dict(value="skip")}),
        # -- device path (engine forced on; CPU backend still JITs) -------
        Scenario("device fragment crash → CPU fallback", "device-fragment",
                 dict(raise_=RuntimeError("chaos: device down"), times=9),
                 vars={"tidb_tpu_engine": "on",
                       "tidb_tpu_row_threshold": "0"}),
        Scenario("HBM upload failure → CPU fallback", "device-transfer",
                 dict(raise_=RuntimeError("chaos: transfer"), times=9),
                 vars={"tidb_tpu_engine": "on",
                       "tidb_tpu_row_threshold": "0"}),
        Scenario("host fetch interrupted", "host-fetch",
                 dict(raise_=ExecutionError("chaos: host-fetch"), times=9),
                 vars={"tidb_tpu_engine": "on",
                       "tidb_tpu_row_threshold": "0"}),
        # -- DDL -----------------------------------------------------------
        Scenario("unique backfill dies mid-reorg", "index-backfill",
                 dict(raise_=ExecutionError("chaos: backfill"), times=1),
                 run="ddl"),
        # -- tools ---------------------------------------------------------
        Scenario("backup dies between tables", "backup-table",
                 dict(raise_=TiDBTPUError("chaos: backup"), times=1),
                 run="backup"),
        Scenario("restore dies between tables", "restore-table",
                 dict(raise_=TiDBTPUError("chaos: restore"), times=1),
                 run="restore"),
    ]


def _run_statement(session, sql: str):
    """→ (rows|None, error|None, elapsed). Non-TiDBTPUError escapes —
    that IS a sweep failure."""
    t0 = time.monotonic()
    try:
        rs = session.query(sql)
        return rs.rows, None, time.monotonic() - t0
    except TiDBTPUError as e:
        return None, e, time.monotonic() - t0


def run_sweep(verbose: bool = False) -> dict:
    from tidb_tpu.session import Engine
    failpoint.disable_all()
    eng = Engine()
    s = eng.new_session()

    # fixture FIRST, faults off — the oracle must be trustworthy
    s.execute("create table cs_dim (id int, name varchar(16))")
    s.execute("create table cs_facts (a int, b int, c varchar(24))")
    dim = ", ".join(f"({i}, 'name{i:02d}')" for i in range(8))
    s.execute(f"insert into cs_dim values {dim}")
    for base in range(0, 4000, 500):
        vals = ", ".join(
            f"({(i * 37) % 997 - 200}, {i % 8}, 'payload-{i:05d}')"
            for i in range(base, base + 500))
        s.execute(f"insert into cs_facts values {vals}")

    # coverage meter: which sites does the clean workload even reach?
    failpoint.reset_counters()
    with failpoint.counting():
        for q in QUERIES:
            s.query(q)
        s.execute("insert into cs_facts values (1, 1, 'probe')")
    coverage = failpoint.counters()

    # oracle recorded AFTER the probe write; re-recorded after every
    # mutating scenario, so "correct result" always means "what a clean
    # run over the CURRENT data returns"
    oracle = {q: s.query(q).rows for q in QUERIES}
    base_count = s.query("select count(*) from cs_facts").scalar()

    failures: List[str] = []
    results: List[dict] = []
    reached = {k for k, v in coverage.items() if v > 0}
    write_seq = 0

    for sc in _scenarios():
        saved = {k: s.vars.get(k) for k in sc.vars}
        s.vars.update(sc.vars)
        if sc.site is not None:
            failpoint.enable(sc.site, **sc.enable_kw)
        for site, kw in sc.extra.items():
            failpoint.enable(site, **kw)
        errors, wrong, slow = 0, 0, 0
        try:
            if sc.run == "read":
                for q in QUERIES:
                    rows, err, dt = _run_statement(s, q)
                    if dt > DEADLINE_S:
                        slow += 1
                        failures.append(f"{sc.name}: {q!r} took {dt:.1f}s")
                    if err is not None:
                        errors += 1
                    elif rows != oracle[q]:
                        wrong += 1
                        failures.append(
                            f"{sc.name}: {q!r} SILENT WRONG RESULT")
            elif sc.run == "write":
                write_seq += 1
                ins = (f"insert into cs_facts values "
                       f"(9000, {write_seq % 8}, 'w{write_seq}')")
                _, err, dt = _run_statement(s, ins)
                if dt > DEADLINE_S:
                    slow += 1
                    failures.append(f"{sc.name}: insert took {dt:.1f}s")
                if err is not None:
                    errors += 1
                else:
                    base_count += 1
                failpoint.disable_all()
                now = s.query("select count(*) from cs_facts").scalar()
                if now != base_count:
                    wrong += 1
                    failures.append(
                        f"{sc.name}: NON-ATOMIC WRITE "
                        f"(count {now} != expected {base_count})")
            elif sc.run == "ddl":
                _, err, dt = _run_statement(
                    s, "create unique index cs_uk on cs_facts (c)")
                if err is None:
                    # injected fault didn't stop it — clean up
                    s.execute("drop index cs_uk on cs_facts")
                else:
                    errors += 1
                if dt > DEADLINE_S:
                    slow += 1
                    failures.append(f"{sc.name}: ddl took {dt:.1f}s")
            elif sc.run in ("backup", "restore"):
                import tempfile
                with tempfile.TemporaryDirectory() as d:
                    if sc.run == "restore":
                        # backup runs CLEAN (only restore-table is armed):
                        # the restore then re-applies identical data, so a
                        # partial restore is detectable as count drift
                        s.query(f"backup to '{d}/bk'")
                        stmt = f"restore from '{d}/bk'"
                    else:
                        stmt = f"backup to '{d}/bk'"
                    _, err, dt = _run_statement(s, stmt)
                    if err is not None:
                        errors += 1
                    if dt > DEADLINE_S:
                        slow += 1
                        failures.append(
                            f"{sc.name}: {sc.run} took {dt:.1f}s")
        except BaseException as e:  # noqa: BLE001 — untyped escape = bug
            failures.append(
                f"{sc.name}: UNTYPED ERROR {type(e).__name__}: {e}")
        finally:
            # hits() survives disable (counters persist), so meter the
            # scenario's own coverage before clearing faults
            for site in ([sc.site] if sc.site else []) + list(sc.extra):
                if failpoint.hits(site) > 0:
                    reached.add(site)
            failpoint.disable_all()
            for k, v in saved.items():
                if v is None:
                    s.vars.pop(k, None)
                else:
                    s.vars[k] = v

        # the session must still work after every scenario
        after = s.query("select count(*) from cs_facts").scalar()
        if after != base_count:
            failures.append(f"{sc.name}: count drifted after scenario")
        if sc.run != "read":
            # mutating scenarios move the goalposts: refresh the oracle
            oracle = {q: s.query(q).rows for q in QUERIES}
            base_count = after
        results.append({"scenario": sc.name, "site": sc.site,
                        "errors": errors, "wrong": wrong, "slow": slow})
        if verbose:
            print(f"  {sc.name:45s} errors={errors} wrong={wrong}")

    unreached = sorted(set(failpoint.catalog()) - reached)
    report = {"scenarios": len(results), "results": results,
              "failures": failures, "coverage": coverage,
              "unreached": unreached}
    eng.close()
    return report


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="chaos_sweep")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    t0 = time.monotonic()
    report = run_sweep(verbose=args.verbose)
    dt = time.monotonic() - t0
    print(f"chaos sweep: {report['scenarios']} scenarios in {dt:.1f}s")
    print(f"  sites reached by clean workload: "
          f"{sorted(k for k, v in report['coverage'].items() if v)}")
    if report["unreached"]:
        print(f"  unreached sites (need their own scenario/workload): "
              f"{report['unreached']}")
    if report["failures"]:
        print(f"FAILURES ({len(report['failures'])}):")
        for f in report["failures"]:
            print(f"  - {f}")
        return 1
    print("OK — every fault produced a correct result or a typed error")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
