"""information_schema virtual tables (ref: infoschema/tables.go — the
reference exposes ~60 memtables; these are the core inspection set).

Each table is a (schema, rows-closure) pair: rows materialize at
execution time from the live catalog/storage/observability state, so a
cached plan still reads fresh data. The reference computes its memtables
the same way (infoschema retrievers fill chunks on demand)."""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from tidb_tpu import types as T
from tidb_tpu.errors import UnknownTableError

# name → (column name, type) list + row builder(session) → rows
_TABLES: Dict[str, Tuple[List[Tuple[str, object]],
                         Callable[[object], List[tuple]]]] = {}


def register(name: str, columns):
    def deco(fn):
        _TABLES[name.lower()] = (columns, fn)
        return fn
    return deco


def lookup(name: str):
    hit = _TABLES.get(name.lower())
    if hit is None:
        raise UnknownTableError(
            f"Unknown table 'information_schema.{name}'")
    return hit


def table_names() -> List[str]:
    return sorted(_TABLES)


def _user_tables(session):
    return [t for t in session.engine.catalog.info_schema.list_tables()
            if not t.name.startswith("#")]


@register("tables", [("TABLE_SCHEMA", T.varchar()),
                     ("TABLE_NAME", T.varchar()),
                     ("TABLE_ROWS", T.bigint()),
                     ("TABLE_ID", T.bigint()),
                     ("REGIONS", T.bigint())])
def _tables(session):
    stats = session.engine.store.stats()
    out = []
    for t in _user_tables(session):
        regions, live = stats.get(t.id, (0, 0))
        out.append(("test", t.name, live, t.id, regions))
    return out


@register("columns", [("TABLE_NAME", T.varchar()),
                      ("COLUMN_NAME", T.varchar()),
                      ("ORDINAL_POSITION", T.bigint()),
                      ("IS_NULLABLE", T.varchar()),
                      ("DATA_TYPE", T.varchar()),
                      ("COLUMN_KEY", T.varchar())])
def _columns(session):
    out = []
    for t in _user_tables(session):
        for i, c in enumerate(t.columns):
            out.append((t.name, c.name, i + 1,
                        "YES" if c.ftype.nullable else "NO",
                        c.ftype.kind.value,
                        "PRI" if c.primary_key else ""))
    return out


@register("statistics", [("TABLE_NAME", T.varchar()),
                         ("INDEX_NAME", T.varchar()),
                         ("SEQ_IN_INDEX", T.bigint()),
                         ("COLUMN_NAME", T.varchar()),
                         ("NON_UNIQUE", T.bigint())])
def _statistics(session):
    out = []
    for t in _user_tables(session):
        if t.primary_key:
            for i, c in enumerate(t.primary_key):
                out.append((t.name, "PRIMARY", i + 1, c, 0))
        for ix in t.indexes:
            for i, c in enumerate(ix.columns):
                out.append((t.name, ix.name, i + 1, c,
                            0 if ix.unique else 1))
    return out


@register("user_privileges", [("GRANTEE", T.varchar()),
                              ("PRIVILEGE_TYPE", T.varchar()),
                              ("SCOPE", T.varchar())])
def _user_privileges(session):
    auth = session.engine.auth
    out = []
    with auth._lock:
        grants = {u: {k: set(v) for k, v in g.items()}
                  for u, g in auth.grants.items()}
    for user, scopes in sorted(grants.items()):
        for (db, tbl), privs in sorted(scopes.items()):
            for p in sorted(privs):
                out.append((f"'{user}'@'%'", p, f"{db}.{tbl}"))
    return out


@register("session_variables", [("VARIABLE_NAME", T.varchar()),
                                ("VARIABLE_VALUE", T.varchar())])
def _session_variables(session):
    return sorted((k, str(v)) for k, v in session.vars.items())


@register("processlist", [("ID", T.bigint()),
                          ("USER", T.varchar()),
                          ("TIME", T.double()),
                          ("INFO", T.varchar()),
                          ("ESCALATIONS", T.varchar()),
                          ("QUEUE_WAIT_MS", T.double())])
def _processlist(session):
    # same source as SHOW PROCESSLIST: every live connection (idle ones
    # included), each with ITS OWN user — not the querying session's —
    # and, like SHOW PROCESSLIST, only the caller's own threads unless
    # they hold the global PROCESS privilege.
    # ESCALATIONS is the running statement's capacity-ladder summary
    # (util/escalation.py): recompiles, exact resizes, shard retries,
    # degraded-mesh re-dispatches — live observability for "why is this
    # query recompiling". QUEUE_WAIT_MS is the statement's cumulative
    # device-scheduler admission wait (executor/scheduler.py) — live
    # observability for "is this query running or queued".
    from tidb_tpu.util.guard import PROCESS_REGISTRY
    see_all = session.engine.auth.has_global(session.user, "PROCESS")
    return sorted(
        (cid, user or "",
         round(guard.elapsed(), 3) if guard is not None else 0.0,
         guard.sql if guard is not None else None,
         guard.escalation.summary() if guard is not None else "",
         round(getattr(guard, "queue_wait_s", 0.0) * 1000.0, 3)
         if guard is not None else 0.0)
        for cid, user, guard, _killed in PROCESS_REGISTRY.snapshot()
        if see_all or user in (None, session.user))


@register("table_storage_stats", [("TABLE_NAME", T.varchar()),
                                  ("LIVE_ROWS", T.bigint()),
                                  ("DEAD_ROWS", T.bigint()),
                                  ("REGION_COUNT", T.bigint())])
def _table_storage_stats(session):
    out = []
    for t in _user_tables(session):
        live, dead, regions = session.engine.store.gc_stats(t.id)
        out.append((t.name, live, dead, regions))
    return out


@register("engines", [("ENGINE", T.varchar()),
                      ("SUPPORT", T.varchar()),
                      ("COMMENT", T.varchar())])
def _engines(session):
    import jax
    backend = jax.default_backend()
    return [("tidb_tpu_cpu", "YES", "vectorized numpy volcano"),
            ("tidb_tpu_device", "DEFAULT" if backend == "tpu" else "YES",
             f"fused XLA fragments ({backend})")]


@register("partitions", [("TABLE_NAME", T.varchar()),
                         ("PARTITION_NAME", T.varchar()),
                         ("PARTITION_ORDINAL_POSITION", T.bigint()),
                         ("PARTITION_METHOD", T.varchar()),
                         ("PARTITION_EXPRESSION", T.varchar()),
                         ("PARTITION_DESCRIPTION", T.varchar()),
                         ("TABLE_ROWS", T.bigint())])
def _partitions(session):
    """Ref: infoschema/tables.go tablePartitionsCols — one row per
    partition with live row counts from its region set."""
    rows = []
    snap = session.engine.store.snapshot()
    for t in _user_tables(session):
        p = getattr(t, "partition", None)
        if p is None:
            rows.append((t.name, None, None, None, None, None,
                         snap.table_data(t.id).live_rows
                         if snap.has_table(t.id) else 0))
            continue
        counts = {k: 0 for k in range(p.n_parts)}
        if snap.has_table(t.id):
            for r, alive in snap.scan(t.id):
                if r.part is not None:
                    counts[r.part] = counts.get(r.part, 0) + \
                        int(alive.sum())
        for i, name in enumerate(p.names):
            if p.kind == "range":
                b = p.bounds[i]
                desc = "MAXVALUE" if b is None else str(b)
            else:
                desc = None
            rows.append((t.name, name, i + 1, p.kind.upper(), p.column,
                         desc, counts.get(i, 0)))
    return rows


@register("statements_summary",
          [("DIGEST_TEXT", T.varchar()),
           ("EXEC_COUNT", T.bigint()),
           ("SUM_LATENCY_S", T.double()),
           ("AVG_LATENCY_S", T.double()),
           ("MAX_LATENCY_S", T.double()),
           ("ROWS_SENT", T.bigint()),
           ("ENGINE", T.varchar()),
           ("DEVICE_SECONDS", T.double()),
           ("H2D_BYTES", T.bigint()),
           ("D2H_BYTES", T.bigint()),
           ("SCAN_BYTES", T.bigint()),
           ("H2D_LOGICAL_BYTES", T.bigint()),
           ("SCAN_LOGICAL_BYTES", T.bigint()),
           ("COMPILES", T.bigint()),
           ("PROGRAMS_LAUNCHED", T.bigint()),
           ("FUSED_PIPELINES", T.bigint()),
           ("SPECIALIZATION_HITS", T.bigint()),
           ("SLABS_SKIPPED", T.bigint()),
           ("H2D_SKIPPED_BYTES", T.bigint()),
           ("QUEUE_WAIT_S", T.double()),
           ("QUEUE_WAITS", T.bigint()),
           ("QUEUE_P50_MS", T.double()),
           ("QUEUE_P99_MS", T.double()),
           ("SCHED_CLASS", T.varchar())])
def _statements_summary(session):
    """TopSQL-style per-digest device-time attribution (ref:
    util/stmtsummary — here extended with the PhaseTimer ledger): every
    counter is the exact sum over that digest's statements, so a row's
    byte/compile columns equal the sum of its EXPLAIN ANALYZE totals."""
    from tidb_tpu.util.observability import REGISTRY
    return [(p["digest"], p["count"], p["sum_s"], p["avg_s"], p["max_s"],
             p["rows"], p["engine"], p["device_s"], p["h2d_bytes"],
             p["d2h_bytes"], p["scan_bytes"], p["h2d_logical_bytes"],
             p["scan_logical_bytes"], p["compiles"],
             p["programs_launched"], p["fused_pipelines"],
             p["specialization_hits"],
             p.get("slabs_skipped", 0), p.get("h2d_skipped_bytes", 0),
             p["queue_wait_s"], p["queue_waits"], p["queue_p50_ms"],
             p["queue_p99_ms"], p.get("sched_class"))
            for p in REGISTRY.summary_profiles()]


@register("slow_query", [("TIME", T.varchar()),
                         ("QUERY_TIME_S", T.double()),
                         ("DEVICE_SECONDS", T.double()),
                         ("QUEUE_WAIT_MS", T.double()),
                         ("H2D_BYTES", T.bigint()),
                         ("COMPILES", T.bigint()),
                         ("ROWS_SENT", T.bigint()),
                         ("ENGINE", T.varchar()),
                         ("QUERY", T.varchar())])
def _slow_query(session):
    """The slow-log ring (ref: infoschema slow_query memtable over the
    slow log file) with per-entry device attribution."""
    from tidb_tpu.util.observability import REGISTRY
    return REGISTRY.slow_rows_full()


@register("table_storage", [("TABLE_NAME", T.varchar()),
                            ("COLUMN_NAME", T.varchar()),
                            ("LAYOUT", T.varchar()),
                            ("PHYSICAL_BYTES", T.bigint()),
                            ("LOGICAL_BYTES", T.bigint()),
                            ("ZONE_MAP_SLABS", T.bigint()),
                            ("ZONE_MAP_MIN", T.varchar()),
                            ("ZONE_MAP_MAX", T.varchar()),
                            ("ZONE_MAP_NULLS", T.bigint())])
def _table_storage(session):
    """Per-(table, column) device residency of the HBM column cache:
    the physical (compressed) bytes actually held in HBM next to the
    raw-equivalent logical bytes, plus the layout signature that
    produced them ('raw', 'pack:wW:rREF:...', 'dict:wW:...'). The
    physical column reconciles with statements_summary's H2D/SCAN
    counters: a cold scan's H2D_BYTES is exactly the physical bytes of
    the columns it uploaded. The ZONE_MAP_* columns expose the
    encode-time per-slab statistics slab pruning consults (slab count,
    global min/max over known slabs, total null count)."""
    from tidb_tpu.executor import device_cache
    names = {t.id: t.name for t in _user_tables(session)}
    cols = {t.id: [c.name for c in t.columns] for t in _user_tables(session)}
    out = []
    for r in device_cache.storage_stats(id(session.engine.store)):
        tid = r["table_id"]
        cnames = cols.get(tid, [])
        cname = cnames[r["column"]] if r["column"] < len(cnames) \
            else str(r["column"])
        out.append((names.get(tid, str(tid)), cname, r["layout"],
                    r["physical_bytes"], r["logical_bytes"],
                    r["zone_map_slabs"],
                    None if r["zone_map_min"] is None
                    else str(r["zone_map_min"]),
                    None if r["zone_map_max"] is None
                    else str(r["zone_map_max"]),
                    r["zone_map_nulls"]))
    return sorted(out)


@register("engine_metrics", [("METRIC", T.varchar()),
                             ("LABELS", T.varchar()),
                             ("VALUE", T.double())])
def _engine_metrics(session):
    """Every registry counter and histogram (bucket/count/sum rows
    included) as SQL — the metrics_schema analog, so percentiles can be
    derived without scraping /metrics."""
    from tidb_tpu.util.observability import REGISTRY
    return REGISTRY.metric_rows()


@register("views", [("TABLE_NAME", T.varchar()),
                    ("VIEW_DEFINITION", T.varchar()),
                    ("IS_UPDATABLE", T.varchar()),
                    ("SECURITY_TYPE", T.varchar())])
def _views(session):
    """Ref: infoschema/tables.go viewsCols."""
    return [(v.name, v.sql, "NO", "DEFINER")
            for v in session.engine.catalog.info_schema.list_views()]
