"""Resumable DDL reorganization (ref: ddl/reorg.go:193 reorg watermark,
ddl/backfilling.go backfill workers).

In this engine, secondary indexes are lazy sorted snapshot views
(executor/index_scan.py), so the only eager cost of CREATE INDEX is the
UNIQUE validation scan — which at SF=10 scale touches 60M rows and used
to be all-or-nothing in one call. This module chunks it per storage
region: each region's sorted key run persists next to a tools.Checkpoint
(the same crash-resume marker backup/restore uses), so a backfill killed
mid-scan resumes after the last finished region instead of restarting
from zero — the single-process analog of the reference's reorg handle
persisting its next-key watermark into the job record.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from tidb_tpu.errors import DuplicateKeyError


DEFAULT_REORG_BATCH = 1 << 16     # ddl/backfilling.go batch-size analog


def unique_backfill(session, info, cols: List[str], name: str,
                    ckpt_dir: Optional[str] = None) -> None:
    """Chunked CREATE UNIQUE INDEX validation over a pinned snapshot.

    Work splits into tidb_ddl_reorg_batch_size row batches. With
    `ckpt_dir` (session var tidb_ddl_reorg_checkpoint_dir), each batch's
    deduped key run is written to disk and marked in a Checkpoint AFTER
    it lands; a rerun skips finished batches and reloads their runs, so
    a killed backfill resumes after the last completed batch. The merge
    at the end catches duplicates that span batches. Raises
    DuplicateKeyError exactly like the reference's write-reorg dup check
    (ddl/backfilling.go)."""
    from tidb_tpu.executor.scan import align_chunk_to_schema
    from tidb_tpu.session import _key_tuples
    from tidb_tpu.util import failpoint

    col_of = {c.name.lower(): i for i, c in enumerate(info.columns)}
    idxs = [col_of[c.lower()] for c in cols]
    snap = session._read_view_snapshot()
    if not snap.has_table(info.id):
        return None
    batch = int(session.vars.get("tidb_ddl_reorg_batch_size",
                                 DEFAULT_REORG_BATCH))
    ck = None
    if ckpt_dir:
        from tidb_tpu.tools import Checkpoint
        os.makedirs(ckpt_dir, exist_ok=True)
        ck = Checkpoint(os.path.join(ckpt_dir, f"reorg_{name}.json"),
                        op=f"create_index:{info.name}:{name}")

    def cleanup():
        if ck is not None:
            ck.finish()
            for pth in run_paths:
                if os.path.exists(pth):
                    os.remove(pth)

    runs: List[np.ndarray] = []
    run_paths: List[str] = []
    for i, (region, alive) in enumerate(snap.scan(info.id)):
        ch = None
        keys = None
        n_rows = region.chunk.num_rows
        n_alive = int(np.asarray(alive).sum())
        for b0 in range(0, n_rows, max(batch, 1)):
            b1 = min(b0 + max(batch, 1), n_rows)
            # the unit key fingerprints the region's LIVE row count too:
            # a delete between runs flips alive bits without changing
            # n_rows, and must invalidate the persisted run
            unit = f"part:{i}:{b0}:{n_rows}:{n_alive}"
            run_path = os.path.join(
                ckpt_dir, f"reorg_{name}.run{i}_{b0}.npy") \
                if ckpt_dir else None
            if ck is not None and ck.is_done(unit):
                runs.append(np.load(run_path, allow_pickle=True))
                run_paths.append(run_path)
                continue
            if keys is None:      # materialize the region lazily, once
                ch = align_chunk_to_schema(region.chunk, info)
                keys = _key_tuples(ch, idxs)
            live_keys = sorted(keys[ri] for ri in range(b0, b1)
                               if alive[ri] and keys[ri] is not None)
            for a, b in zip(live_keys, live_keys[1:]):
                if a == b:
                    # validation FAILED (not crashed): the job is over —
                    # drop the checkpoint so a later retry revalidates
                    # fresh data instead of replaying stale runs
                    cleanup()
                    raise DuplicateKeyError(
                        f"Duplicate entry {a!r} for key '{name}'")
            arr = np.empty(len(live_keys), dtype=object)
            arr[:] = live_keys
            if run_path:
                np.save(run_path, arr, allow_pickle=True)
                run_paths.append(run_path)
            runs.append(arr)
            if ck is not None:
                ck.mark(unit)
            # test seam: die between batches (the reorg.go:193 "owner
            # crash between batches" scenario) — the marked checkpoint
            # makes the NEXT run resume after this batch
            failpoint.inject("index-backfill")
    # cross-batch duplicates: merge the (already sorted) runs
    merged = sorted(k for run in runs for k in run)
    for a, b in zip(merged, merged[1:]):
        if a == b:
            cleanup()
            raise DuplicateKeyError(
                f"Duplicate entry {a!r} for key '{name}'")
    cleanup()
    # the TableData identity this pass validated — the caller loops
    # until it matches the live table (online-DDL quiescence check)
    return snap.table_data(info.id)
