"""Minimal MySQL-protocol client (text path).

The reference ships tools (dumpling, br) that reach the cluster through
stock MySQL drivers; no driver ships in this image, so this is the
in-repo equivalent — handshake with mysql_native_password, COM_QUERY,
text resultset decoding. Used by tidb_tpu.tools (dump/CSV CLIs) and
available as a programmatic driver for the wire server.

Resilience: with auto_reconnect (default on), a connection the server
closed (KILL <id>, restart) is re-established with exponential backoff
and the statement retried — but ONLY for read-only statements, where the
retry cannot double-apply work (go-sql-driver's ErrBadConn contract:
never auto-retry a write on an ambiguous connection death)."""

from __future__ import annotations

import hashlib
import socket
import struct
import time
from typing import List, Optional, Tuple


class ClientError(RuntimeError):
    def __init__(self, code: int, msg: str):
        super().__init__(f"ERROR {code}: {msg}")
        self.code = code


def _scramble(password: str, salt: bytes) -> bytes:
    if not password:
        return b""
    sha_pw = hashlib.sha1(password.encode()).digest()
    stage2 = hashlib.sha1(sha_pw).digest()
    mix = hashlib.sha1(salt + stage2).digest()
    return bytes(a ^ b for a, b in zip(sha_pw, mix))


# statements safe to replay on a fresh connection: no server-side state
# beyond session vars is at stake and re-running cannot double-apply
_RETRYABLE_PREFIXES = ("select", "show", "explain", "desc", "use")


def _is_retryable_stmt(sql: str) -> bool:
    return sql.lstrip().lower().startswith(_RETRYABLE_PREFIXES)


class Client:
    RECONNECT_ATTEMPTS = 4

    def __init__(self, host: str = "127.0.0.1", port: int = 4000,
                 user: str = "root", password: str = "",
                 timeout: float = 30.0, ssl: bool = False,
                 ssl_ca: str = None, auto_reconnect: bool = True):
        self._params = (host, port, user, password, timeout)
        self._ssl = ssl
        self._ssl_ca = ssl_ca
        self.auto_reconnect = auto_reconnect
        self.seq = 0
        self.sock = None
        self._connect()

    def _connect(self) -> None:
        host, port, user, password, timeout = self._params
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.seq = 0
        try:
            self._handshake(user, password)
        except BaseException:
            self.sock.close()     # caller never gets a half-open client
            raise

    def _reconnect_with_backoff(self) -> None:
        delay = 0.05
        last = None
        for _ in range(self.RECONNECT_ATTEMPTS):
            try:
                self.sock.close()
            except Exception:  # noqa: BLE001
                pass
            try:
                self._connect()
                return
            except (OSError, ClientError) as e:
                last = e
                time.sleep(delay)
                delay *= 2
        raise ClientError(2013, f"reconnect failed: {last}")

    # -- framing -------------------------------------------------------------
    def _recv(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            part = self.sock.recv(n - len(buf))
            if not part:
                raise ClientError(2013, "server closed connection")
            buf += part
        return buf

    def _read_packet(self) -> bytes:
        h = self._recv(4)
        ln = h[0] | (h[1] << 8) | (h[2] << 16)
        self.seq = (h[3] + 1) & 0xFF
        return self._recv(ln) if ln else b""

    def _write_packet(self, payload: bytes) -> None:
        self.sock.sendall(struct.pack("<I", len(payload))[:3]
                          + bytes([self.seq]) + payload)
        self.seq = (self.seq + 1) & 0xFF

    @staticmethod
    def _lenenc(data: bytes, i: int) -> Tuple[int, int]:
        c = data[i]
        if c < 251:
            return c, i + 1
        if c == 0xFC:
            return data[i + 1] | (data[i + 2] << 8), i + 3
        if c == 0xFD:
            return int.from_bytes(data[i + 1:i + 4], "little"), i + 4
        return int.from_bytes(data[i + 1:i + 9], "little"), i + 9

    # -- protocol ------------------------------------------------------------
    def _handshake(self, user: str, password: str) -> None:
        g = self._read_packet()
        if g and g[0] == 0xFF:
            code = struct.unpack("<H", g[1:3])[0]
            raise ClientError(code, g[9:].decode(errors="replace"))
        i = g.index(b"\x00", 1) + 1
        i += 4
        salt = g[i:i + 8]
        srv_caps = (g[i + 9] | (g[i + 10] << 8)
                    | (g[i + 12 + 2] << 16) | (g[i + 12 + 3] << 24)) \
            if len(g) >= i + 16 else 0
        i += 9 + 2 + 1 + 2 + 2 + 1 + 10
        salt += g[i:i + 12]
        token = _scramble(password, salt)
        caps = 0x0200 | 0x8000 | 0x1
        if self._ssl and not (srv_caps & 0x800):
            raise ClientError(2026, "server does not support SSL")
        if self._ssl:
            caps |= 0x800                      # CLIENT_SSL
            # SSLRequest, then upgrade the transport before the real
            # handshake response (the server mirrors this order)
            self._write_packet(struct.pack("<I", caps)
                               + struct.pack("<I", 1 << 24)
                               + bytes([0xFF]) + b"\x00" * 23)
            import ssl as _ssl_mod
            if self._ssl_ca:
                ctx = _ssl_mod.create_default_context(
                    cafile=self._ssl_ca)
                ctx.check_hostname = False
            else:
                ctx = _ssl_mod.SSLContext(_ssl_mod.PROTOCOL_TLS_CLIENT)
                ctx.check_hostname = False
                ctx.verify_mode = _ssl_mod.CERT_NONE
            self.sock = ctx.wrap_socket(self.sock)
        resp = (struct.pack("<I", caps) + struct.pack("<I", 1 << 24)
                + bytes([0xFF]) + b"\x00" * 23
                + user.encode() + b"\x00"
                + bytes([len(token)]) + token)
        self._write_packet(resp)
        ok = self._read_packet()
        if ok[0] != 0x00:
            code = struct.unpack("<H", ok[1:3])[0]
            raise ClientError(code, ok[9:].decode(errors="replace"))

    def query(self, sql: str) -> Tuple[List[str], List[Tuple]]:
        """→ (column names, rows) for queries; ([], []) for OK packets.
        Every value arrives as str or None (text protocol)."""
        try:
            return self._query_once(sql)
        except (OSError, ClientError) as e:
            dead = isinstance(e, OSError) or \
                getattr(e, "code", None) == 2013
            if not (dead and self.auto_reconnect):
                raise
            self._reconnect_with_backoff()
            if not _is_retryable_stmt(sql):
                # fresh connection, but the statement's fate on the dead
                # one is unknowable — surface it instead of re-applying
                raise ClientError(
                    2013, "connection lost; statement not retried "
                          "(not read-only)") from e
            return self._query_once(sql)

    def _query_once(self, sql: str) -> Tuple[List[str], List[Tuple]]:
        self.seq = 0
        self._write_packet(b"\x03" + sql.encode())
        first = self._read_packet()
        if first[0] == 0xFF:
            code = struct.unpack("<H", first[1:3])[0]
            raise ClientError(code, first[9:].decode(errors="replace"))
        if first[0] == 0x00:
            return [], []
        ncols, _ = self._lenenc(first, 0)
        names = []
        for _ in range(ncols):
            col = self._read_packet()
            i = 0
            parts = []
            for _f in range(6):
                ln, i = self._lenenc(col, i)
                parts.append(col[i:i + ln])
                i += ln
            names.append(parts[4].decode())
        assert self._read_packet()[0] == 0xFE
        rows: List[Tuple] = []
        while True:
            pkt = self._read_packet()
            if pkt and pkt[0] == 0xFE and len(pkt) < 9:
                break
            i = 0
            row = []
            while i < len(pkt):
                if pkt[i] == 0xFB:
                    row.append(None)
                    i += 1
                else:
                    ln, i = self._lenenc(pkt, i)
                    row.append(pkt[i:i + ln].decode())
                    i += ln
            rows.append(tuple(row))
        return names, rows

    def execute(self, sql: str) -> None:
        self.query(sql)

    def close(self) -> None:
        try:
            self.seq = 0
            self._write_packet(b"\x01")
        except Exception:  # noqa: BLE001
            pass
        finally:
            self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
