"""In-memory columnar region store with snapshot reads + optimistic txns.

Ref: /root/reference/store/mockstore/unistore/ — the reference embeds a full
TiKV mock (badger MVCC, Percolator 2PC, region splits) so the whole SQL stack
runs in one process. The TPU-first re-design stores data COLUMNAR from the
start (the reference stores rows and re-columnarizes in every coprocessor
scan): a table is an append-only list of immutable Regions, each one Chunk of
up to REGION_ROWS rows plus a copy-on-write deletion bitmap. Regions are the
parallel-scan unit exactly like TiKV regions are the coprocessor-task unit
(store/copr/coprocessor.go:178) — and, later, the device-shard unit.

Concurrency model (ref: optimistic txns, session/txn.go + Percolator):
  * readers take an immutable Snapshot (region list + bitmap refs) — no locks;
  * writers stage inserts/deletes in a MemBuffer (ref: txn memBuffer) and
    apply atomically at commit under the store lock;
  * conflicts: first-committer-wins on row deletes (a row deleted by two
    overlapping txns raises TxnConflict for the second — the Percolator
    write-conflict analog).
"""

from __future__ import annotations

import itertools
import threading
import time as _time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.errors import DeadlockError, TxnError, UnknownTableError

REGION_ROWS = 1 << 16  # region split threshold (ref: TiKV region ~96MB)


@dataclass(frozen=True)
class Region:
    """One immutable slab of rows. `deleted` is copy-on-write: never mutated
    after publication, so snapshot readers are race-free. `part` tags the
    table partition every row of this region belongs to (INSERT routes
    rows so regions never mix partitions — region-level colocation is the
    pruning unit, the slab-native analog of a partition's own region set
    in table/tables/partition.go)."""

    id: int
    chunk: Chunk
    deleted: np.ndarray  # bool (n_rows,)
    part: Optional[int] = None

    @property
    def num_rows(self) -> int:
        return self.chunk.num_rows

    @property
    def live_rows(self) -> int:
        return int((~self.deleted).sum())


@dataclass(frozen=True)
class TableData:
    regions: Tuple[Region, ...]

    @property
    def live_rows(self) -> int:
        return sum(r.live_rows for r in self.regions)


class Snapshot:
    """Immutable point-in-time view (ref: kv.Snapshot, kv/kv.go:373)."""

    def __init__(self, tables: Dict[int, TableData], version: int,
                 store: "Store" = None):
        self._tables = tables
        self.version = version
        self.store = store        # owning engine's store (device-cache key)

    def table_data(self, table_id: int) -> TableData:
        td = self._tables.get(table_id)
        if td is None:
            raise UnknownTableError(f"no storage for table id {table_id}")
        return td

    def has_table(self, table_id: int) -> bool:
        return table_id in self._tables

    def scan(self, table_id: int, parts=None
             ) -> Iterable[Tuple[Region, np.ndarray]]:
        """Yield (region, alive_mask) pairs — the coprocessor-task stream.
        `parts` (a set of partition ordinals) SKIPS non-matching regions:
        region-level partition pruning, zero bytes touched for pruned
        partitions."""
        for r in self.table_data(table_id).regions:
            if parts is not None and r.part is not None \
                    and r.part not in parts:
                continue
            yield r, ~r.deleted


class Store:
    """The storage engine singleton (ref: kv.Storage, kv/kv.go:409)."""

    # MVCC history bounds (ref: store/gcworker safepoint discipline)
    MAX_HISTORY = 256
    GC_LIFE_SECONDS = 600.0

    def __init__(self):
        self._lock = threading.Lock()
        self._tables: Dict[int, TableData] = {}
        self._region_ids = itertools.count(1)
        self._version = 0
        self._open_txns = 0     # compaction defers while txns are open
        # version history for AS OF reads: (version, wall time, tables).
        # Region objects are immutable and shared, so an entry costs one
        # dict — the MVCC version chain without per-row versions
        self._history: List[Tuple[int, float, Dict[int, TableData]]] = [
            (0, _time.time(), {})]
        # pessimistic row locks: (table_id, region_id) → {row → txn_id}
        # (ref: the TiKV lock CF the pessimistic mode acquires through)
        self._locks: Dict[Tuple[int, int], Dict[int, int]] = {}
        # wait-for edges between blocked pessimistic txns: waiter → owner
        # (the deadlock detector's graph, unistore/tikv/detector.go:24)
        self._waits: Dict[int, int] = {}
        self._txn_seq = itertools.count(1)

    def _bump_locked(self) -> None:
        self._version += 1
        now = _time.time()
        self._history.append((self._version, now, dict(self._tables)))
        cutoff = now - self.GC_LIFE_SECONDS
        while len(self._history) > self.MAX_HISTORY or (
                len(self._history) > 1 and self._history[1][1] <= cutoff
                and self._history[0][1] < cutoff):
            self._history.pop(0)

    def snapshot_at(self, ts: float) -> Snapshot:
        """Historical read view: the newest version committed at or
        before `ts` (the tidb_snapshot / AS OF TIMESTAMP read path)."""
        with self._lock:
            best = None
            for v, t, tables in self._history:
                if t <= ts:
                    best = (v, tables)
                else:
                    break
            if best is None:
                raise TxnError(
                    "snapshot is older than the GC safepoint "
                    "(tidb_gc_life_time)")
            return Snapshot(dict(best[1]), best[0], self)

    # ---- pessimistic row locks -------------------------------------------
    def lock_rows(self, txn: "Transaction", table_id: int,
                  region_masks: Dict[int, np.ndarray],
                  timeout_s: float = 5.0) -> None:
        """Acquire row locks, waiting (bounded) on conflicting owners —
        SELECT ... FOR UPDATE / pessimistic-DML semantics. Lock-wait
        beyond the timeout raises the MySQL lock-wait error."""
        deadline = _time.time() + timeout_s
        while True:
            with self._lock:
                blocker = None
                for rid, mask in region_masks.items():
                    owners = self._locks.get((table_id, rid))
                    if not owners:
                        continue
                    for row in np.nonzero(mask)[0]:
                        o = owners.get(int(row))
                        if o is not None and o != txn.txn_id:
                            blocker = o
                            break
                    if blocker is not None:
                        break
                if blocker is None:
                    self._waits.pop(txn.txn_id, None)
                    for rid, mask in region_masks.items():
                        owners = self._locks.setdefault((table_id, rid), {})
                        for row in np.nonzero(mask)[0]:
                            owners[int(row)] = txn.txn_id
                        txn.locked.append((table_id, rid, mask.copy()))
                    return
                # wait-for edge + cycle walk (detector.go:Detect): if this
                # wait closes a cycle, the closing waiter aborts with
                # ER 1213 in milliseconds instead of stalling every txn
                # in the cycle to its full lock_wait_timeout
                self._waits[txn.txn_id] = blocker
                seen = set()
                cur = blocker
                while cur is not None and cur not in seen:
                    if cur == txn.txn_id:
                        self._waits.pop(txn.txn_id, None)
                        raise DeadlockError(
                            "Deadlock found when trying to get lock; "
                            "try restarting transaction")
                    seen.add(cur)
                    cur = self._waits.get(cur)
            if _time.time() >= deadline:
                with self._lock:
                    self._waits.pop(txn.txn_id, None)
                raise TxnError(
                    "Lock wait timeout exceeded; try restarting "
                    "transaction")
            _time.sleep(0.005)

    def release_entries(self, txn: "Transaction", entries) -> None:
        """Release a subset of a txn's lock entries (stale retry
        iterations of a pessimistic statement)."""
        with self._lock:
            self._release_entries_locked(txn, entries)

    def _release_entries_locked(self, txn, entries) -> None:
        for tid, rid, mask in entries:
            owners = self._locks.get((tid, rid))
            if not owners:
                continue
            for row in np.nonzero(mask)[0]:
                if owners.get(int(row)) == txn.txn_id:
                    del owners[int(row)]
            if not owners:
                del self._locks[(tid, rid)]

    def release_locks(self, txn: "Transaction") -> None:
        with self._lock:
            self._release_entries_locked(txn, txn.locked)
            txn.locked.clear()
            self._waits.pop(txn.txn_id, None)

    # ---- lifecycle -------------------------------------------------------
    def create_table(self, table_id: int) -> None:
        with self._lock:
            self._tables.setdefault(table_id, TableData(()))
            self._bump_locked()

    def drop_table(self, table_id: int) -> None:
        with self._lock:
            self._tables.pop(table_id, None)
            self._bump_locked()

    def truncate_table(self, table_id: int) -> None:
        with self._lock:
            if table_id not in self._tables:
                raise UnknownTableError(f"no storage for table id {table_id}")
            self._tables[table_id] = TableData(())
            self._bump_locked()

    # ---- reads -----------------------------------------------------------
    def snapshot(self) -> Snapshot:
        with self._lock:
            return Snapshot(dict(self._tables), self._version, self)

    @property
    def version(self) -> int:
        """Monotonic commit version — bumps on every applied write/DDL.
        The device cache stamps it on each generation (delta version)."""
        with self._lock:
            return self._version

    # ---- writes (autocommit fast path) -----------------------------------
    def append(self, table_id: int, chunk: Chunk,
               part: Optional[int] = None) -> None:
        """Append rows, splitting into REGION_ROWS regions."""
        with self._lock:
            self._append_locked(table_id, chunk, part)
            self._bump_locked()

    def _append_locked(self, table_id: int, chunk: Chunk,
                       part: Optional[int] = None) -> None:
        td = self._tables.get(table_id)
        if td is None:
            raise UnknownTableError(f"no storage for table id {table_id}")
        regions = list(td.regions)
        # top off the last region if it has headroom and is undeleted-pure
        for start in range(0, chunk.num_rows, REGION_ROWS):
            piece = chunk.slice(start, min(start + REGION_ROWS,
                                           chunk.num_rows))
            if (regions and regions[-1].num_rows + piece.num_rows
                    <= REGION_ROWS
                    and not regions[-1].deleted.any()
                    and regions[-1].part == part
                    and regions[-1].chunk.num_cols == piece.num_cols):
                # layouts must match: a region written before ADD COLUMN
                # keeps its narrow layout (padded at read); new rows with
                # the wider layout start a fresh region — and regions
                # never mix partitions
                last = regions[-1]
                merged = Chunk.concat([last.chunk, piece])
                regions[-1] = Region(last.id, merged,
                                     np.zeros(merged.num_rows, dtype=bool),
                                     part)
            else:
                regions.append(Region(next(self._region_ids), piece,
                                      np.zeros(piece.num_rows, dtype=bool),
                                      part))
        self._tables[table_id] = TableData(tuple(regions))

    GC_DEAD_RATIO = 0.5     # compact when half a table is tombstones

    def delete(self, table_id: int, region_masks: Dict[int, np.ndarray]) -> int:
        """Mark rows deleted; masks are keyed by region id. Returns count."""
        with self._lock:
            n = self._delete_locked(table_id, region_masks)
            self._maybe_compact_locked(table_id)
            self._bump_locked()
            return n

    def _maybe_compact_locked(self, table_id: int,
                              closing: int = 0) -> None:
        """GC (ref: store/gcworker/gc_worker.go — MVCC version GC; here
        tombstone reclamation): rewrite regions dropping deleted rows once
        the dead fraction crosses GC_DEAD_RATIO. Produces fresh TableData,
        so every identity-keyed cache (HBM tables, sorted indexes)
        invalidates for free."""
        if self._open_txns - closing > 0:
            # an open txn may hold staged deletes against current region
            # ids; rewriting them would abort it spuriously (GC safepoint
            # discipline, gc_worker.go — don't GC under active readers);
            # `closing` excludes the txn whose commit is applying now
            return
        td = self._tables.get(table_id)
        if td is None or not td.regions:
            return
        total = sum(r.num_rows for r in td.regions)
        dead = sum(int(r.deleted.sum()) for r in td.regions)
        if total == 0 or dead / total < self.GC_DEAD_RATIO:
            return
        regions = []
        for r in td.regions:
            if not r.deleted.any():
                regions.append(r)
                continue
            alive = ~r.deleted
            if not alive.any():
                continue            # fully dead region vanishes
            kept = r.chunk.take(np.nonzero(alive)[0])
            regions.append(Region(next(self._region_ids), kept,
                                  np.zeros(kept.num_rows, dtype=bool),
                                  r.part))
        self._tables[table_id] = TableData(tuple(regions))

    def drop_partition_rows(self, table_id: int, ordinal: int,
                            remap=None) -> int:
        """TRUNCATE/DROP PARTITION: remove every region tagged `ordinal`
        wholesale (no tombstones — the partition IS the region set), and
        optionally re-tag surviving regions (DROP shifts later ordinals).
        Returns rows removed."""
        with self._lock:
            td = self._tables.get(table_id)
            if td is None:
                raise UnknownTableError(f"no storage for table {table_id}")
            kept = []
            removed = 0
            for r in td.regions:
                if r.part == ordinal:
                    removed += r.live_rows
                    continue
                if remap is not None and r.part is not None:
                    new_part = remap.get(r.part, r.part)
                    if new_part != r.part:
                        r = Region(r.id, r.chunk, r.deleted, new_part)
                kept.append(r)
            self._tables[table_id] = TableData(tuple(kept))
            self._bump_locked()
            return removed

    def gc_stats(self, table_id: int):
        """(live_rows, dead_rows, regions) — observability hook."""
        with self._lock:
            td = self._tables.get(table_id)
            if td is None:
                return (0, 0, 0)
            total = sum(r.num_rows for r in td.regions)
            dead = sum(int(r.deleted.sum()) for r in td.regions)
            return (total - dead, dead, len(td.regions))

    def _pad_mask(self, mask: np.ndarray, region: Region) -> np.ndarray:
        """A staged mask may be shorter than the region if rows were appended
        (top-off) after the txn's snapshot: regions only ever grow at the
        tail, so the mask covers an unchanged prefix — pad with False."""
        if len(mask) == region.num_rows:
            return mask
        if len(mask) > region.num_rows:
            raise TxnError("write conflict: region shrank (truncated)")
        padded = np.zeros(region.num_rows, dtype=bool)
        padded[:len(mask)] = mask
        return padded

    def _validate_deletes_locked(self, table_id: int,
                                 region_masks: Dict[int, np.ndarray]) -> None:
        """Conflict checks only — no mutation (keeps commit atomic)."""
        td = self._tables.get(table_id)
        if td is None:
            raise TxnError("write conflict: table dropped")
        by_id = {r.id: r for r in td.regions}
        for rid, mask in region_masks.items():
            r = by_id.get(rid)
            if r is None:
                raise TxnError("write conflict: region gone (truncated)")
            mask = self._pad_mask(mask, r)
            if (r.deleted & mask).any():
                raise TxnError(
                    "write conflict: row deleted by a concurrent transaction")

    def _delete_locked(self, table_id: int,
                       region_masks: Dict[int, np.ndarray]) -> int:
        td = self._tables.get(table_id)
        if td is None:
            raise UnknownTableError(f"no storage for table id {table_id}")
        deleted_count = 0
        regions = list(td.regions)
        by_id = {r.id: i for i, r in enumerate(regions)}
        for rid, mask in region_masks.items():
            idx = by_id.get(rid)
            if idx is None:
                continue
            r = regions[idx]
            mask = self._pad_mask(mask, r)
            effective = mask & ~r.deleted
            deleted_count += int(effective.sum())
            regions[idx] = Region(r.id, r.chunk, r.deleted | mask)
        self._tables[table_id] = TableData(tuple(regions))
        return deleted_count

    # ---- transactions ----------------------------------------------------
    def begin(self) -> "Transaction":
        with self._lock:
            self._open_txns += 1
        return Transaction(self, self.snapshot())

    def _txn_closed(self) -> None:
        with self._lock:
            self._open_txns = max(self._open_txns - 1, 0)

    def commit(self, txn: "Transaction") -> None:
        from tidb_tpu.util import failpoint
        bo = None
        while True:
            try:
                failpoint.inject("store-commit")
                failpoint.inject("commit-conflict")
                # two-phase delta append: everything above is staging
                # (host-side, txn-private); the locked block below is the
                # atomic apply+version-bump. A fault HERE — the boundary —
                # either heals through the retry loop (retryable) or
                # surfaces typed with the old delta version intact; it can
                # never leave a torn delta because nothing is applied yet.
                failpoint.inject("delta-append")
                with self._lock:
                    # first-committer-wins: validate EVERYTHING before
                    # applying anything, so a conflict leaves no partial
                    # writes behind
                    for tid, masks in txn.staged_deletes.items():
                        self._validate_deletes_locked(tid, masks)
                    for tid in txn.staged_inserts:
                        if tid not in self._tables:
                            raise TxnError("write conflict: table dropped")
                    for tid, masks in txn.staged_deletes.items():
                        self._delete_locked(tid, masks)
                    for tid, items in txn.staged_inserts.items():
                        for ch, part in items:
                            self._append_locked(tid, ch, part)
                    for tid in txn.staged_deletes:
                        self._maybe_compact_locked(tid, closing=1)
                    self._bump_locked()
                return
            except TxnError as e:
                # only errors marked retryable (transient region churn,
                # injected conflicts) re-enter; real first-committer-wins
                # conflicts propagate immediately
                if not getattr(e, "retryable", False):
                    raise
                if bo is None:
                    from tidb_tpu.util.backoff import Backoffer
                    bo = Backoffer("store-commit", base_ms=1.0,
                                   max_ms=20.0, budget_ms=250.0)
                bo.backoff(e)

    # ---- introspection ---------------------------------------------------
    def stats(self) -> Dict[int, Tuple[int, int]]:
        """table_id → (regions, live rows)."""
        with self._lock:
            return {tid: (len(td.regions), td.live_rows)
                    for tid, td in self._tables.items()}


class Transaction:
    """Optimistic txn: staged writes + snapshot reads (ref: session/txn.go
    LazyTxn + kv memBuffer). Readers inside the txn merge staged state via
    `scan` — the UnionScanExec pattern (executor/union_scan.go)."""

    def __init__(self, store: Store, snapshot: Snapshot):
        self._store = store
        self.snapshot = snapshot
        # table_id → [(chunk, partition ordinal or None)]
        self.staged_inserts: Dict[int, List[Tuple[Chunk, Optional[int]]]] = {}
        self.staged_deletes: Dict[int, Dict[int, np.ndarray]] = {}
        self.active = True
        self.txn_id = next(store._txn_seq)
        self.pessimistic = False
        self.locked: List[Tuple[int, int, np.ndarray]] = []
        # table_id → rows this txn modified; the session flushes it into
        # the engine's auto-analyze counters at COMMIT (never on rollback)
        self.modified: Dict[int, int] = {}

    def has_staged_writes(self) -> bool:
        return bool(self.staged_inserts) or bool(self.staged_deletes)

    # ---- writes ----------------------------------------------------------
    def append(self, table_id: int, chunk: Chunk,
               part: Optional[int] = None) -> None:
        self.staged_inserts.setdefault(table_id, []).append((chunk, part))

    def delete(self, table_id: int, region_masks: Dict[int, np.ndarray]) -> int:
        staged = self.staged_deletes.setdefault(table_id, {})
        n = 0
        for rid, mask in region_masks.items():
            prev = staged.get(rid)
            if prev is None:
                staged[rid] = mask.copy()
                n += int(mask.sum())
            else:
                n += int((mask & ~prev).sum())
                staged[rid] = prev | mask
        return n

    def delete_staged(self, table_id: int, keep_mask: np.ndarray) -> None:
        """Remove rows from this txn's own staged inserts (delete-after-insert
        inside one txn)."""
        items = self.staged_inserts.get(table_id)
        if not items:
            return
        # keep_mask follows scan order (chunks in list order); filter each
        # piece separately so partition tags survive
        kept_items = []
        off = 0
        for ch, part in items:
            m = keep_mask[off:off + ch.num_rows]
            off += ch.num_rows
            k = ch.filter(m)
            if k.num_rows:
                kept_items.append((k, part))
        self.staged_inserts[table_id] = kept_items

    # ---- reads (UnionScan merge) -----------------------------------------
    def scan(self, table_id: int, parts=None
             ) -> Iterable[Tuple[Optional[Region], Chunk, np.ndarray]]:
        """Yield (region_or_None, chunk, alive_mask): committed regions with
        staged deletes applied, then staged-insert chunks (both honoring
        partition pruning via `parts`)."""
        staged_del = self.staged_deletes.get(table_id, {})
        if self.snapshot.has_table(table_id):
            for r, alive in self.snapshot.scan(table_id, parts):
                mask = alive
                sd = staged_del.get(r.id)
                if sd is not None:
                    mask = mask & ~sd
                yield r, r.chunk, mask
        elif self._store.snapshot().has_table(table_id):
            # table created AFTER this txn began (session-private CTE
            # temp materialization): read it from the current store view
            for r, alive in self._store.snapshot().scan(table_id, parts):
                yield r, r.chunk, alive
        for ch, part in self.staged_inserts.get(table_id, []):
            if ch.num_rows and (parts is None or part is None
                                or part in parts):
                yield None, ch, np.ones(ch.num_rows, dtype=bool)

    # ---- lifecycle -------------------------------------------------------
    def commit(self) -> None:
        if not self.active:
            raise TxnError("transaction is not active")
        try:
            self._store.commit(self)
        finally:
            self.active = False
            self._store.release_locks(self)
            self._store._txn_closed()

    def rollback(self) -> None:
        if self.active:
            self._store.release_locks(self)
            self._store._txn_closed()
        self.active = False
        self.staged_inserts.clear()
        self.staged_deletes.clear()
