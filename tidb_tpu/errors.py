"""Error hierarchy (ref: parser/terror, errno/ — simplified).

The reference carries MySQL error codes end-to-end (errno/errcode.go); we keep
a small typed hierarchy with MySQL-compatible codes on the classes users see.
"""


class TiDBTPUError(Exception):
    """Base error."""

    code = 1105  # ER_UNKNOWN_ERROR
    # transient failures (injected faults, lock contention) may be retried
    # by backoff-wrapped paths; anything else propagates immediately
    retryable = False


class ParseError(TiDBTPUError):
    code = 1064  # ER_PARSE_ERROR


class PlanError(TiDBTPUError):
    code = 1105


class ExecutionError(TiDBTPUError):
    code = 1105


class UnknownTableError(TiDBTPUError):
    code = 1146  # ER_NO_SUCH_TABLE


class UnknownColumnError(TiDBTPUError):
    code = 1054  # ER_BAD_FIELD_ERROR


class TableExistsError(TiDBTPUError):
    code = 1050  # ER_TABLE_EXISTS_ERROR


class TypeError_(TiDBTPUError):
    code = 1366  # ER_TRUNCATED_WRONG_VALUE_FOR_FIELD


class OverflowError_(TiDBTPUError):
    code = 1690  # ER_DATA_OUT_OF_RANGE


class MemoryQuotaExceeded(TiDBTPUError):
    code = 1038  # ER_OUT_OF_SORTMEMORY (closest MySQL analog)


class QueryKilledError(TiDBTPUError):
    code = 1317  # ER_QUERY_INTERRUPTED


class QueryInterrupted(QueryKilledError):
    """Cooperative KILL [QUERY] observed at a guard checkpoint (ref:
    util/sqlkiller — the reference's atomic kill flag, polled by every
    Next loop)."""

    code = 1317  # ER_QUERY_INTERRUPTED


class QueryTimeout(TiDBTPUError):
    """max_execution_time deadline crossed at a guard checkpoint."""

    code = 3024  # ER_QUERY_TIMEOUT


class NoSuchThreadError(TiDBTPUError):
    """KILL target conn id not found in the process-info registry."""

    code = 1094  # ER_NO_SUCH_THREAD


class KillDeniedError(TiDBTPUError):
    """KILL target exists but belongs to another user and the killer
    lacks SUPER (MySQL: you need SUPER to kill other users' threads)."""

    code = 1095  # ER_KILL_DENIED_ERROR


class SpecificAccessDeniedError(TiDBTPUError):
    """A statement needs a specific global privilege (PROCESS, SUPER)
    the current user was not granted."""

    code = 1227  # ER_SPECIFIC_ACCESS_DENIED_ERROR


class BackoffExhausted(TiDBTPUError):
    """Retry budget spent without success (ref: tikv/client-go
    retry.BackOffer's errors.New("backoffer.maxSleep exceeded"))."""

    code = 1105


class CapacityError(ExecutionError):
    """A static-shape capacity (exchange bucket, group cap, join out-cap)
    overflowed and the escalation ladder is exhausted. Raised instead of
    returning truncated rows — overflow is NEVER silent row loss."""

    code = 1104  # ER_TOO_BIG_SELECT


class ShardFailure(ExecutionError):
    """One shard's step of a distributed fragment failed (injected fault
    or a real device/runtime error) and the per-shard recovery ladder —
    retry on the same device, then re-dispatch onto a surviving device
    (degraded mesh) — is exhausted. Surfaces as this one typed retryable
    error; the session and store stay fully usable."""

    code = 1105
    retryable = True


class DeviceLost(ExecutionError):
    """A serving-pool device failed at a dispatch or upload boundary
    (launch error, device_put/transfer failure). The scheduler's health
    monitor quarantines the device, queued waiters migrate to survivors,
    and the in-flight victim retries ONCE on a survivor — mirroring
    degraded-mesh semantics. Surfaces typed and retryable only when no
    survivor exists or the retry itself hits a second lost device."""

    code = 1105
    retryable = True

    def __init__(self, msg, device=None):
        super().__init__(msg)
        self.device = device


class LayoutError(ExecutionError):
    """A compressed column's physical-layout descriptor is invalid or
    inconsistent with the data it describes (corrupted kind/width/ref).
    Raised BEFORE any decode runs so a bad descriptor can never produce
    silently wrong rows; the executor's generic fallback ladder re-runs
    the fragment on the CPU oracle path."""

    code = 1105


class DivisionByZero(TiDBTPUError):
    code = 1365  # ER_DIVISION_BY_ZERO


class TxnError(TiDBTPUError):
    code = 1205  # ER_LOCK_WAIT_TIMEOUT


class DeadlockError(TxnError):
    """Wait-for cycle between pessimistic transactions (ref:
    unistore/tikv/detector.go)."""

    code = 1213  # ER_LOCK_DEADLOCK


class SchemaChangedError(TxnError):
    """Schema-lease violation at commit: a table this transaction wrote
    changed shape (columns / indexes / primary key) between the
    statement's plan snapshot and its commit (ref:
    domain/schema_validator.go — ErrInfoSchemaChanged). DDL on tables
    the transaction never touched does NOT raise this."""

    code = 1105  # ER_UNKNOWN_ERROR (TiDB reports 8028 via 1105 envelope)


class DuplicateKeyError(TiDBTPUError):
    code = 1062  # ER_DUP_ENTRY


class NotNullViolation(ExecutionError):
    code = 1048  # ER_BAD_NULL_ERROR


class SubqueryRowError(ExecutionError):
    code = 1242  # ER_SUBQUERY_NO_1_ROW


class UnsupportedFunctionError(PlanError):
    code = 1305  # ER_SP_DOES_NOT_EXIST (MySQL's unknown-function errno)


class DataTooLongError(ExecutionError):
    code = 1406  # ER_DATA_TOO_LONG


class WrongValueCountError(PlanError):
    code = 1136  # ER_WRONG_VALUE_COUNT_ON_ROW


class DerivedMustHaveAliasError(PlanError):
    code = 1248  # ER_DERIVED_MUST_HAVE_ALIAS


class OperandColumnsError(PlanError):
    code = 1241  # ER_OPERAND_COLUMNS


class DDLError(TiDBTPUError):
    """Schema-change failure (ref: ddl/ddl error codes)."""

    code = 1091  # ER_CANT_DROP_FIELD_OR_KEY (default; override per raise)

    def __init__(self, msg, code=None):
        super().__init__(msg)
        if code is not None:
            self.code = code


class PartitionError(ExecutionError):
    code = 1526  # ER_NO_PARTITION_FOR_GIVEN_VALUE
