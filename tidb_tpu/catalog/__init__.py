"""Schema metadata & versioned catalog.

Ref: /root/reference/infoschema/ (versioned InfoSchema snapshots,
infoschema/infoschema.go), parser/model/ (TableInfo/ColumnInfo), meta/
(catalog persistence). The reference persists catalog state in KV and syncs
schema versions across nodes via etcd; here the catalog is an in-process
versioned map — every DDL bumps `version` and replaces the snapshot, so
readers hold an immutable InfoSchema exactly like domain.Domain's infoCache
(domain/domain.go:69-99).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

from tidb_tpu.errors import (DDLError, TableExistsError,
                             UnknownColumnError, UnknownTableError)
from tidb_tpu.types import FieldType


@dataclass(frozen=True)
class ColumnInfo:
    """Ref: parser/model/model.go ColumnInfo."""

    name: str
    ftype: FieldType
    offset: int = 0
    primary_key: bool = False
    default: object = None
    has_default: bool = False
    auto_increment: bool = False


@dataclass(frozen=True)
class IndexInfo:
    """Ref: parser/model/model.go IndexInfo. `state` follows the F1
    online-schema-change ladder collapsed to the two states this
    engine's lazy sorted-view indexes need: "write_only" (DML enforces
    and maintains the key; readers must not use it — its uniqueness is
    not yet validated) and "public" (ddl/index.go:519's state walk)."""

    name: str
    columns: Tuple[str, ...]
    unique: bool = False
    state: str = "public"          # write_only | public


@dataclass(frozen=True)
class PartitionInfo:
    """RANGE/HASH partition metadata (ref: parser/model/model.go
    PartitionInfo). `bounds` holds ENCODED upper bounds per range
    partition (None = MAXVALUE); physically, partitions are region
    colocation tags in the one columnar store table — the slab-native
    unit the device cache and dist sharding already consume."""

    kind: str                             # range | hash
    column: str
    col_offset: int
    names: Tuple[str, ...]
    bounds: Tuple[Optional[int], ...] = ()   # range: encoded, ascending
    num: int = 0                             # hash partition count

    @property
    def n_parts(self) -> int:
        return len(self.names)


@dataclass(frozen=True)
class TableInfo:
    """Ref: parser/model/model.go TableInfo."""

    id: int
    name: str
    columns: Tuple[ColumnInfo, ...]
    primary_key: Tuple[str, ...] = ()
    indexes: Tuple[IndexInfo, ...] = ()
    partition: Optional["PartitionInfo"] = None

    def column(self, name: str) -> ColumnInfo:
        lname = name.lower()
        for c in self.columns:
            if c.name.lower() == lname:
                return c
        raise UnknownColumnError(f"Unknown column '{name}' in '{self.name}'")

    def has_column(self, name: str) -> bool:
        lname = name.lower()
        return any(c.name.lower() == lname for c in self.columns)

    @property
    def field_types(self) -> List[FieldType]:
        return [c.ftype for c in self.columns]

    @property
    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]


@dataclass(frozen=True)
class ViewInfo:
    """A stored SELECT (ref: parser/model/model.go ViewInfo). Expansion
    happens in the plan builder — a view is a named derived table."""

    name: str
    sql: str                        # the definition's SELECT text
    columns: Tuple[str, ...] = ()   # optional explicit column names


class InfoSchema:
    """One immutable schema snapshot (ref: infoschema/infoschema.go:60)."""

    def __init__(self, version: int, tables: Dict[str, TableInfo],
                 views: Optional[Dict[str, ViewInfo]] = None):
        self.version = version
        self._tables = tables  # lower-name → TableInfo
        self._views: Dict[str, ViewInfo] = views or {}

    def view(self, name: str) -> Optional[ViewInfo]:
        return self._views.get(name.lower())

    def list_views(self) -> List[ViewInfo]:
        return sorted(self._views.values(), key=lambda v: v.name.lower())

    def table(self, name: str) -> TableInfo:
        t = self._tables.get(name.lower())
        if t is None:
            if name.lower() in self._views:
                # views resolve in the plan builder; reaching here means
                # a base-table-only operation (DML/DDL) targeted a view
                raise DDLError(f"'{name}' is not BASE TABLE", code=1347)
            raise UnknownTableError(f"Table '{name}' doesn't exist")
        return t

    def table_by_id(self, tid: int) -> Optional[TableInfo]:
        for t in self._tables.values():
            if t.id == tid:
                return t
        return None

    def has_table(self, name: str) -> bool:
        return name.lower() in self._tables

    def list_tables(self) -> List[TableInfo]:
        return sorted(self._tables.values(), key=lambda t: t.name.lower())


class Catalog:
    """Mutable catalog owner; DDL entry point (ref: domain.Domain + ddl/).

    The reference runs DDL as an async owner-elected job queue with F1 state
    transitions (ddl/ddl_worker.go:82) because schema changes must propagate
    across stateless nodes; in-process we apply synchronously under a lock but
    keep the same observable contract: monotonically increasing schema
    versions and immutable snapshots.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._snapshot = InfoSchema(0, {})
        self._history: List[str] = []  # DDL job log (ref: meta DDL job queue)
        # schema version excluding session-private temp tables (CTE
        # materializations): the commit-time lease check compares THIS, so
        # a txn's own WITH queries don't read as concurrent DDL
        self.user_version = 0

    @property
    def info_schema(self) -> InfoSchema:
        return self._snapshot

    def _bump(self, tables: Dict[str, TableInfo], job: str,
              temp: bool = False, views=None) -> None:
        self._snapshot = InfoSchema(
            self._snapshot.version + 1, tables,
            self._snapshot._views if views is None else views)
        if not temp:
            self.user_version += 1
        self._history.append(job)

    def create_view(self, name: str, sql: str, columns=(),
                    or_replace: bool = False) -> ViewInfo:
        """Ref: ddl/ddl_api.go:2186 CreateView — one namespace with
        tables (ER 1050 on conflict unless OR REPLACE over a view)."""
        with self._lock:
            key = name.lower()
            if key in self._snapshot._tables:
                raise TableExistsError(f"Table '{name}' already exists")
            if key in self._snapshot._views and not or_replace:
                raise TableExistsError(f"Table '{name}' already exists")
            views = dict(self._snapshot._views)
            v = ViewInfo(name, sql, tuple(columns or ()))
            views[key] = v
            self._bump(dict(self._snapshot._tables),
                       f"create view {name}", views=views)
            return v

    def drop_view(self, name: str, if_exists: bool = False) -> None:
        with self._lock:
            key = name.lower()
            if key not in self._snapshot._views:
                if if_exists:
                    return
                raise UnknownTableError(f"Unknown view '{name}'")
            views = dict(self._snapshot._views)
            views.pop(key)
            self._bump(dict(self._snapshot._tables),
                       f"drop view {name}", views=views)

    def ddl_history(self) -> List[str]:
        return list(self._history)

    def create_table(self, name: str, columns: Sequence[ColumnInfo],
                     primary_key: Sequence[str] = (),
                     indexes: Sequence[IndexInfo] = (),
                     if_not_exists: bool = False,
                     partition: Optional[PartitionInfo] = None
                     ) -> Optional[TableInfo]:
        with self._lock:
            key = name.lower()
            if key in self._snapshot._tables:
                if if_not_exists:
                    return None
                raise TableExistsError(f"Table '{name}' already exists")
            if key in self._snapshot._views:
                # one namespace: a table may not shadow a view
                raise TableExistsError(f"Table '{name}' already exists")
            cols = tuple(replace(c, offset=i) for i, c in enumerate(columns))
            info = TableInfo(next(self._ids), name, cols,
                             tuple(primary_key), tuple(indexes),
                             partition)
            tables = dict(self._snapshot._tables)
            tables[key] = info
            self._bump(tables, f"create table {name}",
                       temp=name.startswith("#"))
            return info

    def set_partition(self, table: str,
                      pinfo: Optional[PartitionInfo]) -> TableInfo:
        """ALTER partition-metadata update (ADD/DROP PARTITION)."""
        with self._lock:
            key = table.lower()
            info = self._snapshot._tables.get(key)
            if info is None:
                raise UnknownTableError(f"Unknown table '{table}'")
            new = replace(info, partition=pinfo)
            tables = dict(self._snapshot._tables)
            tables[key] = new
            self._bump(tables, f"alter table {table} partitions")
            return new

    def set_index_state(self, table: str, index_name: str,
                        state: str) -> TableInfo:
        """One step of the online-DDL state walk (ddl/ddl_worker.go:493
        schema-version bump per transition)."""
        with self._lock:
            key = table.lower()
            info = self._snapshot._tables.get(key)
            if info is None:
                raise UnknownTableError(f"Unknown table '{table}'")
            idxs = tuple(replace(ix, state=state)
                         if ix.name.lower() == index_name.lower() else ix
                         for ix in info.indexes)
            new = replace(info, indexes=idxs)
            tables = dict(self._snapshot._tables)
            tables[key] = new
            self._bump(tables,
                       f"alter index {index_name} on {table} -> {state}")
            return new

    def add_index(self, table: str, index: IndexInfo) -> TableInfo:
        """CREATE INDEX (ref: ddl/ddl_api.go CreateIndex; synchronous —
        backfill is lazy because indexes are sorted snapshot views)."""
        with self._lock:
            key = table.lower()
            info = self._snapshot._tables.get(key)
            if info is None:
                raise UnknownTableError(f"Unknown table '{table}'")
            if any(ix.name.lower() == index.name.lower()
                   for ix in info.indexes):
                raise DDLError(f"Duplicate key name '{index.name}'",
                               code=1061)  # ER_DUP_KEYNAME
            for c in index.columns:
                info.column(c)        # raises on unknown column
            new = replace(info, indexes=info.indexes + (index,))
            tables = dict(self._snapshot._tables)
            tables[key] = new
            self._bump(tables, f"create index {index.name} on {table}")
            return new

    def drop_index(self, table: str, index_name: str,
                   if_exists: bool = False) -> Optional[TableInfo]:
        with self._lock:
            key = table.lower()
            info = self._snapshot._tables.get(key)
            if info is None:
                raise UnknownTableError(f"Unknown table '{table}'")
            keep = tuple(ix for ix in info.indexes
                         if ix.name.lower() != index_name.lower())
            if len(keep) == len(info.indexes):
                if if_exists:
                    return None
                raise DDLError(f"Can't DROP '{index_name}'; check that "
                               f"column/key exists")
            new = replace(info, indexes=keep)
            tables = dict(self._snapshot._tables)
            tables[key] = new
            self._bump(tables, f"drop index {index_name} on {table}")
            return new

    def drop_column(self, table: str, col_name: str) -> TableInfo:
        """DROP COLUMN (ref: ddl/column.go onDropColumn); the session
        rewrites storage eagerly (regions hold positional layouts)."""
        with self._lock:
            info = self._snapshot.table(table)
            keep = [c for c in info.columns
                    if c.name.lower() != col_name.lower()]
            if len(keep) == len(info.columns):
                raise UnknownColumnError(
                    f"Unknown column '{col_name}' in '{table}'")
            if not keep:
                raise DDLError("cannot drop the only column")
            if any(c.lower() == col_name.lower() for c in info.primary_key):
                raise DDLError(
                    f"cannot drop primary-key column '{col_name}'")
            keep = tuple(replace(c, offset=i) for i, c in enumerate(keep))
            idxs = tuple(ix for ix in info.indexes
                         if col_name.lower() not in
                         (c.lower() for c in ix.columns))
            updated = replace(info, columns=keep, indexes=idxs)
            tables = dict(self._snapshot._tables)
            tables[table.lower()] = updated
            self._bump(tables,
                       f"alter table {table} drop column {col_name}")
            return updated

    def drop_table(self, name: str, if_exists: bool = False) -> Optional[TableInfo]:
        with self._lock:
            key = name.lower()
            info = self._snapshot._tables.get(key)
            if info is None:
                if if_exists:
                    return None
                raise UnknownTableError(f"Unknown table '{name}'")
            tables = dict(self._snapshot._tables)
            del tables[key]
            self._bump(tables, f"drop table {name}",
                       temp=name.startswith("#"))
            return info

    def rename_table(self, old: str, new: str) -> TableInfo:
        with self._lock:
            info = self._snapshot.table(old)
            if new.lower() in self._snapshot._tables:
                raise TableExistsError(f"Table '{new}' already exists")
            renamed = replace(info, name=new)
            tables = dict(self._snapshot._tables)
            del tables[old.lower()]
            tables[new.lower()] = renamed
            self._bump(tables, f"rename table {old} to {new}")
            return renamed

    def add_column(self, table: str, col: ColumnInfo) -> TableInfo:
        """Online ADD COLUMN (ref: ddl/column.go). Storage backfills lazily:
        existing regions surface the column's default via schema offset."""
        with self._lock:
            info = self._snapshot.table(table)
            if info.has_column(col.name):
                raise TableExistsError(
                    f"Duplicate column name '{col.name}'")
            cols = info.columns + (replace(col, offset=len(info.columns)),)
            updated = replace(info, columns=cols)
            tables = dict(self._snapshot._tables)
            tables[table.lower()] = updated
            self._bump(tables, f"alter table {table} add column {col.name}")
            return updated
