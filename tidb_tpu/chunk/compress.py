"""Compressed physical column layouts for the device cache.

Per-column layout chosen once at encode time ("Fine-Tuning Data
Structures for Analytical Query Processing" — the load-time layout
decision is the highest-leverage lever for scan-bound analytics):

  * pack — frame-of-reference bit-packing: codes are `value - ref`
    (ref = min over valid values, so negative ints need no zig-zag)
    packed at the observed bit width into uint32 words;
  * dict — low-cardinality int columns store sorted-dictionary rank
    codes (the string-dictionary idea extended to ints), packed at the
    code width, with ONE shared dictionary values array per column;
  * delta — monotonically non-decreasing, fully-valid int columns
    (sorted PKs, event timestamps) store successive differences packed
    at the max-gap width, with a per-slab base value; decode is one
    cumulative sum. Constant runs pack at the zero-diff width, so delta
    subsumes run-length encoding for sorted data.

The layout decision is workload-adaptive: `choose_layout` accepts
hints distilled from the Registry's per-digest profiles (group-by
heavy workloads raise the dictionary cardinality cap — dictionary
codes feed group factorization directly).

Width is rounded up to {0, 1, 2, 4, 8, 16, 32} so codes never straddle
a word boundary and the device decode is a gather-free broadcast
shift/mask. Width 0 means every valid value equals `ref` (single
distinct value, or an all-NULL column) — no words are stored at all.
Validity masks are themselves bit-packed at width 1 over the padded
slab, so a compressed slab is (words, mask_words[, dictvals]) and the
raw representation never crosses PCIe.

Decode is xp-generic (numpy for the CPU oracle in tests, jnp inside
traced fragments via device_emit.emit_decode) and byte-exact: packing
the PADDED slab preserves the False padding of the mask, and invalid
slots pack as code 0 — their decoded values are don't-care because
every consumer masks by validity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from tidb_tpu.errors import LayoutError

#: legal packed widths — each divides the 32-bit word exactly
WIDTHS = (0, 1, 2, 4, 8, 16, 32)
WORD_BITS = 32
#: dictionary layout only below this cardinality (TiFlash's low-card
#: dictionary threshold is the same order of magnitude)
DICT_CARD_CAP = 4096


@dataclass(frozen=True)
class ColLayout:
    """Static per-column layout descriptor — hashable and data-light so
    it keys program signatures (escalation recompiles stay exact-need)."""

    kind: str      # "pack" (FoR) | "dict" (dictionary) | "delta" (diffs)
    width: int     # bits per packed code — one of WIDTHS
    ref: int       # frame-of-reference base (pack); 0 for dict/delta
    dtype: str     # logical numpy dtype name the decode restores
    card: int = 0  # dictionary cardinality (dict kind only)

    def sig(self) -> str:
        return (f"{self.kind}:w{self.width}:r{self.ref}:"
                f"c{self.card}:{self.dtype}")

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(self.dtype)


def validate(layout) -> None:
    """Reject a corrupted/inconsistent descriptor with a typed error —
    consumers call this BEFORE decoding, so a bad descriptor can never
    reach the traced decode and produce silently wrong rows."""
    if not isinstance(layout, ColLayout):
        raise LayoutError(
            f"column layout descriptor is not a ColLayout: {layout!r}")
    if layout.kind not in ("pack", "dict", "delta"):
        raise LayoutError(f"unknown layout kind {layout.kind!r}")
    if layout.kind == "delta" and layout.width == 0:
        raise LayoutError("delta layout with width 0 (constant columns "
                          "must use pack width 0)")
    if layout.width not in WIDTHS:
        raise LayoutError(
            f"illegal packed width {layout.width} (legal: {WIDTHS})")
    try:
        dt = np.dtype(layout.dtype)
    except TypeError as e:
        raise LayoutError(
            f"layout dtype {layout.dtype!r} is not a dtype") from e
    if dt.kind not in "iu":
        raise LayoutError(
            f"layout dtype {layout.dtype!r} is not an integer type")
    if layout.kind == "dict" and layout.card <= 0:
        raise LayoutError(
            f"dict layout with non-positive cardinality {layout.card}")


def _round_width(bits: int) -> Optional[int]:
    for w in WIDTHS:
        if bits <= w:
            return w
    return None


def choose_layout(vals: np.ndarray, valid: np.ndarray,
                  allow_dict: bool = True, hints: Optional[dict] = None
                  ) -> Tuple[Optional[ColLayout], Optional[np.ndarray]]:
    """GLOBAL per-column layout decision → (layout or None, dictvals).

    Over the FULL column so every slab shares one layout (and one
    program signature). Floats, wide decimals (never integer dtype
    here) and columns whose observed range needs more than half the
    logical width stay raw — compression must at least halve the value
    bytes to be worth a layout.

    `hints` carries workload evidence distilled from the per-digest
    statement profiles (device_cache.workload_hints): a group-by-heavy
    workload sets "group_heavy", which raises the dictionary
    cardinality cap 4× and lets dictionary win width ties — dict codes
    double as pre-factorized group ids, so the wider cap pays for
    itself on the agg side even when pack would be byte-equal."""
    hints = hints or {}
    dt = vals.dtype
    if dt.kind not in "iu" or dt.itemsize > 8:
        return None, None
    max_width = dt.itemsize * 8 // 2
    name = dt.name
    all_valid = bool(valid.all())
    vv = vals if all_valid else vals[valid]
    if vv.size == 0:
        # all-NULL column: width 0, nothing stored but the packed mask
        return ColLayout("pack", 0, 0, name), None
    lo, hi = int(vv.min()), int(vv.max())
    pw = _round_width((hi - lo).bit_length())
    pack = ColLayout("pack", pw, lo, name) \
        if pw is not None and pw <= max_width else None
    # sorted fully-valid columns (PKs, timestamps): successive diffs
    # need max-gap bits, not range bits — a dense sorted PK packs at
    # width 1-2 regardless of its absolute range
    if all_valid and vv.size >= 2:
        v64 = vv.astype(np.int64)
        diffs = np.diff(v64)
        if diffs.size and int(diffs.min()) >= 0 and int(diffs.max()) > 0:
            xw = _round_width(int(diffs.max()).bit_length())
            if xw is not None and 0 < xw <= max_width and \
                    (pack is None or xw < pack.width):
                pack = ColLayout("delta", xw, 0, name)
    if allow_dict and (pack is None or pack.width > 1):
        uniq = np.unique(vv)
        card = int(uniq.size)
        dict_cap = DICT_CARD_CAP * (4 if hints.get("group_heavy") else 1)
        if card <= dict_cap:
            dw = _round_width(max(card - 1, 0).bit_length())
            better = dw is not None and dw <= max_width and (
                pack is None or dw < pack.width or
                (hints.get("group_heavy") and dw == pack.width and
                 pack.kind == "pack"))
            if better:
                return ColLayout("dict", dw, 0, name, card), uniq
    return pack, None


def _pack_codes(codes: np.ndarray, width: int) -> np.ndarray:
    """Non-negative uint64 codes (< 2^width) → uint32 words, element j
    of word w at bits [j*width, (j+1)*width)."""
    per = WORD_BITS // width
    n = codes.shape[0]
    n_words = -(-n // per)
    if n_words * per != n:
        pad = np.zeros(n_words * per, dtype=np.uint64)
        pad[:n] = codes
        codes = pad
    codes = codes.reshape(n_words, per)
    shifts = np.arange(per, dtype=np.uint64) * np.uint64(width)
    words = np.bitwise_or.reduce(codes << shifts[None, :], axis=1)
    return words.astype(np.uint32)


def pack_slab(layout: ColLayout, vals: np.ndarray, mask: np.ndarray,
              dictvals: Optional[np.ndarray] = None):
    """Host-side encode of ONE padded slab → (words, mask_words) — plus
    a trailing per-slab base array for delta slabs. Invalid/padding
    slots pack as code 0 (decoded values there are don't-care —
    consumers mask by validity); the mask packs the padded slab
    exactly, so decode restores it byte-for-byte."""
    mask = np.asarray(mask, dtype=bool)
    mask_words = _pack_codes(mask.astype(np.uint64), 1)
    if layout.width == 0:
        # nothing to store: every valid value IS layout.ref
        return np.zeros(1, dtype=np.uint32), mask_words
    if layout.kind == "dict":
        safe = np.where(mask, vals, dictvals[0])
        codes = np.searchsorted(dictvals, safe).astype(np.uint64)
    elif layout.kind == "delta":
        # delta columns are fully valid, so the valid prefix IS the
        # slab's rows; padding diffs stay 0 (cumsum holds the last
        # value there, masked out by the packed validity)
        n = int(mask.sum())
        v64 = vals.astype(np.int64)
        codes = np.zeros(vals.shape[0], dtype=np.uint64)
        if n > 1:
            codes[1:n] = np.diff(v64[:n]).astype(np.uint64)
        base = np.asarray([v64[0] if n else 0], dtype=np.int64)
        return _pack_codes(codes, layout.width), mask_words, base
    else:
        codes = np.where(mask, vals.astype(np.int64) - np.int64(layout.ref),
                         0).astype(np.uint64)
    return _pack_codes(codes, layout.width), mask_words


def _unpack_codes(words, width: int, cap: int, xp):
    per = WORD_BITS // width
    w = xp.asarray(words)
    shifts = (xp.arange(per) * width).astype(np.uint32)
    m = np.uint32(0xFFFFFFFF) if width == WORD_BITS \
        else np.uint32((1 << width) - 1)
    codes = (w[:, None] >> shifts[None, :]) & m
    return codes.reshape(-1)[:cap]


def decode_slab(layout: ColLayout, slab, cap: int, xp):
    """One packed slab → (vals, mask) in the logical dtype. xp is numpy
    (CPU oracle) or jnp (traced inside the fragment — a gather-free
    broadcast shift/mask, plus one take for dict columns)."""
    validate(layout)
    words, mask_words = slab[0], slab[1]
    mask = _unpack_codes(mask_words, 1, cap, xp) != 0
    dt = layout.np_dtype
    if layout.width == 0:
        return xp.full(cap, layout.ref, dtype=dt), mask
    codes = _unpack_codes(words, layout.width, cap, xp)
    if layout.kind == "dict":
        # dict codes are < DICT_CARD_CAP, so int32 indexing is exact
        idx = xp.clip(codes.astype(np.int32), 0, layout.card - 1)
        return xp.take(xp.asarray(slab[2]), idx).astype(dt), mask
    if layout.kind == "delta":
        base = xp.asarray(slab[2]).astype(np.int64)[0]
        return (base + xp.cumsum(codes.astype(np.int64))).astype(dt), mask
    return (codes.astype(np.int64) + np.int64(layout.ref)).astype(dt), mask


def raw_slab_bytes(layout: ColLayout, cap: int) -> int:
    """Logical bytes one slab WOULD occupy uncompressed: values at the
    logical dtype plus the 1-byte-per-row bool validity mask."""
    return cap * (layout.np_dtype.itemsize + 1)


def packed_slab_bytes(layout: ColLayout, cap: int) -> int:
    """Physical bytes one packed slab occupies (words + mask words +
    the delta base), computable WITHOUT encoding it — the upload-bytes
    figure for slabs that zone-map pruning never encodes. Excludes the
    dict-layout dictionary array (uploaded once per column, not per
    slab)."""
    mask_bytes = 4 * (-(-cap // WORD_BITS))
    if layout.width == 0:
        return 4 + mask_bytes                 # the 1-word stub
    per = WORD_BITS // layout.width
    word_bytes = 4 * (-(-cap // per))
    base_bytes = 8 if layout.kind == "delta" else 0
    return word_bytes + mask_bytes + base_bytes
