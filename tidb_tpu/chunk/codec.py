"""Chunk wire codec (ref: util/chunk/codec.go:43-77).

Per column, little-endian, concatenated:

    [length u32][nullCount u32][nullBitmap ceil(len/8) bytes if nullCount>0]
    [offsets (len+1) x i64 if varlen][data]

Same layout as the reference (it is already Arrow-shaped: validity bitmap +
offsets + values), so chunks serialized here are byte-compatible in structure
with tipb EncodeType_TypeChunk payloads. Used for host<->host exchange between
distributed workers and for spill files.
"""

from __future__ import annotations

import struct
from typing import List, Sequence

import numpy as np

from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.types import FieldType


def _pack_bitmap(valid: np.ndarray) -> bytes:
    return np.packbits(valid, bitorder="little").tobytes()


def _unpack_bitmap(buf: bytes, n: int) -> np.ndarray:
    bits = np.unpackbits(np.frombuffer(buf, dtype=np.uint8), bitorder="little")
    return bits[:n].astype(bool)


def encode_column(col: Column) -> bytes:
    n = len(col)
    null_count = col.null_count
    parts = [struct.pack("<II", n, null_count)]
    if null_count > 0:
        parts.append(_pack_bitmap(col.valid_mask()))
    if col.ftype.is_varlen or col.ftype.is_wide_decimal:
        # wide decimals hold arbitrary-precision ints: serialize decimal
        # text like varlen (types/mydecimal.go ToString analog)
        encoded = [b"" if col.is_null(i) else str(col.values[i]).encode("utf-8")
                   for i in range(n)]
        lens = np.fromiter((len(e) for e in encoded), dtype=np.int64, count=n)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        parts.append(offsets.tobytes())
        parts.append(b"".join(encoded))
    else:
        parts.append(np.ascontiguousarray(col.values).tobytes())
    return b"".join(parts)


def decode_column(buf: bytes, pos: int, ftype: FieldType):
    n, null_count = struct.unpack_from("<II", buf, pos)
    pos += 8
    validity = None
    if null_count > 0:
        nbytes = (n + 7) // 8
        validity = _unpack_bitmap(buf[pos:pos + nbytes], n)
        pos += nbytes
    if ftype.is_varlen or ftype.is_wide_decimal:
        offsets = np.frombuffer(buf, dtype=np.int64, count=n + 1, offset=pos)
        pos += (n + 1) * 8
        total = int(offsets[-1]) if n else 0
        blob = buf[pos:pos + total]
        pos += total
        texts = [blob[offsets[i]:offsets[i + 1]].decode("utf-8")
                 for i in range(n)]
        if ftype.is_wide_decimal:
            values = np.array([int(t) if t else 0 for t in texts],
                              dtype=object)
        else:
            values = np.array(texts, dtype=object)
    else:
        dt = ftype.np_dtype
        values = np.frombuffer(buf, dtype=dt, count=n, offset=pos).copy()
        pos += n * dt.itemsize
    return Column(ftype, values, validity), pos


def encode_chunk(chunk: Chunk) -> bytes:
    header = struct.pack("<I", chunk.num_cols)
    return header + b"".join(encode_column(c) for c in chunk.columns)


def decode_chunk(buf: bytes, ftypes: Sequence[FieldType]) -> Chunk:
    (ncol,) = struct.unpack_from("<I", buf, 0)
    if ncol != len(ftypes):
        from tidb_tpu.errors import ExecutionError
        raise ExecutionError(f"schema mismatch: {ncol} vs {len(ftypes)}")
    pos = 4
    cols: List[Column] = []
    for ft in ftypes:
        col, pos = decode_column(buf, pos, ft)
        cols.append(col)
    return Chunk(cols)
