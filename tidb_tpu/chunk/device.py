"""Host Chunk ↔ device marshalling (the Arrow→HBM bridge of SURVEY §7.3).

A DeviceChunk is the on-device mirror of a Chunk: one jnp array per column
plus a shared validity story. Three TPU-first rules (SURVEY §7 "hard parts"):

  * static shapes — rows are padded up to a bucket capacity (powers of two),
    and the logical row count rides along as a device scalar so varying row
    counts inside one bucket do NOT retrigger XLA compilation;
  * the selection vector becomes a mask — `sel []int` (util/chunk/chunk.go:44)
    has no efficient TPU equivalent; filters produce boolean row masks that
    downstream kernels fuse;
  * strings become int32 dictionary codes; the dictionary stays on host.

DeviceChunk is registered as a pytree so it can flow through jit directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.ops.jax_env import jax, jnp, device_float_dtype
from tidb_tpu.types import FieldType, TypeKind

MIN_BUCKET = 1024


def bucket_capacity(n: int) -> int:
    """Round row count up to the shape bucket XLA compiles for."""
    cap = MIN_BUCKET
    while cap < n:
        cap <<= 1
    return cap


def _device_dtype(ftype: FieldType):
    dt = ftype.np_dtype
    if dt == np.dtype(np.float64):
        return device_float_dtype()
    if ftype.is_varlen:
        return jnp.int32  # dictionary codes
    return jnp.dtype(dt)


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceColumn:
    values: "jnp.ndarray"              # (capacity,) padded
    validity: "jnp.ndarray"            # (capacity,) bool; False in padding
    ftype: FieldType = field(default=None)
    dictionary: Optional[np.ndarray] = None  # host-side string dictionary

    def tree_flatten(self):
        # The dictionary deliberately does NOT ride the pytree: aux data keys
        # the jit cache (arrays there are unhashable, and a cached trace would
        # resurrect call-1 dictionaries onto call-2 outputs). Kernels operate
        # on codes; the host executor re-attaches dictionaries afterwards.
        return (self.values, self.validity), (self.ftype,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        values, validity = children
        (ftype,) = aux
        return cls(values, validity, ftype, None)

    def with_dictionary(self, dictionary: Optional[np.ndarray]) -> "DeviceColumn":
        return DeviceColumn(self.values, self.validity, self.ftype, dictionary)


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceChunk:
    columns: List[DeviceColumn]
    n_rows: "jnp.ndarray"              # () int32 device scalar — logical rows

    def tree_flatten(self):
        return (self.columns, self.n_rows), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        columns, n_rows = children
        return cls(list(columns), n_rows)

    @property
    def capacity(self) -> int:
        return self.columns[0].values.shape[0] if self.columns else 0

    def row_mask(self) -> "jnp.ndarray":
        """True for logical rows, False for padding."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.n_rows


def encode_strings(col: Column) -> Tuple[np.ndarray, np.ndarray]:
    """Dictionary-encode a string column → (codes int32, dictionary).

    Codes are dense [0, len(dict)); NULL rows get code 0 (masked by
    validity). Case-insensitive collations dictionary-normalize (the
    util/collate analog): values equal under the fold share ONE code, so
    device compares/groups/joins on codes are collation-correct; the
    dictionary keeps the first-seen representative per fold class
    (sorted by fold, so code order = collation order) and decode returns
    it — which representative a ci group shows is unspecified, as in
    MySQL."""
    str_vals = np.array([str(v) for v in col.values], dtype=object)
    if col.ftype.is_ci:
        from tidb_tpu.types import fold_ci_array
        folded = fold_ci_array(str_vals)
        _, first, codes = np.unique(folded, return_index=True,
                                    return_inverse=True)
        dictionary = str_vals[first]        # representative per class
        return codes.astype(np.int32), dictionary
    dictionary, codes = np.unique(str_vals, return_inverse=True)
    return codes.astype(np.int32), dictionary


def to_device_column(col: Column, capacity: int,
                     dictionary: Optional[np.ndarray] = None) -> DeviceColumn:
    n = len(col)
    dt = _device_dtype(col.ftype)
    if col.ftype.is_varlen:
        if dictionary is not None:
            # encode against a fixed dictionary (e.g. join-key alignment)
            lookup = {s: i for i, s in enumerate(dictionary)}
            codes = np.fromiter((lookup.get(str(v), -1) for v in col.values),
                                dtype=np.int32, count=n)
        else:
            codes, dictionary = encode_strings(col)
        host = codes
    else:
        host = np.asarray(col.values)
    padded = np.zeros(capacity, dtype=np.dtype(dt))
    padded[:n] = host.astype(np.dtype(dt), copy=False)
    valid = np.zeros(capacity, dtype=bool)
    valid[:n] = col.valid_mask()
    return DeviceColumn(jnp.asarray(padded), jnp.asarray(valid),
                        col.ftype, dictionary)


def to_device(chunk: Chunk, capacity: Optional[int] = None) -> DeviceChunk:
    cap = capacity or bucket_capacity(chunk.num_rows)
    assert cap >= chunk.num_rows
    cols = [to_device_column(c, cap) for c in chunk.columns]
    return DeviceChunk(cols, jnp.asarray(chunk.num_rows, dtype=jnp.int32))


def from_device(dchunk: DeviceChunk, n_rows: Optional[int] = None) -> Chunk:
    """Device → host Chunk (trims padding, decodes dictionaries)."""
    n = int(dchunk.n_rows) if n_rows is None else n_rows
    out: List[Column] = []
    for dc in dchunk.columns:
        vals = np.asarray(dc.values)[:n]
        valid = np.asarray(dc.validity)[:n]
        ft = dc.ftype
        if ft.is_varlen and dc.dictionary is None:
            from tidb_tpu.errors import ExecutionError
            raise ExecutionError(
                "varchar DeviceColumn has no dictionary (dictionaries do not "
                "survive jit; reattach with with_dictionary() before "
                "from_device)")
        if ft.is_varlen:
            # negative codes are the fixed-dictionary miss sentinel → NULL,
            # never silently the first dictionary entry
            neg = vals < 0
            if neg.any():
                valid = valid & ~neg
            if len(dc.dictionary):
                decoded = dc.dictionary[np.clip(vals, 0, len(dc.dictionary) - 1)]
                decoded = np.asarray(decoded, dtype=object)
            else:
                decoded = np.full(n, "", dtype=object)
            vals = decoded
        elif ft.np_dtype != vals.dtype and not ft.is_varlen:
            vals = vals.astype(ft.np_dtype)
        out.append(Column(ft, vals, None if valid.all() else valid.copy()))
    return Chunk(out)
