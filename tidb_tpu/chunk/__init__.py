"""Columnar Chunk batch format (ref: util/chunk/chunk.go:36-51, column.go:63-69).

A Chunk is an ordered list of Columns sharing one row count. Each Column is
a flat numpy array of physical values plus an optional validity bitmap
(True = not NULL) — the Arrow layout the reference's chunk codec already uses
(util/chunk/codec.go:43-77: [len][nullCount][nullBitmap][offsets][data]).

Differences from the reference, deliberate and TPU-first:
  * no varlen offsets buffer — strings live as numpy object arrays host-side
    and as int32 dictionary codes on device (TPUs cannot chase offsets);
  * the `sel []int` selection vector (chunk.go:44) is host-side only; on
    device a selection is a boolean mask fused into downstream kernels;
  * `requiredRows` pull-hinting is replaced by fixed padded batch buckets so
    XLA sees a small set of static shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence

import numpy as np

from tidb_tpu.types import FieldType, TypeKind

# Default logical batch size (ref: variable.DefMaxChunkSize = 1024). We run much
# larger batches: TPU kernels amortize launch + transfer over big chunks.
DEFAULT_CHUNK_SIZE = 65536


class Column:
    """One column: physical values + validity. Immutable by convention."""

    __slots__ = ("ftype", "values", "validity")

    def __init__(self, ftype: FieldType, values: np.ndarray,
                 validity: Optional[np.ndarray] = None):
        self.ftype = ftype
        self.values = values
        if validity is not None and validity.all():
            validity = None  # normalize: all-valid → None
        self.validity = validity

    # ---- constructors ----------------------------------------------------
    @staticmethod
    def from_list(ftype: FieldType, data: Sequence) -> "Column":
        n = len(data)
        encoded = [ftype.encode_value(v) for v in data]
        validity = np.array([v is not None for v in encoded], dtype=bool)
        if ftype.is_varlen:
            values = np.array([v if v is not None else "" for v in encoded],
                              dtype=object)
        else:
            zero = 0 if ftype.np_dtype.kind in "iuO" else 0.0
            values = np.array([v if v is not None else zero for v in encoded],
                              dtype=ftype.np_dtype)
        return Column(ftype, values, None if validity.all() else validity)

    @staticmethod
    def all_null(ftype: FieldType, n: int) -> "Column":
        if ftype.is_varlen:
            values = np.full(n, "", dtype=object)
        else:
            values = np.zeros(n, dtype=ftype.np_dtype)
        return Column(ftype, values, np.zeros(n, dtype=bool))

    # ---- accessors -------------------------------------------------------
    def __len__(self) -> int:
        return len(self.values)

    @property
    def null_count(self) -> int:
        return 0 if self.validity is None else int((~self.validity).sum())

    def is_null(self, i: int) -> bool:
        return self.validity is not None and not self.validity[i]

    def get(self, i: int):
        """Decoded Python value at row i (None for NULL)."""
        if self.is_null(i):
            return None
        return self.ftype.decode_value(self.values[i])

    def valid_mask(self) -> np.ndarray:
        if self.validity is None:
            return np.ones(len(self.values), dtype=bool)
        return self.validity

    # ---- transforms ------------------------------------------------------
    def take(self, indices: np.ndarray) -> "Column":
        v = self.values[indices]
        m = None if self.validity is None else self.validity[indices]
        return Column(self.ftype, v, m)

    def filter(self, mask: np.ndarray) -> "Column":
        v = self.values[mask]
        m = None if self.validity is None else self.validity[mask]
        return Column(self.ftype, v, m)

    def slice(self, start: int, stop: int) -> "Column":
        m = None if self.validity is None else self.validity[start:stop]
        return Column(self.ftype, self.values[start:stop], m)

    @staticmethod
    def concat(cols: Sequence["Column"]) -> "Column":
        assert cols
        ftype = cols[0].ftype
        values = np.concatenate([c.values for c in cols])
        if all(c.validity is None for c in cols):
            validity = None
        else:
            validity = np.concatenate([c.valid_mask() for c in cols])
        return Column(ftype, values, validity)

    def to_pylist(self) -> list:
        return [self.get(i) for i in range(len(self))]


@dataclass
class Chunk:
    """A batch of rows in columnar layout (ref: util/chunk/chunk.go:36)."""

    columns: List[Column]

    def __post_init__(self):
        if self.columns:
            n = len(self.columns[0])
            if not all(len(c) == n for c in self.columns):
                from tidb_tpu.errors import ExecutionError
                raise ExecutionError(
                    f"ragged chunk: column lengths "
                    f"{[len(c) for c in self.columns]}")

    # ---- shape -----------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    def __len__(self) -> int:
        return self.num_rows

    @property
    def field_types(self) -> List[FieldType]:
        return [c.ftype for c in self.columns]

    # ---- row access (result delivery; not a hot path) --------------------
    def row(self, i: int) -> tuple:
        return tuple(c.get(i) for c in self.columns)

    def rows(self) -> List[tuple]:
        return [self.row(i) for i in range(self.num_rows)]

    # ---- transforms ------------------------------------------------------
    def take(self, indices: np.ndarray) -> "Chunk":
        return Chunk([c.take(indices) for c in self.columns])

    def filter(self, mask: np.ndarray) -> "Chunk":
        return Chunk([c.filter(mask) for c in self.columns])

    def slice(self, start: int, stop: int) -> "Chunk":
        return Chunk([c.slice(start, stop) for c in self.columns])

    def project(self, col_idx: Sequence[int]) -> "Chunk":
        return Chunk([self.columns[i] for i in col_idx])

    @staticmethod
    def concat(chunks: Sequence["Chunk"]) -> "Chunk":
        assert chunks
        ncol = chunks[0].num_cols
        assert all(ch.num_cols == ncol for ch in chunks), \
            "cannot concat chunks of different widths"
        return Chunk([Column.concat([ch.columns[j] for ch in chunks])
                      for j in range(ncol)])

    @staticmethod
    def from_columns_data(ftypes: Sequence[FieldType],
                          data: Sequence[Sequence]) -> "Chunk":
        return Chunk([Column.from_list(ft, col) for ft, col in zip(ftypes, data)])

    @staticmethod
    def from_rows(ftypes: Sequence[FieldType], rows: Iterable[Sequence]) -> "Chunk":
        rows = list(rows)
        return Chunk([Column.from_list(ft, [r[j] for r in rows])
                      for j, ft in enumerate(ftypes)])

    def memory_usage(self) -> int:
        total = 0
        for c in self.columns:
            if c.ftype.is_varlen:
                total += sum(len(str(s)) for s in c.values) + 8 * len(c)
            else:
                total += c.values.nbytes
            if c.validity is not None:
                total += c.validity.nbytes
        return total

    def __repr__(self) -> str:
        return f"Chunk({self.num_rows} rows × {self.num_cols} cols)"


def iter_chunks(chunk: Chunk, max_rows: int = DEFAULT_CHUNK_SIZE):
    """Split a big chunk into batches (ref: util/chunk/iterator.go)."""
    for start in range(0, chunk.num_rows, max_rows):
        yield chunk.slice(start, min(start + max_rows, chunk.num_rows))
