"""tidb_tpu — a TPU-native analytical SQL execution framework.

A ground-up, TPU-first re-design of the capabilities of TiDB (the reference
at /root/reference): an Arrow-like columnar Chunk batch format, a vectorized
volcano executor (hash aggregation, hash join, sort/TopN, vectorized scalar and
aggregate expression evaluation), a cost-based planner routing plan subtrees to
pluggable execution backends, and a distributed execution layer expressed as
pjit/shard_map partitioning over a TPU mesh instead of MPP gRPC exchanges.

Layer map (mirrors SURVEY.md §1, re-imagined for TPU):

    session/     statement lifecycle (ref: session/session.go)
    parser/      SQL → AST           (ref: parser/)
    planner/     logical+physical optimization (ref: planner/)
    executor/    volcano operators over Chunks (ref: executor/)
    expression/  scalar + aggregate vectorized eval (ref: expression/)
    chunk/       columnar batch format (ref: util/chunk/)
    types/       MySQL-flavoured type system (ref: types/)
    ops/         the TPU kernel library (jax/XLA/pallas) — the "coprocessor"
    parallel/    mesh + shard_map exchanges (ref: MPP / store/copr)
    storage/     in-memory column store w/ region sharding (ref: unistore)
    catalog/     schema metadata (ref: infoschema/, meta/)
    utils/       memory tracking, runtime stats (ref: util/memory, execdetails)
"""

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy: importing tidb_tpu.chunk/types must not pull the whole session
    # stack (and jax) in.
    if name == "Session":
        try:
            from tidb_tpu.session import Session
        except ImportError as e:
            raise AttributeError(f"Session unavailable: {e}") from e
        return Session
    raise AttributeError(name)
