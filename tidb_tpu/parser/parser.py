"""Recursive-descent SQL parser (ref: parser/parser.y grammar, hand-rolled).

Expression precedence ladder (subset of parser/misc.go):
    OR < XOR < AND < NOT < predicate(cmp, IS, LIKE, IN, BETWEEN)
       < add/sub < mul/div/mod < unary < primary
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from tidb_tpu import types as T
from tidb_tpu.errors import ParseError
from tidb_tpu.parser import ast
from tidb_tpu.parser.lexer import Token, tokenize
from tidb_tpu.types import FieldType, TypeKind


def parse(sql: str) -> List[ast.StmtNode]:
    """Parse a semicolon-separated script → statement list."""
    p = Parser(tokenize(sql), sql)
    stmts = []
    while not p.at("eof"):
        if p.try_op(";"):
            continue
        stmts.append(p.statement())
        if not p.at("eof"):
            p.expect_op(";")
    return stmts


def parse_with_text(sql: str) -> List[Tuple[ast.StmtNode, str]]:
    """Like parse(), but pairs each statement with its own source slice
    (for per-statement logging/digests in multi-statement scripts)."""
    toks = tokenize(sql)
    p = Parser(toks, sql)
    out = []
    while not p.at("eof"):
        if p.try_op(";"):
            continue
        start = p.cur.pos
        stmt = p.statement()
        end = p.cur.pos if not p.at("eof") else len(sql)
        out.append((stmt, sql[start:end].strip().rstrip(";").strip()))
        if not p.at("eof"):
            p.expect_op(";")
    return out


def parse_one(sql: str) -> ast.StmtNode:
    stmts = parse(sql)
    if len(stmts) != 1:
        raise ParseError(f"expected one statement, got {len(stmts)}")
    return stmts[0]


class Parser:
    def __init__(self, tokens: List[Token], src: str = ""):
        # hint comments are only meaningful right after SELECT; anywhere
        # else they behave like ordinary comments (dropped), so e.g.
        # INSERT /*+ x() */ INTO keeps parsing
        kept: List[Token] = []
        for t in tokens:
            if t.kind == "hint" and not (kept and kept[-1].is_kw("select")):
                continue
            kept.append(t)
        self.toks = kept
        self.src = src
        self.i = 0

    # ---- token plumbing --------------------------------------------------
    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def at(self, kind: str) -> bool:
        return self.cur.kind == kind

    def at_kw(self, *kws: str) -> bool:
        return self.cur.is_kw(*kws)

    def at_op(self, *ops: str) -> bool:
        return self.cur.kind == "op" and self.cur.value in ops

    def advance(self) -> Token:
        t = self.cur
        self.i += 1
        return t

    def try_kw(self, *kws: str) -> Optional[Token]:
        if self.at_kw(*kws):
            return self.advance()
        return None

    def try_op(self, *ops: str) -> Optional[Token]:
        if self.at_op(*ops):
            return self.advance()
        return None

    def expect_kw(self, *kws: str) -> Token:
        if not self.at_kw(*kws):
            raise ParseError(
                f"expected {'/'.join(kws).upper()} near {self._near()}")
        return self.advance()

    def expect_op(self, op: str) -> Token:
        if not self.at_op(op):
            raise ParseError(f"expected {op!r} near {self._near()}")
        return self.advance()

    def ident(self) -> str:
        if self.at("ident"):
            return self.advance().value
        # non-reserved keywords usable as identifiers
        if self.cur.kind == "kw" and self.cur.value in (
                "date", "time", "timestamp", "key", "tables", "columns",
                "comment", "engine", "charset", "begin", "analyze", "offset",
                "set", "values", "variables", "if",
                "add", "to", "column", "rename", "over", "partition",
                "alter", "mod", "user", "grants", "privileges", "of",
                "data", "load"):
            return self.advance().value
        raise ParseError(f"expected identifier near {self._near()}")

    def _near(self) -> str:
        t = self.cur
        return f"{t.kind}:{t.value!r} (token {self.i})"

    # ---- statements ------------------------------------------------------
    def statement(self) -> ast.StmtNode:
        if self.at_kw("with"):
            return self.with_stmt()
        if self.at_kw("select") or self.at_op("("):
            return self.select_with_setops()
        if self.at_kw("create"):
            if self.toks[self.i + 1].is_kw("user"):
                return self.create_user()
            nxt = [str(self.toks[self.i + k].value).lower()
                   for k in (1, 2, 3)
                   if self.i + k < len(self.toks)]
            if nxt and (nxt[0] == "view" or nxt[:1] == ["or"]
                        and "view" in nxt):
                return self.create_view()
            return self.create_table()
        if self.at_kw("drop"):
            if self.toks[self.i + 1].is_kw("user"):
                return self.drop_user()
            if str(self.toks[self.i + 1].value).lower() == "view":
                return self.drop_view()
            return self.drop_table()
        if self.at_kw("load"):
            return self.load_data()
        if self.at_kw("backup"):
            self.advance()
            self.expect_kw("to")
            if not self.at("str"):
                raise ParseError(f"expected path string near {self._near()}")
            return ast.BackupStmt(self.advance().value)
        if self.at_kw("restore"):
            self.advance()
            self.expect_kw("from")
            if not self.at("str"):
                raise ParseError(f"expected path string near {self._near()}")
            return ast.RestoreStmt(self.advance().value)
        if self.at_kw("grant"):
            return self.grant_stmt()
        if self.at_kw("revoke"):
            return self.grant_stmt(revoke=True)
        if self.at_kw("alter"):
            return self.alter_table()
        if self.at_kw("truncate"):
            self.advance()
            self.try_kw("table")
            return ast.TruncateTable(self.ident())
        if self.at_kw("insert", "replace"):
            return self.insert()
        if self.at_kw("update"):
            return self.update()
        if self.at_kw("delete"):
            return self.delete()
        if self.at_kw("explain"):
            self.advance()
            analyze = bool(self.try_kw("analyze"))
            return ast.Explain(self.statement(), analyze)
        if self.at_kw("trace"):
            self.advance()
            fmt = "row"
            if self._word("format"):
                self.try_op("=")
                if not self.at("str"):
                    raise ParseError(
                        f"expected format string near {self._near()}")
                fmt = str(self.advance().value).lower()
                if fmt not in ("row", "chrome"):
                    raise ParseError(f"unknown TRACE format {fmt!r}")
            return ast.TraceStmt(self.statement(), fmt)
        if self.at_kw("set"):
            return self.set_stmt()
        if self.at_kw("show"):
            return self.show_stmt()
        if self.at_kw("analyze"):
            self.advance()
            self.expect_kw("table")
            names = [self.ident()]
            while self.try_op(","):
                names.append(self.ident())
            return ast.AnalyzeTable(names)
        if self.at_kw("use"):
            self.advance()
            return ast.UseStmt(self.ident())
        if self.at_kw("begin"):
            self.advance()
            mode = None
            if self.at("ident") and str(self.cur.value).lower() in (
                    "pessimistic", "optimistic"):
                mode = self.advance().value.lower()
            return ast.BeginStmt(mode)
        if self.at_kw("start"):
            self.advance()
            self.expect_kw("transaction")
            return ast.BeginStmt()
        if self.at_kw("commit"):
            self.advance()
            return ast.CommitStmt()
        if self.at_kw("rollback"):
            self.advance()
            return ast.RollbackStmt()
        if self.at("ident") and str(self.cur.value).lower() == "kill":
            # KILL [QUERY|CONNECTION] <id> — "kill" stays an ident (like
            # BEGIN's modes) so it remains usable as a column name
            self.advance()
            query_only = False
            if self.at("ident") and str(self.cur.value).lower() in (
                    "query", "connection"):
                query_only = str(self.advance().value).lower() == "query"
            if not self.at("int"):
                raise ParseError(
                    f"expected connection id near {self._near()}")
            return ast.KillStmt(int(self.advance().value), query_only)
        raise ParseError(f"unsupported statement near {self._near()}")

    def load_data(self) -> ast.StmtNode:
        """LOAD DATA [LOCAL] INFILE 'p' INTO TABLE t
        [FIELDS TERMINATED BY 'c'] [IGNORE n LINES]"""
        self.expect_kw("load")
        self.expect_kw("data")
        if self.at("ident") and str(self.cur.value).lower() == "local":
            self.advance()
        if not (self.at("ident") and
                str(self.cur.value).lower() == "infile"):
            raise ParseError(f"expected INFILE near {self._near()}")
        self.advance()
        if not self.at("str"):
            raise ParseError(f"expected file path near {self._near()}")
        path = self.advance().value
        self.expect_kw("into")
        self.expect_kw("table")
        table = self.ident()
        delimiter = ","
        if self.at("ident") and str(self.cur.value).lower() == "fields":
            self.advance()
            if not (self.at("ident") and
                    str(self.cur.value).lower() == "terminated"):
                raise ParseError(f"expected TERMINATED near {self._near()}")
            self.advance()
            self.expect_kw("by")
            delimiter = self.advance().value
        ignore_lines = 0
        if self.try_kw("ignore"):
            ignore_lines = int(self.advance().value)
            if self.at("ident") and str(self.cur.value).lower() == "lines":
                self.advance()
        return ast.LoadData(table, path, delimiter, ignore_lines)

    # ---- user admin (ref: parser grammar CreateUserStmt/GrantStmt) -------
    def _user_spec(self) -> str:
        """'u'@'host' | u@'host' | u — host is parsed and ignored (the
        single-process engine has no host-based rules)."""
        if self.at("str"):
            name = self.advance().value
        else:
            name = self.ident()
        if self.try_op("@"):
            if self.at("str"):
                self.advance()
            else:
                self.ident()
        return name

    def create_user(self) -> ast.StmtNode:
        self.expect_kw("create")
        self.expect_kw("user")
        if_not_exists = False
        if self.try_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            if_not_exists = True
        user = self._user_spec()
        password = ""
        if self.try_kw("identified"):
            self.expect_kw("by")
            if not self.at("str"):
                raise ParseError(f"expected password string near "
                                 f"{self._near()}")
            password = self.advance().value
        return ast.CreateUser(user, password, if_not_exists)

    def drop_user(self) -> ast.StmtNode:
        self.expect_kw("drop")
        self.expect_kw("user")
        if_exists = False
        if self.try_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        return ast.DropUser(self._user_spec(), if_exists)

    def grant_stmt(self, revoke: bool = False) -> ast.StmtNode:
        self.advance()                      # GRANT | REVOKE
        privs = []
        while True:
            if self.try_kw("all"):
                self.try_kw("privileges")
                privs.append("ALL")
            elif self.at_kw("select", "insert", "update", "delete",
                            "create", "drop", "alter", "index"):
                privs.append(self.advance().value.upper())
            elif self.at("ident") and \
                    str(self.cur.value).lower() in ("process", "super"):
                # global admin privileges (not reserved words in MySQL)
                privs.append(str(self.advance().value).upper())
            else:
                raise ParseError(f"expected privilege near {self._near()}")
            if not self.try_op(","):
                break
        self.expect_kw("on")
        scope = self._grant_scope()
        self.expect_kw("from" if revoke else "to")
        user = self._user_spec()
        return ast.GrantStmt(privs, scope, user, revoke)

    def _grant_scope(self) -> str:
        if self.try_op("*"):
            if self.try_op("."):
                if self.try_op("*"):
                    return "*.*"
                return f"*.{self.ident()}"
            return "*.*"
        first = self.ident()
        if self.try_op("."):
            if self.try_op("*"):
                return f"{first}.*"
            return f"{first}.{self.ident()}"
        return first

    # ---- SELECT ----------------------------------------------------------
    def with_stmt(self) -> ast.StmtNode:
        self.expect_kw("with")
        recursive = bool(self.try_kw("recursive"))
        ctes = []
        while True:
            name = self.ident()
            cols = None
            if self.try_op("("):
                cols = [self.ident()]
                while self.try_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
            self.expect_kw("as")
            self.expect_op("(")
            sel = self.select_with_setops()
            self.expect_op(")")
            ctes.append(ast.CteDef(name, cols, sel))
            if not self.try_op(","):
                break
        return ast.WithStmt(recursive, ctes, self.select_with_setops())

    def select_with_setops(self) -> ast.StmtNode:
        left = self.select_core()
        while self.at_kw("union", "except", "intersect"):
            op = self.advance().value
            all_ = bool(self.try_kw("all"))
            self.try_kw("distinct")
            # trailing ORDER BY/LIMIT belongs to the set-op, not the operand
            right = self.select_core(allow_tail=False)
            left = ast.SetOpStmt(op, all_, left, right)
        # trailing ORDER BY / LIMIT bind to the set-op result; also handles
        # "(select ...) order by ..." where the parens consumed no tail
        if isinstance(left, (ast.SetOpStmt, ast.SelectStmt)):
            ob = self.order_by_clause()
            lim = self.limit_clause()
            if ob:
                left.order_by = ob
            if lim is not None:
                left.limit = lim
        return left

    def select_core(self, allow_tail: bool = True) -> ast.StmtNode:
        if self.try_op("("):
            s = self.select_with_setops()
            self.expect_op(")")
            return s
        self.expect_kw("select")
        hints = self._parse_hints() if self.at("hint") else []
        distinct = bool(self.try_kw("distinct"))
        self.try_kw("all")
        items = [self.select_item()]
        while self.try_op(","):
            items.append(self.select_item())
        from_ = None
        if self.try_kw("from"):
            from_ = self.table_refs()
        where = self.expr() if self.try_kw("where") else None
        group_by: List[ast.ExprNode] = []
        rollup = False
        if self.try_kw("group"):
            self.expect_kw("by")
            group_by.append(self.expr())
            while self.try_op(","):
                group_by.append(self.expr())
            if self.try_kw("with"):
                self.expect_kw("rollup")
                rollup = True
        having = self.expr() if self.try_kw("having") else None
        order_by = self.order_by_clause() if allow_tail else []
        limit = self.limit_clause() if allow_tail else None
        for_update = False
        if allow_tail and self.try_kw("for"):
            self.expect_kw("update")
            for_update = True
        return ast.SelectStmt(items, from_, where, group_by, having,
                               order_by, limit, distinct,
                               for_update=for_update, hints=hints,
                               rollup=rollup)

    def _parse_hints(self) -> List:
        """/*+ NAME(arg, ...) NAME2() ... */ → [(name_lower, [args])]
        (ref: parser/hintparser.y; unknown hints are kept — the planner
        ignores what it doesn't steer)."""
        import re as _re
        text = str(self.advance().value)
        out = []
        for m in _re.finditer(r"([A-Za-z_]\w*)\s*\(([^()]*)\)", text):
            args = [a.strip().strip("`").lower()
                    for a in m.group(2).split(",") if a.strip()]
            out.append((m.group(1).lower(), args))
        return out

    def select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.advance()
            return ast.SelectItem(ast.Star())
        # t.* form
        if self.at("ident") and self.toks[self.i + 1].kind == "op" \
                and self.toks[self.i + 1].value == "." \
                and self.toks[self.i + 2].kind == "op" \
                and self.toks[self.i + 2].value == "*":
            t = self.advance().value
            self.advance()
            self.advance()
            return ast.SelectItem(ast.Star(table=t))
        e = self.expr()
        alias = None
        if self.try_kw("as"):
            alias = self.ident_or_string()
        elif self.at("ident"):
            alias = self.advance().value
        elif self.at("str"):
            alias = self.advance().value
        return ast.SelectItem(e, alias)

    def ident_or_string(self) -> str:
        if self.at("str"):
            return self.advance().value
        return self.ident()

    def order_by_clause(self):
        out = []
        if self.try_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.expr()
                desc = False
                if self.try_kw("desc"):
                    desc = True
                else:
                    self.try_kw("asc")
                out.append((e, desc))
                if not self.try_op(","):
                    break
        return out

    def limit_clause(self):
        if not self.try_kw("limit"):
            return None
        first = self._int_value()
        if self.try_op(","):
            return (first, self._int_value())
        if self.try_kw("offset"):
            return (self._int_value(), first)
        return (0, first)

    def _int_value(self) -> int:
        if not self.at("int"):
            raise ParseError(f"expected integer near {self._near()}")
        return self.advance().value

    # ---- table references ------------------------------------------------
    def table_refs(self) -> ast.TableRef:
        left = self.join_chain()
        while self.try_op(","):
            right = self.join_chain()
            left = ast.JoinExpr("cross", left, right)
        return left

    def join_chain(self) -> ast.TableRef:
        left = self.table_factor()
        while True:
            kind = None
            if self.try_kw("inner"):
                self.expect_kw("join")
                kind = "inner"
            elif self.try_kw("cross"):
                self.expect_kw("join")
                kind = "cross"
            elif self.at_kw("left", "right"):
                side = self.advance().value
                self.try_kw("outer")
                self.expect_kw("join")
                kind = side
            elif self.try_kw("join"):
                kind = "inner"
            else:
                break
            right = self.table_factor()
            on = None
            using = None
            if self.try_kw("on"):
                on = self.expr()
            elif self.try_kw("using"):
                self.expect_op("(")
                using = [self.ident()]
                while self.try_op(","):
                    using.append(self.ident())
                self.expect_op(")")
            left = ast.JoinExpr(kind, left, right, on, using)
        return left

    def table_factor(self) -> ast.TableRef:
        if self.try_op("("):
            if self.at_kw("select"):
                s = self.select_with_setops()
                self.expect_op(")")
                self.try_kw("as")
                alias = self.ident()
                return ast.SubqueryTable(s, alias)
            refs = self.table_refs()
            self.expect_op(")")
            return refs
        name = self.ident()
        db = None
        if self.try_op("."):
            db = name
            name = self.ident()
        alias = None
        as_of = None
        if self.try_kw("as"):
            if self.try_kw("of"):
                self.expect_kw("timestamp")
                as_of = self.expr()
            else:
                alias = self.ident()
        elif self.at("ident"):
            alias = self.advance().value
        if as_of is not None and alias is None:
            # optional alias AFTER the AS OF clause: t AS OF ... [AS] x
            if self.try_kw("as"):
                alias = self.ident()
            elif self.at("ident"):
                alias = self.advance().value
        return ast.TableName(name, alias, as_of=as_of, db=db)

    # ---- DDL -------------------------------------------------------------
    def create_table(self):
        self.expect_kw("create")
        unique = bool(self.try_kw("unique"))
        if unique or self.at_kw("index", "key"):
            if not self.at_kw("index", "key"):
                raise ParseError(f"expected INDEX near {self._near()}")
            self.advance()                 # INDEX | KEY
            iname = self.ident()
            self.expect_kw("on")
            tname = self.ident()
            self.expect_op("(")
            cols = [self.ident()]
            while self.try_op(","):
                cols.append(self.ident())
            self.expect_op(")")
            return ast.CreateIndex(iname, tname, cols, unique)
        self.expect_kw("table")
        if_not_exists = False
        if self.try_kw("if"):
            self.expect_kw("not")
            # "exists" arrives as kw
            self.expect_kw("exists")
            if_not_exists = True
        name = self.ident()
        self.expect_op("(")
        columns: List[ast.ColumnDef] = []
        pk: List[str] = []
        indexes: List[Tuple[str, List[str]]] = []
        while True:
            if self.try_kw("primary"):
                self.expect_kw("key")
                self.expect_op("(")
                pk = [self.ident()]
                while self.try_op(","):
                    pk.append(self.ident())
                self.expect_op(")")
            elif self.at_kw("key", "index", "unique"):
                unique = bool(self.try_kw("unique"))
                self.try_kw("key") or self.try_kw("index")
                iname = self.ident() if self.at("ident") else f"idx_{len(indexes)}"
                self.expect_op("(")
                cols = [self.ident()]
                while self.try_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                indexes.append(ast.IndexDef(iname, cols, unique))
            else:
                columns.append(self.column_def())
            if not self.try_op(","):
                break
        self.expect_op(")")
        # swallow table options (ENGINE=x CHARSET=y …) up to an optional
        # PARTITION BY clause
        while not self.at("eof") and not self.at_op(";") \
                and not self.at_kw("partition"):
            self.advance()
        part = self._partition_spec() if self.at_kw("partition") else None
        while not self.at("eof") and not self.at_op(";"):
            self.advance()
        for c in columns:
            if c.primary_key:
                pk = [c.name]
        if pk:
            for c in columns:
                if c.name in pk:
                    c.ftype = c.ftype.with_nullable(False)
        return ast.CreateTable(name, columns, pk, indexes, if_not_exists,
                               part)

    def create_view(self) -> ast.CreateView:
        self.expect_kw("create")
        or_replace = False
        if self.try_kw("or"):
            self.expect_kw("replace")
            or_replace = True
        if not self._word("view"):
            raise ParseError(f"expected VIEW near {self._near()}")
        name = self.ident()
        cols = None
        if self.try_op("("):
            cols = [self.ident()]
            while self.try_op(","):
                cols.append(self.ident())
            self.expect_op(")")
        self.expect_kw("as")
        start = self.cur.pos
        sel = self.select_with_setops()
        end = self.cur.pos if not self.at("eof") else len(self.src)
        text = self.src[start:end].strip().rstrip(";").strip()
        return ast.CreateView(name, sel, cols, or_replace, text)

    def drop_view(self) -> ast.DropView:
        self.expect_kw("drop")
        if not self._word("view"):
            raise ParseError(f"expected VIEW near {self._near()}")
        if_exists = False
        if self.try_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        names = [self.ident()]
        while self.try_op(","):
            names.append(self.ident())
        return ast.DropView(names, if_exists)

    def _word(self, w: str) -> bool:
        """Match a non-reserved word token (ident or kw) by value."""
        if (self.cur.kind in ("ident", "kw")
                and str(self.cur.value).lower() == w):
            self.advance()
            return True
        return False

    def _partition_spec(self) -> ast.PartitionSpec:
        """PARTITION BY RANGE [COLUMNS] (col) (PARTITION p VALUES LESS
        THAN (bound|MAXVALUE), …) | PARTITION BY HASH (col) PARTITIONS n
        (ref: parser/parser.y PartitionOpt)."""
        self.expect_kw("partition")
        self.expect_kw("by")
        if self._word("range"):
            self._word("columns")
            self.expect_op("(")
            col = self.ident()
            self.expect_op(")")
            self.expect_op("(")
            defs = []
            while True:
                self.expect_kw("partition")
                pname = self.ident()
                self.expect_kw("values")
                if not self._word("less") or not self._word("than"):
                    raise ParseError(
                        f"expected VALUES LESS THAN near {self._near()}")
                self.expect_op("(")
                if self._word("maxvalue"):
                    bound = None
                else:
                    bound = self.expr()
                self.expect_op(")")
                defs.append(ast.PartitionDef(pname, bound))
                if not self.try_op(","):
                    break
            self.expect_op(")")
            if not defs:
                raise ParseError("RANGE partitioning needs partitions")
            return ast.PartitionSpec("range", col, defs)
        if self._word("hash"):
            self.expect_op("(")
            col = self.ident()
            self.expect_op(")")
            if not self._word("partitions"):
                raise ParseError(
                    f"expected PARTITIONS near {self._near()}")
            tok = self.advance()
            try:
                num = int(tok.value)
            except (TypeError, ValueError):
                raise ParseError("PARTITIONS requires an integer")
            if num < 1:
                raise ParseError("PARTITIONS must be at least 1")
            return ast.PartitionSpec(
                "hash", col,
                [ast.PartitionDef(f"p{i}") for i in range(num)], num)
        raise ParseError(
            f"unsupported PARTITION BY near {self._near()} "
            f"(RANGE and HASH are supported)")

    def alter_table(self) -> ast.AlterTable:
        self.expect_kw("alter")
        self.expect_kw("table")
        name = self.ident()
        if self.try_kw("add"):
            if self.at_kw("partition"):
                self.advance()
                self.expect_op("(")
                self.expect_kw("partition")
                pname = self.ident()
                self.expect_kw("values")
                if not self._word("less") or not self._word("than"):
                    raise ParseError(
                        f"expected VALUES LESS THAN near {self._near()}")
                self.expect_op("(")
                bound = None if self._word("maxvalue") else self.expr()
                self.expect_op(")")
                self.expect_op(")")
                return ast.AlterTable(name, "add_partition",
                                      partition_def=ast.PartitionDef(
                                          pname, bound))
            self.try_kw("column")
            return ast.AlterTable(name, "add_column",
                                  column=self.column_def())
        if self.try_kw("drop"):
            if self.at_kw("partition"):
                self.advance()
                return ast.AlterTable(name, "drop_partition",
                                      partition_name=self.ident())
            self.try_kw("column")
            return ast.AlterTable(name, "drop_column",
                                  column_name=self.ident())
        if self.try_kw("truncate"):
            self.expect_kw("partition")
            return ast.AlterTable(name, "truncate_partition",
                                  partition_name=self.ident())
        if self.try_kw("rename"):
            self.try_kw("to")
            return ast.AlterTable(name, "rename",
                                  new_name=self.ident())
        raise ParseError(f"unsupported ALTER TABLE near {self._near()}")

    def column_def(self) -> ast.ColumnDef:
        name = self.ident()
        ftype = self.field_type()
        primary = False
        default = None
        nullable = True
        auto_inc = False
        while True:
            if self.try_kw("not"):
                self.expect_kw("null")
                nullable = False
            elif self.try_kw("null"):
                nullable = True
            elif self.try_kw("primary"):
                self.expect_kw("key")
                primary = True
                nullable = False
            elif self.try_kw("default"):
                default = self.expr()
            elif self.try_kw("auto_increment"):
                auto_inc = True
            elif self.try_kw("unique", "key"):
                pass
            elif self.try_kw("comment"):
                self.advance()  # the comment string
            elif self.at_kw("charset", "collate"):
                is_collate = str(self.cur.value).lower() == "collate"
                self.advance()
                self.try_op("=")
                cname = str(self.advance().value).lower()
                if is_collate:
                    from dataclasses import replace as _replace

                    from tidb_tpu.types import (BIN_COLLATIONS,
                                                CI_COLLATIONS)
                    if cname in CI_COLLATIONS:
                        if not ftype.kind.is_string:
                            raise ParseError(
                                f"COLLATE is not valid for "
                                f"{ftype.kind.value} columns")
                        ftype = _replace(ftype, collation=cname)
                    elif cname not in BIN_COLLATIONS:
                        raise ParseError(f"Unknown collation: '{cname}'")
            else:
                break
        ftype = ftype.with_nullable(nullable)
        return ast.ColumnDef(name, ftype, primary, default, auto_inc)

    def field_type(self) -> FieldType:
        t = self.advance()
        if t.kind == "ident" and str(t.value).lower() == "json":
            return FieldType(TypeKind.JSON, True)
        if t.kind == "kw" and t.value == "set" or \
                t.kind == "ident" and str(t.value).lower() == "enum":
            kind = TypeKind.SET if t.value == "set" else TypeKind.ENUM
            self.expect_op("(")
            elems = []
            while True:
                if not self.at("str"):
                    raise ParseError(
                        f"expected string element near {self._near()}")
                elems.append(self.advance().value)
                if not self.try_op(","):
                    break
            self.expect_op(")")
            return FieldType(kind, True, elems=tuple(elems))
        if t.kind != "kw":
            raise ParseError(f"expected type near {self._near()}")
        kw = t.value
        args: List[int] = []
        if self.try_op("("):
            args.append(self._int_value())
            while self.try_op(","):
                args.append(self._int_value())
            self.expect_op(")")
        unsigned = bool(self.try_kw("unsigned"))
        self.try_kw("signed")
        kind_map = {
            "int": TypeKind.INT, "integer": TypeKind.INT,
            "bigint": TypeKind.BIGINT, "smallint": TypeKind.SMALLINT,
            "tinyint": TypeKind.TINYINT, "float": TypeKind.FLOAT,
            "double": TypeKind.DOUBLE, "decimal": TypeKind.DECIMAL,
            "numeric": TypeKind.DECIMAL, "char": TypeKind.CHAR,
            "varchar": TypeKind.VARCHAR, "text": TypeKind.VARCHAR,
            "date": TypeKind.DATE, "datetime": TypeKind.DATETIME,
            "timestamp": TypeKind.TIMESTAMP, "time": TypeKind.TIME,
        }
        kind = kind_map.get(kw)
        if kind is None:
            raise ParseError(f"unsupported type {kw!r}")
        precision = args[0] if args else (10 if kind is TypeKind.DECIMAL else 0)
        scale = args[1] if len(args) > 1 else 0
        return FieldType(kind, True, precision, scale, unsigned)

    def drop_table(self):
        self.expect_kw("drop")
        if self.try_kw("index"):
            iname = self.ident()
            self.expect_kw("on")
            return ast.DropIndex(iname, self.ident())
        self.expect_kw("table")
        if_exists = False
        if self.try_kw("if"):
            self.expect_kw("exists")
            if_exists = True
        names = [self.ident()]
        while self.try_op(","):
            names.append(self.ident())
        return ast.DropTable(names, if_exists)

    # ---- DML -------------------------------------------------------------
    def insert(self) -> ast.Insert:
        replace = self.advance().value == "replace"
        ignore = bool(self.try_kw("ignore"))
        self.expect_kw("into")
        table = self.ident()
        columns = None
        if self.try_op("("):
            columns = [self.ident()]
            while self.try_op(","):
                columns.append(self.ident())
            self.expect_op(")")
        if self.at_kw("select"):
            return ast.Insert(table, columns,
                              select=self.select_with_setops(),
                              replace=replace, ignore=ignore)
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_op("(")
            row = [self.expr()]
            while self.try_op(","):
                row.append(self.expr())
            self.expect_op(")")
            rows.append(row)
            if not self.try_op(","):
                break
        return ast.Insert(table, columns, rows, replace=replace, ignore=ignore)

    def update(self) -> ast.Update:
        self.expect_kw("update")
        tname = self.ident()
        alias = None
        if self.try_kw("as"):
            alias = self.ident()
        elif self.at("ident"):
            alias = self.advance().value
        self.expect_kw("set")
        assigns = []
        while True:
            col = self.ident()
            # allow qualified t.col
            if self.try_op("."):
                col = self.ident()
            self.expect_op("=")
            assigns.append((col, self.expr()))
            if not self.try_op(","):
                break
        where = self.expr() if self.try_kw("where") else None
        return ast.Update(ast.TableName(tname, alias), assigns, where)

    def delete(self) -> ast.Delete:
        self.expect_kw("delete")
        self.expect_kw("from")
        tname = self.ident()
        alias = None
        if self.at("ident"):
            alias = self.advance().value
        where = self.expr() if self.try_kw("where") else None
        return ast.Delete(ast.TableName(tname, alias), where)

    # ---- misc statements -------------------------------------------------
    def set_stmt(self) -> ast.SetStmt:
        self.expect_kw("set")
        global_scope = bool(self.try_kw("global"))
        self.try_kw("session")
        assigns = []
        while True:
            if self.try_op("@@"):
                name = self._sysvar_name()
            elif self.try_op("@"):
                name = "@" + self.ident()
            else:
                name = self.ident()
            if not self.try_op("="):
                self.expect_op(":=")
            assigns.append((name, self.expr()))
            if not self.try_op(","):
                break
        return ast.SetStmt(assigns, global_scope)

    def _sysvar_name(self) -> str:
        # @@x | @@session.x | @@global.x
        if self.try_kw("session", "global"):
            self.expect_op(".")
            return self.ident()
        name = self.ident()
        if self.try_op("."):
            name = self.ident()
        return name

    def show_stmt(self) -> ast.ShowStmt:
        self.expect_kw("show")
        if self.try_kw("grants"):
            target = None
            if self.try_kw("for"):
                target = self._user_spec()
            return ast.ShowStmt("grants", target=target)
        if self.try_kw("tables"):
            return ast.ShowStmt("tables")
        if self.try_kw("databases"):
            return ast.ShowStmt("databases")
        if self.try_kw("variables"):
            like = None
            if self.try_kw("like"):
                if not self.at("str"):
                    raise ParseError(
                        f"expected string pattern near {self._near()}")
                like = self.advance().value
            return ast.ShowStmt("variables", like=like)
        if self.try_kw("columns"):
            self.expect_kw("from")
            return ast.ShowStmt("columns", target=self.ident())
        if self.at_kw("index", "key") or (
                self.cur.kind == "ident"
                and str(self.cur.value).lower() in ("indexes", "keys")):
            self.advance()
            self.expect_kw("from")
            return ast.ShowStmt("index", target=self.ident())
        if self.try_kw("create"):
            if self._word("view"):
                return ast.ShowStmt("create_view", target=self.ident())
            self.expect_kw("table")
            return ast.ShowStmt("create_table", target=self.ident())
        if self.at("ident") or self.at("kw"):
            word = str(self.cur.value).lower()
            if word == "metrics":
                self.advance()
                return ast.ShowStmt("metrics")
            if word == "slow":
                self.advance()
                self.ident()       # QUERIES
                return ast.ShowStmt("slow_queries")
            if word == "statement":
                self.advance()
                self.ident()       # SUMMARY
                return ast.ShowStmt("statement_summary")
            if word == "processlist":
                self.advance()
                return ast.ShowStmt("processlist")
            if word == "warnings":
                self.advance()
                return ast.ShowStmt("warnings")
            if word == "collation":
                self.advance()
                return ast.ShowStmt("collation")
            if word == "charset":
                self.advance()
                return ast.ShowStmt("charset")
        raise ParseError(f"unsupported SHOW near {self._near()}")

    # ---- expressions -----------------------------------------------------
    def expr(self) -> ast.ExprNode:
        return self.or_expr()

    def or_expr(self) -> ast.ExprNode:
        left = self.xor_expr()
        while self.at_kw("or") or self.at_op("||"):
            self.advance()
            left = ast.BinaryOp("or", left, self.xor_expr())
        return left

    def xor_expr(self) -> ast.ExprNode:
        left = self.and_expr()
        while self.try_kw("xor"):
            left = ast.BinaryOp("xor", left, self.and_expr())
        return left

    def and_expr(self) -> ast.ExprNode:
        left = self.not_expr()
        while self.at_kw("and") or self.at_op("&&"):
            self.advance()
            left = ast.BinaryOp("and", left, self.not_expr())
        return left

    def not_expr(self) -> ast.ExprNode:
        if self.try_kw("not") or self.try_op("!"):
            return ast.UnaryOp("not", self.not_expr())
        return self.predicate()

    _CMP = {"=": "eq", "<>": "ne", "!=": "ne", "<": "lt", "<=": "le",
            ">": "gt", ">=": "ge", "<=>": "nulleq"}

    def predicate(self) -> ast.ExprNode:
        left = self.add_expr()
        while True:
            if self.cur.kind == "op" and self.cur.value in self._CMP:
                op = self._CMP[self.advance().value]
                # comparison with subquery: = (SELECT ...)
                right = self.add_expr()
                left = ast.BinaryOp(op, left, right)
                continue
            negated = False
            save = self.i
            if self.try_kw("not"):
                negated = True
            if self.try_kw("is"):
                neg2 = bool(self.try_kw("not"))
                self.expect_kw("null")
                left = ast.IsNull(left, negated ^ neg2)
                continue
            if self.try_kw("in"):
                self.expect_op("(")
                if self.at_kw("select"):
                    sub = ast.Subquery(self.select_with_setops())
                    self.expect_op(")")
                    left = ast.InExpr(left, None, sub, negated)
                else:
                    items = [self.expr()]
                    while self.try_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    left = ast.InExpr(left, items, None, negated)
                continue
            if self.try_kw("between"):
                low = self.add_expr()
                self.expect_kw("and")
                high = self.add_expr()
                left = ast.Between(left, low, high, negated)
                continue
            if self.try_kw("like"):
                left = ast.LikeExpr(left, self.add_expr(), negated)
                continue
            if self.at_kw("regexp", "rlike") or (
                    self.at("ident") and
                    str(self.cur.value).lower() in ("regexp", "rlike")):
                self.advance()
                node = ast.FuncCall("regexp_like",
                                    [left, self.add_expr()])
                left = ast.UnaryOp("not", node) if negated else node
                continue
            if negated:
                self.i = save
            break
        return left

    def add_expr(self) -> ast.ExprNode:
        left = self.mul_expr()
        while self.at_op("+", "-"):
            op = "plus" if self.advance().value == "+" else "minus"
            left = ast.BinaryOp(op, left, self.mul_expr())
        return left

    def mul_expr(self) -> ast.ExprNode:
        left = self.unary_expr()
        while True:
            if self.at_op("*", "/", "%"):
                sym = self.advance().value
                op = {"*": "mul", "/": "div", "%": "mod"}[sym]
            elif self.at_kw("div"):
                self.advance()
                op = "intdiv"
            elif self.at_kw("mod"):
                self.advance()
                op = "mod"
            else:
                break
            left = ast.BinaryOp(op, left, self.unary_expr())
        return left

    def unary_expr(self) -> ast.ExprNode:
        if self.try_op("-"):
            return ast.UnaryOp("minus", self.unary_expr())
        if self.try_op("+"):
            return self.unary_expr()
        e = self.primary()
        # JSON path extraction operators: col->'$.a' / col->>'$.a'
        while self.at_op("->", "->>"):
            op = self.advance().value
            if not self.at("str"):
                raise ParseError(f"expected path string near {self._near()}")
            path = ast.Literal(self.advance().value, "str")
            e = ast.FuncCall("json_extract", [e, path])
            if op == "->>":
                e = ast.FuncCall("json_unquote", [e])
        return e

    def primary(self) -> ast.ExprNode:
        t = self.cur
        if t.kind in ("int", "decimal", "float", "str"):
            self.advance()
            return ast.Literal(t.value, t.kind)
        if t.is_kw("null"):
            self.advance()
            return ast.Literal(None, "null")
        if t.is_kw("true"):
            self.advance()
            return ast.Literal(1, "int")
        if t.is_kw("false"):
            self.advance()
            return ast.Literal(0, "int")
        if self.try_op("@@"):
            return ast.VariableRef(self._sysvar_name(), system=True)
        if self.try_op("@"):
            return ast.VariableRef(self.ident(), system=False)
        if self.try_op("("):
            if self.at_kw("select"):
                s = self.select_with_setops()
                self.expect_op(")")
                return ast.Subquery(s)
            e = self.expr()
            self.expect_op(")")
            return e
        if t.is_kw("exists"):
            self.advance()
            self.expect_op("(")
            s = self.select_with_setops()
            self.expect_op(")")
            return ast.ExistsExpr(ast.Subquery(s))
        if t.is_kw("case"):
            return self.case_expr()
        if t.is_kw("cast"):
            self.advance()
            self.expect_op("(")
            e = self.expr()
            self.expect_kw("as")
            ftype = self.field_type()
            self.expect_op(")")
            return ast.CastExpr(e, ftype)
        if t.is_kw("interval"):
            # INTERVAL(N, N1, ...) the comparison FUNCTION vs
            # INTERVAL expr UNIT the temporal literal: a comma at paren
            # depth 1 decides (MySQL's own disambiguation rule)
            if self.toks[self.i + 1].kind == "op" and \
                    self.toks[self.i + 1].value == "(":
                depth = 0
                is_fn = False
                for k in range(self.i + 1, len(self.toks)):
                    tk = self.toks[k]
                    if tk.kind != "op":
                        continue
                    if tk.value == "(":
                        depth += 1
                    elif tk.value == ")":
                        depth -= 1
                        if depth == 0:
                            break
                    elif tk.value == "," and depth == 1:
                        is_fn = True
                        break
                if is_fn:
                    self.advance()
                    return self._call("interval")
            self.advance()
            v = self.add_expr()
            unit = self.ident().lower()
            return ast.IntervalExpr(v, unit)
        if (t.kind == "ident" and str(t.value).lower() == "extract") and \
                self.toks[self.i + 1].kind == "op" and \
                self.toks[self.i + 1].value == "(":
            # EXTRACT(unit FROM expr) → the matching part function
            self.advance()
            self.expect_op("(")
            unit = str(self.ident()).lower()
            self.expect_kw("from")
            e = self.expr()
            self.expect_op(")")
            fn = {"year": "year", "month": "month", "day": "dayofmonth",
                  "hour": "hour", "minute": "minute", "second": "second",
                  "microsecond": "microsecond", "week": "week",
                  "quarter": "quarter"}.get(unit)
            if fn is None:
                raise ParseError(f"unsupported EXTRACT unit: {unit}")
            return ast.FuncCall(fn, [e])
        if t.is_kw("if"):  # IF(c, a, b) function form
            self.advance()
            self.expect_op("(")
            args = [self.expr()]
            while self.try_op(","):
                args.append(self.expr())
            self.expect_op(")")
            return ast.FuncCall("if", args)
        if t.is_kw("date", "time", "timestamp") and \
                self.toks[self.i + 1].kind == "str":
            # temporal literal: DATE '1994-01-01'
            kw = self.advance().value
            s = self.advance().value
            return ast.FuncCall(f"{kw}_literal", [ast.Literal(s, "str")])
        if t.is_kw("replace", "left", "right", "database",
                   "truncate", "mod", "user", "data", "insert", "char",
                   "format", "set", "charset", "collate",
                   "values", "default", "analyze"):
            # keywords that double as function names
            if self.toks[self.i + 1].kind == "op" and \
                    self.toks[self.i + 1].value == "(":
                name = self.advance().value
                return self._call(name)
        if t.kind == "ident" or (t.kind == "kw" and t.value in (
                "date", "time", "timestamp", "values", "if",
                "add", "to", "column", "rename", "partition")):
            name = self.advance().value
            if self.at_op("("):
                return self._call(name.lower())
            parts = [name]
            while self.try_op("."):
                if self.at_op("*"):
                    self.advance()
                    return ast.Star(table=parts[-1])
                parts.append(self.ident())
            return ast.Name(tuple(parts))
        raise ParseError(f"unexpected token near {self._near()}")

    def _call(self, name: str) -> ast.ExprNode:
        self.expect_op("(")
        if self.try_op("*"):
            self.expect_op(")")
            return self._maybe_window(ast.FuncCall(name, [ast.Star()]))
        if self.try_op(")"):
            return self._maybe_window(ast.FuncCall(name, []))
        distinct = bool(self.try_kw("distinct"))
        args = [self.expr()]
        while self.try_op(","):
            args.append(self.expr())
        self.expect_op(")")
        return self._maybe_window(ast.FuncCall(name, args, distinct))

    def _maybe_window(self, call: ast.FuncCall) -> ast.FuncCall:
        """OVER (PARTITION BY … ORDER BY …) window attachment."""
        if not self.try_kw("over"):
            return call
        self.expect_op("(")
        partition: list = []
        order: list = []
        if self.try_kw("partition"):
            self.expect_kw("by")
            partition.append(self.expr())
            while self.try_op(","):
                partition.append(self.expr())
        if self.try_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.expr()
                desc = False
                if self.try_kw("desc"):
                    desc = True
                elif self.try_kw("asc"):
                    pass
                order.append((e, desc))
                if not self.try_op(","):
                    break
        frame = None
        if self.at("ident") and str(self.cur.value).lower() in ("rows",
                                                                "range"):
            unit = self.advance().value.lower()
            if self.try_kw("between"):
                start = self._frame_bound()
                self.expect_kw("and")
                end = self._frame_bound()
            else:
                # shorthand: only UNBOUNDED PRECEDING / n PRECEDING /
                # CURRENT ROW are legal starts (MySQL frame grammar)
                start = self._frame_bound()
                if start not in (("unbounded", "preceding"),
                                 ("current", 0)) and \
                        not (isinstance(start[0], int)
                             and start[1] == "preceding"):
                    raise ParseError(
                        "frame shorthand requires a PRECEDING or "
                        "CURRENT ROW bound")
                end = ("current", 0)
            frame = (unit, start, end)
        self.expect_op(")")
        call.window = ast.WindowSpec(partition, order, frame)
        return call

    def _frame_bound(self):
        """UNBOUNDED PRECEDING|FOLLOWING | CURRENT ROW | n PRECEDING|
        FOLLOWING → ('unbounded'|'current'|n, direction)."""
        if self.at("ident") and str(self.cur.value).lower() == "unbounded":
            self.advance()
            d = str(self.advance().value).lower()
            if d not in ("preceding", "following"):
                raise ParseError(f"expected PRECEDING/FOLLOWING near "
                                 f"{self._near()}")
            return ("unbounded", d)
        if self.at("ident") and str(self.cur.value).lower() == "current":
            self.advance()
            if not (self.at("ident") and
                    str(self.cur.value).lower() == "row"):
                raise ParseError(f"expected ROW near {self._near()}")
            self.advance()
            return ("current", 0)
        if self.at("int"):
            n = self.advance().value
            d = str(self.advance().value).lower()
            if d not in ("preceding", "following"):
                raise ParseError(f"expected PRECEDING/FOLLOWING near "
                                 f"{self._near()}")
            return (int(n), d)
        raise ParseError(f"expected frame bound near {self._near()}")

    def case_expr(self) -> ast.CaseExpr:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expr()
        whens = []
        while self.try_kw("when"):
            c = self.expr()
            self.expect_kw("then")
            r = self.expr()
            whens.append((c, r))
        else_ = None
        if self.try_kw("else"):
            else_ = self.expr()
        self.expect_kw("end")
        return ast.CaseExpr(operand, whens, else_)
