"""SQL lexer (ref: parser/lexer.go, parser/misc.go keyword table)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from tidb_tpu.errors import ParseError

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "xor", "in", "is", "null", "like",
    "between", "exists", "case", "when", "then", "else", "end", "distinct",
    "nulls", "first", "last",
    "join", "inner", "left", "right", "outer", "cross", "on", "using",
    "union", "all", "except", "intersect", "asc", "desc", "insert", "into",
    "values", "update", "set", "delete", "create", "table", "drop",
    "truncate", "if", "primary", "key", "index", "unique", "default",
    "explain", "analyze", "show", "tables", "columns", "variables", "use",
    "begin", "commit", "rollback", "interval", "cast", "div", "mod",
    "true", "false", "global", "session", "database", "databases",
    "int", "integer", "bigint", "smallint", "tinyint", "float", "double",
    "decimal", "numeric", "char", "varchar", "text", "date", "datetime",
    "timestamp", "time", "unsigned", "signed", "auto_increment", "engine",
    "charset", "collate", "comment", "replace", "ignore", "start",
    "transaction", "over", "partition", "with", "recursive", "alter", "add", "rename", "to", "column",
    "user", "grant", "grants", "revoke", "identified", "privileges",
    "backup", "restore", "trace", "for", "of", "load", "data", "rollup",
}


@dataclass
class Token:
    kind: str       # kw | ident | int | decimal | float | str | op | eof
    value: object
    pos: int

    def is_kw(self, *kws: str) -> bool:
        return self.kind == "kw" and self.value in kws


_TWO_CHAR_OPS = {"<=", ">=", "<>", "!=", "&&", "||", ":=", "->"}
_THREE_CHAR_OPS = {"<=>", "->>"}
_ONE_CHAR_OPS = set("+-*/%(),.;=<>!@")


def tokenize(sql: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        # comments
        if sql.startswith("--", i) or c == "#":
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise ParseError(f"unterminated comment at {i}")
            if sql.startswith("/*+", i):
                # optimizer hint comment: preserved as one token
                # (ref: parser/hintparser.y — /*+ ... */ after SELECT)
                toks.append(Token("hint", sql[i + 3:j].strip(), i))
            i = j + 2
            continue
        # strings
        if c in ("'", '"'):
            val, i = _read_string(sql, i)
            toks.append(Token("str", val, i))
            continue
        # backquoted identifier
        if c == "`":
            j = sql.find("`", i + 1)
            if j < 0:
                raise ParseError(f"unterminated identifier at {i}")
            toks.append(Token("ident", sql[i + 1:j], i))
            i = j + 1
            continue
        # numbers
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            tok, i = _read_number(sql, i)
            toks.append(tok)
            continue
        # identifiers / keywords
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            lw = word.lower()
            if lw in KEYWORDS:
                toks.append(Token("kw", lw, i))
            else:
                toks.append(Token("ident", word, i))
            i = j
            continue
        # operators
        if sql[i:i + 3] in _THREE_CHAR_OPS:
            toks.append(Token("op", sql[i:i + 3], i))
            i += 3
            continue
        if sql[i:i + 2] in _TWO_CHAR_OPS:
            toks.append(Token("op", sql[i:i + 2], i))
            i += 2
            continue
        if sql.startswith("@@", i):
            toks.append(Token("op", "@@", i))
            i += 2
            continue
        if c in _ONE_CHAR_OPS:
            toks.append(Token("op", c, i))
            i += 1
            continue
        raise ParseError(f"unexpected character {c!r} at position {i}")
    toks.append(Token("eof", None, n))
    return toks


def _read_string(sql: str, i: int):
    quote = sql[i]
    out = []
    j = i + 1
    n = len(sql)
    while j < n:
        c = sql[j]
        if c == "\\" and j + 1 < n:
            esc = sql[j + 1]
            out.append({"n": "\n", "t": "\t", "r": "\r", "0": "\0",
                        "\\": "\\", "'": "'", '"': '"', "%": "\\%",
                        "_": "\\_"}.get(esc, esc))
            j += 2
            continue
        if c == quote:
            if j + 1 < n and sql[j + 1] == quote:  # '' escape
                out.append(quote)
                j += 2
                continue
            return "".join(out), j + 1
        out.append(c)
        j += 1
    raise ParseError(f"unterminated string at {i}")


def _read_number(sql: str, i: int):
    j = i
    n = len(sql)
    seen_dot = seen_exp = False
    while j < n:
        c = sql[j]
        if c.isdigit():
            j += 1
        elif c == "." and not seen_dot and not seen_exp:
            seen_dot = True
            j += 1
        elif c in "eE" and not seen_exp and j > i and j + 1 < n and (
                sql[j + 1].isdigit() or (sql[j + 1] in "+-" and j + 2 < n
                                         and sql[j + 2].isdigit())):
            seen_exp = True
            j += 1
            if sql[j] in "+-":
                j += 1
        else:
            break
    text = sql[i:j]
    if seen_exp:
        return Token("float", float(text), i), j
    if seen_dot:
        from decimal import Decimal
        return Token("decimal", Decimal(text), i), j
    return Token("int", int(text), i), j
