"""AST node definitions (ref: parser/ast/{expressions,dml,ddl}.go)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from tidb_tpu.types import FieldType

# ---------------------------------------------------------------------------
# Expressions
# ---------------------------------------------------------------------------


class Node:
    pass


class ExprNode(Node):
    pass


@dataclass
class Literal(ExprNode):
    value: object          # python value; None for NULL
    kind: str              # int | decimal | float | str | null | bool

    def __repr__(self):
        return f"Lit({self.value!r})"


@dataclass
class Name(ExprNode):
    """Possibly-qualified identifier: a | t.a | db.t.a."""

    parts: Tuple[str, ...]

    @property
    def column(self) -> str:
        return self.parts[-1]

    @property
    def qualifier(self) -> Optional[str]:
        return self.parts[-2] if len(self.parts) > 1 else None

    def __repr__(self):
        return ".".join(self.parts)


@dataclass
class Star(ExprNode):
    table: Optional[str] = None


@dataclass
class UnaryOp(ExprNode):
    op: str                # minus | not
    operand: ExprNode


@dataclass
class BinaryOp(ExprNode):
    op: str                # plus minus mul div intdiv mod eq ne lt le gt ge
    left: ExprNode         # nulleq and or xor
    right: ExprNode


@dataclass
class FuncCall(ExprNode):
    name: str
    args: List[ExprNode]
    distinct: bool = False
    window: Optional["WindowSpec"] = None


@dataclass
class WindowSpec(ExprNode):
    """OVER (PARTITION BY … ORDER BY … [frame]) — parser/ast WindowSpec.
    frame = (unit, start, end); bounds are ('unbounded'|'current'|int n,
    'preceding'|'following') pairs; None = the default frame."""
    partition_by: List[ExprNode]
    order_by: List[Tuple[ExprNode, bool]]   # (expr, desc)
    frame: Optional[tuple] = None


@dataclass
class CaseExpr(ExprNode):
    operand: Optional[ExprNode]
    whens: List[Tuple[ExprNode, ExprNode]]
    else_: Optional[ExprNode]


@dataclass
class IsNull(ExprNode):
    expr: ExprNode
    negated: bool = False


@dataclass
class InExpr(ExprNode):
    expr: ExprNode
    items: Optional[List[ExprNode]]      # value list form
    subquery: Optional["Subquery"] = None
    negated: bool = False


@dataclass
class Between(ExprNode):
    expr: ExprNode
    low: ExprNode
    high: ExprNode
    negated: bool = False


@dataclass
class LikeExpr(ExprNode):
    expr: ExprNode
    pattern: ExprNode
    negated: bool = False


@dataclass
class ExistsExpr(ExprNode):
    subquery: "Subquery"
    negated: bool = False


@dataclass
class Subquery(ExprNode):
    select: "SelectStmt"


@dataclass
class CastExpr(ExprNode):
    expr: ExprNode
    target: FieldType


@dataclass
class IntervalExpr(ExprNode):
    value: ExprNode
    unit: str              # day | month | year | hour | minute | second


@dataclass
class VariableRef(ExprNode):
    name: str
    system: bool = False   # @@name vs @name


# ---------------------------------------------------------------------------
# Table references
# ---------------------------------------------------------------------------


class TableRef(Node):
    pass


@dataclass
class TableName(TableRef):
    name: str
    alias: Optional[str] = None
    as_of: Optional[ExprNode] = None     # AS OF TIMESTAMP <expr>
    db: Optional[str] = None             # db-qualified: db.table

    @property
    def ref_name(self) -> str:
        return self.alias or self.name


@dataclass
class JoinExpr(TableRef):
    kind: str              # inner | left | right | cross
    left: TableRef
    right: TableRef
    on: Optional[ExprNode] = None
    using: Optional[List[str]] = None


@dataclass
class SubqueryTable(TableRef):
    select: "SelectStmt"
    alias: str


# ---------------------------------------------------------------------------
# Statements
# ---------------------------------------------------------------------------


class StmtNode(Node):
    pass


@dataclass
class SelectItem(Node):
    expr: ExprNode
    alias: Optional[str] = None


@dataclass
class SelectStmt(StmtNode):
    items: List[SelectItem]
    from_: Optional[TableRef] = None
    where: Optional[ExprNode] = None
    group_by: List[ExprNode] = field(default_factory=list)
    having: Optional[ExprNode] = None
    order_by: List[Tuple[ExprNode, bool]] = field(default_factory=list)  # (e, desc)
    limit: Optional[Tuple[int, int]] = None   # (offset, count)
    distinct: bool = False
    for_update: bool = False
    # optimizer hints from /*+ ... */: [(name_lower, [args])]
    hints: List[Tuple[str, List[str]]] = field(default_factory=list)
    rollup: bool = False                      # GROUP BY ... WITH ROLLUP


@dataclass
class CteDef(Node):
    name: str
    columns: Optional[List[str]]
    select: "StmtNode"


@dataclass
class WithStmt(StmtNode):
    """WITH [RECURSIVE] name [(cols)] AS (select), ... <select>."""
    recursive: bool
    ctes: List[CteDef]
    stmt: "StmtNode"


@dataclass
class SetOpStmt(StmtNode):
    op: str                # union | except | intersect
    all: bool
    left: StmtNode
    right: StmtNode
    order_by: List[Tuple[ExprNode, bool]] = field(default_factory=list)
    limit: Optional[Tuple[int, int]] = None


@dataclass
class ColumnDef(Node):
    name: str
    ftype: FieldType
    primary_key: bool = False
    default: Optional[ExprNode] = None
    auto_increment: bool = False


@dataclass
class IndexDef(Node):
    name: str
    columns: List[str]
    unique: bool = False


@dataclass
class PartitionDef(Node):
    name: str
    less_than: Optional[object] = None    # literal bound; None = MAXVALUE


@dataclass
class PartitionSpec(Node):
    """PARTITION BY RANGE (col) (...) | PARTITION BY HASH (col)
    PARTITIONS n (ref: parser/model/model.go PartitionInfo)."""
    kind: str                             # range | hash
    column: str
    defs: List[PartitionDef] = field(default_factory=list)
    num: int = 0                          # hash partition count


@dataclass
class CreateTable(StmtNode):
    name: str
    columns: List[ColumnDef]
    primary_key: List[str] = field(default_factory=list)
    indexes: List[IndexDef] = field(default_factory=list)
    if_not_exists: bool = False
    partition: Optional[PartitionSpec] = None


@dataclass
class CreateView(StmtNode):
    """CREATE [OR REPLACE] VIEW v [(cols)] AS select (ref:
    ddl/ddl_api.go:2186 CreateView)."""
    name: str
    select: StmtNode
    columns: Optional[List[str]] = None
    or_replace: bool = False
    text: str = ""                  # the definition's SELECT source text


@dataclass
class DropView(StmtNode):
    names: List[str]
    if_exists: bool = False


@dataclass
class CreateIndex(StmtNode):
    name: str
    table: str
    columns: List[str]
    unique: bool = False


@dataclass
class DropIndex(StmtNode):
    name: str
    table: str


@dataclass
class AlterTable(StmtNode):
    table: str
    action: str     # add_column | drop_column | rename | add_partition |
    #                 drop_partition | truncate_partition
    column: Optional[ColumnDef] = None
    column_name: Optional[str] = None
    new_name: Optional[str] = None
    partition_def: Optional[PartitionDef] = None
    partition_name: Optional[str] = None


@dataclass
class DropTable(StmtNode):
    names: List[str]
    if_exists: bool = False


@dataclass
class TruncateTable(StmtNode):
    name: str


@dataclass
class Insert(StmtNode):
    table: str
    columns: Optional[List[str]]
    rows: Optional[List[List[ExprNode]]] = None
    select: Optional[SelectStmt] = None
    replace: bool = False      # REPLACE INTO: delete-then-insert on dup key
    ignore: bool = False       # INSERT IGNORE: skip dup-key rows


@dataclass
class Update(StmtNode):
    table: TableName
    assignments: List[Tuple[str, ExprNode]]
    where: Optional[ExprNode] = None


@dataclass
class Delete(StmtNode):
    table: TableName
    where: Optional[ExprNode] = None


@dataclass
class Explain(StmtNode):
    stmt: StmtNode
    analyze: bool = False


@dataclass
class SetStmt(StmtNode):
    assignments: List[Tuple[str, ExprNode]]   # (var_name, value)
    global_scope: bool = False


@dataclass
class ShowStmt(StmtNode):
    kind: str              # tables | columns | variables | create_table
    target: Optional[str] = None
    like: Optional[str] = None


@dataclass
class AnalyzeTable(StmtNode):
    names: List[str]


@dataclass
class LoadData(StmtNode):
    table: str
    path: str
    delimiter: str = ","
    ignore_lines: int = 0


@dataclass
class TraceStmt(StmtNode):
    stmt: StmtNode
    # 'row' (default span-tree result set) or 'chrome' (one-row Chrome
    # trace JSON — executor/trace.go's TRACE FORMAT='json' analog)
    format: str = "row"


@dataclass
class BackupStmt(StmtNode):
    path: str


@dataclass
class RestoreStmt(StmtNode):
    path: str


@dataclass
class CreateUser(StmtNode):
    user: str
    password: str = ""
    if_not_exists: bool = False


@dataclass
class DropUser(StmtNode):
    user: str
    if_exists: bool = False


@dataclass
class GrantStmt(StmtNode):
    privs: List[str]
    scope: str                 # *.* | db.* | db.tbl | tbl
    user: str
    revoke: bool = False


@dataclass
class UseStmt(StmtNode):
    db: str


@dataclass
class BeginStmt(StmtNode):
    mode: Optional[str] = None     # pessimistic | optimistic | None


@dataclass
class KillStmt(StmtNode):
    # KILL [QUERY] <conn_id>: query_only interrupts the running statement
    # but keeps the connection; bare KILL poisons the connection too
    conn_id: int = 0
    query_only: bool = False


@dataclass
class CommitStmt(StmtNode):
    pass


@dataclass
class RollbackStmt(StmtNode):
    pass
