"""SQL parser (ref: /root/reference/parser/ — a goyacc grammar of ~8k lines).

We use a hand-written lexer + recursive-descent/precedence-climbing parser
over the analytical subset the engine executes: SELECT (joins, group/order/
having/limit, subqueries, set ops), CREATE/DROP/TRUNCATE TABLE, INSERT/
UPDATE/DELETE, EXPLAIN [ANALYZE], SET, SHOW. The AST mirrors parser/ast/
in spirit: plain dataclasses the planner walks.
"""

from tidb_tpu.parser.parser import (parse, parse_one,  # noqa: F401
                                    parse_with_text)
from tidb_tpu.parser import ast  # noqa: F401
