"""Evaluate expression lists over host Chunks / DeviceChunks.

The host path is the CPU oracle and fallback engine; the device path is what
executor fragments trace under jit. Ref pattern: expression/chunk_executor.go
(VectorizedExecute / VectorizedFilter).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.chunk.device import DeviceChunk, DeviceColumn
from tidb_tpu.expression import (Constant, EvalContext, Expression,
                                 collect_preparations)
from tidb_tpu.types import TypeKind


def host_context(chunk: Chunk) -> EvalContext:
    cols = [(c.values, c.valid_mask()) for c in chunk.columns]
    return EvalContext(np, cols, on_device=False)


def eval_on_chunk(exprs: Sequence[Expression], chunk: Chunk) -> Chunk:
    """Host (numpy) vectorized evaluation → new Chunk (the CPU engine)."""
    ctx = host_context(chunk)
    out: List[Column] = []
    for e in exprs:
        v, m = e.eval(ctx)
        ft = e.ftype
        if ft.kind.is_string:
            vals = np.asarray(v, dtype=object)
        else:
            vals = np.asarray(v).astype(ft.np_dtype, copy=False)
        valid = np.asarray(m, dtype=bool)
        out.append(Column(ft, vals, None if valid.all() else valid.copy()))
    return Chunk(out)


def filter_mask(pred: Expression, chunk: Chunk) -> np.ndarray:
    """Host VectorizedFilter: NULL → excluded (SQL WHERE semantics)."""
    ctx = host_context(chunk)
    v, m = pred.eval(ctx)
    return np.asarray((v != 0) & m, dtype=bool)


def device_context(dchunk: DeviceChunk, xp,
                   prepared: Optional[dict] = None) -> EvalContext:
    cols = [(dc.values, dc.validity) for dc in dchunk.columns]
    dicts = [dc.dictionary for dc in dchunk.columns]
    return EvalContext(xp, cols, dictionaries=dicts,
                       prepared=prepared or {}, on_device=True)


def eval_on_device(exprs: Sequence[Expression], dchunk: DeviceChunk,
                   jit: bool = True) -> DeviceChunk:
    """Device evaluation: one traced program over all expressions.

    Host-side dictionary preparations become extra traced arguments so the
    compiled program is reusable across chunks with different dictionaries.
    """
    from tidb_tpu.ops.jax_env import jax, jnp

    dicts = [dc.dictionary for dc in dchunk.columns]
    prepared = collect_preparations(exprs, dicts)
    keys = list(prepared.keys())

    def run(dch, prep_vals):
        ctx = device_context(dch, jnp, dict(zip(keys, prep_vals)))
        out_cols = []
        for e in exprs:
            v, m = e.eval(ctx)
            out_cols.append(DeviceColumn(v, m, e.ftype, None))
        return DeviceChunk(out_cols, dch.n_rows)

    prep_vals = [prepared[k] for k in keys]
    fn = jax.jit(run) if jit else run
    out = fn(dchunk, prep_vals)
    # reattach derived dictionaries for string→string functions
    for e, dc in zip(exprs, out.columns):
        if e.ftype.kind.is_string:
            d = getattr(e, "_derived_dict", None)
            if d is None and e.references():
                src = e.references()[0]
                d = dicts[src] if src < len(dicts) else None
            if d is None and isinstance(e, Constant):
                d = np.array([str(e.value)], dtype=object)
            out.columns[out.columns.index(dc)] = dc.with_dictionary(d)
    return out
